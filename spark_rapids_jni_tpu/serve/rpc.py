"""Cross-process RPC for crash-only serving: the executor-worker side.

Spark's real resilience layer sits ABOVE the resource adaptor this repo
reproduces: executors die and the driver re-dispatches their tasks.  This
module is the executor half of that layer for the serve tier — a worker
process entry point (:func:`executor_worker_main`) that runs today's
:class:`~spark_rapids_jni_tpu.serve.executor.ServingEngine` over its OWN
memory governor, plus the small message protocol it speaks with the
supervisor (serve/supervisor.py) over a ``multiprocessing`` pipe.

Protocol (plain tuples, first element the tag — pickled by the pipe):

- ``(HELLO, worker_id, incarnation, pid)``        worker ready to serve
- ``(BEAT, worker_id, incarnation, wall_t, gauges)``  liveness + pressure
- ``(DISPATCH, rid, handler, payload, deadline_rel_s, priority)``
- ``(RESULT, rid, status, value, (err_type, err_msg) | None)``
- ``(SHUTDOWN, dump_epilogue)``                   drain and exit

Crash-only discipline: the worker never tries to hand off state on the way
down.  A SIGKILL (injected ``proc_kill`` fault, OOM killer, operator) just
drops the pipe; the supervisor's receiver sees EOF, declares the worker
dead, and re-dispatches its leases — the same path a missed-heartbeat or
hung-lease recycle takes.  Symmetrically, a worker whose pipe to the
supervisor breaks exits: an orphaned executor must not keep burning the
machine.

The ``rid`` (supervisor lease id) is deliberately woven into the worker's
flight ring (``EV_LEASE_GRANT`` with ``rid:<id>`` detail next to the
engine-local task id) so ``tools/flightdump.py --cluster`` can stitch
per-process dumps into one cross-process request timeline.
"""

from __future__ import annotations

import importlib
import os
import threading
import time
from typing import Callable, Optional

__all__ = [
    "MSG_HELLO", "MSG_BEAT", "MSG_DISPATCH", "MSG_RESULT", "MSG_SHUTDOWN",
    "MESSAGE_FIELDS",
    "SafeConn", "resolve_factory", "executor_worker_main",
]

MSG_HELLO = "hello"
MSG_BEAT = "beat"
MSG_DISPATCH = "dispatch"
MSG_RESULT = "result"
MSG_SHUTDOWN = "shutdown"

# The declared wire schema: tag -> field names after the tag.  BOTH sides
# of the pipe are checked against this table at merge time (ci/analyze
# wire-protocol pass): every tuple constructed with one of these tags must
# carry exactly these fields, and every destructure site (tuple unpack or
# msg[i] index under an `if tag == MSG_X` guard) must match arity and
# names.  The round-10 blocked_frac drift — a gauge the supervisor read
# but no worker sent — is the defect class this freezes out; changing a
# message means changing this row, which forces every site on both sides
# into the same review.
MESSAGE_FIELDS = {
    MSG_HELLO: ("worker_id", "incarnation", "pid"),
    MSG_BEAT: ("worker_id", "incarnation", "wall_t", "gauges"),
    MSG_DISPATCH: ("rid", "handler", "payload", "deadline_rel_s",
                   "priority"),
    MSG_RESULT: ("rid", "status", "value", "err"),
    MSG_SHUTDOWN: ("dump_epilogue",),
}

# RESULT statuses mirror serve.queue terminal states, plus the one
# non-terminal flow-control verdict a worker may return:
STATUS_BUSY = "busy"        # worker queue full — supervisor re-queues


class SafeConn:
    """A ``multiprocessing`` connection that survives its peer dying.

    ``send`` serializes concurrent senders (heartbeat thread + result
    waiters share one pipe) and returns False instead of raising once the
    peer is gone — by then the supervisor/worker death path owns cleanup,
    and a crashing send inside a waiter thread would just add noise.
    ``recv`` returns None on EOF for the same reason.
    """

    def __init__(self, conn):
        self._conn = conn
        self._send_lock = threading.Lock()

    def send(self, msg: tuple) -> bool:
        try:
            with self._send_lock:
                self._conn.send(msg)
            return True
        # analyze: ignore[retry-protocol] - pipe serialization crosses no
        # seam and launches no governed work: nothing here can originate a
        # control signal.  Any failure (broken pipe mid-crash, an
        # unpicklable result value) means "peer unreachable / message
        # undeliverable", which the caller maps to the dead-worker path.
        except Exception:  # noqa: BLE001
            return False

    def recv(self) -> Optional[tuple]:
        try:
            return self._conn.recv()
        except (EOFError, OSError):
            return None

    def close(self) -> None:
        try:
            self._conn.close()
        except OSError:
            pass


def resolve_factory(factory) -> Callable:
    """Resolve a handler factory: a callable passes through; a
    ``"module:attr"`` string imports in THIS process.  String specs are
    what cross the spawn boundary robustly — the child resolves them
    against its own interpreter instead of unpickling a closure."""
    if callable(factory):
        return factory
    mod_name, _, attr = str(factory).partition(":")
    if not attr:
        raise ValueError(
            f"factory spec {factory!r} must be 'module:function'")
    return getattr(importlib.import_module(mod_name), attr)


def executor_worker_main(worker_id: int, incarnation: int, conn,
                         factory, factory_kwargs: Optional[dict] = None,
                         worker_cfg: Optional[dict] = None,
                         chaos: Optional[dict] = None,
                         flags: Optional[dict] = None) -> None:
    """Entry point of one executor worker process (spawned by the
    supervisor).  Builds its own governor + budget + ServingEngine (one
    failure domain, nothing shared with any sibling), registers handlers
    via ``factory(engine, **factory_kwargs)``, optionally arms the fault
    injector from ``chaos``, then serves DISPATCH messages until the pipe
    closes or a SHUTDOWN arrives."""
    from spark_rapids_jni_tpu import config

    for k, v in (flags or {}).items():
        config.set(k, v)

    from spark_rapids_jni_tpu.mem.governed import default_device_budget
    from spark_rapids_jni_tpu.mem.governor import (
        BudgetedResource,
        MemoryGovernor,
    )
    from spark_rapids_jni_tpu.obs import flight as _flight
    from spark_rapids_jni_tpu.serve.executor import ServingEngine
    from spark_rapids_jni_tpu.serve.queue import OK

    cfg = dict(worker_cfg or {})
    gov = MemoryGovernor(
        watchdog_period_s=float(cfg.pop("watchdog_period_s", 0.05)))
    budget_bytes = cfg.pop("budget_bytes", None)
    budget = (BudgetedResource(gov, int(budget_bytes))
              if budget_bytes is not None else default_device_budget(gov))
    engine = ServingEngine(
        gov=gov, budget=budget,
        workers=int(cfg.pop("workers", 2)),
        queue_size=int(cfg.pop("queue_size", 64)),
        default_deadline_s=cfg.pop("default_deadline_s", 30.0),
        adaptive=bool(cfg.pop("adaptive", False)))
    resolve_factory(factory)(engine, **(factory_kwargs or {}))
    if chaos:
        from spark_rapids_jni_tpu.obs.faultinj import FaultInjector

        FaultInjector.install(chaos)

    # one uncapped internal session: tenant admission (budgets, ladder,
    # priorities) already happened in the supervisor; the worker engine's
    # job is governed execution, not a second front door
    sess = engine.open_session(f"lease:w{worker_id}")
    sconn = SafeConn(conn)
    stop = threading.Event()
    dump_epilogue = [False]

    def heartbeat() -> None:
        period = float(config.get("serve_heartbeat_s"))
        nworkers = max(1, len(engine._workers))
        while not stop.wait(period):
            # blocked_frac mirrors the admission controller's pressure
            # signal (rolling arbiter park time over the window, per
            # worker thread) — the supervisor's ladder reads both
            try:
                rolled = engine.gov.arbiter.rolling_blocked(1.0)
                blocked = min(1.0, sum(rolled.values()) / (1e9 * nworkers))
            except RuntimeError:  # governor closing: no trend signal
                blocked = 0.0
            gauges = {
                "mem_frac": engine.budget.used / max(1, engine.budget.limit),
                "blocked_frac": blocked,
                "queue_depth": engine.queue.depth(),
                "outstanding": engine.queue.outstanding(),
            }
            if not sconn.send((MSG_BEAT, worker_id, incarnation,
                               time.time(), gauges)):
                return  # supervisor gone; main loop will see EOF too

    def waiter(rid: int, resp) -> None:
        resp.wait()  # the engine guarantees a terminal state
        if resp.status == OK:
            err = None
            value = resp.value
        else:
            err = (type(resp.error).__name__ if resp.error is not None
                   else resp.status,
                   str(resp.error) if resp.error is not None else "")
            value = None
        if not sconn.send((MSG_RESULT, rid, resp.status, value, err)):
            # the value may be unpicklable even though the pipe is fine:
            # degrade to an in-band error so the lease still terminates
            sconn.send((MSG_RESULT, rid, "error", None,
                        ("UnserializableResult",
                         f"result of rid {rid} could not cross the pipe")))
        _flight.record(_flight.EV_LEASE_DONE, resp.task_id,
                       detail=f"rid:{rid}:worker:{worker_id}:{resp.status}")

    beat_thread = threading.Thread(target=heartbeat, daemon=True,
                                   name=f"serve-worker-{worker_id}-beat")
    beat_thread.start()
    sconn.send((MSG_HELLO, worker_id, incarnation, os.getpid()))

    try:
        while True:
            msg = sconn.recv()
            if msg is None:
                break  # supervisor died: crash-only both directions
            tag = msg[0]
            if tag == MSG_SHUTDOWN:
                dump_epilogue[0] = bool(msg[1])
                break
            if tag != MSG_DISPATCH:
                continue
            _, rid, handler, payload, deadline_rel_s, priority = msg
            try:
                resp = engine.submit(sess, handler, payload,
                                     priority=priority,
                                     deadline_s=deadline_rel_s)
            # analyze: ignore[retry-protocol] - submit crosses no seam
            # (admission only); failures here are flow control
            # (Backpressure -> BUSY re-queue upstream) or setup bugs
            # (unknown handler), both reported in-band to the supervisor
            except Exception as e:  # noqa: BLE001
                from spark_rapids_jni_tpu.serve.queue import Backpressure

                status = (STATUS_BUSY if isinstance(e, Backpressure)
                          else "error")
                sconn.send((MSG_RESULT, rid, status, None,
                            (type(e).__name__, str(e))))
                continue
            _flight.record(_flight.EV_LEASE_GRANT, resp.task_id,
                           detail=f"rid:{rid}:worker:{worker_id}:local")
            threading.Thread(target=waiter, args=(rid, resp), daemon=True,
                             name=f"serve-worker-{worker_id}-rid{rid}").start()
    finally:
        stop.set()
        if dump_epilogue[0]:
            # end-of-run ring dump so the --cluster merge has this
            # process's timeline even when nothing anomalous happened here
            _flight.anomaly("cluster_epilogue",
                            detail=f"worker:{worker_id}:inc:{incarnation}")
        engine.shutdown(drain=False, timeout=5.0)
        gov.close()
        sconn.close()
