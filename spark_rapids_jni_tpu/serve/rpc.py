"""Cross-process RPC for crash-only serving: the executor-worker side.

Spark's real resilience layer sits ABOVE the resource adaptor this repo
reproduces: executors die and the driver re-dispatches their tasks.  This
module is the executor half of that layer for the serve tier — a worker
process entry point (:func:`executor_worker_main`) that runs today's
:class:`~spark_rapids_jni_tpu.serve.executor.ServingEngine` over its OWN
memory governor, plus the small message protocol it speaks with the
supervisor (serve/supervisor.py) over a ``multiprocessing`` pipe.

Protocol (plain tuples, first element the tag — pickled by the pipe):

- ``(HELLO, worker_id, incarnation, pid)``        worker ready to serve
- ``(BEAT, worker_id, incarnation, wall_t, gauges)``  liveness + pressure
- ``(DISPATCH, rid, handler, payload, deadline_rel_s, priority)``
- ``(RESULT, rid, status, value, (err_type, err_msg) | None)``
- ``(SHUTDOWN, dump_epilogue)``                   drain and exit

Crash-only discipline: the worker never tries to hand off state on the way
down.  A SIGKILL (injected ``proc_kill`` fault, OOM killer, operator) just
drops the pipe; the supervisor's receiver sees EOF, declares the worker
dead, and re-dispatches its leases — the same path a missed-heartbeat or
hung-lease recycle takes.  Symmetrically, a worker whose pipe to the
supervisor breaks exits: an orphaned executor must not keep burning the
machine.

The ``rid`` (supervisor lease id) is deliberately woven into the worker's
flight ring (``EV_LEASE_GRANT`` with ``rid:<id>`` detail next to the
engine-local task id) so ``tools/flightdump.py --cluster`` can stitch
per-process dumps into one cross-process request timeline.
"""

from __future__ import annotations

import importlib
import os
import threading
import time
from typing import Callable, Optional

__all__ = [
    "MSG_HELLO", "MSG_BEAT", "MSG_DISPATCH", "MSG_RESULT", "MSG_SHUTDOWN",
    "MSG_SHUFFLE_PRODUCED", "MSG_SHUFFLE_ACK", "MSG_SHUFFLE_MAP",
    "MSG_SHUFFLE_CLEANUP", "MSG_PRESSURE", "MSG_TELEMETRY",
    "MSG_TABLE_BUMP", "MESSAGE_FIELDS",
    "SafeConn", "resolve_factory", "executor_worker_main",
    "set_shuffle_sink", "shuffle_uplink",
]

MSG_HELLO = "hello"
MSG_BEAT = "beat"
MSG_DISPATCH = "dispatch"
MSG_RESULT = "result"
MSG_SHUTDOWN = "shutdown"
# the columnar data plane's control half (round 13, serve/shuffle.py):
# partition DATA moves peer-to-peer over the framed socket transport; the
# supervisor pipe only carries the partition-map bookkeeping — production
# announcements + consumer acks up, map/cleanup broadcasts down — plus
# the cluster-wide pressure gauge feeding each worker's admission
# controller (the federated-admission tail of ROADMAP item 1)
MSG_SHUFFLE_PRODUCED = "shuffle_produced"
MSG_SHUFFLE_ACK = "shuffle_ack"
MSG_SHUFFLE_MAP = "shuffle_map"
MSG_SHUFFLE_CLEANUP = "shuffle_cleanup"
MSG_PRESSURE = "pressure"
# the live telemetry plane (round 14, serve/telemetry.py): each worker
# piggybacks rolling flight-ring deltas + a metrics snapshot onto the
# heartbeat cadence; the supervisor merges them into the bounded cluster
# timeline its local endpoint serves (tools/servetop.py, flightdump
# --live).  An undeliverable export is SKIPPED, never blocked on — the
# same discipline as the round-13 heartbeat fix.
MSG_TELEMETRY = "telemetry"
# the governed result cache's invalidation plane (round 15,
# plans/rcache.py + models/tables.py): the supervisor owns table-version
# bumps (Supervisor.bump_table) and broadcasts the new version so every
# executor's local registry — and therefore its result-cache keys —
# converges.  Monotonic on the receiving side (tables.advance_to): late
# or duplicate broadcasts are no-ops, never rollbacks.
MSG_TABLE_BUMP = "table_bump"

# The declared wire schema: tag -> field names after the tag.  BOTH sides
# of the pipe are checked against this table at merge time (ci/analyze
# wire-protocol pass): every tuple constructed with one of these tags must
# carry exactly these fields, and every destructure site (tuple unpack or
# msg[i] index under an `if tag == MSG_X` guard) must match arity and
# names.  The round-10 blocked_frac drift — a gauge the supervisor read
# but no worker sent — is the defect class this freezes out; changing a
# message means changing this row, which forces every site on both sides
# into the same review.
MESSAGE_FIELDS = {
    MSG_HELLO: ("worker_id", "incarnation", "pid"),
    MSG_BEAT: ("worker_id", "incarnation", "wall_t", "gauges"),
    # `trace` (round 14) is the supervisor's dispatch-span context
    # (obs/trace.to_wire tuple or None): the worker's queue/compute spans
    # chain under the SAME rid, so one live waterfall crosses the pipe.
    # `tenant` (round 21) is the billing identity the request's
    # attribution record rolls up under — the worker engines run ONE
    # internal lease session each, so the tenant must ride the dispatch
    # itself (hedge copies carry the same rid + tenant, which is how
    # hedge-loser cost stays attributed)
    MSG_DISPATCH: ("rid", "handler", "payload", "deadline_rel_s",
                   "priority", "trace", "tenant"),
    MSG_RESULT: ("rid", "status", "value", "err"),
    MSG_SHUTDOWN: ("dump_epilogue",),
    # worker -> supervisor: map task `map_index` of shuffle `sid` framed
    # its partitions ({part: nbytes} sizes) and serves them at `ep`
    MSG_SHUFFLE_PRODUCED: ("worker_id", "incarnation", "sid", "map_index",
                           "sizes", "ep"),
    # worker -> supervisor: consumer `part` fetched + CRC-verified map
    # task `map_index`'s partition (the partition map's ack column)
    MSG_SHUFFLE_ACK: ("worker_id", "incarnation", "sid", "map_index",
                      "part"),
    # supervisor -> participants: the current partition map of one
    # shuffle ({map_index: {state, ep, incarnation, sizes}})
    MSG_SHUFFLE_MAP: ("sid", "nparts", "tasks"),
    # supervisor -> participants: shuffle finished/abandoned; free stores
    MSG_SHUFFLE_CLEANUP: ("sid",),
    # supervisor -> workers: cluster-wide pressure aggregate (mean/max of
    # heartbeat gauges) for the local AdmissionController's tick
    MSG_PRESSURE: ("cluster",),
    # worker -> supervisor: one telemetry export — flight-ring event
    # dicts since the last export plus a ServeMetrics snapshot, stamped
    # with a paired (wall_t, t_ns) clock so the timeline aligns this
    # process's monotonic event times onto the cluster's wall clock
    MSG_TELEMETRY: ("worker_id", "incarnation", "wall_t", "t_ns",
                    "events", "metrics"),
    # supervisor -> workers: table `name` is now at `version` — advance
    # the local registry (reclaiming dependent result-cache entries)
    MSG_TABLE_BUMP: ("name", "version"),
}

# RESULT statuses mirror serve.queue terminal states, plus the one
# non-terminal flow-control verdict a worker may return:
STATUS_BUSY = "busy"        # worker queue full — supervisor re-queues


class SafeConn:
    """A ``multiprocessing`` connection that survives its peer dying.

    ``send`` serializes concurrent senders (heartbeat thread + result
    waiters share one pipe) and returns False instead of raising once the
    peer is gone — by then the supervisor/worker death path owns cleanup,
    and a crashing send inside a waiter thread would just add noise.
    ``recv`` returns None on EOF for the same reason.

    ``send`` is also BOUNDED-TIME: a live peer that stops draining its
    pipe (wedged receive loop) would otherwise block the sender forever
    while it holds the send lock — heartbeats stop, the sender looks
    dead, and the wrong process gets recycled.  After ``send_timeout_s``
    waiting for pipe writability the send surfaces as backpressure
    instead: an ``EV_TASK_HUNG`` flight event plus a False return, which
    callers already map to the unreachable-peer path.  (The guard bounds
    the wait for buffer SPACE; a message larger than the freed buffer can
    still block in the write itself — supervision's hung-lease bound
    remains the backstop of last resort.)
    """

    def __init__(self, conn, send_timeout_s: Optional[float] = None):
        if send_timeout_s is None:
            from spark_rapids_jni_tpu import config

            send_timeout_s = float(config.get("serve_send_timeout_s"))
        self._conn = conn
        self._send_timeout_s = float(send_timeout_s)
        self._send_lock = threading.Lock()

    def send(self, msg: tuple) -> bool:
        try:
            with self._send_lock:
                if self._send_timeout_s > 0:
                    import select

                    ready = select.select(
                        [], [self._conn.fileno()], [],
                        self._send_timeout_s)[1]
                    if not ready:
                        from spark_rapids_jni_tpu.obs import (
                            flight as _flight,
                        )

                        _flight.record(
                            _flight.EV_TASK_HUNG, -1,
                            detail=f"pipe_send_stalled:"
                                   f"{self._send_timeout_s:g}s:"
                                   f"tag:{msg[0] if msg else '?'}")
                        return False
                # analyze: ignore[blocking-under-lock] - the send lock
                # EXISTS to serialize this pipe write (heartbeat thread +
                # result waiters share one fd; interleaved pickles would
                # corrupt the stream), and the select() guard above
                # bounds the wait for buffer space, so this is the one
                # place a pipe write may block while holding it.  The
                # hung-lease supervision bound backstops the residual
                # giant-message case (class docstring).
                self._conn.send(msg)
            return True
        # analyze: ignore[retry-protocol] - pipe serialization crosses no
        # seam and launches no governed work: nothing here can originate a
        # control signal.  Any failure (broken pipe mid-crash, an
        # unpicklable result value) means "peer unreachable / message
        # undeliverable", which the caller maps to the dead-worker path.
        except Exception:  # noqa: BLE001
            return False

    def recv(self) -> Optional[tuple]:
        try:
            return self._conn.recv()
        except (EOFError, OSError):
            return None

    def close(self) -> None:
        try:
            self._conn.close()
        except OSError:
            pass


# --------------------------------------------------------------------------
# shuffle plumbing: the worker main loop routes shuffle control messages to
# the process's ShuffleService WITHOUT importing serve/shuffle.py (which
# pulls in the plan compiler and jax — workers that never serve a shuffle
# handler must stay cheap to spawn).  The service registers a sink when it
# starts; messages arriving first are buffered and drained at registration.
# The uplink is how the service (running in handler threads) sends
# produced/ack announcements up the ONE supervisor pipe.
# --------------------------------------------------------------------------

_shuffle_lock = threading.Lock()
_shuffle_sink: Optional[Callable[[tuple], None]] = None
_shuffle_pending: list = []
_shuffle_uplink: Optional[tuple] = None  # (send_fn, worker_id, incarnation)


def set_shuffle_sink(fn: Optional[Callable[[tuple], None]]) -> None:
    """Register (or clear) the process ShuffleService's message sink;
    buffered messages drain in arrival order.  The drain AND every
    subsequent delivery run under the one lock, so a map broadcast
    arriving concurrently with registration can never be applied before
    (and then overwritten by) an older buffered map."""
    global _shuffle_sink
    with _shuffle_lock:
        _shuffle_sink = fn
        pending, _shuffle_pending[:] = list(_shuffle_pending), []
        if fn is not None:
            for msg in pending:
                fn(msg)


def _route_shuffle_msg(msg: tuple) -> None:
    # delivery stays under the lock (see set_shuffle_sink): the sink's
    # own state has its own condition, and no sink path re-enters this
    # lock while holding it — produce/ack read the uplink AFTER
    # releasing the service condition
    with _shuffle_lock:
        if _shuffle_sink is None:
            _shuffle_pending.append(msg)
            del _shuffle_pending[:-256]  # bounded: maps re-broadcast
            return
        _shuffle_sink(msg)


def shuffle_uplink() -> Optional[tuple]:
    """(send_fn, worker_id, incarnation) of this executor-worker process,
    or None outside one (standalone services skip announcements)."""
    with _shuffle_lock:
        return _shuffle_uplink


def _set_shuffle_uplink(uplink: Optional[tuple]) -> None:
    global _shuffle_uplink
    with _shuffle_lock:
        _shuffle_uplink = uplink


def resolve_factory(factory) -> Callable:
    """Resolve a handler factory: a callable passes through; a
    ``"module:attr"`` string imports in THIS process.  String specs are
    what cross the spawn boundary robustly — the child resolves them
    against its own interpreter instead of unpickling a closure."""
    if callable(factory):
        return factory
    mod_name, _, attr = str(factory).partition(":")
    if not attr:
        raise ValueError(
            f"factory spec {factory!r} must be 'module:function'")
    return getattr(importlib.import_module(mod_name), attr)


def executor_worker_main(worker_id: int, incarnation: int, conn,
                         factory, factory_kwargs: Optional[dict] = None,
                         worker_cfg: Optional[dict] = None,
                         chaos: Optional[dict] = None,
                         flags: Optional[dict] = None) -> None:
    """Entry point of one executor worker process (spawned by the
    supervisor).  Builds its own governor + budget + ServingEngine (one
    failure domain, nothing shared with any sibling), registers handlers
    via ``factory(engine, **factory_kwargs)``, optionally arms the fault
    injector from ``chaos``, then serves DISPATCH messages until the pipe
    closes or a SHUTDOWN arrives."""
    from spark_rapids_jni_tpu import config

    for k, v in (flags or {}).items():
        config.set(k, v)

    from spark_rapids_jni_tpu.mem.governed import default_device_budget
    from spark_rapids_jni_tpu.mem.governor import (
        BudgetedResource,
        MemoryGovernor,
    )
    from spark_rapids_jni_tpu.obs import flight as _flight
    from spark_rapids_jni_tpu.obs import trace as _trace
    from spark_rapids_jni_tpu.serve.executor import ServingEngine
    from spark_rapids_jni_tpu.serve.queue import OK

    cfg = dict(worker_cfg or {})
    gov = MemoryGovernor(
        watchdog_period_s=float(cfg.pop("watchdog_period_s", 0.05)))
    budget_bytes = cfg.pop("budget_bytes", None)
    budget = (BudgetedResource(gov, int(budget_bytes))
              if budget_bytes is not None else default_device_budget(gov))
    engine = ServingEngine(
        gov=gov, budget=budget,
        workers=int(cfg.pop("workers", 2)),
        queue_size=int(cfg.pop("queue_size", 64)),
        default_deadline_s=cfg.pop("default_deadline_s", 30.0),
        adaptive=bool(cfg.pop("adaptive", False)))
    resolve_factory(factory)(engine, **(factory_kwargs or {}))
    if chaos:
        from spark_rapids_jni_tpu.obs.faultinj import FaultInjector

        FaultInjector.install(chaos)

    # one uncapped internal session: tenant admission (budgets, ladder,
    # priorities) already happened in the supervisor; the worker engine's
    # job is governed execution, not a second front door
    sess = engine.open_session(f"lease:w{worker_id}")
    sconn = SafeConn(conn)
    stop = threading.Event()
    dump_epilogue = [False]

    exporter = None
    if bool(config.get("serve_telemetry")):
        from spark_rapids_jni_tpu.serve import attribution as _attrib
        from spark_rapids_jni_tpu.serve.telemetry import TelemetryExporter

        def _metrics_with_attrib():
            # the cumulative attribution reconciliation gauges ride
            # EVERY export's metrics — including the post-result
            # force-flush, the same message that carries the EV_ATTRIB
            # events — so a chaos SIGKILL can't strand attributed work
            # without the measurement it reconciles against
            m = engine.metrics.snapshot()
            m.setdefault("gauges", {}).update(_attrib.worker_gauges())
            return m

        exporter = TelemetryExporter(worker_id, incarnation,
                                     metrics_source=_metrics_with_attrib)
        # force-flush on the SERVING thread after each popped group fully
        # serves: every span-close finally has run by then, so a chaos
        # SIGKILL landing before the next heartbeat cannot eat the story
        # of work that already completed (deterministic ordering — no
        # sleep-and-hope between waiter and serving threads)
        engine.on_served = lambda: exporter.export(sconn.send, force=True)

    rcache_on = bool(config.get("serve_result_cache"))
    rcache_hot_n = int(config.get("serve_result_cache_advertise"))

    def heartbeat() -> None:
        period = float(config.get("serve_heartbeat_s"))
        nworkers = max(1, len(engine._workers))
        while not stop.wait(period):
            # blocked_frac mirrors the admission controller's pressure
            # signal (rolling arbiter park time over the window, per
            # worker thread) — the supervisor's ladder reads both
            try:
                rolled = engine.gov.arbiter.rolling_blocked(1.0)
                blocked = min(1.0, sum(rolled.values()) / (1e9 * nworkers))
            except RuntimeError:  # governor closing: no trend signal
                blocked = 0.0
            gauges = {
                "mem_frac": engine.budget.used / max(1, engine.budget.limit),
                "blocked_frac": blocked,
                "queue_depth": engine.queue.depth(),
                "outstanding": engine.queue.outstanding(),
            }
            if rcache_on:
                from spark_rapids_jni_tpu.plans.rcache import result_cache

                # key advertisement (round 15): the hottest resident
                # tokens ride the beat so the router knows which submits
                # will hit SOMEWHERE — the cached_only ladder level
                # admits exactly those.  Per-tier residency rides along
                # for servetop's per-worker CACHE column.
                rs = result_cache.stats()
                gauges["rcache"] = {
                    k: rs[k] for k in
                    ("entries", "hbm_bytes", "host_bytes", "disk_bytes",
                     "hits", "misses", "hit_ratio")}
                if rcache_hot_n > 0:
                    gauges["rcache_hot"] = result_cache.hot_tokens(
                        rcache_hot_n)
            if not sconn.send((MSG_BEAT, worker_id, incarnation,
                               time.time(), gauges)):
                # undeliverable beat: the pipe may be CLOSED (supervisor
                # gone — the main loop's EOF owns that) or merely
                # STALLED past the send guard's bound.  Either way the
                # right move is to skip this beat and keep beating: a
                # heartbeat thread that exits on one stalled send leaves
                # a healthy worker permanently silent, and the
                # supervisor would kill it for the supervisor's own
                # congestion
                continue
            if exporter is not None:
                # continuous telemetry piggybacks the beat cadence; the
                # exporter applies the same skip-never-block discipline
                # (a stalled pipe costs this delta, not the thread)
                exporter.export(sconn.send)

    def waiter(rid: int, resp) -> None:
        resp.wait()  # the engine guarantees a terminal state
        if resp.status == OK:
            err = None
            value = resp.value
        else:
            err = (type(resp.error).__name__ if resp.error is not None
                   else resp.status,
                   str(resp.error) if resp.error is not None else "")
            value = None
        if not sconn.send((MSG_RESULT, rid, resp.status, value, err)):
            # the value may be unpicklable even though the pipe is fine:
            # degrade to an in-band error so the lease still terminates
            sconn.send((MSG_RESULT, rid, "error", None,
                        ("UnserializableResult",
                         f"result of rid {rid} could not cross the pipe")))
        _flight.record(_flight.EV_LEASE_DONE, resp.task_id,
                       detail=f"rid:{rid}:worker:{worker_id}:{resp.status}")

    beat_thread = threading.Thread(target=heartbeat, daemon=True,
                                   name=f"serve-worker-{worker_id}-beat")
    beat_thread.start()
    _set_shuffle_uplink((sconn.send, worker_id, incarnation))
    sconn.send((MSG_HELLO, worker_id, incarnation, os.getpid()))

    try:
        while True:
            msg = sconn.recv()
            if msg is None:
                break  # supervisor died: crash-only both directions
            tag = msg[0]
            if tag == MSG_SHUTDOWN:
                dump_epilogue[0] = bool(msg[1])
                break
            if tag == MSG_PRESSURE:
                engine.note_cluster_pressure(dict(msg[1]))
                continue
            if tag == MSG_SHUFFLE_MAP or tag == MSG_SHUFFLE_CLEANUP:
                _route_shuffle_msg(msg)
                continue
            if tag == MSG_TABLE_BUMP:
                # lazy: workers that never see a bump never import the
                # models package.  advance_to runs the result cache's
                # invalidation listener synchronously on this thread, so
                # by the next dispatch the stale entries are gone.
                from spark_rapids_jni_tpu.models import tables as _tables

                _tables.advance_to(msg[1], msg[2])
                continue
            if tag != MSG_DISPATCH:
                continue
            (_, rid, handler, payload, deadline_rel_s, priority, trace,
             tenant) = msg
            try:
                resp = engine.submit(sess, handler, payload,
                                     priority=priority,
                                     deadline_s=deadline_rel_s,
                                     trace=_trace.from_wire(trace),
                                     tenant=tenant)
            # analyze: ignore[retry-protocol] - submit crosses no seam
            # (admission only); failures here are flow control
            # (Backpressure -> BUSY re-queue upstream) or setup bugs
            # (unknown handler), both reported in-band to the supervisor
            except Exception as e:  # noqa: BLE001
                from spark_rapids_jni_tpu.serve.queue import Backpressure

                status = (STATUS_BUSY if isinstance(e, Backpressure)
                          else "error")
                sconn.send((MSG_RESULT, rid, status, None,
                            (type(e).__name__, str(e))))
                continue
            _flight.record(_flight.EV_LEASE_GRANT, resp.task_id,
                           detail=f"rid:{rid}:worker:{worker_id}:local")
            threading.Thread(target=waiter, args=(rid, resp), daemon=True,
                             name=f"serve-worker-{worker_id}-rid{rid}").start()
    finally:
        stop.set()
        _set_shuffle_uplink(None)
        if dump_epilogue[0]:
            # end-of-run ring dump so the --cluster merge has this
            # process's timeline even when nothing anomalous happened here
            _flight.anomaly("cluster_epilogue",
                            detail=f"worker:{worker_id}:inc:{incarnation}")
        engine.shutdown(drain=False, timeout=5.0)
        gov.close()
        sconn.close()
