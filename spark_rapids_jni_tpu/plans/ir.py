"""Query-plan IR: structural, hashable descriptions of whole pipelines.

The reference exposes one JNI entry point per physical op, and Spark's
physical operators pay one kernel launch and one materialization per op;
our model runners inherited that shape (BENCH_r05: q5_rollup at 0.11
Mrows/s is per-op dispatch overhead, not compute).  *Flare* (PAPERS.md)
shows the step-change fix: compile the WHOLE pipeline into one native
program.  This module is the plan vocabulary that makes a pipeline a
*value* — every node is a frozen dataclass whose fields are static
python scalars, strings, tuples or other nodes, so a plan is hashable
and equality-comparable, and (plan, dtype signature, pow2 batch bucket)
can key a compiled-program cache (plans/cache.py).

Two layers:

- **expressions** (:class:`Col`/:class:`Lit`/:class:`Bin`/:class:`Unary`/
  :class:`Cast`) — elementwise column math, evaluated by the compiler
  against an environment of traced arrays;
- **nodes** — the relational operators the NDS queries need, each mapped
  by plans/compiler.py onto the existing ops/ and columnar/ primitives:
  :class:`Scan` (sharded fact input), :class:`Dim` (replicated dimension
  input), :class:`Filter`, :class:`Project`, :class:`GatherJoin` (dense
  surrogate-key join = replicated-table gather), :class:`SemiJoinWindow`
  (date-dim membership via searchsorted — q5's broadcast-join analog),
  :class:`SegmentAgg` (masked segment sums into a dense group space),
  :class:`Union` (tagged row concat), :class:`Exchange` (the all_to_all
  hash shuffle), :class:`PresenceCount` (q97's sort-merge presence
  counting) — and the order-sensitive tier: :class:`RangeExchange` (the
  cross-process range shuffle a distributed sort rides),
  :class:`Window` (rank/dense_rank/row_number and framed sum/min/max
  over sorted runs, plans/window.py), and the :class:`Sort`/:class:`TopK`
  sinks that emit globally ordered row vectors.

A :class:`Plan` bundles sink nodes (aggregate producers) with post
expressions over their outputs; the compiler traces all of it into ONE
jitted program, psum-ing sink outputs over the data axis when a mesh is
given.  Row-level validity is implicit: every Scan carries a runtime
row-valid input (pad rows the executor appends are False) AND'd into the
pipeline mask, so padding to the pow2 bucket lattice never changes
results.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Tuple, Union as _U

__all__ = [
    "Expr", "Col", "Lit", "Bin", "Unary", "Cast",
    "Node", "Scan", "Dim", "Filter", "Project", "GatherJoin",
    "SemiJoinWindow", "SegmentAgg", "Union", "Exchange", "PresenceCount",
    "RangeExchange", "WinFunc", "Window", "Sort", "TopK",
    "Plan", "col", "lit", "band_all", "plan_signature",
    "order_sink", "range_exchange_nodes", "has_any_exchange",
]


# --------------------------------------------------------------- expressions

BIN_OPS = ("add", "sub", "mul", "and", "or", "eq", "ne", "ge", "gt", "le",
           "lt", "min", "max", "shl", "band", "bor")
UNARY_OPS = ("not", "neg")


@dataclasses.dataclass(frozen=True)
class Col:
    """Reference to a column of the current row environment (or, in a
    Plan's ``post`` expressions, to a named sink output vector)."""

    name: str


@dataclasses.dataclass(frozen=True)
class Lit:
    """A static scalar literal.  Part of the plan *structure*: two plans
    differing only in a literal are different plans (and cache entries),
    exactly like the lru keys of the per-query step caches they replace."""

    value: _U[int, bool]


@dataclasses.dataclass(frozen=True)
class Bin:
    op: str  # one of BIN_OPS
    lhs: "Expr"
    rhs: "Expr"

    def __post_init__(self):
        if self.op not in BIN_OPS:
            raise ValueError(f"unknown binary op {self.op!r}")


@dataclasses.dataclass(frozen=True)
class Unary:
    op: str  # one of UNARY_OPS
    x: "Expr"

    def __post_init__(self):
        if self.op not in UNARY_OPS:
            raise ValueError(f"unknown unary op {self.op!r}")


@dataclasses.dataclass(frozen=True)
class Cast:
    x: "Expr"
    dtype: str  # "int8" | "int32" | "int64" | "uint64" | "bool"


Expr = _U[Col, Lit, Bin, Unary, Cast]


def col(name: str) -> Col:
    return Col(name)


def lit(value) -> Lit:
    # geometry scalars arrive as numpy ints from array mins/lens; normalize
    # so equal geometries always build EQUAL plans (the q5 step-cache
    # geometry-keying fix: a np.int64-keyed and an int-keyed plan must be
    # one cache entry, never two)
    if isinstance(value, bool):
        return Lit(value)
    return Lit(int(value))


def band_all(*exprs: Expr) -> Expr:
    """AND-fold a non-empty list of boolean expressions."""
    out = exprs[0]
    for e in exprs[1:]:
        out = Bin("and", out, e)
    return out


# --------------------------------------------------------------------- nodes


@dataclasses.dataclass(frozen=True)
class Scan:
    """Sharded fact input: ``fields`` of host table ``table`` ride the
    data axis.  The executor appends an implicit row-valid bool array
    (False on pad rows) that seeds the pipeline mask."""

    table: str
    fields: Tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class Dim:
    """Replicated dimension input (small table, uploaded whole)."""

    table: str
    fields: Tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class Filter:
    child: "Node"
    pred: Expr  # AND'd into the row mask


@dataclasses.dataclass(frozen=True)
class Project:
    child: "Node"
    cols: Tuple[Tuple[str, Expr], ...]  # (out_name, expr), added to the env


@dataclasses.dataclass(frozen=True)
class GatherJoin:
    """Dense surrogate-key inner-join: gather ``dim`` fields at
    ``clip(key - base, 0, len-1)``.  Out-of-range / null keys must be
    excluded by a Filter on the pipeline mask (the gather itself clips,
    matching the per-op device bodies bit for bit)."""

    child: "Node"
    dim: Dim
    key: Expr
    base: Expr  # usually lit(1) (1-based sks) or lit(date_sk0)
    fields: Tuple[Tuple[str, str], ...]  # (dim_field, out_name)


@dataclasses.dataclass(frozen=True)
class SemiJoinWindow:
    """q5's date-dim membership: mask &= (key found in dim.sk_field via
    searchsorted) AND (dim.days_field in [lo, hi)) AND key_valid."""

    child: "Node"
    dim: Dim
    key: Expr
    key_valid: Expr
    sk_field: str
    days_field: str
    lo: Expr
    hi: Expr


@dataclasses.dataclass(frozen=True)
class SegmentAgg:
    """Masked segment sums into ``num_segments`` dense buckets.

    ``key`` is the 0-based segment id; masked rows scatter-drop.  Each
    agg is ``(output_name, value_expr, dtype)`` — the classic additive
    partial vector, exact over any disjoint row partition (what the
    plan-level SplitAndRetry relies on)."""

    child: "Node"
    key: Expr
    num_segments: int
    aggs: Tuple[Tuple[str, Expr, str], ...]


@dataclasses.dataclass(frozen=True)
class Union:
    """Tagged row concat of pipelines sharing column names; adds an int8
    ``tag`` column carrying ``tag_values[i]`` for child ``i``."""

    children: Tuple["Node", ...]
    tag: str
    tag_values: Tuple[int, ...]


@dataclasses.dataclass(frozen=True)
class Exchange:
    """The all_to_all hash shuffle (parallel/shuffle.py): co-locate rows
    by ``partition_of(key) % ndev`` into fixed ``capacity`` buckets.
    Capacity is static plan structure (one compiled variant per pow2
    capacity, as before); overflow surfaces through the plan's implicit
    ``dropped`` output for the grow retry.  Mesh-only: a local plan must
    not contain an Exchange."""

    child: "Node"
    key: Expr
    capacity: int
    fields: Tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class RangeExchange:
    """The cross-process RANGE shuffle (serve/shuffle.py): co-locate rows
    into CONTIGUOUS key ranges so partition ``p``'s every row orders
    before partition ``p+1``'s — the shape that makes a distributed sort
    a per-shard sort plus an ordered concatenation (the classic
    sample -> splitters -> shuffle-by-range plan Flare compiles).

    ``keys`` are ``(expr, ascending)`` sort keys; splitters are NOT plan
    structure — they are sampled from the data at dispatch time and ride
    the shard payloads, so one compiled reduce program serves every
    dataset.  ``limit`` pushes a partial top-k below the shuffle: each
    map shard sends only its ``limit`` first-ordered rows, so at most
    ``limit * shards`` rows cross the wire.

    Cross-process only: there is no in-mesh emitter (psum cannot merge
    ordered row vectors) — compile_plan refuses a plan containing one;
    execution goes through split_exchange_plan + the serve shuffle
    plane (or its single-process oracle)."""

    child: "Node"
    keys: Tuple[Tuple[Expr, bool], ...]  # (key expr, ascending)
    fields: Tuple[str, ...]
    limit: _U[int, None] = None


WINDOW_FUNCS = ("rank", "dense_rank", "row_number", "sum", "min", "max")


@dataclasses.dataclass(frozen=True)
class WinFunc:
    """One window function column: ``rank``/``dense_rank``/``row_number``
    need no argument; ``sum``/``min``/``max`` aggregate ``arg`` over the
    ROWS frame ``[current - preceding, current]`` (``preceding=None`` =
    UNBOUNDED PRECEDING) within the partition, in order."""

    name: str
    kind: str  # one of WINDOW_FUNCS
    arg: _U[Col, Lit, Bin, Unary, Cast, None] = None
    dtype: str = "int64"
    preceding: _U[int, None] = None

    def __post_init__(self):
        if self.kind not in WINDOW_FUNCS:
            raise ValueError(f"unknown window function {self.kind!r}")
        if self.kind in ("sum", "min", "max") and self.arg is None:
            raise ValueError(f"window {self.kind} requires an arg expr")
        if self.preceding is not None and self.kind in (
                "rank", "dense_rank", "row_number"):
            raise ValueError(f"window {self.kind} takes no frame")


@dataclasses.dataclass(frozen=True)
class Window:
    """Window functions over sorted runs: rows reorder by
    ``(partition_by, order_by)`` (invalid rows last), every run of equal
    partition keys becomes one segment, and each :class:`WinFunc` appends
    a column computed by segment-scan primitives (plans/window.py).
    Downstream nodes (Filter on a rank, a Sort sink) see the reordered
    row environment."""

    child: "Node"
    partition_by: Tuple[Expr, ...]
    order_by: Tuple[Tuple[Expr, bool], ...]  # (expr, ascending)
    funcs: Tuple[WinFunc, ...]


@dataclasses.dataclass(frozen=True)
class Sort:
    """Order-sensitive SINK: emit ``fields`` as row vectors ordered by
    ``keys`` (invalid rows sort last and are excluded from the implicit
    ``rows`` count output).  Local-compile only — a distributed sort is
    a RangeExchange below this sink plus an ordered concatenation of the
    per-partition results."""

    child: "Node"
    keys: Tuple[Tuple[Expr, bool], ...]
    fields: Tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class TopK:
    """Order-sensitive SINK: the first ``k`` rows by ``keys``.  Output
    vectors are ``min(k, padded_rows)`` long; ``rows`` counts the valid
    ones (``K > total rows`` simply returns them all)."""

    child: "Node"
    keys: Tuple[Tuple[Expr, bool], ...]
    k: int
    fields: Tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class PresenceCount:
    """q97's sort-merge presence counting over co-located tagged rows:
    for every distinct valid key, which sources appear?  Emits the three
    scalar outputs named in ``names``."""

    child: "Node"
    key: str
    tag: str
    names: Tuple[str, str, str] = ("store_only", "catalog_only", "both")


Node = _U[Scan, Dim, Filter, Project, GatherJoin, SemiJoinWindow,
          SegmentAgg, Union, Exchange, PresenceCount,
          RangeExchange, Window, Sort, TopK]

#: the concrete node classes _walk recurses into (single source of truth
#: — a node type missing here is invisible to scan/dim/exchange discovery)
NODE_TYPES = (Scan, Dim, Filter, Project, GatherJoin, SemiJoinWindow,
              SegmentAgg, Union, Exchange, PresenceCount,
              RangeExchange, Window, Sort, TopK)


# ---------------------------------------------------------------------- plan


@dataclasses.dataclass(frozen=True)
class Plan:
    """A whole query pipeline: sink nodes produce named aggregate arrays
    (psum'd over the data axis under a mesh), then ``post`` expressions
    compute derived outputs over those vectors — all inside ONE jitted
    program.  ``outputs`` orders/filters what the compiled program
    returns (empty = every sink output, then every post output)."""

    name: str
    sinks: Tuple[Node, ...]
    post: Tuple[Tuple[str, Expr], ...] = ()
    outputs: Tuple[str, ...] = ()


def _walk(node) -> list:
    out = [node]
    for f in dataclasses.fields(node):
        v = getattr(node, f.name)
        if dataclasses.is_dataclass(v) and isinstance(v, NODE_TYPES):
            out.extend(_walk(v))
        elif isinstance(v, tuple):
            for item in v:
                if dataclasses.is_dataclass(item) and isinstance(
                        item, NODE_TYPES):
                    out.extend(_walk(item))
    return out


def walk(plan: Plan) -> list:
    """Every node of every sink, preorder (duplicates preserved)."""
    out = []
    for sink in plan.sinks:
        out.extend(_walk(sink))
    return out


@functools.lru_cache(maxsize=256)
def scan_tables(plan: Plan) -> Tuple[Scan, ...]:
    """Distinct Scan nodes, ordered by table name (the executor's stable
    argument order).  Cached — plans are immutable values and this runs
    on the per-request hot path (execute_plan + pad_tables)."""
    seen = {}
    for n in walk(plan):
        if isinstance(n, Scan):
            prev = seen.setdefault(n.table, n)
            if prev != n:
                raise ValueError(
                    f"conflicting Scan field sets for table {n.table!r}")
    return tuple(seen[t] for t in sorted(seen))


@functools.lru_cache(maxsize=256)
def dim_tables(plan: Plan) -> Tuple[Dim, ...]:
    """Distinct Dim nodes, ordered by table name.  Cached (hot path)."""
    seen = {}
    for n in walk(plan):
        if isinstance(n, (GatherJoin, SemiJoinWindow)):
            prev = seen.setdefault(n.dim.table, n.dim)
            if prev != n.dim:
                raise ValueError(
                    f"conflicting Dim field sets for table {n.dim.table!r}")
    return tuple(seen[t] for t in sorted(seen))


@functools.lru_cache(maxsize=256)
def exchange_nodes(plan: Plan) -> Tuple[Exchange, ...]:
    """Every Exchange in the plan, preorder.  Cached (hot path: the
    working-set estimate runs per governed admission)."""
    return tuple(n for n in walk(plan) if isinstance(n, Exchange))


def has_exchange(plan: Plan) -> bool:
    return bool(exchange_nodes(plan))


@functools.lru_cache(maxsize=256)
def range_exchange_nodes(plan: Plan) -> Tuple[RangeExchange, ...]:
    """Every RangeExchange in the plan, preorder.  Cached (hot path)."""
    return tuple(n for n in walk(plan) if isinstance(n, RangeExchange))


def has_any_exchange(plan: Plan) -> bool:
    """Hash OR range exchange: either makes the plan non-local (the hash
    kind needs a mesh, the range kind needs the cross-process split)."""
    return bool(exchange_nodes(plan)) or bool(range_exchange_nodes(plan))


@functools.lru_cache(maxsize=256)
def order_sink(plan: Plan):
    """The plan's Sort/TopK sink, or None.  Ordered row output cannot
    coexist with additive sinks (they combine by summation, ordered rows
    by concatenation — one plan, one combine discipline), so mixing or
    repeating order sinks is a structural error."""
    order = [s for s in plan.sinks if isinstance(s, (Sort, TopK))]
    if not order:
        return None
    if len(order) > 1 or len(plan.sinks) > 1:
        raise ValueError(
            f"plan {plan.name!r} mixes an order-sensitive sink with other "
            f"sinks; a Sort/TopK sink must be the plan's only sink")
    return order[0]


@functools.lru_cache(maxsize=256)
def plan_signature(plan: Plan) -> str:
    """Short stable id for telemetry/seam labels (not the cache key — the
    cache keys on the plan value itself).  Deterministic ACROSS processes
    (hashlib over the canonical repr, not salted ``hash()``): a faultinj
    rule or cross-run trace correlation pinned to a label from one run's
    flight dump must match the next run's."""
    import hashlib

    digest = hashlib.sha1(repr(plan).encode()).hexdigest()[:8]
    return f"{plan.name}:{digest}"  # lru-cached: repr+sha1 paid once
