"""Plan-level compilation: whole query pipelines as single jitted programs.

- :mod:`plans.ir` — the hashable plan vocabulary (scan/filter/project/
  join/aggregate/exchange nodes over the existing ops/columnar
  primitives);
- :mod:`plans.compiler` — traces a plan into ONE jitted (shard_map'd)
  program, bit-identical to the per-op path;
- :mod:`plans.cache` — compiled variants keyed on (plan structure,
  dtype signature, pow2 batch bucket), gauges through serve/metrics and
  obs/flight;
- :mod:`plans.runtime` — the governed bracket at plan granularity (one
  admission, one retry/split boundary, one flight task per plan);
- :mod:`plans.rcache` — the governed multi-tier RESULT cache (round 15):
  hot queries skip compute entirely, keyed on (plan/handler, input
  content fingerprint, bucket signature, table versions), resident
  HBM -> host -> disk under the same byte budgets as live queries.
"""

from spark_rapids_jni_tpu.plans import ir
from spark_rapids_jni_tpu.plans.cache import CompiledPlan, plan_cache
from spark_rapids_jni_tpu.plans.compiler import (
    RaggedProgram,
    cached_compile,
    cached_ragged_compile,
    compile_plan,
    compile_ragged,
    input_signature,
    output_names,
)
from spark_rapids_jni_tpu.plans.rcache import ResultCache, result_cache
from spark_rapids_jni_tpu.plans.runtime import (
    combine_outputs,
    execute_plan,
    pad_tables,
    plan_working_set_bytes,
    run_governed_plan,
    split_scan_tables,
)

__all__ = [
    "ir",
    "CompiledPlan",
    "RaggedProgram",
    "ResultCache",
    "plan_cache",
    "result_cache",
    "cached_compile",
    "cached_ragged_compile",
    "compile_plan",
    "compile_ragged",
    "input_signature",
    "output_names",
    "combine_outputs",
    "execute_plan",
    "pad_tables",
    "plan_working_set_bytes",
    "run_governed_plan",
    "split_scan_tables",
]
