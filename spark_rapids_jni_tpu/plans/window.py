"""Order-sensitive execution primitives: sort keys, sorted runs, and
segment-scan window functions.

Everything order-related reduces to ONE canonical transform:
:func:`sort_rank` maps a column to uint64 ranks whose unsigned ascending
order IS the column's SQL order (descending keys bit-flip, floats use
the IEEE total order with Spark's NaN/±0.0 canonicalization: -0.0 == 0.0
and every NaN is one largest value).  Rank vectors are what everything
downstream consumes — the traced Sort/Window/TopK emitters lexsort them
(compiler.py), and the HOST side samples them to choose range splitters
and assign shuffle partitions (:func:`choose_splitters` /
:func:`range_partition`), so the device order and the cross-process
partition order can never disagree.

Window functions run on sorted runs, the q97 ``_count_runs`` idiom
generalized: equal-partition-key rows form segments (run boundaries from
rank change points), and rank/dense_rank/row_number plus running
sum/min/max with ROWS-frame semantics all come from segment scans —
``cummax`` over start indices, segmented ``associative_scan``, and
cumsum differences.  Static shapes throughout (XLA-friendly: no dynamic
grouping), invalid rows sort last and form their own runs so they can
never contaminate a valid segment's aggregate.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "sort_rank", "sort_rank_np", "order_permutation", "run_boundaries",
    "change_points", "segment_start_indices", "row_number", "rank",
    "dense_rank", "framed_sum", "framed_minmax",
    "choose_splitters", "range_partition",
]

_SIGN = np.uint64(1) << np.uint64(63)
#: one canonical quiet-NaN bit pattern (Spark: all NaNs equal, largest)
_CANON_NAN = np.int64(0x7FF8000000000000)


# ------------------------------------------------------------- sort ranks


# twin: sort_rank
def sort_rank(x, ascending: bool = True):
    """uint64 ranks whose unsigned ascending order is ``x``'s sort order.

    - ints/bool: sign-bias to uint64 (order-preserving);
    - floats: widen to float64, canonicalize ``-0.0 -> +0.0`` and every
      NaN to one quiet-NaN pattern (NaN == NaN, NaN largest — Spark's
      ordering), then the IEEE total-order transform;
    - ``ascending=False`` bit-flips, so a descending key is just another
      ascending uint64 — lexsort and splitters never special-case
      direction.
    """
    x = jnp.asarray(x)
    if jnp.issubdtype(x.dtype, jnp.floating):
        f = x.astype(jnp.float64)
        f = jnp.where(f == 0.0, 0.0, f)  # -0.0 and +0.0 are one value
        bits = jax.lax.bitcast_convert_type(f, jnp.int64)
        bits = jnp.where(jnp.isnan(f), jnp.int64(_CANON_NAN), bits)
        u = jnp.where(bits < 0,
                      ~bits.astype(jnp.uint64),
                      bits.astype(jnp.uint64) | jnp.uint64(_SIGN))
    elif x.dtype == jnp.bool_:
        u = x.astype(jnp.uint64)
    else:
        u = x.astype(jnp.int64).astype(jnp.uint64) ^ jnp.uint64(_SIGN)
    return u if ascending else ~u


# twin: sort_rank
def sort_rank_np(x: np.ndarray, ascending: bool = True) -> np.ndarray:
    """Host twin of :func:`sort_rank`, bit-identical — splitter choice
    and range partitioning happen on numpy shards, and the partition a
    row lands in must agree exactly with the order the traced reduce
    side sorts it into."""
    x = np.asarray(x)
    if np.issubdtype(x.dtype, np.floating):
        f = x.astype(np.float64)
        f = np.where(f == 0.0, 0.0, f)
        bits = f.view(np.int64).copy()
        bits[np.isnan(f)] = _CANON_NAN
        u = np.where(bits < 0,
                     ~bits.view(np.uint64),
                     bits.view(np.uint64) | _SIGN)
    elif x.dtype == np.bool_:
        u = x.astype(np.uint64)
    else:
        u = x.astype(np.int64).view(np.uint64) ^ _SIGN
    return u if ascending else ~u


def order_permutation(ranks: Sequence, valid):
    """The gather permutation sorting rows by ``ranks`` (major key
    first), valid rows before invalid — the multi-key generalization of
    q97's sentinel argsort.  jnp.lexsort is stable, so equal-key rows
    keep their input order."""
    invalid = (~valid).astype(jnp.uint8)
    return jnp.lexsort(tuple(reversed(list(ranks))) + (invalid,))


# ------------------------------------------------------------ sorted runs


def change_points(ranks: Sequence):
    """Row i differs from row i-1 in ANY rank column (row 0 is True) —
    the run-start primitive over already-sorted rank columns."""
    out = None
    for r in ranks:
        prev = jnp.concatenate([~r[:1], r[:-1]])
        c = r != prev
        out = c if out is None else (out | c)
    n = out.shape[0]
    return out.at[0].set(True) if n else out


def run_boundaries(part_ranks: Sequence, valid):
    """Run starts over sorted partition-key ranks, with the validity
    flag as an extra key: the first invalid row (they sort last) always
    opens a new run, so invalid garbage can never extend a valid
    segment."""
    return change_points(list(part_ranks) + [valid.astype(jnp.uint8)])


def segment_start_indices(run_start):
    """For every row, the index of its run's first row (monotone cummax
    over start positions — run_start[0] is True by construction)."""
    n = run_start.shape[0]
    idx = jnp.arange(n, dtype=jnp.int64)
    return jax.lax.cummax(jnp.where(run_start, idx, jnp.int64(0)))


# ------------------------------------------------------ window functions


def row_number(run_start):
    """1-based position within the run."""
    n = run_start.shape[0]
    idx = jnp.arange(n, dtype=jnp.int64)
    return idx - segment_start_indices(run_start) + 1


def rank(run_start, order_change):
    """SQL rank: 1 + number of rows strictly before this row's tie
    group.  Depends only on key VALUES (ties share a rank), never on the
    within-tie order — what keeps ranked outputs deterministic under a
    stable-but-arbitrary tie order."""
    n = run_start.shape[0]
    idx = jnp.arange(n, dtype=jnp.int64)
    change = run_start | order_change
    group_start = jax.lax.cummax(jnp.where(change, idx, jnp.int64(0)))
    return group_start - segment_start_indices(run_start) + 1


def dense_rank(run_start, order_change):
    """SQL dense_rank: 1 + number of DISTINCT order keys before this
    row's within its run."""
    change = run_start | order_change
    c = jnp.cumsum(change.astype(jnp.int64))
    seg0 = segment_start_indices(run_start)
    return c - c[seg0] + 1


def framed_sum(v, run_start, preceding: Optional[int] = None):
    """Running sum over the ROWS frame ``[i - preceding, i]`` within the
    run (``preceding=None`` = UNBOUNDED PRECEDING), via cumsum
    differences clamped at the segment start — exact for int dtypes."""
    n = v.shape[0]
    idx = jnp.arange(n, dtype=jnp.int64)
    seg0 = segment_start_indices(run_start)
    cs = jnp.cumsum(v)
    if preceding is None:
        lo = seg0
    else:
        lo = jnp.maximum(seg0, idx - int(preceding))
    base = jnp.where(lo > 0, cs[jnp.maximum(lo - 1, 0)],
                     jnp.zeros((), v.dtype))
    return cs - base


def _seg_scan(v, run_start, op):
    """Segmented inclusive scan: the classic (flag, value) associative
    combine — a start flag resets the accumulation."""
    def combine(a, b):
        fa, va = a
        fb, vb = b
        return fa | fb, jnp.where(fb, vb, op(va, vb))

    _f, out = jax.lax.associative_scan(combine, (run_start, v))
    return out


def framed_minmax(v, run_start, kind: str, preceding: Optional[int] = None):
    """Running min/max over the ROWS frame ``[i - preceding, i]`` within
    the run.  Unbounded frames use one segmented associative scan;
    bounded frames unroll ``preceding`` identity-filled shifts (static,
    small — the plan value bakes the frame in)."""
    op = jnp.minimum if kind == "min" else jnp.maximum
    if preceding is None:
        return _seg_scan(v, run_start, op)
    ident = (jnp.iinfo(v.dtype).max if kind == "min"
             else jnp.iinfo(v.dtype).min) if jnp.issubdtype(
                 v.dtype, jnp.integer) else (
                     jnp.inf if kind == "min" else -jnp.inf)
    n = v.shape[0]
    idx = jnp.arange(n, dtype=jnp.int64)
    seg0 = segment_start_indices(run_start)
    out = v
    # a shift of >= n rows contributes only identity — cap the unroll so
    # frames wider than the batch stay shape-correct
    for j in range(1, min(int(preceding), max(n - 1, 0)) + 1):
        shifted = jnp.concatenate([jnp.full((j,), ident, v.dtype), v[:-j]])
        out = op(out, jnp.where(idx - j >= seg0, shifted,
                                jnp.asarray(ident, v.dtype)))
    return out


# ------------------------------------------- host-side range partitioning


def choose_splitters(rank_cols: Sequence[np.ndarray], valid: np.ndarray,
                     nparts: int, sample_cap: int = 4096
                     ) -> List[Tuple[int, ...]]:
    """``nparts - 1`` composite-rank splitters from an even row sample:
    sort the sampled rank tuples lexicographically and take the
    quantile boundaries.  Returned as tuples of python ints (payload-
    serializable; every map shard must receive the SAME splitters).

    Degenerate inputs degrade safely: heavy skew yields duplicate
    splitters (equal keys all land in one partition — imbalanced but
    correct), and an empty sample yields all-zero splitters (every row
    ranks after them, partition ``nparts - 1`` takes the lot)."""
    valid = np.asarray(valid, bool)
    sel = np.flatnonzero(valid)
    if sel.size > sample_cap:
        sel = sel[np.linspace(0, sel.size - 1, sample_cap).astype(np.int64)]
    if sel.size == 0:
        return [tuple(0 for _ in rank_cols) for _ in range(nparts - 1)]
    sample = [np.asarray(r)[sel] for r in rank_cols]
    order = np.lexsort(tuple(reversed(sample)))
    n = sel.size
    out = []
    for p in range(1, nparts):
        at = order[min(n - 1, n * p // nparts)]
        out.append(tuple(int(r[at]) for r in sample))
    return out


def range_partition(rank_cols: Sequence[np.ndarray],
                    splitters: Sequence[Tuple[int, ...]]) -> np.ndarray:
    """Partition index per row: how many splitters order strictly before
    the row's composite rank (rows equal to splitter ``p`` stay in
    partition ``p``).  Concatenating partitions in index order therefore
    yields globally sorted rows — the merge-free distributed sort."""
    n = len(np.asarray(rank_cols[0]))
    part = np.zeros(n, np.int64)
    for s in splitters:
        gt = np.zeros(n, bool)
        eq = np.ones(n, bool)
        for rc, sv in zip(rank_cols, s):
            rc = np.asarray(rc)
            sv = np.uint64(sv)
            gt |= eq & (rc > sv)
            eq &= rc == sv
        part += gt
    return part
