"""Governed plan execution: the memory bracket at PLAN granularity.

The per-op runners bracketed every launch separately — admission, retry,
split and flight-recorder task per op.  A compiled plan is one program,
so the protocol moves up a level: ONE admission covers the whole fused
pipeline's working set, ONE retry/split boundary re-executes the whole
fused program (on RetryOOM the same batch re-runs; on SplitAndRetryOOM
every scan table halves and the fused program runs per half, partials
combining by addition), and ONE flight-recorder task brackets the plan
(docs/OBSERVABILITY.md).  This is exactly the reference protocol
(RmmSpark.java:402-416) applied to a Flare-style fused pipeline instead
of a physical op.

Padding discipline: scan tables are padded to the dp-aligned
pow2-quantized length (``parallel.shuffle.quantized_rows`` — the bucket
lattice the plan cache keys on) with an appended row-valid array, False
on pad rows, that the compiler ANDs into the pipeline mask — more
padding never changes results, and a long-lived executor holds
O(log rows) compiled variants per plan, not one per distinct length.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from spark_rapids_jni_tpu.obs import flight as _flight
from spark_rapids_jni_tpu.plans import ir
from spark_rapids_jni_tpu.plans.cache import plan_cache
from spark_rapids_jni_tpu.plans.compiler import (
    VALID_FIELD,
    cached_compile,
)

__all__ = ["pad_tables", "plan_working_set_bytes", "execute_plan",
           "run_governed_plan", "split_scan_tables", "combine_outputs",
           "input_signature_raw", "compiled_plan_for",
           "plan_retry_stats", "suggested_presplit_depth",
           "reset_plan_retry_stats"]

Tables = Dict[str, Dict[str, np.ndarray]]


# --------------------------------------------------------------------------
# per-plan retry statistics (adaptive admission, round 9)
#
# Every governed plan execution records its retry/split history per PLAN
# NAME — the request-class granularity the admission controller steers on.
# ``suggested_presplit_depth`` turns that history into a pre-emptive split
# depth: a plan whose recent runs SplitAndRetried starts its next run
# already split, skipping the doomed full-size attempt (and its blocked
# windows).  The hint DECAYS — one depth level per ``_PRESPLIT_DECAY_S``
# without a new split — so a transient pressure episode doesn't pin small
# pieces forever.  Gated on the serve_adaptive flag (and the controller
# kill switch), so static configurations are bit-identical to round 8.
# --------------------------------------------------------------------------

_PRESPLIT_DECAY_S = 30.0
_STATS_LOCK = threading.Lock()
_PLAN_STATS: Dict[str, dict] = {}


def _stats_entry(name: str) -> dict:
    st = _PLAN_STATS.get(name)
    if st is None:
        st = _PLAN_STATS[name] = {
            "runs": 0, "retries": 0, "split_retries": 0,
            "presplit_depth": 0, "last_split_t": 0.0,
        }
    return st


def _record_plan_retry(name: str) -> None:
    with _STATS_LOCK:
        _stats_entry(name)["retries"] += 1


def _note_plan_run(name: str, presplit: int, reactive_splits: int,
                   max_depth: int) -> None:
    """Record one completed run: the observed total depth (pre-splits plus
    the depth implied by REACTIVE split events — pre-split invocations of
    the split callback are excluded, or the hint could never decay)
    becomes the new hint when it exceeds the decayed current one."""
    observed = presplit
    if reactive_splits > 0:
        observed += max(1, (reactive_splits + 1).bit_length() - 1)
    now = time.monotonic()
    with _STATS_LOCK:
        st = _stats_entry(name)
        st["runs"] += 1
        if reactive_splits > 0:
            st["split_retries"] += reactive_splits
            st["last_split_t"] = now
        # collapse the stored hint to its decayed value first, so a long-
        # faded episode doesn't resurrect at full depth on the next split
        st["presplit_depth"] = min(
            max(observed, _decayed_depth(st, now)), max_depth)


def _decayed_depth(st: dict, now: float) -> int:
    if st["presplit_depth"] <= 0 or st["last_split_t"] <= 0.0:
        return 0
    faded = int((now - st["last_split_t"]) / _PRESPLIT_DECAY_S)
    return max(0, st["presplit_depth"] - faded)


def plan_retry_stats() -> Dict[str, dict]:
    """Per-plan retry/split history (non-destructive copy), with the
    decayed ``suggested_depth`` the next run would start at."""
    now = time.monotonic()
    with _STATS_LOCK:
        return {name: dict(st, suggested_depth=_decayed_depth(st, now))
                for name, st in _PLAN_STATS.items()}


def suggested_presplit_depth(name: str, max_depth: int = 8) -> int:
    """Pre-emptive split depth for the next run of plan ``name`` (0 =
    attempt full size).  Returns 0 unless adaptive admission is enabled
    AND the kill switch is clear — the static path must stay untouched."""
    from spark_rapids_jni_tpu import config

    if not config.get("serve_adaptive") or config.get(
            "serve_controller_freeze"):
        return 0
    now = time.monotonic()
    with _STATS_LOCK:
        st = _PLAN_STATS.get(name)
        if st is None:
            return 0
        return min(_decayed_depth(st, now), max_depth)


def reset_plan_retry_stats() -> None:
    with _STATS_LOCK:
        _PLAN_STATS.clear()


_flight.register_telemetry_source("plan_retry", plan_retry_stats)


def _quantized(n: int, dp: int) -> int:
    from spark_rapids_jni_tpu.parallel.shuffle import quantized_rows

    return quantized_rows(n, dp)


def pad_tables(plan: ir.Plan, tables: Tables, dp: int) -> Tables:
    """Pad every scan table onto the pow2 bucket lattice (dp-aligned) and
    append its row-valid array; dims pass through contiguous."""
    import jax

    scans = {s.table for s in ir.scan_tables(plan)}
    out: Tables = {}
    for table, fields in tables.items():
        if table not in scans:
            # already-uploaded device dims (run_governed_plan's one-time
            # hoist) pass through untouched; device_put on them later is
            # a no-op, so split pieces never re-pay the transfer
            out[table] = {k: v if isinstance(v, jax.Array)
                          else np.ascontiguousarray(v)
                          for k, v in fields.items()}
            continue
        n = len(next(iter(fields.values())))
        m = _quantized(n, dp)
        padded = {}
        for k, v in fields.items():
            if len(v) != n:
                raise ValueError(
                    f"ragged scan table {table!r}: field {k!r} has "
                    f"{len(v)} rows, expected {n}")
            if m == n:
                padded[k] = np.ascontiguousarray(v)
            else:
                padded[k] = np.concatenate(
                    [v, np.zeros(m - n, dtype=v.dtype)])
        valid = np.zeros(m, bool)
        valid[:n] = True
        padded[VALID_FIELD] = valid
        out[table] = padded
    return out


def input_signature_raw(plan: ir.Plan, tables: Tables, dp: int):
    """The padded-input signature of RAW (unpadded) ``tables`` — exactly
    what :func:`compiler.input_signature` returns for
    ``pad_tables(plan, tables, dp)``, computed from lengths and dtypes
    alone, with ZERO data movement.  This is how a caller that only
    wants the cached compiled step (make_distributed_q3/q5) looks it up
    without re-padding the whole dataset per call."""
    from spark_rapids_jni_tpu.plans.compiler import _arg_layout

    scans = {s.table for s in ir.scan_tables(plan)}
    sig = []
    for kind, table, field in _arg_layout(plan):
        if field == VALID_FIELD:
            n = len(next(iter(tables[table].values())))
            sig.append((kind, table, field, "bool", _quantized(n, dp)))
            continue
        a = tables[table][field]
        m = _quantized(len(a), dp) if table in scans else len(a)
        sig.append((kind, table, field, str(a.dtype), m))
    return tuple(sig)


def compiled_plan_for(plan: ir.Plan, mesh, tables: Tables):
    """The cached compiled step for (plan, mesh, ``tables``' geometry) —
    compile on miss, O(1) host work on hit (signature from lengths and
    dtypes, no padding copies)."""
    from spark_rapids_jni_tpu.plans.cache import plan_cache
    from spark_rapids_jni_tpu.plans.compiler import compile_plan

    if mesh is None:
        dp = 1
    else:
        from spark_rapids_jni_tpu.parallel.mesh import DATA_AXIS

        dp = mesh.shape[DATA_AXIS]
    sig = input_signature_raw(plan, tables, dp)
    return plan_cache.get_or_compile(
        (plan, mesh, sig), lambda: compile_plan(plan, mesh, sig))


def plan_working_set_bytes(plan: ir.Plan, tables: Tables, dp: int) -> int:
    """Admission estimate for one fused execution: quantized input bytes
    x3 (inputs + masks/buckets + partials headroom — the same margin the
    per-op runners reserved), plus exchange send/recv buffers for plans
    with a shuffle."""
    scans = {s.table for s in ir.scan_tables(plan)}
    total = 0
    for table, fields in tables.items():
        if table not in scans:
            continue
        for v in fields.values():
            total += _quantized(len(v), dp) * v.itemsize
    total *= 3
    for node in ir.exchange_nodes(plan):
        slots = dp * dp * node.capacity
        total += 2 * slots * (8 * len(node.fields) + 10)
    return total


def execute_plan(mesh, plan: ir.Plan, tables: Tables) -> Dict[str, np.ndarray]:
    """ONE fused launch: pad, compile (cached), upload, run, download.

    Raises :class:`mem.governed.ShuffleCapacityExceeded` when an
    Exchange overflowed (``dropped > 0``) — the caller grows the
    capacity and re-runs, like any shuffle-spill retry.  No governance
    here: callers bracket this (run_governed_plan, or the model runners'
    own drivers).
    """
    import jax

    from spark_rapids_jni_tpu.mem.governed import ShuffleCapacityExceeded
    from spark_rapids_jni_tpu.obs.seam import COLLECTIVE, TRANSFER, seam

    if mesh is None:
        dp = 1
        shardings = None
    else:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from spark_rapids_jni_tpu.parallel.mesh import DATA_AXIS

        dp = mesh.shape[DATA_AXIS]
        shardings = (NamedSharding(mesh, P(DATA_AXIS)),
                     NamedSharding(mesh, P()))
    padded = pad_tables(plan, tables, dp)
    compiled = cached_compile(plan, mesh, padded)
    sig = ir.plan_signature(plan)
    scans = {s.table for s in ir.scan_tables(plan)}
    with seam(TRANSFER, f"plan_upload:{plan.name}"):
        flat = []
        for _kind, table, field in _layout_of(compiled):
            arr = padded[table][field]
            if shardings is None:
                flat.append(jax.device_put(arr))
            else:
                flat.append(jax.device_put(
                    arr, shardings[0] if table in scans else shardings[1]))
    t0 = time.perf_counter()
    with seam(COLLECTIVE, f"launch:plan:{sig}"):
        out = compiled.fn(*flat)
        jax.block_until_ready(out)
    plan_cache.record_execute(time.perf_counter() - t0)
    outputs = {name: np.asarray(v)
               for name, v in zip(compiled.out_names, out)}
    if int(outputs.get("dropped", 0)) > 0:
        raise ShuffleCapacityExceeded(
            f"{int(outputs['dropped'])} rows overflowed the plan's "
            f"exchange capacity")
    return outputs


def _layout_of(compiled):
    for name in compiled.arg_names:
        table, field = name.split(".", 1)
        yield None, table, field


def _upload_dims(plan: ir.Plan, tables: Tables, mesh) -> Tables:
    """Hoist the replicated dim-table uploads to ONCE per governed
    bracket: the device arrays pass through pad_tables untouched and the
    per-piece device_put in execute_plan sees correctly-placed inputs (a
    no-op), so retry/split pieces never re-pay the transfer — the per-op
    q3 runner's deliberate hoist, kept at plan granularity."""
    import jax

    dims = ir.dim_tables(plan)
    if not dims:
        return tables
    rep = None
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        rep = NamedSharding(mesh, P())
    out = dict(tables)
    for d in dims:
        out[d.table] = {
            # analyze: ignore[governed-allocation] - small replicated dim
            # tables uploaded ONCE per governed bracket and shared by
            # every retry/split piece; uploading inside the bracket would
            # re-pay the transfer up to 2^max_split_depth times.  Their
            # bytes ride the working-set margin.
            k: jax.device_put(np.ascontiguousarray(v), rep)
            for k, v in tables[d.table].items()}
    return out


def split_scan_tables(tables: Tables, scans) -> List[Tables]:
    """Halve every scan table's rows (dims replicated into both halves).
    Exact for plans whose sinks are additive aggregates — every fused
    NDS plan here."""
    halves: List[Tables] = [{}, {}]
    scan_names = {s.table for s in scans}
    for table, fields in tables.items():
        if table not in scan_names:
            halves[0][table] = fields
            halves[1][table] = fields
            continue
        n = len(next(iter(fields.values())))
        halves[0][table] = {k: v[: n // 2] for k, v in fields.items()}
        halves[1][table] = {k: v[n // 2:] for k, v in fields.items()}
    return halves


def combine_outputs(results: Sequence[Dict[str, np.ndarray]]) -> Dict:
    """Element-wise sum of output dicts (additive partials)."""
    out = dict(results[0])
    for r in results[1:]:
        for k, v in r.items():
            out[k] = out[k] + v
    return out


def run_governed_plan(
    mesh,
    plan: ir.Plan,
    tables: Tables,
    *,
    budget=None,
    task_id: int = 0,
    manage_task: bool = True,
    nbytes_of: Optional[Callable[[Tables], int]] = None,
    split: Optional[Callable[[Tables], Sequence[Tables]]] = None,
    combine: Optional[Callable[[List[Any]], Any]] = None,
    max_split_depth: int = 8,
) -> Dict[str, np.ndarray]:
    """Execute ``plan`` under ONE governed bracket.

    The whole fused pipeline is admitted as one working set; RetryOOM
    re-runs the fused program on the same batch, SplitAndRetryOOM halves
    every scan table and re-executes the fused program per half (NOT a
    disband into per-op launches), and partial outputs combine by
    addition.  One flight-recorder task spans the plan.
    """
    from spark_rapids_jni_tpu import config
    from spark_rapids_jni_tpu.mem.governed import (
        default_device_budget,
        run_with_split_retry,
        task_context,
    )

    if mesh is None:
        dp = 1
    else:
        from spark_rapids_jni_tpu.parallel.mesh import DATA_AXIS

        dp = mesh.shape[DATA_AXIS]
    if budget is None:
        budget = default_device_budget()
    # the stats-driven rewriter runs FIRST (round 19): stats observed from
    # this upload seed the join-reorder rule, and the CANONICALIZED plan —
    # not the as-written one — keys the result cache below, so two queries
    # that rewrite to the same tree share one cached entry.  Memoized per
    # (plan, stats); off by default, so static configs never re-key.
    if config.get("plan_optimizer"):
        from spark_rapids_jni_tpu.models import tables as _tabreg
        from spark_rapids_jni_tpu.plans.optimizer import optimize_plan

        _tabreg.observe_tables(tables)
        plan = optimize_plan(plan)
    # the result cache consults BEFORE admission (round 15): a hit costs
    # a fingerprint pass over the raw host tables — never a reservation,
    # a retry bracket, or a launch.  Fingerprinted here, before the dim
    # upload below moves anything to the device.
    ckey = cdeps = None
    if config.get("serve_result_cache"):
        from spark_rapids_jni_tpu.obs import trace as _trace
        from spark_rapids_jni_tpu.plans.rcache import (
            plan_result_key,
            result_cache,
        )

        ckey, cdeps = plan_result_key(plan, dp, tables)
        hit = result_cache.lookup(ckey)
        if hit is not None:
            with _trace.maybe_span(_trace.SPAN_CACHE,
                                   extra=f"plan:{plan.name}"):
                return hit
    scans = ir.scan_tables(plan)
    tables = _upload_dims(plan, tables, mesh)
    if ir.order_sink(plan) is not None and split is None and combine is None:
        # ordered row vectors do not combine by addition, and a row-
        # halved re-execution would need a merge step the default path
        # doesn't have: under pressure an order plan retries at full
        # size (RetryOOM) but never silently splits into wrong answers
        max_split_depth = 0

    # plan-granularity adaptive presplit: this request class's recent
    # retry history decides whether to skip the full-size attempt (0 under
    # static config / kill switch — bit-identical to the round-8 path)
    presplit = suggested_presplit_depth(plan.name, max_split_depth)
    inline_splits = [0]
    attempted = [False]  # flips at the first run attempt: split() calls
    # before it are the pre-split phase (NOT reactive pressure — counting
    # them would pin the hint against decay; exact regardless of how many
    # parts a custom split returns)
    base_split = split or (lambda t: split_scan_tables(t, scans))

    def split_counted(t):
        if attempted[0]:
            inline_splits[0] += 1
        return base_split(t)

    def run(piece: Tables):
        attempted[0] = True
        return execute_plan(mesh, plan, piece)

    def on_retry(_count: int) -> None:
        _record_plan_retry(plan.name)

    ctx = (task_context(budget.gov, task_id) if manage_task
           else contextlib.nullcontext())
    with ctx:
        out = run_with_split_retry(
            budget, tables,
            nbytes_of=nbytes_of or (
                lambda t: plan_working_set_bytes(plan, t, dp)),
            run=run,
            split=split_counted,
            combine=combine or combine_outputs,
            max_split_depth=max_split_depth,
            initial_split_depth=presplit,
            on_retry=on_retry,
        )
    _note_plan_run(plan.name, presplit, inline_splits[0], max_split_depth)
    if ckey is not None:
        from spark_rapids_jni_tpu.plans.rcache import result_cache

        # put() revalidates cdeps against the live version registry: a
        # table bumped while this plan computed drops the insert — the
        # result is correct for the OLD content, which no future key
        # can (or should) name
        result_cache.put(ckey, out, cdeps, label=plan.name)
    return out
