"""Compiled-plan cache: one traced program per (plan, dtypes, pow2 bucket).

The model runners used to keep one ``functools.lru_cache`` of jitted
steps per query module, each with its own geometry-keying rules — and the
soak tool caught what happens when a key drifts (a fresh jit wrapper plus
a compiled-executable cache entry leaked per call, ~3 MB RSS each).  This
module centralizes that caching for every plan-compiled query:

- the key is ``(plan value, mesh, input signature)`` where the input
  signature is the tuple of (table, field, dtype, padded-length) the
  executor actually uploads — lengths come pre-quantized onto the pow2
  bucket lattice (``parallel.shuffle.quantized_rows`` / ``next_pow2``,
  the same lattice columnar/buckets.py bounds string shapes with), so
  data-dependent row counts collapse onto O(log rows) variants;
- plans are frozen dataclasses built through :func:`plans.ir.lit`, which
  normalizes numpy scalars, so equal geometry can never build two
  unequal keys (the q5 ``_q5_step_cached`` geometry-keying fix, now a
  structural property);
- hit/miss/trace/eviction counters and cumulative trace/compile/execute
  seconds are exported as gauges through ``serve/metrics`` (the engine's
  gauge source) and as an ``obs/flight`` telemetry source, so anomaly
  dumps and BENCH json both show compile amortization.

Entries are LRU-bounded by the ``plan_cache_size`` flag — the Sparkle
large-memory-tier model: compiled variants stay resident while hot.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from spark_rapids_jni_tpu.obs import flight as _flight

__all__ = ["CompiledPlan", "PlanCache", "plan_cache"]


class CompiledPlan:
    """One cached executable: the fused program plus its call metadata."""

    __slots__ = ("fn", "plan", "mesh", "signature", "out_names", "arg_names",
                 "aot", "trace_s", "compile_s", "aot_error")

    def __init__(self, fn, plan, mesh, signature, out_names, arg_names,
                 aot: bool, trace_s: float, compile_s: float,
                 aot_error: str = ""):
        self.fn = fn
        self.plan = plan
        self.mesh = mesh
        self.signature = signature
        self.out_names = out_names
        self.arg_names = arg_names
        self.aot = aot
        self.trace_s = trace_s
        self.compile_s = compile_s
        # why AOT lower+compile fell back to plain jit ("" = it didn't):
        # a real trace bug surfacing here would otherwise defer to first
        # launch and misattribute to the COLLECTIVE seam
        self.aot_error = aot_error


class PlanCache:
    """Process-global LRU of :class:`CompiledPlan` + gauge counters."""

    def __init__(self, maxsize: Optional[int] = None):
        self._maxsize = maxsize
        self._lock = threading.RLock()
        # the LRU table + its gauges: every access below goes through
        # _lock (the guarded-by pass enforces it), so stats() readers on
        # dump/telemetry threads can never see a half-updated eviction
        self._entries: "collections.OrderedDict" = \
            collections.OrderedDict()  # guarded-by: _lock
        self._building: Dict[Tuple, threading.Event] = {}  # guarded-by: _lock
        self._stats: Dict[str, float] = {  # guarded-by: _lock
            "hits": 0, "misses": 0, "evictions": 0, "aot_fallbacks": 0,
            "trace_s": 0.0, "compile_s": 0.0,
            "execute_calls": 0, "execute_s": 0.0,
        }
        self._last_aot_error = ""  # guarded-by: _lock

    def _cap(self) -> int:
        if self._maxsize is not None:
            return self._maxsize
        from spark_rapids_jni_tpu import config

        return int(config.get("plan_cache_size"))

    def get_or_compile(self, key: Tuple,
                       builder: Callable[[], CompiledPlan]) -> CompiledPlan:
        """Return the cached program for ``key``, building (tracing +
        compiling) on miss.  Builds are deduplicated PER KEY, not by
        holding the cache lock across the multi-second compile: a
        concurrent same-key request waits for the one in-flight build,
        while different keys compile in parallel and cache hits — and
        the stats() readers behind serve gauges and flight anomaly
        dumps — never stall behind someone else's cold shape."""
        while True:
            with self._lock:
                hit = self._entries.get(key)
                if hit is not None:
                    self._entries.move_to_end(key)
                    self._stats["hits"] += 1
                    return hit
                ev = self._building.get(key)
                if ev is None:
                    ev = threading.Event()
                    self._building[key] = ev
                    break  # we own this build
            # same-key build in flight: wait, then re-check (the owner
            # may have failed — an injected compile fault — in which
            # case the next loop iteration claims the build itself)
            ev.wait()
        try:
            t0 = time.perf_counter()
            entry = builder()
            dt = time.perf_counter() - t0
        except BaseException:
            with self._lock:
                del self._building[key]
            ev.set()
            raise
        with self._lock:
            del self._building[key]
            self._stats["misses"] += 1
            if not entry.aot:
                # the build fell back from AOT lower+compile to plain jit
                # (entry.aot_error says why): surfaced as a gauge so a
                # swallowed trace failure is visible in telemetry, not
                # silently deferred to the first launch
                self._stats["aot_fallbacks"] += 1
                self._last_aot_error = entry.aot_error
            # builder-reported phase split when available (AOT lower/
            # compile); else the whole build counts as trace time
            if entry.trace_s or entry.compile_s:
                self._stats["trace_s"] += entry.trace_s
                self._stats["compile_s"] += entry.compile_s
            else:
                self._stats["trace_s"] += dt
            self._entries[key] = entry
            cap = self._cap()
            while len(self._entries) > max(cap, 1):
                self._entries.popitem(last=False)
                self._stats["evictions"] += 1
        ev.set()
        return entry

    def record_execute(self, seconds: float) -> None:
        with self._lock:
            self._stats["execute_calls"] += 1
            self._stats["execute_s"] += seconds

    def stats(self) -> Dict[str, Any]:
        """Gauge snapshot (JSON-able).  ``traces`` mirrors ``misses``:
        every miss is exactly one trace of the fused program — the
        number a retrace-stability test watches."""
        with self._lock:
            out = dict(self._stats)
            out["entries"] = len(self._entries)
            out["traces"] = out["misses"]
            if self._last_aot_error:
                out["last_aot_error"] = self._last_aot_error
            for k in ("trace_s", "compile_s", "execute_s"):
                out[k] = round(out[k], 6)
            return out

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def reset_stats(self) -> None:
        with self._lock:
            for k in self._stats:
                self._stats[k] = 0 if isinstance(self._stats[k], int) else 0.0
            self._last_aot_error = ""


#: the process-global cache every plan-compiled query shares (like the
#: governor's default budget: one resident set, one gauge surface)
plan_cache = PlanCache()

# anomaly dumps carry the compile-cache state next to serve/governor
# gauges: a retry storm caused by compile-variant churn is visible as a
# miss/eviction ramp in the same artifact
_flight.register_telemetry_source("plan_cache", plan_cache.stats)
