"""Stats-driven rule rewriter over the plan IR (round 19).

Plans used to compile exactly as written — every join order, every
filter position fixed at construction time.  *Flare* (PAPERS.md) pairs
whole-plan compilation with relational optimization; this module is that
missing middle: a FIXED-POINT rewrite engine over the frozen-dataclass
IR (plans/ir.py) whose every rule is an exact algebraic identity of the
compiler's masked-row semantics, so the rewritten plan is bit-identical
to the unrewritten oracle by construction (tests/test_optimizer.py
fuzzes exactly this claim).

Rules (applied bottom-up until a bounded fixed point):

- **filter_fuse** — ``Filter(Filter(x, p), q)`` folds to one AND'd
  predicate: the pipeline mask is a boolean AND chain, associativity is
  exact.
- **filter_below_gather** — a Filter whose predicate reads none of a
  GatherJoin's output columns slides below it: the gather neither
  reorders rows nor touches the mask, so AND-ing the predicate before
  or after gathers identical bits.
- **filter_below_exchange** — a Filter whose predicate reads only the
  Exchange's wire fields slides below the shuffle, so masked rows are
  dropped BEFORE they cross the wire (the classic pushdown byte win);
  applied only when every additive sink aggregates an integer dtype —
  integer segment sums are order-exact over any row placement, which
  keeps the in-mesh bucket path bit-identical too.
- **project_fuse** — adjacent Projects fold into one by substituting
  the inner definitions into the outer expressions (the env is built
  sequentially, so the fold preserves shadowing).
- **join_reorder** — adjacent independent GatherJoins (disjoint outputs,
  the upper key reads nothing the lower gather produced) are ordered by
  the table-stats registry's ROW COUNTS (models/tables.py,
  ``stats_of``), smallest dim first, table name as the deterministic
  tie-break.  Gathers commute exactly, so this is simultaneously a cost
  rule and a CANONICALIZATION: two queries written with different join
  orders rewrite to the same tree.
- **common-subplan extraction** — the canonicalized plan's subtree
  signatures land in a process registry; when another plan already
  registered the same subtree, the optimizer narrates the shared prefix
  (``EV_PLAN_REWRITE rule:common_subplan``).  Because the result cache
  keys on the canonical plan signature (plans/rcache.py
  ``plan_result_key``), two different queries that canonicalize to the
  same tree literally hit each other's cached work.

Every applied rewrite is recorded as ``EV_PLAN_REWRITE`` in the flight
ring (``tools/flightdump.py --control`` renders the decision ledger).
The optimizer is memoized per (plan, dim-stats) — rewriting is paid once
per plan shape, not per request — and gated behind the
``plan_optimizer`` config flag at its callers (plans/runtime.py), so
static configurations stay byte-for-byte on the round-18 path.
"""

from __future__ import annotations

import dataclasses
import functools
import threading
from typing import Dict, FrozenSet, List, Tuple

from spark_rapids_jni_tpu.obs import flight as _flight
from spark_rapids_jni_tpu.plans import ir

__all__ = ["optimize_plan", "rewrite_plan", "expr_columns",
           "subplan_signatures", "common_subplan_tokens",
           "reset_for_tests", "MAX_PASSES"]

#: fixed-point bound: every rule strictly shrinks a well-founded measure
#: (filter depth, inversions against the canonical join order), so real
#: plans converge in 2-3 passes; the bound only guards against a buggy
#: oscillating rule pair turning the optimizer into a spin loop.
MAX_PASSES = 8

_NO_STATS_ROWS = 1 << 62  # unknown-size dims order after every known one


# --------------------------------------------------------------------------
# expression helpers
# --------------------------------------------------------------------------


def expr_columns(expr) -> FrozenSet[str]:
    """Every column name an expression reads."""
    if isinstance(expr, ir.Col):
        return frozenset((expr.name,))
    if isinstance(expr, ir.Lit):
        return frozenset()
    if isinstance(expr, ir.Bin):
        return expr_columns(expr.lhs) | expr_columns(expr.rhs)
    if isinstance(expr, ir.Unary):
        return expr_columns(expr.x)
    if isinstance(expr, ir.Cast):
        return expr_columns(expr.x)
    raise TypeError(f"not an expression: {expr!r}")


def _substitute(expr, env: Dict[str, object]):
    """Replace ``Col(name)`` reads by ``env[name]`` definitions (the
    project-fuse inlining step)."""
    if isinstance(expr, ir.Col):
        return env.get(expr.name, expr)
    if isinstance(expr, ir.Bin):
        return ir.Bin(expr.op, _substitute(expr.lhs, env),
                      _substitute(expr.rhs, env))
    if isinstance(expr, ir.Unary):
        return ir.Unary(expr.op, _substitute(expr.x, env))
    if isinstance(expr, ir.Cast):
        return ir.Cast(_substitute(expr.x, env), expr.dtype)
    return expr


def _int_sinks_only(plan: ir.Plan) -> bool:
    """True when every additive sink aggregates an integer dtype —
    the precondition for rules that move rows relative to an in-mesh
    Exchange's bucket scatter (integer sums are placement-exact)."""
    for sink in plan.sinks:
        for node in ir._walk(sink):
            if isinstance(node, ir.SegmentAgg):
                for _name, _expr, dtype in node.aggs:
                    if "int" not in dtype and dtype != "bool":
                        return False
    return True


# --------------------------------------------------------------------------
# the rules: each takes a node, returns the rewrite or None
# --------------------------------------------------------------------------


def _rule_filter_fuse(node, _stats, _intish):
    if isinstance(node, ir.Filter) and isinstance(node.child, ir.Filter):
        inner = node.child
        return ir.Filter(inner.child,
                         ir.Bin("and", inner.pred, node.pred))
    return None


def _rule_filter_below_gather(node, _stats, _intish):
    if not (isinstance(node, ir.Filter)
            and isinstance(node.child, ir.GatherJoin)):
        return None
    join = node.child
    produced = {out for _dim_field, out in join.fields}
    if expr_columns(node.pred) & produced:
        return None
    return dataclasses.replace(
        join, child=ir.Filter(join.child, node.pred))


def _rule_filter_below_exchange(node, _stats, intish):
    if not (intish and isinstance(node, ir.Filter)
            and isinstance(node.child, ir.Exchange)):
        return None
    ex = node.child
    if not expr_columns(node.pred) <= set(ex.fields):
        return None
    return dataclasses.replace(ex, child=ir.Filter(ex.child, node.pred))


def _rule_project_fuse(node, _stats, _intish):
    if not (isinstance(node, ir.Project)
            and isinstance(node.child, ir.Project)):
        return None
    inner = node.child
    env = {name: expr for name, expr in inner.cols}
    fused = tuple(inner.cols) + tuple(
        (name, _substitute(expr, env)) for name, expr in node.cols)
    return ir.Project(inner.child, fused)


def _dim_rows(stats: Dict[str, int], dim: ir.Dim) -> Tuple[int, str]:
    return (stats.get(dim.table, _NO_STATS_ROWS), dim.table)


def _rule_join_reorder(node, stats, _intish):
    """Bubble one inversion of the canonical (rows, name) dim order in a
    stack of independent GatherJoins; the fixed-point loop sorts the
    whole stack."""
    if not (isinstance(node, ir.GatherJoin)
            and isinstance(node.child, ir.GatherJoin)):
        return None
    upper, lower = node, node.child
    upper_out = {out for _f, out in upper.fields}
    lower_out = {out for _f, out in lower.fields}
    if upper_out & lower_out:
        return None
    # the upper gather must not consume anything the lower one produced
    if (expr_columns(upper.key) | expr_columns(upper.base)) & lower_out:
        return None
    if _dim_rows(stats, upper.dim) >= _dim_rows(stats, lower.dim):
        return None  # already canonical (smaller dim applies first)
    return dataclasses.replace(
        lower, child=dataclasses.replace(upper, child=lower.child))


_RULES = (
    ("filter_fuse", _rule_filter_fuse),
    ("filter_below_gather", _rule_filter_below_gather),
    ("filter_below_exchange", _rule_filter_below_exchange),
    ("project_fuse", _rule_project_fuse),
    ("join_reorder", _rule_join_reorder),
)


# --------------------------------------------------------------------------
# the fixed-point engine
# --------------------------------------------------------------------------


def _rewrite_node(node, stats, intish, applied: List[Tuple[str, str]]):
    """One bottom-up pass: rebuild children, then try every rule at this
    node (repeating while any fires — a slid filter may fuse at once)."""
    kw = {}
    changed = False
    for f in dataclasses.fields(node):
        v = getattr(node, f.name)
        if isinstance(v, ir.NODE_TYPES):
            nv = _rewrite_node(v, stats, intish, applied)
            changed = changed or nv is not v
            kw[f.name] = nv
        elif isinstance(v, tuple) and v and all(
                isinstance(item, ir.NODE_TYPES) for item in v):
            nv = tuple(_rewrite_node(item, stats, intish, applied)
                       for item in v)
            changed = changed or nv != v
            kw[f.name] = nv
        else:
            kw[f.name] = v
    out = dataclasses.replace(node, **kw) if changed else node
    fired = True
    while fired:
        fired = False
        for name, rule in _RULES:
            nv = rule(out, stats, intish)
            if nv is not None:
                applied.append((name, type(out).__name__))
                out = nv
                fired = True
    return out


def rewrite_plan(plan: ir.Plan, stats: Dict[str, int]
                 ) -> Tuple[ir.Plan, Tuple[Tuple[str, str], ...]]:
    """Rewrite ``plan`` to a fixed point under ``stats`` (dim table ->
    row count).  Returns (rewritten plan, applied (rule, node) log).
    Pure: no flight events, no registry — the memoized/narrating front
    door is :func:`optimize_plan`."""
    applied: List[Tuple[str, str]] = []
    intish = _int_sinks_only(plan)
    for _pass in range(MAX_PASSES):
        before = len(applied)
        sinks = tuple(_rewrite_node(s, stats, intish, applied)
                      for s in plan.sinks)
        if sinks != plan.sinks:
            plan = dataclasses.replace(plan, sinks=sinks)
        if len(applied) == before:
            break
    return plan, tuple(applied)


# --------------------------------------------------------------------------
# common-subplan registry + the memoized, narrating front door
# --------------------------------------------------------------------------

class _SubplanRegistry:
    """Process ledger of canonical subtree signatures: which plan first
    registered each shared subtree (a class, not module globals, so the
    guarded-by pass checks every access site)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # signature -> first plan name that registered it: the
        # cross-query shared-prefix ledger
        self._seen: Dict[str, str] = {}  # guarded-by: _lock

    def note(self, sigs: Dict[str, str], plan_name: str
             ) -> List[Tuple[str, str, str]]:
        """Register ``sigs`` under ``plan_name``; return the subtrees
        some OTHER plan already registered."""
        shared: List[Tuple[str, str, str]] = []
        with self._lock:
            for sig, ntype in sigs.items():
                first = self._seen.setdefault(sig, plan_name)
                if first != plan_name:
                    shared.append((sig, ntype, first))
        return shared

    def reset(self) -> None:
        with self._lock:
            self._seen.clear()


_csp_registry = _SubplanRegistry()


def subplan_signatures(plan: ir.Plan) -> Dict[str, str]:
    """Canonical signature per non-leaf subtree (sha1 of the frozen
    repr, like ir.plan_signature) -> node type name.  Leaves (Scan/Dim)
    are excluded: every query over a table shares those trivially."""
    import hashlib

    out: Dict[str, str] = {}
    for node in ir.walk(plan):
        if isinstance(node, (ir.Scan, ir.Dim)):
            continue
        digest = hashlib.sha1(repr(node).encode()).hexdigest()[:12]
        out[digest] = type(node).__name__
    return out


def common_subplan_tokens(plan: ir.Plan) -> List[Tuple[str, str, str]]:
    """Register ``plan``'s canonical subtrees and return the (signature,
    node type, first-seen plan name) of every subtree some OTHER plan
    already registered — the shared join prefixes the result cache will
    serve across queries."""
    return _csp_registry.note(subplan_signatures(plan), plan.name)


def reset_for_tests() -> None:
    _csp_registry.reset()
    _optimize_cached.cache_clear()


@functools.lru_cache(maxsize=256)
def _optimize_cached(plan: ir.Plan,
                     stats_items: Tuple[Tuple[str, int], ...]) -> ir.Plan:
    """The cached rewrite (plans are immutable values; stats ride the key
    so a registry update re-optimizes).  Narration happens HERE — once
    per distinct (plan, stats), never per request."""
    out, applied = rewrite_plan(plan, dict(stats_items))
    for passno, (rule, ntype) in enumerate(applied, 1):
        _flight.record(_flight.EV_PLAN_REWRITE, -1,
                       detail=f"plan:{plan.name}:rule:{rule}:node:{ntype}",
                       value=passno)
    for sig, ntype, first in common_subplan_tokens(out):
        _flight.record(_flight.EV_PLAN_REWRITE, -1,
                       detail=f"plan:{plan.name}:rule:common_subplan:"
                              f"node:{ntype}:sig:{sig}:with:{first}")
    if applied:
        _flight.record(_flight.EV_PLAN_REWRITE, -1,
                       detail=f"plan:{plan.name}:rule:done",
                       value=len(applied))
    return out


def optimize_plan(plan: ir.Plan) -> ir.Plan:
    """Rewrite ``plan`` under the live table-stats registry.  Memoized
    per (plan, relevant stats); emits one EV_PLAN_REWRITE per applied
    rule on first rewrite.  Callers gate on the ``plan_optimizer``
    config flag — this function itself is unconditional so tests and
    benches can exercise it directly."""
    from spark_rapids_jni_tpu.models import tables as _tables

    stats_items = []
    for dim in ir.dim_tables(plan):
        st = _tables.stats_of(dim.table)
        if st is not None:
            stats_items.append((dim.table, int(st["rows"])))
    return _optimize_cached(plan, tuple(sorted(stats_items)))
