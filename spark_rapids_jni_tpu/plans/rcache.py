"""Governed multi-tier result cache: hot queries skip compute entirely.

Every layer below this one makes one query cheaper; this module makes a
REPEATED query nearly free.  *Sparkle*'s large-memory result tier is the
model (PAPERS.md): analytics traffic is Zipf-skewed — millions of users
asking the same hot questions — so a result keyed on *exactly what was
computed over exactly which bytes* turns the hot tail of the workload
into memory-speed lookups while cold queries still pay compute.

**Key** = (what ran, over which bytes, at which geometry):

- the plan signature (``plans/ir.plan_signature``) or handler name +
  handler-declared payload key,
- the input table fingerprint — per column ``(field, dtype, pow2-padded
  length, CRC32 of the raw buffer)`` so equal keys imply bit-equal
  inputs (stale serves are structurally impossible),
- the dtype/pow2-bucket signature (the same lattice the plan cache keys
  compiled variants on — a result computed at one padded geometry IS the
  result at any other, but keeping the bucket in the key keeps hit
  accounting aligned with compile-variant accounting),
- the version of every named input table (``models/tables.py``): a bump
  changes every dependent key, making stale entries unreachable the
  instant it returns — and a registered listener reclaims their bytes.

**Tiers** — HBM -> host RAM -> disk, governed end to end:

- the HBM tier reserves its bytes from the SAME ``BudgetedResource``
  live queries admit through, via :meth:`BudgetedResource.try_acquire`
  (opportunistic: cached bytes never block or steal from live work);
- the cache registers a spill handler on that budget, consulted BEFORE
  the arbiter's BLOCKED/BUFN escalation — a RetryOOM storm squeezes the
  cache first, demoting HBM entries to host (and host to disk under the
  host cap) instead of killing live tasks;
- the disk tier reuses ``columnar/frames.py`` framing: CRC32 over the
  whole payload, verified on load — a corrupt file is dropped loudly
  (``EV_RCACHE_EVICT`` reason ``corrupt``) and the query recomputes.

**Read path** (wired in round 15): ``plans/runtime.run_governed_plan``
consults the cache before admission (a hit never enters the governed
bracket), ``serve/executor`` consults it before the handler bracket, and
``serve/supervisor`` short-circuits hits before dispatch (a hit never
costs a lease or a pipe crossing).  Every hit/store/demote/evict/
invalidate is a flight event and a gauge (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import os
import pickle
import threading
import zlib
from typing import Any, Dict, Optional, Tuple

import numpy as np

from spark_rapids_jni_tpu.columnar import frames as _frames
from spark_rapids_jni_tpu.obs import flight as _flight
# per-request attribution hooks (TLS pointer mutations only — no lock,
# no blocking — so calling them under self._lock is safe)
from spark_rapids_jni_tpu.serve import attribution as _attrib

__all__ = [
    "ResultCache", "result_cache",
    "array_digest", "tables_fingerprint", "plan_result_key",
    "request_key", "key_token",
]

# storage kinds (how a value serializes / which tiers it may occupy)
_KIND_TABLE = "table"   # Dict[str, np.ndarray]: HBM-capable, framed disk
_KIND_ARRAY = "array"   # one np.ndarray: HBM-capable, framed disk
_KIND_BLOB = "blob"     # any picklable value: host + (pickled) disk

# entry residency: fresh entries materialize host-side and PLACE once
# (host->hbm when the budget has headroom, host->disk when larger than
# the host cap — both before the entry is visible in the table); after
# that residency only walks DOWN (promote = recompute).  Every
# transition site below carries the matching annotation so the analyze
# gate's state-machine pass pins the direction at merge time.
# state-machine: rcache_tier field=tier
_TIER_TRANSITIONS = {
    "hbm": ("host",),          # pressure/cap demotion (budget released)
    "host": ("hbm", "disk"),   # insert placement up; host-cap demotion
    #                            down (framed + CRC to disk)
    "disk": (),                # terminal residency; drops delete the file
}


def array_digest(a: np.ndarray) -> int:
    """CRC32 content fingerprint of one column buffer (dtype + shape +
    raw bytes — bit-equal arrays and only bit-equal arrays collide)."""
    a = np.ascontiguousarray(a)
    h = zlib.crc32(f"{a.dtype.str}:{a.shape}".encode())
    return zlib.crc32(a.tobytes(), h) & 0xFFFFFFFF


def _quantized(n: int, dp: int) -> int:
    from spark_rapids_jni_tpu.parallel.shuffle import quantized_rows

    return quantized_rows(n, dp)


def tables_fingerprint(tables: Dict[str, Dict[str, np.ndarray]],
                       dp: int) -> Tuple[tuple, tuple]:
    """(fingerprint, deps) of a name->{field: array} table dict.

    The fingerprint carries, per table (name-sorted): the table's
    current version (models/tables.py), then per field the dtype, the
    pow2/dp-quantized padded length (the bucket the compiled variant
    keys on), and the content CRC.  ``deps`` is the (name, version)
    stamp :meth:`ResultCache.put` revalidates — a version bump between
    fingerprint and result drops the insert instead of caching a result
    no future key can name truthfully."""
    from spark_rapids_jni_tpu.models import tables as _tables

    deps = _tables.versions_of(sorted(tables))
    fp = []
    for (name, version) in deps:
        fields = tables[name]
        cols = tuple(
            (f, str(np.asarray(v).dtype), _quantized(len(v), dp),
             array_digest(np.asarray(v)))
            for f, v in sorted(fields.items()))
        fp.append((name, version, cols))
    return tuple(fp), deps


def plan_result_key(plan, dp: int,
                    tables: Dict[str, Dict[str, np.ndarray]]) -> Tuple:
    """Cache key of one governed plan execution: (plan value, input
    fingerprint, bucket geometry).  Returns ``(key, deps)``."""
    from spark_rapids_jni_tpu.plans import ir

    fp, deps = tables_fingerprint(tables, dp)
    return ("plan", ir.plan_signature(plan), int(dp), fp), deps


def request_key(handler: str, payload_key: Any,
                table_names=()) -> Tuple:
    """Cache key of one serving request: handler name + the handler's
    declared payload key + the version of every named table dependency.
    Returns ``(key, deps)`` — ``payload_key`` should already embed a
    content digest (``array_digest``) for any data the payload ships."""
    from spark_rapids_jni_tpu.models import tables as _tables

    deps = _tables.versions_of(sorted(table_names))
    return ("req", handler, payload_key, deps), deps


def key_token(key: Tuple) -> str:
    """Short stable token of a key (flight-event details, hot-key
    advertisement across the supervisor pipe).  repr-based: keys are
    built from str/int/tuple only, so the token is identical in every
    process that builds the same key."""
    return f"{zlib.crc32(repr(key).encode()) & 0xFFFFFFFF:08x}"


def _release_budget(budget, nbytes: int) -> None:
    # resource: release budget
    """Hand ``nbytes`` of HBM reservation back.  A budget whose governor
    already closed (teardown, shutdown race) raises from the native
    arbiter AFTER the byte accounting already settled — the reservation
    is gone either way, so the wake-blocked-tenants side effect is all
    that's lost."""
    try:
        budget.release(nbytes)
    except RuntimeError:
        pass


class _Entry:
    """One cached result's residency record."""

    __slots__ = ("key", "token", "kind", "tier", "value", "nbytes",
                 "deps", "hits", "seq", "path", "budget", "label")

    def __init__(self, key, token, kind, value, nbytes, deps, label):
        self.key = key
        self.token = token
        self.kind = kind
        self.tier = "host"  # fresh entries materialize host-side; see
        #                     _TIER_TRANSITIONS for the residency ladder
        self.value = value      # device dict | host dict/array/object |
        #                         None while resident on disk only
        self.nbytes = nbytes
        self.deps = deps        # ((table, version), ...) at store time
        self.hits = 0
        self.seq = 0            # LRU clock value
        self.path = ""          # disk-tier frame file
        self.budget = None      # BudgetedResource holding the HBM bytes
        self.label = label      # handler / plan name (events, servetop)


class ResultCache:
    """Process-global multi-tier result store (see module doc).

    One re-entrant lock guards the table and every residency move; disk
    I/O runs under it too — demotions and cold disk hits are rare and
    small next to the compute they replace, and a lock-free file path
    would reintroduce exactly the remove-vs-readmit races the spill
    pool had to close.  Lock order is cache -> budget everywhere (the
    budget never calls the cache while holding its own lock: spill
    handlers run outside it)."""

    def __init__(self, *, hbm_bytes: Optional[int] = None,
                 host_bytes: Optional[int] = None,
                 max_entries: Optional[int] = None,
                 directory: Optional[str] = None):
        self._hbm_cap = hbm_bytes
        self._host_cap = host_bytes
        self._max_entries = max_entries
        self._dir = directory
        self._lock = threading.RLock()
        self._entries: Dict[Tuple, _Entry] = {}  # guarded-by: _lock
        self._clock = 0  # guarded-by: _lock
        self._budget = None  # guarded-by: _lock
        self._tier_bytes = {"hbm": 0, "host": 0, "disk": 0}  # guarded-by: _lock
        self._stats: Dict[str, int] = {  # guarded-by: _lock
            "lookups": 0, "hits": 0, "hits_hbm": 0, "hits_host": 0,
            "hits_disk": 0, "misses": 0, "stores": 0, "stale_puts": 0,
            "demotes_hbm_host": 0, "demotes_host_disk": 0,
            "evictions": 0, "invalidated": 0, "corrupt_drops": 0,
        }
        self._listening = False  # guarded-by: _lock

    # -- configuration -----------------------------------------------------
    def _cap(self, which: str) -> int:
        ctor = {"hbm": self._hbm_cap, "host": self._host_cap,
                "entries": self._max_entries}[which]
        if ctor is not None:
            return int(ctor)
        from spark_rapids_jni_tpu import config

        flag = {"hbm": "serve_result_cache_hbm_bytes",
                "host": "serve_result_cache_host_bytes",
                "entries": "serve_result_cache_entries"}[which]
        return int(config.get(flag))

    def _disk_dir(self) -> str:
        if self._dir is not None:
            return self._dir
        from spark_rapids_jni_tpu import config

        return str(config.get("serve_result_cache_dir") or "")

    def bind_budget(self, budget) -> None:
        """Attach the device budget the HBM tier reserves from, and
        register the pressure spill handler on it (idempotent per
        budget).  Rebinding demotes entries held on the OLD budget —
        their reservations must not outlive the binding."""
        with self._lock:
            old = self._budget
            if old is budget:
                return
            if old is not None:
                for e in list(self._entries.values()):
                    if e.tier == "hbm":
                        self._demote_hbm_locked(e, reason="rebind")
                old.unregister_spill_handler(self._pressure_demote)
            self._budget = budget
            if budget is not None:
                budget.register_spill_handler(self._pressure_demote)
            self._ensure_listener_locked()

    def _ensure_listener_locked(self) -> None:
        if self._listening:
            return
        self._listening = True
        from spark_rapids_jni_tpu.models import tables as _tables

        _tables.add_listener(self._on_table_bump)

    # -- the read path -----------------------------------------------------
    def lookup(self, key: Tuple, *, rid: int = -1) -> Optional[Any]:
        """The cached value for ``key``, or None.  Revalidates the
        entry's dependency versions against the live registry on every
        hit — an entry that raced a bump into the table is dropped here,
        never served.  Disk-tier values are CRC-verified on load; any
        damage evicts the entry (reason ``corrupt``) and returns None so
        the caller recomputes."""
        from spark_rapids_jni_tpu.models import tables as _tables

        with self._lock:
            self._ensure_listener_locked()
            self._stats["lookups"] += 1
            e = self._entries.get(key)
            if e is None:
                self._stats["misses"] += 1
                _attrib.note_cache_miss()
                return None
            if e.deps and tuple(_tables.versions_of(
                    [t for t, _ in e.deps])) != e.deps:
                # raced insert from before a bump: reclaim, never serve
                self._drop_locked(e, reason="stale")
                self._stats["misses"] += 1
                _attrib.note_cache_miss()
                return None
            value = self._materialize_locked(e)
            if value is None:  # corrupt disk frame: already evicted
                self._stats["misses"] += 1
                _attrib.note_cache_miss()
                return None
            self._clock += 1
            e.seq = self._clock
            e.hits += 1
            self._stats["hits"] += 1
            self._stats[f"hits_{e.tier}"] += 1
            prefix = f"rid:{rid}:" if rid >= 0 else ""
            _flight.record(_flight.EV_RCACHE_HIT, rid,
                           detail=f"{prefix}handler:{e.label}:tier:"
                                  f"{e.tier}:key:{e.token}",
                           value=e.nbytes)
            _attrib.note_cache_hit(e.nbytes)
            return value

    def _materialize_locked(self, e: _Entry) -> Optional[Any]:
        """The servable value of one entry (caller holds the lock)."""
        if e.tier == "hbm":
            return {k: np.asarray(v) for k, v in e.value.items()} \
                if e.kind == _KIND_TABLE else np.asarray(e.value)
        if e.tier == "host":
            if e.kind == _KIND_TABLE:
                return dict(e.value)
            if e.kind == _KIND_BLOB:
                return self._unpickle_locked(e, e.value)
            return e.value
        return self._load_disk_locked(e)

    def _unpickle_locked(self, e: _Entry, raw) -> Optional[Any]:
        """Each blob hit decodes its own copy (see _adopt); a value that
        stopped unpickling (its class was redefined/removed) drops to a
        recompute rather than failing the request."""
        try:
            return pickle.loads(bytes(raw))
        except (pickle.UnpicklingError, ValueError, EOFError,
                AttributeError, IndexError, ImportError):
            self._stats["corrupt_drops"] += 1
            self._drop_locked(e, reason="corrupt")
            return None

    def _load_disk_locked(self, e: _Entry) -> Optional[Any]:
        try:
            with open(e.path, "rb") as f:
                meta, bufs = _frames.decode_frame(f.read())
        except (OSError, _frames.FrameError):
            self._stats["corrupt_drops"] += 1
            self._drop_locked(e, reason="corrupt")
            return None
        # identity is the FULL key, not just the 32-bit filename token:
        # two keys whose tokens collide share a path (the later demote
        # overwrote it), and serving the survivor's payload under the
        # other key would be a wrong answer — exactly what this module
        # promises cannot happen.  A mismatch reads as corruption: drop
        # and recompute.
        if (meta[0] != _frames.FR_RESULT or meta[1] != e.token
                or meta[5] != repr(e.key)):
            self._stats["corrupt_drops"] += 1
            self._drop_locked(e, reason="corrupt")
            return None
        tag, token, kind, names, shapes, keyrepr = meta
        if kind == _KIND_BLOB:
            return self._unpickle_locked(e, bufs[0].tobytes())
        arrays = [b.reshape(tuple(s)) for b, s in zip(bufs, shapes)]
        if kind == _KIND_ARRAY:
            return arrays[0]
        return dict(zip(names, arrays))

    # -- the write path ----------------------------------------------------
    def put(self, key: Tuple, value: Any, deps=(), *,
            label: str = "") -> bool:
        """Insert one computed result.  Returns False (and stores
        nothing) when a dependency version moved since ``deps`` was
        stamped — the bump-mid-flight guard — or when the value cannot
        be sized/serialized.  Insert tier: HBM when the bound budget has
        headroom RIGHT NOW (``try_acquire`` — never blocks, never
        squeezes live work to make room for cache), else host, demoting
        LRU residents down the ladder to respect each cap."""
        from spark_rapids_jni_tpu.models import tables as _tables

        kind, stored, nbytes = self._adopt(value)
        if stored is None:
            return False
        with self._lock:
            self._ensure_listener_locked()
            deps = tuple(deps)
            if deps and tuple(_tables.versions_of(
                    [t for t, _ in deps])) != deps:
                self._stats["stale_puts"] += 1
                return False
            old = self._entries.get(key)
            if old is not None:
                self._drop_locked(old, reason="replaced", quiet=True)
            e = _Entry(key, key_token(key), kind, stored, nbytes,
                       deps, label)
            placed = self._place_locked(e)
            if not placed:
                return False
            self._clock += 1
            e.seq = self._clock
            self._entries[key] = e
            self._stats["stores"] += 1
            _flight.record(_flight.EV_RCACHE_STORE, -1,
                           detail=f"handler:{label}:tier:{e.tier}:"
                                  f"key:{e.token}",
                           value=nbytes)
            _attrib.note_cache_store(nbytes)
            cap = max(1, self._cap("entries"))
            while len(self._entries) > cap:
                lru = min(self._entries.values(), key=lambda x: x.seq)
                self._drop_locked(lru, reason="cap")
            return True

    def _adopt(self, value: Any):
        """(kind, stored_value, nbytes) — host copies decoupled from the
        caller and frozen read-only, so neither side can mutate the
        other's view of a cached result."""
        if isinstance(value, dict) and value and all(
                isinstance(v, np.ndarray) for v in value.values()):
            stored = {}
            for k, v in value.items():
                c = np.array(v, copy=True)
                c.flags.writeable = False
                stored[k] = c
            return (_KIND_TABLE, stored,
                    sum(int(v.nbytes) for v in stored.values()))
        if isinstance(value, np.ndarray):
            c = np.array(value, copy=True)
            c.flags.writeable = False
            return _KIND_ARRAY, c, int(c.nbytes)
        try:
            pickled = pickle.dumps(value)
        except (pickle.PicklingError, TypeError, ValueError,
                AttributeError):
            return _KIND_BLOB, None, 0  # unpicklable: not cacheable
        # blobs are stored as their PICKLED bytes, not the live object:
        # a mutable result (list, dict of scalars) the caller keeps a
        # reference to must not be able to poison the cache, and every
        # hit must hand each client its own fresh copy
        return _KIND_BLOB, pickled, len(pickled)

    def _place_locked(self, e: _Entry) -> bool:
        """Choose the insert tier for a fresh host-side entry."""
        if (e.kind in (_KIND_TABLE, _KIND_ARRAY)
                and self._budget is not None
                and e.nbytes <= self._cap("hbm")):
            while (self._tier_bytes["hbm"] + e.nbytes > self._cap("hbm")
                   and self._demote_lru_locked("hbm", reason="cap")):
                pass
            if (self._tier_bytes["hbm"] + e.nbytes <= self._cap("hbm")
                    and self._budget.try_acquire(e.nbytes)):
                # the opportunistic bytes are held from HERE until the
                # entry owns them (e.budget) or a release hands them
                # back: round 15's review found the narrower
                # except-clause release leaking the reservation when
                # device_put failed with anything OUTSIDE the expected
                # types (the exact historical shape the
                # resource-lifecycle gate now pins — the outer
                # BaseException arm is the all-paths backstop)
                try:
                    import jax

                    host = e.value

                    try:
                        if e.kind == _KIND_TABLE:
                            # analyze: ignore[governed-allocation] -
                            # cached residency deliberately bypasses the
                            # retry bracket: its bytes were just
                            # try_acquire'd from the SAME budget
                            # (accounted, never blocking), and a cache
                            # insert must never park a thread or draw
                            # Retry/Split signals meant for live queries
                            e.value = {k: jax.device_put(v)
                                       for k, v in host.items()}
                        else:
                            # analyze: ignore[governed-allocation] - same
                            # try_acquire-accounted cache upload as above
                            e.value = jax.device_put(host)
                    except (RuntimeError, ValueError):
                        # backend refused (fragmentation, shutdown):
                        # stay host-side and hand the bytes back
                        e.value = host
                        _release_budget(self._budget, e.nbytes)
                    else:
                        e.tier = "hbm"  # transition: rcache_tier host->hbm
                        #                 (insert placement: the entry is
                        #                 not yet visible in the table)
                        e.budget = self._budget
                        self._tier_bytes["hbm"] += e.nbytes
                        return True
                except BaseException:
                    # an unexpected fault mid-upload (anything but the
                    # refusal types above) must not leak the reservation
                    _release_budget(self._budget, e.nbytes)
                    raise
        # host tier: make room under the cap (demote LRU to disk when a
        # spool dir is configured, else evict)
        if e.nbytes > self._cap("host"):
            return self._spill_to_disk_locked(e)
        while (self._tier_bytes["host"] + e.nbytes > self._cap("host")
               and self._demote_lru_locked("host", reason="cap")):
            pass
        if self._tier_bytes["host"] + e.nbytes > self._cap("host"):
            return False  # nothing left to demote and still no room
        self._tier_bytes["host"] += e.nbytes
        return True

    def _spill_to_disk_locked(self, e: _Entry) -> bool:
        """Write a fresh entry straight to the disk tier (value larger
        than the host cap).  False when no dir is configured."""
        if not self._write_disk_locked(e):
            return False
        e.tier = "disk"  # transition: rcache_tier host->disk (insert
        #                  placement of an over-host-cap value)
        e.value = None
        self._tier_bytes["disk"] += e.nbytes
        return True

    # -- demotion / eviction ----------------------------------------------
    def _lru_locked(self, tier: str) -> Optional[_Entry]:
        cands = [e for e in self._entries.values() if e.tier == tier]
        return min(cands, key=lambda e: e.seq) if cands else None

    def _demote_lru_locked(self, tier: str, *, reason: str) -> bool:
        e = self._lru_locked(tier)
        if e is None:
            return False
        if tier == "hbm":
            return self._demote_hbm_locked(e, reason=reason)
        return self._demote_host_locked(e, reason=reason)

    def _demote_hbm_locked(self, e: _Entry, *, reason: str) -> bool:
        if e.tier != "hbm":
            return False
        host = ({k: np.asarray(v) for k, v in e.value.items()}
                if e.kind == _KIND_TABLE else np.asarray(e.value))
        if e.kind == _KIND_TABLE:
            for v in host.values():
                v.flags.writeable = False
        else:
            host.flags.writeable = False
        e.tier = "host"  # transition: rcache_tier hbm->host
        e.value = host
        self._tier_bytes["hbm"] -= e.nbytes
        self._tier_bytes["host"] += e.nbytes
        if e.budget is not None:
            _release_budget(e.budget, e.nbytes)
            e.budget = None
        self._stats["demotes_hbm_host"] += 1
        _flight.record(_flight.EV_RCACHE_DEMOTE, -1,
                       detail=f"key:{e.token}:hbm->host:reason:{reason}",
                       value=e.nbytes)
        # respect the host cap the demotion just pressured
        while (self._tier_bytes["host"] > self._cap("host")
               and self._demote_lru_locked("host", reason="cap")):
            pass
        return True

    def _demote_host_locked(self, e: _Entry, *, reason: str) -> bool:
        if e.tier != "host":
            return False
        if not self._write_disk_locked(e):
            self._drop_locked(e, reason="cap")
            return True  # room WAS freed, just not preserved
        e.tier = "disk"  # transition: rcache_tier host->disk
        e.value = None
        self._tier_bytes["host"] -= e.nbytes
        self._tier_bytes["disk"] += e.nbytes
        self._stats["demotes_host_disk"] += 1
        _flight.record(_flight.EV_RCACHE_DEMOTE, -1,
                       detail=f"key:{e.token}:host->disk:reason:{reason}",
                       value=e.nbytes)
        return True

    def _write_disk_locked(self, e: _Entry) -> bool:
        d = self._disk_dir()
        if not d:
            return False
        if e.kind == _KIND_TABLE:
            names = sorted(e.value)
            meta = (_frames.FR_RESULT, e.token, e.kind, names,
                    [list(e.value[n].shape) for n in names],
                    repr(e.key))
            bufs = [np.ascontiguousarray(e.value[n]).reshape(-1)
                    for n in names]
        elif e.kind == _KIND_ARRAY:
            meta = (_frames.FR_RESULT, e.token, e.kind, [],
                    [list(e.value.shape)], repr(e.key))
            bufs = [np.ascontiguousarray(e.value).reshape(-1)]
        else:  # blob: e.value already IS the pickled bytes (_adopt)
            meta = (_frames.FR_RESULT, e.token, e.kind, [], [],
                    repr(e.key))
            bufs = [np.frombuffer(e.value, np.uint8)]
        path = os.path.join(d, f"rc_{e.token}.frame")
        try:
            os.makedirs(d, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(_frames.encode_frame(meta, bufs))
            os.replace(tmp, path)  # atomic: readers never see a torn file
        except OSError:
            return False
        e.path = path
        return True

    def _drop_locked(self, e: _Entry, *, reason: str,
                     quiet: bool = False) -> None:
        self._entries.pop(e.key, None)
        self._tier_bytes[e.tier] -= e.nbytes
        if e.tier == "hbm" and e.budget is not None:
            _release_budget(e.budget, e.nbytes)
            e.budget = None
        if e.tier == "disk" and e.path:
            try:
                os.remove(e.path)
            except OSError:
                pass
        e.value = None
        if not quiet:
            # drop categories stay DISJOINT gauges (an operator sums
            # them): stale drops count as `invalidated`, CRC failures as
            # `corrupt_drops` (both at their call sites) — `evictions`
            # is capacity pressure only.  The flight event narrates all
            # of them, with the reason in its detail.
            if reason not in ("stale", "corrupt"):
                self._stats["evictions"] += 1
            _flight.record(_flight.EV_RCACHE_EVICT, -1,
                           detail=f"key:{e.token}:tier:{e.tier}:"
                                  f"reason:{reason}",
                           value=e.nbytes)

    # -- governance hooks --------------------------------------------------
    def _pressure_demote(self, nbytes: int) -> int:
        """Budget spill handler: live queries are short of ``nbytes`` —
        demote LRU HBM entries until that much budget came back.  Runs
        BEFORE the arbiter's BLOCKED/BUFN escalation, so a RetryOOM
        storm squeezes cached residency first and kills nothing."""
        freed = 0
        with self._lock:
            while freed < nbytes:
                e = self._lru_locked("hbm")
                if e is None:
                    break
                n = e.nbytes
                if self._demote_hbm_locked(e, reason="pressure"):
                    freed += n
                else:  # pragma: no cover - defensive: tier raced
                    break
        return freed

    def _on_table_bump(self, name: str, version: int) -> None:
        """models/tables listener: reclaim every entry depending on an
        older version of ``name`` (the bump already made them
        unreachable — this returns their bytes)."""
        with self._lock:
            victims = [e for e in self._entries.values()
                       if any(t == name and v < version
                              for t, v in e.deps)]
            for e in victims:
                self._stats["invalidated"] += 1
                self._drop_locked(e, reason="stale")

    # -- introspection / lifecycle ----------------------------------------
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = dict(self._stats)
            out["entries"] = len(self._entries)
            for tier in ("hbm", "host", "disk"):
                out[f"{tier}_bytes"] = self._tier_bytes[tier]
                out[f"{tier}_entries"] = sum(
                    1 for e in self._entries.values() if e.tier == tier)
            looked = out["lookups"]
            out["hit_ratio"] = round(out["hits"] / looked, 4) if looked \
                else 0.0
            return out

    def hot_tokens(self, n: int = 16):
        """The ``n`` hottest resident keys' tokens, hits-descending —
        what a worker advertises in its heartbeat gauges so the router
        knows which submits will hit somewhere (serve/supervisor.py's
        cached_only admission)."""
        with self._lock:
            hot = sorted(self._entries.values(),
                         key=lambda e: (-e.hits, -e.seq))[:max(0, n)]
            return [e.token for e in hot if e.hits > 0]

    def clear(self) -> None:
        with self._lock:
            for e in list(self._entries.values()):
                self._drop_locked(e, reason="clear", quiet=True)

    def reset_for_tests(self) -> None:
        from spark_rapids_jni_tpu.models import tables as _tables

        with self._lock:
            self.clear()
            for k in self._stats:
                self._stats[k] = 0
            if self._budget is not None:
                self._budget.unregister_spill_handler(
                    self._pressure_demote)
                self._budget = None
            _tables.remove_listener(self._on_table_bump)
            self._listening = False


#: the process-global cache every read/write path shares (one resident
#: set, one gauge surface — like plan_cache and the default budget)
result_cache = ResultCache()

_flight.register_telemetry_source("result_cache", result_cache.stats)
