"""Plan compiler: trace a whole query plan into ONE jitted program.

Every IR node maps onto the existing device primitives (the same jnp
calls the per-op model bodies used, kept bit-identical so fused results
equal the per-op path exactly); the compiler walks the plan, builds one
python callable over the flat input arrays, wraps it in ``shard_map``
when a mesh is given (facts ride the data axis, dims are replicated,
sink outputs psum), and jits the whole thing — one launch per plan
execution instead of one per op.

Compilation crosses the COMPILE seam (a chaos rule can fail it like the
reference's module-load injector) and is cached in plans/cache.py; the
trace/compile split is measured with the AOT API (``jit(...).lower()``
then ``.compile()``) when the backend supports it, falling back to a
plain jit whose first call pays both.

Emitters are registered with the :func:`emitter` decorator —
``ci/analyze.py``'s governed-allocation pass treats emitter-decorated
functions as traced device code (allocations materialize at the
governed plan launch, not at trace time), the same seeding rule as
``with seam(COMPILE)`` blocks and jit/shard_map arguments.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Tuple

import jax
import jax.numpy as jnp

from spark_rapids_jni_tpu.plans import ir
from spark_rapids_jni_tpu.plans.cache import CompiledPlan, plan_cache

__all__ = ["compile_plan", "cached_compile", "input_signature",
           "output_names", "emitter", "DTYPES",
           "RaggedProgram", "compile_ragged", "cached_ragged_compile",
           "EXCHANGE_SOURCE", "split_exchange_plan",
           "emit_exchange_partitions", "emit_range_partitions",
           "sample_range_splitters", "eval_post"]

DTYPES = {
    "bool": jnp.bool_,
    "int8": jnp.int8,
    "int32": jnp.int32,
    "int64": jnp.int64,
    "uint64": jnp.uint64,
    "float32": jnp.float32,
    "float64": jnp.float64,
}

#: the implicit per-scan row-validity input the executor appends
VALID_FIELD = "__valid__"


# ---------------------------------------------------------------- expressions


def _eval(expr, env: Dict[str, object]):
    """Evaluate an IR expression against an environment of traced arrays
    (or, for Plan.post, of aggregate output vectors)."""
    if isinstance(expr, ir.Col):
        return env[expr.name]
    if isinstance(expr, ir.Lit):
        return expr.value
    if isinstance(expr, ir.Cast):
        x = _eval(expr.x, env)
        return jnp.asarray(x).astype(DTYPES[expr.dtype])
    if isinstance(expr, ir.Unary):
        x = _eval(expr.x, env)
        return (~x) if expr.op == "not" else (-x)
    if isinstance(expr, ir.Bin):
        a = _eval(expr.lhs, env)
        b = _eval(expr.rhs, env)
        op = expr.op
        if op == "add":
            return a + b
        if op == "sub":
            return a - b
        if op == "mul":
            return a * b
        if op == "and":
            return a & b
        if op == "or":
            return a | b
        if op == "eq":
            return a == b
        if op == "ne":
            return a != b
        if op == "ge":
            return a >= b
        if op == "gt":
            return a > b
        if op == "le":
            return a <= b
        if op == "lt":
            return a < b
        if op == "min":
            return jnp.minimum(a, b)
        if op == "max":
            return jnp.maximum(a, b)
        if op == "shl":
            return a << b
        if op == "band":
            return a & b
        if op == "bor":
            return a | b
    raise TypeError(f"not an IR expression: {expr!r}")


# ------------------------------------------------------------------- emitters


class _Ctx:
    """One trace: bound input arrays + exchange-drop accumulation."""

    def __init__(self, inputs, rowvalid, mesh):
        self.inputs = inputs      # table -> field -> traced array
        self.rowvalid = rowvalid  # scan table -> traced bool array
        self.mesh = mesh
        self.dropped: List[object] = []


class _Rows:
    """A row-level pipeline state: named columns + the AND'd mask."""

    def __init__(self, cols: Dict[str, object], mask):
        self.cols = cols
        self.mask = mask


_EMITTERS: Dict[type, Callable] = {}


def emitter(node_cls):
    """Register the emit function of one IR node type.  Emitter bodies
    are traced device code: ci/analyze.py seeds them as governed roots
    (their allocations happen at the governed plan launch)."""

    def deco(fn):
        _EMITTERS[node_cls] = fn
        return fn

    return deco


def _emit(node, ctx: _Ctx):
    return _EMITTERS[type(node)](node, ctx)


@emitter(ir.Scan)
def _emit_scan(node: ir.Scan, ctx: _Ctx) -> _Rows:
    cols = {f: ctx.inputs[node.table][f] for f in node.fields}
    return _Rows(cols, ctx.rowvalid[node.table])


@emitter(ir.Filter)
def _emit_filter(node: ir.Filter, ctx: _Ctx) -> _Rows:
    rows = _emit(node.child, ctx)
    return _Rows(rows.cols, rows.mask & _eval(node.pred, rows.cols))


@emitter(ir.Project)
def _emit_project(node: ir.Project, ctx: _Ctx) -> _Rows:
    rows = _emit(node.child, ctx)
    cols = dict(rows.cols)
    for name, expr in node.cols:
        cols[name] = _eval(expr, cols)
    return _Rows(cols, rows.mask)


@emitter(ir.GatherJoin)
def _emit_gather_join(node: ir.GatherJoin, ctx: _Ctx) -> _Rows:
    rows = _emit(node.child, ctx)
    dim = ctx.inputs[node.dim.table]
    key = _eval(node.key, rows.cols)
    base = _eval(node.base, rows.cols)
    n_dim = dim[node.fields[0][0]].shape[0]
    idx = jnp.clip(key - base, 0, n_dim - 1)
    cols = dict(rows.cols)
    for dfield, out in node.fields:
        cols[out] = dim[dfield][idx]
    return _Rows(cols, rows.mask)


@emitter(ir.SemiJoinWindow)
def _emit_semi_join_window(node: ir.SemiJoinWindow, ctx: _Ctx) -> _Rows:
    rows = _emit(node.child, ctx)
    dim_sk = ctx.inputs[node.dim.table][node.sk_field]
    dim_days = ctx.inputs[node.dim.table][node.days_field]
    date = _eval(node.key, rows.cols)
    valid = _eval(node.key_valid, rows.cols)
    lo = _eval(node.lo, rows.cols)
    hi = _eval(node.hi, rows.cols)
    idx = jnp.clip(jnp.searchsorted(dim_sk, date), 0, dim_sk.shape[0] - 1)
    hit = dim_sk[idx] == date
    in_win = (dim_days[idx] >= lo) & (dim_days[idx] < hi)
    return _Rows(rows.cols, rows.mask & valid & hit & in_win)


@emitter(ir.SegmentAgg)
def _emit_segment_agg(node: ir.SegmentAgg, ctx: _Ctx) -> Dict[str, object]:
    rows = _emit(node.child, ctx)
    key = _eval(node.key, rows.cols)
    n = node.num_segments
    # masked rows scatter into the drop bucket — the _masked_segment
    # shape, bit-identical for integer sums
    bucket = jnp.where(rows.mask, key, n)
    out = {}
    for name, value_expr, dtype in node.aggs:
        vals = jnp.where(rows.mask, _eval(value_expr, rows.cols), 0).astype(
            DTYPES[dtype])
        out[name] = jax.ops.segment_sum(vals, bucket, num_segments=n + 1)[:-1]
    return out


@emitter(ir.Union)
def _emit_union(node: ir.Union, ctx: _Ctx) -> _Rows:
    parts = [_emit(c, ctx) for c in node.children]
    fields = [f for f in parts[0].cols if all(f in p.cols for p in parts)]
    cols = {f: jnp.concatenate([p.cols[f] for p in parts]) for f in fields}
    cols[node.tag] = jnp.concatenate([
        jnp.full(p.mask.shape, tv, jnp.int8)
        for p, tv in zip(parts, node.tag_values)
    ])
    return _Rows(cols, jnp.concatenate([p.mask for p in parts]))


@emitter(ir.Exchange)
def _emit_exchange(node: ir.Exchange, ctx: _Ctx) -> _Rows:
    from spark_rapids_jni_tpu.parallel.mesh import DATA_AXIS, axis_size
    from spark_rapids_jni_tpu.parallel.shuffle import (
        all_to_all_shuffle,
        partition_of,
    )

    rows = _emit(node.child, ctx)
    dp = axis_size(DATA_AXIS)
    part = partition_of(_eval(node.key, rows.cols), dp)
    ex = all_to_all_shuffle(
        {f: rows.cols[f] for f in node.fields}, part, node.capacity,
        axis=DATA_AXIS, row_valid=rows.mask,
    )
    ctx.dropped.append(ex.dropped)
    return _Rows(dict(ex.columns), ex.valid)


@emitter(ir.RangeExchange)
def _emit_range_exchange(node: ir.RangeExchange, ctx: _Ctx):
    # registration keeps the split/rebuild machinery node-aware; there is
    # deliberately no traced body — psum cannot merge ordered row vectors,
    # so a range shuffle only exists on the cross-process plane
    raise ValueError(
        "RangeExchange has no in-process emitter: split the plan "
        "(split_exchange_plan) and run it on the serve shuffle plane, or "
        "through its single-process oracle (serve.shuffle."
        "run_range_plan_local)")


def _order_env(keys, cols, mask):
    """(permutation, sorted per-key ranks) for ``(expr, ascending)`` sort
    keys over a row environment — the shared front half of every
    order-sensitive emitter."""
    from spark_rapids_jni_tpu.plans import window as win

    ranks = [win.sort_rank(jnp.asarray(_eval(e, cols)), asc)
             for e, asc in keys]
    order = win.order_permutation(ranks, mask)
    return order, [r[order] for r in ranks]


def _gather_cols(cols, order):
    return {k: jnp.asarray(v)[order] if jnp.ndim(v) else v
            for k, v in cols.items()}


@emitter(ir.Window)
def _emit_window(node: ir.Window, ctx: _Ctx) -> _Rows:
    from spark_rapids_jni_tpu.plans import window as win

    rows = _emit(node.child, ctx)
    pkeys = tuple((e, True) for e in node.partition_by)
    order, sranks = _order_env(pkeys + node.order_by, rows.cols, rows.mask)
    cols = _gather_cols(rows.cols, order)
    mask = rows.mask[order]
    np_keys = len(node.partition_by)
    run_start = win.run_boundaries(sranks[:np_keys], mask)
    ochange = win.change_points(sranks[np_keys:]) if node.order_by else (
        jnp.zeros_like(run_start))
    for f in node.funcs:
        if f.kind == "row_number":
            out = win.row_number(run_start)
        elif f.kind == "rank":
            out = win.rank(run_start, ochange)
        elif f.kind == "dense_rank":
            out = win.dense_rank(run_start, ochange)
        else:
            v = jnp.asarray(_eval(f.arg, cols)).astype(DTYPES[f.dtype])
            # invalid rows sort last and open their own run
            # (run_boundaries), so their garbage can never reach a valid
            # segment; zeroing keeps even the masked outputs finite
            v = jnp.where(mask, v, jnp.zeros((), v.dtype))
            if f.kind == "sum":
                out = win.framed_sum(v, run_start, f.preceding)
            else:
                out = win.framed_minmax(v, run_start, f.kind, f.preceding)
        cols[f.name] = out.astype(DTYPES[f.dtype]) if f.kind in (
            "rank", "dense_rank", "row_number") else out
    return _Rows(cols, mask)


def _order_sink_outputs(node, ctx: _Ctx, k=None) -> Dict[str, object]:
    """Shared Sort/TopK sink body: order rows (invalid last), emit the
    named field vectors plus the implicit valid-``rows`` count; TopK
    additionally slices the first ``min(k, n)`` rows (static shapes)."""
    rows = _emit(node.child, ctx)
    order, _ranks = _order_env(node.keys, rows.cols, rows.mask)
    cols = _gather_cols(rows.cols, order)
    nvalid = jnp.sum(rows.mask.astype(jnp.int64))
    out = {}
    for f in node.fields:
        v = cols[f]
        out[f] = v[:min(int(k), v.shape[0])] if k is not None else v
    out["rows"] = jnp.minimum(nvalid, k) if k is not None else nvalid
    return out


@emitter(ir.Sort)
def _emit_sort(node: ir.Sort, ctx: _Ctx) -> Dict[str, object]:
    return _order_sink_outputs(node, ctx)


@emitter(ir.TopK)
def _emit_topk(node: ir.TopK, ctx: _Ctx) -> Dict[str, object]:
    return _order_sink_outputs(node, ctx, k=int(node.k))


@emitter(ir.PresenceCount)
def _emit_presence_count(node: ir.PresenceCount,
                         ctx: _Ctx) -> Dict[str, object]:
    # lazy: models.q97 imports plans at module level; by trace time the
    # module exists, and _count_runs stays single-owner over there
    from spark_rapids_jni_tpu.models.q97 import _count_runs

    rows = _emit(node.child, ctx)
    so, co, b = _count_runs(rows.cols[node.key],
                            rows.cols[node.tag] == 1, rows.mask)
    return dict(zip(node.names, (so, co, b)))


# ------------------------------------------------------------------ compiling


def output_names(plan: ir.Plan) -> Tuple[str, ...]:
    """Static output order of a compiled plan: sink outputs in sink/agg
    order, then the implicit ``dropped`` (plans with an Exchange), then
    post outputs — filtered/ordered by ``plan.outputs`` when set."""
    names: List[str] = []
    ir.order_sink(plan)  # validates order sinks don't mix with others
    for sink in plan.sinks:
        if isinstance(sink, ir.SegmentAgg):
            names.extend(name for name, _e, _d in sink.aggs)
        elif isinstance(sink, ir.PresenceCount):
            names.extend(sink.names)
        elif isinstance(sink, (ir.Sort, ir.TopK)):
            # ordered field vectors plus the implicit valid-row count
            names.extend(sink.fields)
            names.append("rows")
        else:
            raise TypeError(f"not a sink node: {sink!r}")
    if ir.has_exchange(plan):
        names.append("dropped")
    names.extend(name for name, _e in plan.post)
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate output names in plan {plan.name!r}")
    if plan.outputs:
        missing = set(plan.outputs) - set(names)
        if missing:
            raise ValueError(f"unknown plan outputs {sorted(missing)}")
        if ir.has_exchange(plan) and "dropped" not in plan.outputs:
            # the runtime's overflow guard reads 'dropped' from the
            # compiled outputs; filtering it away would silently disable
            # ShuffleCapacityExceeded and return wrong counts on overflow
            raise ValueError(
                f"plan {plan.name!r} contains an Exchange: its 'outputs' "
                f"must include 'dropped' (the overflow retry signal)")
        return tuple(plan.outputs)
    return tuple(names)


def _arg_layout(plan: ir.Plan):
    """Flat argument order: scans (table-sorted; fields then the implicit
    row-valid), then dims (table-sorted)."""
    layout = []
    for scan in ir.scan_tables(plan):
        for f in scan.fields:
            layout.append(("scan", scan.table, f))
        layout.append(("scan", scan.table, VALID_FIELD))
    for dim in ir.dim_tables(plan):
        for f in dim.fields:
            layout.append(("dim", dim.table, f))
    return layout


def input_signature(plan: ir.Plan, tables) -> Tuple:
    """The dtype+bucket signature of already-padded input ``tables``
    (table -> field -> array, row-valid included) in flat arg order —
    the variable half of the plan-cache key."""
    sig = []
    for kind, table, field in _arg_layout(plan):
        a = tables[table][field]
        sig.append((kind, table, field, str(a.dtype), int(a.shape[0])))
    return tuple(sig)


def compile_plan(plan: ir.Plan, mesh, signature: Tuple) -> CompiledPlan:
    """Trace + compile ``plan`` for one input signature.  Uncached —
    go through :func:`cached_compile`."""
    from spark_rapids_jni_tpu.obs.seam import COMPILE, seam

    layout = _arg_layout(plan)
    if len(signature) != len(layout):
        raise ValueError("signature does not match the plan's arg layout")
    out_names = output_names(plan)
    local = mesh is None
    if local and ir.has_exchange(plan):
        raise ValueError(
            f"plan {plan.name!r} contains an Exchange: mesh required")
    if ir.range_exchange_nodes(plan):
        raise ValueError(
            f"plan {plan.name!r} contains a RangeExchange: it only runs "
            f"split across the serve shuffle plane (split_exchange_plan)")
    if not local and ir.order_sink(plan) is not None:
        # the mesh path psums every sink output over the data axis —
        # correct for additive partials, destruction for ordered row
        # vectors; distribution happens via the range shuffle instead
        raise ValueError(
            f"plan {plan.name!r} has an order-sensitive sink: compile "
            f"locally (per shuffle partition), not under a mesh")

    def body(*flat):
        inputs: Dict[str, Dict[str, object]] = {}
        rowvalid: Dict[str, object] = {}
        for (kind, table, field), arr in zip(layout, flat):
            if field == VALID_FIELD:
                rowvalid[table] = arr
            else:
                inputs.setdefault(table, {})[field] = arr
        ctx = _Ctx(inputs, rowvalid, mesh)
        outputs: Dict[str, object] = {}
        for sink in plan.sinks:
            outputs.update(_emit(sink, ctx))
        if ctx.dropped:
            outputs["dropped"] = sum(ctx.dropped[1:], ctx.dropped[0])
        if not local:
            from spark_rapids_jni_tpu.parallel.mesh import DATA_AXIS

            outputs = {k: jax.lax.psum(v, (DATA_AXIS,))
                       for k, v in outputs.items()}
        for name, expr in plan.post:
            outputs[name] = _eval(expr, outputs)
        return tuple(outputs[n] for n in out_names)

    with seam(COMPILE, f"plan:{ir.plan_signature(plan)}"):
        if local:
            step = jax.jit(body)
        else:
            from jax.sharding import PartitionSpec as P

            from spark_rapids_jni_tpu.parallel.mesh import (
                DATA_AXIS,
                shard_map,
            )

            in_specs = tuple(
                P(DATA_AXIS) if kind == "scan" else P()
                for kind, _t, _f in layout)
            step = jax.jit(shard_map(
                body, mesh=mesh, in_specs=in_specs,
                out_specs=tuple(P() for _ in out_names),
                check_vma=False,
            ))
        fn, aot, trace_s, compile_s, aot_err = _try_aot(
            step, mesh, layout, signature)
    return CompiledPlan(fn, plan, mesh, signature, out_names,
                        tuple(f"{t}.{f}" for _k, t, f in layout),
                        aot, trace_s, compile_s, aot_err)


def _try_aot(step, mesh, layout, signature):
    """AOT lower+compile so trace and compile are separately timed (the
    bench's compile-amortization story); fall back to the plain jit —
    whose first call pays both — if the backend refuses the abstract
    shardings."""
    try:
        from jax.sharding import NamedSharding, PartitionSpec as P

        avals = []
        for (kind, _t, _f), (_k2, _t2, _f2, dtype, n) in zip(layout,
                                                             signature):
            sharding = None
            if mesh is not None:
                from spark_rapids_jni_tpu.parallel.mesh import DATA_AXIS

                sharding = NamedSharding(
                    mesh, P(DATA_AXIS) if kind == "scan" else P())
            avals.append(jax.ShapeDtypeStruct((n,), DTYPES.get(dtype, dtype),
                                              sharding=sharding))
        t0 = time.perf_counter()
        lowered = step.lower(*avals)
        t1 = time.perf_counter()
        compiled = lowered.compile()
        t2 = time.perf_counter()
        return compiled, True, t1 - t0, t2 - t1, ""
    # analyze: ignore[retry-protocol] - AOT probe at compile time, before
    # any device work launches: no retry bracket is open, and the plain
    # jit fallback is the correct degradation for any lowering failure.
    # NOT silent: the reason rides CompiledPlan.aot_error and the cache
    # counts aot_fallbacks in its stats gauge, so a genuine trace bug
    # deferred to first launch is still visible at the compile layer.
    except Exception as e:  # noqa: BLE001
        return step, False, 0.0, 0.0, f"{type(e).__name__}: {e}"[:200]


def cached_compile(plan: ir.Plan, mesh, tables) -> CompiledPlan:
    """The front door: compiled program for (plan, mesh, padded inputs),
    via the process-global plan cache."""
    sig = input_signature(plan, tables)
    return plan_cache.get_or_compile(
        (plan, mesh, sig), lambda: compile_plan(plan, mesh, sig))


# ------------------------------------------- cross-process exchange split
# A plan whose Exchange runs as a REAL shuffle (serve/shuffle.py: framed
# partition push/pull between executor processes) splits at the Exchange
# node into two halves that reuse this compiler unchanged:
#
# - the **map fragment** — the Exchange's child subtree — runs eagerly
#   per executor over its shard of the scan tables (the SAME registered
#   emitter bodies the jitted path traces, so values are bit-identical),
#   then rows partition by ``partition_of(key) % nparts`` and masked rows
#   drop (exactly what the in-mesh all_to_all's validity mask does);
# - the **reduce plan** — the original plan with the Exchange replaced by
#   a Scan of the synthetic ``EXCHANGE_SOURCE`` table — compiles through
#   :func:`cached_compile` as a LOCAL plan over the concatenated received
#   partitions.  Its sinks are additive partials (psum's host analog is
#   summation at the combiner), so ``post`` expressions move OUT of the
#   reduce plan and evaluate once over the summed sinks (:func:`eval_post`).


#: the synthetic scan table the reduce half reads received rows from
EXCHANGE_SOURCE = "__exchange__"


def split_exchange_plan(plan: ir.Plan):
    """``(exchange_node, reduce_plan)`` for a plan with exactly ONE
    Exchange or RangeExchange.  The reduce plan is local (no Exchange,
    no mesh), reads the shuffled fields from
    ``Scan(EXCHANGE_SOURCE, fields)``, keeps the sinks, and drops
    ``post``/``outputs`` — partials must be combined across executors
    (summed, or order-concatenated for a range shuffle) BEFORE post
    expressions run."""
    exchanges = ir.exchange_nodes(plan) + ir.range_exchange_nodes(plan)
    if len(exchanges) != 1:
        raise ValueError(
            f"plan {plan.name!r} has {len(exchanges)} Exchange nodes; the "
            f"cross-process shuffle supports exactly one")
    exchange = exchanges[0]

    def rebuild(node):
        if node is exchange or node == exchange:
            return ir.Scan(EXCHANGE_SOURCE, node.fields)
        kw = {}
        changed = False
        for f in dataclasses.fields(node):
            v = getattr(node, f.name)
            if isinstance(v, tuple) and v and all(
                    type(item) in _EMITTERS for item in v):
                nv = tuple(rebuild(item) for item in v)
                changed = changed or nv != v
                kw[f.name] = nv
            elif type(v) in _EMITTERS:
                nv = rebuild(v)
                changed = changed or nv is not v
                kw[f.name] = nv
            else:
                kw[f.name] = v
        return dataclasses.replace(node, **kw) if changed else node

    sinks = tuple(rebuild(s) for s in plan.sinks)
    reduce_plan = ir.Plan(f"{plan.name}:reduce", sinks)
    extra = [s.table for s in ir.scan_tables(reduce_plan)
             if s.table != EXCHANGE_SOURCE]
    if extra:
        raise ValueError(
            f"plan {plan.name!r} scans {extra} ABOVE its Exchange: the "
            f"reduce half would re-read whole fact tables per executor "
            f"and double-count — every Scan must feed the Exchange")
    return exchange, reduce_plan


def emit_exchange_partitions(exchange: ir.Exchange, tables,
                             nparts: int) -> list:
    """The map side of one executor's shard: emit the Exchange's child
    subtree eagerly (same emitter bodies as the traced path), hash the
    key with the SAME placement hash the in-mesh all_to_all uses, and
    return ``nparts`` host partition tables of the exchange fields
    (masked rows dropped — the slot-validity analog).  Partition sizes
    are exact, so the fixed-capacity overflow retry of the in-mesh path
    has no cross-process counterpart."""
    import numpy as np

    from spark_rapids_jni_tpu.parallel.shuffle import partition_of

    rows = _emit_host_rows(exchange, tables)
    key = _eval(exchange.key, rows.cols)
    part = np.asarray(partition_of(key, nparts))
    mask = np.asarray(rows.mask)
    cols = {f: np.asarray(rows.cols[f]) for f in exchange.fields}
    out = []
    for p in range(nparts):
        sel = mask & (part == p)
        out.append({f: np.ascontiguousarray(v[sel])
                    for f, v in cols.items()})
    return out


def _emit_host_rows(exchange, tables) -> _Rows:
    """Eagerly emit an exchange node's child subtree over host shard
    tables (same emitter bodies as the traced path, so values are
    bit-identical) — the shared map-side front half of the hash and
    range partition emitters."""
    inputs: Dict[str, Dict[str, object]] = {}
    rowvalid: Dict[str, object] = {}
    for table, fields in tables.items():
        inputs[table] = {k: jnp.asarray(v) for k, v in fields.items()}
        n = len(next(iter(fields.values())))
        # analyze: ignore[governed-allocation] - the all-valid row mask
        # of an EXACT (unpadded) shard: O(rows) bools inside the serve
        # bracket that admitted the shuffle piece, already covered by
        # the shard's working-set estimate like the shard columns above
        rowvalid[table] = jnp.ones((n,), jnp.bool_)
    return _emit(exchange.child, _Ctx(inputs, rowvalid, None))


def _host_rank_cols(exchange: "ir.RangeExchange", rows: _Rows) -> list:
    """The host uint64 rank columns of a range exchange's sort keys —
    the SAME canonical transform the traced order emitters apply, so
    partition placement and device order can never disagree."""
    import numpy as np

    from spark_rapids_jni_tpu.plans import window as win

    return [win.sort_rank_np(np.asarray(_eval(e, rows.cols)), asc)
            for e, asc in exchange.keys]


def sample_range_splitters(exchange: "ir.RangeExchange", tables,
                           nparts: int, sample_cap: int = 4096) -> list:
    """Driver-side splitter choice for one range shuffle: emit the map
    fragment over the full input ONCE, sample the valid rows' composite
    sort ranks evenly, take quantile boundaries.  Every map shard must
    ride with the SAME splitters (they define the global partition
    order), so this runs once at dispatch, not per shard."""
    import numpy as np

    from spark_rapids_jni_tpu.plans import window as win

    rows = _emit_host_rows(exchange, tables)
    ranks = _host_rank_cols(exchange, rows)
    return win.choose_splitters(ranks, np.asarray(rows.mask), nparts,
                                sample_cap=sample_cap)


def emit_range_partitions(exchange: "ir.RangeExchange", tables,
                          nparts: int, splitters) -> list:
    """The map side of one executor's shard of a RANGE shuffle: emit the
    child subtree eagerly, rank rows by the exchange's sort keys (the
    canonical uint64 transform), and bucket them against the dispatch-
    time ``splitters`` — partition ``p``'s every row orders before
    partition ``p+1``'s, so the reduce side's per-partition sorted
    outputs concatenate into global order with no merge.

    With ``exchange.limit`` set (partial top-k pushdown), only this
    shard's first ``limit`` ordered VALID rows are partitioned at all:
    the global top-k is a subset of the per-shard top-k's, so at most
    ``limit * shards`` rows cross the wire instead of every row."""
    import numpy as np

    from spark_rapids_jni_tpu.plans import window as win

    if len(splitters) != nparts - 1:
        raise ValueError(
            f"range shuffle wants {nparts - 1} splitters, got "
            f"{len(splitters)}")
    rows = _emit_host_rows(exchange, tables)
    ranks = _host_rank_cols(exchange, rows)
    mask = np.asarray(rows.mask)
    sel = np.flatnonzero(mask)
    # valid rows in key order (np.lexsort: last key is primary)
    sel = sel[np.lexsort(tuple(reversed([r[sel] for r in ranks])))]
    if exchange.limit is not None:
        sel = sel[:int(exchange.limit)]
    part = win.range_partition([r[sel] for r in ranks], splitters)
    cols = {f: np.asarray(rows.cols[f])[sel] for f in exchange.fields}
    out = []
    for p in range(nparts):
        take = part == p
        out.append({f: np.ascontiguousarray(v[take])
                    for f, v in cols.items()})
    return out


def eval_post(plan: ir.Plan, sums: Dict[str, object]) -> Dict[str, object]:
    """Post expressions over the cross-executor SUMMED sink outputs —
    the host twin of the traced path's psum-then-post ordering.  Returns
    sinks + posts filtered/ordered like :func:`output_names` (minus the
    in-mesh path's implicit ``dropped``, which exact-size framed
    partitions cannot produce)."""
    import numpy as np

    env = dict(sums)
    for name, expr in plan.post:
        env[name] = np.asarray(_eval(expr, env))
    names = [n for n in output_names(plan) if n != "dropped"]
    return {n: env[n] for n in names}


# ----------------------------------------------- ragged calling convention


@dataclasses.dataclass(frozen=True)
class RaggedProgram:
    """The hashable identity of one page-pool-shaped fused program — the
    plan-cache key the ragged serving path compiles under (the analog of
    an :class:`ir.Plan` value for a handler kernel instead of a query
    IR).  ``geometry`` is a :class:`columnar.pages.PageGeometry`; equal
    (kernel, geometry, out) ticks share one compiled executable, so a
    long-lived executor's cache holds one entry per PAGE GEOMETRY, not
    one per request-shape bucket.

    ``kernel_key`` names the kernel (module-qualified by default):
    handler registration is per engine, but the plan cache is process
    global, so the key must identify the FUNCTION, not the handler name
    a second engine may rebind.
    """

    kernel_key: str
    geometry: object  # columnar.pages.PageGeometry (frozen, hashable)
    out: str          # "rows" (row-aligned) | "riders" (per-rider vector)

    @property
    def name(self) -> str:
        return f"ragged:{self.kernel_key}:{self.geometry.describe()}"


def _ragged_signature(prog: RaggedProgram) -> Tuple:
    """The flat input signature of the page-pool calling convention:
    ``(data[total_rows] dtype, valid[total_rows] bool,
    rid[total_rows] int32)`` — entirely geometry-derived, the property
    the cache-bounding acceptance test pins."""
    g = prog.geometry
    n = g.total_rows
    return (("pages", "pool", "data", g.dtype, n),
            ("pages", "pool", VALID_FIELD, "bool", n),
            ("pages", "pool", "rid", "int32", n))


def compile_ragged(prog: RaggedProgram, kernel: Callable) -> CompiledPlan:
    """Trace + compile ``kernel`` under the page-pool calling convention.

    ``kernel(data, valid, rid, riders_cap)`` is traced device code over
    the flat pool buffers (``riders_cap`` is static, baked into the
    trace); it returns ONE array, either row-aligned (``out="rows"`` —
    the executor scatters slices back per rider) or per-rider
    (``out="riders"``, indexed by the pack's rider ids; padding rows
    carry ``rid == riders_cap`` so a segment scatter's drop bucket is
    index ``riders_cap`` — kernels must size segment outputs
    ``riders_cap + 1`` and drop the tail, like the masked-segment
    aggregate emitter).  Uncached — go through
    :func:`cached_ragged_compile`.
    """
    from spark_rapids_jni_tpu.obs.seam import COMPILE, seam

    g = prog.geometry
    riders_cap = g.riders_cap

    def body(data, valid, rid):
        return (kernel(data, valid, rid, riders_cap),)

    with seam(COMPILE, prog.name):
        step = jax.jit(body)
        fn, aot, trace_s, compile_s, aot_err = _try_aot_flat(
            step, _ragged_signature(prog))
    return CompiledPlan(fn, prog, None, _ragged_signature(prog),
                        ("out",), ("pool.data", "pool.__valid__",
                                   "pool.rid"),
                        aot, trace_s, compile_s, aot_err)


def _try_aot_flat(step, signature):
    """AOT lower+compile over a flat (unsharded) signature — the ragged
    twin of :func:`_try_aot` (which builds per-table shardings a page
    pool does not have)."""
    try:
        avals = [jax.ShapeDtypeStruct((n,), DTYPES.get(dtype, dtype))
                 for _k, _t, _f, dtype, n in signature]
        t0 = time.perf_counter()
        lowered = step.lower(*avals)
        t1 = time.perf_counter()
        compiled = lowered.compile()
        t2 = time.perf_counter()
        return compiled, True, t1 - t0, t2 - t1, ""
    # analyze: ignore[retry-protocol] - AOT probe at compile time, before
    # any device work launches (same degradation contract as _try_aot):
    # the plain-jit fallback is correct for any lowering refusal, and the
    # reason rides CompiledPlan.aot_error + the cache's aot_fallbacks
    # gauge rather than being swallowed.
    except Exception as e:  # noqa: BLE001
        return step, False, 0.0, 0.0, f"{type(e).__name__}: {e}"[:200]


def cached_ragged_compile(prog: RaggedProgram,
                          kernel: Callable) -> CompiledPlan:
    """The ragged front door: one compiled executable per
    (kernel, page geometry, out kind), via the SAME process-global plan
    cache (ragged programs compete for residency with query plans and
    show up in the same hit/miss gauges — the compile-pressure story is
    one story)."""
    return plan_cache.get_or_compile(
        (prog, None, _ragged_signature(prog)),
        lambda: compile_ragged(prog, kernel))
