"""Vectorized UTF-8 decoding over padded byte matrices.

Several reference kernels operate on *characters* (codepoints) rather than
bytes — cudf::string_view indexes by character (regex_rewrite_utils.cu,
parse_uri.cu's UTF-8 handling).  This module decodes a dense ``[n, L]`` byte
matrix into a character-indexed codepoint matrix with pure lane arithmetic:
classify lead bytes, gather up to 3 continuation bytes with static shifts,
then compact to char positions with a cumsum scatter.

Invalid sequences decode to the replacement semantics of "whatever the bytes
say": no validation is performed (matching cudf's permissive utf8 decode).
"""

from __future__ import annotations

import jax.numpy as jnp


def decode_utf8(padded: jnp.ndarray, lens: jnp.ndarray):
    """Decode ``bytes[n, L]`` (lengths in bytes) to characters.

    Returns ``(cp[n, L] int32, nchars[n] int32)`` where ``cp[:, k]`` is the
    codepoint of character ``k`` (0 beyond ``nchars``).  The output is
    char-compacted: column k holds the k-th character, not the byte at k.
    """
    n, L = padded.shape
    b = padded.astype(jnp.int32)
    pos = jnp.arange(L, dtype=jnp.int32)[None, :]
    in_str = pos < lens[:, None]

    is_cont = (b & 0xC0) == 0x80
    is_lead = in_str & ~is_cont
    # bytes of the sequence: gather with static shifts (zeros beyond L)
    bp = jnp.pad(b, ((0, 0), (0, 3)))
    b1, b2, b3 = bp[:, 1 : L + 1], bp[:, 2 : L + 2], bp[:, 3 : L + 3]

    one = b < 0x80
    two = (b & 0xE0) == 0xC0
    three = (b & 0xF0) == 0xE0
    # four = (b & 0xF8) == 0xF0 (the fall-through case)
    cp = jnp.where(
        one,
        b,
        jnp.where(
            two,
            ((b & 0x1F) << 6) | (b1 & 0x3F),
            jnp.where(
                three,
                ((b & 0x0F) << 12) | ((b1 & 0x3F) << 6) | (b2 & 0x3F),
                ((b & 0x07) << 18) | ((b1 & 0x3F) << 12) | ((b2 & 0x3F) << 6)
                | (b3 & 0x3F),
            ),
        ),
    )

    # compact to character positions
    char_idx = jnp.cumsum(is_lead.astype(jnp.int32), axis=1) - 1
    nchars = jnp.sum(is_lead, axis=1).astype(jnp.int32)
    out = jnp.zeros((n, L), jnp.int32)
    rows = jnp.arange(n, dtype=jnp.int32)[:, None]
    tgt = jnp.where(is_lead, char_idx, L)  # dropped when not a lead byte
    out = out.at[rows, tgt].set(jnp.where(is_lead, cp, 0), mode="drop")
    return out, nchars
