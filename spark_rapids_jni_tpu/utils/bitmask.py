"""Arrow validity-bitmask pack/unpack and bitwise utilities.

The reference keeps validity as packed bits (cudf) and provides
`bitmask_bitwise_or` (utilities.cu:24-72) for merging.  On TPU we keep validity
unpacked (bool lanes) inside ops and pack only at interchange boundaries
(JCUDF rows, serialized bloom filters, Arrow IPC).
"""

from __future__ import annotations

import jax.numpy as jnp


def pack_bits(mask: jnp.ndarray) -> jnp.ndarray:
    """bool[n] -> uint8[ceil(n/8)], LSB-first (Arrow order)."""
    n = mask.shape[0]
    pad = (-n) % 8
    m = jnp.pad(mask.astype(jnp.uint8), (0, pad)).reshape(-1, 8)
    weights = (jnp.uint8(1) << jnp.arange(8, dtype=jnp.uint8))[None, :]
    return jnp.sum(m * weights, axis=1, dtype=jnp.uint8)


def unpack_bits(packed: jnp.ndarray, n: int) -> jnp.ndarray:
    """uint8[ceil(n/8)] -> bool[n], LSB-first."""
    bits = (packed[:, None] >> jnp.arange(8, dtype=jnp.uint8)[None, :]) & jnp.uint8(1)
    return bits.reshape(-1)[:n].astype(jnp.bool_)


def bitmask_or(masks) -> jnp.ndarray:
    """Bitwise OR of equal-length packed masks (utilities.hpp:33-40 analog)."""
    out = masks[0]
    for m in masks[1:]:
        out = out | m
    return out


def bitmask_and(masks) -> jnp.ndarray:
    out = masks[0]
    for m in masks[1:]:
        out = out & m
    return out
