"""Vectorized 128-bit integer arithmetic as (hi int64, lo uint64) limb pairs.

TPUs have no native int128; the MXU/VPU operate on 32-bit lanes and JAX's x64
mode executes 64-bit integer ops as 32-bit pairs.  Spark's DECIMAL128 semantics
(reference: decimal_utils.cu `chunked256`, cast_string.cu `__int128_t` paths)
therefore run here as two's-complement (hi, lo) limb arithmetic: every helper is
elementwise over same-shape arrays and safe under jit.

Conventions: value = hi * 2**64 + lo  (hi signed int64, lo unsigned uint64).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

MASK64 = (1 << 64) - 1
_MASK32 = (1 << 32) - 1


def const128(v: int):
    """Split a python int into scalar (hi int64, lo uint64) numpy constants."""
    v &= (1 << 128) - 1
    hi = (v >> 64) & MASK64
    if hi >= 1 << 63:
        hi -= 1 << 64
    return np.int64(hi), np.uint64(v & MASK64)





def add_small(hi, lo, d):
    """(hi, lo) + d where d is a small non-negative int64/array."""
    d = jnp.asarray(d).astype(jnp.uint64)
    lo2 = lo + d
    carry = (lo2 < lo).astype(jnp.int64)
    return hi + carry, lo2


def sub_small(hi, lo, d):
    d = jnp.asarray(d).astype(jnp.uint64)
    lo2 = lo - d
    borrow = (lo2 > lo).astype(jnp.int64)
    return hi - borrow, lo2


def neg(hi, lo):
    nh = ~hi
    nl = ~lo
    lo2 = nl + jnp.uint64(1)
    # +1 carries into hi exactly when nl was all-ones, i.e. lo2 wrapped to 0
    carry = (lo2 == jnp.uint64(0)).astype(jnp.int64)
    return nh + carry, lo2


def abs_(hi, lo):
    is_neg = hi < 0
    nh, nl = neg(hi, lo)
    return jnp.where(is_neg, nh, hi), jnp.where(is_neg, nl, lo)


def mul_small(hi, lo, k: int):
    """(hi, lo) * k for a small positive python-int k (fits in 32 bits).

    The low-limb product is built from 32-bit halves so no intermediate
    exceeds uint64.
    """
    ku = jnp.uint64(k)
    a = lo >> jnp.uint64(32)
    b = lo & jnp.uint64(_MASK32)
    t = b * ku
    u = a * ku + (t >> jnp.uint64(32))
    lo2 = (u << jnp.uint64(32)) | (t & jnp.uint64(_MASK32))
    carry = (u >> jnp.uint64(32)).astype(jnp.int64)
    return hi * jnp.int64(k) + carry, lo2



def lt(ah, al, bh, bl):
    """Signed (ah,al) < (bh,bl)."""
    return (ah < bh) | ((ah == bh) & (al < bl))


def gt(ah, al, bh, bl):
    return (ah > bh) | ((ah == bh) & (al > bl))


def eq(ah, al, bh, bl):
    return (ah == bh) & (al == bl)





# |value| >= 10**k comparisons, used for digit counting of 128-bit magnitudes.
_POW10_TABLE = [const128(10**k) for k in range(40)]


def count_digits(hi, lo):
    """Number of base-10 digits of |value| (0 -> 0 digits, like the reference's
    count_digits which loops while val != 0; cast_string.cu:490-497)."""
    mh, ml = abs_(hi, lo)
    count = jnp.zeros(hi.shape, dtype=jnp.int32)
    for k in range(40):
        ph, pl = _POW10_TABLE[k]
        ge = ~lt(mh, ml, jnp.int64(ph), jnp.uint64(pl))
        count = count + ge.astype(jnp.int32)
    return count


