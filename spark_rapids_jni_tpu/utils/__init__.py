from spark_rapids_jni_tpu.utils import bitmask

__all__ = ["bitmask"]
