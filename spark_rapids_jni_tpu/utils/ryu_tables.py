"""Ryu power-of-5 lookup tables, generated exactly with python big ints.

The reference computes these on device from compressed tables
(ftos_converter.cuh:404-456 double_computePow5/double_computeInvPow5, matching
ryu's PrintDoubleLookupTable).  Here the full split tables are materialized at
import with exact integer arithmetic:

- DOUBLE_POW5_SPLIT[i]  = 5^i normalized to 125 bits (floor), i in [0, 326)
- DOUBLE_POW5_INV_SPLIT[i] = floor(2^k / 5^i) + 1 normalized to 125 bits,
  i in [0, 292)
- FLOAT_POW5_SPLIT / FLOAT_POW5_INV_SPLIT: the 64-bit (61-bit count) variants.

Each 125-bit double entry is stored as (lo uint64, hi uint64).
"""

from __future__ import annotations

import numpy as np

DOUBLE_POW5_BITCOUNT = 125
DOUBLE_POW5_INV_BITCOUNT = 125
FLOAT_POW5_BITCOUNT = DOUBLE_POW5_BITCOUNT - 64  # 61
FLOAT_POW5_INV_BITCOUNT = DOUBLE_POW5_INV_BITCOUNT - 64  # 61

_MASK64 = (1 << 64) - 1


def _pow5bits(e: int) -> int:
    """ceil(e * log2(5)) + 1, the bit length of 5^e (ftos_converter.cuh:185)."""
    return ((e * 1217359) >> 19) + 1


def _gen_double_tables():
    n_pow, n_inv = 326, 292
    pow_lo = np.zeros(n_pow, np.uint64)
    pow_hi = np.zeros(n_pow, np.uint64)
    inv_lo = np.zeros(n_inv, np.uint64)
    inv_hi = np.zeros(n_inv, np.uint64)
    for i in range(n_pow):
        p = 5**i
        bits = _pow5bits(i)
        # normalize to exactly DOUBLE_POW5_BITCOUNT bits: exact left shift for
        # small powers, truncating right shift (floor) for large ones
        shift = DOUBLE_POW5_BITCOUNT - bits
        v = p << shift if shift >= 0 else p >> -shift
        pow_lo[i] = v & _MASK64
        pow_hi[i] = v >> 64
    for i in range(n_inv):
        p = 5**i
        bits = _pow5bits(i)
        v = ((1 << (bits + DOUBLE_POW5_INV_BITCOUNT - 1)) // p) + 1
        inv_lo[i] = v & _MASK64
        inv_hi[i] = v >> 64
    return pow_lo, pow_hi, inv_lo, inv_hi


def _gen_float_tables():
    n_pow, n_inv = 47, 55
    pw = np.zeros(n_pow, np.uint64)
    inv = np.zeros(n_inv, np.uint64)
    for i in range(n_pow):
        p = 5**i
        bits = _pow5bits(i)
        shift = FLOAT_POW5_BITCOUNT - bits
        pw[i] = (p << shift if shift >= 0 else p >> -shift) & _MASK64
    for i in range(n_inv):
        p = 5**i
        bits = _pow5bits(i)
        inv[i] = ((1 << (bits + FLOAT_POW5_INV_BITCOUNT - 1)) // p) + 1
    return pw, inv


(
    DOUBLE_POW5_SPLIT_LO,
    DOUBLE_POW5_SPLIT_HI,
    DOUBLE_POW5_INV_SPLIT_LO,
    DOUBLE_POW5_INV_SPLIT_HI,
) = _gen_double_tables()

FLOAT_POW5_SPLIT, FLOAT_POW5_INV_SPLIT = _gen_float_tables()
