"""Vectorized 256-bit integer arithmetic as 8x32-bit limb tensors.

The reference's DECIMAL128 math (decimal_utils.cu `chunked256`, multiply at
decimal_utils.cu:126, long division at :148, half-up rounding at :192) runs on
native 64/128-bit scalars per CUDA thread.  TPUs have neither int128 nor a
per-row scalar unit; here a 256-bit value is a little-endian tensor of eight
32-bit limbs (``uint32[..., 8]``) so limb products fit exactly in uint64 lanes
and every operation is elementwise over the leading (row) axes, safe under jit.

Sign convention: two's complement over the full 256 bits (limb 7's top bit).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

NLIMBS = 8
_M32 = jnp.uint64(0xFFFFFFFF)
_U32 = jnp.uint32
_U64 = jnp.uint64


def const256(v: int) -> np.ndarray:
    """Python int -> (8,) uint32 little-endian two's-complement limbs."""
    v &= (1 << 256) - 1
    return np.array([(v >> (32 * i)) & 0xFFFFFFFF for i in range(NLIMBS)], dtype=np.uint32)


# 10**k for k in 0..76 (product of two decimal-38 values is < 10**76), the
# vectorized analog of the reference's generated pow_ten switch
# (decimal_utils.cu:246+).
POW10 = np.stack([const256(10**k) for k in range(77)])  # (77, 8) uint32


def from_i128(hi, lo):
    """Sign-extend (hi int64, lo uint64) into limbs[..., 8]."""
    hi = hi.astype(jnp.int64)
    lo = lo.astype(jnp.uint64)
    sign = jnp.where(hi < 0, _U32(0xFFFFFFFF), _U32(0))
    limbs = [
        (lo & _M32).astype(_U32),
        ((lo >> _U64(32)) & _M32).astype(_U32),
        (hi.astype(jnp.uint64) & _M32).astype(_U32),
        ((hi.astype(jnp.uint64) >> _U64(32)) & _M32).astype(_U32),
        sign,
        sign,
        sign,
        sign,
    ]
    return jnp.stack(limbs, axis=-1)


def from_i64(x):
    """Sign-extend int64 into limbs[..., 8]."""
    x = x.astype(jnp.int64)
    hi = jnp.where(x < 0, jnp.int64(-1), jnp.int64(0))
    return from_i128(hi, x.astype(jnp.uint64))


def to_i128(limbs):
    """Truncate to the low 128 bits as (hi int64, lo uint64)."""
    l = limbs.astype(jnp.uint64)
    lo = l[..., 0] | (l[..., 1] << _U64(32))
    hi = (l[..., 2] | (l[..., 3] << _U64(32))).astype(jnp.int64)
    return hi, lo


def to_i64(limbs):
    """Truncate to the low 64 bits as signed int64 (reference as_64_bits)."""
    l = limbs.astype(jnp.uint64)
    return (l[..., 0] | (l[..., 1] << _U64(32))).astype(jnp.int64)


def is_negative(limbs):
    return (limbs[..., 7] >> _U32(31)) != _U32(0)


def add(a, b):
    """256-bit add, carries rippled through uint64 lanes."""
    out = []
    carry = _U64(0)
    for i in range(NLIMBS):
        s = a[..., i].astype(_U64) + b[..., i].astype(_U64) + carry
        out.append((s & _M32).astype(_U32))
        carry = s >> _U64(32)
    return jnp.stack(out, axis=-1)


def add_small(a, d):
    """a + d for signed int64/int32 d (sign-extended); d may be an array."""
    return add(a, from_i64(jnp.asarray(d)))


def negate(a):
    out = []
    carry = _U64(1)
    for i in range(NLIMBS):
        s = (~a[..., i]).astype(_U64) + carry
        out.append((s & _M32).astype(_U32))
        carry = s >> _U64(32)
    return jnp.stack(out, axis=-1)


def abs256(a):
    return jnp.where(is_negative(a)[..., None], negate(a), a)


def multiply(a, b):
    """Schoolbook 8x8 32-bit-limb multiply keeping the low 256 bits
    (reference multiply, decimal_utils.cu:126)."""
    au = [a[..., i].astype(_U64) for i in range(NLIMBS)]
    bu = [b[..., i].astype(_U64) for i in range(NLIMBS)]
    r = [jnp.zeros_like(au[0]) for _ in range(NLIMBS)]
    for b_idx in range(NLIMBS):
        carry = _U64(0)
        for a_idx in range(NLIMBS - b_idx):
            r_idx = a_idx + b_idx
            m = au[a_idx] * bu[b_idx] + r[r_idx] + carry
            r[r_idx] = m & _M32
            carry = m >> _U64(32)
    return jnp.stack([x.astype(_U32) for x in r], axis=-1)


def lt_unsigned(a, b):
    """Unsigned a < b, lexicographic from the high limb down."""
    lt = jnp.zeros(a.shape[:-1], dtype=jnp.bool_)
    eq = jnp.ones(a.shape[:-1], dtype=jnp.bool_)
    for i in range(NLIMBS - 1, -1, -1):
        lt = lt | (eq & (a[..., i] < b[..., i]))
        eq = eq & (a[..., i] == b[..., i])
    return lt


def gte_unsigned(a, b):
    return ~lt_unsigned(a, b)


def eq256(a, b):
    return jnp.all(a == b, axis=-1)


def _bcast(table_row, like):
    """Broadcast a host (8,) limb constant against limbs[..., 8]."""
    c = jnp.asarray(table_row)
    return jnp.broadcast_to(c, like.shape[:-1] + (NLIMBS,))


def pow_ten(k, like):
    """10**k as limbs broadcast to ``like``'s shape; k is a traced int array
    (clipped to [0, 76]) or a python int."""
    if isinstance(k, int):
        return _bcast(POW10[k], like)
    table = jnp.asarray(POW10)
    return table[jnp.clip(k, 0, 76)]


def precision10(a):
    """Smallest i with 10**i >= |a| (reference precision10,
    decimal_utils.cu:520: NOT digit count — exact powers of ten return their
    exponent).  Equals the number of k in [0, 76] with 10**k < |a|."""
    mag = abs256(a)
    table = jnp.asarray(POW10)  # (77, 8)
    # lt_unsigned(pow10[k], mag) for all k at once: broadcast rows axis.
    p = jnp.broadcast_to(table, mag.shape[:-1] + (77, NLIMBS))
    lt = jnp.zeros(p.shape[:-1], dtype=jnp.bool_)
    eq = jnp.ones(p.shape[:-1], dtype=jnp.bool_)
    m = mag[..., None, :]
    for i in range(NLIMBS - 1, -1, -1):
        lt = lt | (eq & (p[..., i] < m[..., i]))
        eq = eq & (p[..., i] == m[..., i])
    return jnp.sum(lt, axis=-1).astype(jnp.int32)


def is_greater_than_decimal_38(a):
    """|a| >= 10**38: Spark's precision-38 overflow test
    (decimal_utils.cu:537)."""
    return gte_unsigned(abs256(a), _bcast(POW10[38], a))


def _u128_lt(ahi, alo, bhi, blo):
    return (ahi < bhi) | ((ahi == bhi) & (alo < blo))


def divide_unsigned(n, d_hi, d_lo):
    """256-bit / 128-bit long division (reference divide_unsigned,
    decimal_utils.cu:148): returns (quotient limbs, remainder (hi, lo) u64).

    n must be non-negative (as unsigned), d positive and < 2**127.  Bitwise
    restoring division: 256 sequential steps of elementwise vector work — the
    per-bit loop is over a *scalar* index, all rows advance in lockstep on the
    VPU.  The inner 32 bits of each limb run in a fori_loop; the 8 limbs are
    unrolled so limb indexing stays static.
    """
    d_hi = d_hi.astype(_U64)
    d_lo = d_lo.astype(_U64)
    r_hi = jnp.zeros_like(d_hi)
    r_lo = jnp.zeros_like(d_lo)
    q_limbs = []

    for block in range(NLIMBS - 1, -1, -1):
        nb = n[..., block].astype(_U64)

        def body(i, state, nb=nb):
            r_hi, r_lo, q_block = state
            bit_pos = _U64(31) - i.astype(_U64)
            read = (nb >> bit_pos) & _U64(1)
            r_hi = (r_hi << _U64(1)) | (r_lo >> _U64(63))
            r_lo = (r_lo << _U64(1)) | read
            ge = ~_u128_lt(r_hi, r_lo, d_hi, d_lo)
            new_lo = r_lo - d_lo
            borrow = (new_lo > r_lo).astype(_U64)
            new_hi = r_hi - d_hi - borrow
            r_hi = jnp.where(ge, new_hi, r_hi)
            r_lo = jnp.where(ge, new_lo, r_lo)
            q_block = q_block | jnp.where(ge, _U64(1) << bit_pos, _U64(0))
            return r_hi, r_lo, q_block

        r_hi, r_lo, q_block = jax.lax.fori_loop(
            0, 32, body, (r_hi, r_lo, jnp.zeros_like(r_lo))
        )
        q_limbs.append((q_block & _M32).astype(_U32))

    q_limbs.reverse()
    return jnp.stack(q_limbs, axis=-1), r_hi, r_lo


def divide(n, d_hi, d_lo):
    """Signed divide: 256-bit n / 128-bit d -> (quotient limbs, remainder
    (hi int64, lo uint64) signed, sign of n).  Truncating (toward zero), like
    the reference divide (decimal_utils.cu:170): quotient negative iff signs
    differ, remainder carries n's sign."""
    n_neg = is_negative(n)
    d_neg = d_hi.astype(jnp.int64) < 0
    abs_n = abs256(n)
    # |d| in unsigned 128
    nd_lo = (~d_lo) + _U64(1)
    nd_hi = (~d_hi.astype(_U64)) + (nd_lo == _U64(0)).astype(_U64)
    ad_hi = jnp.where(d_neg, nd_hi, d_hi.astype(_U64))
    ad_lo = jnp.where(d_neg, nd_lo, d_lo)
    q, r_hi, r_lo = divide_unsigned(abs_n, ad_hi, ad_lo)
    q = jnp.where((d_neg != n_neg)[..., None], negate(q), q)
    # negate remainder where n negative
    nr_lo = (~r_lo) + _U64(1)
    nr_hi = (~r_hi) + (nr_lo == _U64(0)).astype(_U64)
    r_hi = jnp.where(n_neg, nr_hi, r_hi).astype(jnp.int64)
    r_lo = jnp.where(n_neg, nr_lo, r_lo)
    return q, r_hi, r_lo


def round_from_remainder(q, r_hi, r_lo, n_neg, d_hi, d_lo):
    """Half-up rounding increment from a division remainder (reference
    round_from_remainder, decimal_utils.cu:192): bump |q| by one ulp away from
    zero when |2r| >= |d|, with the doubled-remainder-overflow short circuit."""
    r_hi = r_hi.astype(jnp.int64)
    r_lo = r_lo.astype(_U64)
    dbl_hi = (r_hi << jnp.int64(1)) | (r_lo >> _U64(63)).astype(jnp.int64)
    dbl_lo = r_lo << _U64(1)
    # did (r << 1) >> 1 lose information?
    back_hi = (dbl_hi >> jnp.int64(1))
    back_lo = (dbl_lo >> _U64(1)) | (dbl_hi.astype(_U64) << _U64(63))
    lost = (back_hi != r_hi) | (back_lo != r_lo)
    # |2r| and |d| as unsigned 128
    a2_hi, a2_lo = _abs_i128(dbl_hi, dbl_lo)
    ad_hi, ad_lo = _abs_i128(d_hi.astype(jnp.int64), d_lo)
    ge = ~_u128_lt(a2_hi, a2_lo, ad_hi, ad_lo)
    need_inc = lost | ge
    d_neg = d_hi.astype(jnp.int64) < 0
    round_down = n_neg != d_neg
    inc = jnp.where(
        need_inc, jnp.where(round_down, jnp.int64(-1), jnp.int64(1)), jnp.int64(0)
    )
    return add(q, from_i64(inc))


def _abs_i128(hi, lo):
    neg = hi < 0
    n_lo = (~lo) + _U64(1)
    n_hi = (~hi.astype(_U64)) + (n_lo == _U64(0)).astype(_U64)
    return jnp.where(neg, n_hi, hi.astype(_U64)), jnp.where(neg, n_lo, lo)


def divide_and_round(n, d_hi, d_lo):
    """n / d with Java HALF_UP rounding (decimal_utils.cu:228)."""
    q, r_hi, r_lo = divide(n, d_hi, d_lo)
    return round_from_remainder(q, r_hi, r_lo, is_negative(n), d_hi, d_lo)


def integer_divide(n, d_hi, d_lo):
    """n / d truncated toward zero — Java DOWN rounding (decimal_utils.cu:238)."""
    q, _, _ = divide(n, d_hi, d_lo)
    return q
