"""Minimal TZif (RFC 8536) reader for building timezone transition tables.

The reference builds its transition tables from ``java.time.ZoneRules`` on the
JVM (GpuTimeZoneDB.java:261-335).  Python's ``zoneinfo`` does not expose
transitions, so we read the TZif files (system ``/usr/share/zoneinfo`` or the
``tzdata`` wheel) directly.  Only the pieces the timezone DB needs are parsed:
the 64-bit transition instants, the pre/post offsets of each transition, and
the footer TZ string (used to decide whether the zone has recurring DST rules,
the equivalent of ``ZoneRules.getTransitionRules().isEmpty()``).
"""

from __future__ import annotations

import dataclasses
import os
import re
import struct
from typing import List, Optional

import zoneinfo


@dataclasses.dataclass
class TzTransition:
    instant: int  # epoch seconds of the transition
    offset_before: int  # utc offset seconds in effect before
    offset_after: int  # utc offset seconds in effect after

    @property
    def is_gap(self) -> bool:
        return self.offset_after > self.offset_before


@dataclasses.dataclass
class TzRules:
    """Parsed rules of one zone."""

    transitions: List[TzTransition]
    initial_offset: int  # offset before the first transition (or the fixed offset)
    footer: str  # TZ string ('' for v1 files)

    @property
    def is_fixed(self) -> bool:
        return not self.transitions

    @property
    def has_recurring_dst(self) -> bool:
        """True if the footer TZ string specifies a DST name/rule part.

        Equivalent to Java's ``!ZoneRules.getTransitionRules().isEmpty()``:
        a POSIX TZ string ``std offset [dst [offset] [,start[/t],end[/t]]]``
        has recurring rules iff a dst part follows the std offset.
        """
        s = self.footer.strip()
        if not s:
            return False
        i = 0
        # std name: quoted <...> or alpha run
        if s[i] == "<":
            i = s.index(">", i) + 1
        else:
            while i < len(s) and (s[i].isalpha()):
                i += 1
        # offset: [+-]hh[:mm[:ss]]
        while i < len(s) and (s[i].isdigit() or s[i] in "+-:"):
            i += 1
        return i < len(s)  # anything left is a dst part


_KEY_PART = re.compile(r"^[A-Za-z0-9_.+-]+$")


def _valid_key(key: str) -> bool:
    """Reject path traversal: each '/'-part must be a plain name (no '..')."""
    parts = key.split("/")
    return bool(parts) and all(
        p not in ("", ".", "..") and _KEY_PART.match(p) for p in parts
    )


def _find_tzfile(key: str) -> Optional[str]:
    if not _valid_key(key):
        return None
    for base in zoneinfo.TZPATH:
        path = os.path.join(base, *key.split("/"))
        if os.path.isfile(path):
            return path
    try:
        import importlib.resources as res

        pkg = "tzdata.zoneinfo." + ".".join(key.split("/")[:-1])
        name = key.split("/")[-1]
        ref = res.files(pkg.rstrip(".")) / name
        if ref.is_file():
            return str(ref)
    except (ImportError, OSError):  # no tzdata wheel / unreadable resource
        pass
    return None


def read_tzif(key: str) -> TzRules:
    """Parse the TZif file of ``key`` (e.g. 'Asia/Shanghai')."""
    path = _find_tzfile(key)
    if path is None:
        raise KeyError(f"No TZif data found for zone '{key}'")
    with open(path, "rb") as f:
        data = f.read()
    return parse_tzif(data)


def _parse_header(data: bytes, pos: int):
    magic, version = data[pos : pos + 4], data[pos + 4 : pos + 5]
    if magic != b"TZif":
        raise ValueError("Not a TZif file")
    counts = struct.unpack(">6I", data[pos + 20 : pos + 44])
    return version, counts  # isutcnt, isstdcnt, leapcnt, timecnt, typecnt, charcnt


def _block_size(counts, time_size: int) -> int:
    isutcnt, isstdcnt, leapcnt, timecnt, typecnt, charcnt = counts
    return (
        timecnt * time_size
        + timecnt
        + typecnt * 6
        + charcnt
        + leapcnt * (time_size + 4)
        + isstdcnt
        + isutcnt
    )


def _parse_block(data: bytes, pos: int, counts, time_size: int):
    _, _, _, timecnt, typecnt, _ = counts
    fmt = ">%d%s" % (timecnt, "q" if time_size == 8 else "i")
    times = list(struct.unpack_from(fmt, data, pos)) if timecnt else []
    pos += timecnt * time_size
    type_idx = list(data[pos : pos + timecnt])
    pos += timecnt
    ttinfos = []
    for i in range(typecnt):
        utoff, isdst, _desig = struct.unpack_from(">iBB", data, pos + i * 6)
        ttinfos.append((utoff, bool(isdst)))
    return times, type_idx, ttinfos


def parse_tzif(data: bytes) -> TzRules:
    version, counts = _parse_header(data, 0)
    pos = 44
    if version == b"\x00":
        times, type_idx, ttinfos = _parse_block(data, pos, counts, 4)
        footer = ""
    else:
        pos += _block_size(counts, 4)  # skip v1 block
        version2, counts2 = _parse_header(data, pos)
        pos += 44
        times, type_idx, ttinfos = _parse_block(data, pos, counts2, 8)
        pos += _block_size(counts2, 8)
        footer = data[pos:].decode("ascii", errors="replace").strip("\n")

    if not ttinfos:
        raise ValueError("TZif file has no time types")

    # Offset in effect before the first transition: the first standard-time
    # (isdst == 0) type, falling back to type 0 (RFC 8536 §3.2 convention,
    # matching CPython zoneinfo and java.time's compiled rules).
    initial = next((off for off, isdst in ttinfos if not isdst), ttinfos[0][0])

    transitions = []
    prev_off = initial
    for t, ti in zip(times, type_idx):
        off_after = ttinfos[ti][0]
        if off_after != prev_off:
            transitions.append(TzTransition(t, prev_off, off_after))
        prev_off = off_after
    return TzRules(transitions, initial, footer)
