"""float64 <-> int64 bit-pattern conversion via uint32 limbs.

The framework carries FLOAT64 column data as IEEE-754 bits in int64
(columnar.column doc): TPU f64 is float32-pair emulated, so Spark-exact double
semantics are done over the exact bits.

CAVEAT: the f64 conversions here only lower on CPU-backend JAX (tests, host
staging).  On the TPU backend the x64 rewrite pass cannot bitcast emulated-f64
at all — ops must either stay in integer bit space on device or decode on host
with ``np.view`` (see ops.histogram for the pattern).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def f64_to_bits(x: jnp.ndarray) -> jnp.ndarray:
    """float64 -> int64 IEEE-754 bit pattern."""
    limbs = jax.lax.bitcast_convert_type(x, jnp.uint32)  # [..., 2] little-endian
    lo = limbs[..., 0].astype(jnp.uint64)
    hi = limbs[..., 1].astype(jnp.uint64)
    return ((hi << jnp.uint64(32)) | lo).astype(jnp.int64)


def bits_to_f64(bits: jnp.ndarray) -> jnp.ndarray:
    """int64 IEEE-754 bit pattern -> float64."""
    u = bits.astype(jnp.uint64)
    lo = (u & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    hi = (u >> jnp.uint64(32)).astype(jnp.uint32)
    return jax.lax.bitcast_convert_type(jnp.stack([lo, hi], axis=-1), jnp.float64)


def f32_to_bits(x: jnp.ndarray) -> jnp.ndarray:
    """float32 -> int32 IEEE-754 bit pattern."""
    return jax.lax.bitcast_convert_type(x, jnp.int32)


def bits_to_f32(bits: jnp.ndarray) -> jnp.ndarray:
    """int32 IEEE-754 bit pattern -> float32."""
    return jax.lax.bitcast_convert_type(bits.astype(jnp.int32), jnp.float32)
