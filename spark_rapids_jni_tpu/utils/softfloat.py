"""Exact IEEE-754 binary64 arithmetic in integer ops (device-safe).

TPU f64 is float32-pair emulated and NOT bit-exact binary64 (columnar.column
doc), so any op that must reproduce the reference's double math bit-for-bit
(string->float assembly, JSON number re-rendering) cannot use jnp.float64 on
device.  This module implements the three operations those paths need as
pure integer (uint64/int32) lane arithmetic — exact on every backend:

- :func:`u64_to_f64_bits` — u64 -> nearest binary64 (round-to-nearest-even);
- :func:`f64_mul_bits` — full IEEE multiply incl. subnormal output and
  overflow-to-inf, single rounding;
- :func:`f64_div_bits` — IEEE divide via 55-step vectorized long division.

All values travel as int64 *bit patterns* (the framework's FLOAT64 column
convention).  Inputs are expected finite; zero and inf inputs are handled
(propagated) but NaN payloads are not preserved beyond the default quiet
NaN.  Mirrors the arithmetic used by cast_string_to_float.cu:153-199.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

__all__ = [
    "u64_to_f64_bits",
    "f64_mul_bits",
    "f64_div_bits",
    "f64_from_parts",
    "f64_bits_to_f32_bits",
]

_U64 = jnp.uint64
_I64 = jnp.int64

_EXP_MASK = np.int64(0x7FF)
_MANT_MASK = np.uint64((1 << 52) - 1)
_IMPLICIT = np.uint64(1 << 52)
_INF_BITS = np.int64(0x7FF0000000000000)


def _u(x):
    return x.astype(_U64)


def _clz64(x):
    """Count leading zeros of uint64 (64 for x == 0), via binary search."""
    x = _u(x)
    n = jnp.zeros(x.shape, jnp.int32)
    cur = x
    for shift in (32, 16, 8, 4, 2, 1):
        big = cur >= (_U64(1) << _U64(shift))
        n = n + jnp.where(big, 0, shift)
        cur = jnp.where(big, cur >> _U64(shift), cur)
    # after loop cur is 0 or 1; if original was 0, n counted 63 -> fix to 64
    return jnp.where(x == 0, jnp.int32(64), n)


def _shr_sticky(m, k):
    """(m >> k, sticky: any shifted-out bit), k in [0, 63]."""
    k = k.astype(_U64)
    kept = m >> k
    lost = m ^ (kept << k)
    return kept, lost != 0


def _rne(mant_with_grs, sticky_extra):
    """Round a value carrying 2 extra bits (guard, round/sticky-merged).

    ``mant_with_grs``: mantissa << 2 | guard << 1 | roundbit; plus a bool
    sticky for anything below.  Returns rounded mantissa (may be 2^53).
    """
    mant = mant_with_grs >> _U64(2)
    guard = (mant_with_grs >> _U64(1)) & _U64(1)
    rbit = mant_with_grs & _U64(1)
    sticky = (rbit != 0) | sticky_extra
    round_up = (guard != 0) & (sticky | ((mant & _U64(1)) != 0))
    return mant + round_up.astype(_U64)


def f64_from_parts(sign, e_unb, mant53, guard, sticky):
    """Assemble bits from sign (0/1), unbiased exponent of the leading
    mantissa bit, a 53-bit mantissa with a guard bit and sticky, with RNE,
    subnormal flushing, and overflow to inf.

    ``mant53`` in [2^52, 2^53); ``e_unb`` is the exponent such that value =
    mant53 * 2^(e_unb - 52).
    """
    sign = sign.astype(_I64)
    e_b = e_unb.astype(jnp.int32) + 1023

    # subnormal: shift mantissa right so exponent becomes 1 - 1023
    sub_shift = jnp.clip(1 - e_b, 0, 63)
    total = _u(mant53) << _U64(2) | _u(guard) << _U64(1)
    shifted, lost = _shr_sticky(total, sub_shift)
    mant = _rne(shifted, sticky | lost)
    e_b = jnp.where(sub_shift > 0, 1, e_b)

    # rounding overflow: mantissa reached 2^53 -> bump exponent
    ovf = mant >= (_U64(1) << _U64(53))
    mant = jnp.where(ovf, mant >> _U64(1), mant)
    e_b = e_b + ovf.astype(jnp.int32)

    # subnormal result: exponent field 0 when mantissa has no implicit bit
    is_sub = mant < _IMPLICIT
    exp_field = jnp.where(is_sub, 0, e_b).astype(_I64)
    inf = e_b >= 2047
    bits = (sign << _I64(63)) | jnp.where(
        inf, _INF_BITS,
        (exp_field << _I64(52)) | (mant & _MANT_MASK).astype(_I64),
    )
    zero = mant == 0
    bits = jnp.where(zero, sign << _I64(63), bits)
    return bits


def u64_to_f64_bits(x) -> jnp.ndarray:
    """Nearest binary64 of a uint64 (RNE), as int64 bits.  Exact for
    x < 2^53; matches (double)x elsewhere."""
    x = _u(x)
    lz = _clz64(x)
    bitlen = 64 - lz
    # place the leading bit at position 52: value = mant * 2^(bitlen-53)
    left = jnp.clip(53 - bitlen, 0, 63)
    right = jnp.clip(bitlen - 53, 0, 63)
    mant_exact = x << left.astype(_U64)
    kept, lost = _shr_sticky(x, right)
    shifted_g, lost_g = _shr_sticky(x, jnp.maximum(right - 1, 0))
    guard = jnp.where(right > 0, shifted_g & _U64(1), _U64(0))
    below = lost_g & (right > 1)
    mant = jnp.where(right > 0, kept, mant_exact)
    bits = f64_from_parts(
        jnp.zeros(x.shape, _I64), bitlen - 1, mant, guard, below
    )
    return jnp.where(x == 0, _I64(0), bits)


def _decompose(bits):
    """(sign, unbiased exp of value's 2^e, 53-bit mantissa, is_zero, is_inf,
    is_nan); subnormals are normalized into the same (e, mant) form."""
    b = bits.astype(_I64)
    sign = (b >> _I64(63)) & _I64(1)
    e_field = ((b >> _I64(52)) & _EXP_MASK).astype(jnp.int32)
    frac = _u(b) & _MANT_MASK
    is_zero = (e_field == 0) & (frac == 0)
    is_inf = (e_field == 2047) & (frac == 0)
    is_nan = (e_field == 2047) & (frac != 0)
    # normal: implicit bit; subnormal: normalize left
    lz = _clz64(frac)  # for subnormals; frac < 2^52 so lz >= 12
    sub_shift = jnp.clip(lz - 11, 0, 63)
    mant = jnp.where(e_field == 0, frac << sub_shift.astype(_U64),
                     frac | _IMPLICIT)
    e_unb = jnp.where(
        e_field == 0, 1 - 1023 - (sub_shift - 0), e_field - 1023
    ).astype(jnp.int32)
    return sign, e_unb, mant, is_zero, is_inf, is_nan


def _mul_64x64(a, b):
    """(hi, lo) 128-bit product of two uint64 via 32-bit halves."""
    mask32 = _U64(0xFFFFFFFF)
    ah, al = a >> _U64(32), a & mask32
    bh, bl = b >> _U64(32), b & mask32
    ll = al * bl
    lh = al * bh
    hl = ah * bl
    hh = ah * bh
    mid = (ll >> _U64(32)) + (lh & mask32) + (hl & mask32)
    lo = (mid << _U64(32)) | (ll & mask32)
    hi = hh + (lh >> _U64(32)) + (hl >> _U64(32)) + (mid >> _U64(32))
    return hi, lo


def f64_mul_bits(a_bits, b_bits) -> jnp.ndarray:
    """IEEE binary64 multiply on bit patterns (RNE, subnormals, inf)."""
    sa, ea, ma, za, ia, na = _decompose(a_bits)
    sb, eb, mb, zb, ib, nb = _decompose(b_bits)
    s = sa ^ sb

    hi, lo = _mul_64x64(ma, mb)  # product in [2^104, 2^106)
    # leading bit at 105 or 104: normalize to 53-bit mantissa + guard+sticky
    top = (hi >> _U64(41)) != 0  # bit 105 set (hi holds bits 64..127)
    # mant53 = P >> (52 or 53); P = hi*2^64 + lo
    sh = jnp.where(top, 53, 52).astype(_U64)
    # P >> sh for sh in {52, 53}: combine hi/lo
    mant = (hi << (_U64(64) - sh)) | (lo >> sh)
    guard = (lo >> (sh - _U64(1))) & _U64(1)
    below_mask = (_U64(1) << (sh - _U64(1))) - _U64(1)
    sticky = (lo & below_mask) != 0
    e = ea + eb + top.astype(jnp.int32)

    bits = f64_from_parts(s, e, mant, guard, sticky)

    any_nan = na | nb | (za & ib) | (zb & ia)
    any_inf = (ia | ib) & ~any_nan
    any_zero = (za | zb) & ~any_nan & ~any_inf
    bits = jnp.where(any_zero, s << _I64(63), bits)
    bits = jnp.where(any_inf, (s << _I64(63)) | _INF_BITS, bits)
    bits = jnp.where(any_nan, _I64(0x7FF8000000000000), bits)
    return bits


def f64_div_bits(a_bits, b_bits) -> jnp.ndarray:
    """IEEE binary64 divide on bit patterns (RNE, subnormals, inf)."""
    sa, ea, ma, za, ia, na = _decompose(a_bits)
    sb, eb, mb, zb, ib, nb = _decompose(b_bits)
    s = sa ^ sb
    e = ea - eb

    # pre-align so quotient lands in [1, 2): if ma < mb, scale ma by 2
    small = ma < mb
    ma2 = jnp.where(small, ma << _U64(1), ma)
    e = e - small.astype(jnp.int32)

    # 54 quotient bits (1 integer + 52 frac + guard) by restoring division;
    # fori_loop keeps the compiled graph 54x smaller than unrolling
    import jax

    def _div_step(_, st):
        rem, q = st
        ge = rem >= mb
        q = (q << _U64(1)) | ge.astype(_U64)
        rem = jnp.where(ge, rem - mb, rem) << _U64(1)
        return rem, q

    rem, q = jax.lax.fori_loop(
        0, 54, _div_step, (ma2, jnp.zeros(ma.shape, _U64)))
    sticky = rem != 0
    mant = q >> _U64(1)  # 53 bits, leading bit set by construction
    guard = q & _U64(1)

    bits = f64_from_parts(s, e, mant, guard, sticky)

    any_nan = na | nb | (za & zb) | (ia & ib)
    div_zero = zb & ~any_nan
    res_zero = (za | ib) & ~any_nan & ~div_zero
    res_inf = (ia | div_zero) & ~any_nan
    bits = jnp.where(res_zero, s << _I64(63), bits)
    bits = jnp.where(res_inf, (s << _I64(63)) | _INF_BITS, bits)
    bits = jnp.where(any_nan, _I64(0x7FF8000000000000), bits)
    return bits


def f64_bits_to_f32_bits(bits) -> jnp.ndarray:
    """(float)d on bit patterns: binary64 -> binary32 with RNE, subnormal
    flushing, and overflow to inf (C cast semantics)."""
    sign, e_unb, mant, is_zero, is_inf, is_nan = _decompose(bits)
    s32 = sign.astype(jnp.int32)

    # f32: 24-bit mantissa, bias 127, exponent field in [1, 254] for normals.
    # 53 -> 24 bits is a right shift of 29 (+ subnormal shift); keep two of
    # those bits as guard+round for _rne and fold the rest into sticky.
    e_b = e_unb + 127
    sub_shift = jnp.clip(1 - e_b, 0, 34)
    kept, lost = _shr_sticky(mant, jnp.int32(27) + sub_shift)
    mant24 = _rne(kept, lost)
    e_b = jnp.where(sub_shift > 0, 1, e_b)

    ovf = mant24 >= (_U64(1) << _U64(24))
    mant24 = jnp.where(ovf, mant24 >> _U64(1), mant24)
    e_b = e_b + ovf.astype(jnp.int32)

    is_sub = mant24 < (_U64(1) << _U64(23))
    exp_field = jnp.where(is_sub, 0, e_b).astype(jnp.int32)
    inf = e_b >= 255
    out = (s32 << 31) | jnp.where(
        inf, jnp.int32(0x7F800000),
        (exp_field << 23) | (mant24 & _U64(0x7FFFFF)).astype(jnp.int32),
    )
    out = jnp.where(mant24 == 0, s32 << 31, out)
    out = jnp.where(is_zero, s32 << 31, out)
    out = jnp.where(is_inf, (s32 << 31) | jnp.int32(0x7F800000), out)
    out = jnp.where(is_nan, jnp.int32(0x7FC00000), out)
    return out
