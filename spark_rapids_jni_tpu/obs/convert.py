"""Offline profile converter CLI (spark_rapids_profile_converter analog).

Parses the SRTP capture format (obs/profiler.py) and emits either JSON lines
(one event per line) or a chrome://tracing / Perfetto-compatible trace —
the role NVTXT output plays for the reference
(spark_rapids_profile_converter.cpp:106-116).

With ``--device-trace DIR`` (the ``xplane_dir`` handed to Profiler.init),
the jax.profiler perfetto export found under ``DIR/plugins/profile/*/`` is
merged into the chrome output: host seam ranges and on-device kernel
events interleave on one timeline, the role the reference's per-kernel
device activity records play in its capture stream (profiler.fbs:124-287,
ProfilerJni.cpp:366).  Device events sit under shifted pids so tracks
stay distinguishable; alignment uses the wall/monotonic clock anchor the
profiler banks at start() when the device clock looks wall-based, else
falls back to aligning both streams at their first event.

Usage::

    python -m spark_rapids_jni_tpu.obs.convert capture.srtp --format json
    python -m spark_rapids_jni_tpu.obs.convert capture.srtp --format chrome \
        --device-trace /tmp/xplane -o trace.json
"""

from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
import struct
import sys
from typing import Iterator, List, Optional

from spark_rapids_jni_tpu.obs.flight import EVENT_KINDS
from spark_rapids_jni_tpu.obs.profiler import CLOCK_ANCHOR, MAGIC, VERSION

_CATEGORY_NAMES = ["op", "transfer", "collective", "alloc", "marker",
                   "spill", "compile", "serve"]

SUPPORTED_VERSIONS = (1, 2)

# per-version record sizes that differ: v1 COUNTER carried no tid
_COUNTER_FMT = {1: "<IQq", 2: "<IQqI"}


def parse_capture(data: bytes, *, midstream: bool = False,
                  version: Optional[int] = None,
                  strict: bool = False) -> Iterator[dict]:
    """Yield event dicts from a raw capture byte string.

    Reads format v1 and v2 (v2 adds STATE records and a tid on COUNTER).
    ``midstream=True`` starts at a *block boundary* with no file header —
    every block is self-contained (the string table restarts per block),
    so a consumer attaching to a live stream can begin at any size prefix;
    ``version`` then selects the record layout (default: current).

    A truncated final block (a writer killed mid-flush) ends iteration
    cleanly instead of raising, unless ``strict=True``.  Corruption
    *inside* a complete block (unknown record kind) still raises.
    """
    if midstream:
        pos = 0
        version = VERSION if version is None else version
    else:
        if data[:4] != MAGIC:
            raise ValueError("not an SRTP capture (bad magic)")
        version = struct.unpack_from("<I", data, 4)[0]
        pos = 8
    if version not in SUPPORTED_VERSIONS:
        raise ValueError(f"unsupported SRTP version {version}")
    cfmt = _COUNTER_FMT[version]
    clen = struct.calcsize(cfmt)
    while pos < len(data):
        if pos + 4 > len(data):
            if strict:
                raise ValueError("truncated capture: partial block length")
            return
        (blen,) = struct.unpack_from("<I", data, pos)
        pos += 4
        end = pos + blen
        if end > len(data):
            if strict:
                raise ValueError("truncated capture: partial final block")
            return
        names = {}
        while pos < end:
            kind = data[pos]
            pos += 1
            if kind == 0:  # STRING_DEF
                nid, ln = struct.unpack_from("<IH", data, pos)
                pos += 6
                names[nid] = data[pos : pos + ln].decode("utf-8")
                pos += ln
            elif kind == 1:  # RANGE
                nid, cat, t0, t1, tid = struct.unpack_from("<IBQQI", data, pos)
                pos += 25
                yield {"type": "range", "name": names.get(nid, f"#{nid}"),
                       "category": _CATEGORY_NAMES[cat], "start_ns": t0,
                       "end_ns": t1, "tid": tid}
            elif kind == 2:  # INSTANT
                nid, cat, t, tid = struct.unpack_from("<IBQI", data, pos)
                pos += 17
                yield {"type": "instant", "name": names.get(nid, f"#{nid}"),
                       "category": _CATEGORY_NAMES[cat], "t_ns": t, "tid": tid}
            elif kind == 3:  # COUNTER
                vals = struct.unpack_from(cfmt, data, pos)
                pos += clen
                nid, t, value = vals[0], vals[1], vals[2]
                yield {"type": "counter", "name": names.get(nid, f"#{nid}"),
                       "t_ns": t, "value": value,
                       "tid": vals[3] if version >= 2 else None}
            elif kind == 4 and version >= 2:  # STATE
                ek, task_id, t, tid, did, value = struct.unpack_from(
                    "<BqQIIq", data, pos)
                pos += 33
                yield {"type": "state",
                       "kind": (EVENT_KINDS[ek] if ek < len(EVENT_KINDS)
                                else f"#{ek}"),
                       "task_id": task_id, "t_ns": t, "tid": tid,
                       "detail": names.get(did, f"#{did}"), "value": value}
            else:
                raise ValueError(f"corrupt capture: record kind {kind}")
        pos = end


# pid for the reconstructed per-task governance tracks (host seam events
# are pid 0, merged device tracks sit at >= 1000)
_GOV_PID = 2000

# state kinds whose `value` carries a duration (ns) ending at t_ns: they
# render as complete ('X') slices so blocked windows are visible spans
_STATE_DUR_KINDS = {"woken": "blocked", "spill_end": "spill"}


def _state_to_chrome(e: dict, out: list, named_tracks: set) -> None:
    """One governance STATE event -> chrome events on a per-task track."""
    track = e["task_id"] if e["task_id"] >= 0 else e["tid"]
    if track not in named_tracks:
        named_tracks.add(track)
        if not named_tracks - {track}:  # first track names the process
            out.append({"ph": "M", "pid": _GOV_PID, "name": "process_name",
                        "args": {"name": "governance"}})
        label = (f"task {track}" if e["task_id"] >= 0
                 else f"thread {e['tid']} (untasked)")
        out.append({"ph": "M", "pid": _GOV_PID, "tid": track,
                    "name": "thread_name", "args": {"name": label}})
    span = _STATE_DUR_KINDS.get(e["kind"])
    if span is not None and e["value"] > 0:
        out.append({"name": span, "cat": "governance", "ph": "X",
                    "ts": (e["t_ns"] - e["value"]) / 1e3,
                    "dur": e["value"] / 1e3, "pid": _GOV_PID, "tid": track,
                    "args": {"detail": e["detail"]}})
    else:
        out.append({"name": e["kind"], "cat": "governance", "ph": "i",
                    "ts": e["t_ns"] / 1e3, "pid": _GOV_PID, "tid": track,
                    "s": "t", "args": {"detail": e["detail"]}})


def to_chrome(events) -> dict:
    """Chrome trace-event JSON (ts/dur in microseconds).

    Governance STATE events land on per-task tracks under a dedicated
    ``governance`` pid, on the same monotonic timeline as the op/serve
    ranges — blocked windows (and spills) render as spans, the other
    transitions as instants.
    """
    out = []
    named_tracks: set = set()
    for e in events:
        if e["type"] == "range":
            out.append({"name": e["name"], "cat": e["category"], "ph": "X",
                        "ts": e["start_ns"] / 1e3,
                        "dur": (e["end_ns"] - e["start_ns"]) / 1e3,
                        "pid": 0, "tid": e["tid"]})
        elif e["type"] == "instant":
            out.append({"name": e["name"], "cat": e["category"], "ph": "i",
                        "ts": e["t_ns"] / 1e3, "pid": 0, "tid": e["tid"],
                        "s": "t"})
        elif e["type"] == "state":
            _state_to_chrome(e, out, named_tracks)
        else:
            out.append({"name": e["name"], "ph": "C", "ts": e["t_ns"] / 1e3,
                        "pid": 0, "args": {"value": e["value"]}})
    return {"traceEvents": out}


# pid offset for merged device tracks: SRTP host events are pid 0
_DEVICE_PID_BASE = 1000


def load_device_trace(xplane_dir: str) -> List[dict]:
    """Raw trace events from the newest jax.profiler perfetto export under
    ``xplane_dir`` ([] when no run was captured there)."""
    cands = sorted(
        glob.glob(os.path.join(xplane_dir, "plugins", "profile", "*",
                               "perfetto_trace.json.gz"))
        + glob.glob(os.path.join(xplane_dir, "plugins", "profile", "*",
                                 "*.trace.json.gz")),
        key=os.path.getmtime)
    if not cands:
        return []
    with gzip.open(cands[-1], "rt") as f:
        doc = json.load(f)
    evs = doc.get("traceEvents", doc if isinstance(doc, list) else [])
    return [e for e in evs if isinstance(e, dict)]


def merge_device_events(chrome: dict, dev_events: List[dict],
                        wall_minus_mono_ns: Optional[int]) -> dict:
    """Interleave device trace events into a chrome trace built from SRTP.

    Complete ('X') device events are remapped to pids >= 1000; metadata
    ('M') events ride along so track names survive.  If the device clock
    reads as wall time and the capture carries the clock anchor, events
    are placed exactly on the host monotonic timeline; otherwise both
    streams are aligned at their first event.
    """
    host = chrome["traceEvents"]
    xs = [e for e in dev_events if e.get("ph") == "X" and "ts" in e]
    if not xs:
        return chrome
    dev_min_us = min(e["ts"] for e in xs)
    host_min_us = min((e["ts"] for e in host if "ts" in e), default=0.0)

    shift_us = host_min_us - dev_min_us  # fallback: align first events
    if wall_minus_mono_ns is not None:
        exact = -wall_minus_mono_ns / 1e3  # wall us -> monotonic us
        # trust the anchor only when it lands the device stream inside an
        # hour of the host stream (i.e. the device ts really is wall time)
        if abs((dev_min_us + exact) - host_min_us) < 3600e6:
            shift_us = exact

    for e in dev_events:
        ph = e.get("ph")
        if ph not in ("X", "M"):
            continue
        m = dict(e)
        m["pid"] = _DEVICE_PID_BASE + int(e.get("pid", 0))
        if ph == "X":
            m["ts"] = e["ts"] + shift_us
            m.setdefault("cat", "device")
        host.append(m)
    return chrome


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Convert an SRTP profiler capture to JSON or chrome trace")
    ap.add_argument("capture")
    ap.add_argument("--format", choices=["json", "chrome"], default="json")
    ap.add_argument("-o", "--output", default="-")
    ap.add_argument("--device-trace", default="",
                    help="xplane_dir of the run: merge the jax.profiler "
                         "perfetto export into the chrome trace")
    args = ap.parse_args(argv)

    with open(args.capture, "rb") as f:
        data = f.read()
    events = parse_capture(data)

    def emit(out) -> None:
        if args.format == "json":
            for e in events:
                out.write(json.dumps(e) + "\n")
            return
        evs = list(events)
        chrome = to_chrome(evs)
        if args.device_trace:
            anchor = next(
                (e["value"] for e in evs
                 if e["type"] == "counter" and e["name"] == CLOCK_ANCHOR),
                None)
            chrome = merge_device_events(
                chrome, load_device_trace(args.device_trace), anchor)
        json.dump(chrome, out)

    if args.output == "-":
        emit(sys.stdout)
    else:
        with open(args.output, "w") as out:
            emit(out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
