"""Offline profile converter CLI (spark_rapids_profile_converter analog).

Parses the SRTP capture format (obs/profiler.py) and emits either JSON lines
(one event per line) or a chrome://tracing / Perfetto-compatible trace —
the role NVTXT output plays for the reference
(spark_rapids_profile_converter.cpp:106-116).

Usage::

    python -m spark_rapids_jni_tpu.obs.convert capture.srtp --format json
    python -m spark_rapids_jni_tpu.obs.convert capture.srtp --format chrome -o trace.json
"""

from __future__ import annotations

import argparse
import json
import struct
import sys
from typing import Iterator

from spark_rapids_jni_tpu.obs.profiler import MAGIC, VERSION

_CATEGORY_NAMES = ["op", "transfer", "collective", "alloc", "marker", "spill"]


def parse_capture(data: bytes) -> Iterator[dict]:
    """Yield event dicts from a raw capture byte string."""
    if data[:4] != MAGIC:
        raise ValueError("not an SRTP capture (bad magic)")
    version = struct.unpack_from("<I", data, 4)[0]
    if version != VERSION:
        raise ValueError(f"unsupported SRTP version {version}")
    pos = 8
    while pos < len(data):
        (blen,) = struct.unpack_from("<I", data, pos)
        pos += 4
        end = pos + blen
        names = {}
        while pos < end:
            kind = data[pos]
            pos += 1
            if kind == 0:  # STRING_DEF
                nid, ln = struct.unpack_from("<IH", data, pos)
                pos += 6
                names[nid] = data[pos : pos + ln].decode("utf-8")
                pos += ln
            elif kind == 1:  # RANGE
                nid, cat, t0, t1, tid = struct.unpack_from("<IBQQI", data, pos)
                pos += 25
                yield {"type": "range", "name": names.get(nid, f"#{nid}"),
                       "category": _CATEGORY_NAMES[cat], "start_ns": t0,
                       "end_ns": t1, "tid": tid}
            elif kind == 2:  # INSTANT
                nid, cat, t, tid = struct.unpack_from("<IBQI", data, pos)
                pos += 17
                yield {"type": "instant", "name": names.get(nid, f"#{nid}"),
                       "category": _CATEGORY_NAMES[cat], "t_ns": t, "tid": tid}
            elif kind == 3:  # COUNTER
                nid, t, value = struct.unpack_from("<IQq", data, pos)
                pos += 20
                yield {"type": "counter", "name": names.get(nid, f"#{nid}"),
                       "t_ns": t, "value": value}
            else:
                raise ValueError(f"corrupt capture: record kind {kind}")
        pos = end


def to_chrome(events) -> dict:
    """Chrome trace-event JSON (ts/dur in microseconds)."""
    out = []
    for e in events:
        if e["type"] == "range":
            out.append({"name": e["name"], "cat": e["category"], "ph": "X",
                        "ts": e["start_ns"] / 1e3,
                        "dur": (e["end_ns"] - e["start_ns"]) / 1e3,
                        "pid": 0, "tid": e["tid"]})
        elif e["type"] == "instant":
            out.append({"name": e["name"], "cat": e["category"], "ph": "i",
                        "ts": e["t_ns"] / 1e3, "pid": 0, "tid": e["tid"],
                        "s": "t"})
        else:
            out.append({"name": e["name"], "ph": "C", "ts": e["t_ns"] / 1e3,
                        "pid": 0, "args": {"value": e["value"]}})
    return {"traceEvents": out}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Convert an SRTP profiler capture to JSON or chrome trace")
    ap.add_argument("capture")
    ap.add_argument("--format", choices=["json", "chrome"], default="json")
    ap.add_argument("-o", "--output", default="-")
    args = ap.parse_args(argv)

    with open(args.capture, "rb") as f:
        data = f.read()
    events = parse_capture(data)
    out = sys.stdout if args.output == "-" else open(args.output, "w")
    try:
        if args.format == "json":
            for e in events:
                out.write(json.dumps(e) + "\n")
        else:
            json.dump(to_chrome(events), out)
    finally:
        if out is not sys.stdout:
            out.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
