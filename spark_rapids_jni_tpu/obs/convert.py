"""Offline profile converter CLI (spark_rapids_profile_converter analog).

Parses the SRTP capture format (obs/profiler.py) and emits either JSON lines
(one event per line) or a chrome://tracing / Perfetto-compatible trace —
the role NVTXT output plays for the reference
(spark_rapids_profile_converter.cpp:106-116).

With ``--device-trace DIR`` (the ``xplane_dir`` handed to Profiler.init),
the jax.profiler perfetto export found under ``DIR/plugins/profile/*/`` is
merged into the chrome output: host seam ranges and on-device kernel
events interleave on one timeline, the role the reference's per-kernel
device activity records play in its capture stream (profiler.fbs:124-287,
ProfilerJni.cpp:366).  Device events sit under shifted pids so tracks
stay distinguishable; alignment uses the wall/monotonic clock anchor the
profiler banks at start() when the device clock looks wall-based, else
falls back to aligning both streams at their first event.

Usage::

    python -m spark_rapids_jni_tpu.obs.convert capture.srtp --format json
    python -m spark_rapids_jni_tpu.obs.convert capture.srtp --format chrome \
        --device-trace /tmp/xplane -o trace.json
"""

from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
import struct
import sys
from typing import Iterator, List, Optional

from spark_rapids_jni_tpu.obs.profiler import CLOCK_ANCHOR, MAGIC, VERSION

_CATEGORY_NAMES = ["op", "transfer", "collective", "alloc", "marker",
                   "spill", "compile", "serve"]


def parse_capture(data: bytes) -> Iterator[dict]:
    """Yield event dicts from a raw capture byte string."""
    if data[:4] != MAGIC:
        raise ValueError("not an SRTP capture (bad magic)")
    version = struct.unpack_from("<I", data, 4)[0]
    if version != VERSION:
        raise ValueError(f"unsupported SRTP version {version}")
    pos = 8
    while pos < len(data):
        (blen,) = struct.unpack_from("<I", data, pos)
        pos += 4
        end = pos + blen
        names = {}
        while pos < end:
            kind = data[pos]
            pos += 1
            if kind == 0:  # STRING_DEF
                nid, ln = struct.unpack_from("<IH", data, pos)
                pos += 6
                names[nid] = data[pos : pos + ln].decode("utf-8")
                pos += ln
            elif kind == 1:  # RANGE
                nid, cat, t0, t1, tid = struct.unpack_from("<IBQQI", data, pos)
                pos += 25
                yield {"type": "range", "name": names.get(nid, f"#{nid}"),
                       "category": _CATEGORY_NAMES[cat], "start_ns": t0,
                       "end_ns": t1, "tid": tid}
            elif kind == 2:  # INSTANT
                nid, cat, t, tid = struct.unpack_from("<IBQI", data, pos)
                pos += 17
                yield {"type": "instant", "name": names.get(nid, f"#{nid}"),
                       "category": _CATEGORY_NAMES[cat], "t_ns": t, "tid": tid}
            elif kind == 3:  # COUNTER
                nid, t, value = struct.unpack_from("<IQq", data, pos)
                pos += 20
                yield {"type": "counter", "name": names.get(nid, f"#{nid}"),
                       "t_ns": t, "value": value}
            else:
                raise ValueError(f"corrupt capture: record kind {kind}")
        pos = end


def to_chrome(events) -> dict:
    """Chrome trace-event JSON (ts/dur in microseconds)."""
    out = []
    for e in events:
        if e["type"] == "range":
            out.append({"name": e["name"], "cat": e["category"], "ph": "X",
                        "ts": e["start_ns"] / 1e3,
                        "dur": (e["end_ns"] - e["start_ns"]) / 1e3,
                        "pid": 0, "tid": e["tid"]})
        elif e["type"] == "instant":
            out.append({"name": e["name"], "cat": e["category"], "ph": "i",
                        "ts": e["t_ns"] / 1e3, "pid": 0, "tid": e["tid"],
                        "s": "t"})
        else:
            out.append({"name": e["name"], "ph": "C", "ts": e["t_ns"] / 1e3,
                        "pid": 0, "args": {"value": e["value"]}})
    return {"traceEvents": out}


# pid offset for merged device tracks: SRTP host events are pid 0
_DEVICE_PID_BASE = 1000


def load_device_trace(xplane_dir: str) -> List[dict]:
    """Raw trace events from the newest jax.profiler perfetto export under
    ``xplane_dir`` ([] when no run was captured there)."""
    cands = sorted(
        glob.glob(os.path.join(xplane_dir, "plugins", "profile", "*",
                               "perfetto_trace.json.gz"))
        + glob.glob(os.path.join(xplane_dir, "plugins", "profile", "*",
                                 "*.trace.json.gz")),
        key=os.path.getmtime)
    if not cands:
        return []
    with gzip.open(cands[-1], "rt") as f:
        doc = json.load(f)
    evs = doc.get("traceEvents", doc if isinstance(doc, list) else [])
    return [e for e in evs if isinstance(e, dict)]


def merge_device_events(chrome: dict, dev_events: List[dict],
                        wall_minus_mono_ns: Optional[int]) -> dict:
    """Interleave device trace events into a chrome trace built from SRTP.

    Complete ('X') device events are remapped to pids >= 1000; metadata
    ('M') events ride along so track names survive.  If the device clock
    reads as wall time and the capture carries the clock anchor, events
    are placed exactly on the host monotonic timeline; otherwise both
    streams are aligned at their first event.
    """
    host = chrome["traceEvents"]
    xs = [e for e in dev_events if e.get("ph") == "X" and "ts" in e]
    if not xs:
        return chrome
    dev_min_us = min(e["ts"] for e in xs)
    host_min_us = min((e["ts"] for e in host if "ts" in e), default=0.0)

    shift_us = host_min_us - dev_min_us  # fallback: align first events
    if wall_minus_mono_ns is not None:
        exact = -wall_minus_mono_ns / 1e3  # wall us -> monotonic us
        # trust the anchor only when it lands the device stream inside an
        # hour of the host stream (i.e. the device ts really is wall time)
        if abs((dev_min_us + exact) - host_min_us) < 3600e6:
            shift_us = exact

    for e in dev_events:
        ph = e.get("ph")
        if ph not in ("X", "M"):
            continue
        m = dict(e)
        m["pid"] = _DEVICE_PID_BASE + int(e.get("pid", 0))
        if ph == "X":
            m["ts"] = e["ts"] + shift_us
            m.setdefault("cat", "device")
        host.append(m)
    return chrome


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Convert an SRTP profiler capture to JSON or chrome trace")
    ap.add_argument("capture")
    ap.add_argument("--format", choices=["json", "chrome"], default="json")
    ap.add_argument("-o", "--output", default="-")
    ap.add_argument("--device-trace", default="",
                    help="xplane_dir of the run: merge the jax.profiler "
                         "perfetto export into the chrome trace")
    args = ap.parse_args(argv)

    with open(args.capture, "rb") as f:
        data = f.read()
    events = parse_capture(data)
    out = sys.stdout if args.output == "-" else open(args.output, "w")
    try:
        if args.format == "json":
            for e in events:
                out.write(json.dumps(e) + "\n")
        else:
            evs = list(events)
            chrome = to_chrome(evs)
            if args.device_trace:
                anchor = next(
                    (e["value"] for e in evs
                     if e["type"] == "counter" and e["name"] == CLOCK_ANCHOR),
                    None)
                chrome = merge_device_events(
                    chrome, load_device_trace(args.device_trace), anchor)
            json.dump(chrome, out)
    finally:
        if out is not sys.stdout:
            out.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
