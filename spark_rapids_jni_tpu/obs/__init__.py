"""Observability & chaos: profiler, trace converter, fault injection.

The reference's CUPTI profiler + libcufaultinj analog (SURVEY.md §2.4, §5),
re-seated on the framework dispatch seam instead of the CUDA API boundary.
"""

from spark_rapids_jni_tpu.obs.faultinj import FaultInjector, install_from_env
from spark_rapids_jni_tpu.obs.profiler import Profiler
from spark_rapids_jni_tpu.obs.seam import (
    ALLOC,
    COLLECTIVE,
    OP,
    SERVE,
    TRANSFER,
    instrument,
)

# NB: the `seam` context manager stays at spark_rapids_jni_tpu.obs.seam.seam —
# re-exporting it here would shadow the submodule attribute of the package.

__all__ = [
    "ALLOC",
    "COLLECTIVE",
    "FaultInjector",
    "OP",
    "Profiler",
    "SERVE",
    "TRANSFER",
    "install_from_env",
    "instrument",
]
