"""Distributed request spans: where did request X spend its 80 ms?

The flight recorder (obs/flight.py) answers "what state transitions
happened" per governor task; it cannot answer the operator's first
question during an incident — *which phase of which request is slow,
right now, across which processes*.  This module adds the missing
dimension: a trace context ``(rid, span, parent)`` stamped on every
serving :class:`~spark_rapids_jni_tpu.serve.queue.Request` and carried
across the supervisor pipe, so one request's

    queue-wait -> dispatch -> (transport) -> compute -> scatter

breakdown reconstructs LIVE from the telemetry plane (serve/telemetry.py)
— not just post-hoc from anomaly dumps.

Design constraints, in order:

- **the hot path is two deque appends per span** — open and close are
  plain flight events (``EV_SPAN_OPEN``/``EV_SPAN_CLOSE``) whose detail
  string carries the context tokens (``rid:<r>:span:<s>:parent:<p>:
  kind:<k>``), so spans ride the existing ring, the existing telemetry
  export, the existing dump merge, and the existing wire-id freeze with
  zero new transport;
- **ids are cluster-unique without coordination** — a span id packs the
  owning pid into its high bits, so two executors can open spans for one
  rid concurrently and the merge never collides;
- **emission lives HERE only** — every layer opens/closes spans through
  these helpers, which keeps the analyze gate's EVENT_PAIRS balance
  check trivially true (one module emits both sides) and gives the
  reconstruction one grammar to parse.

``rid`` is the request's front-door task id (the supervisor lease id in
cluster serving — the same token lease events already carry), so span
chains and lease chains key the merge identically.
"""

from __future__ import annotations

import contextlib
import itertools
import os
import re
import threading
import time
from typing import Dict, List, Optional

from spark_rapids_jni_tpu.obs import flight as _flight

__all__ = [
    "SPAN_QUEUE", "SPAN_DISPATCH", "SPAN_TRANSPORT", "SPAN_COMPUTE",
    "SPAN_SCATTER", "SPAN_CACHE", "SPAN_KINDS",
    "TraceContext", "new_root", "child_of", "to_wire", "from_wire",
    "open_span", "close_span", "span", "maybe_span",
    "push_current", "pop_current", "current",
    "waterfall", "chain_complete", "format_waterfall",
]

# the span-kind vocabulary (the phases a request waterfall is made of)
SPAN_QUEUE = "queue"          # admission-queue wait (submit -> pop/grant)
SPAN_DISPATCH = "dispatch"    # supervisor lease outstanding on one worker
SPAN_TRANSPORT = "transport"  # shuffle partition fetch (consumer side)
SPAN_COMPUTE = "compute"      # governed handler execution on a worker
SPAN_SCATTER = "scatter"      # batch/ragged result redistribution
SPAN_CACHE = "cache_hit"      # result served from the result cache
#                               (plans/rcache.py round 15): the request
#                               skipped dispatch/compute entirely, so a
#                               hit's waterfall is queue -> cache_hit
SPAN_KINDS = (SPAN_QUEUE, SPAN_DISPATCH, SPAN_TRANSPORT, SPAN_COMPUTE,
              SPAN_SCATTER, SPAN_CACHE)

# span ids are (pid | counter) packed so concurrently-opened spans across
# executor processes never collide in a merged timeline; 20 pid bits
# (Linux pid_max default is < 2^22; collisions would only smear two spans
# into one, never crash) + 28 counter bits per process
_ids = itertools.count(1)


def _new_span_id() -> int:
    return ((os.getpid() & 0xFFFFF) << 28) | (next(_ids) & 0xFFFFFFF)


class TraceContext:
    """One node of a request's span tree: (trace id, span id, parent)."""

    __slots__ = ("rid", "span", "parent")

    def __init__(self, rid: int, span: int, parent: int = 0):
        self.rid = int(rid)
        self.span = int(span)
        self.parent = int(parent)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"TraceContext(rid={self.rid}, span={self.span:x}, "
                f"parent={self.parent:x})")


def new_root(rid: int) -> TraceContext:
    """The root context of one request (rid = front-door task id)."""
    return TraceContext(rid, _new_span_id(), 0)


def child_of(ctx: TraceContext) -> TraceContext:
    """A fresh child context under ``ctx`` (same rid lineage)."""
    return TraceContext(ctx.rid, _new_span_id(), ctx.span)


def to_wire(ctx: Optional[TraceContext]) -> Optional[tuple]:
    """The picklable form carried in MSG_DISPATCH's ``trace`` field."""
    return None if ctx is None else (ctx.rid, ctx.span, ctx.parent)


def from_wire(t) -> Optional[TraceContext]:
    """Parse a wire trace tuple; malformed input degrades to None (an
    untraced request still serves — tracing must never fail dispatch)."""
    try:
        if t is None:
            return None
        rid, span, parent = t
        return TraceContext(int(rid), int(span), int(parent))
    except (TypeError, ValueError):
        return None


# --------------------------------------------------------------------------
# emission (the only module that records EV_SPAN_OPEN / EV_SPAN_CLOSE)
# --------------------------------------------------------------------------


class SpanHandle:
    """An open span: close exactly once (idempotent — races between a
    normal close and a cleanup close are benign)."""

    __slots__ = ("ctx", "kind", "task_id", "extra", "t0_ns", "_closed")

    def __init__(self, ctx: TraceContext, kind: str, task_id: int,
                 extra: str, t0_ns: int):
        self.ctx = ctx
        self.kind = kind
        self.task_id = task_id
        self.extra = extra
        self.t0_ns = t0_ns
        self._closed = False


def _detail(ctx: TraceContext, kind: str, extra: str) -> str:
    d = (f"rid:{ctx.rid}:span:{ctx.span}:parent:{ctx.parent}"
         f":kind:{kind}")
    return f"{d}:{extra}" if extra else d


def open_span(parent: Optional[TraceContext], kind: str, *,
              task_id: int = -1, extra: str = "") -> Optional[SpanHandle]:
    """Open a child span under ``parent`` (None parent = no-op: untraced
    requests cost nothing).  Returns the handle to pass to
    :func:`close_span`."""
    if parent is None:
        return None
    ctx = child_of(parent)
    h = SpanHandle(ctx, kind, task_id, extra, time.monotonic_ns())
    _flight.record(_flight.EV_SPAN_OPEN, task_id,
                   detail=_detail(ctx, kind, extra))
    return h


def close_span(handle: Optional[SpanHandle]) -> None:
    """Close an open span (records the duration); None and double closes
    are no-ops so every cleanup path may call this unconditionally."""
    if handle is None or handle._closed:
        return
    handle._closed = True
    _flight.record(_flight.EV_SPAN_CLOSE, handle.task_id,
                   detail=_detail(handle.ctx, handle.kind, handle.extra),
                   value=time.monotonic_ns() - handle.t0_ns)


@contextlib.contextmanager
def span(parent: Optional[TraceContext], kind: str, *, task_id: int = -1,
         extra: str = ""):
    """Open/close a child span around a block; the child context becomes
    the thread's CURRENT context inside, so nested layers (shuffle
    fetches under a compute span) attach without plumbing."""
    h = open_span(parent, kind, task_id=task_id, extra=extra)
    if h is None:
        yield None
        return
    # close_span owns the whole window from here: push/pop stay paired
    # inside it (push_current is a bare thread-local append — it either
    # appends or leaves the stack untouched), and no fault between open
    # and the inner try can leave the span dangling
    try:
        push_current(h.ctx)
        try:
            yield h.ctx
        finally:
            pop_current()
    finally:
        close_span(h)


@contextlib.contextmanager
def maybe_span(kind: str, *, extra: str = ""):
    """A child span under the thread's current context, or a no-op when
    none is set — how deep layers (serve/shuffle.py fetches) narrate
    without threading a context through every signature."""
    cur = current()
    if cur is None:
        yield None
        return
    with span(cur, kind, extra=extra) as ctx:
        yield ctx


# thread-local current-context stack (handler threads set it around the
# governed run; worker threads are pool-owned so the stack never leaks
# across requests as long as push/pop pair — span() guarantees it)
_tls = threading.local()


def push_current(ctx: TraceContext) -> None:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(ctx)


def pop_current() -> None:
    stack = getattr(_tls, "stack", None)
    if stack:
        stack.pop()


def current() -> Optional[TraceContext]:
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


# --------------------------------------------------------------------------
# reconstruction (flightdump --live / --waterfall, servetop, bench gates)
# --------------------------------------------------------------------------

_TOKENS = re.compile(
    r"(?:^|:)rid:(\d+):span:(\d+):parent:(\d+):kind:([a-z_]+)")


def waterfall(events: List[dict]) -> Dict[str, dict]:
    """Reconstruct per-request span trees from flight-event dicts.

    Accepts raw ring snapshots, anomaly-dump events, AND cluster-merged
    events (which add ``pid``/``wall_s``); open/close match on span id.
    Returns ``{rid: {"spans": [span...], "pids": [...],
    "complete": bool}}`` with each span carrying ``kind``, ``span``,
    ``parent``, ``t0`` (wall_s when available, else t_ns seconds),
    ``dur_ms`` (None while open), ``closed`` and ``pid``.
    """
    spans: Dict[int, dict] = {}
    order = 0
    for e in events:
        k = e.get("kind")
        if k not in ("span_open", "span_close"):
            continue
        m = _TOKENS.search(str(e.get("detail", "")))
        if not m:
            continue
        rid, span_id, parent, skind = (m.group(1), int(m.group(2)),
                                       int(m.group(3)), m.group(4))
        s = spans.get(span_id)
        if s is None:
            order += 1
            s = spans[span_id] = {
                "rid": rid, "span": span_id, "parent": parent,
                "kind": skind, "t0": None, "dur_ms": None,
                "closed": False, "pid": e.get("pid"), "order": order,
            }
        if k == "span_open":
            s["t0"] = (float(e["wall_s"]) if "wall_s" in e
                       else float(e.get("t_ns", 0)) / 1e9)
            if e.get("pid") is not None:
                s["pid"] = e.get("pid")
        else:
            s["closed"] = True
            s["dur_ms"] = round(int(e.get("value", 0)) / 1e6, 3)
            if s["t0"] is None and "wall_s" in e:
                # close seen without its open (ring rolled over): back
                # out the start from the duration so the bar still lands
                s["t0"] = float(e["wall_s"]) - int(e.get("value", 0)) / 1e9
    out: Dict[str, dict] = {}
    for s in spans.values():
        rec = out.setdefault(s["rid"], {"spans": [], "pids": set(),
                                        "complete": False})
        rec["spans"].append(s)
        if s.get("pid") is not None:
            rec["pids"].add(s["pid"])
    for rec in out.values():
        rec["spans"].sort(key=lambda s: (s["t0"] if s["t0"] is not None
                                         else float("inf"), s["order"]))
        rec["pids"] = sorted(rec["pids"])
        rec["complete"] = chain_complete(rec)
    return out


def chain_complete(rec: dict, *, require_dispatch: bool = False) -> bool:
    """True when the request's phase chain completed: the LAST span of
    each required kind (queue, compute, and — where one was ever opened
    — dispatch) is closed.  Judged on the last span per kind, not all
    spans: an attempt orphaned mid-compute by a SIGKILLed executor
    leaves its span open forever, but the re-dispatched attempt's closed
    chain IS the request's complete story — redispatch churn shows as
    extra bars, never as "incomplete"."""
    last: Dict[str, dict] = {}
    for s in rec["spans"]:  # spans are sorted by (t0, emission order)
        last[s["kind"]] = s
    if SPAN_CACHE in last and SPAN_COMPUTE not in last:
        # a result-cache hit never dispatched or computed: its complete
        # story is queue -> cache_hit (the round-15 short-circuit shape)
        need = {SPAN_QUEUE, SPAN_CACHE}
    else:
        need = {SPAN_QUEUE, SPAN_COMPUTE}
        if require_dispatch or SPAN_DISPATCH in last:
            need.add(SPAN_DISPATCH)
    return all(k in last and last[k]["closed"] for k in need)


def format_waterfall(rec: dict, *, width: int = 48) -> List[str]:
    """Render one rid's spans as indented bars on a shared time base."""
    spans = [s for s in rec["spans"] if s["t0"] is not None]
    if not spans:
        return ["  (no timed spans)"]
    t0 = min(s["t0"] for s in spans)
    span_end = max((s["t0"] + (s["dur_ms"] or 0.0) / 1e3) for s in spans)
    total = max(span_end - t0, 1e-9)
    depth = {s["span"]: s for s in spans}
    lines = []
    for s in spans:
        d, p = 0, s["parent"]
        while p in depth and d < 8:
            d += 1
            p = depth[p]["parent"]
        off = int(width * (s["t0"] - t0) / total)
        dur_s = (s["dur_ms"] or 0.0) / 1e3
        bar = max(1, int(width * dur_s / total)) if s["closed"] else 1
        mark = "=" * bar if s["closed"] else ">"
        dur = (f"{s['dur_ms']:9.3f} ms" if s["closed"] else "   OPEN     ")
        pid = f" pid {s['pid']}" if s.get("pid") is not None else ""
        lines.append(f"  {'  ' * d}{s['kind']:<10}{dur} "
                     f"|{' ' * off}{mark:<{max(1, width - off)}}|{pid}")
    return lines
