"""Per-op pipeline phase timing (the get_json_object tokenize/evaluate/
render split, round 3, generalized).

A hot kernel that regresses as one opaque number is hard to attribute;
the bench snapshots therefore carry a ``phases_s`` dict per stage so a
regression points at a pipeline phase (bucket / parse / emit, index-build
/ gather), not just the total.  Ops instantiate one module-level
:class:`PhaseTimes` and wrap their phases; bench.py resets, runs one
instrumented call, and snapshots.

Timings are host wall clock around the dispatch: on the host-twin arms
they are the real phase cost; on device arms they measure enqueue +
any host sync the phase performs (documented in docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict

__all__ = ["PhaseTimes"]


class PhaseTimes:
    """Accumulating named phase timers (thread-safe, reset per measurement)."""

    def __init__(self, *keys: str):
        self._lock = threading.Lock()
        self._times: Dict[str, float] = {k: 0.0 for k in keys}  # guarded-by: _lock

    def reset(self) -> None:
        with self._lock:
            for k in self._times:
                self._times[k] = 0.0

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._times)

    @contextlib.contextmanager
    def phase(self, key: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self._times[key] = self._times.get(key, 0.0) + dt
