"""The framework dispatch seam: one instrumentation point for every op call.

The reference hooks its observability and chaos tooling into the CUDA API
boundary from outside the op code (CUPTI subscriber for the profiler,
ProfilerJni.cpp:437; CUDA_INJECTION64_PATH driver hook for fault injection,
faultinj/faultinj.cu).  The equivalent boundary here is the public op
dispatch: every call to an instrumented op/transfer/collective passes through
:func:`seam`, which consults the fault injector (may raise) and the profiler
(records a range).  When neither is active the overhead is two module-flag
checks.

Categories mirror the activity kinds the reference captures: ``op`` (kernel
launches), ``transfer`` (host<->device movement), ``collective`` (multi-chip
exchange), ``alloc`` (memory governance), ``spill`` (host-staging traffic,
mem/spill.py — the reference profiles its spill store's device<->host copies
the same way, as MEMCPY activity), ``compile`` (step build / XLA
compilation — the reference's CUPTI hook sees module loads the same way,
and its CUDA-API injector can fail them, faultinj.cu:32).

The ``transfer``/``collective``/``compile`` crossings sit BENEATH the op
layer, in the runtime paths of the distributed models (batch upload, step
launch, step build), so chaos can simulate a failing device transfer, a
wedged collective, or a failed compile mid-governed-query — the failure
modes the CUPTI-level injector reaches in the reference.

The ``serve`` crossing sits ABOVE the op layer, around each admitted
request's handler execution in the serving engine (serve/executor.py) —
inside the retry bracket, so an injected RetryOOM/SplitAndRetryOOM at this
seam drives the same protocol a mid-query device fault does, and the
profiler sees one range per served request.
"""

from __future__ import annotations

import contextlib
import functools
import threading
from typing import Callable, Optional

__all__ = ["seam", "instrument", "OP", "TRANSFER", "COLLECTIVE", "ALLOC",
           "SPILL", "COMPILE", "SERVE", "SHUFFLE"]

OP = "op"
TRANSFER = "transfer"
COLLECTIVE = "collective"
ALLOC = "alloc"
SPILL = "spill"
COMPILE = "compile"
SERVE = "serve"
# the cross-process columnar data plane (serve/shuffle.py): every framed
# partition send crosses this category, so chaos can corrupt, truncate, or
# stall the transport the way libcufaultinj corrupts a UCX hand-off
SHUFFLE = "shuffle"

# registered sinks; None = inactive (checked without locks on the hot path)
_injector: Optional[Callable[[str, str], None]] = None  # may raise
_profiler_range: Optional[Callable[[str, str], "contextlib.AbstractContextManager"]] = None
# category -> threading.Lock held across the crossing; None = inactive.
# The serving engine installs {COLLECTIVE: lock}: the single-process CPU
# collective runtime wedges when two threads launch rendezvous programs
# concurrently, so multi-threaded serving serializes collective launches
# HERE — beneath every model runner's budget reservation, which keeps the
# lock order (budget, then launch) acyclic by construction.
_serializers: Optional[dict] = None
_install_lock = threading.Lock()


def _set_injector(fn: Optional[Callable[[str, str], None]]) -> None:
    global _injector
    _injector = fn


def _set_profiler(fn) -> None:
    global _profiler_range
    _profiler_range = fn


def serialize_category(category: str) -> None:
    """Install (idempotently) a crossing lock for ``category``.

    Reentrant: a launch crossing (``seam(COLLECTIVE, "launch:...")``)
    re-enters on the same thread when the step traces through an
    ``@instrument(COLLECTIVE, ...)``-wrapped collective at compile time.
    The read-modify-write is guarded: two engines constructed
    concurrently must end up sharing ONE lock per category, or the
    serialization this exists for is void.
    """
    global _serializers
    with _install_lock:
        cur = dict(_serializers or {})
        if category not in cur:
            cur[category] = threading.RLock()
        _serializers = cur


@contextlib.contextmanager
def seam(category: str, name: str):
    """Cross the instrumented dispatch boundary."""
    inj = _injector
    if inj is not None:
        inj(category, name)  # may raise an injected fault
    sers = _serializers
    lock = sers.get(category) if sers is not None else None
    prof = _profiler_range
    if lock is None:
        if prof is None:
            yield
            return
        with prof(category, name):
            yield
        return
    with lock:
        if prof is None:
            yield
            return
        with prof(category, name):
            yield


def instrument(category: str, name: str):
    """Decorator form: wrap a callable in the dispatch seam."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            if (_injector is None and _profiler_range is None
                    and _serializers is None):
                return fn(*args, **kwargs)
            with seam(category, name):
                return fn(*args, **kwargs)

        wrapped.__srt_seam__ = (category, name)
        return wrapped

    return deco
