"""Config-driven fault injection at the framework dispatch seam.

Parity target: ``libcufaultinj`` (faultinj/faultinj.cu) — the CUPTI-hooked
chaos tool that injects faults into CUDA calls per a JSON config with
match-by-name / ``*`` wildcards, probabilities, interception counts, and
inotify hot reload (faultinj.cu:387 config parse, :139-144 trap/assert
injection, README.md).  The TPU analog hooks the dispatch seam
(obs/seam.py) that every instrumented op, transfer, and collective crosses.

Config shape::

    {
      "dynamic": true,            # hot-reload on file change (mtime poll)
      "seed": 42,                 # optional deterministic RNG
      "op": {
        "murmur_hash32": {"percent": 50, "injectionType": "exception",
                           "interceptionCount": 2},
        "*":             {"percent": 1,  "injectionType": "retry_oom"}
      },
      "transfer": { ... }, "collective": { ... }, "alloc": { ... }
    }

``injectionType``:

- ``exception``    -> InjectedException (the PTX ``trap;`` analog: the call
  fails immediately with a framework error)
- ``retry_oom``    -> GpuRetryOOM (drives the arbiter's retry protocol)
- ``split_oom``    -> GpuSplitAndRetryOOM
- ``device_error`` -> GpuOOM (the sticky ``assert(0)`` analog: a
  non-retryable device failure)
- ``host_oom``     -> OffHeapOOM (a hard host/off-heap allocation failure)

Behavioral kinds (round 10, crash-only serving): instead of raising, the
crossing misbehaves the way a sick executor process does —

- ``slow``      -> the crossing stalls ``durationMs`` (default 50) before
  proceeding: a degraded-but-correct executor;
- ``hang``      -> the crossing stalls ``durationMs`` (default one hour):
  a wedged handler thread that will never return on its own — only the
  supervisor's hung-lease recycling (serve/supervisor.py) or the engine's
  hung-task watchdog notices;
- ``proc_kill`` -> ``SIGKILL`` to the CURRENT process: the crash-only
  failure domain drill.  No cleanup runs, no exception propagates — the
  supervisor must detect the dead executor and re-dispatch its leases.

``interceptionCount`` limits how many times the rule fires (faultinj.cu
``injectionCount`` countdown); ``percent`` gates each crossing.

Auto-activation: if ``SRT_FAULT_INJECTOR_CONFIG_PATH`` is set when
``install_from_env()`` runs (the ops package calls it on import), the
injector arms itself — mirroring the driver-level ``CUDA_INJECTION64_PATH``
/ ``FAULT_INJECTOR_CONFIG_PATH`` environment contract.
"""

from __future__ import annotations

import fnmatch
import json
import os
import random
import signal
import threading
import time
from typing import Optional

from spark_rapids_jni_tpu.mem.exceptions import (
    GpuOOM,
    GpuRetryOOM,
    GpuSplitAndRetryOOM,
    InjectedException,
    OffHeapOOM,
)
from spark_rapids_jni_tpu.obs import seam as _seam

__all__ = ["FaultInjector", "install_from_env", "pressure_storm_config",
           "chaos_kill_config", "chaos_shuffle_config", "transport_fault",
           "ENV_CONFIG_PATH"]

ENV_CONFIG_PATH = "SRT_FAULT_INJECTOR_CONFIG_PATH"

_FAULTS = {
    "exception": lambda name: InjectedException(f"injected fault in {name}"),
    "retry_oom": lambda name: GpuRetryOOM(f"injected retry OOM in {name}"),
    "split_oom": lambda name: GpuSplitAndRetryOOM(
        f"injected split-and-retry OOM in {name}"),
    "device_error": lambda name: GpuOOM(f"injected device error in {name}"),
    "host_oom": lambda name: OffHeapOOM(f"injected host OOM in {name}"),
}

# behavioral kinds misbehave instead of raising (executed OUTSIDE the
# injector lock: a hang must wedge the crossing thread, not the injector)
_BEHAVIOR_KINDS = frozenset({"slow", "hang", "proc_kill"})
_BEHAVIOR_DEFAULT_MS = {"slow": 50.0, "hang": 3_600_000.0}

# transport kinds (round 13, the columnar data plane): the shuffle sender
# consults :func:`transport_fault` per framed partition send and APPLIES
# the verdict itself — a corrupted or truncated frame must actually cross
# the wire (the receiver's CRC / length check is what's under test), so
# the injector returns a verdict instead of raising.  ``peer_stall``
# behaves like ``slow`` but lives in the shuffle category so one profile
# can storm all three without rule-name shadowing.
_TRANSPORT_KINDS = frozenset({"frame_corrupt", "frame_truncate",
                              "peer_stall"})
_BEHAVIOR_DEFAULT_MS.update({"peer_stall": 500.0})


class _Rule:
    def __init__(self, spec: dict):
        self.percent = float(spec.get("percent", 100))
        self.kind = spec.get("injectionType", "exception")
        if (self.kind not in _FAULTS and self.kind not in _BEHAVIOR_KINDS
                and self.kind not in _TRANSPORT_KINDS):
            raise ValueError(f"unknown injectionType {self.kind!r}")
        self.duration_s = float(
            spec.get("durationMs", _BEHAVIOR_DEFAULT_MS.get(self.kind, 0.0))
        ) / 1e3
        # None = unlimited, mirroring a missing injectionCount in faultinj
        c = spec.get("interceptionCount")
        self.remaining = None if c is None else int(c)

    def fire(self, rng: random.Random, name: str):
        """Roll the dice; returns ``(kind, payload)`` — payload is the
        exception to raise for fault kinds, the stall duration for
        slow/hang, None for proc_kill — or None when the rule holds."""
        if self.remaining is not None and self.remaining <= 0:
            return None
        if self.percent < 100 and rng.uniform(0, 100) >= self.percent:
            return None
        if self.remaining is not None:
            self.remaining -= 1
        if self.kind in _BEHAVIOR_KINDS or self.kind in _TRANSPORT_KINDS:
            return (self.kind, self.duration_s)
        return ("raise", _FAULTS[self.kind](name))


class FaultInjector:
    """Singleton chaos hook over the dispatch seam."""

    _instance: Optional["FaultInjector"] = None

    def __init__(self, config, config_path: Optional[str] = None):
        self._lock = threading.Lock()
        self._path = config_path
        self._mtime = 0.0
        self._watcher: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._load(config)

    # -- lifecycle ---------------------------------------------------------
    @classmethod
    def install(cls, config_or_path) -> "FaultInjector":
        """Arm the injector from a dict or a JSON config file path."""
        if cls._instance is not None:
            raise RuntimeError("fault injector already installed")
        if isinstance(config_or_path, (str, os.PathLike)):
            path = os.fspath(config_or_path)
            with open(path) as f:
                config = json.load(f)
            inj = cls(config, path)
            inj._mtime = os.stat(path).st_mtime
            if config.get("dynamic"):
                inj._watcher = threading.Thread(
                    target=inj._watch, name="srt-faultinj-watch", daemon=True)
                inj._watcher.start()
        else:
            inj = cls(dict(config_or_path))
        cls._instance = inj
        _seam._set_injector(inj._check)
        return inj

    @classmethod
    def uninstall(cls) -> None:
        inj = cls._instance
        if inj is None:
            return
        _seam._set_injector(None)
        inj._stop.set()
        if inj._watcher is not None:
            inj._watcher.join(timeout=5)
        cls._instance = None

    # -- config ------------------------------------------------------------
    def _load(self, config: dict) -> None:
        rules = {}
        for cat in (_seam.OP, _seam.TRANSFER, _seam.COLLECTIVE, _seam.ALLOC,
                    _seam.SPILL, _seam.COMPILE, _seam.SERVE, _seam.SHUFFLE):
            cat_spec = config.get(cat, {})
            rules[cat] = {name: _Rule(spec) for name, spec in cat_spec.items()}
        with self._lock:
            self._rules = rules
            self._rng = random.Random(config.get("seed"))

    def _watch(self) -> None:
        """Hot reload on config change (faultinj.cu:32 inotify analog)."""
        while not self._stop.wait(0.2):
            try:
                m = os.stat(self._path).st_mtime
                if m != self._mtime:
                    self._mtime = m
                    with open(self._path) as f:
                        self._load(json.load(f))
            except (OSError, ValueError):
                pass  # mid-write config; retry next poll

    # -- the seam hook -----------------------------------------------------
    @staticmethod
    def _match_rule(cat_rules: dict, name: str) -> Optional[_Rule]:
        """Rule precedence for one crossing: exact name, then glob
        patterns (the reference matches interceptionMatchPattern regexes
        the same way), then the catch-all.  ONE definition shared by the
        seam hook and the transport consult, so the two chaos surfaces
        can never resolve a name differently."""
        rule = cat_rules.get(name)
        if rule is None:
            rule = next(
                (r for pat, r in cat_rules.items()
                 if pat != "*" and pat != name
                 and fnmatch.fnmatchcase(name, pat)),
                None) or cat_rules.get("*")
        return rule

    def _check(self, category: str, name: str) -> None:
        with self._lock:
            cat_rules = self._rules.get(category)
            if not cat_rules:
                return
            rule = self._match_rule(cat_rules, name)
            if rule is None:
                return
            fired = rule.fire(self._rng, name)
        if fired is None:
            return
        kind, payload = fired
        if kind == "raise":
            raise payload
        if kind == "proc_kill":
            # the crash-only drill: no cleanup, no exception — the process
            # vanishes mid-crossing exactly like a segfaulted executor
            os.kill(os.getpid(), signal.SIGKILL)
        if kind in ("frame_corrupt", "frame_truncate"):
            # transport verdicts are meaningless at a plain seam crossing
            # (there are no bytes here to damage); only the shuffle
            # sender's transport_fault() consult can apply them
            return
        # slow / hang / peer_stall: stall the crossing thread (outside the
        # lock — a hang wedges THIS thread only, others keep injecting)
        time.sleep(payload)

    def _transport_check(self, name: str):
        """The shuffle transport's consult (serve/shuffle.py, per framed
        partition send): returns ``("frame_corrupt" | "frame_truncate",
        duration)`` for the SENDER to apply to the outgoing bytes, or None.
        ``peer_stall`` stalls the serving thread here (the receiver sees a
        peer that stops talking mid-frame) and returns None."""
        with self._lock:
            cat_rules = self._rules.get(_seam.SHUFFLE)
            if not cat_rules:
                return None
            rule = self._match_rule(cat_rules, name)
            if rule is None:
                return None
            fired = rule.fire(self._rng, name)
        if fired is None:
            return None
        kind, payload = fired
        if kind == "peer_stall":
            time.sleep(payload)
            return None
        if kind in _TRANSPORT_KINDS:
            return (kind, payload)
        if kind == "raise":
            raise payload
        return None  # slow/hang/proc_kill make no sense here; ignore


def pressure_storm_config(seed: int = 0, *, retry_pct: float = 25.0,
                          split_pct: float = 8.0) -> dict:
    """The seeded memory-pressure-storm chaos profile (round 9).

    One canonical scenario shared by the serve_bench ``--chaos-storm``
    tier, the CI chaos gate, and the controller acceptance tests, so
    "adaptive beats static under chaos" is always measured against the
    SAME storm: injected RetryOOMs on a fraction of budget reservations
    (extra arbiter churn inside every retry bracket) plus occasional
    SplitAndRetryOOMs at the serve seam (handler-level split storms).
    Real *sustained* pressure comes from the caller's undersized budget;
    this profile adds the transient-fault weather on top.

    Deterministic: the injector's config-level RNG is seeded, so the same
    seed yields the same injected-fault schedule (the property
    test_observability pins for the injector in general).
    """
    return {
        "seed": int(seed),
        "alloc": {"reserve:*": {"percent": float(retry_pct),
                                "injectionType": "retry_oom"}},
        "serve": {"handle:*": {"percent": float(split_pct),
                               "injectionType": "split_oom"}},
    }


def chaos_kill_config(seed: int = 0, *, kill: bool = True,
                      kill_pct: float = 8.0, slow_pct: float = 5.0,
                      slow_ms: float = 25.0) -> dict:
    """The seeded executor-chaos profile for cluster serving (round 10).

    Armed INSIDE each executor worker process by the supervisor's chaos
    mode (``serve_bench --cluster N --chaos-kill``): a fraction of served
    requests stall briefly (``slow``), and — when ``kill`` is set for this
    incarnation — one seeded crossing SIGKILLs the whole executor mid-
    request (``interceptionCount: 1``: each armed incarnation dies at most
    once, so the kill count across a run is bounded by the incarnations
    the caller chooses to arm).  Deterministic per seed, like
    :func:`pressure_storm_config`.
    """
    cfg = {
        "seed": int(seed),
        "serve": {"handle:*": {"percent": float(slow_pct),
                               "injectionType": "slow",
                               "durationMs": float(slow_ms)}},
    }
    if kill:
        # the kill arms a DIFFERENT seam (the budget reservation every
        # executor-governed handler crosses per attempt) so it rolls
        # independently of the serve-seam slow weather — one rule per
        # crossing name means stacking both on handle:* would shadow
        # (review r10); dying while holding an admission slot is also
        # the nastier drill
        cfg["alloc"] = {"reserve:*": {"percent": float(kill_pct),
                                      "injectionType": "proc_kill",
                                      "interceptionCount": 1}}
    return cfg


def transport_fault(name: str):
    """Module-level consult for the shuffle transport: the armed
    injector's shuffle-category verdict for ``name``, or None when no
    injector is installed (the zero-overhead default)."""
    inj = FaultInjector._instance
    if inj is None:
        return None
    return inj._transport_check(name)


def chaos_shuffle_config(seed: int = 0, *, kill: bool = True,
                         corrupt_pct: float = 12.0,
                         truncate_pct: float = 8.0,
                         stall_pct: float = 6.0, stall_ms: float = 400.0,
                         kill_pct: float = 5.0) -> dict:
    """The seeded data-plane chaos profile (round 13).

    Armed INSIDE each executor worker by ``serve_bench --cluster
    --chaos-shuffle``: framed partition sends are corrupted (receiver's
    CRC must catch and re-fetch), truncated mid-frame (length check), or
    stalled (``peer_stall`` wedges the serving thread past the consumer's
    I/O timeout, driving the seeded-jitter backoff path); when ``kill``
    is armed for an incarnation, one seeded budget-reservation crossing
    SIGKILLs the executor mid-exchange (``interceptionCount: 1`` per
    armed incarnation, like :func:`chaos_kill_config`).  The three
    transport rules bind DIFFERENT crossing names (``frame:*`` /
    ``trunc:*`` / ``stall:*`` — the sender consults all three per send)
    so none shadows another.  Deterministic per seed.
    """
    cfg = {
        "seed": int(seed),
        "shuffle": {
            "frame:*": {"percent": float(corrupt_pct),
                        "injectionType": "frame_corrupt",
                        "interceptionCount": 4},
            "trunc:*": {"percent": float(truncate_pct),
                        "injectionType": "frame_truncate",
                        "interceptionCount": 4},
            "stall:*": {"percent": float(stall_pct),
                        "injectionType": "peer_stall",
                        "durationMs": float(stall_ms),
                        "interceptionCount": 2},
        },
    }
    if kill:
        # die while holding an admission slot mid-exchange: the transport
        # reservation (fetch credit) and the reduce's governed bracket
        # both cross reserve:*, so the kill lands inside the shuffle
        cfg["alloc"] = {"reserve:*": {"percent": float(kill_pct),
                                      "injectionType": "proc_kill",
                                      "interceptionCount": 1}}
    return cfg


def install_from_env() -> Optional[FaultInjector]:
    """Arm from the ``fault_injector_config_path`` config flag (env-backed by
    SRT_FAULT_INJECTOR_CONFIG_PATH) if set and not already armed."""
    from spark_rapids_jni_tpu import config

    path = config.get("fault_injector_config_path")
    if path and FaultInjector._instance is None:
        return FaultInjector.install(path)
    return None
