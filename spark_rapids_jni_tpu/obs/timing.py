"""Device timing that survives the axon TPU tunnel.

Measured facts (round 3, live chip):

- ``block_until_ready()`` returns without waiting for device execution on
  the axon remote platform: a 2^26-element f32 copy "timed" that way
  reports ~19 TB/s, ~25x the physical HBM bandwidth of the chip.  Every
  number produced by block-based timing through this tunnel is fiction.
- Host materialization is honest but brutally slow (~2 MB/s through the
  tunnel; a 64 MB fetch took 36 s), so syncing by pulling the output back
  is unusable for throughput work.
- Materializing a *scalar* computed from the output on device is the
  reliable sync: the reduction program cannot run until the producer
  program finished, and only ~8 bytes cross the tunnel.  One such sync
  costs ~70 ms wall (tunnel round-trip), independent of payload.

So the timing recipe here is:

1. ``device_sync(tree)`` — reduce each jax leaf to a scalar on device and
   pull only that.  Correct on every platform, cheap everywhere but the
   tunnel, where it is the only correct option.
2. ``time_marginal(fn, iters_lo, iters_hi)`` — time the loop at two
   iteration counts and report ``(t_hi - t_lo) / (iters_hi - iters_lo)``.
   The subtraction cancels *all* fixed costs: compile-cache lookup, the
   sync round-trip, dispatch-queue ramp.  What remains is the steady-state
   per-call device time — the number a throughput claim should be made of.

The reference's nvbench benchmarks (e.g.
``src/main/cpp/benchmarks/row_conversion.cpp:27``) get the same effect from
CUDA events; TPU-through-a-tunnel needs it reconstructed host-side.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Tuple

__all__ = ["device_sync", "time_marginal", "time_marginal_for_iters"]


def device_sync(tree: Any) -> None:
    """Block until every jax array in ``tree`` has actually been computed.

    Uses an on-device scalar reduction + 8-byte materialization per leaf
    (see module docstring for why ``block_until_ready`` is not enough on
    remote platforms).  Non-array leaves are ignored.
    """
    import jax
    import jax.numpy as jnp

    for leaf in jax.tree_util.tree_leaves(tree):
        if not hasattr(leaf, "dtype") or not hasattr(leaf, "ravel"):
            continue
        x = leaf
        if x.dtype == jnp.bool_:
            x = x.astype(jnp.int32)
        # max() avoids overflow concerns; the value is discarded.
        float(jnp.max(x.astype(jnp.float32)) if x.size else jnp.float32(0))


def time_marginal(
    fn: Callable[[], Any],
    iters_lo: int = 5,
    iters_hi: int = 25,
    sync: Callable[[Any], None] = device_sync,
) -> Tuple[float, dict]:
    """Steady-state seconds per call of ``fn`` via two-point subtraction.

    Returns ``(seconds_per_call, info)`` where info carries the raw points
    for the bench detail blob.  ``fn`` is invoked ``iters_lo + iters_hi + 1``
    times total (1 warmup).  If noise makes the subtraction non-positive,
    falls back to the amortized hi-point rate (which still contains the
    fixed sync overhead and therefore *understates* throughput — safe
    direction for a reported number).
    """
    out = fn()
    sync(out)  # compile + warm

    times = []
    for iters in (iters_lo, iters_hi):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn()
        sync(out)
        times.append(time.perf_counter() - t0)

    marginal = (times[1] - times[0]) / (iters_hi - iters_lo)
    amortized = times[1] / iters_hi
    info = {
        "t_lo_s": round(times[0], 6),
        "t_hi_s": round(times[1], 6),
        "iters": [iters_lo, iters_hi],
        "amortized_s_per_call": round(amortized, 9),
        "method": "marginal",
    }
    if marginal <= 0:
        info["method"] = "amortized-fallback"
        return amortized, info
    return marginal, info


def time_marginal_for_iters(fn: Callable[[], Any], iters: int):
    """`time_marginal` with the two points derived from a caller's legacy
    iteration budget.  Cheap stages (small ``iters``) stay cheap: total
    calls ~= 2*iters + 1, never more than ~1.3x the pre-marginal loop for
    large ``iters``.  Single place for the derivation so bench.py and
    tools/ cannot drift apart.
    """
    if iters <= 4:
        lo, hi = 1, max(3, iters)
    else:
        lo, hi = max(2, iters // 4), iters
    return time_marginal(fn, lo, hi)
