"""Governance flight recorder: always-on ring of state-transition events.

The reference's only window into its SparkResourceAdaptor state machine is
a CSV transition log the operator must arm *before* the incident
(task_arbiter.cpp log_transition; ``TaskArbiter(log_path=...)`` here) —
after a soak deadlock or a retry storm, "which task was blocked on what,
and what woke it" is unanswerable.  This module is the always-on analog a
production query engine keeps: a bounded, lock-cheap ring buffer of
structured state-transition events fed from every governance layer —

- ``mem/arbiter.py``   blocked/woken around parking calls, retry and
  split-and-retry signal deliveries, deadlock-break verdicts (state_of
  sweeps across ``check_and_break_deadlocks``);
- ``mem/governed.py``  task admission / completion (``task_context``);
- ``mem/spill.py``     spill begin/end with byte counts;
- ``serve/executor.py`` queue rejections/timeouts, split-requeues,
  OOM-killed requests, queue-saturation detection.

Events are tuples appended to a ``collections.deque(maxlen=N)``.  The
hot recording path takes one uncontended leaf lock around the
(sequence-allocate, append) pair — ring order and the round-14 telemetry
cursor's seq order must agree, or a preempted recorder would land a
lower seq after a higher one and every downstream cursor/dedup consumer
would silently drop that event — plus the stats-table lock for four
event kinds only.  When the
SRTP profiler is active each event is additionally streamed into the
capture as a STATE record (format v2, obs/profiler.py), which
``obs/convert.py`` renders as per-task governance tracks aligned with the
op/serve ranges.

On anomaly — deadlock broken, queue saturation, task OOM-killed, watchdog
fire — :func:`anomaly` dumps the full ring plus a unified telemetry
snapshot (every registered source: serve metrics, governor budget gauges,
spill-pool gauges) to a JSON artifact under the ``flight_dump_dir`` config
flag (kept in memory when unset).  ``tools/flightdump.py`` pretty-prints
the reconstructed per-task timeline from such a dump.
"""

from __future__ import annotations

import collections
import itertools
import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from spark_rapids_jni_tpu.obs import seam as _seam

__all__ = [
    "EV_TASK_ADMITTED", "EV_TASK_BLOCKED", "EV_TASK_WOKEN", "EV_RETRY",
    "EV_SPLIT_RETRY", "EV_SPILL_BEGIN", "EV_SPILL_END",
    "EV_DEADLOCK_VERDICT", "EV_QUEUE_REJECT", "EV_QUEUE_TIMEOUT",
    "EV_TASK_DONE", "EV_TASK_KILLED", "EV_ANOMALY",
    "EV_CONTROL_ADJUST", "EV_CONTROL_FREEZE", "EV_CONTROL_PRESPLIT",
    "EV_TASK_HUNG", "EV_DEGRADE_ENTER", "EV_DEGRADE_EXIT",
    "EV_LEASE_GRANT", "EV_LEASE_REDISPATCH", "EV_LEASE_DONE",
    "EV_WORKER_SPAWN", "EV_WORKER_DEAD",
    "EV_RAGGED_PACK", "EV_RAGGED_LAUNCH", "EV_RAGGED_SPLIT",
    "EV_SHUFFLE_PRODUCE", "EV_SHUFFLE_FETCH", "EV_SHUFFLE_RETRY",
    "EV_SHUFFLE_ACK",
    "EV_SPAN_OPEN", "EV_SPAN_CLOSE", "EV_SLO_BURN", "EV_SLO_OK",
    "EV_TELEMETRY_EXPORT", "EV_TELEMETRY_DROP",
    "EV_RCACHE_HIT", "EV_RCACHE_STORE", "EV_RCACHE_DEMOTE",
    "EV_RCACHE_EVICT", "EV_RCACHE_INVALIDATE",
    "EV_PLAN_REWRITE", "EV_ADAPT_EXCHANGE",
    "EV_HEDGE_LAUNCH", "EV_HEDGE_WIN", "EV_HEDGE_LOSE",
    "EV_ATTRIB",
    "EVENT_KINDS", "EVENT_PAIRS", "KIND_IDS", "DUMP_SCHEMA",
    "FlightRecorder", "record", "anomaly", "snapshot", "snapshot_since",
    "task_stats", "task_stat", "ring_stats",
    "register_telemetry_source", "unregister_telemetry_source",
    "unified_snapshot", "recorder",
]

# --------------------------------------------------------------------------
# event-kind vocabulary (wire ids = tuple index; ci/analyze.py's
# flight-discipline pass enforces that emission sites use these constants)
# --------------------------------------------------------------------------

EV_TASK_ADMITTED = "admitted"          # task registered a dedicated thread
EV_TASK_BLOCKED = "blocked"            # thread parked waiting for budget
EV_TASK_WOKEN = "woken"                # parked wait returned (value=wait_ns)
EV_RETRY = "retry"                     # RetryOOM delivered to the thread
EV_SPLIT_RETRY = "split_retry"         # SplitAndRetryOOM / split-requeue
EV_SPILL_BEGIN = "spill_begin"         # D2H staging starts (value=nbytes)
EV_SPILL_END = "spill_end"             # D2H staging done (value=dur_ns)
EV_DEADLOCK_VERDICT = "deadlock_verdict"  # watchdog escalated a thread
EV_QUEUE_REJECT = "queue_reject"       # admission backpressure rejection
EV_QUEUE_TIMEOUT = "queue_timeout"     # deadline expired while queued
EV_TASK_DONE = "task_done"             # task deregistered cleanly
EV_TASK_KILLED = "task_killed"         # task failed terminally on OOM
EV_ANOMALY = "anomaly"                 # a dump was triggered (detail=reason)
# admission-controller decision ledger (serve/controller.py): every knob
# adjustment, freeze transition, and pre-emptive split lands in the ring so
# tools/flightdump.py can reconstruct WHY the admission posture changed
EV_CONTROL_ADJUST = "control_adjust"   # knob changed (detail=knob:old->new
#                                        :reason, value=new scaled)
EV_CONTROL_FREEZE = "control_freeze"   # kill-switch froze (value=1) /
#                                        resumed (value=0) the controller
EV_CONTROL_PRESPLIT = "control_presplit"  # request split BEFORE dispatch
#                                        (detail=handler:pieces)
# crash-only serving (serve/supervisor.py, round 10): the supervisor's
# lease table, executor-process lifecycle, and degradation ladder all
# narrate into the ring so a cross-process incident is reconstructable
# from the per-process dumps (tools/flightdump.py --cluster)
EV_TASK_HUNG = "task_hung"             # handler exceeded its EWMA hang
#                                        bound (value=elapsed_ns)
EV_DEGRADE_ENTER = "degrade_enter"     # ladder stepped DOWN a level
#                                        (detail=level name, value=level)
EV_DEGRADE_EXIT = "degrade_exit"       # ladder recovered UP a level
#                                        (detail=level name, value=level)
EV_LEASE_GRANT = "lease_grant"         # request leased to an executor
#                                        (detail=rid:<id>:worker:<wid>...)
EV_LEASE_REDISPATCH = "lease_redispatch"  # dead/hung executor's lease
#                                        re-queued to survivors
EV_LEASE_DONE = "lease_done"           # lease reached a terminal state
#                                        (detail=rid:<id>:...:status)
EV_WORKER_SPAWN = "worker_spawn"       # executor process (re)started
#                                        (detail=worker:<wid>:inc:<n>:pid)
EV_WORKER_DEAD = "worker_dead"         # executor declared dead (crashed,
#                                        heartbeat-lost, or hung-recycled)
# continuous ragged batching (serve/ragged.py, round 12): every fused
# page-pool tick narrates pack -> launch (-> split) into the ring, so a
# pressure incident shows WHICH riders shared a launch and how the page
# count walked down under SplitAndRetryOOM
EV_RAGGED_PACK = "ragged_pack"         # riders packed into the page pool
#                                        (detail=handler:<h>:riders:<n>
#                                        :pages:<p>, value=rows packed)
EV_RAGGED_LAUNCH = "ragged_launch"     # one fused page-pool launch
#                                        (detail=handler:<h>:geom:<g>,
#                                        value=rows packed)
EV_RAGGED_SPLIT = "ragged_split"       # page-count halving on
#                                        SplitAndRetryOOM (detail=
#                                        handler:<h>:riders:<n>:pages:
#                                        <from>-><to>, value=new depth)
# crash-safe columnar shuffle (serve/shuffle.py, round 13): the
# peer-to-peer data plane narrates map-side production, every framed
# partition fetch (with its source path), every transport retry (CRC
# mismatch, truncation, stalled peer, refused connection), and the
# consumer acks the supervisor's partition map tracks — detail tokens
# carry rid:/sid:/part: so flightdump --cluster can stitch partition
# lineage across executor processes
EV_SHUFFLE_PRODUCE = "shuffle_produce"  # map task's partitions framed +
#                                        stored (detail=rid:<r>:sid:<s>:
#                                        map:<m>:parts:<n>, value=bytes)
EV_SHUFFLE_FETCH = "shuffle_fetch"      # one partition fetched + CRC-
#                                        verified (detail=rid:<r>:sid:<s>
#                                        :from:<k>:part:<p>:src:<how>,
#                                        value=bytes)
EV_SHUFFLE_RETRY = "shuffle_retry"      # fetch attempt failed, backing
#                                        off (detail=...:reason:<why>)
EV_SHUFFLE_ACK = "shuffle_ack"          # consumer acked a fetched
#                                        partition into the supervisor's
#                                        partition map (detail=rid:<r>:
#                                        sid:<s>:from:<k>:part:<p>)
# the live telemetry plane (round 14, obs/trace.py + serve/telemetry.py
# + serve/slo.py): distributed request spans, continuous export, and the
# SLO burn-rate engine all narrate into the ring like every other layer
EV_SPAN_OPEN = "span_open"              # request phase span opened
#                                        (detail=rid:<r>:span:<s>:parent:
#                                        <p>:kind:<queue|dispatch|
#                                        transport|compute|scatter>...;
#                                        emitted ONLY by obs/trace.py)
EV_SPAN_CLOSE = "span_close"            # span closed (same detail
#                                        tokens, value=duration ns)
EV_SLO_BURN = "slo_burn"                # an objective entered burn
#                                        (detail=slo:<name>:obj:<kind>:
#                                        burn:<x>, value=burn x1000)
EV_SLO_OK = "slo_ok"                    # the objective recovered
EV_TELEMETRY_EXPORT = "telemetry_export"  # a worker's export stream came
#                                        up (first delta shipped;
#                                        value=events in the delta)
EV_TELEMETRY_DROP = "telemetry_drop"    # an export was skipped (stalled
#                                        supervisor pipe) or trimmed
#                                        (delta over the cap) — the
#                                        worker NEVER blocks on export
# the governed multi-tier result cache (round 15, plans/rcache.py +
# models/tables.py): every hit/store, every residency move down the
# HBM -> host -> disk ladder, and every table-version invalidation
# narrates into the ring, so "why did this query skip compute" and
# "where did the cache's bytes go under pressure" reconstruct from the
# same artifact as the retry storm that squeezed them
EV_RCACHE_HIT = "rcache_hit"            # result served from the cache
#                                        (detail=[rid:<r>:]handler:<h>:
#                                        tier:<hbm|host|disk>:key:<tok>,
#                                        value=result bytes)
EV_RCACHE_STORE = "rcache_store"        # result inserted (detail=
#                                        handler:<h>:tier:<t>:key:<tok>,
#                                        value=result bytes)
EV_RCACHE_DEMOTE = "rcache_demote"      # entry moved down one tier
#                                        (detail=key:<tok>:<from>-><to>:
#                                        reason:<pressure|cap>,
#                                        value=bytes moved)
EV_RCACHE_EVICT = "rcache_evict"        # entry dropped entirely (detail=
#                                        key:<tok>:tier:<t>:reason:
#                                        <cap|corrupt|stale>, value=bytes)
EV_RCACHE_INVALIDATE = "rcache_invalidate"  # a table-version bump made
#                                        entries unreachable (detail=
#                                        table:<name>:version:<v>,
#                                        value=new version; emitted by
#                                        models/tables.py per bump and
#                                        by the cache per reclaimed key)
# the stats-driven optimizer + adaptive execution (round 19,
# plans/optimizer.py + serve/shuffle.py + serve/supervisor.py): every
# plan rewrite, every runtime reduce-side Exchange decision, and every
# speculative hedge narrates into the ring, so "why did this plan's
# shape change" and "which dispatch was a hedge copy" reconstruct from
# the same artifact as everything else (flightdump --control renders
# the decision ledger)
EV_PLAN_REWRITE = "plan_rewrite"        # optimizer applied one rewrite
#                                        (detail=plan:<name>:rule:<rule>
#                                        :node:<type>, value=pass no.) or
#                                        summary (rule:done, value=total)
EV_ADAPT_EXCHANGE = "adapt_exchange"    # reduce side picked its shape at
#                                        runtime (detail=rid:<r>:sid:<s>:
#                                        strategy:<broadcast|coalesce|
#                                        shuffle>:parts:<from>-><to>,
#                                        value=total exchange bytes)
EV_HEDGE_LAUNCH = "hedge_launch"        # lease sat past its handler's
#                                        windowed p99: hedge copy sent
#                                        (detail=rid:<r>:worker:<w>:inc:
#                                        <i>:handler:<h>, value=age_ns)
EV_HEDGE_WIN = "hedge_win"              # the hedge copy's result
#                                        completed the lease first
#                                        (detail=rid:<r>:worker:<w>)
EV_HEDGE_LOSE = "hedge_lose"            # the primary finished first (or
#                                        the hedge aborted): hedge copy's
#                                        result will be duplicate-dropped
#                                        (detail=rid:<r>:reason:<why>)
# per-request resource attribution (round 21, serve/attribution.py): one
# event per terminal request carrying the full AttributionRecord — what
# the supervisor's per-tenant rollup and the capacity observatory fold.
# Detail grammar: ``rid:<r>:tenant:<t>:handler:<h>:comp:<ns>`` always,
# then nonzero-only ``gbs:<byte_ns>:q:<ns>:blk:<ns>:tx:<bytes>:
# res:<bytes>:hit:<n>:miss:<n>:retry:<n>:split:<n>`` tokens, and
# ``flags:<a+b>`` (``split``/``cache``/``hedge``) last; value=comp ns.
# Tenant and handler names must not contain ':'.
EV_ATTRIB = "attrib"

# Paired kinds: a layer that emits the left side of a pair must also emit
# the right side (module-granular balance, enforced by the analyze gate's
# state-machine pass) — the drift class where one side of a bracket
# protocol is dropped and every reconstruction silently loses its spans.
EVENT_PAIRS = (
    (EV_TASK_BLOCKED, EV_TASK_WOKEN),
    (EV_TASK_ADMITTED, EV_TASK_DONE),
    (EV_SPILL_BEGIN, EV_SPILL_END),
    (EV_DEGRADE_ENTER, EV_DEGRADE_EXIT),
    (EV_LEASE_GRANT, EV_LEASE_DONE),
    (EV_SHUFFLE_PRODUCE, EV_SHUFFLE_ACK),
    # round 14: a module opening spans must close them, and an SLO layer
    # that can declare burn must be able to declare recovery — both sides
    # live in one module (obs/trace.py, serve/slo.py) by construction
    (EV_SPAN_OPEN, EV_SPAN_CLOSE),
    (EV_SLO_BURN, EV_SLO_OK),
)

EVENT_KINDS = (
    EV_TASK_ADMITTED, EV_TASK_BLOCKED, EV_TASK_WOKEN, EV_RETRY,
    EV_SPLIT_RETRY, EV_SPILL_BEGIN, EV_SPILL_END, EV_DEADLOCK_VERDICT,
    EV_QUEUE_REJECT, EV_QUEUE_TIMEOUT, EV_TASK_DONE, EV_TASK_KILLED,
    EV_ANOMALY,
    # round 9: appended (never reordered) so v2 STATE wire ids stay stable
    EV_CONTROL_ADJUST, EV_CONTROL_FREEZE, EV_CONTROL_PRESPLIT,
    # round 10: appended for the same reason
    EV_TASK_HUNG, EV_DEGRADE_ENTER, EV_DEGRADE_EXIT,
    EV_LEASE_GRANT, EV_LEASE_REDISPATCH, EV_LEASE_DONE,
    EV_WORKER_SPAWN, EV_WORKER_DEAD,
    # round 12: appended (wire ids frozen in ci/flight_wire_ids.json)
    EV_RAGGED_PACK, EV_RAGGED_LAUNCH, EV_RAGGED_SPLIT,
    # round 13: appended for the same reason
    EV_SHUFFLE_PRODUCE, EV_SHUFFLE_FETCH, EV_SHUFFLE_RETRY, EV_SHUFFLE_ACK,
    # round 14: appended for the same reason
    EV_SPAN_OPEN, EV_SPAN_CLOSE, EV_SLO_BURN, EV_SLO_OK,
    EV_TELEMETRY_EXPORT, EV_TELEMETRY_DROP,
    # round 15: appended for the same reason
    EV_RCACHE_HIT, EV_RCACHE_STORE, EV_RCACHE_DEMOTE,
    EV_RCACHE_EVICT, EV_RCACHE_INVALIDATE,
    # round 19: appended for the same reason
    EV_PLAN_REWRITE, EV_ADAPT_EXCHANGE,
    EV_HEDGE_LAUNCH, EV_HEDGE_WIN, EV_HEDGE_LOSE,
    # round 21: appended for the same reason
    EV_ATTRIB,
)
KIND_IDS = {k: i for i, k in enumerate(EVENT_KINDS)}

DUMP_SCHEMA = "srt-flight-dump-v1"

# per-task accumulators kept for at most this many distinct tasks (oldest
# evicted); sized above any realistic live-task count, below leak territory
_MAX_TASKS = 1024


def _dump_min_interval_s() -> float:
    """One dump per (reason) per this many seconds — a retry storm must
    produce one artifact, not thousands.  Config-tunable (round 14,
    ``flight_dump_rate_s``): chaos tiers tighten it to see every
    incident; fleets widen it to bound artifact churn."""
    from spark_rapids_jni_tpu import config

    return float(config.get("flight_dump_rate_s"))


class FlightRecorder:
    """Bounded ring of governance events + per-task accumulators."""

    def __init__(self, ring_size: Optional[int] = None):
        if ring_size is None:
            from spark_rapids_jni_tpu import config

            ring_size = int(config.get("flight_ring_size"))
        self._ring: "collections.deque" = collections.deque(maxlen=ring_size)
        # monotonically increasing per-event sequence: the telemetry
        # exporter's cursor (serve/telemetry.py snapshot_since).  Seq
        # allocation and the append must be ONE atomic step — a thread
        # preempted between them would land a lower seq AFTER a higher
        # one, and every cursor/high-water consumer downstream would
        # silently drop that event forever — so the ring append takes a
        # dedicated leaf lock (an uncontended CPython lock is tens of
        # ns; the stats table below keeps its own lock, touched for four
        # kinds only)
        self._ev_seq = itertools.count(1)
        self._ring_lock = threading.Lock()
        # wrap-around loss ledger: every append that evicted the oldest
        # event (satellite, round 21) — completeness claims (waterfall
        # fractions, attribution coverage) can then STATE how many
        # events the ring dropped instead of silently presenting a
        # truncated history as complete
        self.ring_dropped = 0  # guarded-by: _ring_lock
        self._stats_lock = threading.Lock()
        self._tasks: "collections.OrderedDict" = collections.OrderedDict()
        self._sources: Dict[str, Callable[[], dict]] = {}
        self._sources_lock = threading.Lock()
        self._dump_lock = threading.Lock()
        self._last_dump_t: Dict[str, float] = {}
        self._dump_seq = 0
        self.dumps: List[dict] = []          # last few dumps, newest last
        self.dump_count = 0
        self.dumps_suppressed = 0

    # -- recording (the hot path) ------------------------------------------
    def record(self, kind: str, task_id: int = -1, detail: str = "",
               value: int = 0) -> None:
        t_ns = time.monotonic_ns()
        tid = threading.get_ident() & 0xFFFFFFFF
        # seq allocation + append under one leaf lock: ring order and
        # seq order must agree (see _ring_lock above)
        with self._ring_lock:
            if (self._ring.maxlen is not None
                    and len(self._ring) == self._ring.maxlen):
                self.ring_dropped += 1
            self._ring.append((next(self._ev_seq), t_ns, kind, task_id,
                               tid, detail, value))
        if task_id >= 0 and kind in _STAT_KINDS:
            with self._stats_lock:
                st = self._tasks.get(task_id)
                if st is None:
                    if len(self._tasks) >= _MAX_TASKS:
                        self._tasks.popitem(last=False)
                    st = self._tasks[task_id] = {
                        "retries": 0, "split_retries": 0,
                        "blocked_ns": 0, "wakes": 0, "killed": 0,
                    }
                if kind == EV_RETRY:
                    st["retries"] += 1
                elif kind == EV_SPLIT_RETRY:
                    st["split_retries"] += 1
                elif kind == EV_TASK_WOKEN:
                    st["wakes"] += 1
                    st["blocked_ns"] += max(int(value), 0)
                elif kind == EV_TASK_KILLED:
                    st["killed"] += 1
        if _seam._profiler_range is not None:
            from spark_rapids_jni_tpu.obs.profiler import Profiler

            Profiler.state(KIND_IDS[kind], task_id, detail, value,
                           t_ns=t_ns, tid=tid)

    # -- reading -----------------------------------------------------------
    def snapshot(self) -> List[dict]:
        """The ring as event dicts, oldest first (a point-in-time copy)."""
        return [
            {"seq": seq, "t_ns": t, "kind": k, "task_id": task, "tid": tid,
             "detail": d, "value": v}
            for seq, t, k, task, tid, d, v in list(self._ring)
        ]

    def snapshot_since(self, cursor: int) -> Tuple[List[dict], int]:
        """Events with ``seq > cursor`` plus the new cursor — the rolling
        delta the telemetry plane exports (serve/telemetry.py).  A caller
        that falls further behind than the ring's capacity simply misses
        the overwritten prefix: the ring is the retention bound, and the
        gap is visible as non-contiguous ``seq`` values downstream.

        O(delta), not O(ring): the scan walks backward under the ring
        lock and stops at the cursor — per-request force-flushes must
        not pay a full-ring copy for a handful of new events."""
        newest: List[tuple] = []
        with self._ring_lock:
            for item in reversed(self._ring):
                if item[0] <= cursor:
                    break
                newest.append(item)
        newest.reverse()
        events = [
            {"seq": seq, "t_ns": t, "kind": k, "task_id": task, "tid": tid,
             "detail": d, "value": v}
            for seq, t, k, task, tid, d, v in newest
        ]
        return events, (events[-1]["seq"] if events else cursor)

    def task_stats(self) -> Dict[int, dict]:
        """Per-task accumulators (non-destructive, unlike the arbiter's
        get-and-reset metrics — safe to sample from any dump/publish)."""
        with self._stats_lock:
            return {task: dict(st) for task, st in self._tasks.items()}

    def task_stat(self, task_id: int) -> Optional[dict]:
        """ONE task's accumulators (or None) — O(1), unlike task_stats'
        full-table copy: the attribution finish path samples blocked-ns
        and retry counts per request, and must not pay _MAX_TASKS dict
        copies on every completion."""
        with self._stats_lock:
            st = self._tasks.get(task_id)
            return dict(st) if st is not None else None

    def ring_stats(self) -> dict:
        """The ring's retention ledger: capacity, occupancy, and how
        many events wrap-around has evicted since start/reset."""
        with self._ring_lock:
            return {"capacity": self._ring.maxlen or 0,
                    "events": len(self._ring),
                    "dropped": self.ring_dropped}

    # -- telemetry sources -------------------------------------------------
    def register_telemetry_source(self, name: str,
                                  fn: Callable[[], dict]) -> None:
        with self._sources_lock:
            self._sources[name] = fn

    def unregister_telemetry_source(self, name: str) -> None:
        with self._sources_lock:
            self._sources.pop(name, None)

    def unified_snapshot(self) -> dict:
        """Every registered telemetry source, sampled now.  A failing
        source becomes an ``{"error": ...}`` entry — a dump taken mid-crash
        must never itself crash."""
        with self._sources_lock:
            sources = dict(self._sources)
        out = {}
        for name, fn in sources.items():
            try:
                out[name] = fn()
            # analyze: ignore[retry-protocol] - dump-time sampling of user
            # gauge callables: any failure (a closed engine, a shut-down
            # governor) is reported in-band, never propagated out of the
            # anomaly path
            except Exception as e:  # noqa: BLE001
                out[name] = {"error": repr(e)[:200]}
        return out

    # -- anomaly dumps -----------------------------------------------------
    def anomaly(self, reason: str, detail: str = "") -> Optional[dict]:
        """Record an ANOMALY event and dump ring + telemetry.

        Returns the dump dict, or None when rate-limited (one dump per
        reason per second — a storm produces one artifact, counted).
        """
        self.record(EV_ANOMALY, -1, f"{reason}:{detail}" if detail
                    else reason)
        now = time.monotonic()
        min_interval = _dump_min_interval_s()
        with self._dump_lock:
            last = self._last_dump_t.get(reason, -1e9)
            if now - last < min_interval:
                self.dumps_suppressed += 1
                return None
            self._last_dump_t[reason] = now
            self._dump_seq += 1
            seq = self._dump_seq
        dump = {
            "schema": DUMP_SCHEMA,
            "reason": reason,
            "detail": detail,
            # pid + paired (wall, monotonic) stamps let the --cluster merge
            # align per-process monotonic event times on one wall clock
            "pid": os.getpid(),
            "wall_time_s": time.time(),
            "t_ns": time.monotonic_ns(),
            "events": self.snapshot(),
            "ring": self.ring_stats(),
            "tasks": {str(k): v for k, v in self.task_stats().items()},
            "telemetry": self.unified_snapshot(),
        }
        self.dumps.append(dump)
        del self.dumps[:-4]  # keep the newest few in memory
        self.dump_count += 1
        path = self._write_dump(dump, reason, seq)
        if path:
            dump["artifact"] = path
        return dump

    def _write_dump(self, dump: dict, reason: str, seq: int) -> str:
        from spark_rapids_jni_tpu import config

        d = str(config.get("flight_dump_dir") or "")
        if not d:
            return ""
        try:
            os.makedirs(d, exist_ok=True)
            path = os.path.join(
                d, f"flight_{reason}_{os.getpid()}_{seq}.json")
            with open(path, "w") as f:
                json.dump(dump, f, indent=1, sort_keys=True)
                f.write("\n")
            return path
        except OSError:
            return ""  # an unwritable dump dir must not break governance

    def reset_for_tests(self) -> None:
        with self._ring_lock:
            self._ring.clear()
            self.ring_dropped = 0
        with self._stats_lock:
            self._tasks.clear()
        with self._dump_lock:
            self._last_dump_t.clear()
        self.dumps = []
        self.dump_count = 0
        self.dumps_suppressed = 0


_STAT_KINDS = frozenset({EV_RETRY, EV_SPLIT_RETRY, EV_TASK_WOKEN,
                         EV_TASK_KILLED})

# --------------------------------------------------------------------------
# module-level singleton facade (the always-on recorder every layer feeds)
# --------------------------------------------------------------------------

_RECORDER = FlightRecorder()


def recorder() -> FlightRecorder:
    return _RECORDER


def record(kind: str, task_id: int = -1, detail: str = "",
           value: int = 0) -> None:
    _RECORDER.record(kind, task_id, detail, value)


def anomaly(reason: str, detail: str = "") -> Optional[dict]:
    return _RECORDER.anomaly(reason, detail)


def snapshot() -> List[dict]:
    return _RECORDER.snapshot()


def snapshot_since(cursor: int) -> Tuple[List[dict], int]:
    return _RECORDER.snapshot_since(cursor)


def task_stats() -> Dict[int, dict]:
    return _RECORDER.task_stats()


def task_stat(task_id: int) -> Optional[dict]:
    return _RECORDER.task_stat(task_id)


def ring_stats() -> dict:
    return _RECORDER.ring_stats()


def register_telemetry_source(name: str, fn: Callable[[], dict]) -> None:
    _RECORDER.register_telemetry_source(name, fn)


def unregister_telemetry_source(name: str) -> None:
    _RECORDER.unregister_telemetry_source(name)


def unified_snapshot() -> dict:
    return _RECORDER.unified_snapshot()
