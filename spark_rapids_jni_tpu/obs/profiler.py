"""Always-on framework profiler with a buffered background writer.

Parity target: the CUPTI profiler (Profiler.java:50-124 API,
ProfilerJni.cpp:61-180 double-buffering + :366 writer thread,
profiler_serializer.cpp:222 size-prefixed flatbuffer blocks).  The TPU
analog: op/transfer/collective ranges captured at the dispatch seam
(obs/seam.py), double-buffered through a completed-buffer queue, serialized
by a dedicated writer thread into size-prefixed binary blocks delivered to a
user writer (file path or ``write(bytes)`` object), plus optional
jax.profiler XPlane capture for on-chip kernel timelines.

Capture format (little-endian):

- file header: ``b"SRTP"`` + u32 version (2; the converter still reads 1)
- blocks: u32 payload_len + payload (the size-prefix mirrors the
  reference's size-prefixed flatbuffers so a stream can be split without
  parsing records)
- payload records, each starting with a u8 kind:
  - 0 STRING_DEF: u32 id, u16 len, utf-8 bytes (interned names)
  - 1 RANGE: u32 name_id, u8 category, u64 start_ns, u64 end_ns, u32 tid
  - 2 INSTANT: u32 name_id, u8 category, u64 t_ns, u32 tid
  - 3 COUNTER: u32 name_id, u64 t_ns, i64 value [, u32 tid — v2 only:
    v1 counters carried no thread id, unlike RANGE/INSTANT]
  - 4 STATE (v2 only): u8 event_kind (obs/flight.py EVENT_KINDS index),
    i64 task_id, u64 t_ns, u32 tid, u32 detail_name_id, i64 value —
    one governance state-transition event from the flight recorder

Offline conversion to JSON / chrome-trace: ``python -m
spark_rapids_jni_tpu.obs.convert`` (the spark_rapids_profile_converter
analog, spark_rapids_profile_converter.cpp:106-116).
"""

from __future__ import annotations

import contextlib
import queue
import struct
import threading
import time
from typing import Optional

from spark_rapids_jni_tpu.obs import seam as _seam

__all__ = ["Profiler", "MAGIC", "VERSION", "CLOCK_ANCHOR"]

MAGIC = b"SRTP"
VERSION = 2

# counter emitted at start(): wall-clock ns minus monotonic ns, letting the
# converter place wall-stamped device events on the monotonic host timeline
CLOCK_ANCHOR = "__clock_wall_minus_mono_ns"

_CATEGORIES = {_seam.OP: 0, _seam.TRANSFER: 1, _seam.COLLECTIVE: 2,
               _seam.ALLOC: 3, "marker": 4, _seam.SPILL: 5,
               _seam.COMPILE: 6, _seam.SERVE: 7}

_R_STRING, _R_RANGE, _R_INSTANT, _R_COUNTER, _R_STATE = 0, 1, 2, 3, 4


class _State:
    """Module-singleton state (Profiler.java static API shape)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.writer = None
        self.own_file = None
        self.active = False  # between start() and stop()
        self.buf = bytearray()
        self.buf_limit = 1 << 16
        self.completed: "queue.Queue" = queue.Queue()
        self.writer_thread: Optional[threading.Thread] = None
        self.names = {}
        self.next_name_id = 0
        self.xplane_dir: Optional[str] = None
        self.initialized = False


_st = _State()


def _intern(name: str) -> int:
    """Intern a name; emits a STRING_DEF record on first sight."""
    nid = _st.names.get(name)
    if nid is None:
        nid = _st.next_name_id
        _st.next_name_id += 1
        _st.names[name] = nid
        raw = name.encode("utf-8")
        _st.buf += struct.pack("<BIH", _R_STRING, nid, len(raw)) + raw
    return nid


def _flush_active_locked():
    if _st.buf:
        _st.completed.put(bytes(_st.buf))
        _st.buf = bytearray()


def _append_locked(rec: bytes):
    """Append one record and flush at the buffer limit (caller holds lock)."""
    _st.buf += rec
    if len(_st.buf) >= _st.buf_limit:
        _flush_active_locked()
        # string table resets with each buffer: every block is
        # self-contained, so a consumer can start mid-stream
        _st.names = {}
        _st.next_name_id = 0


def _writer_loop():
    """Dedicated writer thread (writer_thread_process, ProfilerJni.cpp:366)."""
    while True:
        item = _st.completed.get()
        if item is None:
            return
        _st.writer.write(struct.pack("<I", len(item)) + item)


@contextlib.contextmanager
def _range(category: str, name: str):
    t0 = time.monotonic_ns()
    try:
        yield
    finally:
        t1 = time.monotonic_ns()
        with _st.lock:
            if _st.active:
                nid = _intern(name)
                _append_locked(struct.pack(
                    "<BIBQQI", _R_RANGE, nid, _CATEGORIES.get(category, 0),
                    t0, t1, threading.get_ident() & 0xFFFFFFFF))


class Profiler:
    """Static facade mirroring Profiler.java init/start/stop/shutdown."""

    @staticmethod
    def init(writer, *, buffer_bytes: int = 1 << 16,
             xplane_dir: Optional[str] = None) -> None:
        """Set up capture.  ``writer`` is a path or an object with
        ``write(bytes)``; events flow only between start() and stop()."""
        with _st.lock:
            if _st.initialized:
                raise RuntimeError("profiler already initialized")
            if isinstance(writer, (str, bytes)):
                _st.own_file = open(writer, "wb")
                _st.writer = _st.own_file
            else:
                _st.writer = writer
            _st.buf_limit = buffer_bytes
            _st.xplane_dir = xplane_dir
            _st.writer.write(MAGIC + struct.pack("<I", VERSION))
            _st.writer_thread = threading.Thread(
                target=_writer_loop, name="srt-profiler-writer", daemon=True)
            _st.writer_thread.start()
            _st.initialized = True
        _seam._set_profiler(_range)

    @staticmethod
    def start() -> None:
        with _st.lock:
            if not _st.initialized:
                raise RuntimeError("profiler not initialized")
            _st.active = True
        # clock-domain anchor: SRTP ranges are monotonic-ns, the device
        # timeline (XPlane/perfetto) is wall-ns — bank the offset so the
        # converter can map device events into the host timebase exactly
        Profiler.counter(CLOCK_ANCHOR,
                         time.time_ns() - time.monotonic_ns())
        if _st.xplane_dir is not None:
            import jax

            # the perfetto trace-event export is what obs/convert.py merges
            # into the durable chrome trace (device kernel timeline)
            jax.profiler.start_trace(_st.xplane_dir,
                                     create_perfetto_trace=True)

    @staticmethod
    def stop() -> None:
        if _st.xplane_dir is not None:
            import jax

            jax.profiler.stop_trace()
        with _st.lock:
            _st.active = False
            _flush_active_locked()
            _st.names = {}
            _st.next_name_id = 0

    @staticmethod
    def shutdown() -> None:
        """Stop capture, drain the queue, detach from the seam."""
        with _st.lock:
            was_init = _st.initialized
            _st.active = False
            _flush_active_locked()
        if not was_init:
            return
        _seam._set_profiler(None)
        _st.completed.put(None)
        _st.writer_thread.join(timeout=10)
        if _st.own_file is not None:
            _st.own_file.close()
        with _st.lock:
            _st.writer = None
            _st.own_file = None
            _st.writer_thread = None
            _st.names = {}
            _st.next_name_id = 0
            _st.initialized = False

    # -- extra event sources ------------------------------------------------
    @staticmethod
    def marker(name: str) -> None:
        """Instant event (NVTX marker analog)."""
        with _st.lock:
            if _st.active:
                nid = _intern(name)
                _append_locked(struct.pack(
                    "<BIBQI", _R_INSTANT, nid, _CATEGORIES["marker"],
                    time.monotonic_ns(), threading.get_ident() & 0xFFFFFFFF))

    @staticmethod
    def counter(name: str, value: int) -> None:
        with _st.lock:
            if _st.active:
                nid = _intern(name)
                _append_locked(struct.pack(
                    "<BIQqI", _R_COUNTER, nid, time.monotonic_ns(), value,
                    threading.get_ident() & 0xFFFFFFFF))

    @staticmethod
    def state(event_kind: int, task_id: int, detail: str = "",
              value: int = 0, *, t_ns: int = 0, tid: int = 0) -> None:
        """Governance state-transition record (obs/flight.py feed).  The
        caller passes its own timestamp/thread so the capture record is
        bit-identical to the ring-buffer event it mirrors."""
        with _st.lock:
            if _st.active:
                did = _intern(detail)
                _append_locked(struct.pack(
                    "<BBqQIIq", _R_STATE, event_kind & 0xFF, task_id,
                    t_ns or time.monotonic_ns(),
                    (tid or threading.get_ident()) & 0xFFFFFFFF,
                    did, value))
