"""Length-bucketed padded views of string columns.

A whole-column padded view materializes ``n x max_len`` bytes, so one 2KB
outlier in a 16M-row column would cost a ~32GB dense buffer.  Instead, rows
are grouped into power-of-two max-length buckets and each bucket gets its own
dense ``[rows, width]`` view:

- memory is bounded by ``2 * total_bytes + n * MIN_WIDTH`` (each row's bucket
  width is < 2x its length, plus the floor bucket),
- compiled shapes form a small fixed set: widths are powers of two and row
  counts are rounded up to powers of two, so XLA recompiles at most
  O(log(rows) * log(max_len)) kernel variants ever, regardless of data.

The reference has no analog — cuDF kernels walk ragged (chars, offsets)
directly with one thread per row; a dense-lane sweep with bounded padding is
the TPU-idiomatic replacement (VPU lanes want rectangles).

Ops consume buckets through two drivers:

- :func:`map_buckets`: per-row fixed-shape outputs (hashes, parsed numbers,
  validity), scattered back into full-size ``[n, ...]`` arrays.
- :func:`strings_from_buckets`: per-row *string* outputs (each bucket yields
  its own padded result matrix), assembled into one Arrow-layout column.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu.columnar.column import StringColumn, next_pow2

__all__ = [
    "PaddedBucket",
    "length_buckets",
    "padded_buckets",
    "map_buckets",
    "strings_from_buckets",
    "count_subbuckets",
    "class_buckets",
    "map_classes",
]

# Narrowest bucket: one VPU lane register row.  Strings shorter than this
# share a bucket; the padding floor costs at most MIN_WIDTH bytes/row.
MIN_WIDTH = 32


@dataclasses.dataclass
class PaddedBucket:
    """One length class of a string column as a dense byte rectangle.

    ``rows[i]`` is the original row index of ``bytes[i]``.  Rows beyond
    ``n_valid`` are zero-length padding used to round the row count up to a
    power of two; their ``rows`` entry repeats a real index and their
    ``lengths`` entry is 0, so kernels can process them harmlessly and
    scatters drop them (callers scatter with the padded tail masked).
    """

    rows: jnp.ndarray  # int32[n_rows] original row indices
    bytes: jnp.ndarray  # uint8[n_rows, width]
    lengths: jnp.ndarray  # int32[n_rows]
    width: int  # static bucket width (power of two)
    n_valid: int  # count of real rows (<= n_rows)

    @property
    def n_rows(self) -> int:
        return self.bytes.shape[0]

    def valid_mask(self) -> jnp.ndarray:
        """[n_rows] bool: True for real rows, False for the pow2-padding tail."""
        return jnp.arange(self.n_rows, dtype=jnp.int32) < self.n_valid


def _next_pow2_arr(v: np.ndarray) -> np.ndarray:
    """Element-wise next power of two for v >= 1 (exact, no float log)."""
    v = v.astype(np.uint32) - 1
    for s in (1, 2, 4, 8, 16):
        v |= v >> s
    return (v.astype(np.int64)) + 1


def length_buckets(
    lens: np.ndarray,
    min_width: int = 1,
    round_rows: bool = True,
) -> List[Tuple[int, np.ndarray, int]]:
    """Group row indices into power-of-two length classes.

    Returns ``[(width, rows, n_valid), ...]`` ordered by width, where
    ``rows`` is int32 row indices padded up to a power-of-two count by
    repeating the last real index (callers mask the tail with
    ``arange < n_valid``).  Zero-length rows land in the ``min_width``
    bucket.  The shared kernel under both padded_buckets and the nested
    hash walk, so the bucketing rules can't drift apart.
    """
    lens = np.asarray(lens)
    widths = np.maximum(min_width, _next_pow2_arr(np.maximum(lens, 1)))
    out = []
    for w in sorted(set(widths.tolist())):
        rows_np = np.nonzero(widths == w)[0].astype(np.int32)
        n_valid = len(rows_np)
        n_rows = next_pow2(n_valid) if round_rows else n_valid
        if n_rows > n_valid:
            rows_np = np.concatenate(
                [rows_np, np.full(n_rows - n_valid, rows_np[-1], np.int32)]
            )
        out.append((int(w), rows_np, n_valid))
    return out


def count_subbuckets(
    counts: np.ndarray,
    cap: int,
    min_rows: int = 512,
) -> List[Tuple[np.ndarray, int]]:
    """Split one padded bucket's rows into power-of-two *count* classes.

    Second-axis companion to :func:`length_buckets`: a byte-width bucket
    already bounds each row's padded width, but a per-row derived count
    (token count for the JSON machine) can still vary by orders of
    magnitude inside it, and lockstep consumers pay the bucket-wide
    maximum for every row.  Grouping rows by ``next_pow2(counts)`` lets
    each class run with its own capacity, so short rows never pay for the
    longest row's count.

    ``counts``: [n] per-row counts (``0 <= counts[i] <= cap``);
    ``cap``: the bucket-wide capacity (class capacities never exceed it);
    ``min_rows``: classes smaller than this merge into the next class up
    (machine-per-class has fixed overhead, so tiny classes cost more than
    their padding saves).  ``min_rows >= n`` degenerates to one class at
    ``cap`` — the "sub-bucketing off" configuration.

    Returns ``[(rows, class_cap), ...]`` with ascending ``class_cap``;
    every input row appears in exactly one class.  Empty input -> [].
    """
    counts = np.asarray(counts)
    n = len(counts)
    if n == 0:
        return []
    cap = max(int(cap), 1)
    widths = np.minimum(_next_pow2_arr(np.maximum(counts, 1)), cap)
    out: List[Tuple[np.ndarray, int]] = []
    pend: List[np.ndarray] = []
    pend_n = 0
    classes = sorted(set(widths.tolist()))
    for i, w in enumerate(classes):
        rows = np.nonzero(widths == w)[0].astype(np.int64)
        pend.append(rows)
        pend_n += len(rows)
        if pend_n >= min_rows or i == len(classes) - 1:
            out.append((np.sort(np.concatenate(pend)), int(w)))
            pend, pend_n = [], 0
    return out


def class_buckets(
    classes: np.ndarray,
    n_classes: int,
    round_rows: bool = True,
) -> List[Tuple[int, np.ndarray, int]]:
    """Group row indices by an arbitrary small class id (round 20).

    The *value-class* axis of :func:`length_buckets`: where length
    bucketing bounds how much a row's padded width costs, class
    bucketing bounds how much *algorithm* a row pays — e.g.
    float_to_string splits specials / simple integers / full-Ryu
    residue so the 22-iteration shortest-search runs only on rows that
    need it.  Same padding discipline as length_buckets: returns
    ``[(class_id, rows, n_valid), ...]`` with ``rows`` int32 padded up
    to a power-of-two count by repeating the last real index; empty
    classes are omitted.
    """
    classes = np.asarray(classes)
    out: List[Tuple[int, np.ndarray, int]] = []
    for cid in range(n_classes):
        rows_np = np.nonzero(classes == cid)[0].astype(np.int32)
        n_valid = len(rows_np)
        if n_valid == 0:
            continue
        n_rows = next_pow2(n_valid) if round_rows else n_valid
        if n_rows > n_valid:
            rows_np = np.concatenate(
                [rows_np, np.full(n_rows - n_valid, rows_np[-1], np.int32)]
            )
        out.append((cid, rows_np, n_valid))
    return out


def map_classes(
    classes: np.ndarray,
    n_classes: int,
    kernel: Callable,
    out_init: Sequence[Tuple[tuple, jnp.dtype]],
    *,
    row_args: Sequence[jnp.ndarray] = (),
):
    """Run ``kernel(class_id, *row_args_for_class)`` per value class and
    scatter each output back into full-size arrays.

    The class-axis companion of :func:`map_buckets`: ``classes`` is a host
    [n] array of small ids (bucket assignment is host metadata, exactly
    like the offsets sync length bucketing makes), ``kernel`` returns a
    tuple matching ``out_init`` with the class's pow2-padded row count as
    leading dim, and the pow2-padding tail is dropped on scatter.
    """
    n = len(np.asarray(classes))
    outs = [jnp.zeros((n,) + tuple(shape), dtype=dt) for shape, dt in out_init]
    for cid, rows_np, n_valid in class_buckets(classes, n_classes):
        rows = jnp.asarray(rows_np)
        extra = [a[rows] for a in row_args]
        res = kernel(cid, *extra)
        if not isinstance(res, (tuple, list)):
            res = (res,)
        mask = jnp.arange(len(rows_np), dtype=jnp.int32) < n_valid
        tgt = jnp.where(mask, rows, jnp.int32(n))
        for i, r in enumerate(res):
            outs[i] = outs[i].at[tgt].set(r, mode="drop")
    return tuple(outs)


def padded_buckets(
    col: StringColumn,
    min_width: int = MIN_WIDTH,
    round_rows: bool = True,
) -> List[PaddedBucket]:
    """Split ``col`` into power-of-two-width padded buckets.

    Bucket assignment happens on host from the offsets (metadata-sized
    transfer; the same host sync ``.padded()`` already needs for max_len).
    Returns buckets ordered by width; empty column -> empty list.
    """
    n = col.size
    if n == 0:
        return []
    offs = np.asarray(col.offsets)
    lens = (offs[1:] - offs[:-1]).astype(np.int32)
    out: List[PaddedBucket] = []
    starts = jnp.asarray(offs[:-1].astype(np.int32))
    jlens = jnp.asarray(lens)
    chars = col.chars
    nchars = int(chars.shape[0])
    for w, rows_np, n_valid in length_buckets(
        lens, min_width=min_width, round_rows=round_rows
    ):
        n_rows = len(rows_np)
        rows = jnp.asarray(rows_np)
        blens = jnp.where(
            jnp.arange(n_rows, dtype=jnp.int32) < n_valid,
            jlens[rows],
            jnp.int32(0),
        )
        bstarts = starts[rows]
        idx = bstarts[:, None] + jnp.arange(w, dtype=jnp.int32)[None, :]
        in_bounds = jnp.arange(w, dtype=jnp.int32)[None, :] < blens[:, None]
        if nchars == 0:
            gathered = jnp.zeros((n_rows, w), dtype=jnp.uint8)
        else:
            gathered = chars[jnp.clip(idx, 0, nchars - 1)]
        out.append(
            PaddedBucket(
                rows=rows,
                bytes=jnp.where(in_bounds, gathered, jnp.uint8(0)),
                lengths=blens,
                width=int(w),
                n_valid=n_valid,
            )
        )
    return out


def map_buckets(
    col: StringColumn,
    kernel: Callable,
    out_init: Sequence[Tuple[tuple, jnp.dtype]],
    *,
    min_width: int = MIN_WIDTH,
    row_args: Sequence[jnp.ndarray] = (),
):
    """Run ``kernel(bytes, lengths, *row_args_for_bucket)`` per bucket and
    scatter each output back into full-size arrays.

    ``kernel`` must return a tuple of arrays whose leading dim is the bucket
    row count and whose trailing shape/dtype matches ``out_init`` (a list of
    ``(trailing_shape, dtype)``).  ``row_args`` are per-row arrays of the full
    column (e.g. validity) gathered into each bucket before the call.
    Returns the tuple of ``[n, *trailing]`` arrays (zero-filled off-bucket).
    """
    n = col.size
    outs = [jnp.zeros((n,) + tuple(shape), dtype=dt) for shape, dt in out_init]
    for b in padded_buckets(col, min_width=min_width):
        extra = [a[b.rows] for a in row_args]
        res = kernel(b.bytes, b.lengths, *extra)
        if not isinstance(res, (tuple, list)):
            res = (res,)
        # drop the pow2-padding tail: scatter real rows only
        tgt = jnp.where(b.valid_mask(), b.rows, jnp.int32(n))
        for i, r in enumerate(res):
            outs[i] = outs[i].at[tgt].set(r, mode="drop")
    return tuple(outs)


def strings_from_buckets(
    n: int,
    results: Sequence[Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, int]],
    validity: Optional[jnp.ndarray] = None,
) -> StringColumn:
    """Assemble per-bucket padded string results into one StringColumn.

    ``results``: per bucket ``(rows, padded[nb, w], lens[nb], n_valid)`` —
    only the first ``n_valid`` entries of each bucket are real.  Row order of
    the output column follows the original row indices.
    """
    lens_full = jnp.zeros((n,), dtype=jnp.int32)
    for rows, padded, lens, n_valid in results:
        mask = jnp.arange(rows.shape[0], dtype=jnp.int32) < n_valid
        tgt = jnp.where(mask, rows, jnp.int32(n))
        lens_full = lens_full.at[tgt].set(lens.astype(jnp.int32), mode="drop")
    offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(lens_full, dtype=jnp.int32)]
    )
    total = int(offsets[-1])
    # pow2 over-allocation: a bounded set of buffer shapes keeps the
    # backend's per-shape executable cache bounded too (StringColumn
    # contract; logical byte count is offsets[-1])
    cap = next_pow2(total)
    chars = jnp.zeros((cap,), dtype=jnp.uint8)
    for rows, padded, lens, n_valid in results:
        nb, w = padded.shape
        mask = jnp.arange(nb, dtype=jnp.int32) < n_valid
        row_start = jnp.where(mask, offsets[:-1][rows], jnp.int32(cap))
        pos = row_start[:, None] + jnp.arange(w, dtype=jnp.int32)[None, :]
        in_bounds = (
            jnp.arange(w, dtype=jnp.int32)[None, :] < lens[:, None]
        ) & mask[:, None]
        chars = chars.at[jnp.where(in_bounds, pos, cap)].set(
            padded, mode="drop"
        )
    return StringColumn(chars, offsets, validity)
