"""Device-resident Arrow-layout columns as JAX pytrees.

The reference's data model is cuDF's column (device buffer + validity bitmask +
offsets for strings); see SURVEY.md §1 L2.  On TPU, a column is a pytree of JAX
arrays living in HBM:

- fixed-width: ``data[n]`` plus optional ``validity[n]`` (bool; None == all valid).
- strings: ``chars[total_bytes]`` (uint8) + ``offsets[n+1]`` (int32), Arrow layout.
- decimal128: two's-complement pair ``(hi int64, lo uint64)`` per row.  TPUs have no
  native int128; limb form keeps the math in vectorizable 64-bit ops.
- list/struct: offsets + child columns, enough for nested hashing and the timezone
  transition tables.

Validity is an *unpacked* bool vector rather than Arrow's packed bits: the VPU
operates on lanes, and packed-bit twiddling per element would serialize.  Packing
to/from Arrow bitmasks for interchange lives in utils.bitmask.

Vectorized string kernels consume a *padded view*: dense ``bytes[rows, width]``
rectangles the VPU can sweep.  Ops go through columnar/buckets.py, which
length-buckets rows into power-of-two widths so memory stays O(total_bytes)
and one long outlier never pads the whole column; bare ``.padded()`` (whole
column at max_len) remains for small/uniform intermediates only.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu.columnar import dtypes
from spark_rapids_jni_tpu.obs.seam import TRANSFER, instrument
from spark_rapids_jni_tpu.columnar.dtypes import DType, Kind


def _register(cls, data_fields, meta_fields):
    jax.tree_util.register_dataclass(cls, data_fields, meta_fields)
    return cls


@dataclasses.dataclass
class Column:
    """Fixed-width column: data[n] with optional validity[n] (True == valid)."""

    data: jnp.ndarray
    validity: Optional[jnp.ndarray]
    dtype: DType

    def __len__(self) -> int:
        return self.data.shape[0]

    @property
    def size(self) -> int:
        return self.data.shape[0]

    def is_valid(self) -> jnp.ndarray:
        if self.validity is None:
            return jnp.ones((self.size,), dtype=jnp.bool_)
        return self.validity

    def null_count(self) -> int:
        if self.validity is None:
            return 0
        return int(jnp.sum(~self.validity))

    def to_list(self):
        """Host materialization with None for nulls (test/oracle use)."""
        data = np.asarray(self.data)
        if self.dtype.kind == Kind.BOOL:
            vals = [bool(v) for v in data]
        elif self.dtype.kind == Kind.FLOAT64:
            vals = [float(v) for v in data.view(np.float64)]
        elif self.dtype.is_floating:
            vals = [float(v) for v in data]
        else:
            vals = [int(v) for v in data]
        return _apply_nulls(vals, self.validity)


@dataclasses.dataclass
class Decimal128Column:
    """DECIMAL128 column as two's-complement (hi, lo) 64-bit limb pairs."""

    hi: jnp.ndarray  # int64
    lo: jnp.ndarray  # uint64
    validity: Optional[jnp.ndarray]
    dtype: DType  # kind == DECIMAL128, carries precision/scale

    def __len__(self) -> int:
        return self.hi.shape[0]

    @property
    def size(self) -> int:
        return self.hi.shape[0]

    def is_valid(self) -> jnp.ndarray:
        if self.validity is None:
            return jnp.ones((self.size,), dtype=jnp.bool_)
        return self.validity

    def unscaled_to_list(self):
        """Unscaled int128 values (or None), reconstructed on host."""
        hi = np.asarray(self.hi).astype(np.int64)
        lo = np.asarray(self.lo).astype(np.uint64)
        vals = [int(h) * (1 << 64) + int(l) for h, l in zip(hi, lo)]
        return _apply_nulls(vals, self.validity)

    def to_list(self):
        """Decimal values as python fractions of 10**scale (None for nulls)."""
        import decimal as pydec

        scale = self.dtype.scale
        out = []
        for v in self.unscaled_to_list():
            out.append(None if v is None else pydec.Decimal(v).scaleb(-scale))
        return out


@dataclasses.dataclass
class StringColumn:
    """UTF-8 string column: Arrow chars+offsets layout.

    ``chars`` may be OVER-ALLOCATED to a power of two (zero-filled tail):
    constructors quantize the buffer so eager ops over it compile a
    bounded set of shape variants — a long-lived executor seeing
    arbitrary exact char totals would otherwise permanently cache one
    XLA executable per distinct total (soak-tool finding, tools/soak.py).
    The logical byte count is ``offsets[-1]``, never ``chars.shape[0]``.
    """

    chars: jnp.ndarray  # uint8[cap >= total_bytes], pow2 cap
    offsets: jnp.ndarray  # int32[n+1]
    validity: Optional[jnp.ndarray]

    dtype: DType = dtypes.STRING

    def __len__(self) -> int:
        return self.offsets.shape[0] - 1

    @property
    def size(self) -> int:
        return self.offsets.shape[0] - 1

    def is_valid(self) -> jnp.ndarray:
        if self.validity is None:
            return jnp.ones((self.size,), dtype=jnp.bool_)
        return self.validity

    def lengths(self) -> jnp.ndarray:
        return self.offsets[1:] - self.offsets[:-1]

    def max_len(self) -> int:
        """Host-side max byte length (concrete; call outside jit)."""
        if self.size == 0:
            return 0
        return int(jnp.max(self.lengths()))

    def padded(self, max_len: Optional[int] = None) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Dense ``(bytes[n, max_len] uint8, lengths[n] int32)`` view.

        Rows are right-padded with zeros.  ``max_len`` must be static under jit;
        when omitted it is computed on host from the offsets.
        """
        if max_len is None:
            max_len = max(self.max_len(), 1)
        starts = self.offsets[:-1]
        lens = self.lengths()
        idx = starts[:, None] + jnp.arange(max_len, dtype=jnp.int32)[None, :]
        in_bounds = jnp.arange(max_len, dtype=jnp.int32)[None, :] < lens[:, None]
        idx = jnp.clip(idx, 0, max(int(self.chars.shape[0]) - 1, 0))
        if self.chars.shape[0] == 0:
            gathered = jnp.zeros((self.size, max_len), dtype=jnp.uint8)
        else:
            gathered = self.chars[idx]
        return jnp.where(in_bounds, gathered, jnp.uint8(0)), lens

    def to_list(self):
        chars = np.asarray(self.chars)
        offs = np.asarray(self.offsets)
        vals = [
            bytes(chars[offs[i] : offs[i + 1]]).decode("utf-8", errors="surrogatepass")
            for i in range(self.size)
        ]
        return _apply_nulls(vals, self.validity)


@dataclasses.dataclass
class ListColumn:
    """LIST column: offsets[n+1] into a child column."""

    offsets: jnp.ndarray  # int32[n+1]
    child: Any
    validity: Optional[jnp.ndarray]
    dtype: DType = DType(Kind.LIST)

    def __len__(self) -> int:
        return self.offsets.shape[0] - 1

    @property
    def size(self) -> int:
        return self.offsets.shape[0] - 1

    def is_valid(self) -> jnp.ndarray:
        if self.validity is None:
            return jnp.ones((self.size,), dtype=jnp.bool_)
        return self.validity


@dataclasses.dataclass
class StructColumn:
    """STRUCT column: tuple of equal-length children."""

    children: Tuple[Any, ...]
    validity: Optional[jnp.ndarray]
    dtype: DType = DType(Kind.STRUCT)

    def __len__(self) -> int:
        return self.children[0].size

    @property
    def size(self) -> int:
        return self.children[0].size

    def is_valid(self) -> jnp.ndarray:
        if self.validity is None:
            return jnp.ones((self.size,), dtype=jnp.bool_)
        return self.validity


_register(Column, ("data", "validity"), ("dtype",))
_register(Decimal128Column, ("hi", "lo", "validity"), ("dtype",))
_register(StringColumn, ("chars", "offsets", "validity"), ("dtype",))
_register(ListColumn, ("offsets", "child", "validity"), ("dtype",))
_register(StructColumn, ("children", "validity"), ("dtype",))


def _apply_nulls(vals, validity):
    if validity is None:
        return vals
    mask = np.asarray(validity)
    return [v if m else None for v, m in zip(vals, mask)]


def _validity_from(values: Sequence) -> Optional[jnp.ndarray]:
    if any(v is None for v in values):
        return jnp.asarray(np.array([v is not None for v in values], dtype=bool))
    return None


@instrument(TRANSFER, "column")
def column(values: Sequence, dtype: DType) -> Column:
    """Build a fixed-width Column from a python sequence (None == null).

    FLOAT64 columns are stored as their IEEE-754 bit pattern in int64: TPUs
    emulate f64 as float32 pairs (not bit-exact binary64), so Spark-exact double
    semantics are implemented as integer ops over the exact bits.  int64 IS
    exact on TPU (pair-of-u32 emulation via the XLA x64 rewrite).
    """
    zero = False if dtype.kind == Kind.BOOL else 0
    if dtype.kind == Kind.FLOAT64:
        filled = np.array(
            [zero if v is None else v for v in values], dtype=np.float64
        ).view(np.int64)
    else:
        filled = np.array(
            [zero if v is None else v for v in values], dtype=np.dtype(dtype.jnp_dtype)
        )
    return Column(jnp.asarray(filled), _validity_from(values), dtype)


@instrument(TRANSFER, "decimal128_column")
def decimal128_column(
    unscaled: Sequence, precision: int, scale: int
) -> Decimal128Column:
    """Build a Decimal128Column from python-int unscaled values (None == null)."""
    hi = np.zeros(len(unscaled), dtype=np.int64)
    lo = np.zeros(len(unscaled), dtype=np.uint64)
    for i, v in enumerate(unscaled):
        if v is None:
            continue
        v128 = v & ((1 << 128) - 1)  # two's complement
        hi[i] = np.int64(np.uint64((v128 >> 64) & 0xFFFFFFFFFFFFFFFF).astype(np.int64))
        lo[i] = np.uint64(v128 & 0xFFFFFFFFFFFFFFFF)
    return Decimal128Column(
        jnp.asarray(hi),
        jnp.asarray(lo),
        _validity_from(unscaled),
        DType(Kind.DECIMAL128, precision, scale),
    )


def next_pow2(total: int) -> int:
    """Next power of two (min 1): the canonical buffer-capacity quantizer
    — bounds the set of shapes eager ops ever see to ~log2(max) variants
    (StringColumn contract; also used by bucket geometry)."""
    return 1 << max(0, int(total) - 1).bit_length() if total > 1 else 1


@instrument(TRANSFER, "strings_column")
def strings_column(values: Sequence[Optional[str]]) -> StringColumn:
    """Build a StringColumn from python strings (None == null).

    Non-BMP/unpaired-surrogate content is encoded with surrogatepass to match the
    JVM's permissive UTF-8 handling in the reference tests.
    """
    bufs = []
    offsets = [0]
    for v in values:
        b = b"" if v is None else v.encode("utf-8", errors="surrogatepass")
        bufs.append(b)
        offsets.append(offsets[-1] + len(b))
    joined = b"".join(bufs)
    chars = np.zeros((next_pow2(len(joined)),), np.uint8)
    chars[:len(joined)] = np.frombuffer(joined, dtype=np.uint8)
    return StringColumn(
        jnp.asarray(chars),
        jnp.asarray(np.array(offsets, dtype=np.int32)),
        _validity_from(values),
    )


@instrument(TRANSFER, "strings_from_bytes")
def strings_from_bytes(values: Sequence[Optional[bytes]]) -> StringColumn:
    """Build a StringColumn from raw byte strings (None == null)."""
    bufs = []
    offsets = [0]
    for v in values:
        b = b"" if v is None else v
        bufs.append(b)
        offsets.append(offsets[-1] + len(b))
    joined = b"".join(bufs)
    chars = np.zeros((next_pow2(len(joined)),), np.uint8)
    chars[:len(joined)] = np.frombuffer(joined, dtype=np.uint8)
    return StringColumn(
        jnp.asarray(chars),
        jnp.asarray(np.array(offsets, dtype=np.int32)),
        _validity_from(values),
    )


def strings_from_padded(
    padded: jnp.ndarray, lengths: jnp.ndarray, validity=None
) -> StringColumn:
    """Rebuild Arrow layout from a dense padded view (inverse of .padded()).

    Output chars are compacted host-side-free via a jittable gather: positions are
    assigned by an exclusive scan of lengths.
    """
    n, max_len = padded.shape
    lengths = lengths.astype(jnp.int32)
    offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(lengths, dtype=jnp.int32)]
    )
    total = int(offsets[-1])  # concrete only outside jit; see note below
    cap = next_pow2(total)  # bounded shape-variant set (see StringColumn)
    flat_idx = offsets[:-1, None] + jnp.arange(max_len, dtype=jnp.int32)[None, :]
    in_bounds = jnp.arange(max_len, dtype=jnp.int32)[None, :] < lengths[:, None]
    chars = jnp.zeros((cap,), dtype=jnp.uint8)
    chars = chars.at[jnp.where(in_bounds, flat_idx, cap)].set(
        padded, mode="drop", unique_indices=False
    )
    return StringColumn(chars, offsets, validity)
