"""Framed columnar transport encoding for the cross-process data plane.

The reference moves shuffle partitions between executors over UCX with
cuDF's serialized column format (buffer table + metadata header); this
module is the host-side analog the peer-to-peer shuffle (serve/shuffle.py)
speaks over sockets, pipes, or the same-host spool fast path: one
**frame** per message, length-prefixed and CRC32-protected, carrying a
control tuple plus zero or more raw column buffers with their offset
table and dtype/row-count signature.

Frame layout (little-endian)::

    MAGIC(4) | frame_len u32 | crc32 u32 | payload[frame_len]
    payload = header_len u32 | header_json | buf0 bytes | buf1 bytes | ...

``header_json`` = ``{"m": [tag, ...], "b": [[dtype, rows], ...]}`` — the
``m`` list is the control tuple (first element one of the ``FR_*`` tags
below), the ``b`` list the buffer signature, in payload order.  The CRC
covers the whole payload, so a flipped bit in EITHER the control tuple or
a column buffer fails verification; a frame cut short fails the length
check first.  Both failure modes raise :class:`FrameError` with a
machine-readable ``reason`` the transport's retry path keys on.

Like ``serve/rpc.py``'s pipe tuples, the control messages have ONE
declared schema (:data:`MESSAGE_FIELDS`) checked on both sides by the
analyze gate's wire-protocol pass — construct sites build tuples led by
an ``FR_*`` tag, destructure sites unpack under an ``if tag == FR_X``
guard.  A one-sided field drift between the fetch client and the serving
loop is a merge-time finding, not a 3 a.m. incident.
"""

from __future__ import annotations

import json
import struct
import zlib
from typing import Dict, List, Sequence, Tuple

import numpy as np

__all__ = [
    "MAGIC", "PREFIX", "FrameError",
    "FR_FETCH", "FR_DATA", "FR_NACK", "FR_RESULT", "MESSAGE_FIELDS",
    "encode_frame", "decode_frame", "frame_meta",
    "encode_table", "decode_table", "table_nbytes", "table_signature",
    "corrupt_frame", "truncate_frame",
]

MAGIC = b"SRTF"
#: the one definition of the frame prefix layout (magic, frame_len,
#: crc32) — socket readers (serve/shuffle.py) size their prefix reads
#: off this struct, so a format change cannot leave a stale mirror
PREFIX = struct.Struct("<4sII")
_U32 = struct.Struct("<I")

# peer-to-peer shuffle control tags (the socket wire protocol between two
# executors' ShuffleServices, serve/shuffle.py).  Declared exactly like
# serve/rpc.MESSAGE_FIELDS: tag -> field names after the tag, enforced on
# both sides by ci/analyze's wire-protocol pass.
FR_FETCH = "fetch"   # consumer -> producer: send me one partition
FR_DATA = "data"     # producer -> consumer: the partition (buffers ride
#                      the same frame; columns/rows describe them)
FR_NACK = "nack"     # producer -> consumer: can't serve it (reason:
#                      "not_ready" = keep backing off, "gone" = cleaned
#                      up or wrong incarnation — wait for a map update)
FR_RESULT = "rcached"  # result-cache disk tier (plans/rcache.py, round
#                      15): one cached query result at rest — the same
#                      CRC-over-payload framing the shuffle transport
#                      trusts, so a flipped bit in a cold cache file is
#                      a detected drop-and-recompute, never a wrong
#                      answer.  kind = table|array|blob; names lists the
#                      table's columns in buffer order (empty otherwise),
#                      shapes the original array shapes (buffers ride the
#                      frame flattened — frame buffers are 1-D), and key
#                      the FULL cache key's repr: the 32-bit token also
#                      names the file, so colliding tokens share a path
#                      and only the full key proves whose result this is
MESSAGE_FIELDS = {
    FR_FETCH: ("sid", "map_index", "part", "consumer"),
    FR_DATA: ("sid", "map_index", "part", "columns", "rows"),
    FR_NACK: ("sid", "map_index", "part", "reason"),
    FR_RESULT: ("token", "kind", "names", "shapes", "key"),
}


class FrameError(Exception):
    """A frame failed decoding; ``reason`` is one of ``"magic"``,
    ``"truncated"``, ``"crc"``, ``"header"`` — the transport retry path
    records it and re-fetches."""

    def __init__(self, msg: str, reason: str):
        super().__init__(msg)
        self.reason = reason


def encode_frame(meta: Sequence, buffers: Sequence[np.ndarray] = ()) -> bytes:
    """One framed message: control tuple ``meta`` (first element an
    ``FR_*`` tag) plus raw 1-D buffers, CRC32 over the whole payload."""
    bufs = [np.ascontiguousarray(b) for b in buffers]
    header = json.dumps(
        {"m": list(meta), "b": [[str(b.dtype), int(b.shape[0])]
                                for b in bufs]},
        separators=(",", ":")).encode()
    parts = [_U32.pack(len(header)), header]
    parts.extend(b.tobytes() for b in bufs)
    payload = b"".join(parts)
    return PREFIX.pack(MAGIC, len(payload),
                       zlib.crc32(payload) & 0xFFFFFFFF) + payload


def decode_frame(data: bytes) -> Tuple[tuple, List[np.ndarray]]:
    """Inverse of :func:`encode_frame`; raises :class:`FrameError` on any
    damage (bad magic, short read, CRC mismatch, malformed header)."""
    if len(data) < PREFIX.size:
        raise FrameError("frame shorter than its prefix", "truncated")
    magic, frame_len, crc = PREFIX.unpack_from(data)
    if magic != MAGIC:
        raise FrameError(f"bad frame magic {magic!r}", "magic")
    payload = data[PREFIX.size:]
    if len(payload) != frame_len:
        raise FrameError(
            f"frame payload {len(payload)}B, prefix says {frame_len}B",
            "truncated")
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        raise FrameError("frame CRC32 mismatch", "crc")
    try:
        (hlen,) = _U32.unpack_from(payload)
        header = json.loads(payload[_U32.size:_U32.size + hlen])
        meta = tuple(header["m"])
        sigs = header["b"]
    except (struct.error, ValueError, KeyError, TypeError) as e:
        raise FrameError(f"malformed frame header: {e}", "header") from e
    bufs: List[np.ndarray] = []
    off = _U32.size + hlen
    for dtype, rows in sigs:
        dt = np.dtype(dtype)
        nbytes = dt.itemsize * int(rows)
        raw = payload[off:off + nbytes]
        if len(raw) != nbytes:
            raise FrameError(
                f"buffer {dtype}[{rows}] truncated ({len(raw)}B of "
                f"{nbytes}B)", "truncated")
        # .copy(): the frame bytes object is transient transport memory;
        # decoded columns must own their storage
        bufs.append(np.frombuffer(raw, dtype=dt).copy())
        off += nbytes
    return meta, bufs


def frame_meta(data: bytes) -> tuple:
    """Just the control tuple (still CRC-verified — a cheap peek is not
    worth trusting damaged bytes)."""
    return decode_frame(data)[0]


# ----------------------------------------------------------------- tables
# A partition crosses the wire as ONE frame: FR_DATA meta names the
# columns in buffer order, every buffer the same row count — the
# dtype/row-count signature in the header is the geometry the receiver
# validates before concatenating partitions.


def encode_table(meta: Sequence,
                 columns: Dict[str, np.ndarray]) -> bytes:
    """Frame a named column table: ``meta`` must be an ``FR_DATA``-shaped
    tuple whose ``columns`` field lists the names in iteration order and
    whose ``rows`` field is the shared row count."""
    names = sorted(columns)
    rows = {int(columns[n].shape[0]) for n in names}
    if len(rows) > 1:
        raise ValueError(f"ragged partition table: row counts {rows}")
    return encode_frame(meta, [columns[n] for n in names])


def decode_table(meta: tuple, bufs: List[np.ndarray]) -> Dict[str, np.ndarray]:
    """Rebuild the named columns of a decoded FR_DATA frame."""
    names = list(meta[4])
    if len(names) != len(bufs):
        raise FrameError(
            f"FR_DATA names {len(names)} columns, frame carries "
            f"{len(bufs)} buffers", "header")
    return dict(zip(names, bufs))


def table_nbytes(columns: Dict[str, np.ndarray]) -> int:
    return sum(int(v.nbytes) for v in columns.values())


def table_signature(columns: Dict[str, np.ndarray]) -> tuple:
    """(name, dtype, rows) per column, name-sorted — what the consumer
    checks against the map's advertised geometry before concat."""
    return tuple((n, str(columns[n].dtype), int(columns[n].shape[0]))
                 for n in sorted(columns))


# ------------------------------------------------------- chaos primitives
# Applied by the SENDER when obs/faultinj's shuffle-category verdict says
# so: the receiver's integrity checks are the code under test, so the
# damage must genuinely cross the wire.


def corrupt_frame(data: bytes, seed: int = 0) -> bytes:
    """Flip one payload byte (position seeded-deterministic): the CRC
    check on the far side must catch it."""
    if len(data) <= PREFIX.size:
        return data
    pos = PREFIX.size + (seed % (len(data) - PREFIX.size))
    return data[:pos] + bytes([data[pos] ^ 0x40]) + data[pos + 1:]


def truncate_frame(data: bytes, seed: int = 0) -> bytes:
    """Cut the frame short (at least the prefix survives, so the reader
    sees a length mismatch rather than a hang)."""
    if len(data) <= PREFIX.size + 1:
        return data
    keep = PREFIX.size + (seed % (len(data) - PREFIX.size - 1))
    return data[:max(PREFIX.size, keep)]
