"""Fixed-size page pools with row-offset tables for ragged batching.

The executor's micro-batcher concatenates compatible payloads, so every
distinct total row count is its own compiled shape — the pow2 bucket
lattice bounds the variant set per geometry, but heterogeneous traffic
still walks the whole lattice (plan_cache gauges show the miss ramp).
*Ragged Paged Attention* (PAPERS.md) is the TPU-serving answer: requests
of arbitrary length pack into FIXED-SIZE pages, the kernel sees one
rectangular ``[num_pages * page_rows]`` buffer plus per-row bookkeeping,
and the compiled-variant set is bounded by page GEOMETRIES (pow2 page
counts), not request shapes.

This module is the host-side half of that convention:

- :func:`pack_ragged` packs N rider row-arrays contiguously into one
  page-pool buffer (``num_pages`` pow2-quantized), with the row-offset
  table, per-row validity, and per-row rider-id arrays the device kernel
  and the scatter-back need;
- :func:`scatter_ragged` slices a row-aligned result back per rider
  (bit-identical to running each rider alone — padding rows are
  validity-masked, and the fuzz parity test pins it);
- :class:`PagePool` recycles the host-side pack buffers per geometry so
  a steady-state serving tick allocates nothing, with occupancy gauges
  for serve/metrics and the flight recorder.

Split discipline: :func:`split_riders` halves a pack's PAGE COUNT by
partitioning riders into two groups (never splitting a rider mid-pack),
the page-granularity analog of ``split_scan_tables`` — a
``SplitAndRetryOOM`` re-packs each group into half the pages and re-runs;
a rider is never silently dropped.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_jni_tpu.columnar.column import next_pow2

__all__ = [
    "PageGeometry", "PackedPages", "PagePool", "page_pool",
    "geometry_for", "pack_ragged", "scatter_ragged", "split_point",
    "split_riders",
]

#: default rows per page — one VPU-friendly rectangle row block; the
#: serving engine reads the ``serve_page_rows`` flag instead of this
DEFAULT_PAGE_ROWS = 256


@dataclasses.dataclass(frozen=True)
class PageGeometry:
    """The compiled-shape half of a pack: everything a traced program's
    input signature depends on.  ``num_pages`` and ``riders_cap`` are
    pow2-quantized, so the set of geometries a serving tick can produce
    is O(log max_rows * log max_riders) per (page_rows, dtype) — the
    plan-cache key bound the ragged path exists to deliver."""

    page_rows: int   # rows per fixed-size page (config, not data)
    num_pages: int   # pow2 page count covering the packed rows
    riders_cap: int  # pow2 bound on riders sharing the pool
    dtype: str       # row dtype of the packed data buffer

    @property
    def total_rows(self) -> int:
        return self.page_rows * self.num_pages

    def describe(self) -> str:
        return (f"p{self.page_rows}x{self.num_pages}"
                f"r{self.riders_cap}:{self.dtype}")


def geometry_for(total_rows: int, n_riders: int, page_rows: int,
                 dtype: str, *, min_pages: int = 1,
                 min_riders: int = 1) -> PageGeometry:
    """Geometry covering ``total_rows`` packed rows from ``n_riders``
    requests: page count AND rider capacity quantized to pow2, floored at
    ``min_pages``/``min_riders``.  The serving dispatcher floors at its
    STANDING pool size, so every steady-state tick — full or half-empty —
    shares ONE compiled program (padding is validity-masked); the floor
    only drops when a split explicitly halves the page count, so the
    compiled-variant set is bounded by page geometries (O(log) under
    pressure), never by request shapes."""
    page_rows = max(1, int(page_rows))
    pages = next_pow2(max(1, int(min_pages),
                          -(-int(total_rows) // page_rows)))
    riders = next_pow2(max(1, int(min_riders), int(n_riders)))
    return PageGeometry(page_rows, pages, riders, str(dtype))


@dataclasses.dataclass
class PackedPages:
    """One packed tick: the device-bound buffers + host scatter table.

    ``data``/``valid``/``rid`` are flat ``[num_pages * page_rows]``
    arrays (the page-pool calling convention — a kernel may reshape to
    ``[num_pages, page_rows]`` freely, the layout is row-major pages);
    ``offsets[i]:offsets[i+1]`` is rider ``i``'s row span, the scatter
    table.  Padding rows have ``valid=False`` and ``rid=riders_cap`` (an
    out-of-range drop bucket for segment scatters).
    """

    geometry: PageGeometry
    data: np.ndarray      # [total_rows] packed rider rows, zero-padded
    valid: np.ndarray     # bool[total_rows] real-row mask
    rid: np.ndarray       # int32[total_rows] rider index (riders_cap=pad)
    offsets: np.ndarray   # int64[n_riders + 1] rider row offsets
    n_riders: int
    rows_packed: int      # sum of rider lengths (== offsets[-1])

    @property
    def occupancy(self) -> float:
        """Real rows / pool capacity — the launch-efficiency gauge."""
        cap = self.geometry.total_rows
        return self.rows_packed / cap if cap else 0.0


def pack_ragged(rows: Sequence[np.ndarray], page_rows: int,
                pool: Optional["PagePool"] = None, *,
                min_pages: int = 1, min_riders: int = 1) -> PackedPages:
    """Pack rider row-arrays contiguously into one page-pool buffer.

    Riders keep their submit order (``offsets`` indexes them the same
    way), zero-row riders occupy an empty span (offsets[i] == offsets[i+1])
    and still scatter back an empty result — a rider is never dropped.
    All riders must share one dtype (the handler class contract).
    ``min_pages``/``min_riders`` floor the geometry (see
    :func:`geometry_for`).
    """
    if not rows:
        raise ValueError("pack_ragged needs at least one rider")
    arrs = [np.asarray(r) for r in rows]
    dtype = arrs[0].dtype
    for a in arrs:
        if a.dtype != dtype:
            raise ValueError(
                f"riders disagree on dtype: {a.dtype} != {dtype}")
        if a.ndim != 1:
            raise ValueError("pack_ragged packs 1-D row arrays")
    lens = [int(a.shape[0]) for a in arrs]
    offsets = np.zeros(len(arrs) + 1, np.int64)
    np.cumsum(lens, out=offsets[1:])
    total = int(offsets[-1])
    geom = geometry_for(total, len(arrs), page_rows, dtype.name,
                        min_pages=min_pages, min_riders=min_riders)
    cap = geom.total_rows
    if pool is not None:
        data, valid, rid = pool.acquire(geom)
    else:
        data = np.zeros(cap, dtype)
        valid = np.zeros(cap, bool)
        rid = np.full(cap, geom.riders_cap, np.int32)
    try:
        for i, a in enumerate(arrs):
            s, e = int(offsets[i]), int(offsets[i + 1])
            data[s:e] = a
            rid[s:e] = i
        valid[:total] = True
    except BaseException:
        # a mid-pack fault (an incompatible cast a rider smuggled past
        # the dtype check) must hand pooled buffers back, not orphan
        # them from the free list forever
        if pool is not None:
            pool.release(PackedPages(geom, data, valid, rid, offsets,
                                     len(arrs), total))
        raise
    return PackedPages(geom, data, valid, rid, offsets, len(arrs), total)


def scatter_ragged(result: np.ndarray, packed: PackedPages) -> List[np.ndarray]:
    """Slice a ROW-ALIGNED result (leading dim == pool rows) back per
    rider, copying so the pooled buffer can be recycled immediately."""
    result = np.asarray(result)
    if result.shape[0] != packed.geometry.total_rows:
        raise ValueError(
            f"result rows {result.shape[0]} != pool rows "
            f"{packed.geometry.total_rows}")
    out = []
    for i in range(packed.n_riders):
        s, e = int(packed.offsets[i]), int(packed.offsets[i + 1])
        out.append(np.array(result[s:e]))
    return out


def split_point(lens: Sequence[int]) -> int:
    """The rider index that halves a pack's ROWS: riders [0, cut) hold
    roughly half the packed rows, [cut, n) the rest, order preserved and
    each side non-empty.  The ONE cut-point rule shared by
    :func:`split_riders` and the serving dispatcher's request-group
    split (serve/ragged.py) — the two views of a pack must halve at the
    same rider or re-packs and re-groups diverge."""
    half = sum(lens) / 2.0
    acc = 0
    cut = 1  # each group keeps at least one rider
    for i, ln in enumerate(lens[:-1]):
        acc += ln
        cut = i + 1
        if acc >= half:
            break
    return cut


def split_riders(rows: Sequence[np.ndarray]) -> List[List[np.ndarray]]:
    """Halve a pack at PAGE granularity: partition riders into two groups
    of roughly half the packed rows each (rider order preserved, no rider
    ever split mid-pack or dropped).  A single rider cannot halve — the
    caller falls back to its per-request split protocol."""
    if len(rows) <= 1:
        return [list(rows)]
    cut = split_point([int(np.asarray(r).shape[0]) for r in rows])
    return [list(rows[:cut]), list(rows[cut:])]


class PagePool:
    """Reusable host-side pack buffers, one free list per geometry.

    The serving tick packs and scatters on the worker thread; recycling
    the (data, valid, rid) triple means a steady-state tick allocates
    nothing on host.  Bounded per geometry (a traffic spike's buffers
    don't pin memory forever) and fully lock-guarded — gauges are read
    from dump/telemetry threads mid-tick.
    """

    MAX_FREE_PER_GEOMETRY = 4

    def __init__(self):
        self._lock = threading.Lock()
        # geometry -> [(data, valid, rid), ...]  # guarded-by: _lock
        self._free: Dict[PageGeometry, List[Tuple]] = {}
        self._stats: Dict[str, int] = {  # guarded-by: _lock
            "acquires": 0, "reuses": 0, "allocated_bytes": 0,
            "buffers_free": 0,
        }

    def acquire(self, geom: PageGeometry) -> Tuple[np.ndarray, np.ndarray,
                                                   np.ndarray]:
        """A zeroed (data, valid, rid) triple for ``geom`` — recycled
        when a buffer of that geometry is free, else freshly allocated."""
        with self._lock:
            self._stats["acquires"] += 1
            free = self._free.get(geom)
            if free:
                data, valid, rid = free.pop()
                self._stats["reuses"] += 1
                self._stats["buffers_free"] -= 1
            else:
                data = valid = rid = None
        if data is None:
            cap = geom.total_rows
            data = np.zeros(cap, np.dtype(geom.dtype))
            valid = np.zeros(cap, bool)
            rid = np.full(cap, geom.riders_cap, np.int32)
            with self._lock:
                self._stats["allocated_bytes"] += (
                    data.nbytes + valid.nbytes + rid.nbytes)
        else:
            data[:] = 0
            valid[:] = False
            rid[:] = geom.riders_cap
        return data, valid, rid

    def release(self, packed: PackedPages) -> None:
        """Return a pack's buffers to the free list (drop past the per-
        geometry bound — spike buffers are not pinned forever)."""
        with self._lock:
            free = self._free.setdefault(packed.geometry, [])
            if len(free) < self.MAX_FREE_PER_GEOMETRY:
                free.append((packed.data, packed.valid, packed.rid))
                self._stats["buffers_free"] += 1

    def gauges(self) -> Dict[str, int]:
        """JSON-able pool stats for serve/metrics + flight telemetry."""
        with self._lock:
            g = dict(self._stats)
            g["geometries"] = len(self._free)
            return g

    def clear(self) -> None:
        with self._lock:
            self._free.clear()
            self._stats["buffers_free"] = 0


#: the process-global pool every ragged dispatcher shares (like the plan
#: cache: one resident set, one gauge surface)
page_pool = PagePool()
