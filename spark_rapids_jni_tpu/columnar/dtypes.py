"""Spark SQL type descriptors for columnar data.

The reference operates on cuDF's type system (cudf::data_type); the Spark plugin maps
Spark SQL types onto it.  We keep a small, explicit descriptor so ops can implement
Spark-exact semantics (sign extension widths, decimal precision/scale, hash byte
widths) without depending on a host dataframe library.
"""

from __future__ import annotations

import dataclasses
import enum

import jax.numpy as jnp


class Kind(enum.Enum):
    BOOL = "bool"
    INT8 = "int8"
    INT16 = "int16"
    INT32 = "int32"
    INT64 = "int64"
    UINT8 = "uint8"
    UINT64 = "uint64"
    FLOAT32 = "float32"
    FLOAT64 = "float64"
    STRING = "string"
    # Days since unix epoch, int32 (Spark DateType).
    DATE32 = "date32"
    # Microseconds since unix epoch, int64 (Spark TimestampType).
    TIMESTAMP_MICROS = "timestamp[us]"
    TIMESTAMP_MILLIS = "timestamp[ms]"
    TIMESTAMP_SECONDS = "timestamp[s]"
    # Unscaled value in an int32/int64/(int64 hi, uint64 lo) pair; see DType.precision.
    DECIMAL32 = "decimal32"
    DECIMAL64 = "decimal64"
    DECIMAL128 = "decimal128"
    # Nested types (children carried by the column, not the dtype).
    LIST = "list"
    STRUCT = "struct"


_JNP = {
    Kind.BOOL: jnp.bool_,
    Kind.INT8: jnp.int8,
    Kind.INT16: jnp.int16,
    Kind.INT32: jnp.int32,
    Kind.INT64: jnp.int64,
    Kind.UINT8: jnp.uint8,
    Kind.UINT64: jnp.uint64,
    Kind.FLOAT32: jnp.float32,
    Kind.FLOAT64: jnp.float64,
    Kind.DATE32: jnp.int32,
    Kind.TIMESTAMP_MICROS: jnp.int64,
    Kind.TIMESTAMP_MILLIS: jnp.int64,
    Kind.TIMESTAMP_SECONDS: jnp.int64,
    Kind.DECIMAL32: jnp.int32,
    Kind.DECIMAL64: jnp.int64,
}

_WIDTH = {
    Kind.BOOL: 1,
    Kind.INT8: 1,
    Kind.INT16: 2,
    Kind.INT32: 4,
    Kind.INT64: 8,
    Kind.UINT8: 1,
    Kind.UINT64: 8,
    Kind.FLOAT32: 4,
    Kind.FLOAT64: 8,
    Kind.DATE32: 4,
    Kind.TIMESTAMP_MICROS: 8,
    Kind.TIMESTAMP_MILLIS: 8,
    Kind.TIMESTAMP_SECONDS: 8,
    Kind.DECIMAL32: 4,
    Kind.DECIMAL64: 8,
    Kind.DECIMAL128: 16,
}

# Spark's max decimal precision (matches reference decimal_utils.cu overflow rules).
MAX_DECIMAL_PRECISION = 38
MAX_DECIMAL64_PRECISION = 18
MAX_DECIMAL32_PRECISION = 9


@dataclasses.dataclass(frozen=True)
class DType:
    """A Spark SQL data type. Hashable and static (usable as a pytree aux leaf)."""

    kind: Kind
    precision: int = 0  # decimals only
    scale: int = 0  # decimals only

    @property
    def is_decimal(self) -> bool:
        return self.kind in (Kind.DECIMAL32, Kind.DECIMAL64, Kind.DECIMAL128)

    @property
    def is_floating(self) -> bool:
        return self.kind in (Kind.FLOAT32, Kind.FLOAT64)

    @property
    def is_integral(self) -> bool:
        return self.kind in (Kind.INT8, Kind.INT16, Kind.INT32, Kind.INT64)

    @property
    def fixed_width(self) -> int:
        """Byte width in the JCUDF row format (row_conversion); 0 for variable."""
        return _WIDTH.get(self.kind, 0)

    @property
    def jnp_dtype(self):
        return _JNP[self.kind]

    def __repr__(self) -> str:
        if self.is_decimal:
            return f"DType({self.kind.value}({self.precision},{self.scale}))"
        return f"DType({self.kind.value})"


def decimal(precision: int, scale: int) -> DType:
    """Spark decimal type, stored like cuDF picks storage by precision."""
    if precision <= MAX_DECIMAL32_PRECISION:
        kind = Kind.DECIMAL32
    elif precision <= MAX_DECIMAL64_PRECISION:
        kind = Kind.DECIMAL64
    else:
        kind = Kind.DECIMAL128
    return DType(kind, precision, scale)


BOOL = DType(Kind.BOOL)
INT8 = DType(Kind.INT8)
INT16 = DType(Kind.INT16)
INT32 = DType(Kind.INT32)
INT64 = DType(Kind.INT64)
UINT8 = DType(Kind.UINT8)
UINT64 = DType(Kind.UINT64)
FLOAT32 = DType(Kind.FLOAT32)
FLOAT64 = DType(Kind.FLOAT64)
STRING = DType(Kind.STRING)
DATE32 = DType(Kind.DATE32)
TIMESTAMP_MICROS = DType(Kind.TIMESTAMP_MICROS)
TIMESTAMP_MILLIS = DType(Kind.TIMESTAMP_MILLIS)
TIMESTAMP_SECONDS = DType(Kind.TIMESTAMP_SECONDS)
