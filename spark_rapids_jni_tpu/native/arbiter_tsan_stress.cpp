// ThreadSanitizer stress driver for the task arbiter state machine.
//
// The reference runs its Java suite under NVIDIA compute-sanitizer
// (pom.xml:219-265 test-with-sanitizer profile); the arbiter's C++ analog
// tier is this standalone binary: N dedicated task threads + shuffle
// threads drive the full retry protocol against a tiny atomic budget with
// injected OOMs and a deadlock watchdog, compiled together with
// task_arbiter.cpp under -fsanitize=thread.  Any data race in the state
// machine surfaces as a TSAN report (non-zero exit via halt_on_error).
//
// Build & run (tests/test_native_sanitizer.py):
//   g++ -std=c++17 -O1 -fsanitize=thread -o arbiter_tsan_stress \
//       arbiter_tsan_stress.cpp task_arbiter.cpp -lpthread
//   TSAN_OPTIONS=halt_on_error=1 ./arbiter_tsan_stress <tasks> <iters>

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <thread>
#include <vector>

extern "C" {
void* arbiter_create(char const* log_path);
void arbiter_destroy(void* h);
int arbiter_start_dedicated_task_thread(void* h, int64_t tid, int64_t task_id);
int arbiter_pool_thread_working_on_task(void* h, int64_t tid, int64_t task_id,
                                        int is_shuffle);
int arbiter_remove_thread_association(void* h, int64_t tid, int64_t task_id);
int arbiter_task_done(void* h, int64_t task_id);
int arbiter_start_retry_block(void* h, int64_t tid);
int arbiter_end_retry_block(void* h, int64_t tid);
int arbiter_force_retry_oom(void* h, int64_t tid, int num, int filter, int skip);
int arbiter_pre_alloc(void* h, int64_t tid, int is_cpu, int blocking);
int arbiter_post_alloc_success(void* h, int64_t tid, int is_cpu, int was_recursive);
int arbiter_post_alloc_failed(void* h, int64_t tid, int is_cpu, int is_oom,
                              int blocking, int was_recursive);
int arbiter_dealloc(void* h, int64_t tid, int is_cpu);
int arbiter_block_thread_until_ready(void* h, int64_t tid);
int arbiter_check_and_break_deadlocks(void* h);
int64_t arbiter_get_and_reset_metric(void* h, int64_t task_id, int which);
int64_t arbiter_get_total_blocked_or_bufn(void* h);
}

namespace {

std::atomic<long> g_budget{1 << 20};
std::atomic<long> g_retries{0};
std::atomic<bool> g_stop{false};
std::atomic<int> g_failures{0};

bool try_reserve(long n)
{
  long cur = g_budget.load();
  while (cur >= n) {
    if (g_budget.compare_exchange_weak(cur, cur - n)) { return true; }
  }
  return false;
}

// One allocation through the full protocol; returns false on hard failure.
bool alloc_one(void* arb, int64_t tid, long size)
{
  while (true) {
    int code = arbiter_pre_alloc(arb, tid, /*is_cpu=*/0, /*blocking=*/1);
    if (code < 0) {
      if (code == -1 || code == -2) {  // retry / split-and-retry signal
        g_retries.fetch_add(1);
        arbiter_block_thread_until_ready(arb, tid);
        size = size > 1 ? size / 2 : 1;
        continue;
      }
      return false;
    }
    if (try_reserve(size)) {
      arbiter_post_alloc_success(arb, tid, 0, code == 1);
      g_budget.fetch_add(size);  // immediately release budget (dealloc below
      arbiter_dealloc(arb, tid, 0);  // wakes the next blocked thread)
      return true;
    }
    int retryable = arbiter_post_alloc_failed(arb, tid, 0, /*is_oom=*/1,
                                              /*blocking=*/1, code == 1);
    if (retryable < 0) {
      if (retryable == -1 || retryable == -2) {
        g_retries.fetch_add(1);
        size = size > 1 ? size / 2 : 1;
        continue;
      }
      return false;
    }
    if (!retryable) { return false; }
  }
}

void task_thread(void* arb, int64_t task_id, int iters)
{
  int64_t tid = static_cast<int64_t>(
    std::hash<std::thread::id>{}(std::this_thread::get_id()) & 0x7FFFFFFF);
  arbiter_start_dedicated_task_thread(arb, tid, task_id);
  arbiter_start_retry_block(arb, tid);
  if ((task_id % 3) == 0) {
    arbiter_force_retry_oom(arb, tid, 2, /*GPU*/ 2, /*skip=*/3);
  }
  for (int i = 0; i < iters; ++i) {
    long size = 1 + ((task_id * 7919 + i * 104729) % (1 << 18));
    if (!alloc_one(arb, tid, size)) {
      g_failures.fetch_add(1);
      break;
    }
  }
  arbiter_end_retry_block(arb, tid);
  arbiter_task_done(arb, task_id);
  arbiter_remove_thread_association(arb, tid, task_id);
}

void shuffle_thread(void* arb, int n_tasks, int iters)
{
  int64_t tid = static_cast<int64_t>(
    std::hash<std::thread::id>{}(std::this_thread::get_id()) & 0x7FFFFFFF);
  for (int64_t t = 0; t < n_tasks; ++t) {
    arbiter_pool_thread_working_on_task(arb, tid, t, /*is_shuffle=*/1);
  }
  for (int i = 0; i < iters && !g_stop.load(); ++i) {
    if (!alloc_one(arb, tid, 4096)) {
      g_failures.fetch_add(1);
      break;
    }
  }
  arbiter_remove_thread_association(arb, tid, -1);
}

void watchdog(void* arb)
{
  while (!g_stop.load()) {
    arbiter_check_and_break_deadlocks(arb);
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
  }
}

}  // namespace

int main(int argc, char** argv)
{
  int n_tasks = argc > 1 ? std::atoi(argv[1]) : 8;
  int iters   = argc > 2 ? std::atoi(argv[2]) : 200;

  void* arb = arbiter_create(nullptr);
  std::thread dog(watchdog, arb);
  std::vector<std::thread> threads;
  for (int t = 0; t < n_tasks; ++t) {
    threads.emplace_back(task_thread, arb, static_cast<int64_t>(t), iters);
  }
  threads.emplace_back(shuffle_thread, arb, n_tasks, iters);
  threads.emplace_back(shuffle_thread, arb, n_tasks, iters);
  for (auto& t : threads) { t.join(); }
  g_stop.store(true);
  dog.join();

  int64_t blocked = arbiter_get_total_blocked_or_bufn(arb);
  std::printf("tasks=%d iters=%d retries=%ld failures=%d blocked_at_end=%ld\n",
              n_tasks, iters, g_retries.load(), g_failures.load(), blocked);
  arbiter_destroy(arb);
  if (g_failures.load() != 0 || blocked != 0) { return 2; }
  return 0;
}
