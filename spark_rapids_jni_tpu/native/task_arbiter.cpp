// Multi-tenant memory-governance state machine (native core).
//
// Re-expression of the reference's SparkResourceAdaptorJni.cpp (2171 LoC): the
// arbiter that lets N concurrent partition tasks share one accelerator's
// memory with priority-based blocking, Block-Until-Further-Notice escalation,
// split-and-retry signaling, deadlock breaking, failure injection and
// per-task metrics.  Design mapping (file:line refer to the reference):
//
// - thread_state enum            <- SparkResourceAdaptorJni.cpp:75-95
// - thread_priority              <- :135-190 (lower task id = higher priority,
//                                  non-task threads highest via task_id -1)
// - block_thread_until_ready     <- :1036-1110
// - pre_alloc / injection        <- :1236-1324
// - post_alloc_success/failed    <- :1336,:1685-1729
// - dealloc (ALLOC->ALLOC_FREE + wake) <- :1754-1788
// - wake_next_highest_priority_blocked <- :1379-1483
// - is_in_deadlock two-pass      <- :1506-1591
// - check_and_update_for_bufn    <- :1598-1672
// - 500-retry livelock cap       <- :982-993
// - CSV transition log           <- :116-133,:396-399,:897-919
// - task_metrics checkpointing   <- :197-227,:960-976
//
// Differences from the reference, forced by the platform:
// - No JNI: a C API consumed via ctypes; exceptions become negative return
//   codes the Python layer re-raises as the RetryOOM hierarchy.
// - Thread ids are passed in explicitly (Python threading idents) instead of
//   pthread_self(), so the GIL-holding thread mapping stays explicit.
// - The JVM ThreadStateRegistry.isThreadBlocked callback (used so the
//   deadlock detector can see JVM-level blocking, :42-73) becomes an
//   "externally blocked" flag the host sets per thread.
// - Allocation interception: on TPU the governed resource is batch admission
//   into an HBM budget rather than malloc; the Python governor drives the
//   same pre_alloc/post_alloc/dealloc protocol around budget reservations.

#include <algorithm>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <functional>
#include <limits>
#include <map>
#include <memory>
#include <mutex>
#include <set>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

// ---- return codes (mirrored in python mem.exceptions) ----
enum arbiter_code : int {
  ARB_OK                 = 0,
  ARB_RECURSIVE          = 1,  // pre_alloc: recursive (spill) allocation
  ARB_GPU_RETRY_OOM      = -1,
  ARB_GPU_SPLIT_RETRY    = -2,
  ARB_CPU_RETRY_OOM      = -3,
  ARB_CPU_SPLIT_RETRY    = -4,
  ARB_INJECTED_EXCEPTION = -5,
  ARB_OOM                = -6,  // real OOM / livelock limit
  ARB_THREAD_REMOVED     = -7,
  ARB_INVALID            = -8,
  ARB_INTERNAL           = -9,
};

namespace {

enum class thread_state : int {
  UNKNOWN       = -1,
  RUNNING       = 0,
  ALLOC         = 1,
  ALLOC_FREE    = 2,
  BLOCKED       = 3,
  BUFN_THROW    = 4,
  BUFN_WAIT     = 5,
  BUFN          = 6,
  SPLIT_THROW   = 7,
  REMOVE_THROW  = 8,
};

const char* as_str(thread_state s)
{
  switch (s) {
    case thread_state::RUNNING: return "THREAD_RUNNING";
    case thread_state::ALLOC: return "THREAD_ALLOC";
    case thread_state::ALLOC_FREE: return "THREAD_ALLOC_FREE";
    case thread_state::BLOCKED: return "THREAD_BLOCKED";
    case thread_state::BUFN_THROW: return "THREAD_BUFN_THROW";
    case thread_state::BUFN_WAIT: return "THREAD_BUFN_WAIT";
    case thread_state::BUFN: return "THREAD_BUFN";
    case thread_state::SPLIT_THROW: return "THREAD_SPLIT_THROW";
    case thread_state::REMOVE_THROW: return "THREAD_REMOVE_THROW";
    default: return "UNKNOWN";
  }
}

thread_local std::string g_last_error;

struct arb_exception {  // internal control-flow signal -> return code
  int code;
  std::string msg;
};

[[noreturn]] void throw_code(int code, std::string msg)
{
  throw arb_exception{code, std::move(msg)};
}

class thread_priority {
 public:
  thread_priority(int64_t tsk, int64_t thr) : task_id(tsk), thread_id(thr) {}
  int64_t get_thread_id() const { return thread_id; }
  bool operator<(thread_priority const& o) const
  {
    int64_t const a = task_priority(), b = o.task_priority();
    return a < b || (a == b && thread_id < o.thread_id);
  }

 private:
  int64_t task_id;
  int64_t thread_id;
  int64_t task_priority() const
  {
    return std::numeric_limits<int64_t>::max() - (task_id + 1);
  }
};

struct task_metrics {
  int64_t num_times_retry_throw       = 0;
  int64_t num_times_split_retry_throw = 0;
  int64_t time_blocked_nanos          = 0;
  int64_t time_lost_nanos             = 0;  // compute time lost to retry

  void add(task_metrics const& o)
  {
    num_times_retry_throw += o.num_times_retry_throw;
    num_times_split_retry_throw += o.num_times_split_retry_throw;
    time_blocked_nanos += o.time_blocked_nanos;
    time_lost_nanos += o.time_lost_nanos;
  }
  void take_from(task_metrics& o)
  {
    add(o);
    o.clear();
  }
  void clear() { *this = task_metrics(); }
};

int64_t now_ns()
{
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
           std::chrono::steady_clock::now().time_since_epoch())
    .count();
}

struct oom_injection {
  int skip_count = 0;
  int hit_count  = 0;
  int oom_filter = 0;  // 0 none, 1 CPU, 2 GPU, 3 both (OomInjectionType)
  bool matches(bool is_for_cpu) const
  {
    return (is_for_cpu && (oom_filter & 1)) || (!is_for_cpu && (oom_filter & 2));
  }
};

struct full_thread_state {
  thread_state state = thread_state::UNKNOWN;
  int64_t thread_id  = -1;
  int64_t task_id    = -1;  // -1 == pool/shuffle thread
  std::set<int64_t> pool_task_ids;
  bool is_cpu_alloc = false;
  // pool-blocked tracking (submittingToPool/waitingOnPool :344-399)
  bool pool_blocked = false;
  // host-set analog of ThreadStateRegistry.isThreadBlocked
  bool externally_blocked = false;

  oom_injection retry_oom;
  oom_injection split_and_retry_oom;
  int cudf_exception_injected = 0;
  int num_times_retried       = 0;  // livelock cap counter

  task_metrics metrics;
  int64_t block_start      = 0;
  int64_t retry_start      = 0;  // for lost-compute accounting

  std::unique_ptr<std::condition_variable> wake_condition =
    std::make_unique<std::condition_variable>();

  thread_priority priority() const { return thread_priority(task_id, thread_id); }

  void before_block() { block_start = now_ns(); }
  void after_block()
  {
    metrics.time_blocked_nanos += now_ns() - block_start;
    retry_start = now_ns();
  }
  void record_failed_retry_time()
  {
    if (retry_start != 0) {
      metrics.time_lost_nanos += now_ns() - retry_start;
      retry_start = now_ns();
    }
  }
};

class task_arbiter {
 public:
  explicit task_arbiter(char const* log_path)
  {
    if (log_path != nullptr && std::strlen(log_path) > 0) {
      if (std::strcmp(log_path, "stderr") == 0) {
        log_ = stderr;
      } else if (std::strcmp(log_path, "stdout") == 0) {
        log_ = stdout;
      } else {
        log_       = std::fopen(log_path, "w");
        owns_log_ = log_ != nullptr;
      }
      if (log_ != nullptr) {
        std::fprintf(log_, "time,op,current thread,op thread,op task,from state,to state,notes\n");
      }
    }
  }

  ~task_arbiter()
  {
    if (owns_log_ && log_ != nullptr) { std::fclose(log_); }
  }

  // ---- registration -------------------------------------------------------

  void start_dedicated_task_thread(int64_t thread_id, int64_t task_id)
  {
    std::unique_lock<std::mutex> lock(mutex_);
    auto found = threads_.find(thread_id);
    if (found != threads_.end()) {
      if (found->second.task_id != task_id) {
        remove_thread_association_core(found->second, -1, lock);
      } else {
        return;
      }
    }
    auto& st     = threads_[thread_id];
    st.thread_id = thread_id;
    st.task_id   = task_id;
    st.state     = thread_state::RUNNING;
    log_transition(thread_id, task_id, thread_state::UNKNOWN, thread_state::RUNNING);
  }

  void pool_thread_working_on_task(int64_t thread_id, int64_t task_id, bool is_shuffle)
  {
    std::unique_lock<std::mutex> lock(mutex_);
    auto& st = threads_[thread_id];
    if (st.state == thread_state::UNKNOWN) {
      st.thread_id = thread_id;
      st.task_id   = -1;
      st.state     = thread_state::RUNNING;
      log_transition(thread_id, -1, thread_state::UNKNOWN, thread_state::RUNNING);
    }
    (void)is_shuffle;  // shuffle threads are pool threads: task_id -1 == top priority
    st.pool_task_ids.insert(task_id);
  }

  void pool_thread_finished_for_task(int64_t thread_id, int64_t task_id)
  {
    std::unique_lock<std::mutex> lock(mutex_);
    auto found = threads_.find(thread_id);
    if (found == threads_.end()) { return; }
    found->second.pool_task_ids.erase(task_id);
    if (found->second.pool_task_ids.empty()) {
      remove_thread_association_core(found->second, -1, lock);
    }
  }

  void remove_thread_association(int64_t thread_id, int64_t task_id)
  {
    std::unique_lock<std::mutex> lock(mutex_);
    auto found = threads_.find(thread_id);
    if (found != threads_.end()) {
      remove_thread_association_core(found->second, task_id, lock);
    }
  }

  void task_done(int64_t task_id)
  {
    std::unique_lock<std::mutex> lock(mutex_);
    std::vector<int64_t> to_remove;
    for (auto& [tid, st] : threads_) {
      if (st.task_id == task_id) {
        to_remove.push_back(tid);
      } else {
        st.pool_task_ids.erase(task_id);
        if (st.task_id < 0 && st.pool_task_ids.empty()) { to_remove.push_back(tid); }
      }
    }
    for (auto tid : to_remove) {
      auto found = threads_.find(tid);
      if (found != threads_.end()) {
        remove_thread_association_core(found->second, -1, lock);
      }
    }
    task_to_metrics_.erase(task_id);  // task complete; metrics were read
  }

  void set_pool_blocked(int64_t thread_id, bool blocked)
  {
    std::unique_lock<std::mutex> lock(mutex_);
    auto found = threads_.find(thread_id);
    if (found != threads_.end()) { found->second.pool_blocked = blocked; }
    if (!blocked) { task_has_woken_.notify_all(); }
  }

  void set_externally_blocked(int64_t thread_id, bool blocked)
  {
    std::unique_lock<std::mutex> lock(mutex_);
    auto found = threads_.find(thread_id);
    if (found != threads_.end()) { found->second.externally_blocked = blocked; }
  }

  // ---- retry blocks / injection ------------------------------------------

  void start_retry_block(int64_t thread_id)
  {
    std::unique_lock<std::mutex> lock(mutex_);
    auto found = threads_.find(thread_id);
    if (found != threads_.end()) { found->second.retry_start = now_ns(); }
  }

  void end_retry_block(int64_t thread_id)
  {
    std::unique_lock<std::mutex> lock(mutex_);
    auto found = threads_.find(thread_id);
    if (found != threads_.end()) {
      found->second.retry_start       = 0;
      found->second.num_times_retried = 0;
    }
  }

  void force_retry_oom(int64_t thread_id, int num_ooms, int oom_filter, int skip_count)
  {
    std::unique_lock<std::mutex> lock(mutex_);
    auto& st                    = get_thread(thread_id);
    st.retry_oom.hit_count      = num_ooms;
    st.retry_oom.skip_count     = skip_count;
    st.retry_oom.oom_filter     = oom_filter;
  }

  void force_split_and_retry_oom(int64_t thread_id, int num_ooms, int oom_filter, int skip_count)
  {
    std::unique_lock<std::mutex> lock(mutex_);
    auto& st                             = get_thread(thread_id);
    st.split_and_retry_oom.hit_count     = num_ooms;
    st.split_and_retry_oom.skip_count    = skip_count;
    st.split_and_retry_oom.oom_filter    = oom_filter;
  }

  void force_cudf_exception(int64_t thread_id, int num_times)
  {
    std::unique_lock<std::mutex> lock(mutex_);
    get_thread(thread_id).cudf_exception_injected = num_times;
  }

  // ---- alloc protocol -----------------------------------------------------

  int pre_alloc(int64_t thread_id, bool is_for_cpu, bool blocking)
  {
    std::unique_lock<std::mutex> lock(mutex_);
    auto const thread = threads_.find(thread_id);
    if (thread != threads_.end()) {
      switch (thread->second.state) {
        case thread_state::ALLOC:
        case thread_state::ALLOC_FREE:
          // recursive allocation (spill inside alloc) (:1244-1261)
          if (is_for_cpu && blocking) {
            throw_code(ARB_INVALID,
                       "blocking admission request from thread " +
                         std::to_string(thread_id) + " rejected: thread is mid-allocation (" +
                         as_str(thread->second.state) + ")");
          }
          return ARB_RECURSIVE;
        default: break;
      }

      auto& st = thread->second;
      if (st.retry_oom.matches(is_for_cpu)) {
        if (st.retry_oom.skip_count > 0) {
          st.retry_oom.skip_count--;
        } else if (st.retry_oom.hit_count > 0) {
          st.retry_oom.hit_count--;
          st.metrics.num_times_retry_throw++;
          log_status(is_for_cpu ? "INJECTED_RETRY_OOM_CPU" : "INJECTED_RETRY_OOM_GPU",
                     thread_id, st.task_id, st.state);
          st.record_failed_retry_time();
          throw_code(is_for_cpu ? ARB_CPU_RETRY_OOM : ARB_GPU_RETRY_OOM, "fault injection: forced retry OOM");
        }
      }
      if (st.cudf_exception_injected > 0) {
        st.cudf_exception_injected--;
        log_status("INJECTED_EXCEPTION", thread_id, st.task_id, st.state);
        st.record_failed_retry_time();
        throw_code(ARB_INJECTED_EXCEPTION, "injected framework exception");
      }
      if (st.split_and_retry_oom.matches(is_for_cpu)) {
        if (st.split_and_retry_oom.skip_count > 0) {
          st.split_and_retry_oom.skip_count--;
        } else if (st.split_and_retry_oom.hit_count > 0) {
          st.split_and_retry_oom.hit_count--;
          st.metrics.num_times_split_retry_throw++;
          log_status(is_for_cpu ? "INJECTED_SPLIT_AND_RETRY_OOM_CPU"
                                : "INJECTED_SPLIT_AND_RETRY_OOM_GPU",
                     thread_id, st.task_id, st.state);
          st.record_failed_retry_time();
          throw_code(is_for_cpu ? ARB_CPU_SPLIT_RETRY : ARB_GPU_SPLIT_RETRY,
                     "fault injection: forced split-and-retry OOM");
        }
      }

      if (blocking) { block_thread_until_ready_core(thread_id, lock); }

      auto const again = threads_.find(thread_id);
      if (again == threads_.end()) { return ARB_OK; }
      switch (again->second.state) {
        case thread_state::RUNNING:
          transition(again->second, thread_state::ALLOC);
          again->second.is_cpu_alloc = is_for_cpu;
          break;
        default:
          throw_code(ARB_INVALID,
                     "admission precheck: thread " + std::to_string(thread_id) +
                       " cannot start an allocation from state " + as_str(again->second.state));
      }
    }
    return ARB_OK;
  }

  void post_alloc_success(int64_t thread_id, bool is_for_cpu, bool was_recursive)
  {
    std::unique_lock<std::mutex> lock(mutex_);
    auto const thread = threads_.find(thread_id);
    if (!was_recursive && thread != threads_.end()) {
      switch (thread->second.state) {
        case thread_state::ALLOC:
        case thread_state::ALLOC_FREE:
          transition(thread->second, thread_state::RUNNING);
          thread->second.is_cpu_alloc = false;
          break;
        default: break;
      }
      wake_next_highest_priority_blocked(lock, false, is_for_cpu);
    }
  }

  bool post_alloc_failed(
    int64_t thread_id, bool is_for_cpu, bool is_oom, bool blocking, bool was_recursive)
  {
    std::unique_lock<std::mutex> lock(mutex_);
    auto const thread = threads_.find(thread_id);
    bool ret          = true;
    if (!was_recursive && thread != threads_.end()) {
      if (thread->second.is_cpu_alloc != is_for_cpu) {
        throw_code(ARB_INVALID,
                   "thread " + std::to_string(thread_id) +
                     " has a mismatch on CPU vs GPU post alloc");
      }
      switch (thread->second.state) {
        case thread_state::ALLOC_FREE:
          transition(thread->second, thread_state::RUNNING);
          break;
        case thread_state::ALLOC:
          if (is_oom && blocking) {
            transition(thread->second, thread_state::BLOCKED);
          } else {
            transition(thread->second, thread_state::RUNNING);
          }
          break;
        default:
          throw_code(ARB_INTERNAL, "unexpected state after alloc failed");
      }
    } else {
      ret = false;
    }
    check_and_update_for_bufn(lock);
    return ret;
  }

  void dealloc(int64_t thread_id, bool is_for_cpu)
  {
    std::unique_lock<std::mutex> lock(mutex_);
    auto const thread = threads_.find(thread_id);
    if (thread != threads_.end()) {
      log_status("DEALLOC", thread_id, thread->second.task_id, thread->second.state);
    } else {
      log_status("DEALLOC", thread_id, -2, thread_state::UNKNOWN);
    }
    for (auto& [tid, st] : threads_) {
      if (tid != thread_id && st.state == thread_state::ALLOC &&
          st.is_cpu_alloc == is_for_cpu) {
        transition(st, thread_state::ALLOC_FREE);
      }
    }
    wake_next_highest_priority_blocked(lock, true, is_for_cpu);
  }

  int block_thread_until_ready(int64_t thread_id)
  {
    std::unique_lock<std::mutex> lock(mutex_);
    block_thread_until_ready_core(thread_id, lock);
    return ARB_OK;
  }

  void check_and_break_deadlocks()
  {
    std::unique_lock<std::mutex> lock(mutex_);
    check_and_update_for_bufn(lock);
  }

  // ---- introspection / metrics -------------------------------------------

  int get_state_of(int64_t thread_id)
  {
    std::unique_lock<std::mutex> lock(mutex_);
    auto const found = threads_.find(thread_id);
    return found == threads_.end() ? -1 : static_cast<int>(found->second.state);
  }

  int64_t get_and_reset_metric(int64_t task_id, int which)
  {
    std::unique_lock<std::mutex> lock(mutex_);
    // fold live thread metrics into the task accumulator first
    for (auto& [tid, st] : threads_) {
      if (st.task_id == task_id || st.pool_task_ids.count(task_id)) {
        checkpoint_metrics(st);
      }
    }
    auto found = task_to_metrics_.find(task_id);
    if (found == task_to_metrics_.end()) { return 0; }
    int64_t out = 0;
    switch (which) {
      case 0: out = found->second.num_times_retry_throw;
              found->second.num_times_retry_throw = 0; break;
      case 1: out = found->second.num_times_split_retry_throw;
              found->second.num_times_split_retry_throw = 0; break;
      case 2: out = found->second.time_blocked_nanos;
              found->second.time_blocked_nanos = 0; break;
      case 3: out = found->second.time_lost_nanos;
              found->second.time_lost_nanos = 0; break;
      default: break;
    }
    return out;
  }

  int64_t get_total_blocked_or_bufn()
  {
    std::unique_lock<std::mutex> lock(mutex_);
    int64_t count = 0;
    for (auto const& [tid, st] : threads_) {
      switch (st.state) {
        case thread_state::BLOCKED:
        case thread_state::BUFN:
        case thread_state::BUFN_THROW:
        case thread_state::BUFN_WAIT: count++; break;
        default: break;
      }
    }
    return count;
  }

 private:
  std::mutex mutex_;
  std::condition_variable task_has_woken_;
  std::unordered_map<int64_t, full_thread_state> threads_;
  std::unordered_map<int64_t, task_metrics> task_to_metrics_;
  std::FILE* log_  = nullptr;
  bool owns_log_   = false;

  full_thread_state& get_thread(int64_t thread_id)
  {
    auto found = threads_.find(thread_id);
    if (found == threads_.end()) {
      throw_code(ARB_INVALID, "thread " + std::to_string(thread_id) + " is not registered");
    }
    return found->second;
  }

  void log_transition(int64_t thread_id, int64_t task_id, thread_state from, thread_state to)
  {
    if (log_ != nullptr) {
      std::fprintf(log_, "%lld,TRANSITION,%lld,%lld,%lld,%s,%s,\n",
                   static_cast<long long>(now_ns()), 0LL,
                   static_cast<long long>(thread_id), static_cast<long long>(task_id),
                   as_str(from), as_str(to));
      std::fflush(log_);
    }
  }

  void log_status(char const* op, int64_t thread_id, int64_t task_id, thread_state state)
  {
    if (log_ != nullptr) {
      std::fprintf(log_, "%lld,%s,%lld,%lld,%s,,\n", static_cast<long long>(now_ns()), op,
                   static_cast<long long>(thread_id), static_cast<long long>(task_id),
                   as_str(state));
      std::fflush(log_);
    }
  }

  void transition(full_thread_state& st, thread_state to)
  {
    log_transition(st.thread_id, st.task_id, st.state, to);
    st.state = to;
  }

  void checkpoint_metrics(full_thread_state& st)
  {
    if (st.task_id < 0) {
      for (auto const task_id : st.pool_task_ids) {
        task_to_metrics_.try_emplace(task_id, task_metrics())
          .first->second.add(st.metrics);
      }
      st.metrics.clear();
    } else {
      task_to_metrics_.try_emplace(st.task_id, task_metrics())
        .first->second.take_from(st.metrics);
    }
  }

  void remove_thread_association_core(full_thread_state& st,
                                      int64_t task_id,
                                      std::unique_lock<std::mutex>& lock)
  {
    checkpoint_metrics(st);
    bool remove_all = task_id < 0;
    if (!remove_all) {
      st.pool_task_ids.erase(task_id);
      remove_all = st.task_id == task_id || (st.task_id < 0 && st.pool_task_ids.empty());
    }
    if (remove_all) {
      int64_t const tid = st.thread_id;
      if (st.state == thread_state::BLOCKED || st.state == thread_state::BUFN) {
        // wake it so it can throw "thread removed"
        transition(st, thread_state::REMOVE_THROW);
        st.wake_condition->notify_all();
      } else {
        log_transition(tid, st.task_id, st.state, thread_state::UNKNOWN);
        threads_.erase(tid);
      }
      wake_next_highest_priority_blocked(lock, false, true);
      wake_next_highest_priority_blocked(lock, false, false);
    }
  }

  void check_before_oom(full_thread_state& st)
  {
    if (st.num_times_retried + 1 > 500) {
      st.record_failed_retry_time();
      throw_code(ARB_OOM, "OutOfMemory: retry limit exceeded");
    }
    st.num_times_retried++;
  }

  [[noreturn]] void throw_retry_oom(full_thread_state& st)
  {
    st.metrics.num_times_retry_throw++;
    check_before_oom(st);
    st.record_failed_retry_time();
    throw_code(st.is_cpu_alloc ? ARB_CPU_RETRY_OOM : ARB_GPU_RETRY_OOM, "OutOfMemory");
  }

  [[noreturn]] void throw_split_and_retry_oom(full_thread_state& st)
  {
    st.metrics.num_times_split_retry_throw++;
    check_before_oom(st);
    st.record_failed_retry_time();
    throw_code(st.is_cpu_alloc ? ARB_CPU_SPLIT_RETRY : ARB_GPU_SPLIT_RETRY, "OutOfMemory");
  }

  static bool is_blocked(thread_state s)
  {
    return s == thread_state::BLOCKED || s == thread_state::BUFN;
  }

  void block_thread_until_ready_core(int64_t thread_id, std::unique_lock<std::mutex>& lock)
  {
    bool done       = false;
    bool first_time = true;
    while (!done) {
      auto thread = threads_.find(thread_id);
      if (thread == threads_.end()) { return; }
      switch (thread->second.state) {
        case thread_state::BLOCKED:
        case thread_state::BUFN:
          log_status("WAITING", thread_id, thread->second.task_id, thread->second.state);
          thread->second.before_block();
          do {
            thread->second.wake_condition->wait(lock);
            thread = threads_.find(thread_id);
          } while (thread != threads_.end() && is_blocked(thread->second.state));
          if (thread != threads_.end()) { thread->second.after_block(); }
          task_has_woken_.notify_all();
          break;
        case thread_state::BUFN_THROW:
          transition(thread->second, thread_state::BUFN_WAIT);
          thread->second.record_failed_retry_time();
          throw_retry_oom(thread->second);
        case thread_state::BUFN_WAIT: {
          transition(thread->second, thread_state::BUFN);
          // the throw may not have freed anything; re-check deadlock state
          check_and_update_for_bufn(lock);
          auto again = threads_.find(thread_id);
          if (again != threads_.end() && is_blocked(again->second.state)) {
            log_status("WAITING", thread_id, again->second.task_id, again->second.state);
            again->second.before_block();
            do {
              again->second.wake_condition->wait(lock);
              again = threads_.find(thread_id);
            } while (again != threads_.end() && is_blocked(again->second.state));
            if (again != threads_.end()) { again->second.after_block(); }
            task_has_woken_.notify_all();
          }
          break;
        }
        case thread_state::SPLIT_THROW:
          transition(thread->second, thread_state::RUNNING);
          thread->second.record_failed_retry_time();
          throw_split_and_retry_oom(thread->second);
        case thread_state::REMOVE_THROW:
          log_transition(thread_id, thread->second.task_id, thread->second.state,
                         thread_state::UNKNOWN);
          threads_.erase(thread);
          task_has_woken_.notify_all();
          throw_code(ARB_THREAD_REMOVED, "thread removed while blocked");
        default:
          if (!first_time) {
            log_status("DONE WAITING", thread_id, thread->second.task_id,
                       thread->second.state);
          }
          done = true;
      }
      first_time = false;
    }
  }

  void wake_next_highest_priority_blocked(std::unique_lock<std::mutex> const& lock,
                                          bool is_from_free,
                                          bool is_for_cpu)
  {
    thread_priority to_wake(-1, -1);
    bool is_set = false;
    for (auto const& [tid, st] : threads_) {
      if (st.state == thread_state::BLOCKED && st.is_cpu_alloc == is_for_cpu) {
        thread_priority cur = st.priority();
        if (!is_set || to_wake < cur) {
          to_wake = cur;
          is_set  = true;
        }
      }
    }
    int64_t const wake_id = to_wake.get_thread_id();
    if (is_set && wake_id > 0) {
      auto const thread = threads_.find(wake_id);
      if (thread != threads_.end() && thread->second.state == thread_state::BLOCKED) {
        transition(thread->second, thread_state::RUNNING);
        thread->second.wake_condition->notify_all();
      }
    } else if (is_from_free) {
      // all tasks BUFN after a free: wake the highest priority one (:1407-1480)
      std::map<int64_t, int64_t> pool_bufn_count, pool_count;
      std::unordered_set<int64_t> bufn_ids, all_ids;
      is_in_deadlock(pool_bufn_count, pool_count, bufn_ids, all_ids, lock);
      if (!all_ids.empty() && all_ids.size() == bufn_ids.size()) {
        thread_priority bw(-1, -1);
        bool bw_set = false;
        for (auto const& [tid, st] : threads_) {
          if (st.state == thread_state::BUFN && st.is_cpu_alloc == is_for_cpu) {
            thread_priority cur = st.priority();
            if (!bw_set || bw < cur) {
              bw     = cur;
              bw_set = true;
            }
          }
        }
        if (bw_set) {
          int64_t const tid = bw.get_thread_id();
          auto const thread = threads_.find(tid);
          // don't wake yourself on a free (:1452-1456)
          if (thread != threads_.end() && tid != current_caller_) {
            switch (thread->second.state) {
              case thread_state::BUFN:
                transition(thread->second, thread_state::RUNNING);
                thread->second.wake_condition->notify_all();
                break;
              case thread_state::BUFN_WAIT:
                transition(thread->second, thread_state::RUNNING);
                break;
              default: break;
            }
          }
        }
      }
    }
  }

  bool is_thread_bufn_or_above(full_thread_state const& st) const
  {
    if (st.pool_blocked) { return true; }
    switch (st.state) {
      case thread_state::BLOCKED: return false;
      case thread_state::BUFN: return true;
      default: return st.externally_blocked;
    }
  }

  bool is_in_deadlock(std::map<int64_t, int64_t>& pool_bufn_count,
                      std::map<int64_t, int64_t>& pool_count,
                      std::unordered_set<int64_t>& bufn_ids,
                      std::unordered_set<int64_t>& all_ids,
                      std::unique_lock<std::mutex> const& lock) const
  {
    std::unordered_set<int64_t> blocked_ids;
    // pass 1: dedicated task threads
    for (auto const& [tid, st] : threads_) {
      if (st.task_id >= 0) {
        all_ids.insert(st.task_id);
        bool const bufn_plus = is_thread_bufn_or_above(st);
        if (bufn_plus) { bufn_ids.insert(st.task_id); }
        if (bufn_plus || st.state == thread_state::BLOCKED) {
          blocked_ids.insert(st.task_id);
        }
      }
    }
    // pass 2: pool threads
    for (auto const& [tid, st] : threads_) {
      if (st.task_id < 0) {
        for (auto const task_id : st.pool_task_ids) {
          pool_count[task_id] += 1;
        }
        bool const bufn_plus = is_thread_bufn_or_above(st);
        if (bufn_plus) {
          for (auto const task_id : st.pool_task_ids) {
            pool_bufn_count[task_id] += 1;
          }
        }
        if (!bufn_plus && st.state != thread_state::BLOCKED) {
          for (auto const task_id : st.pool_task_ids) {
            blocked_ids.erase(task_id);
          }
        }
      }
    }
    return !all_ids.empty() && all_ids.size() == blocked_ids.size();
  }

  void check_and_update_for_bufn(std::unique_lock<std::mutex> const& lock)
  {
    std::map<int64_t, int64_t> pool_bufn_count, pool_count;
    std::unordered_set<int64_t> bufn_ids, all_ids;
    bool const deadlocked =
      is_in_deadlock(pool_bufn_count, pool_count, bufn_ids, all_ids, lock);
    if (!deadlocked) { return; }

    // lowest-priority BLOCKED thread -> BUFN_THROW (:1607-1630)
    thread_priority to_bufn(-1, -1);
    bool bufn_set = false;
    for (auto const& [tid, st] : threads_) {
      if (st.state == thread_state::BLOCKED) {
        thread_priority cur = st.priority();
        if (!bufn_set || cur < to_bufn) {
          to_bufn  = cur;
          bufn_set = true;
        }
      }
    }
    if (bufn_set) {
      auto const thread = threads_.find(to_bufn.get_thread_id());
      if (thread != threads_.end()) {
        transition(thread->second, thread_state::BUFN_THROW);
        thread->second.wake_condition->notify_all();
      }
    }

    // a task is BUFN if all its pool threads are BUFN (:1639-1645)
    for (auto const& [task_id, bufn_cnt] : pool_bufn_count) {
      auto const it = pool_count.find(task_id);
      if (it != pool_count.end() && it->second <= bufn_cnt) { bufn_ids.insert(task_id); }
    }

    if (!all_ids.empty() && all_ids.size() == bufn_ids.size()) {
      // everyone is BUFN: highest priority BUFN thread -> SPLIT_THROW (:1647-1670)
      thread_priority to_wake(-1, -1);
      bool wake_set = false;
      for (auto const& [tid, st] : threads_) {
        if (st.state == thread_state::BUFN) {
          thread_priority cur = st.priority();
          if (!wake_set || to_wake < cur) {
            to_wake  = cur;
            wake_set = true;
          }
        }
      }
      if (wake_set) {
        auto const thread = threads_.find(to_wake.get_thread_id());
        if (thread != threads_.end()) {
          transition(thread->second, thread_state::SPLIT_THROW);
          thread->second.wake_condition->notify_all();
        }
      }
    }
  }

 public:
  // set per-call by the C wrappers so "don't wake yourself" checks work
  thread_local static int64_t current_caller_;
};

thread_local int64_t task_arbiter::current_caller_ = -1;

int wrap(task_arbiter* arb, int64_t caller, std::function<int()> fn)
{
  task_arbiter::current_caller_ = caller;
  try {
    return fn();
  } catch (arb_exception const& e) {
    g_last_error = e.msg;
    return e.code;
  } catch (std::exception const& e) {
    g_last_error = e.what();
    return ARB_INTERNAL;
  }
}

}  // namespace

extern "C" {

void* arbiter_create(char const* log_path) { return new task_arbiter(log_path); }

void arbiter_destroy(void* h) { delete static_cast<task_arbiter*>(h); }

char const* arbiter_last_error() { return g_last_error.c_str(); }

#define ARB static_cast<task_arbiter*>(h)

int arbiter_start_dedicated_task_thread(void* h, int64_t tid, int64_t task_id)
{
  return wrap(ARB, tid, [&] { ARB->start_dedicated_task_thread(tid, task_id); return ARB_OK; });
}

int arbiter_pool_thread_working_on_task(void* h, int64_t tid, int64_t task_id, int is_shuffle)
{
  return wrap(ARB, tid, [&] { ARB->pool_thread_working_on_task(tid, task_id, is_shuffle != 0); return ARB_OK; });
}

int arbiter_pool_thread_finished_for_task(void* h, int64_t tid, int64_t task_id)
{
  return wrap(ARB, tid, [&] { ARB->pool_thread_finished_for_task(tid, task_id); return ARB_OK; });
}

int arbiter_remove_thread_association(void* h, int64_t tid, int64_t task_id)
{
  return wrap(ARB, tid, [&] { ARB->remove_thread_association(tid, task_id); return ARB_OK; });
}

int arbiter_task_done(void* h, int64_t task_id)
{
  return wrap(ARB, -1, [&] { ARB->task_done(task_id); return ARB_OK; });
}

int arbiter_set_pool_blocked(void* h, int64_t tid, int blocked)
{
  return wrap(ARB, tid, [&] { ARB->set_pool_blocked(tid, blocked != 0); return ARB_OK; });
}

int arbiter_set_externally_blocked(void* h, int64_t tid, int blocked)
{
  return wrap(ARB, tid, [&] { ARB->set_externally_blocked(tid, blocked != 0); return ARB_OK; });
}

int arbiter_start_retry_block(void* h, int64_t tid)
{
  return wrap(ARB, tid, [&] { ARB->start_retry_block(tid); return ARB_OK; });
}

int arbiter_end_retry_block(void* h, int64_t tid)
{
  return wrap(ARB, tid, [&] { ARB->end_retry_block(tid); return ARB_OK; });
}

int arbiter_force_retry_oom(void* h, int64_t tid, int num, int filter, int skip)
{
  return wrap(ARB, tid, [&] { ARB->force_retry_oom(tid, num, filter, skip); return ARB_OK; });
}

int arbiter_force_split_and_retry_oom(void* h, int64_t tid, int num, int filter, int skip)
{
  return wrap(ARB, tid, [&] { ARB->force_split_and_retry_oom(tid, num, filter, skip); return ARB_OK; });
}

int arbiter_force_cudf_exception(void* h, int64_t tid, int num)
{
  return wrap(ARB, tid, [&] { ARB->force_cudf_exception(tid, num); return ARB_OK; });
}

int arbiter_pre_alloc(void* h, int64_t tid, int is_cpu, int blocking)
{
  return wrap(ARB, tid, [&] { return ARB->pre_alloc(tid, is_cpu != 0, blocking != 0); });
}

int arbiter_post_alloc_success(void* h, int64_t tid, int is_cpu, int was_recursive)
{
  return wrap(ARB, tid, [&] { ARB->post_alloc_success(tid, is_cpu != 0, was_recursive != 0); return ARB_OK; });
}

int arbiter_post_alloc_failed(void* h, int64_t tid, int is_cpu, int is_oom, int blocking,
                              int was_recursive)
{
  return wrap(ARB, tid, [&] {
    return ARB->post_alloc_failed(tid, is_cpu != 0, is_oom != 0, blocking != 0,
                                  was_recursive != 0)
             ? 1
             : 0;
  });
}

int arbiter_dealloc(void* h, int64_t tid, int is_cpu)
{
  return wrap(ARB, tid, [&] { ARB->dealloc(tid, is_cpu != 0); return ARB_OK; });
}

int arbiter_block_thread_until_ready(void* h, int64_t tid)
{
  return wrap(ARB, tid, [&] { return ARB->block_thread_until_ready(tid); });
}

int arbiter_check_and_break_deadlocks(void* h)
{
  return wrap(ARB, -1, [&] { ARB->check_and_break_deadlocks(); return ARB_OK; });
}

int arbiter_get_state_of(void* h, int64_t tid)
{
  return ARB->get_state_of(tid);
}

int64_t arbiter_get_and_reset_metric(void* h, int64_t task_id, int which)
{
  return ARB->get_and_reset_metric(task_id, which);
}

int64_t arbiter_get_total_blocked_or_bufn(void* h)
{
  return ARB->get_total_blocked_or_bufn();
}

}  // extern "C"
