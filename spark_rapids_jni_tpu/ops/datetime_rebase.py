"""Proleptic Gregorian <-> Julian calendar rebase of DAYS / MICROS timestamps.

Spark-exact semantics of the reference's ``rebase_gregorian_to_julian`` /
``rebase_julian_to_gregorian`` (datetime_rebase.cu:59,130,227,293 — matching
Spark's ``localRebaseGregorianToJulianDays`` family, timezone fixed to UTC).

The reference runs one thread per row over ``cuda::std::chrono`` date math; on
TPU the same closed-form civil-calendar algorithms (Howard Hinnant's
``civil_from_days``/``days_from_civil`` and the 4-year-era Julian variants)
vectorize directly onto the VPU as int32/int64 lane arithmetic — there is no
data-dependent control flow, only ``where`` selects.

Key facts encoded below:
- Gregorian calendar starts 1582-10-15, which is day -141427 since the epoch in
  BOTH calendars (they agree from that day on).
- Gregorian local dates 1582-10-05 .. 1582-10-14 (civil days -141437..-141428)
  do not exist in the hybrid Julian->Gregorian calendar; Spark clamps them to
  the Gregorian start day (datetime_rebase.cu:94-97).
- For MICROS, the time-of-day part is preserved verbatim; only the day part is
  rebased.  The reference's hour/minute/second decomposition via trunc-div with
  negative fixups (datetime_rebase.cu:183-222) is algebraically floor div/mod,
  so ``result = rebased_day * 86_400_000_000 + floor_mod(micros, 86_400_000_000)``.
"""

from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_jni_tpu.columnar.column import Column
from spark_rapids_jni_tpu.columnar.dtypes import Kind

MICROS_PER_DAY = 86_400_000_000
# Day number of 1582-10-15 (Gregorian calendar start) — same in both calendars.
GREGORIAN_START_DAYS = -141427
# Civil day number of 1582-10-04, the last day of the Julian calendar.
JULIAN_END_DAYS = GREGORIAN_START_DAYS - 11
LAST_SWITCH_GREGORIAN_MICROS = GREGORIAN_START_DAYS * MICROS_PER_DAY  # -12219292800000000


def _civil_from_days(days):
    """days since epoch -> (y, m, d) in proleptic Gregorian calendar (int64)."""
    z = days.astype(jnp.int64) + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097  # [0, 146096]
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365  # [0, 399]
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)  # [0, 365]
    mp = (5 * doy + 2) // 153  # [0, 11]
    d = doy - (153 * mp + 2) // 5 + 1  # [1, 31]
    m = mp + jnp.where(mp < 10, 3, -9)  # [1, 12]
    return y + (m <= 2), m, d


def _days_from_civil(y, m, d):
    """(y, m, d) proleptic Gregorian -> days since epoch (int64)."""
    y = y - (m <= 2)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400  # [0, 399]
    doy = (153 * (m + jnp.where(m > 2, -3, 9)) + 2) // 5 + d - 1  # [0, 365]
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy  # [0, 146096]
    return era * 146097 + doe - 719468


def _days_from_julian(y, m, d):
    """(y, m, d) in Julian calendar -> days since epoch (datetime_rebase.cu:40)."""
    y = y - (m <= 2)
    era = jnp.floor_divide(y, 4)
    yoe = y - era * 4  # [0, 3]
    doy = (153 * (m + jnp.where(m > 2, -3, 9)) + 2) // 5 + d - 1  # [0, 365]
    doe = yoe * 365 + doy  # [0, 1460]
    return era * 1461 + doe - 719470


def _julian_from_days(days):
    """days since epoch -> (y, m, d) in Julian calendar (datetime_rebase.cu:109)."""
    z = days.astype(jnp.int64) + 719470
    era = jnp.floor_divide(z, 1461)
    doe = z - era * 1461  # [0, 1460]
    yoe = (doe - doe // 1460) // 365  # [0, 3]
    y = yoe + era * 4
    doy = doe - 365 * yoe  # [0, 365]
    mp = (5 * doy + 2) // 153  # [0, 11]
    d = doy - (153 * mp + 2) // 5 + 1  # [1, 31]
    m = mp + jnp.where(mp < 10, 3, -9)  # [1, 12]
    return y + (m <= 2), m, d


def _gregorian_to_julian_day(days):
    """Rebase one array of civil day numbers; returns int64 day numbers."""
    days = days.astype(jnp.int64)
    y, m, d = _civil_from_days(days)
    rebased = _days_from_julian(y, m, d)
    in_gap = (days > JULIAN_END_DAYS) & (days < GREGORIAN_START_DAYS)
    rebased = jnp.where(in_gap, GREGORIAN_START_DAYS, rebased)
    return jnp.where(days >= GREGORIAN_START_DAYS, days, rebased)


def _julian_to_gregorian_day(days):
    days = days.astype(jnp.int64)
    y, m, d = _julian_from_days(days)
    rebased = _days_from_civil(y, m, d)
    return jnp.where(days >= GREGORIAN_START_DAYS, days, rebased)


def _rebase_micros(micros, day_fn):
    micros = micros.astype(jnp.int64)
    day = jnp.floor_divide(micros, MICROS_PER_DAY)
    time_of_day = micros - day * MICROS_PER_DAY  # floor mod, in [0, MICROS_PER_DAY)
    rebased = day_fn(day) * MICROS_PER_DAY + time_of_day
    return jnp.where(micros >= LAST_SWITCH_GREGORIAN_MICROS, micros, rebased)


def _dispatch(col: Column, day_fn) -> Column:
    if col.dtype.kind == Kind.DATE32:
        out = day_fn(col.data).astype(jnp.int32)
    elif col.dtype.kind == Kind.TIMESTAMP_MICROS:
        out = _rebase_micros(col.data, day_fn)
    else:
        raise TypeError(
            f"rebase requires DATE32 or TIMESTAMP_MICROS, got {col.dtype}"
        )
    return Column(out, col.validity, col.dtype)


def rebase_gregorian_to_julian(col: Column) -> Column:
    """Spark ``rebaseGregorianToJulianDays``/``...Micros`` (UTC).

    Reinterprets each proleptic-Gregorian local date(-time) as a Julian-calendar
    local date(-time) and returns its day/microsecond number.  Dates in the
    1582-10-05..14 gap clamp to the Gregorian start day.
    """
    return _dispatch(col, _gregorian_to_julian_day)


def rebase_julian_to_gregorian(col: Column) -> Column:
    """Spark ``rebaseJulianToGregorianDays``/``...Micros`` (UTC)."""
    return _dispatch(col, _julian_to_gregorian_day)
