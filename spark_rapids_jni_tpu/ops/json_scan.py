"""The JSON path machine as a jitted lax.scan — core of the device pipeline.

A device translation of ops/get_json_object.py's host ``_Machine`` —
the explicit-stack form of evaluate_path (get_json_object.cu:360-394) with
every row advancing one token (or one frame return) per scan step.  State is
a pytree of [n]- and [n, F]-shaped arrays; frame/generator stack updates are
one-hot writes at the stack pointer.  Shapes (n, T, F, G, S) all derive from
the pow2 bucket geometry, so the compiled-variant set stays bounded.

``_run_scan`` is consumed by the fully device-resident product path
(ops/json_render_device.py via _get_json_object_device); the host numpy
machine remains the debug oracle (``json_device_render=False``).  A third,
host-rendered wrapper around this scan (the round-2 ``json_eval_device``
A/B arm) was removed in round 4: equivalence of the product path against
the host oracle is asserted end to end in tests/test_get_json_object*.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu.ops import json_tokenizer as jt
from spark_rapids_jni_tpu.ops.get_json_object import (
    INDEX,
    NAMED,
    WILDCARD,
    _C_CLOSE_ARR,
    _C_COLON,
    _C_COMMA,
    _C_OPEN_ARR,
    _F_CASE2,
    _F_CASE4,
    _F_CASE5,
    _F_CASE6,
    _F_CASE7,
    _F_CASE8,
    _F_COPY,
    _FLATTEN,
    _P_END,
    _QUOTED,
    _RAW,
    _SCALARS,
    _SEG_COND_CLOSE,
    _SEG_COND_OPEN,
    _SEG_CONST,
    _SEG_ESC_TOK,
    _SEG_RAW_TOK,
    _SUB_DRAIN,
    _SUB_ENTERING,
    _SUB_NONE,
    _SUB_WAITING,
)

_I32 = jnp.int32
_I8 = jnp.int8

_SCALARS_ARR = np.asarray(_SCALARS, np.int32)


def _isin(x, values):
    out = jnp.zeros(x.shape, bool)
    for v in values:
        out = out | (x == v)
    return out


@functools.partial(jax.jit, static_argnums=(7, 8, 9))
def _run_scan(kind, match, ntok, ok, nm_stack, ptype, parg,
              T: int, F: int, G: int):
    """Scan the machine over 2T+40 steps; returns final state + per-step ys."""
    n = kind.shape[0]
    S = 2 * T + 40
    P1 = ptype.shape[0]

    rowsF = jnp.arange(F, dtype=_I32)[None, :]
    rowsG = jnp.arange(G, dtype=_I32)[None, :]

    # All per-row "read at dynamic index" helpers delegate to the shared
    # one-hot contraction (jt._take_rows): per-row dynamic gathers
    # scalarize on TPU (round-5 device profile — they made this scan
    # ~137 ms/step), while select+reduce over the small axis vectorizes.
    rowsT = jnp.arange(T, dtype=_I32)[None, :]

    def top(arr, fp):
        return jt._take_rows(arr, jnp.clip(fp, 0, F - 1)[:, None])[:, 0]

    def set_top(arr, fp, mask, val):
        sel = (rowsF == jnp.clip(fp, 0, F - 1)[:, None]) & mask[:, None]
        val = jnp.broadcast_to(jnp.asarray(val, arr.dtype), (n,))
        return jnp.where(sel, val[:, None], arr)

    def gtop(arr, gp):
        return jt._take_rows(arr, jnp.clip(gp, 0, G - 1)[:, None])[:, 0]

    def set_gtop(arr, gp, mask, val):
        sel = (rowsG == jnp.clip(gp, 0, G - 1)[:, None]) & mask[:, None]
        val = jnp.broadcast_to(jnp.asarray(val, arr.dtype), (n,))
        return jnp.where(sel, val[:, None], arr)

    def kind_at(idx):
        return jt._take_rows(kind, jnp.clip(idx, 0, T - 1)[:, None])[:, 0]

    def match_at(idx):
        return jt._take_rows(match, jnp.clip(idx, 0, T - 1)[:, None])[:, 0]

    def step(st, s):
        seg = jnp.zeros((n, 2, 2), _I32)
        close_grp = jnp.full((n,), -1, _I32)
        close_dirty = jnp.zeros((n,), _I32)
        close_nc = jnp.zeros((n,), bool)

        active = ~st["done"] & ~st["err"]

        # ---- 1) process pending returns -------------------------------
        retm = active & st["ret_valid"]
        at_root = retm & (st["fp"] < 0)
        st["done"] = st["done"] | at_root
        st["dirty_root"] = jnp.where(at_root, st["ret_dirty"], st["dirty_root"])
        fr = retm & ~at_root
        case_r = top(st["f_case"], st["fp"])
        sub_r = top(st["f_sub"], st["fp"])
        acc = fr & _isin(case_r, (_F_CASE2, _F_CASE5, _F_CASE6, _F_CASE7))
        st["f_dirty"] = set_top(st["f_dirty"], st["fp"], acc,
                                top(st["f_dirty"], st["fp"]) + st["ret_dirty"])
        c4r = fr & (case_r == _F_CASE4) & (sub_r == _SUB_WAITING)
        bad = c4r & (st["ret_dirty"] == 0)
        st["err"] = st["err"] | bad
        good = c4r & ~bad
        st["f_dirty"] = set_top(st["f_dirty"], st["fp"], good, st["ret_dirty"])
        st["f_flag"] = set_top(st["f_flag"], st["fp"], good, True)
        st["f_sub"] = set_top(st["f_sub"], st["fp"], good, _SUB_NONE)
        c8r = fr & (case_r == _F_CASE8) & (sub_r == _SUB_WAITING)
        st["f_dirty"] = set_top(st["f_dirty"], st["fp"], c8r, st["ret_dirty"])
        st["f_sub"] = set_top(st["f_sub"], st["fp"], c8r, _SUB_DRAIN)
        st["ret_valid"] = st["ret_valid"] & ~retm
        active = active & ~retm & ~st["err"]

        # ---- 2) frame-top dispatch ------------------------------------
        out_of_tok = active & (st["tcur"] >= ntok)
        st["err"] = st["err"] | out_of_tok
        active = active & ~out_of_tok

        k = kind_at(st["tcur"])
        case = top(st["f_case"], st["fp"])
        sub = top(st["f_sub"], st["fp"])
        style = top(st["f_style"], st["fp"])
        fpath = top(st["f_path"], st["fp"])
        faux = top(st["f_aux"], st["fp"])
        fflag = top(st["f_flag"], st["fp"])
        fdirty = top(st["f_dirty"], st["fp"])

        is_root = active & (st["fp"] < 0) & ~st["entered_root"]
        st["entered_root"] = st["entered_root"] | is_root

        close_arr = k == jt.END_ARRAY
        close_obj = k == jt.END_OBJECT

        def pop_ret(st, mask, dirty):
            st["ret_valid"] = st["ret_valid"] | mask
            st["ret_dirty"] = jnp.where(mask, dirty, st["ret_dirty"])
            st["fp"] = jnp.where(mask, st["fp"] - 1, st["fp"])
            return st

        # COPY
        copym = active & (st["fp"] >= 0) & (case == _F_COPY)
        prevk = kind_at(st["tcur"] - 1)
        sep_colon = prevk == jt.FIELD_NAME
        prev_valend = _isin(prevk, tuple(_SCALARS_ARR.tolist())) | \
            (prevk == jt.END_OBJECT) | (prevk == jt.END_ARRAY)
        cur_close = close_arr | close_obj
        sep_comma = prev_valend & ~cur_close
        seg = seg.at[:, 0, 0].set(jnp.where(
            copym & (sep_colon | sep_comma), _SEG_CONST, seg[:, 0, 0]))
        seg = seg.at[:, 0, 1].set(jnp.where(
            copym & sep_colon, _C_COLON,
            jnp.where(copym & sep_comma, _C_COMMA, seg[:, 0, 1])))
        seg = seg.at[:, 1, 0].set(jnp.where(copym, _SEG_ESC_TOK, seg[:, 1, 0]))
        seg = seg.at[:, 1, 1].set(jnp.where(copym, st["tcur"], seg[:, 1, 1]))
        at_end = copym & (st["tcur"] == faux)
        st = pop_ret(st, at_end, jnp.ones((n,), _I32))
        st["tcur"] = jnp.where(copym, st["tcur"] + 1, st["tcur"])
        active = active & ~copym

        # CASE2
        c2 = active & (st["fp"] >= 0) & (case == _F_CASE2)
        c2_close = c2 & close_arr
        st = pop_ret(st, c2_close, fdirty)
        st["tcur"] = jnp.where(c2_close, st["tcur"] + 1, st["tcur"])
        c2_enter = c2 & ~close_arr

        # CASE4
        c4 = active & (st["fp"] >= 0) & (case == _F_CASE4)
        c4_entering = c4 & (sub == _SUB_ENTERING)
        c4 = c4 & (sub != _SUB_ENTERING)
        c4_close = c4 & close_obj
        st = pop_ret(st, c4_close, fdirty)
        st["tcur"] = jnp.where(c4_close, st["tcur"] + 1, st["tcur"])
        c4_field = c4 & ~close_obj
        # per-row name match at (path level, current token)
        lvl = jnp.clip(fpath, 0, P1 - 1)
        sel_t = rowsT[None, :, :] == jnp.clip(
            st["tcur"], 0, T - 1)[None, :, None]          # [1, n, T]
        nm_tok = jnp.where(sel_t, nm_stack, False).sum(axis=2) > 0  # [P1, n]
        sel_p = (jnp.arange(P1, dtype=_I32)[:, None]
                 == lvl[None, :])                          # [P1, n]
        nm = jnp.where(sel_p, nm_tok, False).sum(axis=0) > 0
        found = fflag
        hit = c4_field & nm & ~found
        miss = c4_field & ~hit
        vt = st["tcur"] + 1
        vkind = kind_at(vt)
        vopen = (vkind == jt.START_OBJECT) | (vkind == jt.START_ARRAY)
        skip_to = jnp.where(vopen, match_at(vt) + 1, st["tcur"] + 2)
        st["tcur"] = jnp.where(miss, skip_to, st["tcur"])
        isnull = vkind == jt.VALUE_NULL
        st["err"] = st["err"] | (hit & isnull)
        ok_hit = hit & ~isnull
        st["tcur"] = jnp.where(ok_hit, st["tcur"] + 1, st["tcur"])
        st["f_sub"] = set_top(st["f_sub"], st["fp"], ok_hit, _SUB_ENTERING)
        c4_go = c4_entering
        st["f_sub"] = set_top(st["f_sub"], st["fp"], c4_go, _SUB_WAITING)

        # CASE5
        c5 = active & (st["fp"] >= 0) & (case == _F_CASE5)
        c5_close = c5 & close_arr
        seg = seg.at[:, 1, 0].set(jnp.where(c5_close, _SEG_CONST, seg[:, 1, 0]))
        seg = seg.at[:, 1, 1].set(jnp.where(c5_close, _C_CLOSE_ARR, seg[:, 1, 1]))
        st["g_depth"] = set_gtop(st["g_depth"], st["gp"], c5_close,
                                 gtop(st["g_depth"], st["gp"]) - 1)
        st["g_empty"] = set_gtop(st["g_empty"], st["gp"], c5_close, False)
        st = pop_ret(st, c5_close, fdirty)
        st["tcur"] = jnp.where(c5_close, st["tcur"] + 1, st["tcur"])
        c5_enter = c5 & ~close_arr

        # CASE6
        c6 = active & (st["fp"] >= 0) & (case == _F_CASE6)
        c6_close = c6 & close_arr
        close_grp = jnp.where(c6_close, faux, close_grp)
        close_dirty = jnp.where(c6_close, fdirty, close_dirty)
        close_nc = jnp.where(c6_close, fflag, close_nc)
        seg = seg.at[:, 1, 0].set(jnp.where(c6_close, _SEG_COND_CLOSE,
                                            seg[:, 1, 0]))
        seg = seg.at[:, 1, 1].set(jnp.where(c6_close, faux, seg[:, 1, 1]))
        st["gp"] = jnp.where(c6_close, st["gp"] - 1, st["gp"])
        wrote = c6_close & (fdirty >= 1) & (gtop(st["g_depth"], st["gp"]) > 0)
        st["g_empty"] = set_gtop(st["g_empty"], st["gp"], wrote, False)
        st = pop_ret(st, c6_close, fdirty)
        st["tcur"] = jnp.where(c6_close, st["tcur"] + 1, st["tcur"])
        c6_enter = c6 & ~close_arr

        # CASE7
        c7 = active & (st["fp"] >= 0) & (case == _F_CASE7)
        c7_close = c7 & close_arr
        seg = seg.at[:, 1, 0].set(jnp.where(c7_close, _SEG_CONST, seg[:, 1, 0]))
        seg = seg.at[:, 1, 1].set(jnp.where(c7_close, _C_CLOSE_ARR, seg[:, 1, 1]))
        st["g_depth"] = set_gtop(st["g_depth"], st["gp"], c7_close,
                                 gtop(st["g_depth"], st["gp"]) - 1)
        st["g_empty"] = set_gtop(st["g_empty"], st["gp"], c7_close, False)
        st = pop_ret(st, c7_close, fdirty)
        st["tcur"] = jnp.where(c7_close, st["tcur"] + 1, st["tcur"])
        c7_enter = c7 & ~close_arr

        # CASE8
        c8 = active & (st["fp"] >= 0) & (case == _F_CASE8)
        c8_skip = c8 & (sub == _SUB_NONE) & (faux > 0)
        st["err"] = st["err"] | (c8_skip & close_arr)
        ok8 = c8_skip & ~close_arr
        isopen_k = (k == jt.START_OBJECT) | (k == jt.START_ARRAY)
        skip_cur = jnp.where(isopen_k, match_at(st["tcur"]) + 1, st["tcur"] + 1)
        st["tcur"] = jnp.where(ok8, skip_cur, st["tcur"])
        st["f_aux"] = set_top(st["f_aux"], st["fp"], ok8, faux - 1)
        c8_go = c8 & (sub == _SUB_NONE) & (faux <= 0) & ~c8_skip
        st["f_sub"] = set_top(st["f_sub"], st["fp"], c8_go, _SUB_WAITING)
        c8_drain = c8 & (sub == _SUB_DRAIN)
        d_close = c8_drain & close_arr
        st = pop_ret(st, d_close, fdirty)
        d_skip = c8_drain & ~close_arr
        st["tcur"] = jnp.where(d_skip, skip_cur, st["tcur"])
        st["tcur"] = jnp.where(d_close, st["tcur"] + 1, st["tcur"])

        # ---- 3) ENTER dispatch ----------------------------------------
        enter = is_root | c2_enter | c4_go | c5_enter | c6_enter | c7_enter \
            | c8_go
        e_style = jnp.full((n,), _RAW, _I8)
        e_path = jnp.zeros((n,), _I32)
        e_style = jnp.where(c2_enter, _FLATTEN, e_style)
        e_path = jnp.where(c2_enter, P1 - 1, e_path)
        e_style = jnp.where(c4_go, style, e_style)
        e_path = jnp.where(c4_go, fpath + 1, e_path)
        e_style = jnp.where(c5_enter, _FLATTEN, e_style)
        e_path = jnp.where(c5_enter, fpath, e_path)
        e_style = jnp.where(c6_enter, style, e_style)
        e_path = jnp.where(c6_enter, fpath, e_path)
        e_style = jnp.where(c7_enter, _QUOTED, e_style)
        e_path = jnp.where(c7_enter, fpath, e_path)
        e_style = jnp.where(c8_go, jnp.where(fflag, _QUOTED, style), e_style)
        e_path = jnp.where(c8_go, fpath, e_path)

        # -- enter dispatch (evaluate_path cases) --
        pt = ptype[jnp.clip(e_path, 0, P1 - 1)]
        ptn = ptype[jnp.clip(e_path + 1, 0, P1 - 1)]
        path_end = pt == _P_END
        is_str = k == jt.VALUE_STRING
        is_arr = k == jt.START_ARRAY
        is_obj = k == jt.START_OBJECT
        mtch = match_at(st["tcur"])

        need_comma = (gtop(st["g_depth"], st["gp"]) > 0) & \
            ~gtop(st["g_empty"], st["gp"])

        m1 = enter & is_str & path_end & (e_style == _RAW)
        m2 = enter & is_arr & path_end & (e_style == _FLATTEN) & ~m1
        m3 = enter & path_end & ~m1 & ~m2
        rest = enter & ~path_end
        m4 = rest & is_obj & (pt == NAMED)
        m5 = rest & is_arr & (pt == WILDCARD) & (ptn == WILDCARD)
        m6 = rest & is_arr & (pt == WILDCARD) & (e_style != _QUOTED) & ~m5
        m7 = rest & is_arr & (pt == WILDCARD) & ~m5 & ~m6
        m8 = rest & is_arr & (pt == INDEX)
        m12 = rest & ~m4 & ~m5 & ~m6 & ~m7 & ~m8

        def push(st, mask, case_v, style_v, path_v, aux_v=None, flag_v=None):
            st["fp"] = jnp.where(mask, st["fp"] + 1, st["fp"])
            over = mask & (st["fp"] >= F)
            st["err"] = st["err"] | over
            st["fp"] = jnp.where(over, F - 1, st["fp"])
            m = mask & ~over
            st["f_case"] = set_top(st["f_case"], st["fp"], m, case_v)
            st["f_style"] = set_top(st["f_style"], st["fp"], m, style_v)
            st["f_path"] = set_top(st["f_path"], st["fp"], m, path_v)
            st["f_dirty"] = set_top(st["f_dirty"], st["fp"], m, 0)
            st["f_sub"] = set_top(st["f_sub"], st["fp"], m, _SUB_NONE)
            st["f_aux"] = set_top(st["f_aux"], st["fp"], m,
                                  0 if aux_v is None else aux_v)
            st["f_flag"] = set_top(st["f_flag"], st["fp"], m,
                                   False if flag_v is None else flag_v)
            return st

        # case 1
        seg = seg.at[:, 1, 0].set(jnp.where(m1, _SEG_RAW_TOK, seg[:, 1, 0]))
        seg = seg.at[:, 1, 1].set(jnp.where(m1, st["tcur"], seg[:, 1, 1]))
        wrote1 = m1 & (gtop(st["g_depth"], st["gp"]) > 0)
        st["g_empty"] = set_gtop(st["g_empty"], st["gp"], wrote1, False)
        st["ret_valid"] = st["ret_valid"] | m1
        st["ret_dirty"] = jnp.where(m1, 1, st["ret_dirty"])
        st["tcur"] = jnp.where(m1, st["tcur"] + 1, st["tcur"])

        # case 2
        st = push(st, m2, _F_CASE2, _FLATTEN, P1 - 1)
        st["tcur"] = jnp.where(m2, st["tcur"] + 1, st["tcur"])

        # case 3
        badk = _isin(k, (jt.FIELD_NAME, jt.END_OBJECT, jt.END_ARRAY,
                         jt.ERRORTOK, jt.PAD))
        st["err"] = st["err"] | (m3 & badk)
        ok3 = m3 & ~badk
        seg = seg.at[:, 0, 0].set(jnp.where(ok3 & need_comma, _SEG_CONST,
                                            seg[:, 0, 0]))
        seg = seg.at[:, 0, 1].set(jnp.where(ok3 & need_comma, _C_COMMA,
                                            seg[:, 0, 1]))
        seg = seg.at[:, 1, 0].set(jnp.where(ok3, _SEG_ESC_TOK, seg[:, 1, 0]))
        seg = seg.at[:, 1, 1].set(jnp.where(ok3, st["tcur"], seg[:, 1, 1]))
        st["g_empty"] = set_gtop(st["g_empty"], st["gp"],
                                 ok3 & (gtop(st["g_depth"], st["gp"]) > 0),
                                 False)
        opn = ok3 & (is_arr | is_obj)
        st = push(st, opn, _F_COPY, _RAW, 0, aux_v=mtch)
        scal = ok3 & ~opn
        st["ret_valid"] = st["ret_valid"] | scal
        st["ret_dirty"] = jnp.where(scal, 1, st["ret_dirty"])
        st["tcur"] = jnp.where(ok3, st["tcur"] + 1, st["tcur"])

        # case 4
        st = push(st, m4, _F_CASE4, e_style, e_path)
        st["tcur"] = jnp.where(m4, st["tcur"] + 1, st["tcur"])

        # case 5
        seg = seg.at[:, 0, 0].set(jnp.where(m5 & need_comma, _SEG_CONST,
                                            seg[:, 0, 0]))
        seg = seg.at[:, 0, 1].set(jnp.where(m5 & need_comma, _C_COMMA,
                                            seg[:, 0, 1]))
        seg = seg.at[:, 1, 0].set(jnp.where(m5, _SEG_CONST, seg[:, 1, 0]))
        seg = seg.at[:, 1, 1].set(jnp.where(m5, _C_OPEN_ARR, seg[:, 1, 1]))
        st["g_depth"] = set_gtop(st["g_depth"], st["gp"], m5,
                                 gtop(st["g_depth"], st["gp"]) + 1)
        st["g_empty"] = set_gtop(st["g_empty"], st["gp"], m5, True)
        st = push(st, m5, _F_CASE5, e_style, e_path + 2)
        st["tcur"] = jnp.where(m5, st["tcur"] + 1, st["tcur"])

        # case 6
        child_style = jnp.where(e_style == _RAW, _QUOTED, _FLATTEN).astype(_I8)
        st = push(st, m6, _F_CASE6, child_style, e_path + 1,
                  aux_v=jnp.full((n,), s, _I32), flag_v=need_comma)
        st["gp"] = jnp.where(m6, st["gp"] + 1, st["gp"])
        overg = m6 & (st["gp"] >= G)
        st["err"] = st["err"] | overg
        st["gp"] = jnp.where(overg, G - 1, st["gp"])
        st["g_depth"] = set_gtop(st["g_depth"], st["gp"], m6, 1)
        st["g_empty"] = set_gtop(st["g_empty"], st["gp"], m6, True)
        seg = seg.at[:, 0, 0].set(jnp.where(m6, _SEG_COND_OPEN, seg[:, 0, 0]))
        seg = seg.at[:, 0, 1].set(jnp.where(m6, s, seg[:, 0, 1]))
        st["tcur"] = jnp.where(m6, st["tcur"] + 1, st["tcur"])

        # case 7
        seg = seg.at[:, 0, 0].set(jnp.where(m7 & need_comma, _SEG_CONST,
                                            seg[:, 0, 0]))
        seg = seg.at[:, 0, 1].set(jnp.where(m7 & need_comma, _C_COMMA,
                                            seg[:, 0, 1]))
        seg = seg.at[:, 1, 0].set(jnp.where(m7, _SEG_CONST, seg[:, 1, 0]))
        seg = seg.at[:, 1, 1].set(jnp.where(m7, _C_OPEN_ARR, seg[:, 1, 1]))
        st["g_depth"] = set_gtop(st["g_depth"], st["gp"], m7,
                                 gtop(st["g_depth"], st["gp"]) + 1)
        st["g_empty"] = set_gtop(st["g_empty"], st["gp"], m7, True)
        st = push(st, m7, _F_CASE7, e_style, e_path + 1)
        st["tcur"] = jnp.where(m7, st["tcur"] + 1, st["tcur"])

        # cases 8/9
        idxv = parg[jnp.clip(e_path, 0, P1 - 1)]
        st = push(st, m8, _F_CASE8, e_style, e_path + 1,
                  aux_v=idxv, flag_v=(ptn == WILDCARD))
        st["tcur"] = jnp.where(m8, st["tcur"] + 1, st["tcur"])

        # case 12
        isopen12 = is_arr | is_obj
        skip12 = jnp.where(isopen12, mtch + 1, st["tcur"] + 1)
        st["tcur"] = jnp.where(m12, skip12, st["tcur"])
        st["ret_valid"] = st["ret_valid"] | m12
        st["ret_dirty"] = jnp.where(m12, 0, st["ret_dirty"])

        return st, (seg, close_grp, close_dirty, close_nc)

    init = dict(
        tcur=jnp.zeros((n,), _I32),
        err=~ok,
        done=jnp.zeros((n,), bool),
        dirty_root=jnp.zeros((n,), _I32),
        ret_valid=jnp.zeros((n,), bool),
        ret_dirty=jnp.zeros((n,), _I32),
        fp=jnp.full((n,), -1, _I32),
        f_case=jnp.zeros((n, F), _I8),
        f_path=jnp.zeros((n, F), _I32),
        f_style=jnp.zeros((n, F), _I8),
        f_dirty=jnp.zeros((n, F), _I32),
        f_sub=jnp.zeros((n, F), _I8),
        f_aux=jnp.zeros((n, F), _I32),
        f_flag=jnp.zeros((n, F), bool),
        g_depth=jnp.zeros((n, G), _I32),
        g_empty=jnp.ones((n, G), bool),
        gp=jnp.zeros((n,), _I32),
        entered_root=jnp.zeros((n,), bool),
    )
    st, ys = jax.lax.scan(step, init, jnp.arange(S, dtype=_I32))
    return st["err"], st["done"], st["dirty_root"], ys
