"""Spark-exact string -> float32/float64 cast.

Behavioral parity with the reference's warp-per-row parser
(cast_string_to_float.cu:598 string_to_float_kernel; parse stages :86-557),
including its quirks:

- 'nan' (any case) is only valid as the exact 3-char string; leading
  whitespace/sign make it null (+ ANSI error) but still parse as nan
  (check_for_nan :243-260);
- 'inf'/'infinity' allow leading whitespace and sign, must end the string,
  and garbage after them is null WITHOUT an ANSI error (check_for_inf :276);
- at most 19 significant digits accumulate into a uint64; beyond that, digits
  truncate with the reference's exact (slightly lossy) exponent accounting
  (parse_digits :327-470, max_holding rule :395-445);
- manual exponents read at most 4 digits (parse_manual_exp :505);
- a single trailing f/F/d/D is allowed — except after a zero value, where only
  whitespace may follow (operator() :134-145);
- the final value is digits x 10^exp in IEEE binary64 (subnormal two-step
  :158-195), cast to float32 at the end for FLOAT32 outputs.

TPU split: the O(n x len) character scan is vectorized lane arithmetic on the
padded byte matrix (cummax prefix masks replace the warp ballot/shuffle
choreography).  The final O(n) digits->double assembly ALSO runs on device —
TPU f64 is float32-pair emulated and would not be bit-exact, so the binary64
multiply/divide/convert steps run as exact integer softfloat lane ops
(utils/softfloat; `_assemble_device`).  The host `_assemble` is kept as the
equivalence oracle.  The only host interaction is the ANSI error decision
(one scalar any() sync; row bytes are pulled only on the throw path).
Digit windows longer than one warp batch (32 chars) follow the single-batch
accounting rather than the reference's batch-boundary-dependent truncation
bookkeeping.

Known <=1-ulp divergence: for negative powers (10^-k) our table is the
correctly-rounded binary64 value, while CUDA's exp10 is occasionally 1 ulp
off (verified at exp10(-291)); this only shows in the extreme-exponent range
where the reference already deviates from Java's correctly-rounded parse.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu import config
from spark_rapids_jni_tpu.columnar.buckets import length_buckets, map_buckets
from spark_rapids_jni_tpu.columnar.column import Column, StringColumn
from spark_rapids_jni_tpu.columnar.dtypes import DType, FLOAT64, Kind
from spark_rapids_jni_tpu.obs.phases import PhaseTimes
from spark_rapids_jni_tpu.ops.cast_string import CastException
from spark_rapids_jni_tpu.utils.softfloat import (
    f64_bits_to_f32_bits,
    f64_div_bits,
    f64_mul_bits,
    u64_to_f64_bits,
)

MAX_SAFE_DIGITS = 19
MAX_HOLDING = ((1 << 64) - 1 - 9) // 10  # 1844674407370955160

PHASES = PhaseTimes("bucket", "parse", "assemble")

# binary64 values of 10^k for k in [-360, 359].  Non-negative k: float(10**k)
# is correctly rounded (exact integer -> nearest double), overflowing to inf
# past 308, matching exp10 saturation.  Negative k: libm pow (what CUDA's
# exp10 effectively is within an ulp).
_EXP10_OFFSET = 360
_EXP10 = np.array(
    [float(np.power(10.0, k)) for k in range(-_EXP10_OFFSET, 0)]
    + [float(10**k) if k <= 308 else np.inf for k in range(360)],
    dtype=np.float64,
)


def _exp10(k: np.ndarray) -> np.ndarray:
    idx = np.clip(k + _EXP10_OFFSET, 0, len(_EXP10) - 1)
    return _EXP10[idx]


_SCAN_FIELDS = [
    ("lens", jnp.int32), ("all_ws", jnp.bool_), ("negative", jnp.bool_),
    ("is_nan", jnp.bool_), ("inf3", jnp.bool_), ("inf_exact", jnp.bool_),
    ("n_lead_zeros", jnp.int32), ("n_sig", jnp.int32),
    ("n_digit_chars", jnp.int32), ("decimal_pos", jnp.int32),
    ("dot_in_run", jnp.bool_), ("val19", jnp.uint64), ("d20", jnp.uint64),
    ("has_exp", jnp.bool_), ("exp_neg", jnp.bool_), ("exp_val", jnp.int32),
    ("exp_digits", jnp.int32), ("has_suffix", jnp.bool_),
    ("tail_nonws", jnp.bool_), ("tail0_nonws", jnp.bool_),
]


def _scan(col: StringColumn):
    """Per-row parse fields as a dict of device arrays.

    Runs the padded-sweep kernel per length bucket (columnar/buckets.py) so a
    long outlier doesn't pad the whole column, then scatters fields back.
    """
    outs = map_buckets(
        col,
        _scan_padded,
        [((), dt) for _, dt in _SCAN_FIELDS],
    )
    return {k: v for (k, _), v in zip(_SCAN_FIELDS, outs)}


# twin: s2f_scan
def _scan_padded(padded, lens, max_exp_digits: int = 4):
    """Padded-view parse sweep over one [n, L] byte rectangle (jitted alias
    ``_scan_padded_jit`` below for callers composing it with other jits).

    ``max_exp_digits``: the Spark cast reads at most 4 manual-exponent digits
    (parse_manual_exp :505) — a cast-only quirk.  JSON number re-rendering
    passes the full text width instead, with the accumulated value saturated
    (huge exponents must become 0.0/Infinity, not parse errors)."""
    n, L = padded.shape
    lens = lens.astype(jnp.int32)
    pos_mat = jnp.arange(L, dtype=jnp.int32)[None, :]
    in_str = pos_mat < lens[:, None]
    c = padded
    lower = jnp.where((c >= 65) & (c <= 90), c + 32, c)  # ascii tolower

    is_ws = ((c <= 0x1F) | (c == 32)) & in_str
    is_digit = (c >= 48) & (c <= 57) & in_str
    is_dot = (c == 46) & in_str

    def first_true(mask, default):
        """index of first True per row, else default."""
        any_ = jnp.any(mask, axis=1)
        idx = jnp.argmax(mask, axis=1).astype(jnp.int32)
        return jnp.where(any_, idx, jnp.int32(default))

    def char_at(p):
        """lowercased char at position p (0 beyond string)."""
        pc = jnp.clip(p, 0, L - 1)
        v = jnp.take_along_axis(lower, pc[:, None], axis=1)[:, 0]
        return jnp.where((p >= 0) & (p < lens), v, jnp.uint8(0))

    # leading whitespace: first position that is not whitespace (positions at
    # or beyond the string end count as non-ws, so all-ws rows land on lens)
    ws_end = jnp.minimum(first_true(~is_ws, L), lens)
    all_ws = ws_end >= lens

    c0 = char_at(ws_end)
    has_sign = (c0 == ord("+")) | (c0 == ord("-"))
    negative = c0 == ord("-")
    p0 = ws_end + has_sign.astype(jnp.int32)

    def match(p, word):
        ok = jnp.ones((n,), jnp.bool_)
        for k, ch in enumerate(word):
            ok &= char_at(p + k) == ord(ch)
        return ok

    is_nan = match(p0, "nan")
    inf3 = match(p0, "inf")
    inf8 = inf3 & match(p0 + 3, "inity")
    inf_exact = (inf3 & (p0 + 3 == lens)) | (inf8 & (p0 + 8 == lens))

    # ---- digit run [p0, stop) : digits plus at most the first dot ----
    after_p0 = pos_mat >= p0[:, None]
    dot_in_tail = is_dot & after_p0
    first_dot = first_true(dot_in_tail, L)
    run_char = is_digit | (pos_mat == first_dot[:, None])
    # break at first position >= p0 that is not a run char
    brk = after_p0 & ~run_char
    stop = first_true(brk, L)
    stop = jnp.minimum(stop, lens)
    in_run = after_p0 & (pos_mat < stop[:, None])
    dot_in_run = (first_dot < stop) & (first_dot >= p0)
    digit_in_run = is_digit & in_run

    # leading zeros before the dot (while the value is still zero)
    nonzero_digit = digit_in_run & (c != 48)
    first_sig = first_true(nonzero_digit, L)  # first nonzero digit anywhere
    pre_dot = pos_mat < first_dot[:, None]
    lead_zero = digit_in_run & pre_dot & (pos_mat < first_sig[:, None])
    n_lead_zeros = jnp.sum(lead_zero, axis=1).astype(jnp.int32)

    sig_mask = digit_in_run & ~lead_zero
    n_sig = jnp.sum(sig_mask, axis=1).astype(jnp.int32)  # digit chars kept
    n_digit_chars = jnp.sum(digit_in_run, axis=1).astype(jnp.int32)
    # significant digits before the dot
    decimal_pos = jnp.sum(sig_mask & pre_dot, axis=1).astype(jnp.int32)

    # rank of each significant digit (0-based within the kept sequence);
    # value of the first min(n_sig, 19) digits as u64, plus the 20th digit
    # (post-dot zeros count as significant chars but keep the value small, so
    # the reference's +1-digit rule is reachable for 0.00...ddd inputs)
    rank = jnp.cumsum(sig_mask.astype(jnp.int32), axis=1) - 1
    pow10 = jnp.asarray(np.array([10**k for k in range(20)], dtype=np.uint64))
    digit_vals = (c - jnp.uint8(48)).astype(jnp.uint64)
    k19 = jnp.minimum(n_sig, 19)
    take19 = sig_mask & (rank < 19)
    w19 = pow10[jnp.clip(jnp.where(take19, (k19[:, None] - 1 - rank), 0), 0, 19)]
    val19 = jnp.sum(jnp.where(take19, digit_vals * w19, jnp.uint64(0)), axis=1)
    d20 = jnp.sum(
        jnp.where(sig_mask & (rank == 19), digit_vals, jnp.uint64(0)), axis=1
    )

    # ---- manual exponent at `stop` ----
    ce = char_at(stop)
    has_exp = ce == ord("e")
    pe = stop + 1
    cs = char_at(pe)
    exp_has_sign = has_exp & ((cs == ord("+")) | (cs == ord("-")))
    exp_neg = exp_has_sign & (cs == ord("-"))
    pd = pe + exp_has_sign.astype(jnp.int32)
    # up to max_exp_digits digit chars considered; the value saturates so
    # absurdly long exponents stay order-of-magnitude correct (-> 0.0/inf)
    exp_digits = jnp.zeros((n,), jnp.int32)
    exp_val = jnp.zeros((n,), jnp.int32)
    still = jnp.ones((n,), jnp.bool_)
    for k in range(max_exp_digits):
        ck = char_at(pd + k)
        is_d = (ck >= 48) & (ck <= 57) & still & (pd + k < lens)
        exp_val = jnp.where(
            is_d,
            jnp.minimum(exp_val * 10 + (ck - 48).astype(jnp.int32), 99999),
            exp_val)
        exp_digits = exp_digits + is_d.astype(jnp.int32)
        still = still & is_d
    p_after_exp = jnp.where(has_exp, pd + exp_digits, stop)

    # ---- trailing: one f/d then whitespace then end ----
    cf = char_at(p_after_exp)
    has_suffix = (cf == ord("f")) | (cf == ord("d"))
    pt = p_after_exp + has_suffix.astype(jnp.int32)
    tail = (pos_mat >= pt[:, None]) & in_str
    tail_nonws = jnp.any(tail & ~is_ws, axis=1)

    # zero-value rows allow only whitespace after the number (no f/d suffix)
    tail0 = (pos_mat >= p_after_exp[:, None]) & in_str
    tail0_nonws = jnp.any(tail0 & ~is_ws, axis=1)

    fields = dict(
        lens=lens, all_ws=all_ws, negative=negative,
        is_nan=is_nan, inf3=inf3, inf_exact=inf_exact,
        n_lead_zeros=n_lead_zeros, n_sig=n_sig, n_digit_chars=n_digit_chars,
        decimal_pos=decimal_pos, dot_in_run=dot_in_run,
        val19=val19, d20=d20,
        has_exp=has_exp, exp_neg=exp_neg, exp_val=exp_val,
        exp_digits=exp_digits,
        has_suffix=has_suffix, tail_nonws=tail_nonws, tail0_nonws=tail0_nonws,
    )
    return tuple(fields[k].astype(dt) for k, dt in _SCAN_FIELDS)


# twin: s2f_scan
def _scan_padded_np(padded, lens, max_exp_digits: int = 4):
    """numpy twin of _scan_padded: the same single-pass prefix-mask sweep
    over one [n, L] byte rectangle, lane-for-lane (round 20)."""
    n, L = padded.shape
    lens = lens.astype(np.int32)
    pos_mat = np.arange(L, dtype=np.int32)[None, :]
    in_str = pos_mat < lens[:, None]
    c = padded
    lower = np.where((c >= 65) & (c <= 90), c + 32, c)  # ascii tolower

    is_ws = ((c <= 0x1F) | (c == 32)) & in_str
    is_digit = (c >= 48) & (c <= 57) & in_str
    is_dot = (c == 46) & in_str

    def first_true(mask, default):
        """index of first True per row, else default."""
        any_ = np.any(mask, axis=1)
        idx = np.argmax(mask, axis=1).astype(np.int32)
        return np.where(any_, idx, np.int32(default))

    def char_at(p):
        """lowercased char at position p (0 beyond string)."""
        pc = np.clip(p, 0, L - 1)
        v = np.take_along_axis(lower, pc[:, None], axis=1)[:, 0]
        return np.where((p >= 0) & (p < lens), v, np.uint8(0))

    ws_end = np.minimum(first_true(~is_ws, L), lens)
    all_ws = ws_end >= lens

    c0 = char_at(ws_end)
    has_sign = (c0 == ord("+")) | (c0 == ord("-"))
    negative = c0 == ord("-")
    p0 = ws_end + has_sign.astype(np.int32)

    def match(p, word):
        ok = np.ones((n,), np.bool_)
        for k, ch in enumerate(word):
            ok &= char_at(p + k) == ord(ch)
        return ok

    is_nan = match(p0, "nan")
    inf3 = match(p0, "inf")
    inf8 = inf3 & match(p0 + 3, "inity")
    inf_exact = (inf3 & (p0 + 3 == lens)) | (inf8 & (p0 + 8 == lens))

    after_p0 = pos_mat >= p0[:, None]
    dot_in_tail = is_dot & after_p0
    first_dot = first_true(dot_in_tail, L)
    run_char = is_digit | (pos_mat == first_dot[:, None])
    brk = after_p0 & ~run_char
    stop = first_true(brk, L)
    stop = np.minimum(stop, lens)
    in_run = after_p0 & (pos_mat < stop[:, None])
    dot_in_run = (first_dot < stop) & (first_dot >= p0)
    digit_in_run = is_digit & in_run

    nonzero_digit = digit_in_run & (c != 48)
    first_sig = first_true(nonzero_digit, L)
    pre_dot = pos_mat < first_dot[:, None]
    lead_zero = digit_in_run & pre_dot & (pos_mat < first_sig[:, None])
    n_lead_zeros = np.sum(lead_zero, axis=1).astype(np.int32)

    sig_mask = digit_in_run & ~lead_zero
    n_sig = np.sum(sig_mask, axis=1).astype(np.int32)
    n_digit_chars = np.sum(digit_in_run, axis=1).astype(np.int32)
    decimal_pos = np.sum(sig_mask & pre_dot, axis=1).astype(np.int32)

    rank = np.cumsum(sig_mask.astype(np.int32), axis=1) - 1
    pow10 = np.array([10**k for k in range(20)], dtype=np.uint64)
    digit_vals = (c - np.uint8(48)).astype(np.uint64)
    k19 = np.minimum(n_sig, 19)
    take19 = sig_mask & (rank < 19)
    w19 = pow10[np.clip(np.where(take19, (k19[:, None] - 1 - rank), 0), 0, 19)]
    val19 = np.sum(np.where(take19, digit_vals * w19, np.uint64(0)), axis=1)
    d20 = np.sum(
        np.where(sig_mask & (rank == 19), digit_vals, np.uint64(0)), axis=1
    )

    ce = char_at(stop)
    has_exp = ce == ord("e")
    pe = stop + 1
    cs = char_at(pe)
    exp_has_sign = has_exp & ((cs == ord("+")) | (cs == ord("-")))
    exp_neg = exp_has_sign & (cs == ord("-"))
    pd = pe + exp_has_sign.astype(np.int32)
    exp_digits = np.zeros((n,), np.int32)
    exp_val = np.zeros((n,), np.int32)
    still = np.ones((n,), np.bool_)
    for k in range(max_exp_digits):
        ck = char_at(pd + k)
        is_d = (ck >= 48) & (ck <= 57) & still & (pd + k < lens)
        exp_val = np.where(
            is_d,
            np.minimum(exp_val * 10 + (ck - 48).astype(np.int32), 99999),
            exp_val)
        exp_digits = exp_digits + is_d.astype(np.int32)
        still = still & is_d
    p_after_exp = np.where(has_exp, pd + exp_digits, stop)

    cf = char_at(p_after_exp)
    has_suffix = (cf == ord("f")) | (cf == ord("d"))
    pt = p_after_exp + has_suffix.astype(np.int32)
    tail = (pos_mat >= pt[:, None]) & in_str
    tail_nonws = np.any(tail & ~is_ws, axis=1)

    tail0 = (pos_mat >= p_after_exp[:, None]) & in_str
    tail0_nonws = np.any(tail0 & ~is_ws, axis=1)

    fields = dict(
        lens=lens, all_ws=all_ws, negative=negative,
        is_nan=is_nan, inf3=inf3, inf_exact=inf_exact,
        n_lead_zeros=n_lead_zeros, n_sig=n_sig, n_digit_chars=n_digit_chars,
        decimal_pos=decimal_pos, dot_in_run=dot_in_run,
        val19=val19, d20=d20,
        has_exp=has_exp, exp_neg=exp_neg, exp_val=exp_val,
        exp_digits=exp_digits,
        has_suffix=has_suffix, tail_nonws=tail_nonws, tail0_nonws=tail0_nonws,
    )
    return fields


def _scan_rect_np(padded, lens):
    """Optimized host scan over one zero-filled [n, L] rectangle.

    Equivalent to _scan_padded_np (the pinned twin mirror, kept as the
    cheap parity oracle) but restructured for throughput: the run counts
    collapse to O(n) boundary arithmetic (the run is contiguous, so counting
    chars is subtracting positions), the 19-digit value accumulates as a
    Horner sweep over transposed contiguous columns instead of a
    rank-cumsum + pow10 gather over uint64 rectangles, and the tail checks
    reduce to one last-non-ws position per row.  Requires bytes at and
    beyond each row's length to be zero (see _scan_np's rectangle build).
    """
    n, L = padded.shape
    lens = lens.astype(np.int32)
    c = padded
    nonws = (c > 0x1F) & (c != 32)  # sentinel \0 counts as ws
    is_digit = (c - np.uint8(48)) <= 9  # uint8 wraparound: one compare
    pos_mat = np.arange(L, dtype=np.int32)[None, :]
    # one-column gathers run as flat fancy indexing over a shared row-offset
    # vector: np.take_along_axis pays index broadcasting + a (n, 1) reshape
    # per call, which dominates these O(n) probes on a memory-bound host
    rowoff = np.arange(n, dtype=np.int64) * L
    cflat = c.reshape(-1)

    def first_true(mask, default):
        # checking mask at its own argmax is cheaper than a second np.any
        # reduction over the whole rectangle
        idx = np.argmax(mask, axis=1).astype(np.int32)
        found = mask.reshape(-1)[rowoff + idx]
        return np.where(found, idx, np.int32(default))

    def char_at(p):
        """lowercased char at position p (0 beyond string)."""
        pc = np.clip(p, 0, L - 1)
        v = cflat[rowoff + pc]
        v = np.where((v >= 65) & (v <= 90), v + 32, v)
        return np.where((p >= 0) & (p < lens), v, np.uint8(0))

    ws_end = np.minimum(first_true(nonws, L), lens)
    all_ws = ws_end >= lens

    c0 = char_at(ws_end)
    has_sign = (c0 == ord("+")) | (c0 == ord("-"))
    negative = c0 == ord("-")
    p0 = ws_end + has_sign.astype(np.int32)

    # nan / inf / infinity: only rows whose first payload char is n/i can
    # match, so the 8-char block compare runs on that (usually tiny) subset
    cp0 = char_at(p0)
    cand = np.nonzero((cp0 == ord("n")) | (cp0 == ord("i")))[0]
    is_nan = np.zeros((n,), np.bool_)
    inf3 = np.zeros((n,), np.bool_)
    inf_exact = np.zeros((n,), np.bool_)
    if cand.size:
        cs_ = c[cand]
        ps = p0[cand]
        ar8 = np.arange(8, dtype=np.int32)
        g = np.take_along_axis(cs_, np.minimum(ps[:, None] + ar8, L - 1), axis=1)
        g = np.where((g >= 65) & (g <= 90), g + 32, g)
        g = np.where(ps[:, None] + ar8 < lens[cand][:, None], g, np.uint8(0))

        def match(k0, word):
            ok = np.ones((cand.size,), np.bool_)
            for k, ch in enumerate(word):
                ok &= g[:, k0 + k] == ord(ch)
            return ok

        nan_s = match(0, "nan")
        inf3_s = match(0, "inf")
        inf8_s = inf3_s & match(3, "inity")
        is_nan[cand] = nan_s
        inf3[cand] = inf3_s
        inf_exact[cand] = (inf3_s & (ps + 3 == lens[cand])) | (
            inf8_s & (ps + 8 == lens[cand])
        )

    # digit run [p0, stop): contiguous digits plus at most the first dot.
    # Only ws/sign chars precede p0, so the first dot / first nonzero digit
    # anywhere IS the first one at >= p0 — no after-p0 masking needed.
    first_dot = first_true(c == 46, L)
    run_char = is_digit | (pos_mat == first_dot[:, None])
    after_p0 = pos_mat >= p0[:, None]
    stop = np.minimum(first_true(after_p0 & ~run_char, L), lens)
    dot_in_run = (first_dot < stop) & (first_dot >= p0)
    first_sig = first_true((c - np.uint8(49)) <= 8, L)  # c in '1'..'9'

    # the run is contiguous, so every count is boundary arithmetic:
    # [p0, min(first_dot, first_sig, stop)) are exactly the leading zeros
    n_digit_chars = (stop - p0) - dot_in_run.astype(np.int32)
    n_lead_zeros = np.minimum(np.minimum(first_dot, first_sig), stop) - p0
    n_sig = n_digit_chars - n_lead_zeros
    decimal_pos = np.minimum(first_dot, stop) - p0 - n_lead_zeros

    # first-19-digit value: Horner sweep over transposed contiguous columns.
    # sig = in-run digit past the dot or at/after the first nonzero digit,
    # i.e. a digit at position in [min(first_dot + 1, first_sig), stop).
    # The u8 digit columns feed the u64 accumulator unconverted — numpy's
    # buffered in-ufunc cast is ~35% cheaper than materializing a u64
    # column per iteration on a memory-bound host.
    sig_lo = np.minimum(first_dot + 1, first_sig)
    # sig = in-run digit at position >= sig_lo; the per-column flags come
    # from the one transposed digit rectangle plus two O(n) scalar-vs-row
    # compares per column — no (n, L) sig mask or second transpose copy
    dig_t = np.ascontiguousarray(c.T) - np.uint8(48)
    digit_t = dig_t <= np.uint8(9)
    val19 = np.zeros((n,), np.uint64)
    d20 = np.zeros((n,), np.uint64)
    cnt = np.zeros((n,), np.int32)
    capped = bool((n_sig > 19).any())  # else cnt never reaches 19
    one, nine = np.uint8(1), np.uint8(9)
    for j in range(min(L, int(stop.max(initial=0)))):  # sig positions < stop
        sig_j = digit_t[j] & (sig_lo <= j) & (j < stop)
        d_j = dig_t[j]
        take = sig_j & (cnt < 19) if capped else sig_j
        # val19 = val19 * 10 + d_j where take, else unchanged — as two
        # in-place u64 ops with arithmetic selects (x10/x1 multiplier,
        # digit-or-zero addend): no np.where temporaries on the hot loop
        np.multiply(val19, one + take * nine, out=val19, casting="unsafe")
        np.add(val19, d_j * take, out=val19, casting="unsafe")
        if capped:
            d20 = np.where(sig_j & (cnt == 19), d_j, d20)
            cnt += sig_j
    # mirror semantics: np.minimum(n_sig, 19) digits accumulated, 20th in d20

    # manual exponent: 4-char block gather at pd, then one vectorized
    # consecutive-digit accumulate (4 digits max out at 9999, so the
    # mirror's 99999 saturation clamp can never fire here)
    ce = char_at(stop)
    has_exp = ce == ord("e")
    pe = stop + 1
    cs2 = char_at(pe)
    exp_has_sign = has_exp & ((cs2 == ord("+")) | (cs2 == ord("-")))
    exp_neg = exp_has_sign & (cs2 == ord("-"))
    pd = pe + exp_has_sign.astype(np.int32)
    ar4 = np.arange(4, dtype=np.int32)
    ge = cflat[rowoff[:, None] + np.clip(pd[:, None] + ar4, 0, L - 1)]
    dmask = ((ge - np.uint8(48)) <= 9) & (pd[:, None] + ar4 < lens[:, None])
    run4 = np.logical_and.accumulate(dmask, axis=1)
    exp_digits = np.sum(run4, axis=1).astype(np.int32)
    pw4 = np.array([1, 10, 100, 1000], np.int32)
    shift = np.clip(exp_digits[:, None] - 1 - ar4, 0, 3)
    exp_val = np.sum(
        run4 * (ge - np.uint8(48)).astype(np.int32) * pw4[shift], axis=1
    ).astype(np.int32)
    p_after_exp = np.where(has_exp, pd + exp_digits, stop)

    cf = char_at(p_after_exp)
    has_suffix = (cf == ord("f")) | (cf == ord("d"))
    pt = p_after_exp + has_suffix.astype(np.int32)
    # trailing checks via the last non-ws position (sentinel zeros are ws)
    nonws_rev = nonws[:, ::-1]
    last_nonws = np.where(
        all_ws,  # all_ws == "no non-ws byte anywhere" (sentinel \0 is ws)
        np.int32(-1),
        np.int32(L - 1) - np.argmax(nonws_rev, axis=1).astype(np.int32),
    )
    tail_nonws = last_nonws >= pt
    tail0_nonws = last_nonws >= p_after_exp

    return dict(
        lens=lens, all_ws=all_ws, negative=negative,
        is_nan=is_nan, inf3=inf3, inf_exact=inf_exact,
        n_lead_zeros=n_lead_zeros, n_sig=n_sig, n_digit_chars=n_digit_chars,
        decimal_pos=decimal_pos, dot_in_run=dot_in_run,
        val19=val19, d20=d20,
        has_exp=has_exp, exp_neg=exp_neg, exp_val=exp_val,
        exp_digits=exp_digits,
        has_suffix=has_suffix, tail_nonws=tail_nonws, tail0_nonws=tail0_nonws,
    )


_SCAN_FIELDS_NP = {
    "lens": np.int32, "all_ws": np.bool_, "negative": np.bool_,
    "is_nan": np.bool_, "inf3": np.bool_, "inf_exact": np.bool_,
    "n_lead_zeros": np.int32, "n_sig": np.int32, "n_digit_chars": np.int32,
    "decimal_pos": np.int32, "dot_in_run": np.bool_, "val19": np.uint64,
    "d20": np.uint64, "has_exp": np.bool_, "exp_neg": np.bool_,
    "exp_val": np.int32, "exp_digits": np.int32, "has_suffix": np.bool_,
    "tail_nonws": np.bool_, "tail0_nonws": np.bool_,
}


def _scan_np(col: StringColumn):
    """Host mirror of _scan: pow2 length buckets over the numpy byte arrays
    (so short numerics never pay a long outlier's rectangle), each scanned by
    _scan_rect_np over a zero-filled rectangle clamped to the bucket's true
    max length (host rectangles have no jit shape cache to feed, so nothing
    forces the width itself up to a power of two)."""
    with PHASES.phase("bucket"):
        chars = np.asarray(col.chars)
        offsets = np.asarray(col.offsets)
        lens_all = (offsets[1:] - offsets[:-1]).astype(np.int32)
        n = lens_all.shape[0]
        buckets = length_buckets(lens_all, min_width=4)
        # bucketing only pays when it prunes padded work (long outliers);
        # a flat length profile runs as ONE rectangle, skipping the
        # per-field scatter-backs entirely
        w_max = int(lens_all.max(initial=0))
        bucketed_work = sum(w * nv for w, _, nv in buckets)
        mono = bool(n and n * w_max <= bucketed_work)
        if mono:
            buckets = [(w_max, np.arange(n, dtype=np.int64), n)]
    outs = {k: np.zeros(n, dt) for k, dt in _SCAN_FIELDS_NP.items()}
    for _, rows_np, n_valid in buckets:
        with PHASES.phase("bucket"):
            rows_np = rows_np[:n_valid]
            lens = lens_all[rows_np]
            width = max(int(lens.max(initial=0)), 1)
            in_row = np.arange(width, dtype=np.int32)[None, :] < lens[:, None]
            if mono:
                # all rows in offset order: the chars buffer between
                # offsets[0] and offsets[-1] IS the row-major concatenation
                # of every row's bytes, so one boolean scatter fills the
                # rectangle — no (n, W) int32 index matrix, no gather, no
                # zeroing multiply
                padded = np.zeros((n_valid, width), np.uint8)
                padded[in_row] = chars[int(offsets[0]):int(offsets[-1])]
            else:
                starts = offsets[rows_np].astype(np.int32)
                idx = starts[:, None] + np.arange(
                    width, dtype=np.int32)[None, :]
                pad_chars = np.concatenate(
                    [chars, np.zeros((width,), np.uint8)]
                )
                padded = pad_chars[idx]
                padded *= in_row
        with PHASES.phase("parse"):
            fields = _scan_rect_np(padded, lens)
        with PHASES.phase("bucket"):
            if n_valid == n:
                for k, dt in _SCAN_FIELDS_NP.items():
                    outs[k] = fields[k].astype(dt)
            else:
                for k, dt in _SCAN_FIELDS_NP.items():
                    outs[k][rows_np] = fields[k].astype(dt)
    return outs


_EXP10_BITS = _EXP10.view(np.int64)
_POW10_U64 = np.array([10**k for k in range(20)], dtype=np.uint64)
_NAN_BITS = np.int64(np.float64(np.nan).view(np.int64))


def _exp10_bits(k):
    """binary64 bit pattern of 10^k (same clipped table as _exp10)."""
    idx = jnp.clip(k + _EXP10_OFFSET, 0, len(_EXP10) - 1)
    return jnp.asarray(_EXP10_BITS)[idx]


# twin: s2f_assemble
@jax.jit
def _assemble_device(f):
    """Device replication of the reference's final double assembly
    (cast_string_to_float.cu:134-199) in exact integer binary64 arithmetic
    (utils/softfloat) — TPU f64 is emulated, so the bit-exact math runs as
    uint64 lane ops.  Returns (bits int64, valid, except_) device arrays;
    the host `_assemble` remains as the debug/equivalence oracle."""
    lens = f["lens"].astype(jnp.int64)
    neg = f["negative"]
    sign_bit = neg.astype(jnp.int64) << jnp.int64(63)
    n = lens.shape[0]

    valid = jnp.ones((n,), bool)
    except_ = jnp.zeros((n,), bool)

    nan_rows = f["is_nan"]
    bad_nan = nan_rows & (lens != 3)
    valid &= ~bad_nan
    except_ |= bad_nan

    inf_rows = f["inf3"] & ~nan_rows
    ok_inf = inf_rows & f["inf_exact"]
    valid &= ~(inf_rows & ~f["inf_exact"])  # no ANSI error (cu :276)

    plain = ~nan_rows & ~inf_rows
    seen_digit = (f["n_digit_chars"] > 0) | (f["n_lead_zeros"] > 0)
    no_digits = plain & ~seen_digit
    valid &= ~no_digits
    except_ |= no_digits

    # 19-significant-char accumulation + truncation accounting (:395-445)
    n_sig = f["n_sig"].astype(jnp.int64)
    val19 = f["val19"]
    over = n_sig > 19
    can_add = over & (val19 <= jnp.uint64(MAX_HOLDING)) & (
        val19 * jnp.uint64(10) + f["d20"] <= jnp.uint64(MAX_HOLDING)
    )
    digits = jnp.where(can_add, val19 * jnp.uint64(10) + f["d20"], val19)
    real_digits = jnp.minimum(n_sig, 19)
    truncated = jnp.where(can_add, n_sig - 18, jnp.where(over, n_sig - 19, 0))
    total_digits = real_digits + truncated
    exp_base = truncated - jnp.where(
        f["dot_in_run"], total_digits - f["decimal_pos"].astype(jnp.int64), 0
    )

    bad_exp = plain & f["has_exp"] & (f["exp_digits"] == 0)
    valid &= ~bad_exp
    except_ |= bad_exp
    manual = jnp.where(f["exp_neg"], -f["exp_val"], f["exp_val"]).astype(jnp.int64)
    manual = jnp.where(f["has_exp"], manual, 0)

    zero = plain & (digits == 0) & seen_digit
    bad_zero_tail = zero & f["tail0_nonws"]
    valid &= ~bad_zero_tail
    except_ |= bad_zero_tail

    nonzero = plain & (digits != 0)
    bad_tail = nonzero & f["tail_nonws"]
    valid &= ~bad_tail
    except_ |= bad_tail

    # final assembly (:153-199) in softfloat binary64
    exp_ten = exp_base + manual
    digits_bits = u64_to_f64_bits(digits) | sign_bit
    nd = jnp.ones((n,), jnp.int64)  # decimal digit count of `digits`
    for k in range(1, 20):
        nd += (digits >= _POW10_U64[k]).astype(jnp.int64)

    too_big = exp_ten > 308
    sub_shift = -307 - exp_ten
    subnormal = ~too_big & (sub_shift > 0)
    dsub = f64_div_bits(digits_bits, _exp10_bits(nd - 1 + sub_shift))
    res_sub = f64_mul_bits(dsub, _exp10_bits(exp_ten + nd - 1 + sub_shift))
    e10 = _exp10_bits(jnp.abs(exp_ten))
    res_norm = jnp.where(
        exp_ten < 0,
        f64_div_bits(digits_bits, e10),
        f64_mul_bits(digits_bits, e10),
    )
    inf_bits = sign_bit | jnp.int64(0x7FF0000000000000)
    res = jnp.where(too_big, inf_bits,
                    jnp.where(subnormal, res_sub, res_norm))

    out = jnp.zeros((n,), jnp.int64)
    out = jnp.where(nan_rows, _NAN_BITS, out)
    out = jnp.where(ok_inf, inf_bits, out)
    out = jnp.where(zero, sign_bit, out)
    out = jnp.where(nonzero, res, out)
    return out, valid, except_


_scan_padded_jit = jax.jit(_scan_padded, static_argnums=(2,))


# twin: s2f_assemble
def _assemble(f, out_dtype_np):
    """Host: replicate the reference's final double assembly (:134-199).

    Promoted from debug oracle to the XLA:CPU fast path in round 20 (the
    backend-adaptive `cast_device_parse` dispatch): hardware binary64 is
    exactly the arithmetic the softfloat device twin emulates."""
    f = {k: np.asarray(v) for k, v in f.items()}
    lens = f["lens"].astype(np.int64)
    n = lens.shape[0]
    out = np.zeros((n,), np.float64)
    valid = np.ones((n,), bool)
    except_ = np.zeros((n,), bool)

    sign = np.where(f["negative"], -1.0, 1.0)

    # nan: always writes NaN; only the bare 3-char string is valid
    nan_rows = f["is_nan"]
    out[nan_rows] = np.nan
    bad_nan = nan_rows & (lens != 3)
    valid[bad_nan] = False
    except_[bad_nan] = True

    # inf / infinity
    inf_rows = f["inf3"] & ~nan_rows
    ok_inf = inf_rows & f["inf_exact"]
    out[ok_inf] = np.where(f["negative"][ok_inf], -np.inf, np.inf)
    valid[inf_rows & ~f["inf_exact"]] = False  # no ANSI error (cu :276 comment)

    plain = ~nan_rows & ~inf_rows

    # no digits at all -> invalid + except (includes empty / all-ws strings)
    seen_digit = (f["n_digit_chars"] > 0) | (f["n_lead_zeros"] > 0)
    no_digits = plain & ~seen_digit
    valid[no_digits] = False
    except_[no_digits] = True

    # 19-significant-char accumulation with the reference's truncation
    # accounting (cast_string_to_float.cu:395-445).  The "+1 digit" rule only
    # fires when post-dot zeros pad the window (value stays <= max_holding/10,
    # e.g. "0.0123...": zeros count as chars but not value); for a normalized
    # 19-digit value digits*10 always overflows max_holding.
    n_sig = f["n_sig"].astype(np.int64)
    val19 = f["val19"]
    real_digits = np.minimum(n_sig, 19)
    over = n_sig > 19
    # the val19 <= MAX_HOLDING clause both mirrors the reference's outer
    # check and keeps the *10 below from wrapping u64
    can_add = over & (val19 <= np.uint64(MAX_HOLDING)) & (
        val19 * np.uint64(10) + f["d20"] <= np.uint64(MAX_HOLDING)
    )
    digits = np.where(can_add, val19 * np.uint64(10) + f["d20"], val19)
    # bug-compat: the reference counts one extra truncated char when it adds
    # the 20th digit without incrementing real_digits (:437)
    truncated = np.where(can_add, n_sig - 18, np.where(over, n_sig - 19, 0))

    total_digits = real_digits + truncated
    exp_base = truncated - np.where(
        f["dot_in_run"], total_digits - f["decimal_pos"].astype(np.int64), 0
    )

    # manual exponent; 'e' with no digits is invalid
    bad_exp = plain & f["has_exp"] & (f["exp_digits"] == 0)
    valid[bad_exp] = False
    except_[bad_exp] = True
    manual = np.where(f["exp_neg"], -f["exp_val"], f["exp_val"]).astype(np.int64)
    manual = np.where(f["has_exp"], manual, 0)

    zero = plain & (digits == 0) & seen_digit
    bad_zero_tail = zero & f["tail0_nonws"]
    valid[bad_zero_tail] = False
    except_[bad_zero_tail] = True
    out = np.where(zero, sign * 0.0, out)

    nonzero = plain & (digits != 0)
    bad_tail = nonzero & f["tail_nonws"]
    valid[bad_tail] = False
    except_[bad_tail] = True

    # final assembly in binary64 (cast_string_to_float.cu:153-199)
    exp_ten = (exp_base + manual).astype(np.int64)
    digitsf = sign * digits.astype(np.float64)
    with np.errstate(over="ignore", invalid="ignore", divide="ignore"):
        res = np.zeros((n,), np.float64)
        too_big = exp_ten > 308
        res[too_big] = np.where(f["negative"][too_big], -np.inf, np.inf)
        sub_shift = -307 - exp_ten
        subnormal = ~too_big & (sub_shift > 0)
        if subnormal.any():
            nd = np.char.str_len(
                digits[subnormal].astype("U32")
            ).astype(np.int64)  # number of digits
            dsub = digitsf[subnormal] / _exp10(nd - 1 + sub_shift[subnormal])
            e2 = exp_ten[subnormal] + nd - 1 + sub_shift[subnormal]
            res[subnormal] = dsub * _exp10(e2)
        normal = ~too_big & ~subnormal
        exponent = _exp10(np.abs(exp_ten[normal]))
        dn = digitsf[normal]
        res[normal] = np.where(exp_ten[normal] < 0, dn / exponent, dn * exponent)
    out = np.where(nonzero, res, out)

    if out_dtype_np == np.float32:
        with np.errstate(over="ignore"):  # double->float32 overflow -> inf
            out = out.astype(np.float32)
    return out, valid, except_


def _device_parse_enabled() -> bool:
    v = config.get("cast_device_parse")
    if v == "auto":
        return jax.default_backend() != "cpu"
    return bool(v)


def _string_to_float_host(col: StringColumn, ansi_mode: bool, dtype: DType):
    """XLA:CPU arm: bucketed numpy scan + the hardware-binary64 assembly
    twin, no device round-trips (round 20)."""
    f = _scan_np(col)
    with PHASES.phase("assemble"):
        out_np = np.float32 if dtype.kind == Kind.FLOAT32 else np.float64
        out, valid, except_ = _assemble(f, out_np)

    in_valid = np.asarray(col.is_valid())
    except_ = except_ & in_valid
    if ansi_mode and except_.any():
        row = int(np.argmax(except_))
        offs = np.asarray(col.offsets)
        bad = bytes(np.asarray(col.chars)[offs[row] : offs[row + 1]])
        raise CastException(bad.decode("utf-8", errors="replace"), row)

    validity = jnp.asarray(valid & in_valid)
    if dtype.kind == Kind.FLOAT64:
        data = jnp.asarray(out.view(np.int64))  # bit-pattern convention
    else:
        data = jnp.asarray(out)
    return Column(data, validity, dtype)


def string_to_float(
    col: StringColumn, ansi_mode: bool, dtype: DType = FLOAT64
) -> Column:
    """Parse a string column into FLOAT32/FLOAT64 with Spark semantics.

    Invalid rows become null, or raise CastException (with the first bad row
    index) when ``ansi_mode`` (CastStringJni.cpp CATCH_CAST_EXCEPTION path).
    Backend-adaptive: on accelerators the lane scan + softfloat assembly run
    on device; on XLA:CPU the twin numpy pipeline avoids the transfer tax
    (``cast_device_parse`` pins either arm).
    """
    if dtype.kind not in (Kind.FLOAT32, Kind.FLOAT64):
        raise TypeError("string_to_float produces FLOAT32 or FLOAT64")
    if not _device_parse_enabled():
        return _string_to_float_host(col, ansi_mode, dtype)
    with PHASES.phase("parse"):
        f = _scan(col)
    with PHASES.phase("assemble"):
        bits, valid, except_ = _assemble_device(f)

    in_valid = col.is_valid()
    except_ = except_ & in_valid
    # error control flow is the one host decision: a scalar any() sync, with
    # the failing row's bytes pulled only on the (exceptional) throw path
    if ansi_mode and bool(jnp.any(except_)):
        row = int(jnp.argmax(except_))
        offs = np.asarray(col.offsets)
        bad = bytes(np.asarray(col.chars[offs[row] : offs[row + 1]]))
        raise CastException(bad.decode("utf-8", errors="replace"), row)

    validity = valid & in_valid
    if dtype.kind == Kind.FLOAT64:
        data = bits  # bit-pattern convention for FLOAT64 columns
    else:
        data = jax.lax.bitcast_convert_type(
            f64_bits_to_f32_bits(bits), jnp.float32
        )
    return Column(data, validity, dtype)
