"""Spark-exact string -> float32/float64 cast.

Behavioral parity with the reference's warp-per-row parser
(cast_string_to_float.cu:598 string_to_float_kernel; parse stages :86-557),
including its quirks:

- 'nan' (any case) is only valid as the exact 3-char string; leading
  whitespace/sign make it null (+ ANSI error) but still parse as nan
  (check_for_nan :243-260);
- 'inf'/'infinity' allow leading whitespace and sign, must end the string,
  and garbage after them is null WITHOUT an ANSI error (check_for_inf :276);
- at most 19 significant digits accumulate into a uint64; beyond that, digits
  truncate with the reference's exact (slightly lossy) exponent accounting
  (parse_digits :327-470, max_holding rule :395-445);
- manual exponents read at most 4 digits (parse_manual_exp :505);
- a single trailing f/F/d/D is allowed — except after a zero value, where only
  whitespace may follow (operator() :134-145);
- the final value is digits x 10^exp in IEEE binary64 (subnormal two-step
  :158-195), cast to float32 at the end for FLOAT32 outputs.

TPU split: the O(n x len) character scan is vectorized lane arithmetic on the
padded byte matrix (cummax prefix masks replace the warp ballot/shuffle
choreography).  The final O(n) digits->double assembly ALSO runs on device —
TPU f64 is float32-pair emulated and would not be bit-exact, so the binary64
multiply/divide/convert steps run as exact integer softfloat lane ops
(utils/softfloat; `_assemble_device`).  The host `_assemble` is kept as the
equivalence oracle.  The only host interaction is the ANSI error decision
(one scalar any() sync; row bytes are pulled only on the throw path).
Digit windows longer than one warp batch (32 chars) follow the single-batch
accounting rather than the reference's batch-boundary-dependent truncation
bookkeeping.

Known <=1-ulp divergence: for negative powers (10^-k) our table is the
correctly-rounded binary64 value, while CUDA's exp10 is occasionally 1 ulp
off (verified at exp10(-291)); this only shows in the extreme-exponent range
where the reference already deviates from Java's correctly-rounded parse.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu.columnar.buckets import map_buckets
from spark_rapids_jni_tpu.columnar.column import Column, StringColumn
from spark_rapids_jni_tpu.columnar.dtypes import DType, FLOAT64, Kind
from spark_rapids_jni_tpu.ops.cast_string import CastException
from spark_rapids_jni_tpu.utils.softfloat import (
    f64_bits_to_f32_bits,
    f64_div_bits,
    f64_mul_bits,
    u64_to_f64_bits,
)

MAX_SAFE_DIGITS = 19
MAX_HOLDING = ((1 << 64) - 1 - 9) // 10  # 1844674407370955160

# binary64 values of 10^k for k in [-360, 359].  Non-negative k: float(10**k)
# is correctly rounded (exact integer -> nearest double), overflowing to inf
# past 308, matching exp10 saturation.  Negative k: libm pow (what CUDA's
# exp10 effectively is within an ulp).
_EXP10_OFFSET = 360
_EXP10 = np.array(
    [float(np.power(10.0, k)) for k in range(-_EXP10_OFFSET, 0)]
    + [float(10**k) if k <= 308 else np.inf for k in range(360)],
    dtype=np.float64,
)


def _exp10(k: np.ndarray) -> np.ndarray:
    idx = np.clip(k + _EXP10_OFFSET, 0, len(_EXP10) - 1)
    return _EXP10[idx]


_SCAN_FIELDS = [
    ("lens", jnp.int32), ("all_ws", jnp.bool_), ("negative", jnp.bool_),
    ("is_nan", jnp.bool_), ("inf3", jnp.bool_), ("inf_exact", jnp.bool_),
    ("n_lead_zeros", jnp.int32), ("n_sig", jnp.int32),
    ("n_digit_chars", jnp.int32), ("decimal_pos", jnp.int32),
    ("dot_in_run", jnp.bool_), ("val19", jnp.uint64), ("d20", jnp.uint64),
    ("has_exp", jnp.bool_), ("exp_neg", jnp.bool_), ("exp_val", jnp.int32),
    ("exp_digits", jnp.int32), ("has_suffix", jnp.bool_),
    ("tail_nonws", jnp.bool_), ("tail0_nonws", jnp.bool_),
]


def _scan(col: StringColumn):
    """Per-row parse fields as a dict of device arrays.

    Runs the padded-sweep kernel per length bucket (columnar/buckets.py) so a
    long outlier doesn't pad the whole column, then scatters fields back.
    """
    outs = map_buckets(
        col,
        _scan_padded,
        [((), dt) for _, dt in _SCAN_FIELDS],
    )
    return {k: v for (k, _), v in zip(_SCAN_FIELDS, outs)}


def _scan_padded(padded, lens, max_exp_digits: int = 4):
    """Padded-view parse sweep over one [n, L] byte rectangle (jitted alias
    ``_scan_padded_jit`` below for callers composing it with other jits).

    ``max_exp_digits``: the Spark cast reads at most 4 manual-exponent digits
    (parse_manual_exp :505) — a cast-only quirk.  JSON number re-rendering
    passes the full text width instead, with the accumulated value saturated
    (huge exponents must become 0.0/Infinity, not parse errors)."""
    n, L = padded.shape
    lens = lens.astype(jnp.int32)
    pos_mat = jnp.arange(L, dtype=jnp.int32)[None, :]
    in_str = pos_mat < lens[:, None]
    c = padded
    lower = jnp.where((c >= 65) & (c <= 90), c + 32, c)  # ascii tolower

    is_ws = ((c <= 0x1F) | (c == 32)) & in_str
    is_digit = (c >= 48) & (c <= 57) & in_str
    is_dot = (c == 46) & in_str

    def first_true(mask, default):
        """index of first True per row, else default."""
        any_ = jnp.any(mask, axis=1)
        idx = jnp.argmax(mask, axis=1).astype(jnp.int32)
        return jnp.where(any_, idx, jnp.int32(default))

    def char_at(p):
        """lowercased char at position p (0 beyond string)."""
        pc = jnp.clip(p, 0, L - 1)
        v = jnp.take_along_axis(lower, pc[:, None], axis=1)[:, 0]
        return jnp.where((p >= 0) & (p < lens), v, jnp.uint8(0))

    # leading whitespace: first position that is not whitespace (positions at
    # or beyond the string end count as non-ws, so all-ws rows land on lens)
    ws_end = jnp.minimum(first_true(~is_ws, L), lens)
    all_ws = ws_end >= lens

    c0 = char_at(ws_end)
    has_sign = (c0 == ord("+")) | (c0 == ord("-"))
    negative = c0 == ord("-")
    p0 = ws_end + has_sign.astype(jnp.int32)

    def match(p, word):
        ok = jnp.ones((n,), jnp.bool_)
        for k, ch in enumerate(word):
            ok &= char_at(p + k) == ord(ch)
        return ok

    is_nan = match(p0, "nan")
    inf3 = match(p0, "inf")
    inf8 = inf3 & match(p0 + 3, "inity")
    inf_exact = (inf3 & (p0 + 3 == lens)) | (inf8 & (p0 + 8 == lens))

    # ---- digit run [p0, stop) : digits plus at most the first dot ----
    after_p0 = pos_mat >= p0[:, None]
    dot_in_tail = is_dot & after_p0
    first_dot = first_true(dot_in_tail, L)
    run_char = is_digit | (pos_mat == first_dot[:, None])
    # break at first position >= p0 that is not a run char
    brk = after_p0 & ~run_char
    stop = first_true(brk, L)
    stop = jnp.minimum(stop, lens)
    in_run = after_p0 & (pos_mat < stop[:, None])
    dot_in_run = (first_dot < stop) & (first_dot >= p0)
    digit_in_run = is_digit & in_run

    # leading zeros before the dot (while the value is still zero)
    nonzero_digit = digit_in_run & (c != 48)
    first_sig = first_true(nonzero_digit, L)  # first nonzero digit anywhere
    pre_dot = pos_mat < first_dot[:, None]
    lead_zero = digit_in_run & pre_dot & (pos_mat < first_sig[:, None])
    n_lead_zeros = jnp.sum(lead_zero, axis=1).astype(jnp.int32)

    sig_mask = digit_in_run & ~lead_zero
    n_sig = jnp.sum(sig_mask, axis=1).astype(jnp.int32)  # digit chars kept
    n_digit_chars = jnp.sum(digit_in_run, axis=1).astype(jnp.int32)
    # significant digits before the dot
    decimal_pos = jnp.sum(sig_mask & pre_dot, axis=1).astype(jnp.int32)

    # rank of each significant digit (0-based within the kept sequence);
    # value of the first min(n_sig, 19) digits as u64, plus the 20th digit
    # (post-dot zeros count as significant chars but keep the value small, so
    # the reference's +1-digit rule is reachable for 0.00...ddd inputs)
    rank = jnp.cumsum(sig_mask.astype(jnp.int32), axis=1) - 1
    pow10 = jnp.asarray(np.array([10**k for k in range(20)], dtype=np.uint64))
    digit_vals = (c - jnp.uint8(48)).astype(jnp.uint64)
    k19 = jnp.minimum(n_sig, 19)
    take19 = sig_mask & (rank < 19)
    w19 = pow10[jnp.clip(jnp.where(take19, (k19[:, None] - 1 - rank), 0), 0, 19)]
    val19 = jnp.sum(jnp.where(take19, digit_vals * w19, jnp.uint64(0)), axis=1)
    d20 = jnp.sum(
        jnp.where(sig_mask & (rank == 19), digit_vals, jnp.uint64(0)), axis=1
    )

    # ---- manual exponent at `stop` ----
    ce = char_at(stop)
    has_exp = ce == ord("e")
    pe = stop + 1
    cs = char_at(pe)
    exp_has_sign = has_exp & ((cs == ord("+")) | (cs == ord("-")))
    exp_neg = exp_has_sign & (cs == ord("-"))
    pd = pe + exp_has_sign.astype(jnp.int32)
    # up to max_exp_digits digit chars considered; the value saturates so
    # absurdly long exponents stay order-of-magnitude correct (-> 0.0/inf)
    exp_digits = jnp.zeros((n,), jnp.int32)
    exp_val = jnp.zeros((n,), jnp.int32)
    still = jnp.ones((n,), jnp.bool_)
    for k in range(max_exp_digits):
        ck = char_at(pd + k)
        is_d = (ck >= 48) & (ck <= 57) & still & (pd + k < lens)
        exp_val = jnp.where(
            is_d,
            jnp.minimum(exp_val * 10 + (ck - 48).astype(jnp.int32), 99999),
            exp_val)
        exp_digits = exp_digits + is_d.astype(jnp.int32)
        still = still & is_d
    p_after_exp = jnp.where(has_exp, pd + exp_digits, stop)

    # ---- trailing: one f/d then whitespace then end ----
    cf = char_at(p_after_exp)
    has_suffix = (cf == ord("f")) | (cf == ord("d"))
    pt = p_after_exp + has_suffix.astype(jnp.int32)
    tail = (pos_mat >= pt[:, None]) & in_str
    tail_nonws = jnp.any(tail & ~is_ws, axis=1)

    # zero-value rows allow only whitespace after the number (no f/d suffix)
    tail0 = (pos_mat >= p_after_exp[:, None]) & in_str
    tail0_nonws = jnp.any(tail0 & ~is_ws, axis=1)

    fields = dict(
        lens=lens, all_ws=all_ws, negative=negative,
        is_nan=is_nan, inf3=inf3, inf_exact=inf_exact,
        n_lead_zeros=n_lead_zeros, n_sig=n_sig, n_digit_chars=n_digit_chars,
        decimal_pos=decimal_pos, dot_in_run=dot_in_run,
        val19=val19, d20=d20,
        has_exp=has_exp, exp_neg=exp_neg, exp_val=exp_val,
        exp_digits=exp_digits,
        has_suffix=has_suffix, tail_nonws=tail_nonws, tail0_nonws=tail0_nonws,
    )
    return tuple(fields[k].astype(dt) for k, dt in _SCAN_FIELDS)


_EXP10_BITS = _EXP10.view(np.int64)
_POW10_U64 = np.array([10**k for k in range(20)], dtype=np.uint64)
_NAN_BITS = np.int64(np.float64(np.nan).view(np.int64))


def _exp10_bits(k):
    """binary64 bit pattern of 10^k (same clipped table as _exp10)."""
    idx = jnp.clip(k + _EXP10_OFFSET, 0, len(_EXP10) - 1)
    return jnp.asarray(_EXP10_BITS)[idx]


@jax.jit
def _assemble_device(f):
    """Device replication of the reference's final double assembly
    (cast_string_to_float.cu:134-199) in exact integer binary64 arithmetic
    (utils/softfloat) — TPU f64 is emulated, so the bit-exact math runs as
    uint64 lane ops.  Returns (bits int64, valid, except_) device arrays;
    the host `_assemble` remains as the debug/equivalence oracle."""
    lens = f["lens"].astype(jnp.int64)
    neg = f["negative"]
    sign_bit = neg.astype(jnp.int64) << jnp.int64(63)
    n = lens.shape[0]

    valid = jnp.ones((n,), bool)
    except_ = jnp.zeros((n,), bool)

    nan_rows = f["is_nan"]
    bad_nan = nan_rows & (lens != 3)
    valid &= ~bad_nan
    except_ |= bad_nan

    inf_rows = f["inf3"] & ~nan_rows
    ok_inf = inf_rows & f["inf_exact"]
    valid &= ~(inf_rows & ~f["inf_exact"])  # no ANSI error (cu :276)

    plain = ~nan_rows & ~inf_rows
    seen_digit = (f["n_digit_chars"] > 0) | (f["n_lead_zeros"] > 0)
    no_digits = plain & ~seen_digit
    valid &= ~no_digits
    except_ |= no_digits

    # 19-significant-char accumulation + truncation accounting (:395-445)
    n_sig = f["n_sig"].astype(jnp.int64)
    val19 = f["val19"]
    over = n_sig > 19
    can_add = over & (val19 <= jnp.uint64(MAX_HOLDING)) & (
        val19 * jnp.uint64(10) + f["d20"] <= jnp.uint64(MAX_HOLDING)
    )
    digits = jnp.where(can_add, val19 * jnp.uint64(10) + f["d20"], val19)
    real_digits = jnp.minimum(n_sig, 19)
    truncated = jnp.where(can_add, n_sig - 18, jnp.where(over, n_sig - 19, 0))
    total_digits = real_digits + truncated
    exp_base = truncated - jnp.where(
        f["dot_in_run"], total_digits - f["decimal_pos"].astype(jnp.int64), 0
    )

    bad_exp = plain & f["has_exp"] & (f["exp_digits"] == 0)
    valid &= ~bad_exp
    except_ |= bad_exp
    manual = jnp.where(f["exp_neg"], -f["exp_val"], f["exp_val"]).astype(jnp.int64)
    manual = jnp.where(f["has_exp"], manual, 0)

    zero = plain & (digits == 0) & seen_digit
    bad_zero_tail = zero & f["tail0_nonws"]
    valid &= ~bad_zero_tail
    except_ |= bad_zero_tail

    nonzero = plain & (digits != 0)
    bad_tail = nonzero & f["tail_nonws"]
    valid &= ~bad_tail
    except_ |= bad_tail

    # final assembly (:153-199) in softfloat binary64
    exp_ten = exp_base + manual
    digits_bits = u64_to_f64_bits(digits) | sign_bit
    nd = jnp.ones((n,), jnp.int64)  # decimal digit count of `digits`
    for k in range(1, 20):
        nd += (digits >= _POW10_U64[k]).astype(jnp.int64)

    too_big = exp_ten > 308
    sub_shift = -307 - exp_ten
    subnormal = ~too_big & (sub_shift > 0)
    dsub = f64_div_bits(digits_bits, _exp10_bits(nd - 1 + sub_shift))
    res_sub = f64_mul_bits(dsub, _exp10_bits(exp_ten + nd - 1 + sub_shift))
    e10 = _exp10_bits(jnp.abs(exp_ten))
    res_norm = jnp.where(
        exp_ten < 0,
        f64_div_bits(digits_bits, e10),
        f64_mul_bits(digits_bits, e10),
    )
    inf_bits = sign_bit | jnp.int64(0x7FF0000000000000)
    res = jnp.where(too_big, inf_bits,
                    jnp.where(subnormal, res_sub, res_norm))

    out = jnp.zeros((n,), jnp.int64)
    out = jnp.where(nan_rows, _NAN_BITS, out)
    out = jnp.where(ok_inf, inf_bits, out)
    out = jnp.where(zero, sign_bit, out)
    out = jnp.where(nonzero, res, out)
    return out, valid, except_


_scan_padded_jit = jax.jit(_scan_padded, static_argnums=(2,))


def _assemble(f, out_dtype_np):
    """Host: replicate the reference's final double assembly (:134-199)."""
    f = {k: np.asarray(v) for k, v in f.items()}
    n = f["lens"].shape[0]
    out = np.zeros((n,), np.float64)
    valid = np.ones((n,), bool)
    except_ = np.zeros((n,), bool)

    lens = f["lens"].astype(np.int64)
    sign = np.where(f["negative"], -1.0, 1.0)

    # nan: always writes NaN; only the bare 3-char string is valid
    nan_rows = f["is_nan"]
    out[nan_rows] = np.nan
    bad_nan = nan_rows & (lens != 3)
    valid[bad_nan] = False
    except_[bad_nan] = True

    # inf / infinity
    inf_rows = f["inf3"] & ~nan_rows
    ok_inf = inf_rows & f["inf_exact"]
    out[ok_inf] = np.where(f["negative"][ok_inf], -np.inf, np.inf)
    valid[inf_rows & ~f["inf_exact"]] = False  # no ANSI error (cu :276 comment)

    plain = ~nan_rows & ~inf_rows

    # no digits at all -> invalid + except (includes empty / all-ws strings)
    seen_digit = (f["n_digit_chars"] > 0) | (f["n_lead_zeros"] > 0)
    no_digits = plain & ~seen_digit
    valid[no_digits] = False
    except_[no_digits] = True

    # 19-significant-char accumulation with the reference's truncation
    # accounting (cast_string_to_float.cu:395-445).  The "+1 digit" rule only
    # fires when post-dot zeros pad the window (value stays <= max_holding/10,
    # e.g. "0.0123...": zeros count as chars but not value); for a normalized
    # 19-digit value digits*10 always overflows max_holding.
    n_sig = f["n_sig"].astype(np.int64)
    digits = f["val19"].copy()
    real_digits = np.minimum(n_sig, 19)
    over = n_sig > 19
    # the val19 <= MAX_HOLDING clause both mirrors the reference's outer
    # check and keeps the *10 below from wrapping u64
    can_add = over & (f["val19"] <= MAX_HOLDING) & (
        f["val19"] * 10 + f["d20"] <= MAX_HOLDING
    )
    digits = np.where(can_add, f["val19"] * 10 + f["d20"], digits)
    # bug-compat: the reference counts one extra truncated char when it adds
    # the 20th digit without incrementing real_digits (:437)
    truncated = np.where(can_add, n_sig - 18, np.where(over, n_sig - 19, 0))

    total_digits = real_digits + truncated
    exp_base = truncated - np.where(
        f["dot_in_run"], total_digits - f["decimal_pos"].astype(np.int64), 0
    )

    # manual exponent; 'e' with no digits is invalid
    bad_exp = plain & f["has_exp"] & (f["exp_digits"] == 0)
    valid[bad_exp] = False
    except_[bad_exp] = True
    manual = np.where(f["exp_neg"], -f["exp_val"], f["exp_val"]).astype(np.int64)
    manual = np.where(f["has_exp"], manual, 0)

    zero = plain & (digits == 0) & seen_digit
    bad_zero_tail = zero & f["tail0_nonws"]
    valid[bad_zero_tail] = False
    except_[bad_zero_tail] = True
    out = np.where(zero, sign * 0.0, out)

    nonzero = plain & (digits != 0)
    bad_tail = nonzero & f["tail_nonws"]
    valid[bad_tail] = False
    except_[bad_tail] = True

    # final assembly in binary64 (cast_string_to_float.cu:153-199)
    exp_ten = (exp_base + manual).astype(np.int64)
    digitsf = sign * digits.astype(np.float64)
    with np.errstate(over="ignore", invalid="ignore", divide="ignore"):
        res = np.zeros((n,), np.float64)
        too_big = exp_ten > 308
        res[too_big] = np.where(f["negative"][too_big], -np.inf, np.inf)
        sub_shift = -307 - exp_ten
        subnormal = ~too_big & (sub_shift > 0)
        if subnormal.any():
            nd = np.char.str_len(
                digits[subnormal].astype("U32")
            ).astype(np.int64)  # number of digits
            dsub = digitsf[subnormal] / _exp10(nd - 1 + sub_shift[subnormal])
            e2 = exp_ten[subnormal] + nd - 1 + sub_shift[subnormal]
            res[subnormal] = dsub * _exp10(e2)
        normal = ~too_big & ~subnormal
        exponent = _exp10(np.abs(exp_ten[normal]))
        dn = digitsf[normal]
        res[normal] = np.where(exp_ten[normal] < 0, dn / exponent, dn * exponent)
    out = np.where(nonzero, res, out)

    if out_dtype_np == np.float32:
        with np.errstate(over="ignore"):  # double->float32 overflow -> inf
            out = out.astype(np.float32)
    return out, valid, except_


def string_to_float(
    col: StringColumn, ansi_mode: bool, dtype: DType = FLOAT64
) -> Column:
    """Parse a string column into FLOAT32/FLOAT64 with Spark semantics.

    Invalid rows become null, or raise CastException (with the first bad row
    index) when ``ansi_mode`` (CastStringJni.cpp CATCH_CAST_EXCEPTION path).
    """
    if dtype.kind not in (Kind.FLOAT32, Kind.FLOAT64):
        raise TypeError("string_to_float produces FLOAT32 or FLOAT64")
    f = _scan(col)
    bits, valid, except_ = _assemble_device(f)

    in_valid = col.is_valid()
    except_ = except_ & in_valid
    # error control flow is the one host decision: a scalar any() sync, with
    # the failing row's bytes pulled only on the (exceptional) throw path
    if ansi_mode and bool(jnp.any(except_)):
        row = int(jnp.argmax(except_))
        offs = np.asarray(col.offsets)
        bad = bytes(np.asarray(col.chars[offs[row] : offs[row + 1]]))
        raise CastException(bad.decode("utf-8", errors="replace"), row)

    validity = valid & in_valid
    if dtype.kind == Kind.FLOAT64:
        data = bits  # bit-pattern convention for FLOAT64 columns
    else:
        data = jax.lax.bitcast_convert_type(
            f64_bits_to_f32_bits(bits), jnp.float32
        )
    return Column(data, validity, dtype)
