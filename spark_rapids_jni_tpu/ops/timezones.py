"""Timestamp <-> UTC conversion for timezones without recurring DST rules.

Capability parity with the reference's GpuTimeZoneDB + timezones.cu:
- the host side lazily builds per-zone transition tables
  (utcInstant, tzInstant, utcOffset) — GpuTimeZoneDB.java:261-335, here from
  TZif files via utils.tzif instead of java.time.ZoneRules;
- the device side does one vectorized ``searchsorted`` (upper_bound) per batch
  over the zone's transition instants and applies the found offset
  (timezones.cu:50-91 convert_timestamp_tz_functor).

Spark's gap/overlap policy is encoded in the table itself
(GpuTimeZoneDB.java:296-316): for a gap the tzInstant is
``instant + offsetAfter``, for an overlap ``instant + offsetBefore``, and the
stored offset is always ``offsetAfter``.  The first row is a
``(INT64_MIN, INT64_MIN, initial offset)`` sentinel so the upper_bound index
is always >= 1.

Zones WITH recurring DST rules (America/New_York, ...) are rejected exactly
like the reference (GpuTimeZoneDB.java:277-279) — Spark falls back to CPU for
those.
"""

from __future__ import annotations

import re
import threading
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu.columnar.column import Column
from spark_rapids_jni_tpu.columnar.dtypes import Kind
from spark_rapids_jni_tpu.utils import tzif

LONG_MIN = -(1 << 63)

# java.time.ZoneId.SHORT_IDS (deprecated 3-letter ids Spark still accepts).
SHORT_IDS = {
    "ACT": "Australia/Darwin", "AET": "Australia/Sydney",
    "AGT": "America/Argentina/Buenos_Aires", "ART": "Africa/Cairo",
    "AST": "America/Anchorage", "BET": "America/Sao_Paulo",
    "BST": "Asia/Dhaka", "CAT": "Africa/Harare", "CNT": "America/St_Johns",
    "CST": "America/Chicago", "CTT": "Asia/Shanghai",
    "EAT": "Africa/Addis_Ababa", "ECT": "Europe/Paris",
    "IET": "America/Indiana/Indianapolis", "IST": "Asia/Kolkata",
    "JST": "Asia/Tokyo", "MIT": "Pacific/Apia", "NET": "Asia/Yerevan",
    "NST": "Pacific/Auckland", "PLT": "Asia/Karachi", "PNT": "America/Phoenix",
    "PRT": "America/Puerto_Rico", "PST": "America/Los_Angeles",
    "SST": "Pacific/Guadalcanal", "VST": "Asia/Ho_Chi_Minh",
    "EST": "-05:00", "MST": "-07:00", "HST": "-10:00",
}

_OFFSET_RE = re.compile(
    r"^(?:UTC|GMT|UT)?([+-])(\d{1,2})(?::(\d{2})(?::(\d{2}))?)?$"
)


def normalize_zone_id(zone_id: str) -> str:
    """Spark's pre-normalization (GpuTimeZoneDB.java:250-258): map SHORT_IDS
    and pad the legacy ``(+|-)hh:m`` minute form."""
    zone_id = SHORT_IDS.get(zone_id, zone_id)
    return re.sub(r"([+-])(\d\d):(\d)$", r"\g<1>\g<2>:0\g<3>", zone_id)


def _parse_offset_id(zone_id: str) -> Optional[int]:
    """Fixed-offset zone id ('+08:00', 'UTC+8', 'GMT-05:30', 'Z') -> seconds."""
    if zone_id in ("Z", "UTC", "GMT", "UT"):
        return 0
    m = _OFFSET_RE.match(zone_id)
    if not m:
        return None
    sign = 1 if m.group(1) == "+" else -1
    h = int(m.group(2))
    mnt = int(m.group(3) or 0)
    sec = int(m.group(4) or 0)
    # java.time.ZoneOffset range rules: |offset| <= 18:00, mm/ss in [0,59].
    if h > 18 or mnt > 59 or sec > 59 or (h == 18 and (mnt or sec)):
        raise ValueError(f"Invalid zone offset id: {zone_id}")
    return sign * (h * 3600 + mnt * 60 + sec)


class TimeZoneDB:
    """Lazy singleton cache of transition tables (mirrors GpuTimeZoneDB.java)."""

    _instance: Optional["TimeZoneDB"] = None
    _lock = threading.Lock()

    def __init__(self):
        # zone id -> (utc_instants, tz_instants, offsets) device arrays
        self._tables: Dict[str, Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]] = {}
        self._table_lock = threading.Lock()
        self._loader: Optional[threading.Thread] = None

    @classmethod
    def instance(cls) -> "TimeZoneDB":
        with cls._lock:
            if cls._instance is None:
                if cls._shutdown_called:
                    # GpuTimeZoneDB: once shut down, never load again
                    raise RuntimeError("TimeZoneDB was shut down")
                cls._instance = cls()
            return cls._instance

    @classmethod
    def shutdown(cls) -> None:
        """Drop the cache and refuse future loads until re-enabled
        (GpuTimeZoneDB.java:76 'whether a shutdown is called ever')."""
        with cls._lock:
            inst = cls._instance
            loader = inst._loader if inst is not None else None
        if loader is not None:
            try:
                loader.join(timeout=30)  # shutdown waits for async caching
            except RuntimeError:
                pass  # loader created but never started
        with cls._lock:
            cls._shutdown_called = True
            cls._instance = None

    _shutdown_called = False

    @classmethod
    def cache_database(cls, zone_ids=None) -> None:
        """Eagerly build transition tables (GpuTimeZoneDB.cacheDatabase:129).

        ``zone_ids`` defaults to every zone the host tzdata provides whose
        rules the non-DST cache supports; unsupported/unknown zones are
        skipped, as the reference skips zones it cannot represent.
        """
        with cls._lock:
            if cls._shutdown_called:
                return  # reference: never load again after shutdown
        inst = cls.instance()
        if zone_ids is None:
            import zoneinfo

            zone_ids = sorted(zoneinfo.available_timezones())
        for z in zone_ids:
            try:
                inst.transitions(z)
            except (KeyError, ValueError):
                continue  # unknown or recurring-DST zone: not cacheable

    @classmethod
    def cache_database_async(cls, zone_ids=None) -> None:
        """Background-thread preload (GpuTimeZoneDB.cacheDatabaseAsync:88)."""
        with cls._lock:
            if cls._shutdown_called:
                return
        inst = cls.instance()
        t = threading.Thread(
            target=cls.cache_database, args=(zone_ids,),
            name="srt-tzdb-loader", daemon=True)
        t.start()
        inst._loader = t  # published only once started (shutdown joins it)

    def _build_rows(self, zone_id: str) -> List[Tuple[int, int, int]]:
        """(utcInstant, tzInstant, offset) rows per GpuTimeZoneDB.java:284-318."""
        offset = _parse_offset_id(zone_id)
        if offset is not None:
            return [(LONG_MIN, LONG_MIN, offset)]
        rules = tzif.read_tzif(zone_id)  # KeyError for unknown ids
        if rules.has_recurring_dst:
            raise ValueError(
                f"Timezone {zone_id} has recurring DST transition rules and is "
                "not supported (matches GpuTimeZoneDB's non-DST-only cache)"
            )
        rows = [(LONG_MIN, LONG_MIN, rules.initial_offset)]
        for t in rules.transitions:
            local = t.instant + (t.offset_after if t.is_gap else t.offset_before)
            rows.append((t.instant, local, t.offset_after))
        return rows

    def transitions(self, zone_id: str):
        """Device transition arrays for the zone, building/caching on demand."""
        key = normalize_zone_id(zone_id)
        with self._table_lock:
            if key not in self._tables:
                rows = self._build_rows(key)
                arr = np.asarray(rows, dtype=np.int64).reshape(len(rows), 3)
                self._tables[key] = (
                    jnp.asarray(arr[:, 0]),
                    jnp.asarray(arr[:, 1]),
                    jnp.asarray(arr[:, 2].astype(np.int32)),
                )
            return self._tables[key]

    def host_transitions(self, zone_id: str) -> List[Tuple[int, int, int]]:
        """Host copy, for tests (GpuTimeZoneDB.getHostFixedTransitions)."""
        u, t, o = self.transitions(zone_id)
        return list(
            zip(
                np.asarray(u).tolist(),
                np.asarray(t).tolist(),
                np.asarray(o).tolist(),
            )
        )


_SCALE = {
    Kind.TIMESTAMP_SECONDS: 1,
    Kind.TIMESTAMP_MILLIS: 1_000,
    Kind.TIMESTAMP_MICROS: 1_000_000,
}


def _convert(input: Column, zone_id: str, to_utc: bool) -> Column:
    scale = _SCALE.get(input.dtype.kind)
    if scale is None:
        raise TypeError("Unsupported timestamp unit for timezone conversion")
    utc_instants, tz_instants, offsets = TimeZoneDB.instance().transitions(zone_id)

    ts = input.data.astype(jnp.int64)
    # duration_cast<seconds> truncates toward zero (timezones.cu:73-74).
    q = ts // scale
    epoch_seconds = q + ((ts < 0) & (ts % scale != 0))

    instants = tz_instants if to_utc else utc_instants
    idx = jnp.searchsorted(instants, epoch_seconds, side="right")
    offset = offsets[idx - 1].astype(jnp.int64) * scale
    out = ts - offset if to_utc else ts + offset
    return Column(out, input.validity, input.dtype)


def convert_timestamp_to_utc(input: Column, zone_id: str) -> Column:
    """Interpret ``input`` as local time in ``zone_id`` and return UTC
    (GpuTimeZoneDB.fromTimestampToUtcTimestamp)."""
    return _convert(input, zone_id, to_utc=True)


def convert_utc_timestamp_to_timezone(input: Column, zone_id: str) -> Column:
    """Convert UTC ``input`` to local time in ``zone_id``
    (GpuTimeZoneDB.fromUtcTimestampToTimestamp)."""
    return _convert(input, zone_id, to_utc=False)
