"""Spark ``from_json`` for MAP<STRING,STRING>: raw key/value span extraction.

Parity target: ``MapUtils.extractRawMapFromJsonString`` (MapUtils.java:31-53)
over ``from_json`` (/root/reference/src/main/cpp/src/map_utils.cu:644).
Per row of JSON text, every *top-level object field* becomes one
``STRUCT<STRING,STRING>`` entry in a ``LIST`` column:

- keys: the field-name bytes without quotes, raw (no unescaping) —
  map_utils.cu node_ranges_fn (include_quote_char=false, :394-449);
- values: raw spans — string values lose their quotes, numbers/literals are
  their exact text, nested objects/arrays keep their *entire original text*
  including internal whitespace (``[4,{},null,{"a":[{ }, {}] } ]``);
- null input rows -> null list rows (reference replaces them with ``{}``
  before the parse and copies the input validity, map_utils.cu:86-90,:722);
- non-object rows contribute zero pairs (empty list);
- any malformed non-null row raises (the reference throws on any tokenizer
  error in the concatenated buffer, map_utils.cu:113-135 throw_if_error) —
  a whole-column error, not a per-row null.

Design: the reference concatenates all rows into one buffer and runs cuDF's
nested-JSON tokenizer, then classifies nodes by parent (key = field whose
parent is a row object).  Here rows tokenize independently on their length
bucket (ops/json_tokenizer.py); with per-row token streams, "parent is the
row object" is simply "FIELD_NAME at container depth 1 under a root object",
and the value is the following token (its span extended to the matching
close for containers).  Grammar differences from cuDF's tokenizer are
inherited deliberately from the Spark-JSON dialect of json_parser.cuh
(single quotes allowed, etc.).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu.columnar.buckets import padded_buckets
from spark_rapids_jni_tpu.columnar.column import (
    ListColumn,
    StringColumn,
    StructColumn,
)
from spark_rapids_jni_tpu.ops import json_tokenizer as jt

__all__ = ["from_json", "JsonParsingException"]

_I32 = jnp.int32


class JsonParsingException(ValueError):
    """Malformed JSON in from_json input (maps the reference's throw)."""


def from_json(col: StringColumn) -> ListColumn:
    """Extract raw top-level key/value pairs per row.

    Returns ``LIST<STRUCT<STRING,STRING>>`` with the input's validity.
    """
    n = col.size
    valid = np.asarray(col.is_valid())
    if n == 0:
        empty = StringColumn(
            jnp.zeros((0,), jnp.uint8), jnp.zeros((1,), _I32), None
        )
        return ListColumn(
            jnp.zeros((1,), _I32), StructColumn((empty, empty), None), None
        )

    # per-row pair counts + per-bucket pair records
    pair_counts = np.zeros((n,), np.int64)
    bucket_recs = []  # (rows_np, kstart, kend, vstart, vend, krank  [np arrays])
    for b in padded_buckets(col):
        ts = jt.tokenize(b.bytes, b.lengths)
        kind = np.asarray(ts.kind)
        start = np.asarray(ts.start)
        end = np.asarray(ts.end)
        match = np.asarray(ts.match)
        ntok = np.asarray(ts.n_tokens)
        ok = np.asarray(ts.ok)
        trailing = np.asarray(ts.trailing)
        rows = np.asarray(b.rows)[: b.n_valid]
        kindv = kind[: b.n_valid]
        startv = start[: b.n_valid]
        endv = end[: b.n_valid]
        matchv = match[: b.n_valid]
        ntokv = ntok[: b.n_valid]

        rvalid = valid[rows]
        bad = rvalid & (~ok[: b.n_valid] | trailing[: b.n_valid])
        if bad.any():
            r = int(rows[int(np.argmax(bad))])
            raise JsonParsingException(
                f"JSON Parser encountered an invalid format at row {r}"
            )

        T = kindv.shape[1]
        tok_idx = np.arange(T)[None, :]
        in_tok = tok_idx < ntokv[:, None]
        opens = np.isin(kindv, (jt.START_OBJECT, jt.START_ARRAY)) & in_tok
        closes = np.isin(kindv, (jt.END_OBJECT, jt.END_ARRAY)) & in_tok
        depth_after = np.cumsum(
            opens.astype(np.int32) - closes.astype(np.int32), axis=1
        )
        depth_before = depth_after - opens.astype(np.int32) + closes.astype(
            np.int32
        )
        root_is_obj = (kindv[:, 0] == jt.START_OBJECT) & (ntokv > 0)
        is_key = (
            (kindv == jt.FIELD_NAME)
            & (depth_before == 1)
            & in_tok
            & root_is_obj[:, None]
            & rvalid[:, None]
        )

        if not is_key.any():
            continue
        krank = np.cumsum(is_key, axis=1) - 1
        ri, ti = np.nonzero(is_key)
        vt = ti + 1  # value token follows its field name
        vkind = kindv[ri, vt]
        vstart = startv[ri, vt]
        vend = endv[ri, vt]
        is_str = vkind == jt.VALUE_STRING
        is_container = np.isin(vkind, (jt.START_OBJECT, jt.START_ARRAY))
        vstart = np.where(is_str, vstart + 1, vstart)
        vend = np.where(
            is_container, endv[ri, matchv[ri, vt]], np.where(is_str, vend - 1, vend)
        )
        kstart = startv[ri, ti] + 1  # strip quotes
        kend = endv[ri, ti] - 1

        np.add.at(pair_counts, rows[ri], 1)
        bucket_recs.append(
            (b, rows[ri], ri, kstart, kend, vstart, vend, krank[ri, ti])
        )

    offsets = np.zeros((n + 1,), np.int64)
    np.cumsum(pair_counts, out=offsets[1:])
    total = int(offsets[-1])

    keys = _gather_spans(
        total, bucket_recs, lambda r: (r[3], r[4]), offsets
    )
    values = _gather_spans(
        total, bucket_recs, lambda r: (r[5], r[6]), offsets
    )
    return ListColumn(
        jnp.asarray(offsets.astype(np.int32)),
        StructColumn((keys, values), None),
        col.validity,
    )


def _gather_spans(total, bucket_recs, get_span, row_offsets) -> StringColumn:
    """Assemble a StringColumn from per-bucket (row, span) records.

    Final pair position = row_offsets[row] + within-row rank, so output
    order is row-major regardless of bucket assignment.
    """
    lens = np.zeros((max(total, 1),), np.int64)
    pair_pos = []
    for rec in bucket_recs:
        _, rows_ri, _ri, *_ , krank = rec
        s, e = get_span(rec)
        pos = row_offsets[rows_ri] + krank
        lens[pos] = e - s
        pair_pos.append(pos)
    if total == 0:
        return StringColumn(
            jnp.zeros((0,), jnp.uint8), jnp.zeros((1,), _I32), None
        )
    offs = np.zeros((total + 1,), np.int64)
    np.cumsum(lens[:total], out=offs[1:])
    nbytes = int(offs[-1])
    chars = jnp.zeros((max(nbytes, 1),), jnp.uint8)
    for rec, pos in zip(bucket_recs, pair_pos):
        b = rec[0]
        s, e = get_span(rec)
        bloc = rec[2].astype(np.int32)  # bucket-local row of each pair
        w = int((e - s).max()) if len(s) else 1
        w = max(w, 1)
        lane = jnp.arange(w, dtype=_I32)[None, :]
        src = jnp.asarray(s.astype(np.int32))[:, None] + lane
        mat = b.bytes[jnp.asarray(bloc)[:, None], jnp.clip(src, 0, b.width - 1)]
        span_len = jnp.asarray((e - s).astype(np.int32))
        dst = jnp.asarray(offs[pos].astype(np.int64))[:, None] + lane.astype(
            jnp.int64
        )
        in_b = lane < span_len[:, None]
        chars = chars.at[jnp.where(in_b, dst, nbytes)].set(mat, mode="drop")
    return StringColumn(
        chars[:nbytes], jnp.asarray(offs.astype(np.int32)), None
    )
