"""Spark ``from_json`` for MAP<STRING,STRING>: raw key/value span extraction.

Parity target: ``MapUtils.extractRawMapFromJsonString`` (MapUtils.java:31-53)
over ``from_json`` (/root/reference/src/main/cpp/src/map_utils.cu:644).
Per row of JSON text, every *top-level object field* becomes one
``STRUCT<STRING,STRING>`` entry in a ``LIST`` column:

- keys: the field-name bytes without quotes, raw (no unescaping) —
  map_utils.cu node_ranges_fn (include_quote_char=false, :394-449);
- values: raw spans — string values lose their quotes, numbers/literals are
  their exact text, nested objects/arrays keep their *entire original text*
  including internal whitespace (``[4,{},null,{"a":[{ }, {}] } ]``);
- null input rows -> null list rows (reference replaces them with ``{}``
  before the parse and copies the input validity, map_utils.cu:86-90,:722);
- non-object rows contribute zero pairs (empty list);
- any malformed non-null row raises (the reference throws on any tokenizer
  error in the concatenated buffer, map_utils.cu:113-135 throw_if_error) —
  a whole-column error, not a per-row null.

Design: the reference concatenates all rows into one buffer and runs cuDF's
nested-JSON tokenizer, then classifies nodes by parent (key = field whose
parent is a row object).  Here rows tokenize independently on their length
bucket (ops/json_tokenizer.py); with per-row token streams, "parent is the
row object" is simply "FIELD_NAME at container depth 1 under a root object",
and the value is the following token (its span extended to the matching
close for containers).  Grammar differences from cuDF's tokenizer are
inherited deliberately from the Spark-JSON dialect of json_parser.cuh
(single quotes allowed, etc.).

DEVICE RESIDENCY (round 3): classification, pair compaction and the char
gathers are all jitted; the host sees only scalar decisions (malformed-row
check, per-bucket pair counts / span widths, output byte totals) — the byte
payloads go host-side only at final column materialization.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu.columnar.buckets import padded_buckets
from spark_rapids_jni_tpu.columnar.column import next_pow2
from spark_rapids_jni_tpu.columnar.column import (
    ListColumn,
    StringColumn,
    StructColumn,
)
from spark_rapids_jni_tpu.ops import json_tokenizer as jt

__all__ = ["from_json", "JsonParsingException"]

_I32 = jnp.int32
_I64 = jnp.int64


class JsonParsingException(ValueError):
    """Malformed JSON in from_json input (maps the reference's throw)."""


class _Classified(NamedTuple):
    bad: jnp.ndarray  # [nr] malformed non-null row
    is_key: jnp.ndarray  # [nr, T] top-level field names of valid rows
    krank: jnp.ndarray  # [nr, T] within-row pair rank
    kstart: jnp.ndarray  # [nr, T] key payload span (quotes stripped)
    kend: jnp.ndarray
    vstart: jnp.ndarray  # [nr, T] raw value span
    vend: jnp.ndarray


@jax.jit
def _classify(kind, start, end, match, ntok, ok, trailing, row_valid
              ) -> _Classified:
    """Token-stream classification: which tokens are top-level keys, and the
    key/value spans of each (device port of the old numpy passes)."""
    nr, T = kind.shape
    bad = row_valid & (~ok | trailing)

    tok_idx = jnp.arange(T, dtype=_I32)[None, :]
    in_tok = tok_idx < ntok[:, None]
    opens = ((kind == jt.START_OBJECT) | (kind == jt.START_ARRAY)) & in_tok
    closes = ((kind == jt.END_OBJECT) | (kind == jt.END_ARRAY)) & in_tok
    depth_after = jnp.cumsum(
        opens.astype(_I32) - closes.astype(_I32), axis=1)
    depth_before = depth_after - opens.astype(_I32) + closes.astype(_I32)
    root_is_obj = (kind[:, 0] == jt.START_OBJECT) & (ntok > 0)
    is_key = (
        (kind == jt.FIELD_NAME)
        & (depth_before == 1)
        & in_tok
        & root_is_obj[:, None]
        & row_valid[:, None]
        & ~bad[:, None]
    )
    krank = jnp.cumsum(is_key.astype(_I32), axis=1) - 1

    vt = jnp.clip(tok_idx + 1, 0, T - 1)
    vkind = jnp.take_along_axis(kind, vt, axis=1)
    vstart = jnp.take_along_axis(start, vt, axis=1)
    vend0 = jnp.take_along_axis(end, vt, axis=1)
    vmatch = jnp.clip(jnp.take_along_axis(match, vt, axis=1), 0, T - 1)
    close_end = jnp.take_along_axis(end, vmatch, axis=1)
    is_str = vkind == jt.VALUE_STRING
    is_container = (vkind == jt.START_OBJECT) | (vkind == jt.START_ARRAY)
    vstart = jnp.where(is_str, vstart + 1, vstart)
    vend = jnp.where(is_container, close_end,
                     jnp.where(is_str, vend0 - 1, vend0))
    return _Classified(
        bad=bad, is_key=is_key, krank=krank,
        kstart=start + 1, kend=end - 1, vstart=vstart, vend=vend,
    )


class _Pairs(NamedTuple):
    """Compacted per-bucket pair records ([NP] device arrays)."""

    loc_row: jnp.ndarray  # bucket-local row index
    glob_row: jnp.ndarray  # full-column row index
    krank: jnp.ndarray
    ks: jnp.ndarray
    ke: jnp.ndarray
    vs: jnp.ndarray
    ve: jnp.ndarray


@functools.partial(jax.jit, static_argnums=(2,))
def _compact(cl: _Classified, rows, NP: int) -> _Pairs:
    nr, T = cl.is_key.shape
    flat = cl.is_key.reshape(-1)
    grank = jnp.cumsum(flat.astype(_I64)) - 1
    slot = jnp.where(flat, grank, NP)

    def put(x, dtype=_I32):
        return (jnp.zeros((NP,), dtype)
                .at[slot].set(x.reshape(-1).astype(dtype), mode="drop"))

    loc = jnp.broadcast_to(jnp.arange(nr, dtype=_I32)[:, None], (nr, T))
    return _Pairs(
        loc_row=put(loc),
        glob_row=put(jnp.broadcast_to(rows[:, None], (nr, T))),
        krank=put(cl.krank),
        ks=put(cl.kstart), ke=put(cl.kend),
        vs=put(cl.vstart), ve=put(cl.vend),
    )


@functools.partial(jax.jit, static_argnums=(4, 5))
def _scatter_span_bytes(chars, b_bytes, pairs_sel, dst_off, W: int,
                        nbytes: int):
    """Copy each selected pair's [s, e) bytes into chars at dst_off."""
    loc, s, e = pairs_sel
    lane = jnp.arange(W, dtype=_I32)[None, :]
    src = jnp.clip(s[:, None] + lane, 0, b_bytes.shape[1] - 1)
    mat = b_bytes[loc[:, None], src]
    span = (e - s).astype(_I32)
    dst = dst_off.astype(_I64)[:, None] + lane.astype(_I64)
    in_b = lane < span[:, None]
    return chars.at[jnp.where(in_b, dst, nbytes)].set(mat, mode="drop")


def from_json(col: StringColumn) -> ListColumn:
    """Extract raw top-level key/value pairs per row.

    Returns ``LIST<STRUCT<STRING,STRING>>`` with the input's validity.
    """
    n = col.size
    in_valid = col.is_valid()
    if n == 0:
        empty = StringColumn(
            # analyze: ignore[governed-allocation] - empty-result
            # literals (0/1-element): no budget impact worth a
            # reservation bracket (round 18 baseline burn-down)
            jnp.zeros((0,), jnp.uint8), jnp.zeros((1,), _I32), None
        )
        return ListColumn(
            # analyze: ignore[governed-allocation] - same empty-result
            # literal as above
            jnp.zeros((1,), _I32), StructColumn((empty, empty), None), None
        )

    # phase 1 (no sync within a group): tokenize + classify a GROUP of
    # buckets, collecting the control scalars (any-bad, bad-row id, pair
    # count) on device; one batched pull per group drives the host-side
    # control flow — the same cross-bucket sync batching as device
    # get_json_object, with the same byte-budget grouping so holding
    # several buckets' [nr,T] classification matrices at once cannot blow
    # HBM (json_overlap_bytes; 1 = serial, the pre-batch peak).
    from spark_rapids_jni_tpu import config

    group_budget = max(int(config.get("json_overlap_bytes")), 1)
    # analyze: ignore[governed-allocation] - 8-byte-per-row counter
    # accumulator, dwarfed by the [nr,T] classification matrices whose
    # peak json_overlap_bytes already bounds; serving callers reach
    # from_json inside the plan runtime's governed bracket.  Debt
    # tracked at the site (round 18 baseline burn-down).
    pair_counts = jnp.zeros((n,), _I64)
    recs = []  # (bucket, _Pairs, npairs)

    def _drain(group):
        nonlocal pair_counts
        geom = np.asarray(jnp.stack([g[2] for g in group]))
        for i, (any_bad, bad_row, npairs) in enumerate(geom):
            b, cl, _ = group[i]
            group[i] = None  # free the [nr,T] matrices as we go
            if any_bad:  # malformed non-null row: whole-op throw
                raise JsonParsingException(
                    f"JSON Parser encountered an invalid format at row "
                    f"{int(bad_row)}"
                )
            if npairs == 0:
                continue
            pair_counts = pair_counts.at[b.rows].add(
                jnp.sum(cl.is_key, axis=1).astype(_I64))
            recs.append((b, _compact(cl, b.rows, next_pow2(int(npairs))),
                         int(npairs)))

    group, group_bytes = [], 0
    for b in padded_buckets(col):
        ts = jt.tokenize(b.bytes, b.lengths)
        row_valid = in_valid[b.rows] & b.valid_mask()
        cl = _classify(ts.kind.astype(_I32), ts.start, ts.end, ts.match,
                       ts.n_tokens.astype(_I32), ts.ok, ts.trailing,
                       row_valid)
        if cl.bad.size:
            any_bad = jnp.any(cl.bad).astype(_I64)
            bad_row = b.rows[jnp.argmax(cl.bad)].astype(_I64)
        else:
            any_bad = bad_row = jnp.int64(0)
        bbytes = int(b.bytes.shape[0]) * int(b.bytes.shape[1])
        if group and group_bytes + bbytes > group_budget:
            _drain(group)
            group, group_bytes = [], 0
        group.append((b, cl, jnp.stack(
            [any_bad, bad_row, jnp.sum(cl.is_key).astype(_I64)])))
        group_bytes += bbytes
    if group:
        _drain(group)

    offsets = jnp.pad(jnp.cumsum(pair_counts), (1, 0))
    total = int(offsets[-1])  # list-child size is shape-defining

    keys = _gather_spans(total, recs, lambda p: (p.ks, p.ke), offsets)
    values = _gather_spans(total, recs, lambda p: (p.vs, p.ve), offsets)
    return ListColumn(
        offsets.astype(_I32),
        StructColumn((keys, values), None),
        col.validity,
    )


def _gather_spans(total, recs, get_span, row_offsets) -> StringColumn:
    """Assemble a StringColumn from per-bucket pair records (device).

    Final pair position = row_offsets[row] + within-row rank, so output
    order is row-major regardless of bucket assignment.  Host syncs: the
    output byte total and each bucket's max span width (pow2-padded).
    """
    if total == 0:
        return StringColumn(
            # analyze: ignore[governed-allocation] - empty-result
            # literals (0/1-element): no budget impact worth a
            # reservation bracket (round 18 baseline burn-down)
            jnp.zeros((0,), jnp.uint8), jnp.zeros((1,), _I32), None
        )
    # analyze: ignore[governed-allocation] - 8 bytes per output pair,
    # a rounding error next to the pair records already resident; the
    # op runs under the plan runtime's governed bracket when served.
    # Debt tracked at the site (round 18 baseline burn-down).
    lens = jnp.zeros((total + 1,), _I64)
    positions = []
    for b, p, npairs in recs:
        s, e = get_span(p)
        pos = row_offsets[p.glob_row] + p.krank
        # pad slots beyond npairs carry garbage; mask them to the sink
        slot_ok = jnp.arange(p.ks.shape[0]) < npairs
        pos = jnp.where(slot_ok, pos, total)
        positions.append(pos)
        lens = lens.at[pos].set((e - s).astype(_I64), mode="drop")
    offs = jnp.pad(jnp.cumsum(lens[:total]), (1, 0))
    # one batched pull: the byte total + every bucket's max span width
    widths_dev = [jnp.max(get_span(p)[1] - get_span(p)[0]).astype(_I64)
                  for _b, p, _np in recs]
    pulled = np.asarray(jnp.stack([offs[-1]] + widths_dev))
    nbytes = int(pulled[0])
    cap = next_pow2(nbytes)  # bounded shape-variant set (StringColumn)
    # analyze: ignore[governed-allocation] - the output chars buffer:
    # sized by the extracted spans (bounded by the input bytes a
    # governed reservation already admitted upstream); a per-op bracket
    # here would double-count.  Debt tracked at the site (round 18
    # baseline burn-down).
    chars = jnp.zeros((cap,), jnp.uint8)
    for (b, p, npairs), pos, wmax in zip(recs, positions, pulled[1:]):
        s, e = get_span(p)
        w = next_pow2(max(int(wmax), 1))
        chars = _scatter_span_bytes(
            chars, b.bytes, (p.loc_row, s, e),
            jnp.where(pos < total, offs[jnp.minimum(pos, total - 1)],
                      jnp.int64(cap)),
            w, cap)
    return StringColumn(chars, offs.astype(_I32), None)
