"""Spark DECIMAL128 arithmetic with precision-38 overflow detection.

TPU-native equivalent of the reference's decimal_utils.cu (dec128_multiplier
:662, dec128_divider :738, dec128_add_sub :560, dec128_remainder :845) and the
Java façade DecimalUtils.java:46-178.  All intermediate math runs in 256-bit
limb tensors (utils.int256) so every row is a lane; there is no per-row scalar
code.  Rounding is Java HALF_UP; overflow is Spark's |v| >= 10**38 rule.

Public functions mirror DecimalUtils.java: each returns ``(overflow, result)``
where ``overflow`` is a BOOL Column (true where the row overflowed) and
``result`` carries the requested Spark scale.  Scales at this API are
*Spark-convention* (positive = fraction digits); internally the formulas use
cudf-convention scales (negated) to stay aligned with the reference kernels.

The reference's ``interimCast`` flag (DecimalUtils.java:55-70) reproduces a
Spark <3.4.2 bug (SPARK-40129/SPARK-45786): the raw product is first rounded to
38 digits of precision, then rounded again to the target scale.  We implement
both behaviors.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from spark_rapids_jni_tpu.columnar import dtypes
from spark_rapids_jni_tpu.columnar.column import Column, Decimal128Column
from spark_rapids_jni_tpu.utils import int256 as i256


def _and_validity(a, b):
    if a.validity is None and b.validity is None:
        return None
    return a.is_valid() & b.is_valid()


def _result(valid, ov, hi, lo, spark_scale) -> Tuple[Column, Decimal128Column]:
    overflow = Column(ov, valid, dtypes.BOOL)
    res = Decimal128Column(
        hi, lo, valid, dtypes.DType(dtypes.Kind.DECIMAL128, 38, spark_scale)
    )
    return overflow, res


@functools.partial(jax.jit, static_argnames=("a_cs", "b_cs", "prod_cs", "interim"))
def _multiply_kernel(a_hi, a_lo, b_hi, b_lo, *, a_cs, b_cs, prod_cs, interim):
    a = i256.from_i128(a_hi, a_lo)
    b = i256.from_i128(b_hi, b_lo)
    product = i256.multiply(a, b)

    mult_cs = jnp.full(a_hi.shape, a_cs + b_cs, dtype=jnp.int32)
    if interim:
        # Spark <3.4.2: round the raw product to 38 digits first
        # (dec128_multiplier, decimal_utils.cu:687-716).
        fdp = i256.precision10(product) - jnp.int32(38)
        fdp_pos = jnp.maximum(fdp, 0)
        div = i256.pow_ten(fdp_pos, product)  # rows with fdp<=0 divide by 1
        d_hi, d_lo = i256.to_i128(div)
        rounded = i256.divide_and_round(product, d_hi, d_lo)
        take = fdp > 0
        product = jnp.where(take[..., None], rounded, product)
        mult_cs = mult_cs + jnp.where(take, fdp, 0)

    exponent = jnp.int32(prod_cs) - mult_cs

    # exponent < 0: scale the product up, overflowing if that adds digits past 38
    new_precision = i256.precision10(product)
    up_overflow = (new_precision - exponent) > jnp.int32(38)
    mult = i256.pow_ten(jnp.maximum(-exponent, 0), product)
    scaled_up = i256.multiply(product, mult)

    # exponent >= 0: divide-and-round down to the target scale
    divisor = i256.pow_ten(jnp.maximum(exponent, 0), product)
    dv_hi, dv_lo = i256.to_i128(divisor)
    scaled_down = i256.divide_and_round(product, dv_hi, dv_lo)

    up = exponent < 0
    final = jnp.where(up[..., None], scaled_up, scaled_down)
    overflow = jnp.where(
        up,
        up_overflow | i256.is_greater_than_decimal_38(scaled_up),
        i256.is_greater_than_decimal_38(scaled_down),
    )
    r_hi, r_lo = i256.to_i128(final)
    return overflow, r_hi, r_lo


def multiply128(
    a: Decimal128Column,
    b: Decimal128Column,
    product_scale: int,
    interim_cast: bool = True,
) -> Tuple[Column, Decimal128Column]:
    """a * b at Spark scale ``product_scale`` (DecimalUtils.multiply128,
    DecimalUtils.java:46-71)."""
    ov, hi, lo = _multiply_kernel(
        a.hi,
        a.lo,
        b.hi,
        b.lo,
        a_cs=-a.dtype.scale,
        b_cs=-b.dtype.scale,
        prod_cs=-product_scale,
        interim=interim_cast,
    )
    return _result(_and_validity(a, b), ov, hi, lo, product_scale)


def _safe_divisor(d_hi, d_lo):
    """Replace zero divisors with 1 (rows masked out by the caller)."""
    is_zero = (d_hi == 0) & (d_lo == jnp.uint64(0))
    return (
        is_zero,
        jnp.where(is_zero, jnp.int64(0), d_hi),
        jnp.where(is_zero, jnp.uint64(1), d_lo),
    )


@functools.partial(jax.jit, static_argnames=("n_shift_exp", "is_int_div"))
def _divide_kernel(a_hi, a_lo, b_hi, b_lo, *, n_shift_exp, is_int_div):
    """dec128_divider (decimal_utils.cu:738-834).  ``n_shift_exp`` is the
    static cudf-scale shift quot_cs - (a_cs - b_cs); the three branches of the
    reference are static python branches here."""
    n = i256.from_i128(a_hi, a_lo)
    div_zero, d_hi, d_lo = _safe_divisor(b_hi, b_lo)

    if n_shift_exp > 0:
        # divide twice: truncating divide, then scale down with rounding
        q1, _, _ = i256.divide(n, d_hi, d_lo)
        p_hi, p_lo = i256.to_i128(i256.pow_ten(n_shift_exp, q1))
        if is_int_div:
            result = i256.integer_divide(q1, p_hi, p_lo)
        else:
            result = i256.divide_and_round(q1, p_hi, p_lo)
    else:
        # scale the numerator up before dividing.  When the shift exceeds 38
        # the reference stages the multiply around a first divide so the
        # scaled numerator cannot wrap 256 bits (decimal_utils.cu:788-812);
        # in exact arithmetic the staged form equals
        # divide_and_round(n * 10**shift, d).
        shift = -n_shift_exp
        if shift <= 38:
            if shift > 0:
                n = i256.multiply(n, i256.pow_ten(shift, n))
            if is_int_div:
                result = i256.integer_divide(n, d_hi, d_lo)
            else:
                result = i256.divide_and_round(n, d_hi, d_lo)
        else:
            n = i256.multiply(n, i256.pow_ten(38, n))
            q1, r1_hi, r1_lo = i256.divide(n, d_hi, d_lo)
            rem_exp = shift - 38
            scale_mult = i256.pow_ten(rem_exp, q1)
            result = i256.multiply(q1, scale_mult)
            scaled_r = i256.multiply(i256.from_i128(r1_hi, r1_lo), scale_mult)
            q2, r2_hi, r2_lo = i256.divide(scaled_r, d_hi, d_lo)
            result = i256.add(result, q2)
            if not is_int_div:
                result = i256.round_from_remainder(
                    result, r2_hi, r2_lo, i256.is_negative(scaled_r), d_hi, d_lo
                )

    overflow = div_zero | i256.is_greater_than_decimal_38(result)
    if is_int_div:
        q64 = jnp.where(div_zero, jnp.int64(0), i256.to_i64(result))
        return overflow, q64
    r_hi, r_lo = i256.to_i128(result)
    r_hi = jnp.where(div_zero, jnp.int64(0), r_hi)
    r_lo = jnp.where(div_zero, jnp.uint64(0), r_lo)
    return overflow, r_hi, r_lo


def divide128(
    a: Decimal128Column, b: Decimal128Column, quotient_scale: int
) -> Tuple[Column, Decimal128Column]:
    """a / b at Spark scale ``quotient_scale`` with HALF_UP rounding
    (DecimalUtils.divide128, DecimalUtils.java:86)."""
    n_shift_exp = -quotient_scale - (-a.dtype.scale - -b.dtype.scale)
    ov, hi, lo = _divide_kernel(
        a.hi, a.lo, b.hi, b.lo, n_shift_exp=n_shift_exp, is_int_div=False
    )
    return _result(_and_validity(a, b), ov, hi, lo, quotient_scale)


def integer_divide128(
    a: Decimal128Column, b: Decimal128Column
) -> Tuple[Column, Column]:
    """a div b -> INT64 quotient, truncated (DecimalUtils.integerDivide128,
    DecimalUtils.java:108: divide at cudf scale 0 with DOWN rounding)."""
    n_shift_exp = 0 - (-a.dtype.scale - -b.dtype.scale)
    ov, q64 = _divide_kernel(
        a.hi, a.lo, b.hi, b.lo, n_shift_exp=n_shift_exp, is_int_div=True
    )
    valid = _and_validity(a, b)
    return Column(ov, valid, dtypes.BOOL), Column(q64, valid, dtypes.INT64)


@functools.partial(jax.jit, static_argnames=("a_cs", "b_cs", "rem_cs"))
def _remainder_kernel(a_hi, a_lo, b_hi, b_lo, *, a_cs, b_cs, rem_cs):
    """dec128_remainder (decimal_utils.cu:845-966): Java remainder semantics,
    a % b = a - (a // b) * b, result sign follows the dividend."""
    n = i256.from_i128(a_hi, a_lo)
    div_zero, d_hi, d_lo = _safe_divisor(b_hi, b_lo)

    d_shift_exp = rem_cs - b_cs
    n_shift_exp = rem_cs - a_cs

    ad_hi, ad_lo = i256.to_i128(i256.abs256(i256.from_i128(d_hi, d_lo)))
    if d_shift_exp > 0:
        # shift the divisor itself down to rem_scale, rounding
        p_hi, p_lo = i256.to_i128(i256.pow_ten(d_shift_exp, n))
        abs_d = i256.divide_and_round(i256.from_i128(ad_hi, ad_lo), p_hi, p_lo)
        ad_hi, ad_lo = i256.to_i128(abs_d)
    else:
        n_shift_exp -= d_shift_exp

    n_neg = i256.is_negative(n)
    abs_n = i256.abs256(n)
    # guard again: a down-rounded divisor can hit zero
    rz = (ad_hi == 0) & (ad_lo == jnp.uint64(0))
    div_zero = div_zero | rz
    ad_lo = jnp.where(rz, jnp.uint64(1), ad_lo)

    if n_shift_exp > 0:
        q1, _, _ = i256.divide(abs_n, ad_hi, ad_lo)
        p_hi, p_lo = i256.to_i128(i256.pow_ten(n_shift_exp, q1))
        int_div = i256.integer_divide(q1, p_hi, p_lo)
    else:
        if n_shift_exp < 0:
            abs_n = i256.multiply(abs_n, i256.pow_ten(-n_shift_exp, abs_n))
        int_div = i256.integer_divide(abs_n, ad_hi, ad_lo)

    less_n = i256.multiply(int_div, i256.from_i128(ad_hi, ad_lo))
    if d_shift_exp < 0:
        less_n = i256.multiply(less_n, i256.pow_ten(-d_shift_exp, less_n))
    rem = i256.add(abs_n, i256.negate(less_n))

    overflow = div_zero | i256.is_greater_than_decimal_38(rem)
    rem = jnp.where(n_neg[..., None], i256.negate(rem), rem)
    r_hi, r_lo = i256.to_i128(rem)
    r_hi = jnp.where(div_zero, jnp.int64(0), r_hi)
    r_lo = jnp.where(div_zero, jnp.uint64(0), r_lo)
    return overflow, r_hi, r_lo


def remainder128(
    a: Decimal128Column, b: Decimal128Column, remainder_scale: int
) -> Tuple[Column, Decimal128Column]:
    """a % b at Spark scale ``remainder_scale`` (DecimalUtils.remainder128,
    DecimalUtils.java:128)."""
    ov, hi, lo = _remainder_kernel(
        a.hi,
        a.lo,
        b.hi,
        b.lo,
        a_cs=-a.dtype.scale,
        b_cs=-b.dtype.scale,
        rem_cs=-remainder_scale,
    )
    return _result(_and_validity(a, b), ov, hi, lo, remainder_scale)


def _set_scale_and_round(x, old_cs, new_cs):
    """set_scale_and_round (decimal_utils.cu:544), static scales."""
    if old_cs == new_cs:
        return x
    if new_cs < old_cs:
        return i256.multiply(x, i256.pow_ten(old_cs - new_cs, x))
    p_hi, p_lo = i256.to_i128(i256.pow_ten(new_cs - old_cs, x))
    return i256.divide_and_round(x, p_hi, p_lo)


@functools.partial(jax.jit, static_argnames=("a_cs", "b_cs", "res_cs", "sub"))
def _add_sub_kernel(a_hi, a_lo, b_hi, b_lo, *, a_cs, b_cs, res_cs, sub):
    """dec128_add_sub (decimal_utils.cu:560-611): align to the smaller cudf
    scale, add/sub in 256 bits, round to the result scale."""
    a = i256.from_i128(a_hi, a_lo)
    b = i256.from_i128(b_hi, b_lo)
    inter_cs = min(a_cs, b_cs)
    a = _set_scale_and_round(a, a_cs, inter_cs)
    b = _set_scale_and_round(b, b_cs, inter_cs)
    if sub:
        b = i256.negate(b)
    s = i256.add(a, b)
    s = _set_scale_and_round(s, inter_cs, res_cs)
    overflow = i256.is_greater_than_decimal_38(s)
    r_hi, r_lo = i256.to_i128(s)
    return overflow, r_hi, r_lo


def _add_sub(a, b, target_scale, sub):
    if abs(a.dtype.scale - b.dtype.scale) > 77:
        raise ValueError("The scale of the input columns is too far apart")
    ov, hi, lo = _add_sub_kernel(
        a.hi,
        a.lo,
        b.hi,
        b.lo,
        a_cs=-a.dtype.scale,
        b_cs=-b.dtype.scale,
        res_cs=-target_scale,
        sub=sub,
    )
    return _result(_and_validity(a, b), ov, hi, lo, target_scale)


def add128(
    a: Decimal128Column, b: Decimal128Column, target_scale: int
) -> Tuple[Column, Decimal128Column]:
    """a + b at Spark scale ``target_scale`` (DecimalUtils.add128,
    DecimalUtils.java:172)."""
    return _add_sub(a, b, target_scale, sub=False)


def subtract128(
    a: Decimal128Column, b: Decimal128Column, target_scale: int
) -> Tuple[Column, Decimal128Column]:
    """a - b at Spark scale ``target_scale`` (DecimalUtils.subtract128,
    DecimalUtils.java:149)."""
    return _add_sub(a, b, target_scale, sub=True)
