"""Spark ``percentile`` aggregation over pre-binned data.

Spark-exact semantics of the reference's histogram ops
(histogram.cu:283 create_histogram_if_valid, histogram.cu:431
percentile_from_histogram; interpolation kernel fill_percentile_fn
histogram.cu:50-105).

The reference sorts each LIST segment with a segmented sort, scans counts by
key, then runs one thread per (histogram, percentage) doing a sequential
``lower_bound`` over that histogram's accumulated counts.  On TPU the ragged
segments are instead gathered into a dense padded ``[num_histograms, max_len]``
tile (padding = int64 max) so that every search is a vectorized
compare-and-sum over lanes.  Histograms are small (percentile buckets), so the
padding cost is bounded.

Exactness split: the O(n) work — sorting, the count scan, the per-percentile
binary searches, element gathers — runs on device over *exact integer keys*
(FLOAT64 columns are IEEE-754 bits in int64 per the framework convention;
sorting uses the sign-flip total order on the bits, never emulated-f64
compares).  The final O(H x P) interpolation is finished on host in true
binary64, because TPU f64 is float32-pair emulated and would not be bit-exact
(columnar.column doc; the aggregation finish is negligible next to the scan).
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu.columnar.column import (
    Column,
    ListColumn,
    StructColumn,
)
from spark_rapids_jni_tpu.columnar.dtypes import FLOAT64, Kind
from spark_rapids_jni_tpu.utils.floatbits import f32_to_bits

_I64_MAX = (1 << 63) - 1


def create_histogram_if_valid(
    values: Column, frequencies: Column, output_as_lists: bool
):
    """Validate (values, frequencies) and build a histogram column.

    Mirrors histogram.cu:283-425: frequencies must be INT64, non-null and
    non-negative.  ``output_as_lists=False`` returns STRUCT<value,freq> with
    zero-frequency rows nullified (their freq forced to 1, histogram.cu:365-378);
    ``True`` wraps each row in its own list, with zero-frequency rows becoming
    empty lists.
    """
    if frequencies.dtype.kind != Kind.INT64:
        raise TypeError("The input frequencies must be of type INT64.")
    if frequencies.validity is not None and frequencies.null_count() > 0:
        raise ValueError("The input frequencies must not have nulls.")
    if values.size != frequencies.size:
        raise ValueError("The input values and frequencies must have the same size.")

    # validation decisions are scalar syncs; the frequency bytes stay device
    freq = frequencies.data
    if bool(jnp.any(freq < 0)):
        raise ValueError("The input frequencies must not contain negative values.")
    has_zero = bool(jnp.any(freq == 0))
    n = values.size

    if output_as_lists:
        # Each row becomes a 1-element list; zero-frequency rows become empty.
        struct = StructColumn((values, frequencies), None)
        if not has_zero:
            offsets = jnp.arange(n + 1, dtype=jnp.int32)
            return ListColumn(offsets, struct, None)
        keep = freq > 0
        offsets = jnp.pad(jnp.cumsum(keep.astype(jnp.int32)), (1, 0))
        total = int(offsets[-1])  # list child size is shape-defining
        rank = offsets[1:] - 1
        gather = (
            # analyze: ignore[governed-allocation] - histogram is not
            # yet wired into a governed pipeline (oracle/test callers);
            # debt tracked at the site (round 16 baseline burn-down)
            jnp.zeros((max(total, 1),), jnp.int32)
            .at[jnp.where(keep, rank, total)]
            .set(jnp.arange(n, dtype=jnp.int32), mode="drop")[:total]
        )
        kept_vals = Column(
            values.data[gather],
            None if values.validity is None else values.validity[gather],
            values.dtype,
        )
        kept_freq = Column(frequencies.data[gather], None, frequencies.dtype)
        return ListColumn(offsets, StructColumn((kept_vals, kept_freq), None), None)

    if not has_zero:
        # Reference quirk preserved: when no zero frequencies exist, null-value
        # rows keep their original frequency (the freq->1 fixup below only runs
        # on the zero-frequency path; histogram.cu:399-401 vs :365-378).
        return StructColumn((values, frequencies), None)
    # Nullify zero-frequency values (AND with any existing mask) and force
    # the frequency of EVERY null row (including originally-null values) to 1
    # so downstream MERGE_HISTOGRAM never sees freq 0.
    pos = freq > 0
    validity = pos if values.validity is None else (values.validity & pos)
    fixed_freq = jnp.where(validity, frequencies.data, jnp.int64(1))
    out_vals = Column(values.data, validity, values.dtype)
    return StructColumn((out_vals, Column(fixed_freq, None, frequencies.dtype)), None)


def _total_order_key(col: Column) -> jnp.ndarray:
    """int64 key whose < order equals the column's value order (exact on device).

    FLOAT64 data is already IEEE-754 bits in int64; the standard sign-flip map
    (negatives -> bitwise complement) makes integer compare match float compare.
    """
    kind = col.dtype.kind
    if kind == Kind.FLOAT64:
        bits = col.data.astype(jnp.int64)
        u = bits.astype(jnp.uint64)
        flipped = jnp.where(
            bits < 0, ~u, u | jnp.uint64(0x8000000000000000)
        )
        return (flipped ^ jnp.uint64(0x8000000000000000)).astype(jnp.int64)
    if kind == Kind.FLOAT32:
        bits = f32_to_bits(col.data).astype(jnp.int64)
        u = bits.astype(jnp.uint64)
        flipped = jnp.where(bits < 0, (~u) & jnp.uint64(0xFFFFFFFF), u | jnp.uint64(0x80000000))
        return flipped.astype(jnp.int64)
    if kind == Kind.UINT64:
        return (col.data ^ jnp.uint64(1 << 63)).astype(jnp.int64)
    return col.data.astype(jnp.int64)


def _raw_int_repr(col: Column) -> jnp.ndarray:
    """int64 carrying the exact value representation (bits for floats)."""
    if col.dtype.kind == Kind.FLOAT32:
        return f32_to_bits(col.data).astype(jnp.int64)
    return col.data.astype(jnp.int64)


def _decode_raw(raw: np.ndarray, kind: Kind) -> np.ndarray:
    """Host: raw gathered int64 representations -> float64 values."""
    if kind == Kind.FLOAT64:
        return raw.astype(np.int64).view(np.float64)
    if kind == Kind.FLOAT32:
        return raw.astype(np.int64).astype(np.int32).view(np.float32).astype(np.float64)
    if kind == Kind.UINT64:
        return raw.astype(np.uint64).astype(np.float64)
    return raw.astype(np.float64)


def percentile_from_histogram(
    input: ListColumn, percentages: Sequence[float], output_as_list: bool
):
    """Spark percentile over LIST<STRUCT<value, freq INT64>> histograms.

    Returns FLOAT64 percentiles (as bit-pattern int64 per framework convention):
    a flat Column of ``H * P`` rows, or a ListColumn of P-element lists per
    histogram with all-null histograms yielding empty lists (histogram.cu:255).
    """
    if not isinstance(input, ListColumn) or not isinstance(input.child, StructColumn):
        raise TypeError("The input column must be of type LIST of STRUCT.")
    struct = input.child
    if len(struct.children) != 2:
        raise TypeError("Child of the input column must have two children.")
    if struct.validity is not None and int(jnp.sum(~struct.validity)) > 0:
        raise ValueError("Child of the input column must not have nulls.")
    data_col, counts_col = struct.children
    if not isinstance(counts_col, Column) or counts_col.dtype.kind != Kind.INT64:
        raise TypeError("Histogram frequencies must be INT64.")
    if counts_col.validity is not None and counts_col.null_count() > 0:
        raise ValueError("Histogram frequencies must be non-null.")
    arithmetic = isinstance(data_col, Column) and (
        data_col.dtype.is_integral
        or data_col.dtype.is_floating
        or data_col.dtype.kind in (Kind.BOOL, Kind.UINT8, Kind.UINT64)
    )
    if not arithmetic:
        raise TypeError("Unsupported type in histogram-to-percentile evaluation.")

    num_hist = input.size
    pcts = np.asarray(list(percentages), dtype=np.float64)
    num_pct = pcts.size

    offsets_np = np.asarray(input.offsets).astype(np.int64)
    seg_lens = offsets_np[1:] - offsets_np[:-1]
    max_len = int(seg_lens.max()) if num_hist else 0

    if data_col.size == 0 or num_pct == 0:
        # Reference-faithful: empty data or empty percentages yield
        # num_histograms ALL-NULL rows (flat) / empty lists, NOT 0 rows
        # (percentile_dispatcher early return, histogram.cu:171-180).
        return _wrap_percentile_output(
            np.zeros((num_hist * max(num_pct, 1),), np.int64),
            np.zeros((num_hist,), np.bool_),
            num_pct,
            output_as_list,
        )

    # --- device: segmented sort (label asc, value asc, nulls AFTER) ---
    key = _total_order_key(data_col)
    valid = data_col.is_valid()
    labels = jnp.asarray(np.repeat(np.arange(num_hist, dtype=np.int64), seg_lens))
    order = jnp.argsort(key, stable=True)
    order = order[jnp.argsort((~valid)[order], stable=True)]
    order = order[jnp.argsort(labels[order], stable=True)]

    sorted_raw = _raw_int_repr(data_col)[order]
    sorted_valid = valid[order]
    sorted_counts = counts_col.data[order].astype(jnp.int64)

    # Per-segment inclusive scan of counts: global cumsum minus segment base.
    csum = jnp.cumsum(sorted_counts)
    starts = jnp.asarray(offsets_np[:-1])
    base = jnp.where(starts > 0, csum[jnp.maximum(starts - 1, 0)], jnp.int64(0))
    acc = csum - base[labels]

    # Dense padded [H, L] tiles (pad acc with i64 max so searches stop there).
    n_elem = data_col.size
    pad_idx = np.full((num_hist, max_len), n_elem, dtype=np.int64)
    lane = np.arange(max_len)
    in_seg_np = lane[None, :] < seg_lens[:, None]
    pad_idx[in_seg_np] = (offsets_np[:-1, None] + lane[None, :])[in_seg_np]
    pad_idx_j = jnp.asarray(pad_idx)
    in_seg = jnp.asarray(in_seg_np)

    def padded(arr, fill):
        safe = jnp.concatenate([arr, jnp.array([fill], dtype=arr.dtype)])
        return jnp.where(in_seg, safe[pad_idx_j], fill)

    acc_pad = padded(acc, jnp.int64(_I64_MAX))
    raw_pad = padded(sorted_raw, jnp.int64(0))
    valid_pad = padded(sorted_valid.astype(jnp.int32), jnp.int32(0))

    # Valid prefix length per histogram (nulls sort last; histogram.cu:57-64).
    n_valid_d = jnp.sum(valid_pad, axis=1)
    end_idx = jnp.maximum(n_valid_d - 1, 0)
    max_positions_d = jnp.take_along_axis(acc_pad, end_idx[:, None], axis=1)[:, 0] - 1

    # --- host: exact binary64 position math on [H] / [H,P] scalars ---
    n_valid = np.asarray(n_valid_d)
    has_any = n_valid > 0
    if input.validity is not None:
        # Null histogram rows produce null/empty outputs even if their segment
        # is non-empty (cudf purges null rows' segments; guard it here).
        has_any &= np.asarray(input.validity)
    max_positions = np.where(has_any, np.asarray(max_positions_d), 0)
    position = max_positions[:, None].astype(np.float64) * pcts[None, :]  # [H,P]
    lower = np.floor(position).astype(np.int64)
    higher = np.ceil(position).astype(np.int64)

    # --- device: vectorized lower_bound + element gather ---
    def lower_bound(q_np):
        q = jnp.asarray(q_np)  # [H,P]
        lt = acc_pad[:, None, :] < q[:, :, None]  # [H,P,L]
        return jnp.minimum(jnp.sum(lt, axis=-1), max_len - 1)

    lo_idx = lower_bound(lower + 1)
    hi_idx = lower_bound(higher + 1)
    lo_raw = np.asarray(jnp.take_along_axis(raw_pad, lo_idx, axis=1))
    hi_raw = np.asarray(jnp.take_along_axis(raw_pad, hi_idx, axis=1))

    # --- host: exact binary64 interpolation (fill_percentile_fn :77-104) ---
    kind = data_col.dtype.kind
    lo_elem = _decode_raw(lo_raw, kind)
    hi_elem = _decode_raw(hi_raw, kind)
    lower_part = (higher.astype(np.float64) - position) * lo_elem
    higher_part = (position - lower.astype(np.float64)) * hi_elem
    interp = np.where(
        (higher == lower) | (hi_raw == lo_raw), lo_elem, lower_part + higher_part
    )
    out_bits = interp.view(np.int64).reshape(num_hist * num_pct)
    return _wrap_percentile_output(out_bits, has_any, num_pct, output_as_list)


def _wrap_percentile_output(out_bits_np, row_valid_np, num_pct, output_as_list):
    """Package flat [H*P] percentile bits + per-histogram validity (host arrays)."""
    num_hist = row_valid_np.shape[0]
    if not output_as_list:
        validity = None
        if num_hist and (~row_valid_np).any():
            rep = np.repeat(row_valid_np, max(num_pct, 1))[: out_bits_np.shape[0]]
            validity = jnp.asarray(rep)
        return Column(jnp.asarray(out_bits_np), validity, FLOAT64)
    # Lists: all-null histograms become empty lists (purge_nonempty_nulls).
    sizes = np.where(row_valid_np, num_pct, 0).astype(np.int32)
    offsets = jnp.asarray(np.concatenate([[0], np.cumsum(sizes)]).astype(np.int32))
    keep = np.repeat(row_valid_np, max(num_pct, 1))[: out_bits_np.shape[0]]
    child = Column(jnp.asarray(out_bits_np[keep]), None, FLOAT64)
    return ListColumn(offsets, child, None)
