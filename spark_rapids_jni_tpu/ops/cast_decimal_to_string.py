"""Decimal -> string with Java ``BigDecimal.toString`` semantics.

Parity with the reference's decimal_to_non_ansi_string
(cast_decimal_to_string.cu:52-160): plain ``[-]integer.fraction`` when the
(cudf) scale <= 0 and the adjusted exponent >= -6, scientific
``d.dddE±x`` otherwise — including the ``0E-7`` edge for zero at scale -7.

Note on conventions: the reference takes cuDF scales (negative = fraction
digits); this framework's DType carries Spark scales (positive = fraction
digits), so ``spark_scale = -cudf_scale`` throughout.

Vectorization: the single data-dependent division (split at 10^K, where K is
the per-row fraction width) runs through the 256-bit limb divider
(utils.int256) shared with the DECIMAL128 arithmetic ops; each output byte is
then rendered by grid arithmetic as in ops.format_float.  The reference's
zeros+digits fraction assembly collapses to "print the remainder zero-padded
to K digits", which is a pure digit gather.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu.columnar.column import (
    Column,
    Decimal128Column,
    StringColumn,
    strings_from_padded,
)
from spark_rapids_jni_tpu.columnar.dtypes import Kind
from spark_rapids_jni_tpu.utils import int256

from spark_rapids_jni_tpu.ops.float_to_string import (
    _decimal_length_u64,
    digit_from_table,
    digit_table_u64,
)

_U64 = jnp.uint64
_I32 = jnp.int32

MAX_LEN = 48  # sign + 39 digits + '.' + 'E' + sign + 3 exp digits

# 10^k for k in [0, 39] as (hi, lo) u64 pairs (10^39 > 2^127, clamp at 39)
_P10_HI = np.array([(10**k >> 64) & ((1 << 64) - 1) for k in range(40)], np.uint64)
_P10_LO = np.array([10**k & ((1 << 64) - 1) for k in range(40)], np.uint64)
_P10_SMALL = np.array([1, 10, 100], np.int32)  # exponent digit divisors


def _digits_1919(h19, l19):
    """decimal digit count of h19 * 10^19 + l19."""
    return jnp.where(
        h19 > 0, 19 + _decimal_length_u64(h19, 20), _decimal_length_u64(l19, 20)
    )


def _digit_table_1919(h19, l19) -> jnp.ndarray:
    """``[n, 39]`` uint8 digits (from the right) of h19 * 10^19 + l19.

    Two constant-divisor digit tables concatenated — replaces per-grid-cell
    u64 division with a variable power-of-10 (the axon compile pathology;
    see float_to_string.digit_table_u64)."""
    return jnp.concatenate(
        [digit_table_u64(l19, 19), digit_table_u64(h19, 20)], axis=-1)


def _split_1919(hi, lo):
    """u128 (hi, lo) -> (h19, l19) with value = h19 * 10^19 + l19."""
    limbs = int256.from_i128(hi.astype(jnp.int64), lo)
    # analyze: ignore[governed-allocation] - decimal->string is not
    # yet wired into a governed pipeline (oracle/parity callers);
    # debt tracked at the site (round 16 baseline burn-down)
    q, r_hi, r_lo = int256.divide_unsigned(
        # analyze: ignore[governed-allocation] - same cast debt
        limbs, jnp.zeros_like(lo), jnp.full(lo.shape, 10**19, jnp.uint64)
    )
    q_lo = int256.to_i128(q)[1]  # quotient < 2^64 for |v| < 2^127
    return q_lo, r_lo


def decimal_to_string(col) -> StringColumn:
    """Convert DECIMAL32/64/128 to strings (decimal_to_non_ansi_string)."""
    if isinstance(col, Decimal128Column):
        hi = col.hi.astype(jnp.int64)
        lo = col.lo.astype(jnp.uint64)
        neg = hi < 0
        # |v| in u128
        nlo = (~lo) + _U64(1)
        nhi = (~hi.astype(_U64)) + (nlo == 0).astype(_U64)
        ahi = jnp.where(neg, nhi, hi.astype(_U64))
        alo = jnp.where(neg, nlo, lo)
        ss = col.dtype.scale
        validity = col.validity
        n = col.size
    elif isinstance(col, Column) and col.dtype.kind in (Kind.DECIMAL32, Kind.DECIMAL64):
        v = col.data.astype(jnp.int64)
        neg = v < 0
        alo = jnp.abs(v).astype(jnp.uint64)
        # analyze: ignore[governed-allocation] - same cast debt
        ahi = jnp.zeros_like(alo)
        ss = col.dtype.scale
        validity = col.validity
        n = col.size
    else:
        raise TypeError("decimal_to_string requires a decimal column")

    # digit count via u128 >= 10^k comparisons (no divider needed)
    p10_hi = jnp.asarray(_P10_HI)
    p10_lo = jnp.asarray(_P10_LO)
    # analyze: ignore[governed-allocation] - same cast debt
    nd = jnp.ones(alo.shape, _I32)
    for k in range(1, 39):
        ge = (ahi > p10_hi[k]) | ((ahi == p10_hi[k]) & (alo >= p10_lo[k]))
        nd = nd + ge.astype(_I32)
    adj = _I32(-ss) + nd - 1  # adjusted exponent (cu:72)
    plain = (ss >= 0) & (adj >= -6)
    K = jnp.where(plain, _I32(ss), nd - 1)  # fraction width

    # split |v| at 10^K: integer part and zero-padded fraction
    # (reuse the tables uploaded above: eager callers pay each jnp.asarray
    # as a fresh host->device constant transfer — round 20 audit)
    limbs = int256.from_i128(ahi.astype(jnp.int64), alo)
    d_hi = p10_hi[jnp.clip(K, 0, 39)]
    d_lo = p10_lo[jnp.clip(K, 0, 39)]
    q, r_hi, r_lo = int256.divide_unsigned(limbs, d_hi, d_lo)
    q_hi, q_lo = int256.to_i128(q)
    ih19, il19 = _split_1919(q_hi.astype(_U64), q_lo)
    fh19, fl19 = _split_1919(r_hi, r_lo)

    il = _digits_1919(ih19, il19)  # integer digit count (>= 1, "0" incl.)
    s = neg.astype(_I32)
    has_dot = K > 0
    eabs = jnp.abs(adj)
    elen = 1 + (eabs >= 10).astype(_I32) + (eabs >= 100).astype(_I32)
    sci = ~plain
    lens = (
        s
        + il
        + has_dot.astype(_I32) * (1 + K)
        + sci.astype(_I32) * (2 + elen)
    )

    # ---- render [n, MAX_LEN] grid ----
    p = jnp.arange(MAX_LEN, dtype=_I32)[None, :]
    sC, ilC, KC = s[:, None], il[:, None], K[:, None]
    in_int = (p >= sC) & (p < sC + ilC)
    int_digit = digit_from_table(
        _digit_table_1919(ih19, il19), ilC - 1 - (p - sC))
    dot_pos = sC + ilC
    frac_t = p - (dot_pos + 1)
    in_frac = has_dot[:, None] & (frac_t >= 0) & (frac_t < KC)
    frac_digit = digit_from_table(
        _digit_table_1919(fh19, fl19), KC - 1 - frac_t)
    pE = dot_pos + jnp.where(has_dot, 1 + K, 0)[:, None]
    exp_t = p - (pE + 2)
    elenC = elen[:, None]
    p10_small = jnp.asarray(_P10_SMALL)
    exp_digit = (
        (eabs[:, None] // p10_small[jnp.clip(elenC - 1 - exp_t, 0, 2)]) % 10
    ).astype(jnp.uint8) + jnp.uint8(ord("0"))

    grid = jnp.where(
        (p == 0) & (sC == 1),
        jnp.uint8(ord("-")),
        jnp.where(
            in_int,
            int_digit,
            jnp.where(
                has_dot[:, None] & (p == dot_pos),
                jnp.uint8(ord(".")),
                jnp.where(
                    in_frac,
                    frac_digit,
                    jnp.where(
                        sci[:, None] & (p == pE),
                        jnp.uint8(ord("E")),
                        jnp.where(
                            sci[:, None] & (p == pE + 1),
                            jnp.where(
                                adj[:, None] < 0,
                                jnp.uint8(ord("-")),
                                jnp.uint8(ord("+")),
                            ),
                            jnp.where(
                                sci[:, None] & (exp_t >= 0) & (exp_t < elenC),
                                exp_digit,
                                jnp.uint8(0),
                            ),
                        ),
                    ),
                ),
            ),
        ),
    )
    return strings_from_padded(grid, lens, validity)
