"""Fast-path replacement for the regex ``literal[start-end]{len,}``.

Parity with the reference's literal_range_pattern (regex_rewrite_utils.cu:37
literal_range_pattern_fn): True where the string contains the literal prefix
immediately followed by at least ``range_len`` characters whose codepoints lie
in ``[start, end]``.  Null rows yield null (mask copied; stored value False).

The reference scans per row with nested char loops; here the string column is
decoded to a char-compacted codepoint matrix (utils.utf8) and the match is a
shifted-AND reduction: for window origin i, prefix equality uses ``m`` static
shifts and the range check ``range_len`` more — all elementwise over
``[rows, chars]``.
"""

from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_jni_tpu.columnar.buckets import map_buckets
from spark_rapids_jni_tpu.columnar.column import Column, StringColumn
from spark_rapids_jni_tpu.columnar.dtypes import BOOL


def literal_range_pattern(
    input: StringColumn, prefix: str, range_len: int, start: int, end: int
) -> Column:
    """Does each row match ``prefix`` + ``range_len`` chars in [start, end]?"""
    from spark_rapids_jni_tpu.utils.utf8 import decode_utf8

    pat = [ord(c) for c in prefix]
    m = len(pat)
    window = m + range_len

    def kernel(padded, lens):
        cp, nchars = decode_utf8(padded, lens)
        n, L = cp.shape
        # pad chars so static window shifts stay in bounds
        cp_ext = jnp.pad(cp, ((0, 0), (0, window)), constant_values=-1)
        # analyze: ignore[governed-allocation] - pattern-kernel closure
        # not yet wired into a governed pipeline (oracle/test callers);
        # debt tracked at the site (round 16 baseline burn-down)
        ok = jnp.ones((n, L), jnp.bool_)
        for j, pc in enumerate(pat):
            ok = ok & (cp_ext[:, j : j + L] == pc)
        for j in range(range_len):
            c = cp_ext[:, m + j : m + j + L]
            ok = ok & (c >= start) & (c <= end)
        # origin must satisfy i <= nchars - m - range_len
        origin_ok = (
            jnp.arange(L, dtype=jnp.int32)[None, :] <= (nchars - window)[:, None]
        )
        return (jnp.any(ok & origin_ok, axis=1),)

    (found,) = map_buckets(input, kernel, [((), jnp.bool_)])
    found = jnp.where(input.is_valid(), found, False)
    return Column(found, input.validity, BOOL)
