"""Spark ``get_json_object``: JSON-path extraction over string columns.

Parity target: ``JSONUtils.getJsonObject`` (JSONUtils.java:47) over
``spark_rapids_jni::get_json_object`` (get_json_object.cu:360 evaluate_path,
:891 kernel) with the json_parser.cuh:220 tokenizer semantics.  The reference
runs one sequential pushdown parser per row (one GPU thread each); that shape
is hostile to TPU lanes, so the op is re-architected in three stages:

1. **Tokenize** (device, ops/json_tokenizer.py): whole byte rectangles ->
   validated per-row token streams with O(1) open/close match indices.
2. **Path evaluation** (host, this file): a *lockstep token machine* — every
   row advances through its token stream in parallel, one token (or one
   frame return) per step, with vectorized frame/generator stacks.  This is
   the explicit-stack form of evaluate_path's recursion (cases numbered as
   in get_json_object.cu:360-394); subtree skips are O(1) jumps through the
   tokenizer's match indices instead of token-at-a-time scans.  Token streams
   are ~10-100x smaller than the byte data, so control-heavy path logic runs
   on host while byte-heavy work stays on device.
3. **Render** (vectorized): each step emits up to two *segments* (constant
   bytes, raw/escaped string payloads, re-rendered numbers); per-byte
   escape/unescape emission tables + batched binary searches turn the
   segment streams into the output chars buffer.

The host machine is *adaptive* where the compiled scan cannot be: rows are
grouped into token-count sub-buckets (columnar/buckets.count_subbuckets) so
short rows never pay the bucket-wide step cap, and once at least half the
rows of a sub-bucket finish, state compacts down to the survivors
(``json_compact``) — segments scatter back by original row id, so output is
bit-identical with compaction on or off.  Rows that exhaust the ``2T +
json_step_margin`` step cap are nulled AND counted through the obs seam
(``seam(OP, "json:step_cap_truncated:<k>")`` + a profiler counter), so
truncation is observable instead of indistinguishable from a genuine null.

:func:`get_json_object_multiple_paths` evaluates P paths against ONE
tokenization (the reference ships getJsonObjectMultiplePaths for the same
reason — tokenization dominates and must be amortized): token streams,
byte tables, float re-renders and per-name match tables (deduplicated
across paths) are built once per bucket and fanned out to P machines.

Spark bug-compat quirks preserved (same set as tests/json_oracle.py):
``\\uXXXX`` emits decoded UTF-8 raw even in quoted output; a field name
containing ``\\u`` never matches a path name; ``-0`` normalizes to ``0``;
floats re-render via Java Double.toString with quoted ``"Infinity"``
(ftos_converter.cuh:1154); root-level trailing garbage is ignored; an
out-of-range array index drains tokens to the *next* close bracket at any
depth before returning (the reference's loop structure does the same).
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import time
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu.columnar.buckets import (
    count_subbuckets,
    padded_buckets,
    strings_from_buckets,
)
from spark_rapids_jni_tpu import config
from spark_rapids_jni_tpu.columnar.column import Column, StringColumn, next_pow2
from spark_rapids_jni_tpu.columnar.dtypes import FLOAT64
from spark_rapids_jni_tpu.obs.seam import OP, seam
from spark_rapids_jni_tpu.ops import json_tokenizer as jt
from spark_rapids_jni_tpu.ops.float_to_string import float_to_string

__all__ = [
    "get_json_object",
    "get_json_object_multiple_paths",
    "parse_path",
    "phase_times",
    "reset_phase_times",
    "truncation_count",
    "WILDCARD",
    "INDEX",
    "NAMED",
    "MAX_PATH_DEPTH",
]

# path instruction types (JSONUtils.PathInstructionJni)
WILDCARD, INDEX, NAMED = 0, 1, 2
_P_END = 3  # sentinel: past the last path instruction

MAX_PATH_DEPTH = 16  # get_json_object.cu:51

# write styles (get_json_object.cu write_style)
_RAW, _QUOTED, _FLATTEN = 0, 1, 2

# frame cases (numbered after evaluate_path's case labels)
_F_CASE2, _F_CASE4, _F_CASE5, _F_CASE6, _F_CASE7, _F_CASE8, _F_COPY = range(7)

# frame sub-states
_SUB_NONE, _SUB_ENTERING, _SUB_WAITING, _SUB_DRAIN = 0, 1, 2, 3

# segment types (int/float tokens travel as RAW/ESC and are remapped by kind
# in _render — no dedicated segment types)
_SEG_NONE, _SEG_CONST, _SEG_RAW_TOK, _SEG_ESC_TOK = 0, 1, 2, 3
_SEG_COND_OPEN, _SEG_COND_CLOSE = 4, 5

# constant-byte table (segment arg for _SEG_CONST)
_CONSTS = [b"", b",", b":", b"[", b"]", b"{", b"}", b"true", b"false",
           b"null", b"0", b",["]
_C_EMPTY, _C_COMMA, _C_COLON, _C_OPEN_ARR, _C_CLOSE_ARR = 0, 1, 2, 3, 4
_C_OPEN_OBJ, _C_CLOSE_OBJ, _C_TRUE, _C_FALSE, _C_NULL, _C_ZERO = 5, 6, 7, 8, 9, 10
_C_COMMA_OPEN = 11
_CONST_MAXLEN = max(len(c) for c in _CONSTS)
_CONST_TAB = np.zeros((len(_CONSTS), _CONST_MAXLEN), np.uint8)
_CONST_LEN = np.zeros((len(_CONSTS),), np.int32)
for _i, _c in enumerate(_CONSTS):
    _CONST_TAB[_i, : len(_c)] = np.frombuffer(_c, np.uint8)
    _CONST_LEN[_i] = len(_c)

_SCALARS = (jt.VALUE_STRING, jt.VALUE_NUMBER_INT, jt.VALUE_NUMBER_FLOAT,
            jt.VALUE_TRUE, jt.VALUE_FALSE, jt.VALUE_NULL)

# simple-escape map: source escape char -> unescaped byte
_UNESC = np.zeros(256, np.uint8)
for _src, _dst in [(ord('"'), ord('"')), (ord("'"), ord("'")),
                   (ord("\\"), ord("\\")), (ord("/"), ord("/")),
                   (ord("b"), 8), (ord("f"), 12), (ord("n"), 10),
                   (ord("r"), 13), (ord("t"), 9)]:
    _UNESC[_src] = _dst
# ctrl-char short escapes: code -> second byte, 0 => long \u00XX form
_CTRL_SHORT = np.zeros(32, np.uint8)
for _code, _ch in [(8, ord("b")), (9, ord("t")), (10, ord("n")),
                   (12, ord("f")), (13, ord("r"))]:
    _CTRL_SHORT[_code] = _ch
_HEX_UP = np.frombuffer(b"0123456789ABCDEF", np.uint8)


# ---------------------------------------------------------------------------
# observability: phase wall-clock attribution + step-cap truncation counter
# ---------------------------------------------------------------------------

_PHASE_TIMES: Dict[str, float] = {"tokenize": 0.0, "evaluate": 0.0,
                                  "render": 0.0}
_COUNTERS: Dict[str, int] = {"step_cap_truncated": 0}
# the serve worker pool runs the get_json_object handler from several
# threads at once; the read-modify-write accumulator updates must not race
_OBS_LOCK = threading.Lock()


def reset_phase_times() -> None:
    """Zero the per-phase wall-clock accumulators (bench sub-timings)."""
    with _OBS_LOCK:
        for k in _PHASE_TIMES:
            _PHASE_TIMES[k] = 0.0


def phase_times() -> Dict[str, float]:
    """Seconds spent per pipeline phase since the last reset.

    Host pipeline: exact wall clock per phase.  Device pipeline: phases
    are issued asynchronously, so time lands on the phase whose sync
    point materialized the work (still attributable, just coarser).
    """
    with _OBS_LOCK:
        return dict(_PHASE_TIMES)


def truncation_count() -> int:
    """Process-lifetime count of rows nulled by the machine step cap."""
    with _OBS_LOCK:
        return _COUNTERS["step_cap_truncated"]


@contextlib.contextmanager
def _phase(key: str):
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        with _OBS_LOCK:
            _PHASE_TIMES[key] += dt


def _note_truncation(k: int) -> None:
    """Surface step-cap truncation through the obs seam.

    A row that exhausts the ``2T + json_step_margin`` step cap is nulled —
    indistinguishable, at the column level, from a genuine null result.
    This crossing makes the difference observable: the fault injector can
    target it, the profiler records a cumulative counter, and the crossing
    name carries the per-call count.
    """
    if k <= 0:
        return
    with _OBS_LOCK:
        _COUNTERS["step_cap_truncated"] += int(k)
        total = _COUNTERS["step_cap_truncated"]
    with seam(OP, f"json:step_cap_truncated:{int(k)}"):
        from spark_rapids_jni_tpu.obs.profiler import Profiler

        Profiler.counter("json.step_cap_truncated", total)


def parse_path(path: str) -> List[tuple]:
    """Parse ``$.a[2].*``-style JSON paths into instruction tuples.

    Mirrors Spark's JsonPathParser grammar subset the plugin passes down:
    ``$`` root, ``.name`` / ``['name']`` named fields, ``[n]`` index,
    ``.*`` / ``[*]`` wildcard.  Raises ValueError on malformed paths.
    """
    if not path.startswith("$"):
        raise ValueError(f"JSON path must start with $: {path!r}")
    out: List[tuple] = []
    i = 1
    while i < len(path):
        c = path[i]
        if c == ".":
            i += 1
            if i < len(path) and path[i] == "*":
                out.append((WILDCARD,))
                i += 1
                continue
            j = i
            while j < len(path) and path[j] not in ".[":
                j += 1
            if j == i:
                raise ValueError(f"empty field name in {path!r}")
            out.append((NAMED, path[i:j].encode()))
            i = j
        elif c == "[":
            if path.startswith("['", i):
                # non-greedy \['(.*?)'\] as in Spark's JsonPathParser:
                # names may contain ']'
                j = path.find("']", i + 2)
                if j < 0:
                    raise ValueError(
                        f"unterminated ['name'] selector in {path!r}")
                out.append((NAMED, path[i + 2 : j].encode()))
                i = j + 2  # past the closing '] pair
                continue
            j = path.find("]", i)
            if j < 0:
                raise ValueError(f"unterminated [...] selector in {path!r}")
            inner = path[i + 1 : j]
            if inner == "*":
                out.append((WILDCARD,))
            elif inner == "":
                raise ValueError(f"empty bracket selector in {path!r}")
            elif inner.startswith("-"):
                raise ValueError(f"negative array index in {path!r}")
            elif not (inner.isascii() and inner.isdigit()):
                # int() would accept '+1', ' 2', '1_0' — Spark's parser
                # grammar takes plain digits only
                raise ValueError(f"invalid array index {inner!r} in {path!r}")
            else:
                out.append((INDEX, int(inner)))
            i = j + 1
        else:
            raise ValueError(f"unexpected {c!r} in JSON path {path!r}")
    return out


def _batched_searchsorted_right(a: np.ndarray, v: np.ndarray) -> np.ndarray:
    """Per-row ``searchsorted(a[r], v[r], side='right')``.

    ``a``: [n, m] row-sorted; ``v``: [n, q].  Returns int32 [n, q].
    """
    n, m = a.shape
    lo = np.zeros(v.shape, np.int32)
    hi = np.full(v.shape, m, np.int32)
    steps = max(m, 1).bit_length() + 1  # covers all m+1 outcomes of [0, m]
    rows = np.arange(n, dtype=np.int32)[:, None]
    for _ in range(steps):
        mid = (lo + hi) >> 1
        go_right = a[rows, np.minimum(mid, m - 1)] <= v
        lo = np.where(go_right & (mid < m), mid + 1, lo)
        hi = np.where(go_right & (mid < m), hi, mid)
    return lo


@dataclasses.dataclass
class _ByteInfo:
    """Per-byte escape/unescape emission tables for one bucket."""

    b: np.ndarray          # [n, L] uint8 source bytes
    cls_bs: np.ndarray     # backslash that leads an escape
    cls_esc: np.ndarray    # the escaped char (2nd byte of a simple escape)
    cls_u: np.ndarray      # the 'u' of a \\uXXXX escape
    cls_hex: np.ndarray    # one of the 4 hex digits of a \\u escape
    cp: np.ndarray         # [n, L] int32 codepoint (at the 'u' position)
    ulen: np.ndarray       # [n, L] utf8 byte length of cp (1..3)
    len_u: np.ndarray      # unescape emission length per byte
    len_e: np.ndarray      # escape emission length per byte
    cum_u: np.ndarray      # [n, L+1] exclusive prefix sums
    cum_e: np.ndarray
    cum_uni: np.ndarray    # [n, L+1] prefix count of \\u escapes
    cum_bs: np.ndarray     # [n, L+1] prefix count of escape-leading backslashes


@jax.jit
def _string_states(b_j: jnp.ndarray, lens_j: jnp.ndarray) -> jnp.ndarray:
    n, L = b_j.shape
    in_row = jnp.arange(L, dtype=jnp.int32)[None, :] < lens_j[:, None]
    st_after = jt._string_automaton(b_j, in_row)
    return jnp.pad(st_after, ((0, 0), (1, 0)))[:, :L]


def _byte_info(b_j: jnp.ndarray, lens_j: jnp.ndarray,
               n_valid: Optional[int] = None,
               str_state: Optional[jnp.ndarray] = None) -> _ByteInfo:
    """Per-byte tables for a bucket.  The jitted automaton sees the full
    pow2-padded shape (bounded compile-variant set); the host-side numpy
    passes run only on the first ``n_valid`` real rows.  ``str_state``
    (TokenStream.str_state, the state AFTER each byte) skips the second
    automaton pass when the bucket was already tokenized."""
    if str_state is not None:
        st_after = np.asarray(str_state)
        st_before = np.zeros_like(st_after)
        st_before[:, 1:] = st_after[:, :-1]
    else:
        st_before = np.asarray(_string_states(b_j, lens_j))
    b = np.asarray(b_j)
    if n_valid is not None:
        st_before = st_before[:n_valid]
        b = b[:n_valid]
    n, L = b.shape

    in_dq = (st_before == jt._S_DQ)
    in_sq = (st_before == jt._S_SQ)
    cls_esc_all = (st_before == jt._S_DQE) | (st_before == jt._S_SQE)
    cls_bs = (in_dq | in_sq) & (b == ord("\\"))
    cls_u = cls_esc_all & (b == ord("u"))
    cls_esc = cls_esc_all & ~cls_u
    cls_hex = np.zeros_like(cls_u)
    for k in range(1, 5):
        cls_hex[:, k:] |= cls_u[:, :-k]
    close_q = (in_dq & (b == ord('"'))) | (in_sq & (b == ord("'")))

    # codepoint at 'u' positions from the following 4 hex digits
    hexval = np.zeros(b.shape, np.int32)
    d = b.astype(np.int32)
    hexval = np.where((b >= ord("0")) & (b <= ord("9")), d - ord("0"), hexval)
    hexval = np.where((b >= ord("a")) & (b <= ord("f")), d - ord("a") + 10, hexval)
    hexval = np.where((b >= ord("A")) & (b <= ord("F")), d - ord("A") + 10, hexval)
    cp = np.zeros(b.shape, np.int32)
    for k in range(1, 5):
        sh = np.zeros(b.shape, np.int32)
        sh[:, :-k] = hexval[:, k:]
        cp |= sh << (4 * (4 - k))
    ulen = np.where(cp < 0x80, 1, np.where(cp < 0x800, 2, 3)).astype(np.int32)

    normal = (in_dq | in_sq) & ~cls_bs & ~close_q & ~cls_hex
    is_ctrl = normal & (b < 32)
    short_ctrl = is_ctrl & (_CTRL_SHORT[np.minimum(b, 31)] != 0)

    len_u = np.zeros(b.shape, np.int32)
    len_u = np.where(normal, 1, len_u)
    len_u = np.where(cls_esc, 1, len_u)
    len_u = np.where(cls_u, ulen, len_u)

    len_e = np.zeros(b.shape, np.int32)
    len_e = np.where(normal, 1, len_e)
    len_e = np.where(normal & (b == ord('"')), 2, len_e)
    len_e = np.where(short_ctrl, 2, len_e)
    len_e = np.where(is_ctrl & ~short_ctrl, 6, len_e)
    two_byte = (b == ord('"')) | (b == ord("\\"))
    for ch in b"bfnrt":
        two_byte |= b == ch
    len_e = np.where(cls_esc, np.where(two_byte, 2, 1), len_e)
    len_e = np.where(cls_u, ulen, len_e)

    def excl_cum(x):
        out = np.zeros((n, L + 1), np.int64)
        np.cumsum(x, axis=1, out=out[:, 1:])
        return out

    return _ByteInfo(
        b=b, cls_bs=cls_bs, cls_esc=cls_esc, cls_u=cls_u, cls_hex=cls_hex,
        cp=cp, ulen=ulen, len_u=len_u, len_e=len_e,
        cum_u=excl_cum(len_u), cum_e=excl_cum(len_e),
        cum_uni=excl_cum(cls_u.astype(np.int64)),
        cum_bs=excl_cum(cls_bs.astype(np.int64)),
    )


def _slice_byte_info(bi: _ByteInfo, sel: np.ndarray) -> _ByteInfo:
    """Row-subset view of a bucket's byte tables (token-count sub-buckets)."""
    return _ByteInfo(**{
        f.name: getattr(bi, f.name)[sel] for f in dataclasses.fields(_ByteInfo)
    })


def _utf8_byte(cp: np.ndarray, ulen: np.ndarray, k: np.ndarray) -> np.ndarray:
    """k-th UTF-8 byte of codepoint cp (json_parser.cuh:903 encoding)."""
    b1 = np.where(ulen == 1, cp,
                  np.where(ulen == 2, 0xC0 | (cp >> 6), 0xE0 | (cp >> 12)))
    b2 = np.where(ulen == 2, 0x80 | (cp & 0x3F), 0x80 | ((cp >> 6) & 0x3F))
    b3 = 0x80 | (cp & 0x3F)
    return np.where(k == 0, b1, np.where(k == 1, b2, b3)).astype(np.uint8)


def _emission_byte(bi: _ByteInfo, ri: np.ndarray, si: np.ndarray,
                   k: np.ndarray, escaped: bool) -> np.ndarray:
    """Byte ``k`` of source byte ``(ri, si)``'s emission."""
    c = bi.b[ri, si]
    if not escaped:
        out = c.copy()
        esc = bi.cls_esc[ri, si]
        out = np.where(esc, _UNESC[c], out)
        u = bi.cls_u[ri, si]
        out = np.where(u, _utf8_byte(bi.cp[ri, si], bi.ulen[ri, si], k), out)
        return out.astype(np.uint8)
    # escaped (quoted) emission
    is_ctrl = c < 32
    short = np.where(is_ctrl, _CTRL_SHORT[np.minimum(c, 31)], 0)
    # long ctrl: \ u 0 0 H L
    long_bytes = np.select(
        [k == 0, k == 1, k == 2, k == 3, k == 4],
        [ord("\\"), ord("u"), ord("0"), ord("0"),
         np.where(c >= 16, ord("1"), ord("0"))],
        default=_HEX_UP[c % 16],
    )
    ctrl_out = np.where(
        short != 0, np.where(k == 0, ord("\\"), short), long_bytes
    )
    # normal char: '"' -> \" ; else itself
    norm_out = np.where(
        c == ord('"'), np.where(k == 0, ord("\\"), ord('"')), c
    )
    out = np.where(is_ctrl, ctrl_out, norm_out)
    # simple escape char: 2-byte forms keep backslash, 1-byte map
    esc = bi.cls_esc[ri, si]
    two = (c == ord('"')) | (c == ord("\\"))
    for ch in b"bfnrt":
        two = two | (c == ch)
    esc_out = np.where(two, np.where(k == 0, ord("\\"), c), _UNESC[c])
    # \" is backslash then quote; \\ is backslash backslash; \b.. keep char
    esc_out = np.where((c == ord('"')) & (k == 1), ord('"'), esc_out)
    out = np.where(esc, esc_out, out)
    u = bi.cls_u[ri, si]
    out = np.where(u, _utf8_byte(bi.cp[ri, si], bi.ulen[ri, si], k), out)
    return out.astype(np.uint8)


def _token_tables(bi: _ByteInfo, kind, start, end):
    """Per-token emission lengths for raw and escaped variants, plus flags."""
    n, T = kind.shape
    s64 = start.astype(np.int64)
    e64 = end.astype(np.int64)
    rows = np.arange(n, dtype=np.int64)[:, None]
    L = bi.b.shape[1]

    is_str = (kind == jt.VALUE_STRING) | (kind == jt.FIELD_NAME)
    ps = np.minimum(s64 + 1, L)  # payload start (skip quote)
    pe = np.clip(e64 - 1, 0, L)  # payload end (before close quote)
    pay_u = bi.cum_u[rows, pe] - bi.cum_u[rows, ps]
    pay_e = bi.cum_e[rows, pe] - bi.cum_e[rows, ps]
    has_uni = (bi.cum_uni[rows, pe] - bi.cum_uni[rows, ps]) > 0

    span = e64 - s64
    is_int = kind == jt.VALUE_NUMBER_INT
    # -0 normalization (json_parser.cuh number copy: sign dropped for -0)
    neg0 = is_int & (span == 2) & (bi.b[rows, np.minimum(s64, L - 1)] == ord("-")) \
        & (bi.b[rows, np.minimum(s64 + 1, L - 1)] == ord("0"))

    len_raw = np.zeros((n, T), np.int64)
    len_esc = np.zeros((n, T), np.int64)
    one = (kind == jt.START_OBJECT) | (kind == jt.END_OBJECT) | \
        (kind == jt.START_ARRAY) | (kind == jt.END_ARRAY)
    len_raw = np.where(one, 1, len_raw)
    len_raw = np.where(kind == jt.VALUE_TRUE, 4, len_raw)
    len_raw = np.where(kind == jt.VALUE_FALSE, 5, len_raw)
    len_raw = np.where(kind == jt.VALUE_NULL, 4, len_raw)
    len_raw = np.where(is_int, np.where(neg0, 1, span), len_raw)
    len_esc = np.where(one | (kind == jt.VALUE_TRUE) | (kind == jt.VALUE_FALSE)
                       | (kind == jt.VALUE_NULL) | is_int, len_raw, len_esc)
    len_raw = np.where(is_str, pay_u, len_raw)
    len_esc = np.where(is_str, pay_e + 2, len_esc)
    return len_raw, len_esc, has_uni, neg0


def _float_texts(bi: _ByteInfo, kind, start, end, used=None):
    """Rendered Java Double.toString text per FLOAT token.

    Returns (ftext [nf, W] uint8, flen [nf], fidx [n, T] index or -1).
    Infinity renders quoted (ftos_converter.cuh:1154 quirk).  ``used``
    ([n, T] bool) restricts the build to tokens actually referenced by
    output segments — a path that never emits a float skips the whole
    Ryu re-render instead of paying for every float in the corpus.
    """
    n, T = kind.shape
    fmask = kind == jt.VALUE_NUMBER_FLOAT
    if used is not None:
        fmask = fmask & used
    ri, ti = np.nonzero(fmask)
    fidx = np.full((n, T), -1, np.int64)
    if len(ri) == 0:
        return np.zeros((0, 1), np.uint8), np.zeros((0,), np.int64), fidx
    nf = len(ri)
    fidx[ri, ti] = np.arange(nf)
    # gather each float's text into a padded byte matrix, parse via numpy's
    # bytes->float64 cast (correctly-rounded strtod, vectorized)
    fs = start[ri, ti].astype(np.int64)
    fe = end[ri, ti].astype(np.int64)
    wsrc = max(int((fe - fs).max()), 1)
    L = bi.b.shape[1]
    lane = np.arange(wsrc, dtype=np.int64)[None, :]
    raw = bi.b[ri[:, None], np.clip(fs[:, None] + lane, 0, L - 1)]
    raw = np.where(lane < (fe - fs)[:, None], raw, 0)
    vals = raw.view(f"S{wsrc}").reshape(nf).astype(np.float64)
    col = Column(jnp.asarray(vals.view(np.int64)), None, FLOAT64)
    sc = float_to_string(col)
    offs = np.asarray(sc.offsets).astype(np.int64)
    chars = np.asarray(sc.chars)
    flen = offs[1:] - offs[:-1]
    is_inf = np.isinf(vals)
    out_len = np.where(is_inf, flen + 2, flen)  # quoted "Infinity" quirk
    W = max(int(out_len.max()), 1)
    lane = np.arange(W, dtype=np.int64)[None, :]
    src = offs[:-1, None] + lane - is_inf[:, None]  # shift 1 for open quote
    gathered = chars[np.clip(src, 0, max(len(chars) - 1, 0))]
    in_text = (lane >= is_inf[:, None]) & (lane < (flen + is_inf)[:, None])
    ftext = np.where(in_text, gathered, 0).astype(np.uint8)
    quote_pos = is_inf[:, None] & ((lane == 0) | (lane == out_len[:, None] - 1))
    ftext = np.where(quote_pos, ord('"'), ftext)
    return ftext, out_len, fidx


def _name_matches(bi: _ByteInfo, kind, start, end, names: Sequence[bytes],
                  len_raw, has_uni, cache: Optional[dict] = None):
    """[n, T] bool per path name: token payload unescapes to exactly name.

    Implements field_matches (get_json_object.cu / json_parser.cuh) including
    the \\u-never-matches quirk.  Work is restricted to *candidate* tokens
    (FIELD_NAME, right unescaped length, no \\u): escape-free payloads —
    the overwhelming majority — compare by direct byte gather; only
    payloads containing a backslash walk the per-byte emission tables.
    ``cache`` (name bytes -> table) deduplicates across a multi-path call's
    shared levels.
    """
    n, T = kind.shape
    L = bi.b.shape[1]
    # FIELD_NAME only: the machine consumes name matches solely at the
    # object-field step (CASE4 reads name_match at a FIELD_NAME token),
    # and the device matcher (json_render_device.py _name_match_one) is
    # narrowed the same way — the fuzz tier asserts host/device parity
    # on these tables, so the gates must not diverge.
    is_str = kind == jt.FIELD_NAME
    out = []
    for name in names:
        if name is None:
            out.append(np.zeros((n, T), bool))
            continue
        if cache is not None and name in cache:
            out.append(cache[name])
            continue
        m = len(name)
        ok = is_str & ~has_uni & (len_raw == m)
        ri, ti = np.nonzero(ok)
        if m > 0 and len(ri):
            nb = np.frombuffer(name, np.uint8)
            s64 = start[ri, ti].astype(np.int64)
            ps = np.minimum(s64 + 1, L)       # payload start (skip quote)
            pe = np.clip(end[ri, ti].astype(np.int64) - 1, 0, L)
            esc_free = (bi.cum_bs[ri, pe] - bi.cum_bs[ri, ps]) == 0
            good = np.zeros(len(ri), bool)
            f = np.nonzero(esc_free)[0]
            if len(f):
                # no backslash in the payload -> unescaped payload IS the
                # source bytes; len_raw == m already pinned the width
                lane = np.arange(m, dtype=np.int64)[None, :]
                src = np.minimum(ps[f, None] + lane, L - 1)
                good[f] = (bi.b[ri[f, None], src] == nb[None, :]).all(axis=1)
            s = np.nonzero(~esc_free)[0]
            if len(s):
                rs = ri[s]
                base = bi.cum_u[rs, ps[s]]    # output offset of payload start
                acc = np.ones(len(s), bool)
                cu = bi.cum_u[rs]             # [ns, L+1]
                for q in range(m):
                    tgt = (base + q)[:, None]
                    si = np.minimum(
                        _batched_searchsorted_right(cu[:, 1:], tgt), L - 1)
                    k = tgt - cu[np.arange(len(s))[:, None], si]
                    got = _emission_byte(
                        bi, np.broadcast_to(rs[:, None], si.shape), si, k,
                        escaped=False)
                    acc = acc & (got[:, 0] == nb[q])
                good[s] = acc
            ok[ri, ti] = good
        out.append(ok)
        if cache is not None:
            cache[name] = ok
    return out


class _Machine:
    """Vectorized lockstep evaluator for one (sub-)bucket (numpy, host).

    Mirrors the recursive oracle (tests/json_oracle.py _evaluate) as an
    explicit stack machine; one scan step = one token consumed or one frame
    return processed, across all *active* rows simultaneously.  Rows that
    finish are compacted away (``json_compact``): when at least half the
    current rows are done, state gathers down to the survivors and a row
    map carries their identity, so per-step cost tracks the live frontier
    instead of the original row count.  Per-step segments record their row
    map; :meth:`segment_tables` scatters them back into original-row-id
    space, which makes compaction invisible to the renderer.
    """

    def __init__(self, kind, match, ntok, ok, path_types, path_args,
                 name_match, *, compact=True, step_margin=40):
        self.kind = kind
        self.match = match
        self.ntok = ntok
        n, T = kind.shape
        self.n, self.T = n, T
        self.n0 = n                       # machine-entry row count
        self.compact = compact
        self.step_margin = step_margin
        P = len(path_types)
        self.ptype = np.asarray(list(path_types) + [_P_END], np.int32)
        self.parg = np.asarray(
            [a if isinstance(a, int) else 0 for a in path_args] + [0], np.int64
        )
        # [levels, n, T] stacked name-match tables (one gather per step
        # instead of a per-level python scan)
        self.nm_stack = (np.stack(name_match) if name_match
                         else np.zeros((0, n, T), bool))

        F = min(jt.MAX_DEPTH + MAX_PATH_DEPTH + 6, T + 3)
        G = min(MAX_PATH_DEPTH + 2, F)
        self.F, self.G = F, G
        self.rowmap = np.arange(n, dtype=np.int64)  # current -> entry row id
        self._rows = np.arange(n, dtype=np.int64)   # cached arange(cur_n)
        self.tcur = np.zeros((n,), np.int64)
        self.err = ~ok.copy()
        self.done = np.zeros((n,), bool)
        self.dirty_root = np.zeros((n,), np.int64)
        self.ret_valid = np.zeros((n,), bool)
        self.ret_dirty = np.zeros((n,), np.int64)
        self.fp = np.full((n,), -1, np.int64)  # -1 => root call pending
        self.f_case = np.zeros((n, F), np.int8)
        self.f_path = np.zeros((n, F), np.int32)
        self.f_style = np.zeros((n, F), np.int8)
        self.f_dirty = np.zeros((n, F), np.int64)
        self.f_sub = np.zeros((n, F), np.int8)
        self.f_aux = np.zeros((n, F), np.int64)   # remaining / end_tok / open step
        self.f_flag = np.zeros((n, F), bool)      # case6 need_comma / case8 with_wc
        self.g_depth = np.zeros((n, G), np.int64)
        self.g_empty = np.ones((n, G), bool)
        self.gp = np.zeros((n,), np.int64)
        self.entered_root = np.zeros((n,), bool)
        # entry-row-space results (banked as rows compact away)
        self.err_out = np.zeros((n,), bool)
        self.dirty_out = np.zeros((n,), np.int64)
        self.segs: List[tuple] = []  # per step: (rowmap, [m, 2, 2])
        # deferred case-6 open resolutions: (entry rows, open step, const id)
        self.patches: List[tuple] = []

    # -- small helpers ----------------------------------------------------
    def _set_frame(self, mask, field, val):
        arr = getattr(self, field)
        rows = np.nonzero(mask)[0]
        arr[rows, self.fp[rows]] = val[rows] if isinstance(val, np.ndarray) else val

    def _top(self, field):
        arr = getattr(self, field)
        return arr[self._rows, np.clip(self.fp, 0, self.F - 1)]

    def _gen_top(self, field):
        arr = getattr(self, field)
        return arr[self._rows, np.clip(self.gp, 0, self.G - 1)]

    def _set_gen(self, mask, field, val):
        arr = getattr(self, field)
        rows = np.nonzero(mask)[0]
        arr[rows, self.gp[rows]] = val[rows] if isinstance(val, np.ndarray) else val

    _STATE_FIELDS = ("tcur", "err", "done", "dirty_root", "ret_valid",
                     "ret_dirty", "fp", "f_case", "f_path", "f_style",
                     "f_dirty", "f_sub", "f_aux", "f_flag", "g_depth",
                     "g_empty", "gp", "entered_root", "kind", "match",
                     "ntok", "rowmap")

    def _bank(self, sel):
        """Record final results for current rows ``sel`` (entry space)."""
        tgt = self.rowmap[sel]
        self.err_out[tgt] = self.err[sel]
        self.dirty_out[tgt] = self.dirty_root[sel]

    def _compact(self, keep):
        """Gather machine state down to the rows still running."""
        fin = np.nonzero(~keep)[0]
        self._bank(fin)
        sel = np.nonzero(keep)[0]
        for f in self._STATE_FIELDS:
            setattr(self, f, getattr(self, f)[sel])
        self.nm_stack = self.nm_stack[:, sel]
        self.n = len(sel)
        self._rows = np.arange(self.n, dtype=np.int64)

    def run(self):
        """Step to quiescence; returns the step-cap truncation count.

        Populates ``err_out`` / ``dirty_out`` (entry row space) and the
        per-step segment record consumed by :meth:`segment_tables`.
        """
        S = max(2 * self.T + self.step_margin, 1)
        for s in range(S):
            live = ~(self.done | self.err)
            n_live = int(np.count_nonzero(live))
            if n_live == 0:
                break
            if self.compact and self.n >= 64 and 2 * n_live <= self.n:
                self._compact(live)
            self._step(s)
        # rows that exhausted the step cap: nulled, but observably so
        trunc = ~(self.done | self.err)
        n_trunc = int(np.count_nonzero(trunc))
        self.err |= trunc
        self._bank(self._rows)
        return n_trunc

    def segment_tables(self):
        """Scatter per-step segments back to entry-row space.

        Returns ``(stype, sarg)`` as [n0, 2*steps] int32 — compaction and
        sub-bucketing are invisible past this point.  Case-6 conditional
        opens recorded in ``patches`` resolve here; opens whose close never
        ran (err/truncated rows) stay _SEG_COND_OPEN and are dropped.
        """
        S = len(self.segs)
        stype = np.zeros((self.n0, 2 * max(S, 1)), np.int32)
        sarg = np.zeros_like(stype)
        for s, (rmap, seg) in enumerate(self.segs):
            stype[rmap, 2 * s] = seg[:, 0, 0]
            sarg[rmap, 2 * s] = seg[:, 0, 1]
            stype[rmap, 2 * s + 1] = seg[:, 1, 0]
            sarg[rmap, 2 * s + 1] = seg[:, 1, 1]
        for rows, g, const_id in self.patches:
            stype[rows, 2 * g] = _SEG_CONST
            sarg[rows, 2 * g] = const_id
        unresolved = stype == _SEG_COND_OPEN
        stype = np.where(unresolved, _SEG_NONE, stype)
        return stype, sarg

    def _step(self, s):
        n = self.n
        rows = self._rows
        seg = np.zeros((n, 2, 2), np.int32)  # slots x (type, arg)
        active = ~self.done & ~self.err

        # ---- 1) process pending returns ----------------------------------
        retm = active & self.ret_valid
        if retm.any():
            at_root = retm & (self.fp < 0)
            self.done |= at_root
            self.dirty_root = np.where(at_root, self.ret_dirty, self.dirty_root)
            fr = retm & ~at_root
            if fr.any():
                case = self._top("f_case")
                sub = self._top("f_sub")
                # accumulating cases
                acc = fr & np.isin(case, (_F_CASE2, _F_CASE5, _F_CASE6, _F_CASE7))
                self._set_frame(acc, "f_dirty", self._top("f_dirty") + self.ret_dirty)
                c4 = fr & (case == _F_CASE4) & (sub == _SUB_WAITING)
                bad = c4 & (self.ret_dirty == 0)
                self.err |= bad
                good = c4 & ~bad
                self._set_frame(good, "f_dirty", self.ret_dirty)
                self._set_frame(good, "f_flag", True)  # found
                self._set_frame(good, "f_sub", _SUB_NONE)
                c8 = fr & (case == _F_CASE8) & (sub == _SUB_WAITING)
                self._set_frame(c8, "f_dirty", self.ret_dirty)
                self._set_frame(c8, "f_sub", _SUB_DRAIN)
            self.ret_valid &= ~retm
            active = active & ~retm & ~self.err

        if not active.any():
            self.segs.append((self.rowmap, seg))
            return

        # ---- 2) frame-top / root dispatch --------------------------------
        out_of_tok = active & (self.tcur >= self.ntok)
        self.err |= out_of_tok
        active &= ~out_of_tok

        k = self.kind[rows, np.clip(self.tcur, 0, self.T - 1)].astype(np.int32)
        case = self._top("f_case")
        sub = self._top("f_sub")
        style = self._top("f_style")
        fpath = self._top("f_path")

        is_root = active & (self.fp < 0) & ~self.entered_root
        self.entered_root |= is_root

        close_arr = k == jt.END_ARRAY
        close_obj = k == jt.END_OBJECT

        # COPY frames: emit every token until end marker
        copym = active & (self.fp >= 0) & (case == _F_COPY)
        if copym.any():
            prevk = self.kind[rows, np.clip(self.tcur - 1, 0, self.T - 1)]
            sep_colon = prevk == jt.FIELD_NAME
            prev_valend = np.isin(prevk, _SCALARS) | \
                (prevk == jt.END_OBJECT) | (prevk == jt.END_ARRAY)
            cur_close = close_arr | close_obj
            sep_comma = prev_valend & ~cur_close
            seg[:, 0, 0] = np.where(copym & (sep_colon | sep_comma),
                                    _SEG_CONST, seg[:, 0, 0])
            seg[:, 0, 1] = np.where(copym & sep_colon, _C_COLON, seg[:, 0, 1])
            seg[:, 0, 1] = np.where(copym & sep_comma & ~sep_colon,
                                    _C_COMMA, seg[:, 0, 1])
            seg[:, 1, 0] = np.where(copym, _SEG_ESC_TOK, seg[:, 1, 0])
            seg[:, 1, 1] = np.where(copym, self.tcur, seg[:, 1, 1])
            at_end = copym & (self.tcur == self._top("f_aux"))
            self._pop_ret(at_end, np.ones(n, np.int64))
            self.tcur = np.where(copym, self.tcur + 1, self.tcur)
            active &= ~copym

        # CASE2: flatten-array loop
        c2 = active & (self.fp >= 0) & (case == _F_CASE2)
        c2_close = c2 & close_arr
        self._pop_ret(c2_close, self._top("f_dirty"))
        self.tcur = np.where(c2_close, self.tcur + 1, self.tcur)
        c2_enter = c2 & ~close_arr

        # CASE4: object field loop
        c4 = active & (self.fp >= 0) & (case == _F_CASE4)
        c4_entering = c4 & (sub == _SUB_ENTERING)
        c4 = c4 & (sub != _SUB_ENTERING)
        c4_close = c4 & close_obj
        self._pop_ret(c4_close, self._top("f_dirty"))
        self.tcur = np.where(c4_close, self.tcur + 1, self.tcur)
        c4_field = c4 & ~close_obj
        if c4_field.any():
            nm = np.zeros((n,), bool)
            nlvl = self.nm_stack.shape[0]
            if nlvl:
                sel = np.nonzero(c4_field)[0]
                nm[sel] = self.nm_stack[
                    np.clip(fpath[sel], 0, nlvl - 1), sel,
                    np.clip(self.tcur[sel], 0, self.T - 1)]
            found = self._top("f_flag")
            hit = c4_field & nm & ~found
            miss = c4_field & ~hit
            # skip field name + its value in one step
            vt = np.clip(self.tcur + 1, 0, self.T - 1)
            vkind = self.kind[rows, vt]
            vopen = (vkind == jt.START_OBJECT) | (vkind == jt.START_ARRAY)
            skip_to = np.where(vopen, self.match[rows, vt] + 1, self.tcur + 2)
            self.tcur = np.where(miss, skip_to, self.tcur)
            # matched: null value -> whole row null (evaluate_path named case)
            isnull = vkind == jt.VALUE_NULL
            self.err |= hit & isnull
            ok_hit = hit & ~isnull
            self.tcur = np.where(ok_hit, self.tcur + 1, self.tcur)
            self._set_frame(ok_hit, "f_sub", _SUB_ENTERING)
        c4_go = c4_entering  # dispatch child eval this step
        self._set_frame(c4_go, "f_sub", _SUB_WAITING)

        # CASE5: [*][*] loop
        c5 = active & (self.fp >= 0) & (case == _F_CASE5)
        c5_close = c5 & close_arr
        if c5_close.any():
            seg[:, 1, 0] = np.where(c5_close, _SEG_CONST, seg[:, 1, 0])
            seg[:, 1, 1] = np.where(c5_close, _C_CLOSE_ARR, seg[:, 1, 1])
            self._set_gen(c5_close, "g_depth", self._gen_top("g_depth") - 1)
            self._set_gen(c5_close, "g_empty", False)
            self._pop_ret(c5_close, self._top("f_dirty"))
            self.tcur = np.where(c5_close, self.tcur + 1, self.tcur)
        c5_enter = c5 & ~close_arr

        # CASE6: wildcard with child generator
        c6 = active & (self.fp >= 0) & (case == _F_CASE6)
        c6_close = c6 & close_arr
        if c6_close.any():
            # resolve both conditionals NOW (dirty count and need_comma are
            # final at close): the close emits its resolved const directly,
            # the matching open (slot 0 of step f_aux) resolves through a
            # deferred patch applied in segment_tables — no per-row loop,
            # no per-generation rescan of the segment stream at render
            d = self._top("f_dirty")
            ncf = self._top("f_flag")
            sel = np.nonzero(c6_close)[0]
            open_id = np.where(
                d > 1, np.where(ncf, _C_COMMA_OPEN, _C_OPEN_ARR),
                np.where((d == 1) & ncf, _C_COMMA, _C_EMPTY))
            close_id = np.where(d > 1, _C_CLOSE_ARR, _C_EMPTY)
            self.patches.append((self.rowmap[sel],
                                 self.f_aux[sel, self.fp[sel]],
                                 open_id[sel]))
            seg[:, 1, 0] = np.where(c6_close, _SEG_CONST, seg[:, 1, 0])
            seg[:, 1, 1] = np.where(c6_close, close_id, seg[:, 1, 1])
            self.gp = np.where(c6_close, self.gp - 1, self.gp)  # pop child gen
            # write_child_raw_value: parent empty=False when dirty>=1 & depth>0
            wrote = c6_close & (d >= 1) & (self._gen_top("g_depth") > 0)
            self._set_gen(wrote, "g_empty", False)
            self._pop_ret(c6_close, d)
            self.tcur = np.where(c6_close, self.tcur + 1, self.tcur)
        c6_enter = c6 & ~close_arr

        # CASE7: wildcard, quoted style
        c7 = active & (self.fp >= 0) & (case == _F_CASE7)
        c7_close = c7 & close_arr
        if c7_close.any():
            seg[:, 1, 0] = np.where(c7_close, _SEG_CONST, seg[:, 1, 0])
            seg[:, 1, 1] = np.where(c7_close, _C_CLOSE_ARR, seg[:, 1, 1])
            self._set_gen(c7_close, "g_depth", self._gen_top("g_depth") - 1)
            self._set_gen(c7_close, "g_empty", False)
            self._pop_ret(c7_close, self._top("f_dirty"))
            self.tcur = np.where(c7_close, self.tcur + 1, self.tcur)
        c7_enter = c7 & ~close_arr

        # CASE8: index
        c8 = active & (self.fp >= 0) & (case == _F_CASE8)
        c8_skip = c8 & (sub == _SUB_NONE) & (self._top("f_aux") > 0)
        if c8_skip.any():
            self.err |= c8_skip & close_arr  # index out of bounds mid-skip
            ok8 = c8_skip & ~close_arr
            isopen = (k == jt.START_OBJECT) | (k == jt.START_ARRAY)
            skip_to = np.where(isopen, self.match[rows, np.clip(
                self.tcur, 0, self.T - 1)] + 1, self.tcur + 1)
            self.tcur = np.where(ok8, skip_to, self.tcur)
            self._set_frame(ok8, "f_aux", self._top("f_aux") - 1)
        c8_go = c8 & (sub == _SUB_NONE) & (self._top("f_aux") <= 0) & ~c8_skip
        self._set_frame(c8_go, "f_sub", _SUB_WAITING)
        c8_drain = c8 & (sub == _SUB_DRAIN)
        if c8_drain.any():
            d_close = c8_drain & close_arr
            self._pop_ret(d_close, self._top("f_dirty"))
            d_skip = c8_drain & ~close_arr
            isopen = (k == jt.START_OBJECT) | (k == jt.START_ARRAY)
            skip_to = np.where(isopen, self.match[rows, np.clip(
                self.tcur, 0, self.T - 1)] + 1, self.tcur + 1)
            self.tcur = np.where(d_skip, skip_to, self.tcur)
            self.tcur = np.where(d_close, self.tcur + 1, self.tcur)

        # ---- 3) ENTER dispatch -------------------------------------------
        enter = is_root | c2_enter | c4_go | c5_enter | c6_enter | c7_enter \
            | c8_go
        # child style / path per source
        e_style = np.full((n,), _RAW, np.int8)
        e_path = np.zeros((n,), np.int32)
        e_style = np.where(c2_enter, _FLATTEN, e_style)
        e_path = np.where(c2_enter, len(self.ptype) - 1, e_path)  # path end
        e_style = np.where(c4_go, style, e_style)
        e_path = np.where(c4_go, fpath + 1, e_path)
        e_style = np.where(c5_enter, _FLATTEN, e_style)
        e_path = np.where(c5_enter, fpath, e_path)  # stored as idx+2 at push
        e_style = np.where(c6_enter, style, e_style)  # stored child style
        e_path = np.where(c6_enter, fpath, e_path)    # stored idx+1
        e_style = np.where(c7_enter, _QUOTED, e_style)
        e_path = np.where(c7_enter, fpath, e_path)    # stored idx+1
        c8_enter = c8_go
        wc8 = self._top("f_flag")
        e_style = np.where(c8_enter, np.where(wc8, _QUOTED, style), e_style)
        e_path = np.where(c8_enter, fpath, e_path)    # stored idx+1
        if enter.any():
            self._enter(enter, e_style, e_path, k, seg, s)

        self.segs.append((self.rowmap, seg))

    def _pop_ret(self, mask, dirty):
        if not mask.any():
            return
        self.ret_valid |= mask
        self.ret_dirty = np.where(mask, dirty, self.ret_dirty)
        self.fp = np.where(mask, self.fp - 1, self.fp)

    def _push(self, mask, case, style, path, aux=0, flag=False):
        if not mask.any():
            return
        self.fp = np.where(mask, self.fp + 1, self.fp)
        over = mask & (self.fp >= self.F)
        self.err |= over
        self.fp = np.where(over, self.F - 1, self.fp)
        m = mask & ~over
        self._set_frame(m, "f_case", case)
        self._set_frame(m, "f_style", style if isinstance(style, np.ndarray)
                        else np.full(self.n, style, np.int8))
        self._set_frame(m, "f_path", path if isinstance(path, np.ndarray)
                        else np.full(self.n, path, np.int32))
        self._set_frame(m, "f_dirty", np.zeros(self.n, np.int64))
        self._set_frame(m, "f_sub", _SUB_NONE)
        self._set_frame(m, "f_aux", aux if isinstance(aux, np.ndarray)
                        else np.full(self.n, aux, np.int64))
        self._set_frame(m, "f_flag", flag if isinstance(flag, np.ndarray)
                        else np.full(self.n, flag, bool))

    def _enter(self, mask, style, path_idx, k, seg, s):
        """evaluate_path dispatch at the current token (cases as numbered)."""
        n = self.n
        rows = self._rows
        pt = self.ptype[np.clip(path_idx, 0, len(self.ptype) - 1)]
        ptn = self.ptype[np.clip(path_idx + 1, 0, len(self.ptype) - 1)]
        path_end = pt == _P_END
        is_str = k == jt.VALUE_STRING
        is_arr = k == jt.START_ARRAY
        is_obj = k == jt.START_OBJECT
        tclip = np.clip(self.tcur, 0, self.T - 1)

        need_comma = (self._gen_top("g_depth") > 0) & ~self._gen_top("g_empty")

        c1 = mask & is_str & path_end & (style == _RAW)
        c2 = mask & is_arr & path_end & (style == _FLATTEN) & ~c1
        c3 = mask & path_end & ~c1 & ~c2
        rest = mask & ~path_end
        c4 = rest & is_obj & (pt == NAMED)
        c5 = rest & is_arr & (pt == WILDCARD) & (ptn == WILDCARD)
        c6 = rest & is_arr & (pt == WILDCARD) & (style != _QUOTED) & ~c5
        c7 = rest & is_arr & (pt == WILDCARD) & ~c5 & ~c6
        c8 = rest & is_arr & (pt == INDEX)
        c12 = rest & ~c4 & ~c5 & ~c6 & ~c7 & ~c8

        # case 1: raw string leaf
        if c1.any():
            seg[:, 1, 0] = np.where(c1, _SEG_RAW_TOK, seg[:, 1, 0])
            seg[:, 1, 1] = np.where(c1, self.tcur, seg[:, 1, 1])
            wrote = c1 & (self._gen_top("g_depth") > 0)
            self._set_gen(wrote, "g_empty", False)
            self.ret_valid |= c1
            self.ret_dirty = np.where(c1, 1, self.ret_dirty)
            self.tcur = np.where(c1, self.tcur + 1, self.tcur)

        # case 2: flatten into array
        self._push(c2, _F_CASE2, _FLATTEN, len(self.ptype) - 1)
        self.tcur = np.where(c2, self.tcur + 1, self.tcur)

        # case 3: copy current structure (escaped)
        if c3.any():
            badk = np.isin(k, (jt.FIELD_NAME, jt.END_OBJECT, jt.END_ARRAY,
                               jt.ERRORTOK, jt.PAD))
            self.err |= c3 & badk
            ok3 = c3 & ~badk
            seg[:, 0, 0] = np.where(ok3 & need_comma, _SEG_CONST, seg[:, 0, 0])
            seg[:, 0, 1] = np.where(ok3 & need_comma, _C_COMMA, seg[:, 0, 1])
            seg[:, 1, 0] = np.where(ok3, _SEG_ESC_TOK, seg[:, 1, 0])
            seg[:, 1, 1] = np.where(ok3, self.tcur, seg[:, 1, 1])
            self._set_gen(ok3 & (self._gen_top("g_depth") > 0), "g_empty", False)
            opn = ok3 & (is_arr | is_obj)
            self._push(opn, _F_COPY, _RAW, 0,
                       aux=self.match[rows, tclip].astype(np.int64))
            scal = ok3 & ~opn
            self.ret_valid |= scal
            self.ret_dirty = np.where(scal, 1, self.ret_dirty)
            self.tcur = np.where(ok3, self.tcur + 1, self.tcur)

        # case 4: object + named
        self._push(c4, _F_CASE4, style, path_idx)
        self.tcur = np.where(c4, self.tcur + 1, self.tcur)

        # case 5: [*][*]
        if c5.any():
            seg[:, 0, 0] = np.where(c5 & need_comma, _SEG_CONST, seg[:, 0, 0])
            seg[:, 0, 1] = np.where(c5 & need_comma, _C_COMMA, seg[:, 0, 1])
            seg[:, 1, 0] = np.where(c5, _SEG_CONST, seg[:, 1, 0])
            seg[:, 1, 1] = np.where(c5, _C_OPEN_ARR, seg[:, 1, 1])
            self._set_gen(c5, "g_depth", self._gen_top("g_depth") + 1)
            self._set_gen(c5, "g_empty", True)
            self._push(c5, _F_CASE5, style, path_idx + 2)
            self.tcur = np.where(c5, self.tcur + 1, self.tcur)

        # case 6: wildcard with child generator + deferred wrapping
        if c6.any():
            child_style = np.where(style == _RAW, _QUOTED, _FLATTEN).astype(np.int8)
            self._push(c6, _F_CASE6, child_style, path_idx + 1,
                       aux=np.full(n, s, np.int64), flag=need_comma)
            # push child generator
            self.gp = np.where(c6, self.gp + 1, self.gp)
            overg = c6 & (self.gp >= self.G)
            self.err |= overg
            self.gp = np.where(overg, self.G - 1, self.gp)
            self._set_gen(c6, "g_depth", 1)
            self._set_gen(c6, "g_empty", True)
            seg[:, 0, 0] = np.where(c6, _SEG_COND_OPEN, seg[:, 0, 0])
            seg[:, 0, 1] = np.where(c6, s, seg[:, 0, 1])
            self.tcur = np.where(c6, self.tcur + 1, self.tcur)

        # case 7: wildcard, quoted
        if c7.any():
            seg[:, 0, 0] = np.where(c7 & need_comma, _SEG_CONST, seg[:, 0, 0])
            seg[:, 0, 1] = np.where(c7 & need_comma, _C_COMMA, seg[:, 0, 1])
            seg[:, 1, 0] = np.where(c7, _SEG_CONST, seg[:, 1, 0])
            seg[:, 1, 1] = np.where(c7, _C_OPEN_ARR, seg[:, 1, 1])
            self._set_gen(c7, "g_depth", self._gen_top("g_depth") + 1)
            self._set_gen(c7, "g_empty", True)
            self._push(c7, _F_CASE7, style, path_idx + 1)
            self.tcur = np.where(c7, self.tcur + 1, self.tcur)

        # cases 8/9: index (+optional wildcard)
        if c8.any():
            idxv = self.parg[np.clip(path_idx, 0, len(self.parg) - 1)]
            self._push(c8, _F_CASE8, style, path_idx + 1,
                       aux=idxv, flag=(ptn == WILDCARD))
            self.tcur = np.where(c8, self.tcur + 1, self.tcur)

        # case 12: skip children, dirty 0
        if c12.any():
            isopen = is_arr | is_obj
            skip_to = np.where(isopen, self.match[rows, tclip] + 1,
                               self.tcur + 1)
            self.tcur = np.where(c12, skip_to, self.tcur)
            self.ret_valid |= c12
            self.ret_dirty = np.where(c12, 0, self.ret_dirty)


def _render(bi: _ByteInfo, stype, sarg, err, kind, start, end, len_raw,
            len_esc, neg0, ftext, flen, fidx):
    """Lay out the (already resolved) segment tables, materialize bytes."""
    n, T = kind.shape
    S2 = stype.shape[1]

    rows = np.arange(n)[:, None]
    targ = np.clip(sarg, 0, T - 1)
    slen = np.zeros((n, S2), np.int64)
    slen = np.where(stype == _SEG_CONST,
                    _CONST_LEN[np.clip(sarg, 0, len(_CONSTS) - 1)], slen)
    slen = np.where(stype == _SEG_RAW_TOK, len_raw[rows, targ], slen)
    slen = np.where(stype == _SEG_ESC_TOK, len_esc[rows, targ], slen)
    # RAW/ESC of non-string kinds resolve through the same tables; int/float
    # tokens appear as RAW/ESC too (copy) — map them:
    is_float_tok = kind[rows, targ] == jt.VALUE_NUMBER_FLOAT
    tok_ref = (stype == _SEG_RAW_TOK) | (stype == _SEG_ESC_TOK)
    f_sel = tok_ref & is_float_tok
    fi = np.clip(fidx[rows, targ], 0, max(len(flen) - 1, 0))
    if len(flen):
        slen = np.where(f_sel, flen[fi], slen)

    segcum = np.cumsum(slen, axis=1)  # inclusive
    out_len = segcum[:, -1]
    # nulled rows emit nothing
    out_len = np.where(err, 0, out_len)
    W = max(int(out_len.max()), 1)

    j = np.broadcast_to(np.arange(W, dtype=np.int64)[None, :], (n, W))
    si = _batched_searchsorted_right(segcum, j)  # segment of each out byte
    si = np.minimum(si, S2 - 1)
    prev = np.where(si > 0, segcum[rows, np.maximum(si - 1, 0)], 0)
    d = j - prev  # offset within segment
    st = stype[rows, si]
    sa = sarg[rows, si]
    ta = np.clip(sa, 0, T - 1)
    tk = kind[rows, ta]
    ts = start[rows, ta].astype(np.int64)
    te = end[rows, ta].astype(np.int64)
    L = bi.b.shape[1]

    out = np.zeros((n, W), np.uint8)
    # consts
    cm = st == _SEG_CONST
    out = np.where(cm, _CONST_TAB[np.clip(sa, 0, len(_CONSTS) - 1),
                                  np.clip(d, 0, _CONST_MAXLEN - 1)], out)
    # token text
    is_str = (tk == jt.VALUE_STRING) | (tk == jt.FIELD_NAME)
    is_int = tk == jt.VALUE_NUMBER_INT
    is_float = tk == jt.VALUE_NUMBER_FLOAT
    one_char = np.isin(tk, (jt.START_OBJECT, jt.END_OBJECT, jt.START_ARRAY,
                            jt.END_ARRAY))
    lit = np.isin(tk, (jt.VALUE_TRUE, jt.VALUE_FALSE, jt.VALUE_NULL))
    tokm = (st == _SEG_RAW_TOK) | (st == _SEG_ESC_TOK)
    escm = st == _SEG_ESC_TOK

    # ints: raw copy (or "0" for -0)
    im = tokm & is_int
    n0 = neg0[rows, ta]
    int_byte = bi.b[rows, np.clip(ts + d, 0, L - 1)]
    out = np.where(im, np.where(n0, ord("0"), int_byte), out)
    # structural single chars + literals: copy from source span directly
    sm = tokm & (one_char | lit)
    out = np.where(sm, bi.b[rows, np.clip(ts + d, 0, L - 1)], out)
    # floats
    if len(flen):
        fm = tokm & is_float
        fi2 = np.clip(fidx[rows, ta], 0, len(flen) - 1)
        out = np.where(fm, ftext[fi2, np.clip(d, 0, ftext.shape[1] - 1)], out)
    # strings
    strm = tokm & is_str
    if strm.any():
        ps = np.minimum(ts + 1, L)
        # raw (unescape) variant
        rm = strm & ~escm
        base_u = bi.cum_u[rows, ps]
        tgt = base_u + d
        siU = np.minimum(_batched_searchsorted_right(bi.cum_u[:, 1:], tgt), L - 1)
        kU = tgt - bi.cum_u[rows, siU]
        rbyte = _emission_byte(bi, rows * np.ones_like(siU), siU, kU, False)
        out = np.where(rm, rbyte, out)
        # escaped variant: quote + payload + quote
        em = strm & escm
        elen = len_esc[rows, ta]
        quote = (d == 0) | (d == elen - 1)
        base_e = bi.cum_e[rows, ps]
        tgt = base_e + (d - 1)
        siE = np.minimum(_batched_searchsorted_right(bi.cum_e[:, 1:],
                                                     np.maximum(tgt, 0)), L - 1)
        kE = np.maximum(tgt, 0) - bi.cum_e[rows, siE]
        ebyte = _emission_byte(bi, rows * np.ones_like(siE), siE, kE, True)
        out = np.where(em, np.where(quote, ord('"'), ebyte), out)

    in_bounds = j < out_len[:, None]
    out = np.where(in_bounds, out, 0)
    return out, out_len


def _get_json_object_device(col: StringColumn, parts: Sequence[tuple]
                            ) -> List[StringColumn]:
    """Fully device-resident evaluation: tokenize, byte tables, name match,
    lax.scan machine, and segment rendering all run jitted.  Only three
    scalars per bucket ever reach the host (float count, float source
    width, output width — plus the step-cap truncation count, which rides
    the first pull for free), each pow2-padded so the compile-variant set
    stays bounded — and those syncs are *batched across buckets*: every
    bucket's phase-1 program is issued before the first scalar pull, so
    one tunnel round-trip (~70 ms on axon) serves a whole group of buckets
    instead of serializing 3 syncs x buckets with the device.  Groups are
    capped by ``json_overlap_bytes`` of padded input so holding several
    buckets' token tables concurrently cannot blow HBM.

    ``parts``: [(ptypes, pargs, names), ...] — one entry per path.  All
    paths share one tokenization, byte-table build and float re-render per
    bucket, and name-match tables are computed once per *unique* name
    across paths; only the scan machine and the render fan out per path
    (the reference's getJsonObjectMultiplePaths amortizes the same way).
    Parity: the single-kernel residency of get_json_object.cu:891.
    """
    from spark_rapids_jni_tpu.ops import json_render_device as jrd
    from spark_rapids_jni_tpu.ops.json_scan import _run_scan

    n = col.size
    P = len(parts)
    in_valid = col.is_valid()
    path_consts = []
    for ptypes, pargs, _names in parts:
        ptype_j = jnp.asarray(list(ptypes) + [_P_END], np.int32)
        parg_j = jnp.asarray(
            [a if isinstance(a, int) else 0 for a in pargs] + [0], np.int32)
        path_consts.append((ptype_j, parg_j, len(ptypes) + 1))
    # unique names across every path's levels (None levels share one zeros
    # table per bucket)
    uniq_names: List[bytes] = []
    name_slot = {}
    for _pt, _pa, names in parts:
        for nm in names:
            if nm is not None and nm not in name_slot:
                name_slot[nm] = len(uniq_names)
                uniq_names.append(nm)

    # group buckets so phase intermediates stay bounded (~10-15x the padded
    # input bytes live at once within a group, once per path)
    group_budget = max(int(config.get("json_overlap_bytes")), 1)
    groups, cur, cur_bytes = [], [], 0
    for b in padded_buckets(col):
        bbytes = int(b.bytes.shape[0]) * int(b.bytes.shape[1]) * max(P, 1)
        if cur and cur_bytes + bbytes > group_budget:
            groups.append(cur)
            cur, cur_bytes = [], 0
        cur.append(b)
        cur_bytes += bbytes
    if cur:
        groups.append(cur)

    results: List[list] = [[] for _ in range(P)]
    valid_out = [jnp.zeros((n,), bool) for _ in range(P)]
    for group in groups:
        # ---- phase 1 (no sync): tokenize + scans + float-geometry scalars
        # tokenize and evaluate are sibling phases (never nested), so the
        # bench's phases_s sub-timings partition the stage total on both
        # pipelines; issue time is exact, async device work lands on the
        # phase whose sync point pulls it (the evaluate-phase geom pull)
        ph1 = []
        for b in group:
            with _phase("tokenize"):
                ts = jt.tokenize(b.bytes, b.lengths)
                nr = b.n_rows
                kind = ts.kind.astype(jnp.int32)
                start, end = ts.start, ts.end
                ntok = ts.n_tokens.astype(jnp.int32)
                T = kind.shape[1]

                # reuse the tokenizer's automaton product (state AFTER
                # each byte -> state BEFORE each byte)
                st_before = jnp.pad(
                    ts.str_state, ((0, 0), (1, 0)))[:, : b.bytes.shape[1]]
                bi = jrd.byte_info_device(b.bytes, b.lengths, st_before)
            with _phase("evaluate"):
                len_raw, len_esc, has_uni, neg0 = jrd.token_tables_device(
                    bi, kind, start, end)
                nm_uniq = jrd.name_matches_device(
                    bi, kind, start, len_raw, has_uni, end, uniq_names)
                zeros_nt = jnp.zeros((nr, T), bool)

                F = min(jt.MAX_DEPTH + MAX_PATH_DEPTH + 6, T + 3)
                G = min(MAX_PATH_DEPTH + 2, F)
                per_path = []
                trunc_dev = jnp.int32(0)
                for (ptype_j, parg_j, P1), (_pt, _pa, names) in zip(
                        path_consts, parts):
                    nm = [zeros_nt if nm_ is None else nm_uniq[name_slot[nm_]]
                          for nm_ in names]
                    nm_stack = jnp.concatenate(
                        [jnp.stack(nm) if nm else jnp.zeros((0, nr, T), bool),
                         jnp.zeros((P1 - len(nm), nr, T), bool)])
                    err_s, done, dirty_root, (segs, cg, cd, cn) = _run_scan(
                        kind, ts.match, ntok, ts.ok, nm_stack, ptype_j,
                        parg_j, T, F, G)
                    trunc_dev = trunc_dev + jnp.sum(
                        ~done & ~err_s, dtype=jnp.int32)
                    err = err_s | ~done | (dirty_root <= 0)
                    err = err | ~in_valid[b.rows]
                    err = err | ~b.valid_mask()  # pow2-padding tail rows
                    per_path.append(dict(err=err, segs=(segs, cg, cd, cn)))

                fmask = kind == jt.VALUE_NUMBER_FLOAT
                if fmask.size:
                    nf_dev = jnp.sum(fmask, dtype=jnp.int32)
                    ws_dev = jnp.max(
                        jnp.where(fmask, end - start, 0)).astype(jnp.int32)
                else:
                    nf_dev = ws_dev = jnp.int32(0)
                ph1.append(dict(
                    b=b, bi=bi, kind=kind, start=start, end=end,
                    paths=per_path, len_raw=len_raw, len_esc=len_esc,
                    neg0=neg0, nf=nf_dev, ws=ws_dev, trunc=trunc_dev))

        with _phase("evaluate"):
            # one batched sync: every bucket's (nf, ws, trunc) in one pull
            geom = np.asarray(jnp.stack(
                [jnp.stack([p["nf"], p["ws"], p["trunc"]]) for p in ph1]))
            _note_truncation(int(geom[:, 2].sum()))

        # ---- phase 2 (no sync): float slots + measure + out-width scalar
        with _phase("render"):
            for p, (nf_total, ws, _tr) in zip(ph1, geom):
                b, kind = p["b"], p["kind"]
                nr = b.n_rows
                if nf_total:
                    NF = next_pow2(int(nf_total))
                    WS = next_pow2(max(int(ws), 1))
                    ftext, flen, fidx = jrd.float_texts_device(
                        b.bytes, kind, p["start"], p["end"], NF, WS)
                else:
                    ftext = jnp.zeros((0, 1), jnp.uint8)
                    flen = jnp.zeros((0,), jnp.int64)
                    fidx = jnp.full((nr, kind.shape[1]), -1, jnp.int64)
                p["floats"] = (ftext, flen, fidx)

                for pp in p["paths"]:
                    segs, cg, cd, cn = pp["segs"]
                    stype, sarg, segcum, out_len = jrd.resolve_and_measure(
                        segs, cg, cd, cn, pp["err"], kind, p["len_raw"],
                        p["len_esc"], fidx, flen)
                    pp.update(stype=stype, sarg=sarg, segcum=segcum,
                              out_len=out_len,
                              wmax=jnp.max(out_len).astype(jnp.int32))

            # second batched sync: all (bucket, path) output widths at once
            wmaxes = np.asarray(jnp.stack(
                [pp["wmax"] for p in ph1 for pp in p["paths"]]))

            # ---- phase 3: render (width now static per bucket and path)
            wi = 0
            for p in ph1:
                b = p["b"]
                nv = b.n_valid
                tgt = jnp.where(b.valid_mask(), b.rows, jnp.int32(n))
                for pi, pp in enumerate(p["paths"]):
                    W = next_pow2(max(int(wmaxes[wi]), 1))
                    wi += 1
                    padded = jrd.render_device(
                        p["bi"], pp["stype"], pp["sarg"], pp["segcum"],
                        pp["out_len"], pp["err"], p["kind"], p["start"],
                        p["end"], (p["len_raw"], p["len_esc"], p["neg0"]),
                        p["floats"], W)
                    valid_out[pi] = valid_out[pi].at[tgt].set(
                        ~pp["err"], mode="drop")
                    results[pi].append(
                        (b.rows[:nv], padded[:nv],
                         pp["out_len"][:nv].astype(jnp.int32), nv))

    return [strings_from_buckets(n, results[pi], valid_out[pi])
            for pi in range(P)]


def _get_json_object_host(col: StringColumn, parts: Sequence[tuple]
                          ) -> List[StringColumn]:
    """Host numpy pipeline: tokenize on device, evaluate + render on host.

    One tokenization, byte-table build, float re-render and (unique-)name
    match per bucket is shared by every path; rows are split into
    token-count sub-buckets (``json_subbucket_min_rows``) so a machine's
    step cap tracks its own rows' token counts, and each machine compacts
    to its active rows as they finish (``json_compact``).
    """
    n = col.size
    P = len(parts)
    in_valid = np.asarray(col.is_valid())
    compact = bool(config.get("json_compact"))
    sub_min = int(config.get("json_subbucket_min_rows"))
    margin = int(config.get("json_step_margin"))

    results: List[list] = [[] for _ in range(P)]
    valid_out = [np.zeros((n,), bool) for _ in range(P)]
    n_trunc = 0
    for b in padded_buckets(col):
        with _phase("tokenize"):
            ts = jt.tokenize(b.bytes, b.lengths)
            # one device->host transfer per token array; host paths slice
            nv = b.n_valid
            kind = np.asarray(ts.kind).astype(np.int32)[:nv]
            start = np.asarray(ts.start)[:nv]
            end = np.asarray(ts.end)[:nv]
            match = np.asarray(ts.match)[:nv]
            ntok = np.asarray(ts.n_tokens).astype(np.int64)[:nv]
            ok = np.asarray(ts.ok)[:nv]
            rows_np = np.asarray(b.rows)[:nv]
            bi = _byte_info(b.bytes, b.lengths, n_valid=nv,
                            str_state=ts.str_state)

        with _phase("evaluate"):
            len_raw, len_esc, has_uni, neg0 = _token_tables(
                bi, kind, start, end)
            nm_cache: dict = {}
            nm_paths = [
                _name_matches(bi, kind, start, end, names, len_raw, has_uni,
                              cache=nm_cache)
                for _pt, _pa, names in parts
            ]
        T = kind.shape[1]
        has_float = bool((kind == jt.VALUE_NUMBER_FLOAT).any())
        used_float = (np.zeros((nv, T), bool) if has_float else None)
        pending = []
        for sel, Tcap in count_subbuckets(ntok, T, min_rows=sub_min):
            whole = len(sel) == nv and Tcap == T
            if whole:
                kind_s, start_s, end_s, match_s = kind, start, end, match
                ntok_s, ok_s, bi_s, rows_s = ntok, ok, bi, rows_np
                lr_s, le_s, n0_s = len_raw, len_esc, neg0
            else:
                kind_s = kind[sel][:, :Tcap]
                start_s = start[sel][:, :Tcap]
                end_s = end[sel][:, :Tcap]
                match_s = match[sel][:, :Tcap]
                ntok_s, ok_s = ntok[sel], ok[sel]
                bi_s = _slice_byte_info(bi, sel)
                rows_s = rows_np[sel]
                lr_s = len_raw[sel][:, :Tcap]
                le_s = len_esc[sel][:, :Tcap]
                n0_s = neg0[sel][:, :Tcap]
            for pi, ((ptypes, pargs, _names), nm) in enumerate(
                    zip(parts, nm_paths)):
                with _phase("evaluate"):
                    nm_s = nm if whole else [t[sel][:, :Tcap] for t in nm]
                    m = _Machine(kind_s, match_s, ntok_s, ok_s, ptypes,
                                 pargs, nm_s, compact=compact,
                                 step_margin=margin)
                    n_trunc += m.run()
                    stype, sarg = m.segment_tables()
                    err = (m.err_out | (m.dirty_out <= 0)
                           | ~in_valid[rows_s])
                    if has_float:
                        # note float tokens this path actually emits, so
                        # the Ryu re-render below runs on just those
                        ref = (stype == _SEG_RAW_TOK) | \
                            (stype == _SEG_ESC_TOK)
                        ri2, si2 = np.nonzero(ref)
                        ta2 = np.clip(sarg[ri2, si2], 0, Tcap - 1)
                        fref = kind_s[ri2, ta2] == jt.VALUE_NUMBER_FLOAT
                        used_float[sel[ri2[fref]], ta2[fref]] = True
                pending.append((pi, sel, Tcap, whole, stype, sarg, err,
                                bi_s, kind_s, start_s, end_s, lr_s, le_s,
                                n0_s, rows_s))

        with _phase("render"):
            ftext, flen, fidx = _float_texts(bi, kind, start, end,
                                             used=used_float)
            for (pi, sel, Tcap, whole, stype, sarg, err, bi_s, kind_s,
                 start_s, end_s, lr_s, le_s, n0_s, rows_s) in pending:
                fidx_s = fidx if whole else fidx[sel][:, :Tcap]
                padded, out_len = _render(
                    bi_s, stype, sarg, err, kind_s, start_s, end_s,
                    lr_s, le_s, n0_s, ftext, flen, fidx_s)
                valid_out[pi][rows_s] = ~err
                out_len = np.where(~err, out_len, 0)
                results[pi].append(
                    (jnp.asarray(rows_s), jnp.asarray(padded),
                     jnp.asarray(out_len.astype(np.int32)),
                     len(rows_s)))

    _note_truncation(n_trunc)
    return [strings_from_buckets(n, results[pi], jnp.asarray(valid_out[pi]))
            for pi in range(P)]


def _device_render_enabled() -> bool:
    v = config.get("json_device_render")
    if v == "auto":
        # device rendering keeps bytes resident where that wins (an
        # accelerator behind a tunnel); on XLA:CPU "device" and host are
        # the same silicon and the adaptive numpy machine (early exit,
        # compaction, sub-buckets) beats the fixed 2T+40-step compiled scan
        return jax.default_backend() != "cpu"
    return bool(v)


def _path_parts(path) -> tuple:
    if isinstance(path, str):
        path = parse_path(path)
    path = list(path)
    if len(path) > MAX_PATH_DEPTH:
        # get_json_object.cu:958 CUDF_FAIL("JSONPath query exceeds maximum depth")
        raise ValueError("JSONPath query exceeds maximum depth")
    ptypes = [p[0] for p in path]
    pargs = [p[1] if len(p) > 1 else 0 for p in path]
    names = [p[1] if p[0] == NAMED else None for p in path]
    return ptypes, pargs, names


def get_json_object_multiple_paths(
        col: StringColumn, paths: Sequence) -> List[StringColumn]:
    """Evaluate several JSON paths against ONE tokenization of ``col``.

    The reference ships ``JSONUtils.getJsonObjectMultiplePaths`` precisely
    because tokenization dominates: parsing the column once and fanning the
    token stream out to P path machines makes P paths cost far less than P
    separate calls (shared: tokenize, byte/escape tables, float re-render,
    and per-unique-name match tables).

    ``paths``: sequence of path strings or instruction-tuple lists.
    Returns one StringColumn per path, in order.
    """
    parts = [_path_parts(p) for p in paths]
    if not parts:
        return []
    n = col.size
    if n == 0:
        return [
            StringColumn(
                jnp.zeros((0,), jnp.uint8), jnp.zeros((1,), jnp.int32), None)
            for _ in parts
        ]
    if _device_render_enabled():
        return _get_json_object_device(col, parts)
    return _get_json_object_host(col, parts)


def get_json_object(col: StringColumn, path: Sequence[tuple]) -> StringColumn:
    """Evaluate a JSON path over every row (Spark ``get_json_object``).

    ``path``: instruction tuples — ``(NAMED, bytes)``, ``(INDEX, int)``,
    ``(WILDCARD,)`` — or a ``$.a[0].*`` string (parsed via parse_path).
    Returns a string column; unmatched/malformed/null rows are null.
    """
    return get_json_object_multiple_paths(col, [path])[0]
