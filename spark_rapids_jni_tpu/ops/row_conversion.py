"""JCUDF row format <-> columns (Spark's row-major interchange format).

Byte-compatible with the reference's row_conversion.cu (the largest kernel
file there, 2515 LoC): convert_to_rows :1990 / convert_from_rows :2028 and the
fixed-width-optimized legacy pair :306/:425.

Row layout (RowConversion.java:44-117 doc, compute_column_information
row_conversion.cu:1323-1362):
- columns in order, each aligned to its own byte width (C-struct style);
  a string column occupies an aligned 8-byte (offset:uint32, length:uint32)
  pair pointing at char data appended after the fixed section;
- validity bits follow the last column, byte-aligned, one bit per column,
  LSB-first within each byte, 1 == valid;
- string char data (in column order) follows validity, starting at
  ``size_per_row`` exactly (no alignment, copy_strings_to_rows :837);
- every row is padded to an 8-byte boundary (JCUDF_ROW_ALIGNMENT);
- output is split into batches of at most ``max_batch_bytes`` (2GB in the
  reference), batch boundaries rounded down to 32 rows (build_batches :1505).

TPU re-architecture: the reference stages tiles through shared memory with
cooperative groups + cuda::barrier.  None of that maps to XLA; instead each
direction is a handful of dense gathers/scatters over a [rows, row_size] byte
matrix (fixed part) plus one ragged scatter/gather for string chars — shapes
are static per schema, so XLA fuses the whole conversion into a few kernels.
Values are exploded to little-endian bytes with shifts, never 64-bit bitcasts
(unimplemented in the TPU x64 rewrite).

Round 20 (straggler kill): the (src, dst) byte permutation between the
column byte lanes and the row layout depends only on the schema, so it is
computed once per schema and cached in the process-global plan cache keyed
on (schema signature, pow2 row bucket).  Execution is then a single fused
permutation gather over the lane matrix (plus the one ragged string pass),
on either arm:

- host arm (CPU backend, default there): numpy byte *views* of the column
  buffers — no shift-exploding — permuted in one fancy-index op;
- device arm: the per-column ``.at[].set`` scatter chain collapses to one
  ``jnp.take`` along the cached permutation.

The pre-round-20 per-column scatter/gather chain is retained verbatim as
the parity oracle behind ``rows_plan_cache=False``; arm selection follows
``rows_device_path`` ("auto" == device iff the default backend is not CPU).
"""

from __future__ import annotations

import time
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu import config
from spark_rapids_jni_tpu.columnar.column import (
    Column,
    Decimal128Column,
    ListColumn,
    StringColumn,
    next_pow2,
    strings_from_padded,
)
from spark_rapids_jni_tpu.columnar.dtypes import DType, Kind, UINT8
from spark_rapids_jni_tpu.obs.phases import PhaseTimes
from spark_rapids_jni_tpu.plans.cache import CompiledPlan, plan_cache
from spark_rapids_jni_tpu.utils.floatbits import bits_to_f32, f32_to_bits

JCUDF_ROW_ALIGNMENT = 8
MAX_BATCH_SIZE = (1 << 31) - 1

# Sub-timings across both directions: plan (permutation lookup/build),
# lanes (byte-lane construction / decode), gather (the fused permutation),
# emit (batch split + ragged string pass).  Host arm: wall-clock host work;
# device arm: dispatch time only (XLA is async).
PHASES = PhaseTimes("plan", "lanes", "gather", "emit")


def _round_up(x: int, align: int) -> int:
    return (x + align - 1) // align * align


def compute_layout(dtypes: Sequence[DType]):
    """(col_starts, col_sizes, validity_offset, size_per_row) per
    compute_column_information (row_conversion.cu:1323-1362)."""
    starts, sizes = [], []
    at = 0
    for dt in dtypes:
        if dt.kind == Kind.STRING:
            size, align = 8, 4  # uint32 offset + uint32 length pair
        else:
            size = dt.fixed_width
            if size == 0:
                raise TypeError(f"Unsupported type in JCUDF row conversion: {dt}")
            align = size
        at = _round_up(at, align)
        starts.append(at)
        sizes.append(size)
        at += size
    validity_offset = at
    size_per_row = at + (len(dtypes) + 7) // 8
    return starts, sizes, validity_offset, size_per_row


# ---------------------------------------------------------------------------
# cached byte-permutation plans (round 20)
# ---------------------------------------------------------------------------


def _rows_device_enabled() -> bool:
    v = config.get("rows_device_path")
    if v == "auto":
        return jax.default_backend() != "cpu"
    return bool(v)


def _row_plan_sig(dtypes: Sequence[DType]):
    """Layout-determining schema signature: byte width per column, -1 for
    the variable-width (string) pair slot."""
    return tuple(
        -1 if dt.kind == Kind.STRING else dt.fixed_width for dt in dtypes
    )


def _build_row_plan(sig) -> dict:
    """Precompute the lane->row byte permutation for one schema.

    The lane matrix is the per-column little-endian value bytes concatenated
    in column order (a string column contributes its 8 pair bytes), followed
    by the validity bytes.  ``perm[j]`` is the lane feeding row byte ``j``;
    ``keep[j]`` is 0 on alignment gaps and row padding (forced to zero, so
    gap bytes match the reference's zero-filled rows bit-exactly).
    """
    starts, sizes = [], []
    at = 0
    for w in sig:
        size, align = (8, 4) if w < 0 else (w, w)
        at = _round_up(at, align)
        starts.append(at)
        sizes.append(size)
        at += size
    validity_offset = at
    nbytes = (len(sig) + 7) // 8
    size_per_row = validity_offset + nbytes
    fixed_row = _round_up(size_per_row, JCUDF_ROW_ALIGNMENT)
    perm = np.zeros((fixed_row,), np.int64)
    keep = np.zeros((fixed_row,), np.uint8)
    lane = 0
    for start, size in zip(starts, sizes):
        perm[start : start + size] = np.arange(lane, lane + size)
        keep[start : start + size] = 1
        lane += size
    perm[validity_offset:size_per_row] = np.arange(lane, lane + nbytes)
    keep[validity_offset:size_per_row] = 1
    lane += nbytes
    return {
        "starts": starts,
        "sizes": sizes,
        "validity_offset": validity_offset,
        "size_per_row": size_per_row,
        "fixed_row": fixed_row,
        "lane_width": lane,
        "perm": perm,
        "keep": keep,
        "perm_dev": jnp.asarray(perm),
        "keep_dev": jnp.asarray(keep),
    }


def _get_row_plan(dtypes: Sequence[DType], n: int) -> dict:
    sig = _row_plan_sig(dtypes)
    key = (("rows_perm", sig), next_pow2(max(int(n), 1)))

    def build() -> CompiledPlan:
        t0 = time.perf_counter()
        plan = _build_row_plan(sig)
        return CompiledPlan(
            fn=plan["perm"],
            plan=plan,
            mesh=None,
            signature=key,
            out_names=("fixed",),
            arg_names=("lanes",),
            aot=False,
            trace_s=time.perf_counter() - t0,
            compile_s=0.0,
        )

    return plan_cache.get_or_compile(key, build).plan


# twin: rows_fixed_gather
def _gather_fixed(lanes, perm, keep):
    fixed = jnp.take(lanes, perm, axis=1) * keep
    return fixed


# twin: rows_fixed_gather
def _gather_fixed_np(lanes, perm, keep):
    fixed = np.take(lanes, perm, axis=1) * keep
    return fixed


def _np_col_lanes(col) -> np.ndarray:
    """[n, w] little-endian value bytes of a fixed-width column, via numpy
    buffer views (host mirror of :func:`_col_le_bytes`; bit-exact because
    the platform is little-endian and FLOAT64 data already carries bits)."""
    n = col.size
    if isinstance(col, Decimal128Column):
        lo = np.ascontiguousarray(np.asarray(col.lo)).astype(np.uint64)
        hi = np.ascontiguousarray(np.asarray(col.hi)).astype(np.int64)
        return np.concatenate(
            [lo.view(np.uint8).reshape(n, 8), hi.view(np.uint8).reshape(n, 8)],
            axis=1,
        )
    kind = col.dtype.kind
    w = col.dtype.fixed_width
    if kind == Kind.FLOAT32:
        v = np.ascontiguousarray(np.asarray(col.data).astype(np.float32))
        return v.view(np.uint8).reshape(n, 4)
    if kind == Kind.BOOL:
        return np.asarray(col.data).astype(np.uint8).reshape(n, 1)
    v = np.asarray(col.data)
    if v.dtype != np.int64 or not v.flags["C_CONTIGUOUS"]:
        v = np.ascontiguousarray(v.astype(np.int64))
    return v.view(np.uint8).reshape(n, 8)[:, :w]


def _np_bytes_to_col(raw: np.ndarray, dt: DType, validity):
    """[n, w] contiguous little-endian bytes -> column (host mirror of
    :func:`_bytes_to_col` via numpy views; same sign-extension results)."""
    if dt.kind == Kind.DECIMAL128:
        lo = raw.view(np.uint64)[:, 0]
        hi = raw.view(np.int64)[:, 1]
        return Decimal128Column(jnp.asarray(hi), jnp.asarray(lo), validity, dt)
    w = dt.fixed_width
    if dt.kind == Kind.BOOL:
        data = raw[:, 0] != 0
    elif dt.kind == Kind.FLOAT32:
        data = raw.view(np.float32)[:, 0]
    elif dt.kind == Kind.FLOAT64:
        data = raw.view(np.int64)[:, 0]  # bit pattern carried as int64
    else:
        signed = raw.view(np.dtype("<i%d" % w))[:, 0]
        data = signed.astype(np.dtype(dt.jnp_dtype))
    return Column(jnp.asarray(data), validity, dt)


# ---------------------------------------------------------------------------
# device byte codecs (shared by the oracle and the device fast arm)
# ---------------------------------------------------------------------------


def _col_le_bytes(col) -> jnp.ndarray:
    """[n, w] little-endian bytes of a column's values (shift-based, no bitcast)."""
    if isinstance(col, Decimal128Column):
        lo = col.lo.astype(jnp.uint64)
        hi = col.hi.astype(jnp.uint64)
        parts = [(lo >> jnp.uint64(8 * k)).astype(jnp.uint8) for k in range(8)]
        parts += [(hi >> jnp.uint64(8 * k)).astype(jnp.uint8) for k in range(8)]
        return jnp.stack(parts, axis=1)
    kind = col.dtype.kind
    w = col.dtype.fixed_width
    if kind == Kind.FLOAT32:
        v = f32_to_bits(col.data).astype(jnp.uint32)
    elif kind == Kind.BOOL:
        v = col.data.astype(jnp.uint8)
    else:
        # FLOAT64 column data is already the int64 bit pattern.
        v = col.data.astype(jnp.int64).astype(jnp.uint64)
    parts = [
        (v >> np.uint64(8 * k)).astype(jnp.uint8) if v.dtype == jnp.uint64
        else (v >> np.uint32(8 * k)).astype(jnp.uint8)
        for k in range(w)
    ]
    return jnp.stack(parts, axis=1)


def _bytes_to_col(raw: jnp.ndarray, dtype: DType, validity):
    """[n, w] little-endian bytes -> column of ``dtype``."""
    if dtype.kind == Kind.DECIMAL128:
        u = raw.astype(jnp.uint64)
        lo = sum(u[:, k] << jnp.uint64(8 * k) for k in range(8))
        hi = sum(u[:, 8 + k] << jnp.uint64(8 * k) for k in range(8))
        return Decimal128Column(hi.astype(jnp.int64), lo.astype(jnp.uint64), validity, dtype)
    w = dtype.fixed_width
    u = raw.astype(jnp.uint64)
    v = sum(u[:, k] << jnp.uint64(8 * k) for k in range(w))
    if dtype.kind == Kind.BOOL:
        data = v != 0
    elif dtype.kind == Kind.FLOAT32:
        data = bits_to_f32(v.astype(jnp.uint32).astype(jnp.int32))
    elif dtype.kind == Kind.FLOAT64:
        data = v.astype(jnp.int64)  # bit pattern carried as int64
    else:
        # sign-extend via the appropriate numpy width then widen
        data = v.astype(jnp.uint64)
        if w < 8:
            shift = jnp.uint64(64 - 8 * w)
            data = ((data << shift).astype(jnp.int64) >> (64 - 8 * w)).astype(jnp.int64)
        else:
            data = data.astype(jnp.int64)
        data = data.astype(dtype.jnp_dtype)
    return Column(data, validity, dtype)


def _validity_bytes(columns) -> jnp.ndarray:
    """[n, ceil(ncols/8)] JCUDF validity bytes (bit c%8 of byte c//8, 1=valid)."""
    n = columns[0].size
    nbytes = (len(columns) + 7) // 8
    # analyze: ignore[governed-allocation] - JCUDF row codec not
    # yet wired into a governed pipeline (oracle/parity callers);
    # debt tracked at the site (round 16 baseline burn-down)
    out = jnp.zeros((n, nbytes), jnp.uint8)
    for c, col in enumerate(columns):
        bit = col.is_valid().astype(jnp.uint8) << np.uint8(c % 8)
        out = out.at[:, c // 8].add(bit)
    return out


def _batch_boundaries(row_sizes: np.ndarray, max_batch_bytes: int) -> List[int]:
    """Batch ends per build_batches (row_conversion.cu:1458-1545): lower_bound
    on the running total, rounded down to 32 rows except for the final batch."""
    n = len(row_sizes)
    if n and int(row_sizes.max()) > max_batch_bytes:
        raise ValueError("A single row is larger than the maximum batch size")
    bounds = [0]
    cum = np.cumsum(row_sizes, dtype=np.int64)
    last = 0
    while last < n:
        base = cum[last - 1] if last > 0 else 0
        # first absolute index whose cumulative size exceeds the limit, i.e.
        # rows [last, i) fit.  (side='right' keeps an exactly-fitting row in
        # the batch; the reference's lower_bound is degenerate in that
        # never-hit-in-practice equality case.)
        i = int(np.searchsorted(cum, base + max_batch_bytes, side="right"))
        if i >= n:
            end = n
        elif i - last >= 32:
            end = last + (i - last) // 32 * 32
        else:
            # fewer than 32 rows fit: take all of them rather than degrade to
            # 1-row batches (the reference would round down to 0 and hang)
            end = max(i, last + 1)
        bounds.append(end)
        last = end
    return bounds


# ---------------------------------------------------------------------------
# host fast arm
# ---------------------------------------------------------------------------


def _ragged_char_indices(base: np.ndarray, lens: np.ndarray) -> np.ndarray:
    """Flat char positions for per-row runs starting at ``base`` with the
    given lengths: repeat each base over its run and add a per-run ramp."""
    total = int(lens.sum())
    out = np.repeat(base, lens)
    out += np.arange(total, dtype=np.int64) - np.repeat(np.cumsum(lens) - lens, lens)
    return out


def _convert_to_rows_host(
    columns: Sequence, max_batch_bytes: int
) -> List[ListColumn]:
    n = columns[0].size
    dtypes = [c.dtype for c in columns]
    with PHASES.phase("plan"):
        plan = _get_row_plan(dtypes, n)
    size_per_row = plan["size_per_row"]
    fixed_row = plan["fixed_row"]
    string_cols = [c for c in columns if c.dtype.kind == Kind.STRING]

    with PHASES.phase("lanes"):
        lanes_list: List[np.ndarray] = []
        str_lens: List[np.ndarray] = []
        str_starts: List[np.ndarray] = []
        within = (
            np.full((n,), size_per_row, dtype=np.int64) if string_cols else None
        )
        for col in columns:
            if col.dtype.kind == Kind.STRING:
                lens = np.asarray(col.lengths()).astype(np.int64)
                str_lens.append(lens)
                str_starts.append(within)
                pair = np.empty((n, 2), np.uint32)
                pair[:, 0] = within
                pair[:, 1] = lens
                lanes_list.append(pair.view(np.uint8))
                within = within + lens
            else:
                lanes_list.append(_np_col_lanes(col))
        vbytes = np.zeros((n, (len(columns) + 7) // 8), np.uint8)
        for c, col in enumerate(columns):
            valid = np.asarray(col.is_valid()).astype(np.uint8)
            vbytes[:, c // 8] |= valid << np.uint8(c % 8)
        lanes = np.concatenate(lanes_list + [vbytes], axis=1)

    with PHASES.phase("gather"):
        fixed = _gather_fixed_np(lanes, plan["perm"], plan["keep"])

    with PHASES.phase("emit"):
        if string_cols:
            row_sizes = size_per_row + sum(str_lens)
            a = JCUDF_ROW_ALIGNMENT
            row_sizes = (row_sizes + (a - 1)) // a * a
        else:
            row_sizes = np.full((n,), fixed_row, dtype=np.int64)
        bounds = _batch_boundaries(row_sizes, max_batch_bytes)
        cum_sizes = np.concatenate([[0], np.cumsum(row_sizes)])
        chars_np = [np.asarray(c.chars) for c in string_cols]
        soffs_np = [np.asarray(c.offsets) for c in string_cols]
        out: List[ListColumn] = []
        for b0, b1 in zip(bounds[:-1], bounds[1:]):
            offsets_np = (cum_sizes[b0 : b1 + 1] - cum_sizes[b0]).astype(np.int32)
            total = int(offsets_np[-1])
            if not string_cols:
                # uniform fixed_row rows: the permuted matrix IS the batch
                flat = np.ascontiguousarray(fixed[b0:b1]).reshape(-1)
            else:
                row_off = offsets_np[:-1].astype(np.int64)
                flat = np.zeros((total,), np.uint8)
                pos = row_off[:, None] + np.arange(size_per_row, dtype=np.int64)
                flat[pos] = fixed[b0:b1, :size_per_row]
                for lens, sstart, chars, soffs in zip(
                    str_lens, str_starts, chars_np, soffs_np
                ):
                    lsub = lens[b0:b1]
                    tot = int(lsub.sum())
                    if not tot:
                        continue
                    idx = _ragged_char_indices(row_off + sstart[b0:b1], lsub)
                    c0 = int(soffs[b0])
                    flat[idx] = chars[c0 : c0 + tot]
            out.append(
                ListColumn(
                    jnp.asarray(offsets_np),
                    Column(jnp.asarray(flat), None, UINT8),
                    None,
                )
            )
        return out


def _convert_from_rows_host(rows: ListColumn, dtypes: Sequence[DType]) -> List:
    n = rows.size
    with PHASES.phase("plan"):
        plan = _get_row_plan(dtypes, n)
    starts, sizes = plan["starts"], plan["sizes"]
    validity_offset = plan["validity_offset"]
    size_per_row = plan["size_per_row"]
    fixed_row = plan["fixed_row"]
    flat = np.asarray(rows.child.data)
    offs = np.asarray(rows.offsets).astype(np.int64)
    row_off = offs[:-1]

    with PHASES.phase("gather"):
        if flat.size == n * fixed_row and bool(
            (offs == np.arange(n + 1, dtype=np.int64) * fixed_row).all()
        ):
            # uniform rows (fixed-width-only batch): a reshape view, no copy
            fixed = flat.reshape(n, fixed_row)
        else:
            pos = row_off[:, None] + np.arange(size_per_row, dtype=np.int64)
            fixed = flat[np.minimum(pos, max(flat.size - 1, 0))]

    out: List = []
    with PHASES.phase("lanes"):
        for c, (dt, start, size) in enumerate(zip(dtypes, starts, sizes)):
            vb = fixed[:, validity_offset + c // 8]
            validity = jnp.asarray(((vb >> np.uint8(c % 8)) & np.uint8(1)) == 1)
            if dt.kind == Kind.STRING:
                pr = np.ascontiguousarray(fixed[:, start : start + 8]).view(
                    np.uint32
                )
                soff = pr[:, 0].astype(np.int64)
                slen = pr[:, 1].astype(np.int64)
                tot = int(slen.sum())
                if tot:
                    idx = _ragged_char_indices(row_off + soff, slen)
                    chars = flat[np.minimum(idx, flat.size - 1)]
                else:
                    chars = np.zeros((0,), np.uint8)
                soffsets = np.zeros((n + 1,), np.int32)
                soffsets[1:] = np.cumsum(slen)
                out.append(
                    StringColumn(
                        jnp.asarray(chars), jnp.asarray(soffsets), validity
                    )
                )
            else:
                raw = np.ascontiguousarray(fixed[:, start : start + size])
                out.append(_np_bytes_to_col(raw, dt, validity))
    return out


# ---------------------------------------------------------------------------
# device arm (cached single-gather fast path + pre-round-20 oracle)
# ---------------------------------------------------------------------------


def convert_to_rows(
    columns: Sequence, max_batch_bytes: int = MAX_BATCH_SIZE
) -> List[ListColumn]:
    """Table -> list of LIST<UINT8> batches in JCUDF row format."""
    if not columns:
        raise ValueError("The input table must have at least one column.")
    if bool(config.get("rows_plan_cache")) and not _rows_device_enabled():
        return _convert_to_rows_host(columns, max_batch_bytes)
    return _convert_to_rows_device(columns, max_batch_bytes)


def _convert_to_rows_device(
    columns: Sequence, max_batch_bytes: int
) -> List[ListColumn]:
    n = columns[0].size
    dtypes = [c.dtype for c in columns]
    starts, sizes, validity_offset, size_per_row = compute_layout(dtypes)
    string_cols = [c for c in columns if c.dtype.kind == Kind.STRING]
    fixed_row = _round_up(size_per_row, JCUDF_ROW_ALIGNMENT)

    if string_cols:
        str_lens = [c.lengths().astype(jnp.int64) for c in string_cols]
        row_sizes = np.asarray(_round_up(size_per_row + sum(str_lens), JCUDF_ROW_ALIGNMENT))
    else:
        row_sizes = np.full((n,), fixed_row, dtype=np.int64)

    # ---- fixed-width section as a dense [n, size_per_row] matrix ----
    if bool(config.get("rows_plan_cache")):
        # round 20: one fused permutation gather over the lane matrix
        with PHASES.phase("plan"):
            plan = _get_row_plan(dtypes, n)
        with PHASES.phase("lanes"):
            str_starts = []
            if string_cols:
                # exclusive running char offset per string column: spr + the
                # cumulative lengths of the preceding string columns
                run = jnp.cumsum(jnp.stack(str_lens, axis=0), axis=0)
                str_starts = [
                    run[i] - str_lens[i] + size_per_row
                    for i in range(len(string_cols))
                ]
            lanes_list = []
            si = 0
            for col in columns:
                if col.dtype.kind == Kind.STRING:
                    pair = jnp.stack(
                        [
                            str_starts[si].astype(jnp.uint32),
                            str_lens[si].astype(jnp.uint32),
                        ],
                        axis=1,
                    )
                    lanes_list.append(
                        jnp.stack(
                            [
                                (pair[:, i // 4] >> jnp.uint32(8 * (i % 4))).astype(jnp.uint8)
                                for i in range(8)
                            ],
                            axis=1,
                        )
                    )
                    si += 1
                else:
                    lanes_list.append(_col_le_bytes(col))
            lanes = jnp.concatenate(lanes_list + [_validity_bytes(columns)], axis=1)
        with PHASES.phase("gather"):
            fixed = _gather_fixed(
                lanes, plan["perm_dev"], plan["keep_dev"]
            )[:, :size_per_row]
    else:
        # oracle: per-column scatter chain (pre-round-20 byte path)
        # analyze: ignore[governed-allocation] - same ungoverned row-
        # codec debt as _validity_bytes (tracked at the site, round 16)
        fixed = jnp.zeros((n, size_per_row), jnp.uint8)
        # analyze: ignore[governed-allocation] - same row-codec debt
        within_row = jnp.full((n,), size_per_row, jnp.int64) if string_cols else None
        str_starts = []  # per string col: within-row char start offsets
        for col, start, size in zip(columns, starts, sizes):
            if col.dtype.kind == Kind.STRING:
                lens = col.lengths().astype(jnp.int64)
                str_starts.append(within_row)
                pair = jnp.stack(
                    [within_row.astype(jnp.uint32), lens.astype(jnp.uint32)], axis=1
                )
                pair_bytes = jnp.stack(
                    [(pair[:, i // 4] >> jnp.uint32(8 * (i % 4)))
                     .astype(jnp.uint8) for i in range(8)],
                    axis=1,
                )
                fixed = fixed.at[:, start : start + 8].set(pair_bytes)
                within_row = within_row + lens
            else:
                fixed = fixed.at[:, start : start + size].set(_col_le_bytes(col))
        fixed = fixed.at[:, validity_offset:size_per_row].set(_validity_bytes(columns))

    # ---- emit batches ----
    with PHASES.phase("emit"):
        bounds = _batch_boundaries(row_sizes, max_batch_bytes)
        str_lens_np = [np.asarray(c.lengths()) for c in string_cols]
        out: List[ListColumn] = []
        cum_sizes = np.concatenate([[0], np.cumsum(row_sizes)])
        for b0, b1 in zip(bounds[:-1], bounds[1:]):
            offsets_np = (cum_sizes[b0 : b1 + 1] - cum_sizes[b0]).astype(np.int32)
            total = int(offsets_np[-1])
            row_off = jnp.asarray(offsets_np[:-1].astype(np.int64))
            # analyze: ignore[governed-allocation] - same row-codec debt
            flat = jnp.zeros((max(total, 1),), jnp.uint8)
            # scatter the fixed sections
            pos = row_off[:, None] + jnp.arange(size_per_row, dtype=jnp.int64)[None, :]
            flat = flat.at[pos].set(fixed[b0:b1], mode="drop")
            # scatter string chars (column order); pad per batch so one long
            # string elsewhere in the table doesn't inflate this batch's tile
            for scol, lens_np, sstart in zip(string_cols, str_lens_np, str_starts):
                batch_max = max(int(lens_np[b0:b1].max()) if b1 > b0 else 0, 1)
                sub = StringColumn(
                    scol.chars,
                    scol.offsets[b0 : b1 + 1],
                    None,
                )
                padded, lens = sub.padded(batch_max)
                lane = jnp.arange(batch_max, dtype=jnp.int64)[None, :]
                cpos = row_off[:, None] + sstart[b0:b1, None] + lane
                in_bounds = lane < lens[:, None].astype(jnp.int64)
                cpos = jnp.where(in_bounds, cpos, jnp.int64(total))
                flat = flat.at[cpos].set(padded, mode="drop")
            out.append(
                ListColumn(
                    jnp.asarray(offsets_np), Column(flat[:total], None, UINT8), None
                )
            )
    return out


def convert_to_rows_fixed_width_optimized(columns: Sequence) -> List[ListColumn]:
    """Legacy fixed-width path: <100 columns, row size <= 1KB
    (RowConversion.java:118-121; row_conversion.cu:306)."""
    if len(columns) >= 100:
        raise ValueError("Too many columns for the fixed-width optimized path")
    for c in columns:
        if c.dtype.kind == Kind.STRING:
            raise TypeError("Only fixed width types are supported")
    _, _, _, size_per_row = compute_layout([c.dtype for c in columns])
    if _round_up(size_per_row, JCUDF_ROW_ALIGNMENT) > 1024:
        raise ValueError("Row size is too large")
    return convert_to_rows(columns)


def convert_from_rows(
    rows: ListColumn, dtypes: Sequence[DType]
) -> List:
    """LIST<UINT8> batch in JCUDF format -> columns of ``dtypes``."""
    if bool(config.get("rows_plan_cache")) and not _rows_device_enabled():
        return _convert_from_rows_host(rows, dtypes)
    return _convert_from_rows_device(rows, dtypes)


def _convert_from_rows_device(rows: ListColumn, dtypes: Sequence[DType]) -> List:
    starts, sizes, validity_offset, size_per_row = compute_layout(dtypes)
    n = rows.size
    flat = rows.child.data
    row_off = rows.offsets.astype(jnp.int64)[:-1]

    # round 20 (plan-cached): gather the whole fixed section once, then
    # decode columns from contiguous slices of it.  Oracle: one clipped
    # gather per column straight from the flat buffer.
    fixed = None
    if bool(config.get("rows_plan_cache")):
        with PHASES.phase("plan"):
            _get_row_plan(dtypes, n)  # warm/validate the cached layout
        with PHASES.phase("gather"):
            pos = row_off[:, None] + jnp.arange(size_per_row, dtype=jnp.int64)[None, :]
            fixed = flat[jnp.clip(pos, 0, max(flat.shape[0] - 1, 0))]

    # validity bits for every column
    nbytes = (len(dtypes) + 7) // 8
    if fixed is not None:
        vbytes = fixed[:, validity_offset : validity_offset + nbytes]
    else:
        vpos = row_off[:, None] + validity_offset + jnp.arange(nbytes, dtype=jnp.int64)[None, :]
        vbytes = flat[jnp.clip(vpos, 0, max(flat.shape[0] - 1, 0))]

    out = []
    for c, (dt, start, size) in enumerate(zip(dtypes, starts, sizes)):
        vb = vbytes[:, c // 8]
        # Keep the validity array unconditionally: normalizing all-valid to
        # None would force a blocking device sync per column.
        validity: Optional[jnp.ndarray] = ((vb >> np.uint8(c % 8)) & jnp.uint8(1)) == 1
        if dt.kind == Kind.STRING:
            if fixed is not None:
                praw = fixed[:, start : start + 8].astype(jnp.uint32)
            else:
                ppos = row_off[:, None] + start + jnp.arange(8, dtype=jnp.int64)[None, :]
                praw = flat[ppos].astype(jnp.uint32)
            soff = sum(praw[:, k] << jnp.uint32(8 * k) for k in range(4)).astype(jnp.int64)
            slen = sum(praw[:, 4 + k] << jnp.uint32(8 * k) for k in range(4)).astype(jnp.int32)
            max_len = max(int(jnp.max(slen)) if n else 0, 1)
            lane = jnp.arange(max_len, dtype=jnp.int64)[None, :]
            cpos = row_off[:, None] + soff[:, None] + lane
            in_b = lane < slen[:, None].astype(jnp.int64)
            cpos = jnp.clip(cpos, 0, max(flat.shape[0] - 1, 0))
            padded = jnp.where(in_b, flat[cpos], jnp.uint8(0))
            out.append(strings_from_padded(padded, slen, validity))
        else:
            if fixed is not None:
                raw = fixed[:, start : start + size]
            else:
                pos = row_off[:, None] + start + jnp.arange(size, dtype=jnp.int64)[None, :]
                raw = flat[jnp.clip(pos, 0, max(flat.shape[0] - 1, 0))]
            out.append(_bytes_to_col(raw, dt, validity))
    return out


def convert_from_rows_fixed_width_optimized(
    rows: ListColumn, dtypes: Sequence[DType]
) -> List:
    """Legacy fixed-width read path (row_conversion.cu:306)."""
    for dt in dtypes:
        if dt.kind == Kind.STRING:
            raise TypeError("Only fixed width types are supported")
    return convert_from_rows(rows, dtypes)
