"""Spark-sketch-compatible bloom filter: create / put / merge / probe / (de)serialize.

Capability parity with the reference's bloom filter ops (bloom_filter.cu:63
gpu_bloom_filter_put, :92 bloom_probe_functor, :229 bloom_filter_create, :275
bloom_filter_merge, :324 bloom_filter_probe), matching Spark's
``BloomFilterImpl.putLong``/``mightContainLong`` bit-for-bit.

Design difference from the reference (deliberate, TPU-first): the reference
keeps the filter in Spark's serialized big-endian byte layout at all times and
compensates with ``^0x1`` word / ``^0x18`` bit swizzles on every access
(bloom_filter.cu:44-59).  Here the live filter is a logical uint64 long array
— bit ``i`` of ``longs[i >> 6]`` — which is the natural vector layout; the
big-endian Spark wire format (12-byte header {version=1, numHashes, numLongs}
+ numLongs big-endian int64s) exists only in ``serialize``/``deserialize``.
Byte-level interchange with Spark/the reference is exact.

Put scatter-``set``s each bit into a transient byte-per-bit array (set is
idempotent, so duplicates need no dedup) and packs 64 bits/word with
weighted row-sums, instead of atomicOr, which has no TPU equivalent; probe
is per-hash 1-D gathers + AND-reduce.
"""

from __future__ import annotations

import dataclasses
import struct

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu.columnar.column import Column
from spark_rapids_jni_tpu.columnar.dtypes import BOOL, Kind
from spark_rapids_jni_tpu.ops.hashing import _mm_hash_long

SPARK_BLOOM_FILTER_VERSION = 1
HEADER_SIZE = 12


@dataclasses.dataclass
class BloomFilter:
    """A Spark bloom filter: ``num_longs`` 64-bit words, ``num_hashes`` probes."""

    longs: jnp.ndarray  # uint64[num_longs], logical bit order
    num_hashes: int
    num_longs: int

    @property
    def num_bits(self) -> int:
        return self.num_longs * 64


jax.tree_util.register_dataclass(
    BloomFilter, ("longs",), ("num_hashes", "num_longs")
)


def bloom_filter_create(num_hashes: int, bloom_filter_longs: int) -> BloomFilter:
    """Empty filter of ``bloom_filter_longs`` 64-bit words (bloom_filter.cu:229)."""
    if bloom_filter_longs <= 0:
        raise ValueError("Invalid empty bloom filter size")
    if num_hashes <= 0:
        raise ValueError("Number of bloom filter hashes must be positive")
    return BloomFilter(
        jnp.zeros((bloom_filter_longs,), jnp.uint64),
        int(num_hashes),
        int(bloom_filter_longs),
    )


def _bit_indices(values: jnp.ndarray, num_hashes: int, num_bits: int) -> jnp.ndarray:
    """[num_hashes, n] bloom bit indices of int64 values (BloomFilterImpl.java:87-94).

    h1 = murmur3(long, 0); h2 = murmur3(long, h1); combined_i = h1 + i*h2
    (int32 wraparound), index = (combined < 0 ? ~combined : combined) % num_bits.

    Hash-major layout: with n minor the TPU (8,128) tiling pads only the
    small hash axis.  The value-major [n, num_hashes] orientation padded
    each 3-wide row to a full tile — a measured 42.7x HBM expansion that
    OOMed the v5e at n=2^24 (32 GB requested for a 768 MB gather).
    """
    h1 = _mm_hash_long(values, jnp.uint32(0)).astype(jnp.int32)
    h2 = _mm_hash_long(values, h1.astype(jnp.uint32)).astype(jnp.int32)
    ks = jnp.arange(1, num_hashes + 1, dtype=jnp.int32)
    combined = h1[None, :] + ks[:, None] * h2[None, :]  # int32 wrap
    positive = jnp.where(combined < 0, ~combined, combined)
    return (positive.astype(jnp.int64) % num_bits).astype(jnp.int64)


# Path-selection threshold for put: the scatter-set path materializes
# ~1.25 bytes/BIT of transient HBM (uint8 bit array + two u32 half-packs)
# no matter how few values are inserted, while the sort+dedup path costs
# ~10 bytes per inserted INDEX (int64 sort + word/contrib streams).  The
# break-even is num_bits ~ 8x the index count; below it the dense scatter
# wins (big inserts into a filter they mostly fill), above it a small
# batch into a huge filter must NOT allocate byte-per-bit (a 1-Grow
# runtime filter is 1 GB+ of transient for a 1k-row insert otherwise).
_SCATTER_BITS_PER_INDEX = 8


def _put_scatter_bits(flat: jnp.ndarray, num_bits: int) -> jnp.ndarray:
    """uint64[num_longs] via a byte-per-bit scatter-``set`` + 64x pack.

    ``set`` is idempotent, so duplicate bits need no dedup; out-of-range
    sentinel indices (null rows) drop.  Replaced an earlier always-on
    sort design: the 50M-element sort dominated put at 2^24 keys
    (3.4 -> 53 Mrows/s measured on the v5e, exact parity).
    """
    bits = jnp.zeros((num_bits,), jnp.uint8).at[flat].set(1, mode="drop")
    halves = bits.reshape(-1, 2, 32).astype(jnp.uint32)
    w32 = jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)
    packed = (halves * w32[None, None, :]).sum(axis=2)  # [num_longs, 2]
    return (packed[:, 0].astype(jnp.uint64)
            | (packed[:, 1].astype(jnp.uint64) << jnp.uint64(32)))


def _put_sorted(flat: jnp.ndarray, num_bits: int) -> jnp.ndarray:
    """uint64[num_longs] via sort + first-occurrence dedup + scatter-add.

    Transient HBM scales with the INDEX count, not the filter width:
    dedup guarantees each bit contributes once, so the per-word sum of
    distinct powers of two equals the bitwise or.  Sentinel indices
    (>= num_bits, the null-row route) sort to the top and their word
    index (== num_longs) drops in the scatter.
    """
    s = jnp.sort(flat)
    first = jnp.concatenate(
        [jnp.ones((1,), bool), s[1:] != s[:-1]]) & (s < num_bits)
    word = s >> 6
    contrib = jnp.where(
        first,
        jnp.uint64(1) << (s & 63).astype(jnp.uint64),
        jnp.uint64(0))
    # analyze: ignore[governed-allocation] - the sort-path put variant:
    # reached from bloom_filter_put whose serving caller brackets it;
    # direct callers are parity tests.  Debt tracked at the site
    # (round 16 baseline burn-down).
    return jnp.zeros((num_bits // 64,), jnp.uint64).at[word].add(
        contrib, mode="drop")


def bloom_filter_put(bloom_filter: BloomFilter, input: Column) -> BloomFilter:
    """Insert an INT64 column's non-null values; returns the updated filter.

    Functional (returns a new pytree) rather than in-place atomicOr, with
    the transient-memory shape picked from the static geometry: dense
    inserts scatter-``set`` a byte-per-bit array, sparse inserts into a
    large filter sort+dedup their indices instead (transient bounded by
    the insert size, not the filter width) — both bit-exact vs Spark.
    """
    if input.dtype.kind != Kind.INT64:
        raise TypeError("bloom_filter_put requires an INT64 column")
    idx = _bit_indices(input.data, bloom_filter.num_hashes, bloom_filter.num_bits)
    if input.validity is not None:
        # Route null rows' bits to a sentinel beyond the filter; both
        # paths drop out-of-range indices.
        idx = jnp.where(input.validity[None, :], idx, jnp.int64(bloom_filter.num_bits))
    flat = idx.reshape(-1)
    if bloom_filter.num_bits <= _SCATTER_BITS_PER_INDEX * flat.shape[0]:
        batch = _put_scatter_bits(flat, bloom_filter.num_bits)
    else:
        batch = _put_sorted(flat, bloom_filter.num_bits)
    return dataclasses.replace(bloom_filter, longs=bloom_filter.longs | batch)


def bloom_filter_probe(input: Column, bloom_filter: BloomFilter) -> Column:
    """BOOL column: True if the value may be present (bloom_filter.cu:324).

    Output validity mirrors the input's (null in, null out).
    """
    if input.dtype.kind != Kind.INT64:
        raise TypeError("bloom_filter_probe requires an INT64 column")
    idx = _bit_indices(input.data, bloom_filter.num_hashes, bloom_filter.num_bits)
    # Statically unrolled per-hash 1-D gathers: every intermediate stays
    # [n] (clean lane tiling); num_hashes is a small static int.
    present = None
    for i in range(bloom_filter.num_hashes):
        ii = idx[i]
        words = bloom_filter.longs[ii >> 6]
        hit = (words >> (ii.astype(jnp.uint64) & jnp.uint64(63))) & jnp.uint64(1)
        present = (hit == 1) if present is None else (present & (hit == 1))
    return Column(present, input.validity, BOOL)


def bloom_filter_merge(filters: list[BloomFilter]) -> BloomFilter:
    """Bitwise-or of same-shaped filters (bloom_filter.cu:275)."""
    if not filters:
        raise ValueError("at least one bloom filter is required")
    head = filters[0]
    for f in filters[1:]:
        if (f.num_hashes, f.num_longs) != (head.num_hashes, head.num_longs):
            raise ValueError("Mismatch of bloom filter parameters")
    longs = head.longs
    for f in filters[1:]:
        longs = longs | f.longs
    return dataclasses.replace(head, longs=longs)


def bloom_filter_serialize(bloom_filter: BloomFilter) -> bytes:
    """Spark wire format: big-endian header + big-endian longs (host-side)."""
    header = struct.pack(
        ">iii",
        SPARK_BLOOM_FILTER_VERSION,
        bloom_filter.num_hashes,
        bloom_filter.num_longs,
    )
    longs = np.asarray(bloom_filter.longs).astype(">u8").tobytes()
    return header + longs


def bloom_filter_deserialize(buf: bytes) -> BloomFilter:
    """Parse the Spark wire format (validation per bloom_filter.cu:141-166)."""
    if len(buf) < HEADER_SIZE:
        raise ValueError("Encountered truncated bloom filter")
    version, num_hashes, num_longs = struct.unpack(">iii", buf[:HEADER_SIZE])
    if version != SPARK_BLOOM_FILTER_VERSION:
        raise ValueError("Unexpected bloom filter version")
    if num_longs <= 0:
        raise ValueError("Invalid empty bloom filter size")
    if num_hashes <= 0:
        raise ValueError("Number of bloom filter hashes must be positive")
    if len(buf) != HEADER_SIZE + num_longs * 8:
        raise ValueError("Encountered invalid/mismatched bloom filter buffer data")
    longs = np.frombuffer(buf, dtype=">u8", offset=HEADER_SIZE).astype(np.uint64)
    return BloomFilter(jnp.asarray(longs), num_hashes, num_longs)
