"""Spark ``parse_url``: protocol/host/query/query-param/path extraction.

Parity target: the reference's ``parse_uri.cu`` (validate_uri at
``parse_uri.cu:535``, chunk validators ``:153-493``, query-param narrowing
``find_query_part`` ``:495``) behind ``ParseURI.java:36-98``.  The reference
re-implements ``java.net.URI``'s accept/reject behavior: a URI is validated
*completely* (scheme, fragment, authority incl. IPv4/IPv6/domain hosts, query,
path, escapes, UTF-8) and a fatally-invalid row nulls every chunk, while some
failures (e.g. a bad host) null only that chunk.

TPU-first design notes (vs the reference's one-thread-per-row SIMT kernels):

- All character-class validation (``validate_chunk`` + the ``%XX`` escape and
  UTF-8 rules of ``skip_and_validate_special``, ``parse_uri.cu:92-151``) is
  done with *shift-based elementwise masks* over the padded ``[rows, bytes]``
  matrix — no sequential pass at all.  This relies on a position-independence
  property: in any span that the sequential scanner accepts, every ``%`` begins
  an escape (hex chars are never ``%``), and in any span it rejects, the first
  offending position is flagged by the local rule too, so "each ``%`` must be
  followed by two in-span hex bytes" is exactly equivalent.  Likewise UTF-8
  continuation checks are static shifts of the lead-byte mask.
- The three host grammars (IPv4 dotted-quad, registry domain name, IPv6 — all
  sequential state machines in the reference, ``:165-345``) run as ONE fused
  ``lax.scan`` across the byte axis with small per-row state vectors, keeping
  every row in VPU lanes.
- Bug-compat quirks are preserved deliberately: ``validate_port`` accepts any
  byte (the ``c < '0' && c > '9'`` predicate at ``parse_uri.cu:448`` is never
  true); 'G'-'Z' count as hex digits inside IPv6 groups (``:251``); the
  ``amp == 0`` authority path leaves host offsets relative to the unadvanced
  authority (``:686,:707``); on an empty remainder the valid-bit mask is
  overwritten to just PATH-if-schemeless (``:610``).
- One *resolved* (not preserved) reference quirk: ``has_auth`` probes the byte
  after ``//`` via ``_at``, which clamps past-the-end reads to a zero byte.
  The reference reads ``str[1]`` unconditionally (``parse_uri.cu:650``), an
  out-of-bounds read for a 1-byte remainder like ``"http:/"`` — defined
  behavior here (zero byte, no authority) vs memory-dependent UB there.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from spark_rapids_jni_tpu.columnar.buckets import (
    padded_buckets,
    strings_from_buckets,
)
from spark_rapids_jni_tpu.columnar.column import (
    StringColumn,
    strings_column,
)

__all__ = [
    "parse_uri_protocol",
    "parse_uri_host",
    "parse_uri_query",
    "parse_uri_query_literal",
    "parse_uri_query_column",
    "parse_uri_path",
]

# Chunk selectors (mirror URI_chunks, parse_uri.cu:58-68).
_PROTOCOL, _HOST, _QUERY, _PATH = 0, 1, 2, 3

# Host validation outcomes (chunk_validity, parse_uri.cu:70).
_H_VALID, _H_INVALID, _H_FATAL = 0, 1, 2


def _build_luts():
    hexd = np.zeros(256, np.bool_)
    for c in b"0123456789abcdefABCDEF":
        hexd[c] = True
    alpha = np.zeros(256, np.bool_)
    alpha[ord("a") : ord("z") + 1] = True
    alpha[ord("A") : ord("Z") + 1] = True
    digit = np.zeros(256, np.bool_)
    digit[ord("0") : ord("9") + 1] = True
    alnum = alpha | digit

    def from_ranges(singles=b"", ranges=(), minus=b""):
        t = np.zeros(256, np.bool_)
        for c in singles:
            t[c] = True
        for lo, hi in ranges:
            t[lo : hi + 1] = True
        for c in minus:
            t[c] = False
        return t

    # validate_query (parse_uri.cu:399-411)
    query = from_ranges(b'!"$=_~', [(0x26, 0x3B), (0x3F, 0x5D), (0x61, 0x7A)], b"\\")
    # validate_path (parse_uri.cu:453-465)
    path = from_ranges(b"!$=_~", [(0x26, 0x3B), (0x40, 0x5A), (0x61, 0x7A)])
    # validate_opaque / validate_fragment (parse_uri.cu:467-493) — identical sets
    opaque = from_ranges(b"!$=_~", [(0x26, 0x3B), (0x3F, 0x5D), (0x61, 0x7A)], b"\\")
    # validate_authority (parse_uri.cu:413-429)
    auth = from_ranges(
        b"!$=~", [(0x26, 0x3B), (0x40, 0x5F), (0x61, 0x7A)], b"/^\\"
    )
    auth_pct = auth.copy()
    auth_pct[ord("%")] = True
    # validate_userinfo (parse_uri.cu:431-440): anything but brackets
    userinfo = np.ones(256, np.bool_)
    userinfo[ord("[")] = False
    userinfo[ord("]")] = False
    # validate_port (parse_uri.cu:442-451): the predicate can never fail
    port = np.ones(256, np.bool_)
    scheme_rest = alnum.copy()
    for c in b"+-.":
        scheme_rest[c] = True
    return {
        "hex": hexd,
        "alpha": alpha,
        "digit": digit,
        "alnum": alnum,
        "query": query,
        "path": path,
        "opaque": opaque,
        "fragment": opaque,
        "auth": auth,
        "auth_pct": auth_pct,
        "userinfo": userinfo,
        "port": port,
        "scheme_rest": scheme_rest,
    }


_LUTS = {k: jnp.asarray(v) for k, v in _build_luts().items()}


def _first(mask, pos, L):
    """(first position, found) over axis 1; position is L+9 when not found."""
    p = jnp.where(mask, pos, jnp.int32(L + 9))
    return jnp.min(p, axis=1), jnp.any(mask, axis=1)


def _last(mask, pos):
    p = jnp.where(mask, pos, jnp.int32(-1))
    return jnp.max(p, axis=1), jnp.any(mask, axis=1)


def _at(b, idx):
    """Gather one byte per row at a clipped index (callers gate validity)."""
    L = b.shape[1]
    return jnp.take_along_axis(
        b, jnp.clip(idx, 0, L - 1)[:, None].astype(jnp.int32), axis=1
    )[:, 0]


def _shr(m, k):
    """Shift mask right along the byte axis: out[i] = m[i-k]."""
    return jnp.pad(m, ((0, 0), (k, 0)))[:, : m.shape[1]]


def _validate_span(b, bx, s, e, lut, raw_pct=None):
    """Vectorized validate_chunk (parse_uri.cu:133-151) over per-row spans.

    ``raw_pct`` (bool[n] or None) mirrors allow_invalid_escapes: where True,
    '%' is an ordinary character checked against the LUT instead of starting a
    mandatory %XX escape.
    """
    n, L = b.shape
    pos = jnp.arange(L, dtype=jnp.int32)[None, :]
    in_span = (pos >= s[:, None]) & (pos < e[:, None])
    b1, b2, b3 = bx[:, 1 : L + 1], bx[:, 2 : L + 2], bx[:, 3 : L + 3]

    is_pct = b == ord("%")
    if raw_pct is None:
        esc_start = in_span & is_pct
    else:
        esc_start = in_span & is_pct & ~raw_pct[:, None]
    hex1 = _LUTS["hex"][b1]
    hex2 = _LUTS["hex"][b2]
    esc_ok = (pos + 1 < e[:, None]) & hex1 & (pos + 2 < e[:, None]) & hex2
    esc_viol = esc_start & ~esc_ok
    esc_hex = _shr(esc_start, 1) | _shr(esc_start, 2)

    # Multi-byte UTF-8 handling (skip_and_validate_special, parse_uri.cu:108-123):
    # lead bytes >= 0xC0 consume their continuations; continuations must be
    # 10xxxxxx and the packed codepoint bytes must not be unicode whitespace.
    nb = (
        1
        + (b >= 0xC0).astype(jnp.int32)
        + (b >= 0xE0).astype(jnp.int32)
        + (b >= 0xF0).astype(jnp.int32)
    )
    lead = in_span & (nb > 1) & ~esc_hex
    cont1 = (b1 & 0xC0) == 0x80
    cont2 = (b2 & 0xC0) == 0x80
    cont3 = (b3 & 0xC0) == 0x80
    utf8_ok = jnp.where(
        nb == 2, cont1, jnp.where(nb == 3, cont1 & cont2, cont1 & cont2 & cont3)
    )
    p2 = (b.astype(jnp.int32) << 8) | b1.astype(jnp.int32)
    p3 = (p2 << 8) | b2.astype(jnp.int32)
    forb2 = (p2 >= 0xC280) & (p2 <= 0xC2A0)
    forb3 = (
        ((p3 >= 0xE28080) & (p3 <= 0xE2808A))
        | (p3 == 0xE19A80)
        | (p3 == 0xE280AF)
        | (p3 == 0xE280A8)
        | (p3 == 0xE2819F)
        | (p3 == 0xE38080)
    )
    lead_viol = lead & (~utf8_ok | ((nb == 2) & forb2) | ((nb == 3) & forb3))
    cover = (
        _shr(lead & (nb >= 2), 1) | _shr(lead & (nb >= 3), 2) | _shr(lead & (nb >= 4), 3)
    )

    plain = in_span & ~esc_start & ~esc_hex & ~lead & ~cover
    plain_viol = plain & ~lut[b]
    return ~jnp.any(esc_viol | lead_viol | plain_viol, axis=1)


def _host_machines(b, hs, he):
    """One fused scan over the byte axis running the IPv4 dotted-quad,
    domain-name, and IPv6 validators (parse_uri.cu:165-345) for every row's
    host span simultaneously.  Returns (ipv4_ok, domain_ok, ipv6_ok)."""
    n, L = b.shape
    pos = jnp.arange(L, dtype=jnp.int32)[None, :]
    in_span = (pos >= hs[:, None]) & (pos < he[:, None])
    first = pos == hs[:, None]
    last = pos == (he - 1)[:, None]
    xs = (b.T, in_span.T, first.T, last.T)

    tb = jnp.ones((n,), jnp.bool_)
    z = jnp.zeros((n,), jnp.int32)
    init = dict(
        # ipv4 (parse_uri.cu:269-304)
        a4=z, s4=z, d4=z, ok4=tb,
        # domain (parse_uri.cu:306-345)
        dh=~tb, dp=~tb, dn=~tb, dc=z, okd=tb,
        # ipv6 (parse_uri.cu:165-267)
        v6_dc=~tb, v6_ob=z, v6_cb=z, v6_pr=z, v6_co=z, v6_pc=z,
        v6_prev=jnp.zeros((n,), jnp.uint8), v6_a=z, v6_ac=z, v6_hx=~tb, ok6=tb,
    )

    def step(st, x):
        c, ins, fst, lst = x
        ci = c.astype(jnp.int32)
        dig = _LUTS["digit"][c]
        dv = ci - ord("0")

        # ---- IPv4: digits and interior dots; every prefix value <= 255.
        dot = c == ord(".")
        ok4 = st["ok4"] & (dig | (dot & ~fst))
        ok4 = jnp.where(dot, ok4 & (st["s4"] > 0), ok4)
        a4n = jnp.minimum(st["a4"] * 10 + dv, 1000)
        ok4 = jnp.where(dig, ok4 & (a4n <= 255), ok4)
        a4 = jnp.where(dot, 0, jnp.where(dig, a4n, st["a4"]))
        s4 = jnp.where(dot, 0, jnp.where(dig, st["s4"] + 1, st["s4"]))
        d4 = st["d4"] + jnp.where(dot, 1, 0)

        # ---- Domain name: alnum/-/.; '-' not at edges or beside '.'; '.' not
        # doubled/leading; final label must not start with a digit.
        an = _LUTS["alnum"][c]
        hy = c == ord("-")
        pd = c == ord(".")
        okd = st["okd"] & (an | hy | pd)
        dn = st["dp"] & dig
        okd = jnp.where(hy, okd & ~st["dp"] & ~fst & ~lst, okd)
        okd = jnp.where(pd, okd & ~st["dh"] & ~st["dp"] & (st["dc"] > 0), okd)
        dh = hy
        dp = pd
        dcnt = jnp.where(hy | pd, jnp.where(pd, 0, st["dc"]), st["dc"] + 1)
        dcnt = jnp.where(hy, st["dc"], dcnt)

        # ---- IPv6 (with bracket/zone%/embedded-IPv4 bookkeeping).
        is_ob = c == ord("[")
        is_cb = c == ord("]")
        is_co = c == ord(":")
        is_pd = c == ord(".")
        is_pc = c == ord("%")
        other = ~(is_ob | is_cb | is_co | is_pd | is_pc)
        ok6 = st["ok6"]
        ob = st["v6_ob"] + jnp.where(is_ob, 1, 0)
        cb = st["v6_cb"] + jnp.where(is_cb, 1, 0)
        ok6 = jnp.where(is_ob, ok6 & (ob <= 1), ok6)
        seg_bad = st["v6_hx"] | (st["v6_a"] > 255)
        ok6 = jnp.where(is_cb, ok6 & (cb <= 1) & ~((st["v6_pr"] > 0) & seg_bad), ok6)
        dbl = st["v6_prev"] == ord(":")
        co = st["v6_co"] + jnp.where(is_co, 1, 0)
        ok6 = jnp.where(
            is_co,
            ok6
            & ~(dbl & st["v6_dc"])
            & ~((co > 8) | ((co == 8) & ~(st["v6_dc"] | dbl)))
            & ~((st["v6_pr"] > 0) | (st["v6_pc"] > 0)),
            ok6,
        )
        v6_dc = st["v6_dc"] | (is_co & dbl)
        pr = st["v6_pr"] + jnp.where(is_pd, 1, 0)
        ok6 = jnp.where(
            is_pd,
            ok6
            & (st["v6_pc"] == 0)
            & (pr <= 3)
            & ~st["v6_hx"]
            & (st["v6_a"] <= 255)
            & ((st["v6_co"] == 6) | st["v6_dc"])
            & (st["v6_co"] < 8),
            ok6,
        )
        pc = st["v6_pc"] + jnp.where(is_pc, 1, 0)
        ok6 = jnp.where(
            is_pc, ok6 & (pc <= 1) & ~((st["v6_pr"] > 0) & seg_bad), ok6
        )
        in_group = other & (st["v6_pc"] == 0)
        lower = (c >= ord("a")) & (c <= ord("f"))
        upper = (c >= ord("A")) & (c <= ord("Z"))  # bug-compat: G-Z "hex"
        ok6 = jnp.where(in_group, ok6 & (st["v6_ac"] <= 3) & (lower | upper | dig), ok6)
        add = jnp.where(
            lower, 10 + ci - ord("a"), jnp.where(upper, 10 + ci - ord("A"), dv)
        )
        a6n = jnp.minimum(st["v6_a"] * 10 + jnp.where(lower | upper | dig, add, 0), 99999)
        reset6 = is_co | is_pd | is_pc
        v6_a = jnp.where(reset6, 0, jnp.where(in_group, a6n, st["v6_a"]))
        v6_ac = jnp.where(reset6, 0, jnp.where(in_group, st["v6_ac"] + 1, st["v6_ac"]))
        v6_hx = jnp.where(
            reset6, False, st["v6_hx"] | (in_group & (lower | upper))
        )

        def sel(new, old):
            return jnp.where(ins, new, old)

        return (
            dict(
                a4=sel(a4, st["a4"]), s4=sel(s4, st["s4"]), d4=sel(d4, st["d4"]),
                ok4=sel(ok4, st["ok4"]),
                dh=sel(dh, st["dh"]), dp=sel(dp, st["dp"]), dn=sel(dn, st["dn"]),
                dc=sel(dcnt, st["dc"]), okd=sel(okd, st["okd"]),
                v6_dc=sel(v6_dc, st["v6_dc"]), v6_ob=sel(ob, st["v6_ob"]),
                v6_cb=sel(cb, st["v6_cb"]), v6_pr=sel(pr, st["v6_pr"]),
                v6_co=sel(co, st["v6_co"]), v6_pc=sel(pc, st["v6_pc"]),
                v6_prev=sel(c, st["v6_prev"]), v6_a=sel(v6_a, st["v6_a"]),
                v6_ac=sel(v6_ac, st["v6_ac"]), v6_hx=sel(v6_hx, st["v6_hx"]),
                ok6=sel(ok6, st["ok6"]),
            ),
            None,
        )

    st, _ = lax.scan(step, init, xs)
    ipv4_ok = st["ok4"] & (st["s4"] > 0) & (st["d4"] == 3)
    domain_ok = st["okd"] & ~st["dn"]
    ipv6_ok = st["ok6"] & ((he - hs) >= 2)
    return ipv4_ok, domain_ok, ipv6_ok


def _validate_host(b, bx, hs, he):
    """validate_host (parse_uri.cu:347-397) → 0 VALID / 1 INVALID / 2 FATAL."""
    n, L = b.shape
    pos = jnp.arange(L, dtype=jnp.int32)[None, :]
    in_span = (pos >= hs[:, None]) & (pos < he[:, None])
    ipv4_ok, domain_ok, ipv6_ok = _host_machines(b, hs, he)

    first_b = _at(b, hs)
    last_b = _at(b, he - 1)
    starts_br = (first_b == ord("[")) & (he > hs)
    bracket_any = jnp.any(in_span & ((b == ord("[")) | (b == ord("]"))), axis=1)
    lp, lp_f = _last(in_span & (b == ord(".")), jnp.broadcast_to(pos, (n, L)))
    after = _at(b, lp + 1)
    domain_route = ~lp_f | (lp == he - 1) | ~_LUTS["digit"][after]

    bracket_state = jnp.where(
        (last_b == ord("]")) & ipv6_ok, _H_VALID, _H_FATAL
    )
    plain_state = jnp.where(
        bracket_any,
        _H_FATAL,
        jnp.where(
            domain_route,
            jnp.where(domain_ok, _H_VALID, _H_INVALID),
            jnp.where(ipv4_ok, _H_VALID, _H_INVALID),
        ),
    )
    return jnp.where(starts_br, bracket_state, plain_state)


@functools.partial(jax.jit, static_argnames=("want", "with_needle"))
def _parse(padded, lens, valid_in, want, with_needle, n_padded, n_lens, n_valid):
    """Vectorized validate_uri (parse_uri.cu:535-746) + chunk selection."""
    n, L = padded.shape
    b = padded
    bx = jnp.pad(b, ((0, 0), (0, 4)))
    pos = jnp.arange(L, dtype=jnp.int32)[None, :]
    lens = lens.astype(jnp.int32)
    in_str = pos < lens[:, None]
    posb = jnp.broadcast_to(pos, (n, L))

    col_p, col_f = _first(in_str & (b == ord(":")), posb, L)
    slash_p, slash_f = _first(in_str & (b == ord("/")), posb, L)
    hash_p, hash_f = _first(in_str & (b == ord("#")), posb, L)
    q_p, q_f = _first(in_str & (b == ord("?")), posb, L)

    # Fragment: everything after '#'; invalid fragment kills the row
    # (parse_uri.cu:569-582).
    E = jnp.where(hash_f, hash_p, lens)
    frag_ok = _validate_span(b, bx, hash_p + 1, lens, _LUTS["fragment"])
    row_pre = jnp.where(hash_f, frag_ok, True)
    col_f = col_f & (~hash_f | (col_p < hash_p))
    slash_f = slash_f & (~hash_f | (slash_p < hash_p))
    q_f = q_f & (~hash_f | (q_p < hash_p))

    # Scheme (parse_uri.cu:584-603).
    has_scheme = col_f & (~slash_f | (col_p < slash_p))
    first_alpha = _LUTS["alpha"][b[:, 0]]
    rest_bad = jnp.any(
        (pos >= 1) & (pos < col_p[:, None]) & in_str & ~_LUTS["scheme_rest"][b], axis=1
    )
    scheme_ok = (col_p > 0) & first_alpha & ~rest_bad
    row_pre = row_pre & (~has_scheme | scheme_ok)
    proto_bit = has_scheme & scheme_ok
    rs = jnp.where(has_scheme, col_p + 1, 0)
    empty_rest = (E - rs) <= 0

    # Hierarchical vs opaque (parse_uri.cu:614-616).
    hier = (_at(b, rs) == ord("/")) | (rs == 0)

    # Query (parse_uri.cu:619-647).
    has_q = hier & q_f & (q_p >= rs)
    qs = jnp.where(has_q, q_p + 1, 0)
    qe = jnp.where(has_q, E, 0)
    query_ok = _validate_span(b, bx, qs, qe, _LUTS["query"])
    row_post = jnp.where(has_q, query_ok, True)
    query_bit = has_q & query_ok

    PE = jnp.where(has_q, q_p, E)

    # Authority (parse_uri.cu:650-725).
    has_auth = hier & (_at(b, rs) == ord("/")) & (_at(b, rs + 1) == ord("/"))
    a_s = rs + 2
    ns_p, ns_f = _first(
        (b == ord("/")) & (pos >= a_s[:, None]) & (pos < PE[:, None]), posb, L
    )
    a_e = jnp.where(ns_f, ns_p, jnp.where(has_q, q_p, E))
    auth_nonempty = has_auth & (a_e > a_s)
    ipv6_escapes = auth_nonempty & ((a_e - a_s) > 2) & (_at(b, a_s) == ord("["))
    auth_lut_ok = _validate_span(
        b, bx, a_s, a_e, _LUTS["auth"], raw_pct=None
    )
    auth_lut_ok_pct = _validate_span(
        b, bx, a_s, a_e, _LUTS["auth_pct"], raw_pct=jnp.ones((n,), jnp.bool_)
    )
    auth_ok = jnp.where(ipv6_escapes, auth_lut_ok_pct, auth_lut_ok)
    row_post = row_post & (~auth_nonempty | auth_ok)
    auth_bit = auth_nonempty & auth_ok

    in_auth = (pos >= a_s[:, None]) & (pos < a_e[:, None])
    amp_p, amp_f = _first(in_auth & (b == ord("@")), posb, L)
    bound = jnp.where(amp_f, amp_p, a_s - 1)
    lc_p, lc_f = _last(in_auth & (b == ord(":")) & (pos > bound[:, None]), posb)
    cb_p, cb_f = _first(in_auth & (b == ord("]")) & (pos > bound[:, None]), posb, L)
    amp_rel = amp_p - a_s
    has_ui = auth_bit & amp_f & (amp_rel > 0)
    ui_ok = _validate_span(b, bx, a_s, amp_p, _LUTS["userinfo"])
    row_post = row_post & (~has_ui | ui_ok)
    hs = jnp.where(has_ui, amp_p + 1, a_s)
    # Offsets adjust relative to the '@' only when amp > 0 (parse_uri.cu:686-688)
    adj = amp_f & (amp_rel > 0)
    lc_rel = jnp.where(lc_f, jnp.where(adj, lc_p - amp_p - 1, lc_p - a_s), -1)
    cb_rel = jnp.where(cb_f, jnp.where(adj, cb_p - amp_p, cb_p - a_s), -1)
    has_port = auth_bit & (lc_rel > 0) & (lc_rel > cb_rel)
    port_ok = _validate_span(b, bx, hs + lc_rel + 1, a_e, _LUTS["port"])
    row_post = row_post & (~has_port | port_ok)
    host_s = hs
    host_e = jnp.where(has_port, hs + lc_rel, a_e)
    host_state = _validate_host(b, bx, host_s, host_e)
    row_post = row_post & (~auth_bit | (host_state != _H_FATAL))
    host_bit = auth_bit & (host_state == _H_VALID)

    # Path (parse_uri.cu:661,:726-735): with authority, only from the slash
    # after it (empty — but present — otherwise); without, the whole remainder.
    path_s = jnp.where(has_auth, jnp.where(ns_f, ns_p, 0), rs)
    path_e = jnp.where(has_auth, jnp.where(ns_f, PE, 0), PE)
    path_ok = _validate_span(b, bx, path_s, path_e, _LUTS["path"])
    row_post = row_post & (~hier | path_ok)
    path_bit = hier & path_ok

    # Opaque (parse_uri.cu:736-743).
    opq_ok = _validate_span(b, bx, rs, E, _LUTS["opaque"])
    row_post = row_post & (hier | opq_ok)

    # Query-param narrowing (find_query_part, parse_uri.cu:495-533).
    if with_needle:
        NL = n_padded.shape[1]
        nl = n_lens.astype(jnp.int32)
        B = jnp.pad(b, ((0, 0), (0, NL + 1)))
        m = jnp.ones((n, L), jnp.bool_)
        for j in range(NL):
            m = m & (
                (j >= nl[:, None]) | (B[:, j : j + L] == n_padded[:, j : j + 1])
            )
        eq_at = jnp.take_along_axis(B, pos + nl[:, None], axis=1)
        m = m & (eq_at == ord("="))
        prev_amp = _shr(b == ord("&"), 1)
        cand = (posb == qs[:, None]) | (
            (pos > qs[:, None]) & (pos < qe[:, None]) & prev_amp
        )
        cand = cand & ((pos + nl[:, None]) < qe[:, None])
        hit_p, hit_f = _first(cand & m, posb, L)
        v_s = hit_p + nl + 1
        amp2_p, amp2_f = _first(
            (b == ord("&")) & (pos >= v_s[:, None]) & (pos < qe[:, None]), posb, L
        )
        v_e = jnp.where(amp2_f, amp2_p, qe)
        matched = hit_f & n_valid
        query_bit = query_bit & matched
        qs = jnp.where(matched, v_s, qs)
        qe = jnp.where(matched, v_e, qe)

    row_ok = valid_in & row_pre & (empty_rest | row_post)

    if want == _PROTOCOL:
        s, e, bit = jnp.zeros_like(rs), col_p, proto_bit
    elif want == _HOST:
        s, e, bit = host_s, host_e, host_bit
    elif want == _QUERY:
        s, e, bit = qs, qe, query_bit
    else:
        s, e, bit = path_s, path_e, path_bit

    # Empty remainder: the valid mask collapses to PATH-iff-no-scheme
    # (parse_uri.cu:606-612) — even PROTOCOL/FRAGMENT bits are dropped.
    if want == _PATH:
        bit = jnp.where(empty_rest, ~has_scheme, bit)
        s = jnp.where(empty_rest, 0, s)
        e = jnp.where(empty_rest, 0, e)
    else:
        bit = bit & ~empty_rest

    out_valid = row_ok & bit
    out_len = jnp.maximum(e - s, 0)
    out_len = jnp.where(out_valid, out_len, 0)
    Bout = jnp.pad(b, ((0, 0), (0, L)))
    gathered = jnp.take_along_axis(Bout, s[:, None] + pos, axis=1)
    return gathered, out_len, out_valid


def _run(input: StringColumn, want: int, needle=None) -> StringColumn:
    n = input.size
    if n == 0:
        return StringColumn(
            # analyze: ignore[governed-allocation] - empty-result
            # literals (0/1-element): no budget impact worth a
            # reservation bracket (round 18 baseline burn-down)
            jnp.zeros((0,), jnp.uint8), jnp.zeros((1,), jnp.int32), None
        )
    valid_in = input.is_valid()
    if needle is None:
        np_, nl_, nv_ = (
            # analyze: ignore[governed-allocation] - placeholder needle
            # column for the no-query-key variants: ~5 bytes per row,
            # dwarfed by the padded URI rectangles the bucket sweep
            # below holds; serving callers reach _run inside the plan
            # runtime's governed bracket.  Debt tracked at the site
            # (round 18 baseline burn-down).
            jnp.zeros((n, 1), jnp.uint8),
            jnp.zeros((n,), jnp.int32),  # analyze: ignore[governed-allocation] - same
            jnp.ones((n,), jnp.bool_),  # analyze: ignore[governed-allocation] - same
        )
        with_needle = False
    else:
        if needle.size not in (1, n):
            # The reference JNI layer only ever passes a scalar key or a
            # same-size column (ParseURI.java:70-93); anything else would
            # surface as an opaque broadcast error below.
            raise ValueError(
                f"query key column must have 1 or {n} rows, got {needle.size}"
            )
        npad, nlens = needle.padded()
        if needle.size == 1 and n != 1:
            npad = jnp.broadcast_to(npad, (n, npad.shape[1]))
            nlens = jnp.broadcast_to(nlens, (n,))
            nv_ = jnp.broadcast_to(needle.is_valid(), (n,))
        else:
            nv_ = needle.is_valid()
        np_, nl_ = npad, nlens
        with_needle = True

    # Length-bucketed sweep: each URI length class parses over its own dense
    # rectangle (one long URL doesn't pad the whole column).
    results = []
    # analyze: ignore[governed-allocation] - 1-byte-per-row validity
    # accumulator (same burn-down rationale as the placeholder needle
    # above; round 18)
    out_valid_full = jnp.zeros((n,), jnp.bool_)
    for b in padded_buckets(input):
        gathered, out_len, out_valid = _parse(
            b.bytes,
            b.lengths,
            valid_in[b.rows],
            want,
            with_needle,
            np_[b.rows],
            nl_[b.rows],
            nv_[b.rows],
        )
        results.append((b.rows, gathered, out_len, b.n_valid))
        tgt = jnp.where(b.valid_mask(), b.rows, jnp.int32(n))
        out_valid_full = out_valid_full.at[tgt].set(out_valid, mode="drop")
    return strings_from_buckets(n, results, out_valid_full)


def parse_uri_protocol(input: StringColumn) -> StringColumn:
    """Spark ``parse_url(url, 'PROTOCOL')`` (ParseURI.java:36)."""
    return _run(input, _PROTOCOL)


def parse_uri_host(input: StringColumn) -> StringColumn:
    """Spark ``parse_url(url, 'HOST')`` (ParseURI.java:47)."""
    return _run(input, _HOST)


def parse_uri_query(input: StringColumn) -> StringColumn:
    """Spark ``parse_url(url, 'QUERY')`` (ParseURI.java:58)."""
    return _run(input, _QUERY)


def parse_uri_query_literal(input: StringColumn, literal: str) -> StringColumn:
    """Spark ``parse_url(url, 'QUERY', key)`` with a literal key
    (ParseURI.java:70)."""
    return _run(input, _QUERY, needle=strings_column([literal]))


def parse_uri_query_column(input: StringColumn, keys: StringColumn) -> StringColumn:
    """Spark ``parse_url(url, 'QUERY', key)`` with a per-row key column
    (ParseURI.java:82)."""
    return _run(input, _QUERY, needle=keys)


def parse_uri_path(input: StringColumn) -> StringColumn:
    """Spark ``parse_url(url, 'PATH')`` (ParseURI.java:94)."""
    return _run(input, _PATH)
