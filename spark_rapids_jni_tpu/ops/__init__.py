from spark_rapids_jni_tpu.ops.hashing import (
    murmur_hash32,
    xxhash64,
    DEFAULT_XXHASH64_SEED,
)
from spark_rapids_jni_tpu.ops.datetime_rebase import (
    rebase_gregorian_to_julian,
    rebase_julian_to_gregorian,
)
from spark_rapids_jni_tpu.ops.decimal128 import (
    multiply128,
    divide128,
    integer_divide128,
    remainder128,
    add128,
    subtract128,
)

from spark_rapids_jni_tpu.ops.histogram import (
    create_histogram_if_valid,
    percentile_from_histogram,
)
from spark_rapids_jni_tpu.ops.zorder import hilbert_index, interleave_bits

__all__ = [
    "create_histogram_if_valid",
    "percentile_from_histogram",
    "hilbert_index",
    "interleave_bits",
    "murmur_hash32",
    "rebase_gregorian_to_julian",
    "rebase_julian_to_gregorian",
    "xxhash64",
    "DEFAULT_XXHASH64_SEED",
    "multiply128",
    "divide128",
    "integer_divide128",
    "remainder128",
    "add128",
    "subtract128",
]
