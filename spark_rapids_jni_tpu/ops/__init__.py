from spark_rapids_jni_tpu.ops.hashing import (
    murmur_hash32,
    xxhash64,
    DEFAULT_XXHASH64_SEED,
)
from spark_rapids_jni_tpu.ops.bloom_filter import (
    BloomFilter,
    bloom_filter_create,
    bloom_filter_deserialize,
    bloom_filter_merge,
    bloom_filter_probe,
    bloom_filter_put,
    bloom_filter_serialize,
)
from spark_rapids_jni_tpu.ops.cast_string import (
    CastException,
    from_integers_with_base,
    string_to_decimal,
    string_to_integer,
    to_integers_with_base,
)
from spark_rapids_jni_tpu.ops.cast_string_to_float import string_to_float
from spark_rapids_jni_tpu.ops.datetime_rebase import (
    rebase_gregorian_to_julian,
    rebase_julian_to_gregorian,
)
from spark_rapids_jni_tpu.ops.decimal128 import (
    multiply128,
    divide128,
    integer_divide128,
    remainder128,
    add128,
    subtract128,
)

from spark_rapids_jni_tpu.ops.cast_decimal_to_string import decimal_to_string
from spark_rapids_jni_tpu.ops.float_to_string import float_to_string
from spark_rapids_jni_tpu.ops.format_float import format_float
from spark_rapids_jni_tpu.ops.histogram import (
    create_histogram_if_valid,
    percentile_from_histogram,
)
from spark_rapids_jni_tpu.ops.row_conversion import (
    convert_from_rows,
    convert_from_rows_fixed_width_optimized,
    convert_to_rows,
    convert_to_rows_fixed_width_optimized,
)
from spark_rapids_jni_tpu.ops.timezones import (
    TimeZoneDB,
    convert_timestamp_to_utc,
    convert_utc_timestamp_to_timezone,
)
from spark_rapids_jni_tpu.ops.regex_rewrite import literal_range_pattern
from spark_rapids_jni_tpu.ops.parse_uri import (
    parse_uri_host,
    parse_uri_path,
    parse_uri_protocol,
    parse_uri_query,
    parse_uri_query_column,
    parse_uri_query_literal,
)
from spark_rapids_jni_tpu.ops.zorder import hilbert_index, interleave_bits
from spark_rapids_jni_tpu.ops.from_json import JsonParsingException, from_json
from spark_rapids_jni_tpu.ops.get_json_object import (
    get_json_object,
    get_json_object_multiple_paths,
    parse_path,
)

__all__ = [
    "from_json",
    "get_json_object",
    "get_json_object_multiple_paths",
    "parse_path",
    "JsonParsingException",
    "literal_range_pattern",
    "parse_uri_host",
    "parse_uri_path",
    "parse_uri_protocol",
    "parse_uri_query",
    "parse_uri_query_column",
    "parse_uri_query_literal",
    "BloomFilter",
    "CastException",
    "from_integers_with_base",
    "string_to_decimal",
    "string_to_integer",
    "to_integers_with_base",
    "bloom_filter_create",
    "bloom_filter_deserialize",
    "bloom_filter_merge",
    "bloom_filter_probe",
    "bloom_filter_put",
    "bloom_filter_serialize",
    "create_histogram_if_valid",
    "percentile_from_histogram",
    "decimal_to_string",
    "float_to_string",
    "format_float",
    "string_to_float",
    "TimeZoneDB",
    "convert_from_rows",
    "convert_from_rows_fixed_width_optimized",
    "convert_to_rows",
    "convert_to_rows_fixed_width_optimized",
    "convert_timestamp_to_utc",
    "convert_utc_timestamp_to_timezone",
    "hilbert_index",
    "interleave_bits",
    "murmur_hash32",
    "rebase_gregorian_to_julian",
    "rebase_julian_to_gregorian",
    "xxhash64",
    "DEFAULT_XXHASH64_SEED",
    "multiply128",
    "divide128",
    "integer_divide128",
    "remainder128",
    "add128",
    "subtract128",
]

# Route every public op function through the dispatch seam — the boundary
# where the profiler records ranges and the fault injector may raise
# (obs/seam.py; the CUPTI-subscription analog, zero changes to op code).
import spark_rapids_jni_tpu.obs.faultinj as _faultinj  # noqa: E402
import spark_rapids_jni_tpu.obs.seam as _seam_mod  # noqa: E402

for _name in __all__:
    _fn = globals()[_name]
    if callable(_fn) and not isinstance(_fn, type):
        globals()[_name] = _seam_mod.instrument(_seam_mod.OP, _name)(_fn)
del _name, _fn

# CUDA_INJECTION64_PATH-style auto-arming via env var; a broken config must
# not make the library unimportable
try:
    _faultinj.install_from_env()
# analyze: ignore[retry-protocol] - import-time config parsing: no governor,
# no task, no bracket exists yet; breadth keeps the library importable
except Exception as _e:  # noqa: BLE001
    import warnings as _warnings

    _warnings.warn(
        f"fault injector config ({_faultinj.ENV_CONFIG_PATH}) ignored: {_e!r}",
        RuntimeWarning,
        stacklevel=2,
    )
