from spark_rapids_jni_tpu.ops.hashing import (
    murmur_hash32,
    xxhash64,
    DEFAULT_XXHASH64_SEED,
)

__all__ = [
    "murmur_hash32",
    "xxhash64",
    "DEFAULT_XXHASH64_SEED",
]
