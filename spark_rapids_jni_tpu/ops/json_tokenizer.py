"""Vectorized Spark-JSON tokenizer: byte rectangles -> validated token streams.

The shared front half of ``get_json_object`` and ``from_json``.  The reference
parses per row with a sequential pushdown parser
(/root/reference/src/main/cpp/src/json_parser.cuh:220, one GPU thread per
row); on TPU that serializes, so tokenization is re-architected as dense
whole-rectangle passes over a length bucket's ``[rows, width]`` byte matrix:

1. **String-context automaton** — 5 states (outside / in-double-quote /
   dq-escape / in-single-quote / sq-escape) composed over the byte axis with
   ``lax.associative_scan`` over transition *functions* (state maps composed
   by gather), giving every byte its string context in O(log width) passes.
2. **Number DFA** — the grammar of ``json_parser.cuh`` ``try_parse_number``
   (leading-zero rejection, ``.`` needs digits both sides, exponent needs
   digits; a valid prefix followed by junk splits into value + junk token,
   which reproduces the root-level trailing-garbage tolerance of
   json_parser.cuh:1250-1254) — also a composed-function scan, with resets
   at token starts.
3. **Token compaction** — token-start bytes get ranks by row cumsum and
   scatter into dense ``[rows, T]`` token arrays.
4. **Grammar scan** — one ``lax.scan`` over token steps, all rows in
   lockstep: enforces the object/array separator grammar of
   ``json_parser.cuh`` ``next_token``, bounds nesting at
   ``MAX_DEPTH=64`` (json_parser.cuh:46), records FIELD_NAME context,
   matches open/close pairs (the evaluator's O(1) skip_children), and finds
   the root-value end so trailing garbage is ignored.

Spark quirks preserved (same set as tests/json_oracle.py): single-quoted
strings, raw control chars legal inside strings, ``\\uXXXX`` must be 4 hex
digits, numbers reject leading zeros and bare ``.5``/``5.``, at most
MAX_NUM_LEN digits, root-level trailing garbage after a complete value is
ignored.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["TokenStream", "tokenize", "MAX_DEPTH", "MAX_NUM_LEN"]

MAX_DEPTH = 64  # json_parser.cuh:46 max_json_nesting_depth
MAX_NUM_LEN = 1000  # json_parser.cuh max_num_len

# token kinds (aligned with tests/json_oracle.py)
ERRORTOK = 1
START_OBJECT, END_OBJECT, START_ARRAY, END_ARRAY = 3, 4, 5, 6
FIELD_NAME, VALUE_STRING = 7, 8
VALUE_NUMBER_INT, VALUE_NUMBER_FLOAT = 9, 10
VALUE_TRUE, VALUE_FALSE, VALUE_NULL = 11, 12, 13
COMMA, COLON = 14, 15  # internal: validated then dropped
PAD = 0

_I32 = jnp.int32
_I8 = jnp.int8
_U8 = jnp.uint8

# string automaton states
_S_OUT, _S_DQ, _S_DQE, _S_SQ, _S_SQE = 0, 1, 2, 3, 4

# number DFA states
_N_IDLE, _N_NEG, _N_ZERO, _N_INT, _N_DOT, _N_FRAC = 0, 1, 2, 3, 4, 5
_N_EXP, _N_EXPS, _N_EXPD, _N_DONE, _N_ERR = 6, 7, 8, 9, 10

# grammar expect states
_E_VALUE = 0
_E_FIELD_OR_CLOSE = 1
_E_COLON = 2
_E_COMMA_OR_CLOSE_OBJ = 3
_E_FIELD = 4
_E_COMMA_OR_CLOSE_ARR = 5
_E_VALUE_OR_CLOSE = 6


@dataclasses.dataclass
class TokenStream:
    """Validated, separator-free token stream for one length bucket.

    ``kind[r, t]`` is PAD beyond ``n_tokens[r]``.  ``start``/``end`` are byte
    spans into the bucket's byte matrix (strings include their quotes).
    ``match[r, t]`` is the index of the matching close for START_* tokens
    (self otherwise).  ``ok[r]`` is False for malformed rows (entire row ->
    NULL downstream).
    """

    kind: jnp.ndarray  # uint8 [n, T]
    start: jnp.ndarray  # int32 [n, T]
    end: jnp.ndarray  # int32 [n, T]
    match: jnp.ndarray  # int32 [n, T]
    n_tokens: jnp.ndarray  # int32 [n]
    ok: jnp.ndarray  # bool [n]
    trailing: jnp.ndarray  # bool [n]: tokens existed after the root value
    # reusable byte-analysis product: string-automaton state AFTER each byte
    # ([n, L] int32).  The escape/unescape byte tables (host _byte_info and
    # the device DByteInfo) need exactly this matrix, so exposing it here
    # lets every downstream consumer — including multi-path extraction,
    # which fans one token stream out to P machines — skip a second
    # automaton pass over the bytes.
    str_state: Optional[jnp.ndarray] = None


def _pow2_at_least(x: int) -> int:
    return 1 << max(int(x) - 1, 0).bit_length() if x > 1 else 1


_ONEHOT_GATHERS = None  # resolved lazily: backend known only after jax init


def _use_onehot_gathers() -> bool:
    """One-hot compare-and-reduce beats dynamic gathers on TPU lanes
    (round-5 profile: 1.85 s vs 54 ms at n=2^18), but the inverse holds on
    XLA:CPU, where the one-hot materializes an [n, K, W] intermediate that
    a real gather never builds (measured 10 s vs 0.8 s for _scan_bytes at
    n=2^14, L=128 on the virtual CPU mesh).  Resolved once per process —
    the backend cannot change under a running session."""
    global _ONEHOT_GATHERS
    if _ONEHOT_GATHERS is None:
        _ONEHOT_GATHERS = jax.default_backend() != "cpu"
    return _ONEHOT_GATHERS


def _compose_scan(maps: jnp.ndarray) -> jnp.ndarray:
    """Inclusive prefix composition of per-byte state maps along axis 1.

    ``maps[r, i, s]`` = next state from ``s`` on byte i.  Returns
    ``state_after[r, i]`` starting from state 0.
    """

    S = maps.shape[-1]

    if _use_onehot_gathers():
        def comb(a, b):  # apply a, then b: result[..., s] = b[..., a[..., s]]
            # select-sum over the tiny state axis instead of a per-element
            # gather — dynamic gathers scalarize on TPU (round-5 profile:
            # this combiner dominated the byte-analysis pass)
            sel = a[..., :, None] == jnp.arange(S, dtype=_I8)
            return jnp.where(sel, b[..., None, :], _I8(0)).sum(-1).astype(_I8)
    else:
        def comb(a, b):
            return jnp.take_along_axis(
                b, a.astype(jnp.int32), axis=-1).astype(_I8)

    pref = jax.lax.associative_scan(comb, maps, axis=1)
    return pref[..., 0].astype(_I32)


def _take_rows(arr, idx):
    """``arr[i, idx[i, w]]`` for arr [n, K], idx [n, W] (pre-clipped).

    On TPU: one-hot compare-and-reduce instead of a 2-D advanced-index
    gather — per-row dynamic gathers scalarize there (measured 1.85 s vs
    54 ms at n=2^18, K=126, W=250 on the v5e); XLA fuses the select-reduce.
    On CPU the one-hot's [n, K, W] intermediate dominates instead, so the
    real gather is used.  Shared with json_render_device and json_scan.
    """
    if not _use_onehot_gathers():
        return jnp.take_along_axis(arr, idx.astype(jnp.int32), axis=1)
    K = arr.shape[1]
    ks = jnp.arange(K, dtype=jnp.int32)
    sel = idx[:, None, :] == ks[None, :, None]
    return jnp.where(sel, arr[:, :, None], 0).sum(axis=1).astype(arr.dtype)


def _next_pos(mask: jnp.ndarray, big: int) -> jnp.ndarray:
    """For each i: smallest j >= i with mask[j], else ``big`` (per row)."""
    L = mask.shape[1]
    pos = jnp.arange(L, dtype=_I32)[None, :]
    cand = jnp.where(mask, pos, _I32(big))
    return jax.lax.cummin(cand, axis=1, reverse=True)


def _string_automaton(b, in_row):
    """state_after[r, i] of the 5-state string-context machine."""
    n, L = b.shape
    is_dq = b == ord('"')
    is_sq = b == ord("'")
    is_bs = b == ord("\\")

    maps = jnp.empty((n, L, 5), dtype=_I8)
    frm_out = jnp.where(is_dq, _S_DQ, jnp.where(is_sq, _S_SQ, _S_OUT))
    frm_dq = jnp.where(is_bs, _S_DQE, jnp.where(is_dq, _S_OUT, _S_DQ))
    frm_sq = jnp.where(is_bs, _S_SQE, jnp.where(is_sq, _S_OUT, _S_SQ))
    maps = maps.at[..., _S_OUT].set(frm_out.astype(_I8))
    maps = maps.at[..., _S_DQ].set(frm_dq.astype(_I8))
    maps = maps.at[..., _S_DQE].set(_I8(_S_DQ))
    maps = maps.at[..., _S_SQ].set(frm_sq.astype(_I8))
    maps = maps.at[..., _S_SQE].set(_I8(_S_SQ))
    ident = jnp.broadcast_to(jnp.arange(5, dtype=_I8), (n, L, 5))
    maps = jnp.where(in_row[..., None], maps, ident)
    return _compose_scan(maps)


def _number_dfa(b, run_start, in_num_run):
    """state_after of the number grammar DFA, reset at each run start."""
    n, L = b.shape
    is_d0 = b == ord("0")
    is_d19 = (b >= ord("1")) & (b <= ord("9"))
    is_dig = is_d0 | is_d19
    is_minus = b == ord("-")
    is_plus = b == ord("+")
    is_dot = b == ord(".")
    is_e = (b == ord("e")) | (b == ord("E"))

    def mk(*pairs):
        """byte-class -> state selector, default ERR."""
        out = jnp.full(b.shape, _N_ERR, dtype=_I8)
        for cls, st in reversed(pairs):
            out = jnp.where(cls, _I8(st), out)
        return out

    # transition rows (what each current state maps to on this byte)
    t = {}
    t[_N_IDLE] = jnp.full(b.shape, _N_IDLE, dtype=_I8)
    t[_N_NEG] = mk((is_d0, _N_ZERO), (is_d19, _N_INT))
    t[_N_ZERO] = mk(
        (is_dot, _N_DOT), (is_e, _N_EXP),
        (is_dig, _N_ERR), (~is_dig, _N_DONE),
    )
    t[_N_INT] = mk(
        (is_dig, _N_INT), (is_dot, _N_DOT), (is_e, _N_EXP),
        (~is_dig, _N_DONE),
    )
    t[_N_DOT] = mk((is_dig, _N_FRAC))
    t[_N_FRAC] = mk((is_dig, _N_FRAC), (is_e, _N_EXP), (~is_dig, _N_DONE))
    t[_N_EXP] = mk((is_dig, _N_EXPD), (is_minus | is_plus, _N_EXPS))
    t[_N_EXPS] = mk((is_dig, _N_EXPD))
    t[_N_EXPD] = mk((is_dig, _N_EXPD), (~is_dig, _N_DONE))
    t[_N_DONE] = jnp.full(b.shape, _N_DONE, dtype=_I8)
    t[_N_ERR] = jnp.full(b.shape, _N_ERR, dtype=_I8)

    maps = jnp.stack([t[s] for s in range(11)], axis=-1)

    # at a run start, the map is constant: state after the FIRST char from S0
    first = mk((is_minus, _N_NEG), (is_d0, _N_ZERO), (is_d19, _N_INT))
    maps = jnp.where(run_start[..., None], first[..., None], maps)
    # outside number runs: identity (state parks until next run)
    ident = jnp.broadcast_to(jnp.arange(11, dtype=_I8), (n, L, 11))
    maps = jnp.where((in_num_run | run_start)[..., None], maps, ident)
    return _compose_scan(maps)


_ESC_OK = np.zeros(256, dtype=bool)
for _c in b"\"'\\/bfnrtu":
    _ESC_OK[_c] = True


def _is_hex(b):
    return (
        ((b >= ord("0")) & (b <= ord("9")))
        | ((b >= ord("a")) & (b <= ord("f")))
        | ((b >= ord("A")) & (b <= ord("F")))
    )


def tokenize(bytes_mat: jnp.ndarray, lens: jnp.ndarray) -> TokenStream:
    """Tokenize one bucket's ``[n, L]`` byte matrix into a TokenStream.

    Two jitted stages (cached per shape): a byte-analysis pass, then — after
    one host sync for the max token count (rounded to a power of two so the
    compiled-variant set stays bounded) — compaction + the grammar scan.
    """
    n, L = bytes_mat.shape
    token_start, kind_b, end_b, counts, st_after = _scan_bytes(bytes_mat, lens)
    T = _pow2_at_least(int(jnp.max(counts)) if n else 0)
    if _use_onehot_gathers():
        res = _compact_and_grammar(token_start, kind_b, end_b, counts, T)
    else:
        # XLA:CPU: the T-step lax.scan is dispatch-bound (~60 tiny kernels
        # per step) and the dense compaction scatter pays for every byte;
        # the numpy twins run the identical grammar with microsecond
        # dispatch, scatter only the actual tokens, and really exit at the
        # last live token instead of stepping all T
        tok = _compact_tokens_np(np.asarray(token_start), np.asarray(kind_b),
                                 np.asarray(end_b), T)
        res = _grammar_scan_np(*tok, np.asarray(counts))
    return TokenStream(*res, str_state=st_after)


@jax.jit
def _scan_bytes(bytes_mat: jnp.ndarray, lens: jnp.ndarray):
    """Per-byte analysis: token starts, kinds, and end positions."""
    n, L = bytes_mat.shape
    b = bytes_mat
    lens = lens.astype(_I32)
    pos = jnp.arange(L, dtype=_I32)[None, :]
    in_row = pos < lens[:, None]
    BIG = L + 1

    # ---- phase 1: string context ----------------------------------------
    st_after = _string_automaton(b, in_row)
    st_before = jnp.pad(st_after, ((0, 0), (1, 0)))[:, :L]

    is_open_q = (st_before == _S_OUT) & ((b == ord('"')) | (b == ord("'"))) & in_row
    is_close_q = (
        ((st_before == _S_DQ) & (b == ord('"')))
        | ((st_before == _S_SQ) & (b == ord("'")))
    ) & in_row
    outside = (st_before == _S_OUT) & ~is_open_q & in_row
    escaped_char = ((st_before == _S_DQE) | (st_before == _S_SQE)) & in_row

    # escape validity: escaped char must be legal; \\u needs 4 in-row hex
    esc_ok_lut = jnp.asarray(_ESC_OK)
    bad_esc = escaped_char & ~esc_ok_lut[b.astype(_I32)]
    is_u = escaped_char & (b == ord("u"))
    hex_ok = _is_hex(b) & in_row
    u_ok = jnp.ones((n, L), dtype=bool)
    for k in range(1, 5):
        shifted = jnp.pad(hex_ok, ((0, 0), (0, k)))[:, k : L + k]
        u_ok = u_ok & shifted
    bad_esc = bad_esc | (is_u & ~u_ok)
    next_bad_esc = _next_pos(bad_esc, BIG)
    next_close = _next_pos(is_close_q, BIG)

    # ---- phase 2: structural & runs -------------------------------------
    is_ws = ((b == 0x20) | (b == 0x09) | (b == 0x0A) | (b == 0x0D)) & in_row
    is_struct = (
        (b == ord("{")) | (b == ord("}")) | (b == ord("["))
        | (b == ord("]")) | (b == ord(",")) | (b == ord(":"))
    ) & outside
    run_byte = outside & ~is_ws & ~is_struct
    prev_run = jnp.pad(run_byte, ((0, 0), (1, 0)))[:, :L]
    run_start = run_byte & ~prev_run
    next_nonrun = _next_pos(~run_byte, BIG)  # first i >= here not in a run

    # ---- phase 3: number DFA + literals ---------------------------------
    nstate = _number_dfa(b, run_start, run_byte)
    nstate_prev = jnp.pad(nstate, ((0, 0), (1, 0)))[:, :L]
    done_entry = (nstate == _N_DONE) & (nstate_prev != _N_DONE) & run_byte

    def match_word(word):
        ok = jnp.ones((n, L), dtype=bool)
        for k, ch in enumerate(word):
            shifted = jnp.pad(b, ((0, 0), (0, k)), constant_values=0)[:, k : L + k]
            ok = ok & (shifted == ch) & jnp.pad(
                in_row, ((0, 0), (0, k))
            )[:, k : L + k]
        return ok

    true_at = match_word(b"true")
    false_at = match_word(b"false")
    null_at = match_word(b"null")

    def shift_right(mask, k):
        return jnp.pad(mask, ((0, 0), (k, 0)))[:, :L]

    lit_junk = (
        (shift_right(run_start & true_at, 4) | shift_right(run_start & null_at, 4))
        | shift_right(run_start & false_at, 5)
    ) & run_byte

    token_start = is_struct | is_open_q | run_start | done_entry | lit_junk

    # ---- phase 4: per-start kind/end ------------------------------------
    first_c = b
    is_digit_start = (first_c == ord("-")) | (
        (first_c >= ord("0")) & (first_c <= ord("9"))
    )
    # number value end: first DONE entry or run end
    next_done = _next_pos(done_entry, BIG)
    run_end = next_nonrun
    num_value_end = jnp.minimum(next_done, run_end)
    # number final state: state at value_end - 1
    vend_idx = jnp.clip(num_value_end - 1, 0, L - 1)
    num_final = _take_rows(nstate, vend_idx)
    num_valid = (
        (num_final == _N_ZERO) | (num_final == _N_INT)
        | (num_final == _N_FRAC) | (num_final == _N_EXPD)
        | (num_final == _N_DONE)
    )
    # digit count <= MAX_NUM_LEN over the value span
    is_digit_b = (b >= ord("0")) & (b <= ord("9"))
    dcum = jnp.cumsum((is_digit_b & in_row).astype(_I32), axis=1)
    dcum_at = lambda idx: _take_rows(  # noqa: E731
        jnp.pad(dcum, ((0, 0), (1, 0))), jnp.clip(idx, 0, L)
    )
    ndigits = dcum_at(num_value_end) - dcum_at(pos)
    num_valid = num_valid & (ndigits <= MAX_NUM_LEN)
    # float if '.' or e/E inside the value span
    dot_e = ((b == ord(".")) | (b == ord("e")) | (b == ord("E"))) & in_row
    decum = jnp.cumsum(dot_e.astype(_I32), axis=1)
    decum_at = lambda idx: _take_rows(  # noqa: E731
        jnp.pad(decum, ((0, 0), (1, 0))), jnp.clip(idx, 0, L)
    )
    num_is_float = (decum_at(num_value_end) - decum_at(pos)) > 0

    # string token: end & validity
    str_close = next_close  # first close at/after the open (open isn't one)
    str_end = str_close + 1
    str_bad = (str_close >= BIG - 1) | (next_bad_esc < str_close)

    struct_kind = jnp.where(
        b == ord("{"), START_OBJECT,
        jnp.where(
            b == ord("}"), END_OBJECT,
            jnp.where(
                b == ord("["), START_ARRAY,
                jnp.where(
                    b == ord("]"), END_ARRAY,
                    jnp.where(b == ord(","), COMMA, COLON),
                ),
            ),
        ),
    )

    lit_kind = jnp.where(
        true_at, VALUE_TRUE, jnp.where(false_at, VALUE_FALSE, VALUE_NULL)
    )
    lit_match = true_at | false_at | null_at
    lit_len = jnp.where(false_at, 5, 4)

    num_kind = jnp.where(
        num_valid,
        jnp.where(num_is_float, VALUE_NUMBER_FLOAT, VALUE_NUMBER_INT),
        ERRORTOK,
    )

    kind_b = jnp.where(
        is_struct,
        struct_kind,
        jnp.where(
            is_open_q,
            jnp.where(str_bad, ERRORTOK, VALUE_STRING),
            jnp.where(
                done_entry | lit_junk,
                ERRORTOK,
                jnp.where(
                    is_digit_start,
                    num_kind,
                    jnp.where(lit_match, lit_kind, ERRORTOK),
                ),
            ),
        ),
    )
    end_b = jnp.where(
        is_struct,
        pos + 1,
        jnp.where(
            is_open_q,
            str_end,
            jnp.where(
                done_entry | lit_junk,
                run_end,
                jnp.where(
                    is_digit_start,
                    num_value_end,
                    jnp.where(lit_match, pos + lit_len, run_end),
                ),
            ),
        ),
    )

    counts = jnp.sum(token_start.astype(_I32), axis=1)
    return (token_start, kind_b.astype(_I32), end_b.astype(_I32), counts,
            st_after.astype(_I32))


# twin: compact_tokens
@functools.partial(jax.jit, static_argnums=(4,))
def _compact_tokens(token_start, kind_b, end_b, counts, T: int):
    """Phase 5: scatter token-start bytes into dense [n, T] token arrays."""
    n, L = token_start.shape
    pos = jnp.arange(L, dtype=_I32)[None, :]
    rank = jnp.cumsum(token_start.astype(_I32), axis=1) - 1

    rows2d = jnp.broadcast_to(jnp.arange(n, dtype=_I32)[:, None], (n, L))
    tgt_row = jnp.where(token_start, rows2d, n)
    tgt_tok = jnp.where(token_start, jnp.minimum(rank, T - 1), 0)

    def compact(vals, fill):
        out = jnp.full((n + 1, T), fill, dtype=vals.dtype)
        out = out.at[tgt_row, tgt_tok].set(
            jnp.where(token_start, vals, fill), mode="drop"
        )
        return out[:n]

    tok_kind = compact(kind_b.astype(_U8), _U8(PAD))
    tok_start = compact(pos + jnp.zeros_like(rank), _I32(0))
    tok_end = compact(end_b.astype(_I32), _I32(0))
    return tok_kind, tok_start, tok_end


# twin: compact_tokens
def _compact_tokens_np(token_start, kind_b, end_b, T: int):
    """Numpy twin of :func:`_compact_tokens`: scatters only the ~nnz token
    starts instead of every byte (CPU backend; outputs are identical)."""
    n, L = token_start.shape
    ri, li = np.nonzero(token_start)
    rank = np.cumsum(token_start, axis=1) - 1
    ci = np.minimum(rank[ri, li], T - 1)
    tok_kind = np.full((n, T), PAD, np.uint8)
    tok_start = np.zeros((n, T), np.int32)
    tok_end = np.zeros((n, T), np.int32)
    tok_kind[ri, ci] = kind_b[ri, li]
    tok_start[ri, ci] = li
    tok_end[ri, ci] = end_b[ri, li]
    return tok_kind, tok_start, tok_end


@functools.partial(jax.jit, static_argnums=(4,))
def _compact_and_grammar(token_start, kind_b, end_b, counts, T: int):
    """Phase 5 compaction + phase 6 grammar scan (static token capacity),
    fused in one jit for accelerator backends."""
    tok_kind, tok_start, tok_end = _compact_tokens(
        token_start, kind_b, end_b, counts, T)
    return _grammar_scan(tok_kind, tok_start, tok_end, counts)


# twin: grammar_scan
def _grammar_scan(kind, start, end, counts):
    """Lockstep grammar validation + match computation + separator drop."""
    n, T = kind.shape

    def step(carry, t):
        depth, ctx, open_stack, expect, err, done = carry
        k = kind[:, t].astype(_I32)
        active = ~done & ~err & (t < counts)

        is_scalar = (
            (k == VALUE_STRING) | (k == VALUE_NUMBER_INT)
            | (k == VALUE_NUMBER_FLOAT) | (k == VALUE_TRUE)
            | (k == VALUE_FALSE) | (k == VALUE_NULL)
        )
        is_open_obj = k == START_OBJECT
        is_open_arr = k == START_ARRAY
        is_close_obj = k == END_OBJECT
        is_close_arr = k == END_ARRAY
        is_comma = k == COMMA
        is_colon = k == COLON

        exp_value = (expect == _E_VALUE) | (expect == _E_VALUE_OR_CLOSE)

        # legal moves
        take_scalar = exp_value & is_scalar
        take_open = exp_value & (is_open_obj | is_open_arr)
        take_field = (
            ((expect == _E_FIELD_OR_CLOSE) | (expect == _E_FIELD))
            & (k == VALUE_STRING)
        )
        take_colon = (expect == _E_COLON) & is_colon
        take_comma_obj = (expect == _E_COMMA_OR_CLOSE_OBJ) & is_comma
        take_comma_arr = (expect == _E_COMMA_OR_CLOSE_ARR) & is_comma
        take_close_obj = (
            ((expect == _E_FIELD_OR_CLOSE) | (expect == _E_COMMA_OR_CLOSE_OBJ))
            & is_close_obj
        )
        take_close_arr = (
            ((expect == _E_VALUE_OR_CLOSE) | (expect == _E_COMMA_OR_CLOSE_ARR))
            & is_close_arr
        )
        take_close = take_close_obj | take_close_arr
        legal = (
            take_scalar | take_open | take_field | take_colon
            | take_comma_obj | take_comma_arr | take_close
        )
        overflow = take_open & (depth >= MAX_DEPTH)
        new_err = err | (active & (~legal | overflow))
        do = active & legal & ~overflow

        # stack ops
        push = do & take_open
        pop = do & take_close
        depth2 = depth + push.astype(_I32) - pop.astype(_I32)
        sel = jnp.clip(depth, 0, MAX_DEPTH - 1)
        ctx2 = jnp.where(
            push[:, None]
            & (jnp.arange(MAX_DEPTH, dtype=_I32)[None, :] == sel[:, None]),
            is_open_obj[:, None],
            ctx,
        )
        open_stack2 = jnp.where(
            push[:, None]
            & (jnp.arange(MAX_DEPTH, dtype=_I32)[None, :] == sel[:, None]),
            _I32(t),
            open_stack,
        )
        # matching open for a close: top of stack
        sel_pop = jnp.clip(depth2, 0, MAX_DEPTH - 1)
        popped_open = _take_rows(open_stack, sel_pop[:, None])[:, 0]
        close_rec = jnp.where(pop, popped_open, _I32(-1))
        # close type must match container
        popped_is_obj = _take_rows(ctx, sel_pop[:, None])[:, 0]
        mismatch = pop & (popped_is_obj != is_close_obj)
        new_err = new_err | mismatch
        do = do & ~mismatch
        pop = pop & ~mismatch
        depth2 = jnp.where(mismatch, depth, depth2)

        # value completion (scalar or close) -> what next
        completed = do & (take_scalar | pop)
        at_root = completed & (depth2 == 0)
        done2 = done | at_root
        # parent context for non-root completion
        parent_sel = jnp.clip(depth2 - 1, 0, MAX_DEPTH - 1)
        parent_obj = _take_rows(ctx2, parent_sel[:, None])[:, 0]
        after_value = jnp.where(
            parent_obj, _E_COMMA_OR_CLOSE_OBJ, _E_COMMA_OR_CLOSE_ARR
        )

        expect2 = jnp.where(
            completed & ~at_root, after_value,
            jnp.where(
                do & take_open & is_open_obj, _E_FIELD_OR_CLOSE,
                jnp.where(
                    do & take_open & is_open_arr, _E_VALUE_OR_CLOSE,
                    jnp.where(
                        do & take_field, _E_COLON,
                        jnp.where(
                            do & take_colon, _E_VALUE,
                            jnp.where(
                                do & take_comma_obj, _E_FIELD,
                                jnp.where(
                                    do & take_comma_arr, _E_VALUE, expect
                                ),
                            ),
                        ),
                    ),
                ),
            ),
        )
        is_field_tok = do & take_field

        ys = (is_field_tok, close_rec, done)
        return (depth2, ctx2, open_stack2, expect2, new_err, done2), ys

    init = (
        jnp.zeros((n,), _I32),
        jnp.zeros((n, MAX_DEPTH), dtype=bool),
        jnp.zeros((n, MAX_DEPTH), _I32),
        jnp.full((n,), _E_VALUE, dtype=_I32),
        jnp.zeros((n,), dtype=bool),
        jnp.zeros((n,), dtype=bool),
    )
    (depth, ctx, open_stack, expect, err, done), (
        is_field, close_rec, done_before
    ) = jax.lax.scan(step, init, jnp.arange(T))

    is_field = is_field.T  # [n, T]
    close_rec = close_rec.T
    done_before = done_before.T  # done flag BEFORE processing token t

    ok = done & ~err  # err can only be set while not done

    # reclassify FIELD_NAMEs
    kind = jnp.where(is_field, _U8(FIELD_NAME), kind)
    # match indices: match[open] = close step, match[close] = open, else self
    tok_idx = jnp.broadcast_to(jnp.arange(T, dtype=_I32)[None, :], (n, T))
    match = tok_idx
    rows2d = jnp.broadcast_to(jnp.arange(n, dtype=_I32)[:, None], (n, T))
    has_close = close_rec >= 0
    match = match.at[
        jnp.where(has_close, rows2d, n), jnp.where(has_close, close_rec, 0)
    ].set(tok_idx, mode="drop")
    match = jnp.where(has_close, close_rec, match)

    # keep only value/structure tokens up to the root end
    keep = (
        ~done_before
        & (kind != _U8(COMMA))
        & (kind != _U8(COLON))
        & (kind != _U8(PAD))
        & (tok_idx < counts[:, None])
    )
    new_idx = jnp.cumsum(keep.astype(_I32), axis=1) - 1
    n_tokens = jnp.sum(keep.astype(_I32), axis=1)
    T2 = T  # static upper bound: keeps the whole pipeline inside one jit

    def compact(vals, fill):
        out = jnp.full((n + 1, T2), fill, dtype=vals.dtype)
        out = out.at[
            jnp.where(keep, rows2d, n), jnp.where(keep, jnp.minimum(new_idx, T2 - 1), 0)
        ].set(jnp.where(keep, vals, fill), mode="drop")
        return out[:n]

    kind2 = compact(kind, _U8(PAD))
    start2 = compact(start, _I32(0))
    end2 = compact(end, _I32(0))
    # remap match through new indices (clip: matches of dropped tokens unused)
    match_new = _take_rows(new_idx, jnp.clip(match, 0, T - 1))
    match2 = compact(match_new, _I32(0))

    trailing = jnp.any(done_before & (tok_idx < counts[:, None]), axis=1)
    return kind2, start2, end2, match2, n_tokens, ok, trailing


# twin: grammar_scan
def _grammar_scan_np(kind, start, end, counts):
    """Numpy twin of :func:`_grammar_scan` for the CPU backend.

    Identical grammar, identical outputs (the whole JSON test tier runs on
    the CPU mesh, so any divergence fails corpus/fuzz/parity tests); the
    wins over the lax.scan form are microsecond op dispatch and a real
    early exit at the last live token instead of T fixed steps.
    """
    n, T = kind.shape
    rows = np.arange(n, dtype=np.int32)
    depth = np.zeros((n,), np.int32)
    ctx = np.zeros((n, MAX_DEPTH), bool)
    open_stack = np.zeros((n, MAX_DEPTH), np.int32)
    expect = np.full((n,), _E_VALUE, np.int32)
    err = np.zeros((n,), bool)
    done = np.zeros((n,), bool)
    is_field = np.zeros((n, T), bool)
    close_rec = np.full((n, T), -1, np.int32)
    done_before = np.zeros((n, T), bool)

    for t in range(T):
        done_before[:, t] = done
        active = ~done & ~err & (t < counts)
        if not active.any():
            done_before[:, t:] = done[:, None]
            break
        k = kind[:, t].astype(np.int32)

        is_scalar = (
            (k == VALUE_STRING) | (k == VALUE_NUMBER_INT)
            | (k == VALUE_NUMBER_FLOAT) | (k == VALUE_TRUE)
            | (k == VALUE_FALSE) | (k == VALUE_NULL)
        )
        is_open_obj = k == START_OBJECT
        is_open_arr = k == START_ARRAY
        is_close_obj = k == END_OBJECT
        is_close_arr = k == END_ARRAY
        is_comma = k == COMMA
        is_colon = k == COLON

        exp_value = (expect == _E_VALUE) | (expect == _E_VALUE_OR_CLOSE)

        take_scalar = exp_value & is_scalar
        take_open = exp_value & (is_open_obj | is_open_arr)
        take_field = (
            ((expect == _E_FIELD_OR_CLOSE) | (expect == _E_FIELD))
            & (k == VALUE_STRING)
        )
        take_colon = (expect == _E_COLON) & is_colon
        take_comma_obj = (expect == _E_COMMA_OR_CLOSE_OBJ) & is_comma
        take_comma_arr = (expect == _E_COMMA_OR_CLOSE_ARR) & is_comma
        take_close_obj = (
            ((expect == _E_FIELD_OR_CLOSE) | (expect == _E_COMMA_OR_CLOSE_OBJ))
            & is_close_obj
        )
        take_close_arr = (
            ((expect == _E_VALUE_OR_CLOSE) | (expect == _E_COMMA_OR_CLOSE_ARR))
            & is_close_arr
        )
        take_close = take_close_obj | take_close_arr
        legal = (
            take_scalar | take_open | take_field | take_colon
            | take_comma_obj | take_comma_arr | take_close
        )
        overflow = take_open & (depth >= MAX_DEPTH)
        err = err | (active & (~legal | overflow))
        do = active & legal & ~overflow

        push = do & take_open
        pop = do & take_close
        depth2 = depth + push.astype(np.int32) - pop.astype(np.int32)
        sel = np.clip(depth, 0, MAX_DEPTH - 1)
        pr = np.nonzero(push)[0]
        # matching open for a close: top of stack (read BEFORE this push)
        sel_pop = np.clip(depth2, 0, MAX_DEPTH - 1)
        popped_open = open_stack[rows, sel_pop]
        popped_is_obj = ctx[rows, sel_pop]
        ctx[pr, sel[pr]] = is_open_obj[pr]
        open_stack[pr, sel[pr]] = t
        # recorded PRE-mismatch-filter, exactly like the lax.scan form (the
        # row errs anyway; keeping the record keeps ts.match bit-identical)
        close_rec[:, t] = np.where(pop, popped_open, -1)
        mismatch = pop & (popped_is_obj != is_close_obj)
        err = err | mismatch
        do = do & ~mismatch
        pop = pop & ~mismatch
        depth2 = np.where(mismatch, depth, depth2)

        completed = do & (take_scalar | pop)
        at_root = completed & (depth2 == 0)
        done = done | at_root
        parent_sel = np.clip(depth2 - 1, 0, MAX_DEPTH - 1)
        parent_obj = ctx[rows, parent_sel]
        after_value = np.where(
            parent_obj, _E_COMMA_OR_CLOSE_OBJ, _E_COMMA_OR_CLOSE_ARR
        )

        expect = np.where(
            completed & ~at_root, after_value,
            np.where(
                do & take_open & is_open_obj, _E_FIELD_OR_CLOSE,
                np.where(
                    do & take_open & is_open_arr, _E_VALUE_OR_CLOSE,
                    np.where(
                        do & take_field, _E_COLON,
                        np.where(
                            do & take_colon, _E_VALUE,
                            np.where(
                                do & take_comma_obj, _E_FIELD,
                                np.where(
                                    do & take_comma_arr, _E_VALUE, expect
                                ),
                            ),
                        ),
                    ),
                ),
            ),
        ).astype(np.int32)
        is_field[:, t] = do & take_field
        depth = depth2

    ok = done & ~err  # err can only be set while not done

    kind = np.where(is_field, np.uint8(FIELD_NAME), kind)
    tok_idx = np.broadcast_to(np.arange(T, dtype=np.int32)[None, :], (n, T))
    match = tok_idx.copy()
    ri, ti = np.nonzero(close_rec >= 0)
    match[ri, close_rec[ri, ti]] = ti
    has_close = close_rec >= 0
    match = np.where(has_close, close_rec, match)

    keep = (
        ~done_before
        & (kind != np.uint8(COMMA))
        & (kind != np.uint8(COLON))
        & (kind != np.uint8(PAD))
        & (tok_idx < counts[:, None])
    )
    new_idx = np.cumsum(keep.astype(np.int32), axis=1) - 1
    n_tokens = np.sum(keep.astype(np.int32), axis=1)

    ri, ti = np.nonzero(keep)
    ci = new_idx[ri, ti]

    def compact(vals, fill, dtype):
        out = np.full((n, T), fill, dtype=dtype)
        out[ri, ci] = vals[ri, ti]
        return out

    kind2 = compact(kind, PAD, np.uint8)
    start2 = compact(np.asarray(start), 0, np.int32)
    end2 = compact(np.asarray(end), 0, np.int32)
    match_new = new_idx[rows[:, None], np.clip(match, 0, T - 1)]
    match2 = compact(match_new, 0, np.int32)

    trailing = np.any(done_before & (tok_idx < counts[:, None]), axis=1)
    return (jnp.asarray(kind2), jnp.asarray(start2), jnp.asarray(end2),
            jnp.asarray(match2), jnp.asarray(n_tokens), jnp.asarray(ok),
            jnp.asarray(trailing))
