"""Java ``Double.toString`` / ``Float.toString`` — vectorized Ryu on TPU.

Capability parity with the reference's device Ryu port (ftos_converter.cuh:
d2d :480, f2d :575, to_chars :797/:922, special strings :259; driver
cast_float_to_string.cu:34-128): the shortest decimal representation that
round-trips, formatted per the Java spec — plain notation in [1e-3, 1e7),
scientific ``d.dddE±x`` otherwise, ``NaN`` / ``Infinity`` / ``-0.0`` specials.

The reference runs scalar Ryu per GPU thread.  Here every step is lane
arithmetic over the whole column:

- 128-bit multiplies decompose into 32-bit limb products in uint64 lanes
  (_umul128), with per-lane variable shifts;
- the power-of-5 tables are exact-precomputed host arrays (utils.ryu_tables)
  gathered per element;
- Ryu's shortest-search loop has a bounded trip count (<= 22 digit removals),
  so it unrolls into masked iterations;
- character emission is a batch scatter of (row, position) pairs into a padded
  byte matrix, rebuilt into an Arrow StringColumn.

Round 20 layers the get_json_object playbook on top (the BENCH_r09
0.08 Mrows/s straggler):

- **value-class buckets** (columnar/buckets.class_buckets): specials
  (NaN/Inf/±0) skip Ryu entirely, "simple" doubles — exact integers in
  [1, 1e7), the overwhelming majority of real data — take a 6-step
  trailing-zero strip instead of the 22-iteration shortest-search, and
  only the full-Ryu residue pays the 128-bit limb machinery;
- **strength-reduced emission** (_emit_fast): ONE take_along_axis digit
  gather + two grouped scatters replace the ~85 per-position put()
  scatters of the oracle `_emit`;
- **backend-adaptive dispatch** (`float_device_render="auto"`, the
  json_device_render pattern): XLA:CPU routes to ``# twin:``-pinned
  numpy renderers with branch/active-set compaction the lockstep
  compiled path cannot do.

Every fast path is bit-identical to the monolithic device oracle
(``float_bucketed=False`` + ``float_device_render=True``), which stays
the Spark-parity reference; tests/test_float_to_string.py fuzzes all
three arms against each other and the Java layout oracle.

FLOAT64 input is the int64 bit-pattern convention (columnar.column) — exactly
what Ryu wants: the algorithm never touches float arithmetic.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu import config
from spark_rapids_jni_tpu.columnar.buckets import class_buckets, map_classes
from spark_rapids_jni_tpu.columnar.column import (
    Column,
    StringColumn,
    next_pow2,
    strings_from_padded,
)
from spark_rapids_jni_tpu.columnar.dtypes import Kind
from spark_rapids_jni_tpu.obs.phases import PhaseTimes
from spark_rapids_jni_tpu.utils.floatbits import f32_to_bits
from spark_rapids_jni_tpu.utils import ryu_tables as rt

_U64 = jnp.uint64
_U32 = jnp.uint32
_I32 = jnp.int32
_M32 = jnp.uint64(0xFFFFFFFF)

MAX_D2S_LEN = 24  # sign + 17 digits + '.' + pad0 + 'E' + '-' + 3 exp digits

_POW10_U64 = jnp.asarray(np.array([10**k for k in range(20)], dtype=np.uint64))
_POW5_U64 = jnp.asarray(np.array([5**k for k in range(24)], dtype=np.uint64))

_POW10_NP = np.array([10**k for k in range(20)], dtype=np.uint64)
_POW5_NP = np.array([5**k for k in range(24)], dtype=np.uint64)

# pipeline phase timers (obs/phases.py): bucket = classification + class
# split, ryu = digit computation (shortest-search or strip), emit =
# character emission + column assembly.  bench.py snapshots these into
# the stage's phases_s.
PHASES = PhaseTimes("bucket", "ryu", "emit")

# value classes (class_buckets ids): specials render from a 5-row table,
# simple integers take the strip loop, the residue pays full Ryu.
CLS_SPECIAL = 0
CLS_SIMPLE = 1
CLS_RYU = 2


def _u64(x):
    return jnp.asarray(x, dtype=jnp.uint64)


def digit_table_u64(v, maxd: int = 20) -> jnp.ndarray:
    """``[n, maxd]`` uint8 decimal digits of u64 ``v``, index k = digit from
    the RIGHT (ones digit at k=0), zero-padded above the value's length.

    Built by an unrolled divide-by-constant-10 chain: each step is a
    strength-reduced multiply-high, so the whole table costs ~maxd cheap row
    ops.  Renderers then *gather* from it per output position — replacing
    per-grid-cell ``v // 10^k`` with a variable k, whose emulated-u64
    general division is the dominant term in the axon TPU compile-time
    pathology on the string-rendering ops (docs/PERF.md)."""
    ten = _U64(10)
    cols = []
    for _ in range(maxd):
        cols.append((v % ten).astype(jnp.uint8))
        v = v // ten
    return jnp.stack(cols, axis=-1)


def digit_from_table(tab: jnp.ndarray, k) -> jnp.ndarray:
    """ASCII digit chars gathered at (broadcast) right-index ``k``; out-of-
    range k clamps (callers mask those positions anyway)."""
    maxd = tab.shape[-1]
    kc = jnp.clip(k, 0, maxd - 1)
    if kc.ndim == tab.ndim - 1:
        kc = kc[..., None]
        return jnp.take_along_axis(tab, kc, axis=-1)[..., 0] + jnp.uint8(
            ord("0"))
    return jnp.take_along_axis(tab, kc, axis=-1) + jnp.uint8(ord("0"))


def _umul128(a, b):
    """(hi, lo) of the full 128-bit product of two u64 lane arrays."""
    a_lo, a_hi = a & _M32, a >> _U64(32)
    b_lo, b_hi = b & _M32, b >> _U64(32)
    ll = a_lo * b_lo
    lh = a_lo * b_hi
    hl = a_hi * b_lo
    hh = a_hi * b_hi
    mid = (ll >> _U64(32)) + (lh & _M32) + (hl & _M32)
    lo = (ll & _M32) | ((mid & _M32) << _U64(32))
    hi = hh + (lh >> _U64(32)) + (hl >> _U64(32)) + (mid >> _U64(32))
    return hi, lo


def _shiftright128(lo, hi, dist):
    """(hi:lo) >> dist for per-lane dist in (0, 64)."""
    dist = dist.astype(jnp.uint64)
    return (hi << (_U64(64) - dist)) | (lo >> dist)


def _mul_shift64(m, mul_lo, mul_hi, j):
    """Ryu mulShift64 (ftos_converter.cuh:375): ((m * mul) >> j) low 64."""
    hi1, lo1 = _umul128(m, mul_hi)
    hi0, _lo0 = _umul128(m, mul_lo)
    s = hi0 + lo1
    hi1 = hi1 + (s < hi0).astype(jnp.uint64)  # carry
    return _shiftright128(s, hi1, j - 64)


def _pow5bits(e):
    return ((e * _I32(1217359)) >> 19) + _I32(1)


def _log10_pow2(e):
    return (e * _I32(78913)) >> 18


def _log10_pow5(e):
    return (e * _I32(732923)) >> 20


def _multiple_of_pow5(value, q):
    """value % 5^q == 0 for q in [0, 23] lanes (exact u64 mod)."""
    return value % _POW5_U64[jnp.clip(q, 0, 23)] == 0


def _multiple_of_pow2(value, q):
    mask = (_U64(1) << jnp.clip(q, 0, 63).astype(jnp.uint64)) - _U64(1)
    return (value & mask) == 0


def _decimal_length_u64(v, max_digits):
    """number of decimal digits of v (>= 1)."""
    n = jnp.ones(v.shape, _I32)
    for k in range(1, max_digits):
        n = n + (v >= _POW10_U64[k]).astype(_I32)
    return n


# twin: f2s_d2d
def _d2d(bits):
    """Vectorized Ryu d2d (ftos_converter.cuh:480): bit patterns ->
    (mantissa u64, exponent i32) of the shortest decimal."""
    u = bits.astype(jnp.uint64)
    ieee_mantissa = u & _U64((1 << 52) - 1)
    ieee_exponent = ((u >> _U64(52)) & _U64(0x7FF)).astype(_I32)

    denormal = ieee_exponent == 0
    e2 = jnp.where(denormal, _I32(1 - 1023 - 52 - 2), ieee_exponent - (1023 + 52 + 2))
    m2 = jnp.where(denormal, ieee_mantissa, ieee_mantissa | _U64(1 << 52))
    even = (m2 & _U64(1)) == 0
    accept_bounds = even

    mv = _U64(4) * m2
    mm_shift = ((ieee_mantissa != 0) | (ieee_exponent <= 1)).astype(jnp.uint64)

    # --- branch A: e2 >= 0 (inverse powers of 5) ---
    qa = jnp.maximum(_log10_pow2(e2) - (e2 > 3).astype(_I32), 0)
    ka = _I32(rt.DOUBLE_POW5_INV_BITCOUNT) + _pow5bits(qa) - 1
    ja = -e2 + qa + ka  # shift argument
    qa_c = jnp.clip(qa, 0, len(rt.DOUBLE_POW5_INV_SPLIT_LO) - 1)
    inv_lo = jnp.asarray(rt.DOUBLE_POW5_INV_SPLIT_LO)[qa_c]
    inv_hi = jnp.asarray(rt.DOUBLE_POW5_INV_SPLIT_HI)[qa_c]
    vr_a = _mul_shift64(mv, inv_lo, inv_hi, ja)
    vp_a = _mul_shift64(mv + _U64(2), inv_lo, inv_hi, ja)
    vm_a = _mul_shift64(mv - _U64(1) - mm_shift, inv_lo, inv_hi, ja)
    e10_a = qa
    # trailing-zero flags (q <= 21 guard)
    guard_a = qa <= 21
    mv_mod5 = mv % _U64(5) == 0
    vr_tz_a = guard_a & mv_mod5 & _multiple_of_pow5(mv, qa)
    vm_tz_a = guard_a & ~mv_mod5 & accept_bounds & _multiple_of_pow5(
        mv - _U64(1) - mm_shift, qa
    )
    vp_a = vp_a - (
        guard_a & ~mv_mod5 & ~accept_bounds & _multiple_of_pow5(mv + _U64(2), qa)
    ).astype(jnp.uint64)

    # --- branch B: e2 < 0 (powers of 5) ---
    neg_e2 = -e2
    qb = jnp.maximum(_log10_pow5(neg_e2) - (neg_e2 > 1).astype(_I32), 0)
    ib = neg_e2 - qb
    kb = _pow5bits(ib) - _I32(rt.DOUBLE_POW5_BITCOUNT)
    jb = qb - kb
    ib_c = jnp.clip(ib, 0, len(rt.DOUBLE_POW5_SPLIT_LO) - 1)
    pw_lo = jnp.asarray(rt.DOUBLE_POW5_SPLIT_LO)[ib_c]
    pw_hi = jnp.asarray(rt.DOUBLE_POW5_SPLIT_HI)[ib_c]
    vr_b = _mul_shift64(mv, pw_lo, pw_hi, jb)
    vp_b = _mul_shift64(mv + _U64(2), pw_lo, pw_hi, jb)
    vm_b = _mul_shift64(mv - _U64(1) - mm_shift, pw_lo, pw_hi, jb)
    e10_b = qb + e2
    q_le1 = qb <= 1
    vr_tz_b = q_le1 | ((qb < 63) & _multiple_of_pow2(mv, qb))
    vm_tz_b = q_le1 & (mm_shift == 1)
    vp_b = vp_b - (q_le1 & ~accept_bounds).astype(jnp.uint64)

    pos = e2 >= 0
    vr = jnp.where(pos, vr_a, vr_b)
    vp = jnp.where(pos, vp_a, vp_b)
    vm = jnp.where(pos, vm_a, vm_b)
    e10 = jnp.where(pos, e10_a, e10_b)
    vm_tz = jnp.where(pos, vm_tz_a, vm_tz_b)
    vr_tz = jnp.where(pos, vr_tz_a, vr_tz_b)

    return _shortest_loop(vr, vp, vm, e10, vm_tz, vr_tz, accept_bounds, 22)


# twin: f2s_f2d
def _f2d(bits):
    """Vectorized Ryu f2d (ftos_converter.cuh:575) in u64 lanes."""
    u = bits.astype(jnp.uint64) & _U64(0xFFFFFFFF)
    ieee_mantissa = u & _U64((1 << 23) - 1)
    ieee_exponent = ((u >> _U64(23)) & _U64(0xFF)).astype(_I32)

    denormal = ieee_exponent == 0
    e2 = jnp.where(denormal, _I32(1 - 127 - 23 - 2), ieee_exponent - (127 + 23 + 2))
    m2 = jnp.where(denormal, ieee_mantissa, ieee_mantissa | _U64(1 << 23))
    even = (m2 & _U64(1)) == 0
    accept_bounds = even

    mv = _U64(4) * m2
    mp = mv + _U64(2)
    mm_shift = ((ieee_mantissa != 0) | (ieee_exponent <= 1)).astype(jnp.uint64)
    mm = mv - _U64(1) - mm_shift

    inv_tab = jnp.asarray(rt.FLOAT_POW5_INV_SPLIT)
    pow_tab = jnp.asarray(rt.FLOAT_POW5_SPLIT)

    def mul_pow5_inv_div_pow2(m, q, j):
        factor = inv_tab[jnp.clip(q, 0, len(rt.FLOAT_POW5_INV_SPLIT) - 1)]
        return _mul_shift32(m, factor, j)

    def mul_pow5_div_pow2(m, i, j):
        factor = pow_tab[jnp.clip(i, 0, len(rt.FLOAT_POW5_SPLIT) - 1)]
        return _mul_shift32(m, factor, j)

    # branch A: e2 >= 0
    qa = jnp.maximum(_log10_pow2(e2), 0)
    ka = _I32(rt.FLOAT_POW5_INV_BITCOUNT) + _pow5bits(qa) - 1
    ja = -e2 + qa + ka
    vr_a = mul_pow5_inv_div_pow2(mv, qa, ja)
    vp_a = mul_pow5_inv_div_pow2(mp, qa, ja)
    vm_a = mul_pow5_inv_div_pow2(mm, qa, ja)
    e10_a = qa
    la = _I32(rt.FLOAT_POW5_INV_BITCOUNT) + _pow5bits(jnp.maximum(qa - 1, 0)) - 1
    lrd_a = jnp.where(
        (qa != 0) & ((vp_a - _U64(1)) // _U64(10) <= vm_a // _U64(10)),
        mul_pow5_inv_div_pow2(mv, jnp.maximum(qa - 1, 0), -e2 + qa - 1 + la)
        % _U64(10),
        _U64(0),
    )
    guard_a = qa <= 9
    mv_mod5 = mv % _U64(5) == 0
    vr_tz_a = guard_a & mv_mod5 & _multiple_of_pow5(mv, qa)
    vm_tz_a = guard_a & ~mv_mod5 & accept_bounds & _multiple_of_pow5(mm, qa)
    vp_a = vp_a - (
        guard_a & ~mv_mod5 & ~accept_bounds & _multiple_of_pow5(mp, qa)
    ).astype(jnp.uint64)

    # branch B: e2 < 0
    neg_e2 = -e2
    qb = jnp.maximum(_log10_pow5(neg_e2), 0)
    ib = neg_e2 - qb
    kb = _pow5bits(ib) - _I32(rt.FLOAT_POW5_BITCOUNT)
    jb = qb - kb
    vr_b = mul_pow5_div_pow2(mv, ib, jb)
    vp_b = mul_pow5_div_pow2(mp, ib, jb)
    vm_b = mul_pow5_div_pow2(mm, ib, jb)
    e10_b = qb + e2
    jb2 = qb - 1 - (_pow5bits(ib + 1) - _I32(rt.FLOAT_POW5_BITCOUNT))
    lrd_b = jnp.where(
        (qb != 0) & ((vp_b - _U64(1)) // _U64(10) <= vm_b // _U64(10)),
        mul_pow5_div_pow2(mv, ib + 1, jb2) % _U64(10),
        _U64(0),
    )
    q_le1 = qb <= 1
    vr_tz_b = q_le1 | ((qb < 31) & _multiple_of_pow2(mv, jnp.maximum(qb - 1, 0)))
    vm_tz_b = q_le1 & (mm_shift == 1)
    vp_b = vp_b - (q_le1 & ~accept_bounds).astype(jnp.uint64)

    pos = e2 >= 0
    vr = jnp.where(pos, vr_a, vr_b)
    vp = jnp.where(pos, vp_a, vp_b)
    vm = jnp.where(pos, vm_a, vm_b)
    e10 = jnp.where(pos, e10_a, e10_b)
    vm_tz = jnp.where(pos, vm_tz_a, vm_tz_b)
    vr_tz = jnp.where(pos, vr_tz_a, vr_tz_b)
    lrd = jnp.where(pos, lrd_a, lrd_b)

    return _shortest_loop(
        vr, vp, vm, e10, vm_tz, vr_tz, accept_bounds, 11, last_removed=lrd
    )


def _mul_shift32(m, factor, shift):
    """Ryu mulShift32 (ftos_converter.cuh:242) in u64 lanes; shift > 32."""
    factor_lo = factor & _M32
    factor_hi = factor >> _U64(32)
    bits0 = m * factor_lo
    bits1 = m * factor_hi
    s = (bits0 >> _U64(32)) + bits1
    return s >> (shift.astype(jnp.uint64) - _U64(32))


# twin: f2s_shortest
def _shortest_loop(vr, vp, vm, e10, vm_tz, vr_tz, accept_bounds, max_iter,
                   last_removed=None):
    """Ryu step 4 (ftos_converter.cuh:570-650): masked unrolled digit removal.

    The reference's common-case div100 fast path is an optimization of the
    same recurrence; the general loop with correctly-initialized flags gives
    identical output for all lanes.
    """
    removed = jnp.zeros(vr.shape, _I32)
    lrd = jnp.zeros(vr.shape, jnp.uint64) if last_removed is None else last_removed

    for _ in range(max_iter):
        act = vp // _U64(10) > vm // _U64(10)
        vm_tz = jnp.where(act, vm_tz & (vm % _U64(10) == 0), vm_tz)
        vr_tz = jnp.where(act, vr_tz & (lrd == 0), vr_tz)
        lrd = jnp.where(act, vr % _U64(10), lrd)
        vr = jnp.where(act, vr // _U64(10), vr)
        vp = jnp.where(act, vp // _U64(10), vp)
        vm = jnp.where(act, vm // _U64(10), vm)
        removed = removed + act.astype(_I32)

    for _ in range(max_iter):
        act = vm_tz & (vm % _U64(10) == 0)
        vr_tz = jnp.where(act, vr_tz & (lrd == 0), vr_tz)
        lrd = jnp.where(act, vr % _U64(10), lrd)
        vr = jnp.where(act, vr // _U64(10), vr)
        vp = jnp.where(act, vp // _U64(10), vp)
        vm = jnp.where(act, vm // _U64(10), vm)
        removed = removed + act.astype(_I32)

    lrd = jnp.where(vr_tz & (lrd == 5) & (vr % _U64(2) == 0), _U64(4), lrd)
    round_up = ((vr == vm) & (~accept_bounds | ~vm_tz)) | (lrd >= 5)
    output = vr + round_up.astype(jnp.uint64)
    return output, e10 + removed


def _emit(output, exp10, negative, special_id, is_float):
    """Scatter the decimal into a padded byte matrix per Java formatting
    (to_chars, ftos_converter.cuh:797-893).

    The round-20 fast paths (_emit_fast / _emit_np) replace the ~85
    per-position scatters below with grouped emission; this body stays
    byte-for-byte the parity oracle they are fuzzed against."""
    n = output.shape[0]
    max_digits = 9 if is_float else 17
    olength = _decimal_length_u64(output, max_digits)
    exp = exp10 + olength - 1
    sci = (exp < -3) | (exp >= 7)
    s = negative.astype(_I32)

    out = jnp.full((n, MAX_D2S_LEN), 0, jnp.uint8)
    rows = jnp.arange(n, dtype=_I32)
    OOB = _I32(MAX_D2S_LEN)  # dropped by mode="drop"

    def put(pos, ch, mask):
        p = jnp.where(mask, pos, OOB)
        return lambda o: o.at[rows, p].set(ch, mode="drop")

    writes = []
    normal = special_id < 0

    # sign
    writes.append(put(jnp.zeros(n, _I32), jnp.uint8(ord("-")), normal & negative))

    # digits (MSB-first digit k = (output // 10^(olength-1-k)) % 10)
    plain_neg = normal & ~sci & (exp < 0)
    plain_big = normal & ~sci & (exp >= 0) & (exp + 1 >= olength)
    plain_mid = normal & ~sci & (exp >= 0) & (exp + 1 < olength)
    sci_m = normal & sci
    out_tab = digit_table_u64(output, max_digits)
    for k in range(max_digits):
        have = olength > k
        digit = digit_from_table(out_tab, olength - 1 - k)
        kk = _I32(k)
        writes.append(put(s + kk + (1 if k > 0 else 0), digit, sci_m & have))
        writes.append(put(s + 2 + (-exp - 1) + kk, digit, plain_neg & have))
        writes.append(put(s + kk, digit, plain_big & have))
        writes.append(put(s + kk + (kk > exp).astype(_I32), digit, plain_mid & have))

    dot = jnp.uint8(ord("."))
    zero_c = jnp.uint8(ord("0"))
    # scientific: '.', pad '0' when olength == 1, 'E', exp sign + digits
    writes.append(put(s + 1, dot, sci_m))
    writes.append(put(s + 2, zero_c, sci_m & (olength == 1)))
    p_e = s + olength + 1 + (olength == 1).astype(_I32)
    writes.append(put(p_e, jnp.uint8(ord("E")), sci_m))
    neg_e = exp < 0
    writes.append(put(p_e + 1, jnp.uint8(ord("-")), sci_m & neg_e))
    eabs = jnp.abs(exp)
    elen = 1 + (eabs >= 10).astype(_I32) + (eabs >= 100).astype(_I32)
    pe0 = p_e + 1 + neg_e.astype(_I32)
    # exponent digits MSB-first: digit j of the elen-digit number
    for j in range(3):
        have = elen > j
        p10 = jnp.asarray(np.array([1, 10, 100], np.int32))
        ed = ((eabs // p10[jnp.clip(elen - 1 - j, 0, 2)]) % 10).astype(
            jnp.uint8
        ) + zero_c
        writes.append(put(pe0 + j, ed, sci_m & have))

    # plain, exp < 0: "0." + (-exp-1) zeros + digits
    writes.append(put(s + 0, zero_c, plain_neg))
    writes.append(put(s + 1, dot, plain_neg))
    for t in range(2):  # exp >= -3 -> at most 2 leading zeros
        writes.append(put(s + 2 + t, zero_c, plain_neg & (-exp - 1 > t)))

    # plain, exp+1 >= olength: digits + zeros + ".0"
    for t in range(7):  # exp < 7 -> at most 7 trailing zeros
        writes.append(
            put(s + olength + t, zero_c, plain_big & (exp + 1 - olength > t))
        )
    writes.append(put(s + exp + 1, dot, plain_big))
    writes.append(put(s + exp + 2, zero_c, plain_big))

    # plain, dot between digits
    writes.append(put(s + exp + 1, dot, plain_mid))

    for w in writes:
        out = w(out)

    # lengths (d2s_size, ftos_converter.cuh:877-906)
    len_sci = s + olength + 1 + (olength == 1).astype(_I32) + 1 + neg_e.astype(_I32) + elen
    len_pn = s + 1 - exp + olength
    len_pb = s + exp + 3
    len_pm = s + olength + 1
    lens = jnp.where(
        sci, len_sci, jnp.where(exp < 0, len_pn, jnp.where(exp + 1 >= olength, len_pb, len_pm))
    )

    # specials: 0:"0.0" 1:"-0.0" 2:"Infinity" 3:"-Infinity" 4:"NaN"
    specials = ["0.0", "-0.0", "Infinity", "-Infinity", "NaN"]
    tab = np.zeros((5, MAX_D2S_LEN), np.uint8)
    slen = np.zeros(5, np.int32)
    for i, sp in enumerate(specials):
        b = sp.encode()
        tab[i, : len(b)] = np.frombuffer(b, np.uint8)
        slen[i] = len(b)
    sid = jnp.clip(special_id, 0, 4)
    out = jnp.where(normal[:, None], out, jnp.asarray(tab)[sid])
    lens = jnp.where(normal, lens, jnp.asarray(slen)[sid])
    return out, lens


# --------------------------------------------------------------------------
# round 20: value-class bucketing + strength-reduced emission fast paths
# --------------------------------------------------------------------------


def _special_table():
    """(chars[5, MAX_D2S_LEN] u8, lens[5] i32) of the special strings."""
    specials = ["0.0", "-0.0", "Infinity", "-Infinity", "NaN"]
    tab = np.zeros((5, MAX_D2S_LEN), np.uint8)
    slen = np.zeros(5, np.int32)
    for i, sp in enumerate(specials):
        b = sp.encode()
        tab[i, : len(b)] = np.frombuffer(b, np.uint8)
        slen[i] = len(b)
    return tab, slen


def _classify_np(bits: np.ndarray, special_id: np.ndarray,
                 is_float: bool) -> np.ndarray:
    """[n] int8 value classes from the host bit patterns.

    "simple" = an exact integer v in [1, 1e7): unbiased exponent E in
    [0, mbits] with all fractional mantissa bits zero and the shifted
    value under 10^7 (E <= mbits keeps the shift non-negative; any
    integer < 10^7 satisfies it since 10^7 < 2^24).  The Ryu interval
    around such a v is far narrower than 1 (ulp/2 <= 0.5 even at the
    float32 worst case), so the shortest round-trip decimal is v itself
    with trailing zeros stripped — proven bit-identical to the full-Ryu
    oracle by the fuzz corpora."""
    mbits = 23 if is_float else 52
    bias = 127 if is_float else 1023
    emask = 0xFF if is_float else 0x7FF
    mant = bits & np.uint64((1 << mbits) - 1)
    expo = ((bits >> np.uint64(mbits)) & np.uint64(emask)).astype(np.int32)
    E = expo - bias
    m2 = mant | np.uint64(1 << mbits)
    frac_bits = np.clip(mbits - E, 0, 63).astype(np.uint64)
    frac_mask = (np.uint64(1) << frac_bits) - np.uint64(1)
    v = m2 >> frac_bits
    simple = (
        (expo != 0)
        & (E >= 0)
        & (E <= mbits)
        & ((m2 & frac_mask) == 0)
        & (v < np.uint64(10**7))
    )
    return np.where(
        special_id >= 0, CLS_SPECIAL, np.where(simple, CLS_SIMPLE, CLS_RYU)
    ).astype(np.int8)


# twin: f2s_simple
def _simple_digits(bits, is_float):
    """Shortest digits of a 'simple' value — an exact integer v in
    [1, 1e7): strip trailing zeros (<= 6 for v < 10^7), no shortest-search
    needed (see _classify_np for the interval argument)."""
    mbits = 23 if is_float else 52
    bias = 127 if is_float else 1023
    emask = 0xFF if is_float else 0x7FF
    u = bits.astype(jnp.uint64)
    mant = u & jnp.uint64((1 << mbits) - 1)
    expo = ((u >> jnp.uint64(mbits)) & jnp.uint64(emask)).astype(jnp.int32)
    E = expo - bias
    m2 = mant | jnp.uint64(1 << mbits)
    v = m2 >> jnp.clip(mbits - E, 0, 63).astype(jnp.uint64)
    e10 = jnp.zeros(v.shape, jnp.int32)
    for _ in range(6):
        strip = (v > jnp.uint64(9)) & (v % jnp.uint64(10) == 0)
        v = jnp.where(strip, v // jnp.uint64(10), v)
        e10 = e10 + strip.astype(jnp.int32)
    return v, e10


# twin: f2s_simple
def _simple_digits_np(bits, is_float):
    """numpy twin of _simple_digits."""
    mbits = 23 if is_float else 52
    bias = 127 if is_float else 1023
    emask = 0xFF if is_float else 0x7FF
    u = bits.astype(np.uint64)
    mant = u & np.uint64((1 << mbits) - 1)
    expo = ((u >> np.uint64(mbits)) & np.uint64(emask)).astype(np.int32)
    E = expo - bias
    m2 = mant | np.uint64(1 << mbits)
    v = m2 >> np.clip(mbits - E, 0, 63).astype(np.uint64)
    e10 = np.zeros(v.shape, np.int32)
    for _ in range(6):
        strip = (v > np.uint64(9)) & (v % np.uint64(10) == 0)
        v = np.where(strip, v // np.uint64(10), v)
        e10 = e10 + strip.astype(np.int32)
    return v, e10


# twin: f2s_emit
def _emit_fast(output, exp10, negative, special_id, is_float):
    """Strength-reduced twin of the `_emit` oracle: one take_along_axis
    digit gather + two grouped scatters replace ~85 per-position put()
    scatters.  Layout classes, positions, and length formulas mirror
    d2s_size (ftos_converter.cuh:877-906) byte for byte."""
    n = output.shape[0]
    max_digits = 9 if is_float else 17
    olength = _decimal_length_u64(output, max_digits)
    exp = exp10 + olength - 1
    sci = (exp < -3) | (exp >= 7)
    s = negative.astype(_I32)
    normal = special_id < 0
    neg_e = exp < 0
    eabs = jnp.abs(exp)
    elen = 1 + (eabs >= 10).astype(_I32) + (eabs >= 100).astype(_I32)

    sci_m = normal & sci
    plain_neg = normal & ~sci & (exp < 0)
    plain_big = normal & ~sci & (exp >= 0) & (exp + 1 >= olength)
    plain_mid = normal & ~sci & (exp >= 0) & (exp + 1 < olength)

    # MSB-first digit characters: ONE gather from the div-10 chain table
    # (digit k from the left sits at right-index olength-1-k)
    karr = jnp.arange(max_digits, dtype=jnp.int32)[None, :]
    tab = digit_table_u64(output, max_digits)
    msb = jnp.clip(olength[:, None] - 1 - karr, 0, max_digits - 1)
    digits = jnp.take_along_axis(tab, msb, axis=1) + jnp.uint8(ord("0"))

    # per-layout digit positions, one [n, max_digits] matrix
    dpos = jnp.where(
        sci[:, None],
        s[:, None] + karr + (karr > 0).astype(jnp.int32),
        jnp.where(
            plain_neg[:, None],
            s[:, None] + 2 + (-exp[:, None] - 1) + karr,
            jnp.where(
                plain_big[:, None],
                s[:, None] + karr,
                s[:, None] + karr + (karr > exp[:, None]).astype(jnp.int32),
            ),
        ),
    )
    have = (karr < olength[:, None]) & normal[:, None]

    out = jnp.zeros((n, MAX_D2S_LEN), jnp.uint8)
    rows = jnp.arange(n, dtype=jnp.int32)
    OOB = _I32(MAX_D2S_LEN)
    out = out.at[rows[:, None], jnp.where(have, dpos, OOB)].set(
        digits, mode="drop"
    )

    # the ~19 per-class scalar characters, grouped into one scatter
    dot = jnp.uint8(ord("."))
    zero_c = jnp.uint8(ord("0"))
    p_e = s + olength + 1 + (olength == 1).astype(_I32)
    pe0 = p_e + 1 + neg_e.astype(_I32)
    p10 = jnp.asarray(np.array([1, 10, 100], np.int32))
    ps = []
    cs = []

    def sput(pos, ch, mask):
        ps.append(jnp.where(mask, pos, OOB))
        cs.append(jnp.broadcast_to(jnp.asarray(ch, jnp.uint8), pos.shape))

    sput(s * 0, jnp.uint8(ord("-")), normal & negative)
    sput(s + 1, dot, sci_m)
    sput(s + 2, zero_c, sci_m & (olength == 1))
    sput(p_e, jnp.uint8(ord("E")), sci_m)
    sput(p_e + 1, jnp.uint8(ord("-")), sci_m & neg_e)
    for j in range(3):
        ed = ((eabs // p10[jnp.clip(elen - 1 - j, 0, 2)]) % 10).astype(
            jnp.uint8
        ) + zero_c
        sput(pe0 + j, ed, sci_m & (elen > j))
    sput(s + 0, zero_c, plain_neg)
    sput(s + 1, dot, plain_neg)
    for t in range(2):
        sput(s + 2 + t, zero_c, plain_neg & (-exp - 1 > t))
    for t in range(7):
        sput(s + olength + t, zero_c, plain_big & (exp + 1 - olength > t))
    sput(s + exp + 1, dot, plain_big)
    sput(s + exp + 2, zero_c, plain_big)
    sput(s + exp + 1, dot, plain_mid)

    out = out.at[rows[:, None], jnp.stack(ps, axis=1)].set(
        jnp.stack(cs, axis=1), mode="drop"
    )

    len_sci = s + olength + 1 + (olength == 1).astype(_I32) + 1 + neg_e.astype(_I32) + elen
    len_pn = s + 1 - exp + olength
    len_pb = s + exp + 3
    len_pm = s + olength + 1
    lens = jnp.where(
        sci, len_sci, jnp.where(exp < 0, len_pn, jnp.where(exp + 1 >= olength, len_pb, len_pm))
    )

    tab_sp, slen_sp = _special_table()
    sid = jnp.clip(special_id, 0, 4)
    out = jnp.where(normal[:, None], out, jnp.asarray(tab_sp)[sid])
    lens = jnp.where(normal, lens, jnp.asarray(slen_sp)[sid])
    return out, lens


# twin: f2s_emit
def _emit_np(output, exp10, negative, special_id, is_float):
    """numpy twin of _emit_fast.

    The layout math (classes, exponent split, length formulas) is pinned
    line-for-line against the device twin; the character emission itself
    compacts rows per layout class and writes the digit run as contiguous
    column-slice copies (str(v) is left-aligned, so each layout is a few
    block moves plus a handful of masked scalar stores), where the
    lockstep device twin must scatter through position matrices."""
    n = output.shape[0]
    max_digits = 9 if is_float else 17
    olength = _decimal_length_np(output, max_digits)
    exp = exp10 + olength - 1
    sci = (exp < -3) | (exp >= 7)
    s = negative.astype(np.int32)
    normal = special_id < 0
    neg_e = exp < 0
    eabs = np.abs(exp)
    elen = 1 + (eabs >= 10).astype(np.int32) + (eabs >= 100).astype(np.int32)

    sci_m = normal & sci
    plain_neg = normal & ~sci & (exp < 0)
    plain_big = normal & ~sci & (exp >= 0) & (exp + 1 >= olength)
    plain_mid = normal & ~sci & (exp >= 0) & (exp + 1 < olength)

    # MSB-first digit codepoints, left-aligned: scale by 10^(max_digits -
    # olength) so the value is exactly max_digits wide (no overflow: output
    # has olength digits), then peel digits with divmod-by-10 over u32
    # halves — ~4x cheaper than per-row str() formatting (astype("U17")).
    # Columns past olength hold '0', not '\0'; every emit layout below
    # either overwrites them or leaves them past lens, and
    # _strings_from_padded_np extracts padded[j < lens] only.
    scaled = output.astype(np.uint64) * _POW10_NP[
        np.clip(max_digits - olength, 0, 19)]
    dcols = np.empty((max_digits, n), np.uint8)
    lo10 = (scaled % np.uint64(10**9)).astype(np.uint32)
    hi10 = (scaled // np.uint64(10**9)).astype(np.uint32)
    for j in range(min(9, max_digits)):
        lo10, r = np.divmod(lo10, np.uint32(10))
        dcols[max_digits - 1 - j] = r
    for j in range(max_digits - 9):
        hi10, r = np.divmod(hi10, np.uint32(10))
        dcols[max_digits - 10 - j] = r
    digits32 = dcols.T + np.uint8(ord("0"))

    p_e = s + olength + 1 + (olength == 1).astype(np.int32)
    pe0 = p_e + 1 + neg_e.astype(np.int32)
    p10 = np.array([1, 10, 100], np.int32)

    out = np.zeros((n, MAX_D2S_LEN), np.uint8)
    flat = out.reshape(-1)
    rowoff = np.arange(n, dtype=np.int64) * MAX_D2S_LEN
    DOT = np.uint8(ord("."))
    ZERO = np.uint8(ord("0"))

    ridx = np.nonzero(normal & negative)[0]
    if ridx.size:
        flat[rowoff[ridx]] = np.uint8(ord("-"))

    if sci_m.any():
        # d0 '.' d1..d_{ol-1} 'E' [-] exp -- digit run at fixed columns per
        # sign; trailing '\0's land past the E block and under lens
        for sgn in (0, 1):
            ridx = np.nonzero(sci_m & (s == sgn))[0]
            if not ridx.size:
                continue
            dsub = digits32[ridx]
            out[ridx, sgn] = dsub[:, 0]
            out[ridx, sgn + 1] = DOT
            out[ridx, sgn + 2:sgn + 1 + max_digits] = dsub[:, 1:]
        ridx = np.nonzero(sci_m)[0]
        base = rowoff[ridx]
        pad = ridx[olength[ridx] == 1]
        flat[rowoff[pad] + s[pad] + 2] = ZERO
        flat[base + p_e[ridx]] = np.uint8(ord("E"))
        rneg = ridx[neg_e[ridx]]
        flat[rowoff[rneg] + p_e[rneg] + 1] = np.uint8(ord("-"))
        eb = eabs[ridx]
        el = elen[ridx]
        p0 = pe0[ridx] + base
        for j in range(3):
            rj = np.nonzero(el > j)[0]
            if rj.size:
                edc = (
                    (eb[rj] // p10[np.clip(el[rj] - 1 - j, 0, 2)]) % 10
                ).astype(np.uint8) + ZERO
                flat[p0[rj] + j] = edc

    if plain_big.any():
        # digits, pad zeros to the ones place, then ".0"
        for sgn in (0, 1):
            ridx = np.nonzero(plain_big & (s == sgn))[0]
            if ridx.size:
                out[ridx, sgn:sgn + max_digits] = digits32[ridx]
        ridx = np.nonzero(plain_big)[0]
        base = rowoff[ridx]
        nz = exp[ridx] + 1 - olength[ridx]
        for t in range(7):  # exp < 7 -> at most 7 trailing zeros
            rz = np.nonzero(nz > t)[0]
            if rz.size:
                flat[base[rz] + s[ridx[rz]] + olength[ridx[rz]] + t] = ZERO
        flat[base + s[ridx] + exp[ridx] + 1] = DOT
        flat[base + s[ridx] + exp[ridx] + 2] = ZERO

    if plain_mid.any():
        # dot inside the digit run: exp in [0, 7), so two block moves per
        # (sign, exp) group
        for sgn in (0, 1):
            for e in range(7):
                ridx = np.nonzero(plain_mid & (s == sgn) & (exp == e))[0]
                if not ridx.size:
                    continue
                dsub = digits32[ridx]
                out[ridx, sgn:sgn + e + 1] = dsub[:, : e + 1]
                out[ridx, sgn + e + 1] = DOT
                out[ridx, sgn + e + 2:sgn + max_digits + 1] = dsub[:, e + 1:]

    if plain_neg.any():
        # "0." + up to 2 zeros + digits (exp in [-3, -1))
        for sgn in (0, 1):
            for e in (-1, -2, -3):
                ridx = np.nonzero(plain_neg & (s == sgn) & (exp == e))[0]
                if not ridx.size:
                    continue
                out[ridx, sgn] = ZERO
                out[ridx, sgn + 1] = DOT
                for t in range(-e - 1):
                    out[ridx, sgn + 2 + t] = ZERO
                z0 = sgn + 1 - e
                out[ridx, z0:z0 + max_digits] = digits32[ridx]

    len_sci = s + olength + 1 + (olength == 1).astype(np.int32) + 1 + neg_e.astype(np.int32) + elen
    len_pn = s + 1 - exp + olength
    len_pb = s + exp + 3
    len_pm = s + olength + 1
    lens = np.where(
        sci, len_sci, np.where(exp < 0, len_pn, np.where(exp + 1 >= olength, len_pb, len_pm))
    )

    tab_sp, slen_sp = _special_table()
    sid = np.clip(special_id, 0, 4)
    if not normal.all():
        out = np.where(normal[:, None], out, tab_sp[sid])
    lens = np.where(normal, lens, slen_sp[sid])
    return out, lens


# --------------------------------------------------------------------------
# numpy host Ryu twins (branch + active-set compaction the lockstep
# compiled path cannot do; helpers mirror the device ones 1:1)
# --------------------------------------------------------------------------


def _umul128_np(a, b):
    a_lo, a_hi = a & np.uint64(0xFFFFFFFF), a >> np.uint64(32)
    b_lo, b_hi = b & np.uint64(0xFFFFFFFF), b >> np.uint64(32)
    ll = a_lo * b_lo
    lh = a_lo * b_hi
    hl = a_hi * b_lo
    hh = a_hi * b_hi
    mid = (ll >> np.uint64(32)) + (lh & np.uint64(0xFFFFFFFF)) + (
        hl & np.uint64(0xFFFFFFFF))
    lo = (ll & np.uint64(0xFFFFFFFF)) | (
        (mid & np.uint64(0xFFFFFFFF)) << np.uint64(32))
    hi = hh + (lh >> np.uint64(32)) + (hl >> np.uint64(32)) + (
        mid >> np.uint64(32))
    return hi, lo


def _shiftright128_np(lo, hi, dist):
    dist = dist.astype(np.uint64)
    return (hi << (np.uint64(64) - dist)) | (lo >> dist)


def _shiftright128_safe_np(lo, hi, dist):
    """_shiftright128_np that also tolerates dist == 0 lanes (the halved-
    product shift in _mul_shift_all64_np can hit it)."""
    dist = dist.astype(np.uint64)
    lsh = np.where(dist == 0, np.uint64(1), np.uint64(64) - dist)
    return np.where(dist == 0, lo, (hi << lsh) | (lo >> dist))


def _mul_shift_all64_np(mv, mul_lo, mul_hi, j, mm_shift):
    """Upstream Ryu's mulShiftAll64: two umul128s instead of six.

    One exact 192-bit product of m = 2*m2 (mv/2) with the 128-bit pow5
    factor; the (mv, mv+2, mv-1-mmShift) products differ from it by
    +-factor, so they're derived additively and shifted by j-65 (the
    halving).  mmShift == 0 lanes (whole powers of two, rare) need the
    odd mv-1 multiplier: doubled product minus factor at shift j-64.
    Exact integer arithmetic throughout — bit-identical to three
    independent _mul_shift64_np calls."""
    m = mv >> np.uint64(1)  # 2*m2; mv = 4*m2 is always even
    hi0, lo = _umul128_np(m, mul_lo)
    hi1, lo1 = _umul128_np(m, mul_hi)
    mid = hi0 + lo1
    hi = hi1 + (mid < hi0).astype(np.uint64)  # carry
    d1 = j - 65
    vr = _shiftright128_safe_np(mid, hi, d1)
    lo2 = lo + mul_lo
    mid2 = mid + mul_hi + (lo2 < lo).astype(np.uint64)
    hi2 = hi + (mid2 < mid).astype(np.uint64)
    vp = _shiftright128_safe_np(mid2, hi2, d1)
    lo3 = lo - mul_lo
    mid3 = mid - mul_hi - (lo3 > lo).astype(np.uint64)
    hi3 = hi - (mid3 > mid).astype(np.uint64)
    vm = _shiftright128_safe_np(mid3, hi3, d1)
    z = np.nonzero(mm_shift == 0)[0]
    if z.size:
        lo3b = lo[z] + lo[z]
        mid3b = mid[z] + mid[z] + (lo3b < lo[z]).astype(np.uint64)
        hi3b = hi[z] + hi[z] + (mid3b < mid[z]).astype(np.uint64)
        lo4 = lo3b - mul_lo[z]
        mid4 = mid3b - mul_hi[z] - (lo4 > lo3b).astype(np.uint64)
        hi4 = hi3b - (mid4 > mid3b).astype(np.uint64)
        vm[z] = _shiftright128_np(mid4, hi4, j[z] - 64)
    return vr, vp, vm


def _mul_shift64_np(m, mul_lo, mul_hi, j):
    hi1, lo1 = _umul128_np(m, mul_hi)
    hi0, _lo0 = _umul128_np(m, mul_lo)
    s = hi0 + lo1
    hi1 = hi1 + (s < hi0).astype(np.uint64)  # carry
    return _shiftright128_np(s, hi1, j - 64)


def _mul_shift32_np(m, factor, shift):
    factor_lo = factor & np.uint64(0xFFFFFFFF)
    factor_hi = factor >> np.uint64(32)
    bits0 = m * factor_lo
    bits1 = m * factor_hi
    s = (bits0 >> np.uint64(32)) + bits1
    return s >> (shift.astype(np.uint64) - np.uint64(32))


def _pow5bits_np(e):
    return ((e * np.int32(1217359)) >> 19) + np.int32(1)


def _log10_pow2_np(e):
    return (e * np.int32(78913)) >> 18


def _log10_pow5_np(e):
    return (e * np.int32(732923)) >> 20


def _multiple_of_pow5_np(value, q):
    return value % _POW5_NP[np.clip(q, 0, 23)] == 0


def _multiple_of_pow2_np(value, q):
    mask = (np.uint64(1) << np.clip(q, 0, 63).astype(np.uint64)) - np.uint64(1)
    return (value & mask) == 0


def _decimal_length_np(v, max_digits):
    n = np.ones(v.shape, np.int32)
    for k in range(1, max_digits):
        n = n + (v >= _POW10_NP[k]).astype(np.int32)
    return n


def _d2d_pos_np(e2, mv, mm_shift, accept_bounds):
    """Branch A of _d2d (e2 >= 0, inverse powers of 5), compacted rows."""
    qa = np.maximum(_log10_pow2_np(e2) - (e2 > 3).astype(np.int32), 0)
    ka = np.int32(rt.DOUBLE_POW5_INV_BITCOUNT) + _pow5bits_np(qa) - 1
    ja = -e2 + qa + ka
    qa_c = np.clip(qa, 0, len(rt.DOUBLE_POW5_INV_SPLIT_LO) - 1)
    inv_lo = rt.DOUBLE_POW5_INV_SPLIT_LO[qa_c]
    inv_hi = rt.DOUBLE_POW5_INV_SPLIT_HI[qa_c]
    vr, vp, vm = _mul_shift_all64_np(mv, inv_lo, inv_hi, ja, mm_shift)
    # trailing-zero flags only exist under the q <= 21 guard; the u64
    # pow5 modulos run on those survivor rows alone
    vr_tz = np.zeros(mv.shape, np.bool_)
    vm_tz = np.zeros(mv.shape, np.bool_)
    gi = np.nonzero(qa <= 21)[0]
    if gi.size:
        mv_g = mv[gi]
        q_g = qa[gi]
        mod5_g = mv_g % np.uint64(5) == 0
        ab_g = accept_bounds[gi]
        vr_tz[gi] = mod5_g & _multiple_of_pow5_np(mv_g, q_g)
        vm_tz[gi] = ~mod5_g & ab_g & _multiple_of_pow5_np(
            mv_g - np.uint64(1) - mm_shift[gi], q_g
        )
        vp[gi] -= (
            ~mod5_g & ~ab_g & _multiple_of_pow5_np(mv_g + np.uint64(2), q_g)
        ).astype(np.uint64)
    return vr, vp, vm, qa, vm_tz, vr_tz


def _d2d_neg_np(e2, mv, mm_shift, accept_bounds):
    """Branch B of _d2d (e2 < 0, powers of 5), compacted rows."""
    neg_e2 = -e2
    qb = np.maximum(_log10_pow5_np(neg_e2) - (neg_e2 > 1).astype(np.int32), 0)
    ib = neg_e2 - qb
    kb = _pow5bits_np(ib) - np.int32(rt.DOUBLE_POW5_BITCOUNT)
    jb = qb - kb
    ib_c = np.clip(ib, 0, len(rt.DOUBLE_POW5_SPLIT_LO) - 1)
    pw_lo = rt.DOUBLE_POW5_SPLIT_LO[ib_c]
    pw_hi = rt.DOUBLE_POW5_SPLIT_HI[ib_c]
    vr, vp, vm = _mul_shift_all64_np(mv, pw_lo, pw_hi, jb, mm_shift)
    e10 = qb + e2
    q_le1 = qb <= 1
    vr_tz = q_le1 | ((qb < 63) & _multiple_of_pow2_np(mv, qb))
    vm_tz = q_le1 & (mm_shift == 1)
    vp = vp - (q_le1 & ~accept_bounds).astype(np.uint64)
    return vr, vp, vm, e10, vm_tz, vr_tz


# twin: f2s_d2d
def _d2d_np(bits):
    """numpy twin of _d2d with branch compaction: each power-of-5 branch
    (and its 128-bit limb multiplies) runs only on its survivor rows."""
    u = bits.astype(np.uint64)
    ieee_mantissa = u & np.uint64((1 << 52) - 1)
    ieee_exponent = ((u >> np.uint64(52)) & np.uint64(0x7FF)).astype(np.int32)

    denormal = ieee_exponent == 0
    e2 = np.where(denormal, np.int32(1 - 1023 - 52 - 2), ieee_exponent - (1023 + 52 + 2))
    m2 = np.where(denormal, ieee_mantissa, ieee_mantissa | np.uint64(1 << 52))
    even = (m2 & np.uint64(1)) == 0
    accept_bounds = even

    mv = np.uint64(4) * m2
    mm_shift = ((ieee_mantissa != 0) | (ieee_exponent <= 1)).astype(np.uint64)

    pos = e2 >= 0
    n = u.shape[0]
    vr = np.zeros(n, np.uint64)
    vp = np.zeros(n, np.uint64)
    vm = np.zeros(n, np.uint64)
    e10 = np.zeros(n, np.int32)
    vm_tz = np.zeros(n, np.bool_)
    vr_tz = np.zeros(n, np.bool_)
    for sel, branch in ((pos, _d2d_pos_np), (~pos, _d2d_neg_np)):
        idx = np.nonzero(sel)[0]
        if idx.size:
            (vr[idx], vp[idx], vm[idx], e10[idx], vm_tz[idx],
             vr_tz[idx]) = branch(
                e2[idx], mv[idx], mm_shift[idx], accept_bounds[idx])
    return _shortest_loop_np(vr, vp, vm, e10, vm_tz, vr_tz, accept_bounds, 22)


def _f2d_mul_inv_np(m, q, j):
    factor = rt.FLOAT_POW5_INV_SPLIT[
        np.clip(q, 0, len(rt.FLOAT_POW5_INV_SPLIT) - 1)]
    return _mul_shift32_np(m, factor, j)


def _f2d_mul_pow_np(m, i, j):
    factor = rt.FLOAT_POW5_SPLIT[np.clip(i, 0, len(rt.FLOAT_POW5_SPLIT) - 1)]
    return _mul_shift32_np(m, factor, j)


def _f2d_pos_np(e2, mv, mp, mm, mm_shift, accept_bounds):
    """Branch A of _f2d (e2 >= 0), compacted rows."""
    qa = np.maximum(_log10_pow2_np(e2), 0)
    ka = np.int32(rt.FLOAT_POW5_INV_BITCOUNT) + _pow5bits_np(qa) - 1
    ja = -e2 + qa + ka
    vr = _f2d_mul_inv_np(mv, qa, ja)
    vp = _f2d_mul_inv_np(mp, qa, ja)
    vm = _f2d_mul_inv_np(mm, qa, ja)
    la = np.int32(rt.FLOAT_POW5_INV_BITCOUNT) + _pow5bits_np(
        np.maximum(qa - 1, 0)) - 1
    lrd = np.where(
        (qa != 0) & ((vp - np.uint64(1)) // np.uint64(10) <= vm // np.uint64(10)),
        _f2d_mul_inv_np(mv, np.maximum(qa - 1, 0), -e2 + qa - 1 + la)
        % np.uint64(10),
        np.uint64(0),
    )
    guard = qa <= 9
    mv_mod5 = mv % np.uint64(5) == 0
    vr_tz = guard & mv_mod5 & _multiple_of_pow5_np(mv, qa)
    vm_tz = guard & ~mv_mod5 & accept_bounds & _multiple_of_pow5_np(mm, qa)
    vp = vp - (
        guard & ~mv_mod5 & ~accept_bounds & _multiple_of_pow5_np(mp, qa)
    ).astype(np.uint64)
    return vr, vp, vm, qa, vm_tz, vr_tz, lrd


def _f2d_neg_np(e2, mv, mp, mm, mm_shift, accept_bounds):
    """Branch B of _f2d (e2 < 0), compacted rows."""
    neg_e2 = -e2
    qb = np.maximum(_log10_pow5_np(neg_e2), 0)
    ib = neg_e2 - qb
    kb = _pow5bits_np(ib) - np.int32(rt.FLOAT_POW5_BITCOUNT)
    jb = qb - kb
    vr = _f2d_mul_pow_np(mv, ib, jb)
    vp = _f2d_mul_pow_np(mp, ib, jb)
    vm = _f2d_mul_pow_np(mm, ib, jb)
    e10 = qb + e2
    jb2 = qb - 1 - (_pow5bits_np(ib + 1) - np.int32(rt.FLOAT_POW5_BITCOUNT))
    lrd = np.where(
        (qb != 0) & ((vp - np.uint64(1)) // np.uint64(10) <= vm // np.uint64(10)),
        _f2d_mul_pow_np(mv, ib + 1, jb2) % np.uint64(10),
        np.uint64(0),
    )
    q_le1 = qb <= 1
    vr_tz = q_le1 | ((qb < 31) & _multiple_of_pow2_np(mv, np.maximum(qb - 1, 0)))
    vm_tz = q_le1 & (mm_shift == 1)
    vp = vp - (q_le1 & ~accept_bounds).astype(np.uint64)
    return vr, vp, vm, e10, vm_tz, vr_tz, lrd


# twin: f2s_f2d
def _f2d_np(bits):
    """numpy twin of _f2d with branch compaction."""
    u = bits.astype(np.uint64) & np.uint64(0xFFFFFFFF)
    ieee_mantissa = u & np.uint64((1 << 23) - 1)
    ieee_exponent = ((u >> np.uint64(23)) & np.uint64(0xFF)).astype(np.int32)

    denormal = ieee_exponent == 0
    e2 = np.where(denormal, np.int32(1 - 127 - 23 - 2), ieee_exponent - (127 + 23 + 2))
    m2 = np.where(denormal, ieee_mantissa, ieee_mantissa | np.uint64(1 << 23))
    even = (m2 & np.uint64(1)) == 0
    accept_bounds = even

    mv = np.uint64(4) * m2
    mp = mv + np.uint64(2)
    mm_shift = ((ieee_mantissa != 0) | (ieee_exponent <= 1)).astype(np.uint64)
    mm = mv - np.uint64(1) - mm_shift

    pos = e2 >= 0
    n = u.shape[0]
    vr = np.zeros(n, np.uint64)
    vp = np.zeros(n, np.uint64)
    vm = np.zeros(n, np.uint64)
    e10 = np.zeros(n, np.int32)
    vm_tz = np.zeros(n, np.bool_)
    vr_tz = np.zeros(n, np.bool_)
    lrd = np.zeros(n, np.uint64)
    for sel, branch in ((pos, _f2d_pos_np), (~pos, _f2d_neg_np)):
        idx = np.nonzero(sel)[0]
        if idx.size:
            (vr[idx], vp[idx], vm[idx], e10[idx], vm_tz[idx], vr_tz[idx],
             lrd[idx]) = branch(
                e2[idx], mv[idx], mp[idx], mm[idx], mm_shift[idx],
                accept_bounds[idx])
    return _shortest_loop_np(
        vr, vp, vm, e10, vm_tz, vr_tz, accept_bounds, 11, last_removed=lrd
    )


# twin: f2s_shortest
def _shortest_loop_np(vr, vp, vm, e10, vm_tz, vr_tz, accept_bounds, max_iter,
                      last_removed=None):
    """numpy twin of _shortest_loop with active-set compaction.

    A lane that fails the removal condition once never re-enters it (the
    divisions only apply to active lanes), so the survivor index set only
    shrinks — the compacted while-loop visits exactly the lanes the
    device's masked unroll would modify, in the same order."""
    vr, vp, vm = vr.copy(), vp.copy(), vm.copy()
    vm_tz, vr_tz = vm_tz.copy(), vr_tz.copy()
    removed = np.zeros(vr.shape, np.int32)
    lrd = np.zeros(vr.shape, np.uint64) if last_removed is None else last_removed.copy()

    ai = np.nonzero(vp // np.uint64(10) > vm // np.uint64(10))[0]
    it = 0
    while ai.size and it < max_iter:
        it += 1
        vm_tz[ai] &= vm[ai] % np.uint64(10) == 0
        vr_tz[ai] &= lrd[ai] == 0
        lrd[ai] = vr[ai] % np.uint64(10)
        vr[ai] //= np.uint64(10)
        vp[ai] //= np.uint64(10)
        vm[ai] //= np.uint64(10)
        removed[ai] += 1
        ai = ai[vp[ai] // np.uint64(10) > vm[ai] // np.uint64(10)]

    ai = np.nonzero(vm_tz & (vm % np.uint64(10) == 0))[0]
    it = 0
    while ai.size and it < max_iter:
        it += 1
        vr_tz[ai] &= lrd[ai] == 0
        lrd[ai] = vr[ai] % np.uint64(10)
        vr[ai] //= np.uint64(10)
        vp[ai] //= np.uint64(10)
        vm[ai] //= np.uint64(10)
        removed[ai] += 1
        ai = ai[vm[ai] % np.uint64(10) == 0]

    lrd = np.where(vr_tz & (lrd == 5) & (vr % np.uint64(2) == 0), np.uint64(4), lrd)
    round_up = ((vr == vm) & (~accept_bounds | ~vm_tz)) | (lrd >= 5)
    output = vr + round_up.astype(np.uint64)
    return output, e10 + removed


# --------------------------------------------------------------------------
# renderers + dispatch
# --------------------------------------------------------------------------

# governed-allocation seeds for the traced fast-path kernels (the
# _scan_padded_jit pattern): allocations inside materialize at launch.
_d2d_jit = jax.jit(_d2d)
_f2d_jit = jax.jit(_f2d)
_simple_digits_jit = jax.jit(_simple_digits, static_argnums=(1,))
_emit_fast_jit = jax.jit(_emit_fast, static_argnums=(4,))


# twin: f2s_render
def _render_device(bits, negative, special_id, cls, is_float):
    """Device value-class renderer: per-class compacted kernels scattered
    back through columnar/buckets.map_classes (pow2-padded row sets keep
    the compiled-shape universe bounded, exactly like length buckets)."""

    def kernel(cid, b_bits, b_neg, b_sid):
        with PHASES.phase("ryu"):
            if cid == CLS_SIMPLE:
                output, e10 = _simple_digits_jit(b_bits, is_float)
            elif cid == CLS_RYU:
                output, e10 = (_f2d_jit if is_float else _d2d_jit)(b_bits)
            else:  # specials never reach the digit path; emit masks them
                output, e10 = b_bits, b_sid * 0
        with PHASES.phase("emit"):
            return _emit_fast_jit(output, e10, b_neg, b_sid, is_float)

    padded, lens = map_classes(
        cls, 3, kernel,
        [((MAX_D2S_LEN,), jnp.uint8), ((), jnp.int32)],
        row_args=[bits, negative, special_id],
    )
    return padded, lens


# twin: f2s_render
def _render_host(bits, negative, special_id, cls, is_float):
    """numpy twin of _render_device (no pow2 row padding: host kernels
    compact instead of compile)."""
    n = bits.shape[0]
    padded = np.zeros((n, MAX_D2S_LEN), np.uint8)
    lens = np.zeros(n, np.int32)
    buckets = class_buckets(cls, 3, round_rows=False)
    for cid, rows_np, n_valid in buckets:
        whole = len(buckets) == 1 and n_valid == n
        if whole:
            b_bits, b_neg, b_sid = bits, negative, special_id
        else:
            b_bits = bits[rows_np]
            b_neg = negative[rows_np]
            b_sid = special_id[rows_np]
        with PHASES.phase("ryu"):
            if cid == CLS_SIMPLE:
                output, e10 = _simple_digits_np(b_bits, is_float)
            elif cid == CLS_RYU:
                output, e10 = (_f2d_np if is_float else _d2d_np)(b_bits)
            else:
                output, e10 = b_bits, b_sid * 0
        with PHASES.phase("emit"):
            p, l = _emit_np(output, e10, b_neg, b_sid, is_float)
        if whole:
            return p, l
        padded[rows_np] = p
        lens[rows_np] = l
    return padded, lens


def _strings_from_padded_np(padded, lens, validity):
    """Host mirror of columnar.column.strings_from_padded: identical
    offsets / pow2-cap chars layout, assembled in numpy and wrapped once
    (no per-piece device scatters on the host arm)."""
    lens = lens.astype(np.int32)
    offsets = np.concatenate(
        [np.zeros(1, np.int32), np.cumsum(lens, dtype=np.int32)]
    )
    total = int(offsets[-1])
    cap = next_pow2(total)
    chars = np.zeros(cap, np.uint8)
    w = padded.shape[1]
    mask = np.arange(w, dtype=np.int32)[None, :] < lens[:, None]
    # row-major boolean extraction IS the concatenation of each row's
    # first len bytes — no offset index matrix needed
    chars[:total] = padded[mask]
    return StringColumn(jnp.asarray(chars), jnp.asarray(offsets), validity)


def _device_render_enabled() -> bool:
    v = config.get("float_device_render")
    if v == "auto":
        return jax.default_backend() != "cpu"
    return bool(v)


def _special_id_expr(is_nan, is_inf, is_zero, negative):
    """0:"0.0" 1:"-0.0" 2:"Infinity" 3:"-Infinity" 4:"NaN"; -1 normal."""
    return jnp.where(
        is_nan,
        _I32(4),
        jnp.where(
            is_inf,
            jnp.where(negative, _I32(3), _I32(2)),
            jnp.where(is_zero, jnp.where(negative, _I32(1), _I32(0)), _I32(-1)),
        ),
    )


def _float_to_string_device(col: Column) -> StringColumn:
    """Device arm: value-class bucketed fast path, or (float_bucketed off)
    the monolithic whole-column oracle."""
    if col.dtype.kind == Kind.FLOAT64:
        bits = col.data.astype(jnp.int64).astype(jnp.uint64)
        negative = col.data.astype(jnp.int64) < 0
        mant = bits & _U64((1 << 52) - 1)
        expo = (bits >> _U64(52)) & _U64(0x7FF)
        is_nan = (expo == 0x7FF) & (mant != 0)
        is_inf = (expo == 0x7FF) & (mant == 0)
        is_zero = (expo == 0) & (mant == 0)
        is_float = False
    else:
        bits32 = f32_to_bits(col.data)
        bits = bits32.astype(jnp.uint64) & _M32
        negative = bits32 < 0
        mant = bits & _U64((1 << 23) - 1)
        expo = (bits >> _U64(23)) & _U64(0xFF)
        is_nan = (expo == 0xFF) & (mant != 0)
        is_inf = (expo == 0xFF) & (mant == 0)
        is_zero = (expo == 0) & (mant == 0)
        is_float = True

    special_id = _special_id_expr(is_nan, is_inf, is_zero, negative)

    if not config.get("float_bucketed"):
        # monolithic oracle: every row pays full Ryu + per-position emission
        with PHASES.phase("ryu"):
            output, e10 = (_f2d if is_float else _d2d)(bits)
        with PHASES.phase("emit"):
            padded, lens = _emit(output, e10, negative, special_id, is_float)
        return strings_from_padded(padded, lens, col.validity)

    with PHASES.phase("bucket"):
        cls = _classify_np(
            np.asarray(bits), np.asarray(special_id), is_float
        )
    padded, lens = _render_device(bits, negative, special_id, cls, is_float)
    with PHASES.phase("emit"):
        return strings_from_padded(padded, lens, col.validity)


def _float_to_string_host(col: Column) -> StringColumn:
    """Host-twin arm (XLA:CPU): classify + render entirely in numpy."""
    is_float = col.dtype.kind == Kind.FLOAT32
    with PHASES.phase("bucket"):
        if is_float:
            bits32 = np.asarray(col.data).view(np.int32)
            bits = bits32.astype(np.uint64) & np.uint64(0xFFFFFFFF)
            negative = bits32 < 0
            mant = bits & np.uint64((1 << 23) - 1)
            expo = (bits >> np.uint64(23)) & np.uint64(0xFF)
            is_nan = (expo == 0xFF) & (mant != 0)
            is_inf = (expo == 0xFF) & (mant == 0)
            is_zero = (expo == 0) & (mant == 0)
        else:
            data = np.asarray(col.data)  # int64 IEEE bit patterns
            bits = data.view(np.uint64)
            negative = data < 0
            mant = bits & np.uint64((1 << 52) - 1)
            expo = (bits >> np.uint64(52)) & np.uint64(0x7FF)
            is_nan = (expo == 0x7FF) & (mant != 0)
            is_inf = (expo == 0x7FF) & (mant == 0)
            is_zero = (expo == 0) & (mant == 0)
        special_id = np.where(
            is_nan,
            np.int32(4),
            np.where(
                is_inf,
                np.where(negative, np.int32(3), np.int32(2)),
                np.where(
                    is_zero,
                    np.where(negative, np.int32(1), np.int32(0)),
                    np.int32(-1),
                ),
            ),
        )
        cls = _classify_np(bits, special_id, is_float)
    padded, lens = _render_host(bits, negative, special_id, cls, is_float)
    with PHASES.phase("emit"):
        return _strings_from_padded_np(padded, lens, col.validity)


def float_to_string(col: Column) -> StringColumn:
    """Shortest round-trip decimal string of a FLOAT32/FLOAT64 column
    (spark_rapids_jni::float_to_string), backend-adaptive (round 20)."""
    if col.dtype.kind not in (Kind.FLOAT32, Kind.FLOAT64):
        raise TypeError("float_to_string requires FLOAT32 or FLOAT64")
    if _device_render_enabled():
        return _float_to_string_device(col)
    return _float_to_string_host(col)
