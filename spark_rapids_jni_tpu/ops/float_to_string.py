"""Java ``Double.toString`` / ``Float.toString`` — vectorized Ryu on TPU.

Capability parity with the reference's device Ryu port (ftos_converter.cuh:
d2d :480, f2d :575, to_chars :797/:922, special strings :259; driver
cast_float_to_string.cu:34-128): the shortest decimal representation that
round-trips, formatted per the Java spec — plain notation in [1e-3, 1e7),
scientific ``d.dddE±x`` otherwise, ``NaN`` / ``Infinity`` / ``-0.0`` specials.

The reference runs scalar Ryu per GPU thread.  Here every step is lane
arithmetic over the whole column:

- 128-bit multiplies decompose into 32-bit limb products in uint64 lanes
  (_umul128), with per-lane variable shifts;
- the power-of-5 tables are exact-precomputed host arrays (utils.ryu_tables)
  gathered per element;
- Ryu's shortest-search loop has a bounded trip count (<= 22 digit removals),
  so it unrolls into masked iterations;
- character emission is a batch scatter of (row, position) pairs into a padded
  byte matrix, rebuilt into an Arrow StringColumn.

FLOAT64 input is the int64 bit-pattern convention (columnar.column) — exactly
what Ryu wants: the algorithm never touches float arithmetic.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu.columnar.column import (
    Column,
    StringColumn,
    strings_from_padded,
)
from spark_rapids_jni_tpu.columnar.dtypes import Kind
from spark_rapids_jni_tpu.utils.floatbits import f32_to_bits
from spark_rapids_jni_tpu.utils import ryu_tables as rt

_U64 = jnp.uint64
_U32 = jnp.uint32
_I32 = jnp.int32
_M32 = jnp.uint64(0xFFFFFFFF)

MAX_D2S_LEN = 24  # sign + 17 digits + '.' + pad0 + 'E' + '-' + 3 exp digits

_POW10_U64 = jnp.asarray(np.array([10**k for k in range(20)], dtype=np.uint64))
_POW5_U64 = jnp.asarray(np.array([5**k for k in range(24)], dtype=np.uint64))


def _u64(x):
    return jnp.asarray(x, dtype=jnp.uint64)


def digit_table_u64(v, maxd: int = 20) -> jnp.ndarray:
    """``[n, maxd]`` uint8 decimal digits of u64 ``v``, index k = digit from
    the RIGHT (ones digit at k=0), zero-padded above the value's length.

    Built by an unrolled divide-by-constant-10 chain: each step is a
    strength-reduced multiply-high, so the whole table costs ~maxd cheap row
    ops.  Renderers then *gather* from it per output position — replacing
    per-grid-cell ``v // 10^k`` with a variable k, whose emulated-u64
    general division is the dominant term in the axon TPU compile-time
    pathology on the string-rendering ops (docs/PERF.md)."""
    ten = _U64(10)
    cols = []
    for _ in range(maxd):
        cols.append((v % ten).astype(jnp.uint8))
        v = v // ten
    return jnp.stack(cols, axis=-1)


def digit_from_table(tab: jnp.ndarray, k) -> jnp.ndarray:
    """ASCII digit chars gathered at (broadcast) right-index ``k``; out-of-
    range k clamps (callers mask those positions anyway)."""
    maxd = tab.shape[-1]
    kc = jnp.clip(k, 0, maxd - 1)
    if kc.ndim == tab.ndim - 1:
        kc = kc[..., None]
        return jnp.take_along_axis(tab, kc, axis=-1)[..., 0] + jnp.uint8(
            ord("0"))
    return jnp.take_along_axis(tab, kc, axis=-1) + jnp.uint8(ord("0"))


def _umul128(a, b):
    """(hi, lo) of the full 128-bit product of two u64 lane arrays."""
    a_lo, a_hi = a & _M32, a >> _U64(32)
    b_lo, b_hi = b & _M32, b >> _U64(32)
    ll = a_lo * b_lo
    lh = a_lo * b_hi
    hl = a_hi * b_lo
    hh = a_hi * b_hi
    mid = (ll >> _U64(32)) + (lh & _M32) + (hl & _M32)
    lo = (ll & _M32) | ((mid & _M32) << _U64(32))
    hi = hh + (lh >> _U64(32)) + (hl >> _U64(32)) + (mid >> _U64(32))
    return hi, lo


def _shiftright128(lo, hi, dist):
    """(hi:lo) >> dist for per-lane dist in (0, 64)."""
    dist = dist.astype(jnp.uint64)
    return (hi << (_U64(64) - dist)) | (lo >> dist)


def _mul_shift64(m, mul_lo, mul_hi, j):
    """Ryu mulShift64 (ftos_converter.cuh:375): ((m * mul) >> j) low 64."""
    hi1, lo1 = _umul128(m, mul_hi)
    hi0, _lo0 = _umul128(m, mul_lo)
    s = hi0 + lo1
    hi1 = hi1 + (s < hi0).astype(jnp.uint64)  # carry
    return _shiftright128(s, hi1, j - 64)


def _pow5bits(e):
    return ((e * _I32(1217359)) >> 19) + _I32(1)


def _log10_pow2(e):
    return (e * _I32(78913)) >> 18


def _log10_pow5(e):
    return (e * _I32(732923)) >> 20


def _multiple_of_pow5(value, q):
    """value % 5^q == 0 for q in [0, 23] lanes (exact u64 mod)."""
    return value % _POW5_U64[jnp.clip(q, 0, 23)] == 0


def _multiple_of_pow2(value, q):
    mask = (_U64(1) << jnp.clip(q, 0, 63).astype(jnp.uint64)) - _U64(1)
    return (value & mask) == 0


def _decimal_length_u64(v, max_digits):
    """number of decimal digits of v (>= 1)."""
    n = jnp.ones(v.shape, _I32)
    for k in range(1, max_digits):
        n = n + (v >= _POW10_U64[k]).astype(_I32)
    return n


def _d2d(bits):
    """Vectorized Ryu d2d (ftos_converter.cuh:480): bit patterns ->
    (mantissa u64, exponent i32) of the shortest decimal."""
    u = bits.astype(jnp.uint64)
    ieee_mantissa = u & _U64((1 << 52) - 1)
    ieee_exponent = ((u >> _U64(52)) & _U64(0x7FF)).astype(_I32)

    denormal = ieee_exponent == 0
    e2 = jnp.where(denormal, _I32(1 - 1023 - 52 - 2), ieee_exponent - (1023 + 52 + 2))
    m2 = jnp.where(denormal, ieee_mantissa, ieee_mantissa | _U64(1 << 52))
    even = (m2 & _U64(1)) == 0
    accept_bounds = even

    mv = _U64(4) * m2
    mm_shift = ((ieee_mantissa != 0) | (ieee_exponent <= 1)).astype(jnp.uint64)

    # --- branch A: e2 >= 0 (inverse powers of 5) ---
    qa = jnp.maximum(_log10_pow2(e2) - (e2 > 3).astype(_I32), 0)
    ka = _I32(rt.DOUBLE_POW5_INV_BITCOUNT) + _pow5bits(qa) - 1
    ja = -e2 + qa + ka  # shift argument
    qa_c = jnp.clip(qa, 0, len(rt.DOUBLE_POW5_INV_SPLIT_LO) - 1)
    inv_lo = jnp.asarray(rt.DOUBLE_POW5_INV_SPLIT_LO)[qa_c]
    inv_hi = jnp.asarray(rt.DOUBLE_POW5_INV_SPLIT_HI)[qa_c]
    vr_a = _mul_shift64(mv, inv_lo, inv_hi, ja)
    vp_a = _mul_shift64(mv + _U64(2), inv_lo, inv_hi, ja)
    vm_a = _mul_shift64(mv - _U64(1) - mm_shift, inv_lo, inv_hi, ja)
    e10_a = qa
    # trailing-zero flags (q <= 21 guard)
    guard_a = qa <= 21
    mv_mod5 = mv % _U64(5) == 0
    vr_tz_a = guard_a & mv_mod5 & _multiple_of_pow5(mv, qa)
    vm_tz_a = guard_a & ~mv_mod5 & accept_bounds & _multiple_of_pow5(
        mv - _U64(1) - mm_shift, qa
    )
    vp_a = vp_a - (
        guard_a & ~mv_mod5 & ~accept_bounds & _multiple_of_pow5(mv + _U64(2), qa)
    ).astype(jnp.uint64)

    # --- branch B: e2 < 0 (powers of 5) ---
    neg_e2 = -e2
    qb = jnp.maximum(_log10_pow5(neg_e2) - (neg_e2 > 1).astype(_I32), 0)
    ib = neg_e2 - qb
    kb = _pow5bits(ib) - _I32(rt.DOUBLE_POW5_BITCOUNT)
    jb = qb - kb
    ib_c = jnp.clip(ib, 0, len(rt.DOUBLE_POW5_SPLIT_LO) - 1)
    pw_lo = jnp.asarray(rt.DOUBLE_POW5_SPLIT_LO)[ib_c]
    pw_hi = jnp.asarray(rt.DOUBLE_POW5_SPLIT_HI)[ib_c]
    vr_b = _mul_shift64(mv, pw_lo, pw_hi, jb)
    vp_b = _mul_shift64(mv + _U64(2), pw_lo, pw_hi, jb)
    vm_b = _mul_shift64(mv - _U64(1) - mm_shift, pw_lo, pw_hi, jb)
    e10_b = qb + e2
    q_le1 = qb <= 1
    vr_tz_b = q_le1 | ((qb < 63) & _multiple_of_pow2(mv, qb))
    vm_tz_b = q_le1 & (mm_shift == 1)
    vp_b = vp_b - (q_le1 & ~accept_bounds).astype(jnp.uint64)

    pos = e2 >= 0
    vr = jnp.where(pos, vr_a, vr_b)
    vp = jnp.where(pos, vp_a, vp_b)
    vm = jnp.where(pos, vm_a, vm_b)
    e10 = jnp.where(pos, e10_a, e10_b)
    vm_tz = jnp.where(pos, vm_tz_a, vm_tz_b)
    vr_tz = jnp.where(pos, vr_tz_a, vr_tz_b)

    return _shortest_loop(vr, vp, vm, e10, vm_tz, vr_tz, accept_bounds, 22)


def _f2d(bits):
    """Vectorized Ryu f2d (ftos_converter.cuh:575) in u64 lanes."""
    u = bits.astype(jnp.uint64) & _M32
    ieee_mantissa = u & _U64((1 << 23) - 1)
    ieee_exponent = ((u >> _U64(23)) & _U64(0xFF)).astype(_I32)

    denormal = ieee_exponent == 0
    e2 = jnp.where(denormal, _I32(1 - 127 - 23 - 2), ieee_exponent - (127 + 23 + 2))
    m2 = jnp.where(denormal, ieee_mantissa, ieee_mantissa | _U64(1 << 23))
    even = (m2 & _U64(1)) == 0
    accept_bounds = even

    mv = _U64(4) * m2
    mp = mv + _U64(2)
    mm_shift = ((ieee_mantissa != 0) | (ieee_exponent <= 1)).astype(jnp.uint64)
    mm = mv - _U64(1) - mm_shift

    inv_tab = jnp.asarray(rt.FLOAT_POW5_INV_SPLIT)
    pow_tab = jnp.asarray(rt.FLOAT_POW5_SPLIT)

    def mul_pow5_inv_div_pow2(m, q, j):
        factor = inv_tab[jnp.clip(q, 0, len(rt.FLOAT_POW5_INV_SPLIT) - 1)]
        return _mul_shift32(m, factor, j)

    def mul_pow5_div_pow2(m, i, j):
        factor = pow_tab[jnp.clip(i, 0, len(rt.FLOAT_POW5_SPLIT) - 1)]
        return _mul_shift32(m, factor, j)

    # branch A: e2 >= 0
    qa = jnp.maximum(_log10_pow2(e2), 0)
    ka = _I32(rt.FLOAT_POW5_INV_BITCOUNT) + _pow5bits(qa) - 1
    ja = -e2 + qa + ka
    vr_a = mul_pow5_inv_div_pow2(mv, qa, ja)
    vp_a = mul_pow5_inv_div_pow2(mp, qa, ja)
    vm_a = mul_pow5_inv_div_pow2(mm, qa, ja)
    e10_a = qa
    la = _I32(rt.FLOAT_POW5_INV_BITCOUNT) + _pow5bits(jnp.maximum(qa - 1, 0)) - 1
    lrd_a = jnp.where(
        (qa != 0) & ((vp_a - _U64(1)) // _U64(10) <= vm_a // _U64(10)),
        mul_pow5_inv_div_pow2(mv, jnp.maximum(qa - 1, 0), -e2 + qa - 1 + la)
        % _U64(10),
        _U64(0),
    )
    guard_a = qa <= 9
    mv_mod5 = mv % _U64(5) == 0
    vr_tz_a = guard_a & mv_mod5 & _multiple_of_pow5(mv, qa)
    vm_tz_a = guard_a & ~mv_mod5 & accept_bounds & _multiple_of_pow5(mm, qa)
    vp_a = vp_a - (
        guard_a & ~mv_mod5 & ~accept_bounds & _multiple_of_pow5(mp, qa)
    ).astype(jnp.uint64)

    # branch B: e2 < 0
    neg_e2 = -e2
    qb = jnp.maximum(_log10_pow5(neg_e2), 0)
    ib = neg_e2 - qb
    kb = _pow5bits(ib) - _I32(rt.FLOAT_POW5_BITCOUNT)
    jb = qb - kb
    vr_b = mul_pow5_div_pow2(mv, ib, jb)
    vp_b = mul_pow5_div_pow2(mp, ib, jb)
    vm_b = mul_pow5_div_pow2(mm, ib, jb)
    e10_b = qb + e2
    jb2 = qb - 1 - (_pow5bits(ib + 1) - _I32(rt.FLOAT_POW5_BITCOUNT))
    lrd_b = jnp.where(
        (qb != 0) & ((vp_b - _U64(1)) // _U64(10) <= vm_b // _U64(10)),
        mul_pow5_div_pow2(mv, ib + 1, jb2) % _U64(10),
        _U64(0),
    )
    q_le1 = qb <= 1
    vr_tz_b = q_le1 | ((qb < 31) & _multiple_of_pow2(mv, jnp.maximum(qb - 1, 0)))
    vm_tz_b = q_le1 & (mm_shift == 1)
    vp_b = vp_b - (q_le1 & ~accept_bounds).astype(jnp.uint64)

    pos = e2 >= 0
    vr = jnp.where(pos, vr_a, vr_b)
    vp = jnp.where(pos, vp_a, vp_b)
    vm = jnp.where(pos, vm_a, vm_b)
    e10 = jnp.where(pos, e10_a, e10_b)
    vm_tz = jnp.where(pos, vm_tz_a, vm_tz_b)
    vr_tz = jnp.where(pos, vr_tz_a, vr_tz_b)
    lrd = jnp.where(pos, lrd_a, lrd_b)

    return _shortest_loop(
        vr, vp, vm, e10, vm_tz, vr_tz, accept_bounds, 11, last_removed=lrd
    )


def _mul_shift32(m, factor, shift):
    """Ryu mulShift32 (ftos_converter.cuh:242) in u64 lanes; shift > 32."""
    factor_lo = factor & _M32
    factor_hi = factor >> _U64(32)
    bits0 = m * factor_lo
    bits1 = m * factor_hi
    s = (bits0 >> _U64(32)) + bits1
    return s >> (shift.astype(jnp.uint64) - _U64(32))


def _shortest_loop(vr, vp, vm, e10, vm_tz, vr_tz, accept_bounds, max_iter,
                   last_removed=None):
    """Ryu step 4 (ftos_converter.cuh:570-650): masked unrolled digit removal.

    The reference's common-case div100 fast path is an optimization of the
    same recurrence; the general loop with correctly-initialized flags gives
    identical output for all lanes.
    """
    removed = jnp.zeros(vr.shape, _I32)
    lrd = jnp.zeros(vr.shape, jnp.uint64) if last_removed is None else last_removed

    for _ in range(max_iter):
        act = vp // _U64(10) > vm // _U64(10)
        vm_tz = jnp.where(act, vm_tz & (vm % _U64(10) == 0), vm_tz)
        vr_tz = jnp.where(act, vr_tz & (lrd == 0), vr_tz)
        lrd = jnp.where(act, vr % _U64(10), lrd)
        vr = jnp.where(act, vr // _U64(10), vr)
        vp = jnp.where(act, vp // _U64(10), vp)
        vm = jnp.where(act, vm // _U64(10), vm)
        removed = removed + act.astype(_I32)

    for _ in range(max_iter):
        act = vm_tz & (vm % _U64(10) == 0)
        vr_tz = jnp.where(act, vr_tz & (lrd == 0), vr_tz)
        lrd = jnp.where(act, vr % _U64(10), lrd)
        vr = jnp.where(act, vr // _U64(10), vr)
        vp = jnp.where(act, vp // _U64(10), vp)
        vm = jnp.where(act, vm // _U64(10), vm)
        removed = removed + act.astype(_I32)

    lrd = jnp.where(vr_tz & (lrd == 5) & (vr % _U64(2) == 0), _U64(4), lrd)
    round_up = ((vr == vm) & (~accept_bounds | ~vm_tz)) | (lrd >= 5)
    output = vr + round_up.astype(jnp.uint64)
    return output, e10 + removed


def _emit(output, exp10, negative, special_id, is_float):
    """Scatter the decimal into a padded byte matrix per Java formatting
    (to_chars, ftos_converter.cuh:797-893)."""
    n = output.shape[0]
    max_digits = 9 if is_float else 17
    olength = _decimal_length_u64(output, max_digits)
    exp = exp10 + olength - 1
    sci = (exp < -3) | (exp >= 7)
    s = negative.astype(_I32)

    out = jnp.full((n, MAX_D2S_LEN), 0, jnp.uint8)
    rows = jnp.arange(n, dtype=_I32)
    OOB = _I32(MAX_D2S_LEN)  # dropped by mode="drop"

    def put(pos, ch, mask):
        p = jnp.where(mask, pos, OOB)
        return lambda o: o.at[rows, p].set(ch, mode="drop")

    writes = []
    normal = special_id < 0

    # sign
    writes.append(put(jnp.zeros(n, _I32), jnp.uint8(ord("-")), normal & negative))

    # digits (MSB-first digit k = (output // 10^(olength-1-k)) % 10)
    plain_neg = normal & ~sci & (exp < 0)
    plain_big = normal & ~sci & (exp >= 0) & (exp + 1 >= olength)
    plain_mid = normal & ~sci & (exp >= 0) & (exp + 1 < olength)
    sci_m = normal & sci
    out_tab = digit_table_u64(output, max_digits)
    for k in range(max_digits):
        have = olength > k
        digit = digit_from_table(out_tab, olength - 1 - k)
        kk = _I32(k)
        writes.append(put(s + kk + (1 if k > 0 else 0), digit, sci_m & have))
        writes.append(put(s + 2 + (-exp - 1) + kk, digit, plain_neg & have))
        writes.append(put(s + kk, digit, plain_big & have))
        writes.append(put(s + kk + (kk > exp).astype(_I32), digit, plain_mid & have))

    dot = jnp.uint8(ord("."))
    zero_c = jnp.uint8(ord("0"))
    # scientific: '.', pad '0' when olength == 1, 'E', exp sign + digits
    writes.append(put(s + 1, dot, sci_m))
    writes.append(put(s + 2, zero_c, sci_m & (olength == 1)))
    p_e = s + olength + 1 + (olength == 1).astype(_I32)
    writes.append(put(p_e, jnp.uint8(ord("E")), sci_m))
    neg_e = exp < 0
    writes.append(put(p_e + 1, jnp.uint8(ord("-")), sci_m & neg_e))
    eabs = jnp.abs(exp)
    elen = 1 + (eabs >= 10).astype(_I32) + (eabs >= 100).astype(_I32)
    pe0 = p_e + 1 + neg_e.astype(_I32)
    # exponent digits MSB-first: digit j of the elen-digit number
    for j in range(3):
        have = elen > j
        p10 = jnp.asarray(np.array([1, 10, 100], np.int32))
        ed = ((eabs // p10[jnp.clip(elen - 1 - j, 0, 2)]) % 10).astype(
            jnp.uint8
        ) + zero_c
        writes.append(put(pe0 + j, ed, sci_m & have))

    # plain, exp < 0: "0." + (-exp-1) zeros + digits
    writes.append(put(s + 0, zero_c, plain_neg))
    writes.append(put(s + 1, dot, plain_neg))
    for t in range(2):  # exp >= -3 -> at most 2 leading zeros
        writes.append(put(s + 2 + t, zero_c, plain_neg & (-exp - 1 > t)))

    # plain, exp+1 >= olength: digits + zeros + ".0"
    for t in range(7):  # exp < 7 -> at most 7 trailing zeros
        writes.append(
            put(s + olength + t, zero_c, plain_big & (exp + 1 - olength > t))
        )
    writes.append(put(s + exp + 1, dot, plain_big))
    writes.append(put(s + exp + 2, zero_c, plain_big))

    # plain, dot between digits
    writes.append(put(s + exp + 1, dot, plain_mid))

    for w in writes:
        out = w(out)

    # lengths (d2s_size, ftos_converter.cuh:877-906)
    len_sci = s + olength + 1 + (olength == 1).astype(_I32) + 1 + neg_e.astype(_I32) + elen
    len_pn = s + 1 - exp + olength
    len_pb = s + exp + 3
    len_pm = s + olength + 1
    lens = jnp.where(
        sci, len_sci, jnp.where(exp < 0, len_pn, jnp.where(exp + 1 >= olength, len_pb, len_pm))
    )

    # specials: 0:"0.0" 1:"-0.0" 2:"Infinity" 3:"-Infinity" 4:"NaN"
    specials = ["0.0", "-0.0", "Infinity", "-Infinity", "NaN"]
    tab = np.zeros((5, MAX_D2S_LEN), np.uint8)
    slen = np.zeros(5, np.int32)
    for i, sp in enumerate(specials):
        b = sp.encode()
        tab[i, : len(b)] = np.frombuffer(b, np.uint8)
        slen[i] = len(b)
    sid = jnp.clip(special_id, 0, 4)
    out = jnp.where(normal[:, None], out, jnp.asarray(tab)[sid])
    lens = jnp.where(normal, lens, jnp.asarray(slen)[sid])
    return out, lens


def float_to_string(col: Column) -> StringColumn:
    """Shortest round-trip decimal string of a FLOAT32/FLOAT64 column
    (spark_rapids_jni::float_to_string)."""
    if col.dtype.kind == Kind.FLOAT64:
        bits = col.data.astype(jnp.int64).astype(jnp.uint64)
        negative = (col.data.astype(jnp.int64) < 0)
        mant = bits & _U64((1 << 52) - 1)
        expo = (bits >> _U64(52)) & _U64(0x7FF)
        is_nan = (expo == 0x7FF) & (mant != 0)
        is_inf = (expo == 0x7FF) & (mant == 0)
        is_zero = (expo == 0) & (mant == 0)
        output, e10 = _d2d(bits)
        is_float = False
    elif col.dtype.kind == Kind.FLOAT32:
        bits32 = f32_to_bits(col.data)
        bits = bits32.astype(jnp.uint64) & _M32
        negative = bits32 < 0
        mant = bits & _U64((1 << 23) - 1)
        expo = (bits >> _U64(23)) & _U64(0xFF)
        is_nan = (expo == 0xFF) & (mant != 0)
        is_inf = (expo == 0xFF) & (mant == 0)
        is_zero = (expo == 0) & (mant == 0)
        output, e10 = _f2d(bits)
        is_float = True
    else:
        raise TypeError("float_to_string requires FLOAT32 or FLOAT64")

    special_id = jnp.where(
        is_nan,
        _I32(4),
        jnp.where(
            is_inf,
            jnp.where(negative, _I32(3), _I32(2)),
            jnp.where(is_zero, jnp.where(negative, _I32(1), _I32(0)), _I32(-1)),
        ),
    )
    padded, lens = _emit(output, e10, negative, special_id, is_float)
    return strings_from_padded(padded, lens, col.validity)
