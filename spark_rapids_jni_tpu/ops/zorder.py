"""DeltaLake z-order clustering ops: interleave_bits and hilbert_index.

Spark-exact semantics of the reference's zorder ops (zorder.cu:138 interleave_bits,
zorder.cu:224 hilbert_index; Hilbert transform per David Moten's port of Skilling's
"Programming the Hilbert curve", zorder.cu:66-74).

The reference computes one output byte per GPU thread with per-bit gather loops.
On TPU both ops are reformulated as dense bit-plane arithmetic:

- ``interleave_bits``: each value is exploded to a big-endian bit plane
  ``bits[n, width]``; the interleave is then a single static-permutation gather
  producing ``bits[n, width*ncols]``, packed back to bytes with a matmul-free
  shift-or reduction.  The permutation is computed host-side (shapes are static
  under jit) so XLA sees a plain gather — no per-bit control flow.
- ``hilbert_index``: Skilling's inverse-undo loop has a static trip count
  (num_bits x num_dims <= 64), so it fully unrolls into vectorized xor/select
  lane ops over ``x[dim][n]`` arrays; the data-dependent branches become
  ``jnp.where`` selects.

Null handling matches the reference: null cells read as 0 and the outputs carry
no null mask (zorder.cu:205-207,:262).
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp

from spark_rapids_jni_tpu.utils.floatbits import f32_to_bits
from spark_rapids_jni_tpu.columnar.column import Column, ListColumn
from spark_rapids_jni_tpu.columnar.dtypes import DType, Kind, UINT8


def _to_bit_planes(col: Column, width_bits: int) -> jnp.ndarray:
    """``bits[n, width_bits]`` of each value, most significant bit first.

    Nulls read as 0 (matches zorder.cu:205 ``column.is_valid(...) ? data : 0``).
    """
    # Widen through uint64 so the shift is well-defined for every input width.
    data = col.data
    if col.dtype.kind == Kind.FLOAT32:
        # interleave operates on the IEEE-754 bit pattern, not the value
        # (FLOAT64 columns already store their bits in int64; see columnar.column).
        data = f32_to_bits(data)
    if data.dtype == jnp.bool_:
        v = data.astype(jnp.uint64)
    else:
        # signed -> unsigned reinterpret of the low `width_bits` bits
        v = data.astype(jnp.int64).astype(jnp.uint64) & jnp.uint64(
            (1 << width_bits) - 1 if width_bits < 64 else 0xFFFFFFFFFFFFFFFF
        )
    if col.validity is not None:
        v = jnp.where(col.validity, v, jnp.uint64(0))
    shifts = jnp.arange(width_bits - 1, -1, -1, dtype=jnp.uint64)
    return ((v[:, None] >> shifts[None, :]) & jnp.uint64(1)).astype(jnp.uint8)


def _pack_bits_to_bytes(bits: jnp.ndarray) -> jnp.ndarray:
    """``bits[n, 8*k]`` (MSB first) -> ``bytes[n, k]`` uint8."""
    n, total = bits.shape
    assert total % 8 == 0
    grouped = bits.reshape(n, total // 8, 8).astype(jnp.uint8)
    weights = (jnp.uint8(1) << jnp.arange(7, -1, -1, dtype=jnp.uint8))[None, None, :]
    return jnp.sum(grouped * weights, axis=-1, dtype=jnp.uint8)


def interleave_bits(columns: Sequence[Column]) -> ListColumn:
    """DeltaLake ``interleaveBits``: LIST<UINT8> of round-robin interleaved bits.

    Bit ``b`` (MSB-first) of every column is emitted before bit ``b+1`` of any,
    column 0 first — the deltalake source-of-truth loop shape
    (InterleaveBitsTest.java:44-66).  Output row width is
    ``ncols * value_byte_width`` bytes.
    """
    if not columns:
        raise ValueError("The input table must have at least one column.")
    kinds = {c.dtype.kind for c in columns}
    if len(kinds) != 1:
        raise TypeError("All columns of the input table must be the same type.")
    width_bytes = columns[0].dtype.fixed_width
    if width_bytes == 0 or not all(isinstance(c, Column) for c in columns):
        raise TypeError("Only fixed width columns can be used")
    if any(c.size != columns[0].size for c in columns):
        raise ValueError("All columns of the input table must be the same size.")
    n = columns[0].size
    ncols = len(columns)
    width_bits = width_bytes * 8

    # bits[n, ncols, width_bits] -> transpose to [n, width_bits, ncols] so that
    # flattening yields (bit0 of col0, bit0 of col1, ..., bit1 of col0, ...).
    planes = jnp.stack([_to_bit_planes(c, width_bits) for c in columns], axis=1)
    interleaved = jnp.transpose(planes, (0, 2, 1)).reshape(n, width_bits * ncols)
    data = _pack_bits_to_bytes(interleaved).reshape(n * width_bytes * ncols)

    row_bytes = width_bytes * ncols
    offsets = jnp.arange(n + 1, dtype=jnp.int32) * row_bytes
    child = Column(data, None, UINT8)
    return ListColumn(offsets, child, None)


def hilbert_index(num_bits_per_entry: int, columns: Sequence[Column]) -> Column:
    """Hilbert-curve distance of each row's point (zorder.cu:224).

    Each INT32 column is one coordinate using the low ``num_bits_per_entry``
    bits; the result is the INT64 position along the ``ndims``-dimensional
    Hilbert curve (Skilling transpose + gray decode, zorder.cu:95-133).
    """
    if not (0 < num_bits_per_entry <= 32):
        raise ValueError("the number of bits must be >0 and <= 32.")
    if not columns:
        raise ValueError("at least one column is required.")
    ndims = len(columns)
    if num_bits_per_entry * ndims > 64:
        raise ValueError("we only support up to 64 bits of output right now.")
    for c in columns:
        if not isinstance(c, Column) or c.dtype.kind != Kind.INT32:
            raise TypeError("All columns of the input table must be INT32.")
        if c.size != columns[0].size:
            raise ValueError("All columns of the input table must be the same size.")

    nb = num_bits_per_entry
    mask_val = jnp.uint32((1 << nb) - 1) if nb < 32 else jnp.uint32(0xFFFFFFFF)
    x = []
    for c in columns:
        v = c.data.astype(jnp.uint32) & mask_val
        if c.validity is not None:
            v = jnp.where(c.validity, v, jnp.uint32(0))
        x.append(v)

    # Inverse undo (static unroll: nb-1 outer x ndims inner iterations).
    m = 1 << (nb - 1)
    q = m
    while q > 1:
        p = jnp.uint32(q - 1)
        for i in range(ndims):
            cond = (x[i] & jnp.uint32(q)) != 0
            if i == 0:
                x[0] = jnp.where(cond, x[0] ^ p, x[0])
            else:
                t = (x[0] ^ x[i]) & p
                x0_else, xi_else = x[0] ^ t, x[i] ^ t
                x[0] = jnp.where(cond, x[0] ^ p, x0_else)
                x[i] = jnp.where(cond, x[i], xi_else)
        q >>= 1

    # Gray encode.
    for i in range(1, ndims):
        x[i] = x[i] ^ x[i - 1]
    # analyze: ignore[governed-allocation] - hilbert_index is not yet
    # wired into a governed pipeline (bench/oracle callers only); the
    # transient is O(rows) alongside the caller's own arrays.  Debt
    # tracked HERE (round 16 baseline burn-down), not in the baseline.
    t = jnp.zeros_like(x[0])
    q = m
    while q > 1:
        t = jnp.where((x[ndims - 1] & jnp.uint32(q)) != 0, t ^ jnp.uint32(q - 1), t)
        q >>= 1
    for i in range(ndims):
        x[i] = x[i] ^ t

    # Transposed form -> distance: bit (nb-1-i) of each dim j, MSB-first
    # (zorder.cu:76-93 to_hilbert_index).
    # analyze: ignore[governed-allocation] - same ungoverned-caller debt
    # as the transient above (tracked at the site, round 16)
    b = jnp.zeros(x[0].shape, dtype=jnp.uint64)
    for i in range(nb - 1, -1, -1):
        for j in range(ndims):
            bit = ((x[j] >> jnp.uint32(i)) & jnp.uint32(1)).astype(jnp.uint64)
            b = (b << jnp.uint64(1)) | bit
    return Column(b.astype(jnp.int64), None, DType(Kind.INT64))
