"""Pallas TPU kernels for the murmur3 fixed-width hot path.

The XLA path in ``hashing.py`` expresses the Spark murmur3 chain as ~10
fused elementwise u32 ops; XLA handles that well, but it leaves tiling to
the compiler and re-materializes the running-hash vector between column
contributions at HBM.  These kernels express one column *contribution*
(running hash in, updated hash out — the unit from which
``murmur_hash32`` chains columns, reference murmur_hash.cu:44-48) as a
single VMEM-resident Pallas kernel:

- ``mm_hash_int_pallas``  == hashing._mm_hash_int  (one 4-byte round + fmix)
- ``mm_hash_long_pallas`` == hashing._mm_hash_long (two rounds + fmix)

Everything is uint32 lane arithmetic — no 64-bit types enter the kernel
(the TPU x64 rewrite has no 64-bit bitcast; int64 inputs are split into
u32 limbs *outside* with plain shifts, which the rewrite does support).

Off-TPU the kernels run in Pallas interpret mode, so correctness is
CI-testable on the CPU mesh; selection is via the ``hash_backend`` config
flag ("xla" default, "pallas" to route murmur3 fixed-width contributions
here).  On hardware the two backends are A/B benchable
(tools/perf_capture.py sweep).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_U32 = jnp.uint32

# VMEM block: 512 sublane-rows x 128 lanes of u32 = 256 KiB per operand.
_BLOCK_ROWS = 512
_LANES = 128
_TILE = _BLOCK_ROWS * _LANES


def _use_interpret() -> bool:
    # Mosaic lowering needs a real TPU; everywhere else (CPU mesh tests,
    # debugging) the interpreter executes the same kernel semantics.
    return jax.default_backend() not in ("tpu", "axon")


# ---- kernel bodies (u32 lane math, mirrors hashing.py primitives) --------


def _mix_k1(k1):
    k1 = k1 * _U32(0xCC9E2D51)
    k1 = (k1 << _U32(15)) | (k1 >> _U32(17))
    return k1 * _U32(0x1B873593)


def _mix_h1(h1, k1):
    h1 = h1 ^ k1
    h1 = (h1 << _U32(13)) | (h1 >> _U32(19))
    return h1 * _U32(5) + _U32(0xE6546B64)


def _fmix(h, length_u32):
    h = h ^ length_u32
    h = h ^ (h >> _U32(16))
    h = h * _U32(0x85EBCA6B)
    h = h ^ (h >> _U32(13))
    h = h * _U32(0xC2B2AE35)
    return h ^ (h >> _U32(16))


def _int_kernel(v_ref, h_ref, out_ref):
    out_ref[:] = _fmix(_mix_h1(h_ref[:], _mix_k1(v_ref[:])), _U32(4))


def _long_kernel(lo_ref, hi_ref, h_ref, out_ref):
    h = _mix_h1(h_ref[:], _mix_k1(lo_ref[:]))
    h = _mix_h1(h, _mix_k1(hi_ref[:]))
    out_ref[:] = _fmix(h, _U32(8))


# ---- blocking helpers -----------------------------------------------------


def _to_blocks(x_u32: jnp.ndarray) -> jnp.ndarray:
    """[n] u32 -> [R, 128] u32, R a multiple of _BLOCK_ROWS (zero padded)."""
    n = x_u32.shape[0]
    pad = (-n) % _TILE
    if pad:
        x_u32 = jnp.pad(x_u32, (0, pad))
    return x_u32.reshape(-1, _LANES)


@functools.partial(jax.jit, static_argnames=("n_inputs",))
def _launch(n_inputs, *flat_u32):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    blocks = [_to_blocks(x) for x in flat_u32]
    rows = blocks[0].shape[0]
    kernel = _int_kernel if n_inputs == 2 else _long_kernel
    spec = pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        kernel,
        grid=(rows // _BLOCK_ROWS,),
        in_specs=[spec] * n_inputs,
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((rows, _LANES), _U32),
        interpret=_use_interpret(),
    )(*blocks)
    return out.reshape(-1)


def mm_hash_int_pallas(v_i32: jnp.ndarray, h_u32: jnp.ndarray) -> jnp.ndarray:
    """Pallas twin of hashing._mm_hash_int (Spark Murmur3.hashInt round)."""
    n = v_i32.shape[0]
    if n == 0:
        return jnp.zeros((0,), _U32)
    h = jnp.broadcast_to(jnp.asarray(h_u32, _U32), (n,))  # scalar seeds ok
    return _launch(2, v_i32.astype(_U32), h)[:n]


def mm_hash_long_pallas(v_i64: jnp.ndarray, h_u32: jnp.ndarray) -> jnp.ndarray:
    """Pallas twin of hashing._mm_hash_long; 64-bit split happens out here
    (shifts only — safe under the u32-pair x64 rewrite)."""
    n = v_i64.shape[0]
    if n == 0:
        return jnp.zeros((0,), _U32)
    v = v_i64.astype(jnp.uint64)
    lo = (v & jnp.uint64(0xFFFFFFFF)).astype(_U32)
    hi = (v >> jnp.uint64(32)).astype(_U32)
    h = jnp.broadcast_to(jnp.asarray(h_u32, _U32), (n,))
    return _launch(3, lo, hi, h)[:n]
