"""Pallas TPU kernels for the murmur3 fixed-width hot path.

The XLA path in ``hashing.py`` expresses the Spark murmur3 chain as ~10
fused elementwise u32 ops; XLA handles that well, but it leaves tiling to
the compiler and re-materializes the running-hash vector between column
contributions at HBM.  These kernels express one column *contribution*
(running hash in, updated hash out — the unit from which
``murmur_hash32`` chains columns, reference murmur_hash.cu:44-48) as a
single VMEM-resident Pallas kernel:

- ``mm_hash_int_pallas``  == hashing._mm_hash_int  (one 4-byte round + fmix)
- ``mm_hash_long_pallas`` == hashing._mm_hash_long (two rounds + fmix)

Everything is uint32 lane arithmetic — no 64-bit types enter the kernel
(the TPU x64 rewrite has no 64-bit bitcast; int64 inputs are split into
u32 limbs *outside* with plain shifts, which the rewrite does support).

Off-TPU the kernels run in Pallas interpret mode, so correctness is
CI-testable on the CPU mesh; selection is via the ``hash_backend`` config
flag ("xla" default, "pallas" to route murmur3 fixed-width contributions
here).  On hardware the two backends are A/B benchable
(tools/perf_capture.py sweep).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_U32 = jnp.uint32

# Index-map constants must be explicitly 32-bit: under jax_enable_x64 a
# plain Python ``0`` lowers as i64 and Mosaic rejects the index-map
# function ("failed to legalize 'func.return' (i32, i64)") — reproduced
# and fixed against the live v5e backend (round 5).
_I0 = np.int32(0)

# VMEM block: 512 sublane-rows x 128 lanes of u32 = 256 KiB per operand.
_BLOCK_ROWS = 512
_LANES = 128
_TILE = _BLOCK_ROWS * _LANES


def _use_interpret() -> bool:
    # Mosaic lowering needs a real TPU; everywhere else (CPU mesh tests,
    # debugging) the interpreter executes the same kernel semantics.
    return jax.default_backend() not in ("tpu", "axon")


# ---- kernel bodies (u32 lane math, mirrors hashing.py primitives) --------


def _mix_k1(k1):
    k1 = k1 * _U32(0xCC9E2D51)
    k1 = (k1 << _U32(15)) | (k1 >> _U32(17))
    return k1 * _U32(0x1B873593)


def _mix_h1(h1, k1):
    h1 = h1 ^ k1
    h1 = (h1 << _U32(13)) | (h1 >> _U32(19))
    return h1 * _U32(5) + _U32(0xE6546B64)


def _fmix(h, length_u32):
    h = h ^ length_u32
    h = h ^ (h >> _U32(16))
    h = h * _U32(0x85EBCA6B)
    h = h ^ (h >> _U32(13))
    h = h * _U32(0xC2B2AE35)
    return h ^ (h >> _U32(16))


# ---- u64-as-u32-limb-pair arithmetic (xxhash64 kernels) -------------------
# No 64-bit types exist inside Mosaic on this TPU; every u64 op is spelled
# in u32 lanes, multiplies via 16-bit splits (four 16x16->32 partials).
# Constants stay PYTHON ints (Pallas rejects closed-over array constants);
# the helpers accept int or u32-array operands interchangeably.


def _lo16(x):
    return x & 0xFFFF


def _hi16(x):
    return x >> 16


def _mul32_full(a, b):
    """(hi, lo) u32 pair = full 64-bit product of two u32 lanes/ints."""
    a0, a1 = _lo16(a), _hi16(a)
    b0, b1 = _lo16(b), _hi16(b)
    p00 = a0 * b0
    p01 = a0 * b1
    p10 = a1 * b0
    p11 = a1 * b1
    mid = _hi16(p00) + _lo16(p01) + _lo16(p10)
    lo = _lo16(p00) | (_lo16(mid) << 16)
    hi = p11 + _hi16(p01) + _hi16(p10) + _hi16(mid)
    return hi, lo


def _mul64(ah, al, bh, bl):
    """Low 64 bits of a 64x64 product, as a (hi, lo) u32 pair."""
    hi, lo = _mul32_full(al, bl)
    return hi + al * bh + ah * bl, lo


def _add64(ah, al, bh, bl):
    lo = al + bl
    carry = (lo < al).astype(_U32)
    return ah + bh + carry, lo


def _rotl64(h, l, r: int):
    if r == 32:
        return l, h
    if r < 32:
        return ((h << r) | (l >> (32 - r)), (l << r) | (h >> (32 - r)))
    r -= 32
    return ((l << r) | (h >> (32 - r)), (h << r) | (l >> (32 - r)))


def _shr64(h, l, r: int):
    if r == 32:
        return jnp.zeros_like(h), h
    if r < 32:
        return h >> r, (l >> r) | (h << (32 - r))
    return jnp.zeros_like(h), h >> (r - 32)


def _xor64(ah, al, bh, bl):
    return ah ^ bh, al ^ bl


def _p(v: int):
    return v >> 32, v & 0xFFFFFFFF


# xxhash64 primes as (hi, lo) int pairs (hashing.py _XX_P*)
_XP1 = _p(0x9E3779B185EBCA87)
_XP2 = _p(0xC2B2AE3D27D4EB4F)
_XP3 = _p(0x165667B19E3779F9)
_XP4 = _p(0x85EBCA77C2B2AE63)
_XP5_PLUS_4 = _p((0x27D4EB2F165667C5 + 4) & ((1 << 64) - 1))
_XP5_PLUS_8 = _p((0x27D4EB2F165667C5 + 8) & ((1 << 64) - 1))


def _xx_finalize_pair(h, l):
    h, l = _xor64(h, l, *_shr64(h, l, 33))
    h, l = _mul64(h, l, *_XP2)
    h, l = _xor64(h, l, *_shr64(h, l, 29))
    h, l = _mul64(h, l, *_XP3)
    return _xor64(h, l, *_shr64(h, l, 32))


def _xx4_kernel(v_ref, sh_ref, sl_ref, oh_ref, ol_ref):
    """xxhash64 of one 4-byte value per lane (hashing._xx_hash_fixed4)."""
    h, l = _add64(sh_ref[:], sl_ref[:], *_XP5_PLUS_4)
    wh, wl = _mul64(jnp.zeros_like(h), v_ref[:], *_XP1)
    h, l = _xor64(h, l, wh, wl)
    h, l = _rotl64(h, l, 23)
    h, l = _mul64(h, l, *_XP2)
    h, l = _add64(h, l, *_XP3)
    oh_ref[:], ol_ref[:] = _xx_finalize_pair(h, l)


def _xx8_kernel(vh_ref, vl_ref, sh_ref, sl_ref, oh_ref, ol_ref):
    """xxhash64 of one 8-byte value per lane (hashing._xx_hash_fixed8)."""
    h, l = _add64(sh_ref[:], sl_ref[:], *_XP5_PLUS_8)
    kh, kl = _mul64(vh_ref[:], vl_ref[:], *_XP2)
    kh, kl = _rotl64(kh, kl, 31)
    kh, kl = _mul64(kh, kl, *_XP1)
    h, l = _xor64(h, l, kh, kl)
    h, l = _rotl64(h, l, 27)
    h, l = _mul64(h, l, *_XP1)
    h, l = _add64(h, l, *_XP4)
    oh_ref[:], ol_ref[:] = _xx_finalize_pair(h, l)


def _int_kernel(v_ref, h_ref, out_ref):
    out_ref[:] = _fmix(_mix_h1(h_ref[:], _mix_k1(v_ref[:])), _U32(4))


def _long_kernel(lo_ref, hi_ref, h_ref, out_ref):
    h = _mix_h1(h_ref[:], _mix_k1(lo_ref[:]))
    h = _mix_h1(h, _mix_k1(hi_ref[:]))
    out_ref[:] = _fmix(h, _U32(8))


# ---- blocking helpers -----------------------------------------------------


def _block_rows_for(n: int) -> int:
    """Row-block height for n elements: full _BLOCK_ROWS for large inputs,
    a pow2-rounded smaller block for small ones so a few-hundred-row
    length bucket doesn't pad (and compute over) a 65k-lane tile."""
    rows_needed = max(1, -(-n // _LANES))
    return min(_BLOCK_ROWS, max(8, 1 << (rows_needed - 1).bit_length()))


def _to_blocks(x, dtype, block_rows: int) -> jnp.ndarray:
    """[n] -> [R, 128] of ``dtype``, R a multiple of block_rows (0-pad)."""
    x = jnp.asarray(x, dtype)
    n = x.shape[0]
    pad = (-n) % (block_rows * _LANES)
    if pad:
        x = jnp.pad(x, (0, pad))
    return x.reshape(-1, _LANES)


_KERNELS = {  # name -> (kernel_fn, n_outputs); one launch scaffold for all
    "mm_int": (_int_kernel, 1),
    "mm_long": (_long_kernel, 1),
    "xx4": (_xx4_kernel, 2),
    "xx8": (_xx8_kernel, 2),
}


@functools.partial(jax.jit, static_argnames=("kern",))
def _launch(kern, *flat_u32):
    """Shared row-block launch scaffold for every elementwise hash kernel:
    one place owns block sizing, VMEM specs, grid, and interpret gating."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    kernel, n_out = _KERNELS[kern]
    br = _block_rows_for(flat_u32[0].shape[0])
    blocks = [_to_blocks(x, _U32, br) for x in flat_u32]
    rows = blocks[0].shape[0]
    spec = pl.BlockSpec((br, _LANES), lambda i: (i, _I0),
                        memory_space=pltpu.VMEM)
    shape = jax.ShapeDtypeStruct((rows, _LANES), _U32)
    out = pl.pallas_call(
        kernel,
        grid=(rows // br,),
        in_specs=[spec] * len(blocks),
        out_specs=spec if n_out == 1 else (spec,) * n_out,
        out_shape=shape if n_out == 1 else (shape,) * n_out,
        interpret=_use_interpret(),
    )(*blocks)
    if n_out == 1:
        return out.reshape(-1)
    return tuple(o.reshape(-1) for o in out)


def _bytes_words_kernel(words_ref, h_ref, nw_ref, out_ref):
    """One murmur word round for one (row-block, word) grid step.

    TPU grids execute sequentially with the word index as the
    fastest-varying dimension, so ``out_ref`` (same block for every w of a
    row block) carries the running hash across the whole word loop in
    VMEM — the lax.scan path re-materializes that carry through the XLA
    loop instead.
    """
    import jax.experimental.pallas as pl

    w = pl.program_id(1)

    @pl.when(w == 0)
    def _():
        out_ref[:] = h_ref[:]

    word = words_ref[0]
    h = out_ref[:]
    upd = _mix_h1(h, _mix_k1(word))
    out_ref[:] = jnp.where(w < nw_ref[:], upd, h)


def mm_bytes_words_pallas(words: jnp.ndarray, nwords: jnp.ndarray,
                          h_u32: jnp.ndarray) -> jnp.ndarray:
    """All aligned-word murmur rounds of hashUnsafeBytes as one Pallas
    kernel: ``words`` [n, Lw] u32, ``nwords`` [n] valid-word counts,
    ``h_u32`` [n] running hashes -> updated [n] hashes.  The <=3 tail-byte
    rounds + fmix stay in the caller (hashing._mm_bytes_tail)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, lw = words.shape
    if n == 0 or lw == 0:
        return jnp.broadcast_to(jnp.asarray(h_u32, _U32), (n,))

    br = _block_rows_for(n)
    h2 = _to_blocks(jnp.broadcast_to(jnp.asarray(h_u32, _U32), (n,)),
                    _U32, br)
    nw2 = _to_blocks(nwords, jnp.int32, br)
    rows = h2.shape[0]
    # words -> [Lw, R, 128] so each grid step streams one word-column block
    wpad = jnp.pad(words, ((0, rows * _LANES - n), (0, 0)))
    w3 = wpad.T.reshape(lw, rows, _LANES)

    row_spec = pl.BlockSpec((br, _LANES), lambda i, w: (i, _I0),
                            memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        _bytes_words_kernel,
        grid=(rows // br, lw),
        in_specs=[
            pl.BlockSpec((1, br, _LANES), lambda i, w: (w, i, _I0),
                         memory_space=pltpu.VMEM),
            row_spec,
            row_spec,
        ],
        out_specs=row_spec,
        out_shape=jax.ShapeDtypeStruct((rows, _LANES), _U32),
        interpret=_use_interpret(),
    )(w3, h2, nw2)
    return out.reshape(-1)[:n]





def _seed_limbs(seed, n):
    s = jnp.broadcast_to(jnp.asarray(seed, jnp.uint64), (n,))
    return ((s >> jnp.uint64(32)).astype(_U32),
            (s & jnp.uint64(0xFFFFFFFF)).astype(_U32))


def _pair_to_u64(oh, ol, n):
    return ((oh[:n].astype(jnp.uint64) << jnp.uint64(32))
            | ol[:n].astype(jnp.uint64))


def xx_hash_fixed4_pallas(v_u32: jnp.ndarray, seed) -> jnp.ndarray:
    """Pallas twin of hashing._xx_hash_fixed4; all 64-bit arithmetic runs
    as u32 limb pairs in VMEM (16-bit-split multiplies) instead of the
    XLA x64 rewrite's generic emulation."""
    n = v_u32.shape[0]
    if n == 0:
        return jnp.zeros((0,), jnp.uint64)
    sh, sl = _seed_limbs(seed, n)
    oh, ol = _launch("xx4", v_u32.astype(_U32), sh, sl)
    return _pair_to_u64(oh, ol, n)


def xx_hash_fixed8_pallas(v_u64: jnp.ndarray, seed) -> jnp.ndarray:
    """Pallas twin of hashing._xx_hash_fixed8 (8-byte values)."""
    n = v_u64.shape[0]
    if n == 0:
        return jnp.zeros((0,), jnp.uint64)
    v = jnp.asarray(v_u64, jnp.uint64)
    vh = (v >> jnp.uint64(32)).astype(_U32)
    vl = (v & jnp.uint64(0xFFFFFFFF)).astype(_U32)
    sh, sl = _seed_limbs(seed, n)
    oh, ol = _launch("xx8", vh, vl, sh, sl)
    return _pair_to_u64(oh, ol, n)


def mm_hash_int_pallas(v_i32: jnp.ndarray, h_u32: jnp.ndarray) -> jnp.ndarray:
    """Pallas twin of hashing._mm_hash_int (Spark Murmur3.hashInt round)."""
    n = v_i32.shape[0]
    if n == 0:
        return jnp.zeros((0,), _U32)
    h = jnp.broadcast_to(jnp.asarray(h_u32, _U32), (n,))  # scalar seeds ok
    return _launch("mm_int", v_i32.astype(_U32), h)[:n]


def mm_hash_long_pallas(v_i64: jnp.ndarray, h_u32: jnp.ndarray) -> jnp.ndarray:
    """Pallas twin of hashing._mm_hash_long; 64-bit split happens out here
    (shifts only — safe under the u32-pair x64 rewrite)."""
    n = v_i64.shape[0]
    if n == 0:
        return jnp.zeros((0,), _U32)
    v = v_i64.astype(jnp.uint64)
    lo = (v & jnp.uint64(0xFFFFFFFF)).astype(_U32)
    hi = (v >> jnp.uint64(32)).astype(_U32)
    h = jnp.broadcast_to(jnp.asarray(h_u32, _U32), (n,))
    return _launch("mm_long", lo, hi, h)[:n]
