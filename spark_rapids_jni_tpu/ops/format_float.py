"""Spark ``format_number`` for float columns (``#,###,###.##`` layout).

Parity with the reference's format_float (format_float.cu:113; layout kernel
to_formatted_chars ftos_converter.cuh:1271-1383, round_half_even :1247,
specials copy_format_special_str :1413-1432): Ryu shortest digits, grouped
with commas, rounded half-even to a fixed number of fraction digits;
NaN -> U+FFFD, +-inf -> U+221E, zero keeps its sign ("-0.00000").

Vectorization: reuses the Ryu cores (_d2d/_f2d) for (mantissa, exponent),
then renders every output byte position with grid arithmetic over
``[rows, width]`` — each position computes its distance-from-the-right ``q``,
decides comma (q % 4 == 3) vs digit (q - q//4), and gathers the digit — so
the reference's per-thread reverse-writing loops become pure lane math with
no data-dependent control flow.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu.columnar.column import (
    Column,
    StringColumn,
    strings_from_padded,
)
from spark_rapids_jni_tpu.columnar.dtypes import Kind
from spark_rapids_jni_tpu.ops.float_to_string import (
    _I32,
    _M32,
    _POW10_U64,
    _U64,
    _d2d,
    _decimal_length_u64,
    _f2d,
    digit_from_table,
    digit_table_u64,
)
from spark_rapids_jni_tpu.utils.floatbits import f32_to_bits


def _round_half_even(value, olength, digits):
    """round_half_even (ftos_converter.cuh:1247): keep ``digits`` leading
    decimal digits of ``value`` (which has ``olength`` digits)."""
    k = jnp.clip(olength - digits, 0, 19)
    div = _POW10_U64[k]
    mod = value % div
    num = value // div
    half = div // _U64(2)
    inc = (mod > half) | ((mod == half) & (num % _U64(2) == 1) & (mod != 0))
    return jnp.where(digits >= olength, value, num + inc.astype(jnp.uint64))


# (Per-row digit tables + gathers — digit_table_u64/digit_from_table in the
# import block above — replace the per-grid-cell u64 division with a variable
# power-of-10 divisor that dominated the axon compile-time pathology.)


def format_float(col: Column, digits: int, width_hint: int = 0) -> StringColumn:
    """Format FLOAT32/FLOAT64 like Spark's ``format_number(col, digits)``.

    ``width_hint`` (optional) caps the integer-part digit count used to size
    the render grid — callers under ``jit`` (where the host peek below cannot
    run) can pass the largest expected decimal exponent + 2 to keep the
    compiled grid small.
    """
    if digits < 0:
        raise ValueError("digits must be >= 0")
    if col.dtype.kind == Kind.FLOAT64:
        bits = col.data.astype(jnp.int64).astype(jnp.uint64)
        negative = col.data.astype(jnp.int64) < 0
        mant_f = bits & _U64((1 << 52) - 1)
        expo_f = (bits >> _U64(52)) & _U64(0x7FF)
        is_nan = (expo_f == 0x7FF) & (mant_f != 0)
        is_inf = (expo_f == 0x7FF) & (mant_f == 0)
        is_zero = (expo_f == 0) & (mant_f == 0)
        output, e10 = _d2d(bits)
        max_exp = 309
    elif col.dtype.kind == Kind.FLOAT32:
        bits32 = f32_to_bits(col.data)
        bits = bits32.astype(jnp.uint64) & _M32
        negative = bits32 < 0
        mant_f = bits & _U64((1 << 23) - 1)
        expo_f = (bits >> _U64(23)) & _U64(0xFF)
        is_nan = (expo_f == 0xFF) & (mant_f != 0)
        is_inf = (expo_f == 0xFF) & (mant_f == 0)
        is_zero = (expo_f == 0) & (mant_f == 0)
        output, e10 = _f2d(bits)
        max_exp = 39
    else:
        raise TypeError("Values for format_float function must be a float type.")

    n = output.shape[0]
    # Bound the render width by the column's actual largest magnitude when the
    # data is concrete (host peek at the IEEE exponent field); under jit fall
    # back to the type's maximum.  decimal_digits <= floor(e2 * log10(2)) + 2.
    import jax.core as _core

    if width_hint > 0:
        max_exp = min(max_exp, width_hint)
    elif n > 0 and not isinstance(col.data, _core.Tracer):
        e2_max = int(np.max(np.asarray(expo_f).astype(np.int64)))
        bias = 1023 if col.dtype.kind == Kind.FLOAT64 else 127
        max_exp = max(2, min(max_exp, int((max(e2_max - bias, 1)) * 0.30103) + 3))
    width = 1 + max_exp + (max_exp - 1) // 3 + 1 + digits + 1
    olength = _decimal_length_u64(output, 17)
    exp = e10 + olength - 1
    s = negative.astype(_I32)
    D = _I32(digits)

    normal = ~(is_nan | is_inf | is_zero)
    b1 = normal & (exp < 0)
    b23 = normal & (exp >= 0)
    b2 = b23 & (exp + 1 >= olength)
    b3 = b23 & (exp + 1 < olength)

    # ---- branch 1: 0.xxx (ftos_converter.cuh:1280-1314) ----
    nz_full = -exp - 1  # zeros between '.' and the first value digit
    early = b1 & (D < nz_full)  # rounding window ends inside the zeros
    nz = jnp.minimum(nz_full, D)
    actual_round = jnp.maximum(D - nz, 0)
    aol1 = jnp.minimum(olength, actual_round)
    r1 = _round_half_even(output, olength, actual_round)
    # digits == 0 returns the bare '0' before any rounding (cuh:1284)
    carry1 = b1 & ~early & (D > 0) & (r1 >= _POW10_U64[jnp.clip(aol1, 0, 19)])
    r1 = jnp.where(carry1, r1 - _POW10_U64[jnp.clip(aol1, 0, 19)], r1)
    carrier_pos = jnp.where(nz > 0, s + 2 + nz - 1, s)

    # ---- branch 3 rounding (ftos_converter.cuh:1343-1357); the trailing
    # zeros after the temp_d fraction digits fall out of the in_frac grid ----
    temp_d = jnp.minimum(D, olength - exp - 1)
    r3 = _round_half_even(output, olength, exp + temp_d + 1)
    p10_td = _POW10_U64[jnp.clip(temp_d, 0, 19)]
    int3 = r3 // p10_td
    dec3 = r3 % p10_td
    il3 = _decimal_length_u64(int3, 19)

    # integer-section lengths (with commas)
    il2 = exp + 1  # digits in branch 2's integer (before commas)
    fl2 = il2 + exp // 3
    fl3 = il3 + (il3 - 1) // 3
    z2 = exp + 1 - olength  # trailing zeros appended to output in branch 2

    int_fl = jnp.where(b2, fl2, fl3)  # formatted integer length
    # total length per row (format_size :1386-1410 + specials)
    len_norm = jnp.where(
        b1,
        s + 2 + D,
        s + int_fl + 1 + D,
    )
    if digits == 0:
        len_norm = len_norm - 1
    lens = jnp.where(
        is_nan,
        _I32(3),
        jnp.where(
            is_inf,
            s + 3,
            jnp.where(is_zero, jnp.where(D > 0, s + 2 + D, s + 1), len_norm),
        ),
    )

    # ---- render the [n, width] grid ----
    p = jnp.arange(width, dtype=_I32)[None, :]
    sC = s[:, None]
    ZERO, ONE, DOT, COMMA, MINUS = (
        jnp.uint8(ord("0")),
        jnp.uint8(ord("1")),
        jnp.uint8(ord(".")),
        jnp.uint8(ord(",")),
        jnp.uint8(ord("-")),
    )
    # analyze: ignore[governed-allocation] - format_float is not yet
    # wired into a governed pipeline (oracle/test callers); debt tracked
    # at the site (round 16 baseline burn-down)
    out = jnp.zeros((n, width), jnp.uint8)
    tab_r1 = digit_table_u64(r1)
    tab_dec3 = digit_table_u64(dec3)

    # branch 1 grid
    in_zeros = (p >= sC + 2) & (p < sC + 2 + nz[:, None])
    j1 = p - (sC + 2 + nz[:, None])  # index into value digits (from left)
    in_val1 = (j1 >= 0) & (j1 < aol1[:, None])
    ch1 = jnp.where(
        p == sC,
        jnp.where(carry1[:, None] & (nz[:, None] == 0), ONE, ZERO),
        jnp.where(
            p == sC + 1,
            DOT,
            jnp.where(
                in_zeros,
                jnp.where(carry1[:, None] & (p == carrier_pos[:, None]), ONE, ZERO),
                jnp.where(
                    in_val1,
                    digit_from_table(tab_r1, aol1[:, None] - 1 - j1),
                    ZERO,  # trailing zeros
                ),
            ),
        ),
    )

    # branches 2/3 grid: integer section with commas, then '.', fraction
    tab_int = digit_table_u64(jnp.where(b2, output, int3))
    z = jnp.where(b2, z2, 0)[:, None]
    fl = int_fl[:, None]
    q = fl - 1 - (p - sC)  # distance from right within the integer section
    in_int = (p >= sC) & (q >= 0)
    is_comma = in_int & (q % 4 == 3)
    dr = q - q // 4  # digit index from the right
    int_digit = jnp.where(
        dr < z, ZERO, digit_from_table(tab_int, jnp.maximum(dr - z, 0))
    )
    frac_t = p - (sC + fl + 1)  # fraction digit index (0-based)
    in_frac = (frac_t >= 0) & (frac_t < D)
    # branch 2 fraction is all zeros; branch 3: temp_d digits then zeros
    frac_digit = jnp.where(
        b3[:, None] & (frac_t < temp_d[:, None]),
        digit_from_table(tab_dec3, temp_d[:, None] - 1 - frac_t),
        ZERO,
    )
    ch23 = jnp.where(
        is_comma,
        COMMA,
        jnp.where(
            in_int,
            int_digit,
            jnp.where(p == sC + fl, DOT, jnp.where(in_frac, frac_digit, ZERO)),
        ),
    )

    grid = jnp.where(b1[:, None], ch1, ch23)
    # sign for normal/inf/zero rows
    grid = jnp.where((p == 0) & (sC == 1), MINUS, grid)
    # zero rows: "0." + zeros (grid already ZERO beyond; set the dot)
    zero_m = is_zero[:, None]
    grid = jnp.where(zero_m & (p == sC), ZERO, grid)
    grid = jnp.where(zero_m & (p == sC + 1), DOT, grid)
    grid = jnp.where(zero_m & (p > sC + 1), ZERO, grid)
    # specials
    nan_bytes = jnp.asarray(np.frombuffer("�".encode(), np.uint8))
    inf_bytes = jnp.asarray(np.frombuffer("∞".encode(), np.uint8))
    for k in range(3):
        grid = jnp.where(is_nan[:, None] & (p == k), nan_bytes[k], grid)
        grid = jnp.where(is_inf[:, None] & (p == sC + k), inf_bytes[k], grid)

    return strings_from_padded(grid, lens, col.validity)
