"""Device-resident rendering for ``get_json_object``.

jnp re-expression of the host render pipeline in ops/get_json_object.py —
per-byte escape tables (`_byte_info`), per-token emission tables, path-name
matching, float re-rendering, and the segment->bytes expansion (`_render`)
— so a bucket's bytes never leave the device: the only host interaction is
three scalar shape syncs (float count, float source width, output width),
each padded to a power of two to bound the compile-variant set.  The host
numpy pipeline remains the debug oracle (config ``json_device_render``).

Reference parity target is unchanged: get_json_object.cu:891 runs the whole
evaluation + output write in one kernel; this module restores that residency
on the TPU shape (rectangles + gathers instead of per-thread byte loops).

Float re-rendering uses the Spark-exact parse (cast_string_to_float's
device scan + softfloat assembly) followed by the Ryu digit core
(float_to_string._d2d/_emit).  For numbers with <= 15 significant digits and
|exp10| <= 22 this equals the host oracle's correctly-rounded strtod; beyond
that the two-step rounding may differ by 1 ulp from python/Java parsing —
the same territory where the CUDA reference's own stod diverges.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from spark_rapids_jni_tpu.ops import json_tokenizer as jt
from spark_rapids_jni_tpu.ops.get_json_object import (
    _CONST_LEN,
    _CONST_MAXLEN,
    _CONST_TAB,
    _CONSTS,
    _CTRL_SHORT,
    _HEX_UP,
    _SEG_COND_CLOSE,
    _SEG_COND_OPEN,
    _SEG_CONST,
    _SEG_ESC_TOK,
    _SEG_RAW_TOK,
    _UNESC,
)

_I32 = jnp.int32
_I64 = jnp.int64
_U8 = jnp.uint8

_UNESC_J = jnp.asarray(_UNESC)
_CTRL_SHORT_J = jnp.asarray(_CTRL_SHORT)
_HEX_UP_J = jnp.asarray(_HEX_UP)
_CONST_TAB_J = jnp.asarray(_CONST_TAB)
_CONST_LEN_J = jnp.asarray(_CONST_LEN)


class DByteInfo(NamedTuple):
    """Device twin of get_json_object._ByteInfo (all jnp, [n, L]-shaped)."""

    b: jnp.ndarray
    cls_esc: jnp.ndarray
    cls_u: jnp.ndarray
    cp: jnp.ndarray
    ulen: jnp.ndarray
    len_e: jnp.ndarray
    cum_u: jnp.ndarray
    cum_e: jnp.ndarray
    cum_uni: jnp.ndarray


# one-hot row read (rationale + measurement in its docstring)
_take_rows = jt._take_rows


def _searchsorted_rows(a, v):
    """Per-row searchsorted-right: a [n, L] row-sorted, v [n, W] -> [n, W].

    Implemented as a count of ``a[i, :] <= v[i, w]`` rather than binary
    search: per-row dynamic gathers scalarize on TPU (round-5 device
    profile), while the O(L*W) compare-and-sum is pure vector work that
    XLA fuses without materializing the [n, L, W] intermediate.
    """
    return (a[:, :, None] <= v[:, None, :]).sum(axis=1, dtype=_I32)


@jax.jit
def byte_info_device(b, lens, st_before):
    """Port of _byte_info's numpy passes (the automaton result is shared)."""
    n, L = b.shape

    in_dq = st_before == jt._S_DQ
    in_sq = st_before == jt._S_SQ
    cls_esc_all = (st_before == jt._S_DQE) | (st_before == jt._S_SQE)
    cls_u = cls_esc_all & (b == ord("u"))
    cls_esc = cls_esc_all & ~cls_u
    cls_hex = jnp.zeros_like(cls_u)
    for k in range(1, 5):
        cls_hex = cls_hex.at[:, k:].set(cls_hex[:, k:] | cls_u[:, :-k])
    close_q = (in_dq & (b == ord('"'))) | (in_sq & (b == ord("'")))

    d = b.astype(_I32)
    hexval = jnp.zeros(b.shape, _I32)
    hexval = jnp.where((b >= ord("0")) & (b <= ord("9")), d - ord("0"), hexval)
    hexval = jnp.where((b >= ord("a")) & (b <= ord("f")), d - ord("a") + 10,
                       hexval)
    hexval = jnp.where((b >= ord("A")) & (b <= ord("F")), d - ord("A") + 10,
                       hexval)
    cp = jnp.zeros(b.shape, _I32)
    for k in range(1, 5):
        sh = jnp.zeros(b.shape, _I32)
        sh = sh.at[:, :-k].set(hexval[:, k:])
        cp = cp | (sh << (4 * (4 - k)))
    ulen = jnp.where(cp < 0x80, 1, jnp.where(cp < 0x800, 2, 3)).astype(_I32)

    normal = (in_dq | in_sq) & ~((in_dq | in_sq) & (b == ord("\\"))) \
        & ~close_q & ~cls_hex
    is_ctrl = normal & (b < 32)
    short_ctrl = is_ctrl & (_CTRL_SHORT_J[jnp.minimum(b, _U8(31))] != 0)

    len_u = jnp.zeros(b.shape, _I32)
    len_u = jnp.where(normal, 1, len_u)
    len_u = jnp.where(cls_esc, 1, len_u)
    len_u = jnp.where(cls_u, ulen, len_u)

    len_e = jnp.zeros(b.shape, _I32)
    len_e = jnp.where(normal, 1, len_e)
    len_e = jnp.where(normal & (b == ord('"')), 2, len_e)
    len_e = jnp.where(short_ctrl, 2, len_e)
    len_e = jnp.where(is_ctrl & ~short_ctrl, 6, len_e)
    two_byte = (b == ord('"')) | (b == ord("\\"))
    for ch in b"bfnrt":
        two_byte = two_byte | (b == ch)
    len_e = jnp.where(cls_esc, jnp.where(two_byte, 2, 1), len_e)
    len_e = jnp.where(cls_u, ulen, len_e)

    def excl_cum(x):
        return jnp.pad(jnp.cumsum(x.astype(_I64), axis=1), ((0, 0), (1, 0)))

    return DByteInfo(
        b=b, cls_esc=cls_esc, cls_u=cls_u, cp=cp, ulen=ulen, len_e=len_e,
        cum_u=excl_cum(len_u), cum_e=excl_cum(len_e),
        cum_uni=excl_cum(cls_u.astype(_I64)),
    )


def _utf8_byte(cp, ulen, k):
    b1 = jnp.where(ulen == 1, cp,
                   jnp.where(ulen == 2, 0xC0 | (cp >> 6), 0xE0 | (cp >> 12)))
    b2 = jnp.where(ulen == 2, 0x80 | (cp & 0x3F), 0x80 | ((cp >> 6) & 0x3F))
    b3 = 0x80 | (cp & 0x3F)
    return jnp.where(k == 0, b1, jnp.where(k == 1, b2, b3)).astype(_U8)


def _emission_byte(bi: DByteInfo, ri, si, k, escaped: bool):
    """Device port of get_json_object._emission_byte (same case logic).

    ``ri`` is retained for signature stability but unused: all source
    reads go through the one-hot ``_take_rows`` (si is row-aligned).
    """
    del ri
    c = _take_rows(bi.b, si)
    u = _take_rows(bi.cls_u, si)
    esc = _take_rows(bi.cls_esc, si)
    if not escaped:
        out = jnp.where(esc, _UNESC_J[c], c)
        out = jnp.where(u, _utf8_byte(_take_rows(bi.cp, si),
                                      _take_rows(bi.ulen, si), k), out)
        return out.astype(_U8)
    is_ctrl = c < 32
    short = jnp.where(is_ctrl, _CTRL_SHORT_J[jnp.minimum(c, _U8(31))], _U8(0))
    long_bytes = jnp.select(
        [k == 0, k == 1, k == 2, k == 3, k == 4],
        [jnp.full(c.shape, ord("\\"), _U8), jnp.full(c.shape, ord("u"), _U8),
         jnp.full(c.shape, ord("0"), _U8), jnp.full(c.shape, ord("0"), _U8),
         jnp.where(c >= 16, _U8(ord("1")), _U8(ord("0")))],
        default=_HEX_UP_J[c % 16],
    )
    ctrl_out = jnp.where(short != 0,
                         jnp.where(k == 0, _U8(ord("\\")), short), long_bytes)
    norm_out = jnp.where(
        c == ord('"'),
        jnp.where(k == 0, _U8(ord("\\")), _U8(ord('"'))), c)
    out = jnp.where(is_ctrl, ctrl_out, norm_out)
    two = (c == ord('"')) | (c == ord("\\"))
    for ch in b"bfnrt":
        two = two | (c == ch)
    esc_out = jnp.where(two, jnp.where(k == 0, _U8(ord("\\")), c), _UNESC_J[c])
    esc_out = jnp.where((c == ord('"')) & (k == 1), _U8(ord('"')), esc_out)
    out = jnp.where(esc, esc_out, out)
    out = jnp.where(u, _utf8_byte(_take_rows(bi.cp, si),
                                  _take_rows(bi.ulen, si), k), out)
    return out.astype(_U8)


@jax.jit
def token_tables_device(bi: DByteInfo, kind, start, end):
    """Device port of _token_tables."""
    n, T = kind.shape
    L = bi.b.shape[1]
    s64 = start.astype(_I64)
    e64 = end.astype(_I64)

    is_str = (kind == jt.VALUE_STRING) | (kind == jt.FIELD_NAME)
    ps = jnp.minimum(s64 + 1, L)
    pe = jnp.clip(e64 - 1, 0, L)
    pay_u = _take_rows(bi.cum_u, pe) - _take_rows(bi.cum_u, ps)
    pay_e = _take_rows(bi.cum_e, pe) - _take_rows(bi.cum_e, ps)
    has_uni = (_take_rows(bi.cum_uni, pe) - _take_rows(bi.cum_uni, ps)) > 0

    span = e64 - s64
    is_int = kind == jt.VALUE_NUMBER_INT
    neg0 = is_int & (span == 2) \
        & (_take_rows(bi.b, jnp.minimum(s64, L - 1)) == ord("-")) \
        & (_take_rows(bi.b, jnp.minimum(s64 + 1, L - 1)) == ord("0"))

    one = (kind == jt.START_OBJECT) | (kind == jt.END_OBJECT) | \
        (kind == jt.START_ARRAY) | (kind == jt.END_ARRAY)
    len_raw = jnp.zeros((n, T), _I64)
    len_esc = jnp.zeros((n, T), _I64)
    len_raw = jnp.where(one, 1, len_raw)
    len_raw = jnp.where(kind == jt.VALUE_TRUE, 4, len_raw)
    len_raw = jnp.where(kind == jt.VALUE_FALSE, 5, len_raw)
    len_raw = jnp.where(kind == jt.VALUE_NULL, 4, len_raw)
    len_raw = jnp.where(is_int, jnp.where(neg0, 1, span), len_raw)
    len_esc = jnp.where(one | (kind == jt.VALUE_TRUE) | (kind == jt.VALUE_FALSE)
                        | (kind == jt.VALUE_NULL) | is_int, len_raw, len_esc)
    len_raw = jnp.where(is_str, pay_u, len_raw)
    len_esc = jnp.where(is_str, pay_e + 2, len_esc)
    return len_raw, len_esc, has_uni, neg0


@functools.partial(jax.jit, static_argnums=(6,))
def _name_match_one(bi: DByteInfo, kind, start, len_raw, has_uni, end,
                    name: bytes):
    """[n, T] bool: token payload unescapes to exactly ``name``.

    Two paths, selected PER ROW:

    - **fast** (no escape in the row's candidate payloads, the
      overwhelmingly common case): a [n, L] match table built from
      ``len(name)`` static byte-shift compares (pure vector ops), then a
      single gather per token.  A payload with no escapes and no unicode
      emits its raw bytes verbatim, so raw-width == m plus byte equality
      is exact.
    - **slow** (the row has a candidate with a 2-byte escape): the
      original per-character searchsorted walk through the cum_u
      emission mapping.

    The round-5 device profile showed the searchsorted walk was 64% of a
    warm get_json_object call on the v5e (134 s of 208 s at 2^18 rows) —
    per-(token, char) gathers scalarize on TPU.  The fast path replaces
    ~8 gather rounds per character with one gather per name.  Selection
    is per-row (an outer ``lax.cond`` still skips the slow walk entirely
    when NO row needs it): one escaped field name routes only ITS row
    through the escape-aware walk — every clean row keeps the fast
    table result, instead of the whole batch changing path.
    """
    n, T = kind.shape
    L = bi.b.shape[1]
    # FIELD_NAME only: name matches are consumed solely at field-name
    # tokens (the object-field step), and gating on string VALUES too
    # would let a common escaped value route rows down the slow path;
    # the host matcher (get_json_object.py _name_matches) is narrowed
    # identically — the fuzz tier asserts parity on these tables.
    is_str = kind == jt.FIELD_NAME
    m = len(name)
    ok = is_str & ~has_uni & (len_raw == m)
    if m == 0:
        return ok
    ps = jnp.minimum(start.astype(_I64) + 1, L)
    raw_w = end.astype(_I64) - start.astype(_I64) - 2  # quoted payload width
    no_esc = raw_w == m  # every non-unicode escape shrinks 2 raw -> 1 emitted
    # rows with an escaped same-emitted-width candidate (the only tokens
    # where fast and slow can disagree)
    need_slow = jnp.any(ok & ~no_esc, axis=1)

    def fast(_):
        bpad = jnp.pad(bi.b, ((0, 0), (0, m)))
        table = jnp.ones((n, L), bool)
        for q, ch in enumerate(name):
            table = table & (bpad[:, q:q + L] == ch)
        hit = _take_rows(table, jnp.minimum(ps, L - 1))
        return ok & no_esc & hit

    def mixed(_):
        base = _take_rows(bi.cum_u, ps)
        acc = ok
        for q, ch in enumerate(name):
            tgt = base + q
            si = jnp.minimum(_searchsorted_rows(bi.cum_u[:, 1:], tgt), L - 1)
            k = (tgt - _take_rows(bi.cum_u, si)).astype(_I32)
            got = _emission_byte(bi, None, si, k, escaped=False)
            acc = acc & (got == ch)
        return jnp.where(need_slow[:, None], acc, fast(0))

    return jax.lax.cond(jnp.any(need_slow), mixed, fast, 0)


def name_matches_device(bi, kind, start, len_raw, has_uni, end, names):
    return [
        jnp.zeros(kind.shape, bool) if nm is None
        else _name_match_one(bi, kind, start, len_raw, has_uni, end, nm)
        for nm in names
    ]


# ---------------------------------------------------------------- floats ---

_FLOAT_W = 32  # Double.toString max ~24 chars + quoted-Infinity room


@functools.partial(jax.jit, static_argnums=(4, 5))
def _float_gather(b, kind, start, end, NF: int, WS: int):
    """Compact float-token source texts into [NF, WS] byte slots."""
    n, T = kind.shape
    L = b.shape[1]
    fmask = kind == jt.VALUE_NUMBER_FLOAT
    rank = (jnp.cumsum(fmask.reshape(-1).astype(_I64)) - 1).reshape(n, T)
    fidx = jnp.where(fmask, rank, -1)

    slot = jnp.where(fmask, rank, NF).reshape(-1)
    rows2d = jnp.broadcast_to(jnp.arange(n, dtype=_I64)[:, None], (n, T))
    frow = jnp.zeros((NF,), _I64).at[slot].set(rows2d.reshape(-1), mode="drop")
    fs = jnp.zeros((NF,), _I64).at[slot].set(
        start.astype(_I64).reshape(-1), mode="drop")
    fe = jnp.zeros((NF,), _I64).at[slot].set(
        end.astype(_I64).reshape(-1), mode="drop")

    lane = jnp.arange(WS, dtype=_I64)[None, :]
    src = jnp.clip(fs[:, None] + lane, 0, L - 1)
    # whole-row gather (contiguous, embedding-shaped — TPU-friendly),
    # then the one-hot in-row read; the fused 2-D b[frow, src] gather
    # scalarized (round-5 profile: 4.4 s of the warm call)
    raw = _take_rows(b[frow], src)
    flen_src = (fe - fs).astype(_I32)
    raw = jnp.where(lane < flen_src[:, None], raw, _U8(0))
    return raw, flen_src, fidx


@jax.jit
def _float_render(bits):
    """Ryu digits + Java formatting of parsed float bits, with the
    quoted-Infinity quirk (ftos_converter.cuh:1154)."""
    from spark_rapids_jni_tpu.ops.float_to_string import _d2d, _emit

    u = bits.astype(jnp.uint64)
    mant = u & jnp.uint64((1 << 52) - 1)
    expo = (u >> jnp.uint64(52)) & jnp.uint64(0x7FF)
    is_nan = (expo == 0x7FF) & (mant != 0)
    is_inf = (expo == 0x7FF) & (mant == 0)
    is_zero = (expo == 0) & (mant == 0)
    negative = bits < 0
    output, e10 = _d2d(u)
    special_id = jnp.where(
        is_nan, _I32(4),
        jnp.where(is_inf, jnp.where(negative, _I32(3), _I32(2)),
                  jnp.where(is_zero,
                            jnp.where(negative, _I32(1), _I32(0)), _I32(-1))))
    padded, lens = _emit(output, e10, negative, special_id, is_float=False)
    lens = lens.astype(_I64)

    # quoted-Infinity: shift right by one and wrap in quotes
    out_len = jnp.where(is_inf, lens + 2, lens)
    lane_w = jnp.arange(_FLOAT_W, dtype=_I64)[None, :]
    srcpos = jnp.clip(lane_w - is_inf[:, None], 0, padded.shape[1] - 1)
    gathered = _take_rows(
        jnp.pad(padded, ((0, 0), (0, max(_FLOAT_W - padded.shape[1], 0)))),
        srcpos)
    in_text = (lane_w >= is_inf[:, None]) & \
        (lane_w < (lens + is_inf)[:, None])
    ftext = jnp.where(in_text, gathered, _U8(0))
    quote_pos = is_inf[:, None] & ((lane_w == 0) |
                                   (lane_w == out_len[:, None] - 1))
    ftext = jnp.where(quote_pos, _U8(ord('"')), ftext)
    return ftext, out_len


def float_texts_device(b, kind, start, end, NF: int, WS: int):
    """Device float re-rendering with a static float-slot count.

    Returns (ftext [NF, _FLOAT_W] uint8, flen [NF] int64, fidx [n, T] int64).
    Slots beyond the real float count are zero.  Parsing is the Spark-exact
    device parse; rendering is the Ryu digit core.

    Composed of three separately-jitted stages (gather -> parse -> render)
    so each compiles once per NF geometry and the parse/render modules are
    shared across buckets — one fused module was a pathological XLA compile.
    """
    from spark_rapids_jni_tpu.ops.cast_string_to_float import (
        _assemble_device,
        _scan_padded_jit,
        _SCAN_FIELDS,
    )

    raw, flen_src, fidx = _float_gather(b, kind, start, end, NF, WS)
    # full-width exponent reading (the 4-digit cap is a cast quirk)
    fields = _scan_padded_jit(raw, flen_src, WS)
    fdict = {k: v for (k, _), v in zip(_SCAN_FIELDS, fields)}
    bits, _valid, _exc = _assemble_device(fdict)
    ftext, out_len = _float_render(bits)
    return ftext, out_len, fidx


# ---------------------------------------------------------------- render ---


@jax.jit
def resolve_and_measure(segs, close_grp, close_dirty, close_nc, err,
                        kind, len_raw, len_esc, fidx, flen):
    """Resolve case-6 conditionals + per-segment lengths + output lengths.

    ``segs``: [S, n, 2, 2] scan outputs.  Returns (stype, sarg, slen [n, 2S],
    out_len [n]).
    """
    S, n = segs.shape[0], segs.shape[1]
    allseg = jnp.transpose(segs, (1, 0, 2, 3)).reshape(n, S * 2, 2)
    stype = allseg[:, :, 0]
    sarg = allseg[:, :, 1]

    # close events -> per-(row, open-step) dirty/nc tables (device scatter)
    rowsSn = jnp.broadcast_to(jnp.arange(n, dtype=_I32)[None, :], (S, n))
    g = jnp.where(close_grp >= 0, close_grp, S)
    res_dirty = jnp.zeros((n, S + 1), _I32).at[
        rowsSn.reshape(-1), g.reshape(-1)].set(
        close_dirty.reshape(-1), mode="drop")
    res_nc = jnp.zeros((n, S + 1), bool).at[
        rowsSn.reshape(-1), g.reshape(-1)].set(
        close_nc.reshape(-1), mode="drop")
    res_seen = jnp.zeros((n, S + 1), bool).at[
        rowsSn.reshape(-1), g.reshape(-1)].set(True, mode="drop")

    is_open = stype == _SEG_COND_OPEN
    is_close = stype == _SEG_COND_CLOSE
    gi = jnp.clip(sarg, 0, S)
    seen = _take_rows(res_seen, gi)
    d = _take_rows(res_dirty, gi)
    nc = _take_rows(res_nc, gi)
    open_id = jnp.where(
        d > 1, jnp.where(nc, _CONSTS.index(b",["), _CONSTS.index(b"[")),
        jnp.where((d == 1) & nc, _CONSTS.index(b","), _CONSTS.index(b"")))
    close_id = jnp.where(d > 1, _CONSTS.index(b"]"), _CONSTS.index(b""))
    sarg = jnp.where(is_open & seen, open_id, sarg)
    stype = jnp.where(is_open & seen, _SEG_CONST, stype)
    sarg = jnp.where(is_close & seen, close_id, sarg)
    stype = jnp.where(is_close & seen, _SEG_CONST, stype)
    unres = (stype == _SEG_COND_OPEN) | (stype == _SEG_COND_CLOSE)
    stype = jnp.where(unres, 0, stype)

    T = kind.shape[1]
    targ = jnp.clip(sarg, 0, T - 1)
    slen = jnp.zeros((n, S * 2), _I64)
    slen = jnp.where(stype == _SEG_CONST,
                     _CONST_LEN_J[jnp.clip(sarg, 0, len(_CONSTS) - 1)], slen)
    slen = jnp.where(stype == _SEG_RAW_TOK, _take_rows(len_raw, targ), slen)
    slen = jnp.where(stype == _SEG_ESC_TOK, _take_rows(len_esc, targ), slen)
    is_float_tok = _take_rows(kind, targ) == jt.VALUE_NUMBER_FLOAT
    tok_ref = (stype == _SEG_RAW_TOK) | (stype == _SEG_ESC_TOK)
    f_sel = tok_ref & is_float_tok
    NF = flen.shape[0]
    fi = jnp.clip(_take_rows(fidx, targ), 0, max(NF - 1, 0))
    if NF:
        slen = jnp.where(f_sel, flen[fi], slen)

    segcum = jnp.cumsum(slen, axis=1)
    out_len = jnp.where(err, 0, segcum[:, -1])
    return stype, sarg, segcum, out_len


@functools.partial(jax.jit, static_argnums=(11,))
def render_device(bi: DByteInfo, stype, sarg, segcum, out_len, err,
                  kind, start, end, tok_tabs, floats, W: int):
    """Materialize output bytes [n, W] from resolved segments (device port
    of _render's emission pass)."""
    len_raw, len_esc, neg0 = tok_tabs
    ftext, flen, fidx = floats
    n = stype.shape[0]
    T = kind.shape[1]
    L = bi.b.shape[1]
    S2 = stype.shape[1]

    j = jnp.broadcast_to(jnp.arange(W, dtype=_I64)[None, :], (n, W))
    si = jnp.minimum(_searchsorted_rows(segcum, j), S2 - 1)
    prev = jnp.where(si > 0, _take_rows(segcum, jnp.maximum(si - 1, 0)), 0)
    d = j - prev
    st = _take_rows(stype, si)
    sa = _take_rows(sarg, si)
    ta = jnp.clip(sa, 0, T - 1)
    tk = _take_rows(kind, ta)
    ts = _take_rows(start, ta).astype(_I64)

    out = jnp.zeros((n, W), _U8)
    cm = st == _SEG_CONST
    out = jnp.where(cm, _CONST_TAB_J[jnp.clip(sa, 0, len(_CONSTS) - 1),
                                     jnp.clip(d, 0, _CONST_MAXLEN - 1)], out)

    is_str = (tk == jt.VALUE_STRING) | (tk == jt.FIELD_NAME)
    is_int = tk == jt.VALUE_NUMBER_INT
    is_float = tk == jt.VALUE_NUMBER_FLOAT
    one_char = (tk == jt.START_OBJECT) | (tk == jt.END_OBJECT) | \
        (tk == jt.START_ARRAY) | (tk == jt.END_ARRAY)
    lit = (tk == jt.VALUE_TRUE) | (tk == jt.VALUE_FALSE) | \
        (tk == jt.VALUE_NULL)
    tokm = (st == _SEG_RAW_TOK) | (st == _SEG_ESC_TOK)
    escm = st == _SEG_ESC_TOK

    im = tokm & is_int
    n0 = _take_rows(neg0, ta)
    src_byte = _take_rows(bi.b, jnp.clip(ts + d, 0, L - 1))
    out = jnp.where(im, jnp.where(n0, _U8(ord("0")), src_byte), out)
    sm = tokm & (one_char | lit)
    out = jnp.where(sm, src_byte, out)

    NF = flen.shape[0]
    if NF:
        fm = tokm & is_float
        fi2 = jnp.clip(_take_rows(fidx, ta), 0, NF - 1)
        out = jnp.where(
            fm, ftext[fi2, jnp.clip(d, 0, ftext.shape[1] - 1)], out)

    strm = tokm & is_str
    ps = jnp.minimum(ts + 1, L)
    # raw (unescape) variant
    rm = strm & ~escm
    base_u = _take_rows(bi.cum_u, ps)
    tgt = base_u + d
    siU = jnp.minimum(_searchsorted_rows(bi.cum_u[:, 1:], tgt), L - 1)
    kU = (tgt - _take_rows(bi.cum_u, siU)).astype(_I32)
    rbyte = _emission_byte(bi, None, siU, kU, False)
    out = jnp.where(rm, rbyte, out)
    # escaped variant: quote + payload + quote
    em = strm & escm
    elen = _take_rows(len_esc, ta)
    quote = (d == 0) | (d == elen - 1)
    base_e = _take_rows(bi.cum_e, ps)
    tgt_e = jnp.maximum(base_e + (d - 1), 0)
    siE = jnp.minimum(_searchsorted_rows(bi.cum_e[:, 1:], tgt_e), L - 1)
    kE = (tgt_e - _take_rows(bi.cum_e, siE)).astype(_I32)
    ebyte = _emission_byte(bi, None, siE, kE, True)
    out = jnp.where(em, jnp.where(quote, _U8(ord('"')), ebyte), out)

    in_bounds = j < out_len[:, None]
    return jnp.where(in_bounds, out, _U8(0))
