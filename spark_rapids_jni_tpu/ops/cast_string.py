"""Spark-exact string -> integer / decimal casts, and `conv`-style base casts.

Reference behavior being reproduced (semantics only, TPU-first implementation):
- ``CastStrings.toInteger`` (reference ``CastStrings.java:36-68``,
  ``cast_string.cu:159`` ``string_to_integer_kernel``): per-row parser with
  optional whitespace strip, sign, digit accumulation with exact overflow
  detection, non-ANSI truncation at a decimal point, ANSI error row capture
  (``CastStringJni.cpp:37-57`` -> ``CastException``).
- ``CastStrings.toDecimal`` (``cast_string.cu:392`` ``string_to_decimal_kernel``
  with the two-pass validate/accumulate design of ``validate_and_exponent``,
  ``cast_string.cu:248-374``): scientific notation, half-up rounding at the
  scale boundary, precision overflow checks.
- ``CastStrings.toIntegersWithBase`` / ``fromIntegersWithBase``
  (``CastStringJni.cpp:159-257``): Spark ``conv()`` semantics — prefix match
  ``^\\s*(-?[0-9a-fA-F]+).*``, junk -> 0, empty/whitespace -> null, uint64
  wraparound for negatives, hex output without leading zeros.

Where the reference walks each row with one GPU thread (SIMT), here every
character position is a vectorized step over all rows (SIMD-over-lanes): the
parser state machine advances with `lax.scan` across the padded byte matrix,
keeping one small state vector per row.  This keeps the inner loop on the VPU
with static shapes, which is what XLA needs to pipeline it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from spark_rapids_jni_tpu.columnar import dtypes
from spark_rapids_jni_tpu.columnar.buckets import map_buckets
from spark_rapids_jni_tpu.columnar.column import (
    Column,
    Decimal128Column,
    StringColumn,
    strings_from_padded,
)
from spark_rapids_jni_tpu.columnar.dtypes import DType, Kind
from spark_rapids_jni_tpu.utils import int128

__all__ = [
    "CastException",
    "string_to_integer",
    "string_to_decimal",
    "to_integers_with_base",
    "from_integers_with_base",
]


class CastException(ValueError):
    """ANSI-mode cast failure; carries the first offending row, mirroring the
    reference's ``CastException`` (``CastException.java``, thrown from
    ``validate_ansi_column`` at ``cast_string.cu:602-635``)."""

    def __init__(self, string_with_error: str, row_with_error: int):
        super().__init__(
            f"Error casting data on row {row_with_error}: {string_with_error}"
        )
        self.string_with_error = string_with_error
        self.row_with_error = row_with_error


# Whitespace per the reference's is_whitespace (cast_string.cu:46-56):
# C0 control codes 0x00-0x1F plus ' ' — i.e. any byte <= 0x20.  Bytes >= 0x80
# are "negative chars" there and never whitespace; uint8 <= 0x20 matches that.
def _is_ws(c):
    return c <= jnp.uint8(0x20)


def _is_digit(c):
    return (c >= jnp.uint8(ord("0"))) & (c <= jnp.uint8(ord("9")))


_INT_BOUNDS = {
    Kind.INT8: (-(2**7), 2**7 - 1),
    Kind.INT16: (-(2**15), 2**15 - 1),
    Kind.INT32: (-(2**31), 2**31 - 1),
    Kind.INT64: (-(2**63), 2**63 - 1),
}


def _leading_ws_count(padded, lens):
    """Per-row count of leading whitespace bytes (within the row length)."""
    L = padded.shape[1]
    pos = jnp.arange(L, dtype=jnp.int32)[None, :]
    ws_run = _is_ws(padded) & (pos < lens[:, None])
    return jnp.sum(jnp.cumprod(ws_run.astype(jnp.int32), axis=1), axis=1).astype(
        jnp.int32
    )


def _sign_and_start(padded, lens, strip: bool, signed: bool):
    """Apply leading-whitespace skip + sign detection; returns (sign, i0).

    Mirrors cast_string.cu:184-201 (integer) / :325-341 (decimal): skip ws only
    when strip, then one optional +/- (signed types only).
    """
    n, L = padded.shape
    if strip:
        p = _leading_ws_count(padded, lens)
    else:
        p = jnp.zeros((n,), dtype=jnp.int32)
    c = jnp.take_along_axis(
        padded, jnp.clip(p, 0, max(L - 1, 0))[:, None], axis=1
    )[:, 0]
    in_range = p < lens
    if signed:
        is_minus = in_range & (c == jnp.uint8(ord("-")))
        is_plus = in_range & (c == jnp.uint8(ord("+")))
        sign = jnp.where(is_minus, jnp.int32(-1), jnp.int32(1))
        i0 = p + (is_minus | is_plus).astype(jnp.int32)
    else:
        sign = jnp.ones((n,), dtype=jnp.int32)
        i0 = p
    return sign, i0


@functools.partial(jax.jit, static_argnames=("ansi_mode", "strip", "min_v", "max_v"))
def _string_to_integer_kernel(
    padded, lens, valid_in, *, ansi_mode: bool, strip: bool, min_v: int, max_v: int
):
    """Vectorized port of string_to_integer_kernel (cast_string.cu:159-245)."""
    n, L = padded.shape
    signed = min_v < 0
    sign, i0 = _sign_and_start(padded, lens, strip, signed)
    positive = sign > 0

    valid0 = valid_in & (lens > 0) & (i0 < lens)

    # element-wise overflow guards in int64; bounds are the target type's
    max_div10 = jnp.int64(max_v // 10)
    # C++ truncates toward zero: INT_MIN/10
    min_div10 = jnp.int64(-((-min_v) // 10)) if signed else jnp.int64(0)

    def step(state, xs):
        val, valid, trunc, trailing, done = state
        chr_col, j = xs
        active = valid0 & (j >= i0) & (j < lens) & valid & ~done

        ws = _is_ws(chr_col)
        dig = _is_digit(chr_col)

        # decision chain, in reference order (cast_string.cu:205-236)
        inv_trailing = trailing & ~ws
        set_trunc = (
            ~inv_trailing & ~trunc & (chr_col == jnp.uint8(ord("."))) & (not ansi_mode)
        )
        other = ~inv_trailing & ~set_trunc & ~dig
        set_trailing = other & ws & (j != i0) & strip
        invalid_now = active & (inv_trailing | (other & ~set_trailing))

        trunc2 = trunc | (active & set_trunc)
        trailing2 = trailing | (active & set_trailing)

        acc = active & ~invalid_now & ~trunc2 & ~trailing2 & dig
        first = j == i0
        d = (chr_col - jnp.uint8(ord("0"))).astype(jnp.int64)

        ov1 = ~first & jnp.where(positive, val > max_div10, val < min_div10)
        val1 = jnp.where(first, val, val * 10)
        ov2 = jnp.where(
            positive, val1 > jnp.int64(max_v) - d, val1 < jnp.int64(min_v) + d
        )
        overflow = acc & (ov1 | ov2)
        val2 = jnp.where(
            acc & ~overflow, jnp.where(positive, val1 + d, val1 - d), val
        )

        invalid_now = invalid_now | overflow
        return (
            val2,
            valid & ~invalid_now,
            trunc2,
            trailing2,
            done | invalid_now,
        ), None

    init = (
        jnp.zeros((n,), dtype=jnp.int64),
        jnp.ones((n,), dtype=jnp.bool_),
        jnp.zeros((n,), dtype=jnp.bool_),
        jnp.zeros((n,), dtype=jnp.bool_),
        jnp.zeros((n,), dtype=jnp.bool_),
    )
    xs = (padded.T, jnp.arange(L, dtype=jnp.int32))
    (val, valid, _, _, _), _ = lax.scan(step, init, xs)
    valid = valid0 & valid
    return jnp.where(valid, val, jnp.int64(0)), valid


def _raise_if_ansi_error(col: StringColumn, valid_out):
    """Mirror validate_ansi_column (cast_string.cu:602-635): first row that was
    non-null on input but null on output raises CastException.

    The error decision is one scalar sync; row bytes are pulled only on the
    (exceptional) throw path."""
    errors = col.is_valid() & ~jnp.asarray(valid_out)
    if not bool(jnp.any(errors)):
        return
    row = int(jnp.argmax(errors))
    chars = np.asarray(col.chars)
    offs = np.asarray(col.offsets)
    s = bytes(chars[offs[row] : offs[row + 1]]).decode(
        "utf-8", errors="surrogatepass"
    )
    raise CastException(s, row)


def string_to_integer(
    col: StringColumn,
    dtype: DType,
    ansi_mode: bool = False,
    strip: bool = True,
) -> Column:
    """Cast a string column to an integral column with Spark semantics.

    Equivalent of ``CastStrings.toInteger`` (CastStrings.java:36-68).  Invalid
    rows become null (or raise :class:`CastException` in ANSI mode); values
    after a decimal point are truncated in non-ANSI mode; whitespace (bytes
    <= 0x20) is stripped when ``strip``.
    """
    if dtype.kind not in _INT_BOUNDS:
        raise ValueError(f"not an integral type: {dtype}")
    min_v, max_v = _INT_BOUNDS[dtype.kind]
    n = col.size
    if n == 0:
        return Column(jnp.zeros((0,), dtype=dtype.jnp_dtype), None, dtype)
    val, valid = map_buckets(
        col,
        lambda b, l, v: _string_to_integer_kernel(
            b, l, v, ansi_mode=ansi_mode, strip=strip, min_v=min_v, max_v=max_v
        ),
        [((), jnp.int64), ((), jnp.bool_)],
        row_args=[col.is_valid()],
    )
    if ansi_mode:
        # the only host sync on the cast path, and only in ANSI mode
        _raise_if_ansi_error(col, valid)
    return Column(val.astype(dtype.jnp_dtype), valid, dtype)


# ---------------------------------------------------------------------------
# string -> decimal
# ---------------------------------------------------------------------------

# validate_and_exponent states (cast_string.cu:261-270)
_ST_DIGITS = 0
_ST_EXPONENT = 1
_ST_DECIMAL_POINT = 2
_ST_EXPONENT_OR_SIGN = 3
_ST_EXPONENT_SIGN = 4
_ST_TRAILING_WS = 5
_ST_INVALID = 6


@functools.partial(jax.jit, static_argnames=("strip", "precision", "scale"))
def _string_to_decimal_kernel(
    padded, lens, valid_in, *, precision: int, scale: int, strip: bool
):
    """Vectorized port of string_to_decimal_kernel + validate_and_exponent
    (cast_string.cu:248-582).  ``scale`` is cudf-convention here (value =
    unscaled * 10**scale) to keep the formulas aligned with the reference.

    Accumulation runs in 128-bit limb math regardless of target width; the
    overflow guards compare against the target width's bounds, which makes the
    wider accumulator exactly equivalent to the reference's in-type arithmetic.
    """
    n, L = padded.shape
    sign, i0 = _sign_and_start(padded, lens, strip, signed=True)
    positive = sign > 0
    first_digit = i0

    valid0 = valid_in & (lens > 0) & (i0 < lens)

    B_DOT = jnp.uint8(ord("."))
    B_E1, B_E2 = jnp.uint8(ord("e")), jnp.uint8(ord("E"))
    B_PLUS, B_MINUS = jnp.uint8(ord("+")), jnp.uint8(ord("-"))

    # ---- pass 1: validate + find decimal location (validate_and_exponent) ----
    def v_step(state, xs):
        st, dl, expv, exp_pos, last_digit = state
        chr_col, j = xs
        active = valid0 & (j >= i0) & (j < lens) & (st != _ST_INVALID)
        char_num = (j - i0).astype(jnp.int32)

        ws = _is_ws(chr_col)
        dig = _is_digit(chr_col)
        allow_trailing = ws & (char_num != 0) & strip

        in_digits = (st == _ST_DIGITS) | (st == _ST_DECIMAL_POINT)
        # ST_DIGITS / ST_DECIMAL_POINT transitions (cast_string.cu:278-293)
        d_dot = in_digits & ~dig & (chr_col == B_DOT) & (dl == -1)
        d_exp = in_digits & ~dig & ~d_dot & ((chr_col == B_E1) | (chr_col == B_E2))
        d_tws = in_digits & ~dig & ~d_dot & ~d_exp & allow_trailing
        d_inv = in_digits & ~dig & ~d_dot & ~d_exp & ~d_tws
        st_digits_next = jnp.where(
            dig,
            _ST_DIGITS,
            jnp.where(
                d_dot,
                _ST_DECIMAL_POINT,
                jnp.where(
                    d_exp,
                    _ST_EXPONENT_OR_SIGN,
                    jnp.where(d_tws, _ST_TRAILING_WS, _ST_INVALID),
                ),
            ),
        )

        # ST_EXPONENT_OR_SIGN transitions (:294-308)
        eos = st == _ST_EXPONENT_OR_SIGN
        e_sign = (chr_col == B_PLUS) | (chr_col == B_MINUS)
        e_tws = ~e_sign & allow_trailing
        st_eos_next = jnp.where(
            e_sign,
            _ST_EXPONENT_SIGN,
            jnp.where(
                e_tws,
                _ST_TRAILING_WS,
                jnp.where(dig, _ST_EXPONENT, _ST_INVALID),
            ),
        )

        # ST_EXPONENT_SIGN / ST_EXPONENT (:309-316)
        in_exp = (st == _ST_EXPONENT) | (st == _ST_EXPONENT_SIGN)
        st_exp_next = jnp.where(dig, _ST_EXPONENT, _ST_INVALID)

        # ST_TRAILING_WHITESPACE (:275-277)
        in_tws = st == _ST_TRAILING_WS
        st_tws_next = jnp.where(ws, _ST_TRAILING_WS, _ST_INVALID)

        st_next = jnp.where(
            in_digits,
            st_digits_next,
            jnp.where(
                eos, st_eos_next, jnp.where(in_exp, st_exp_next, st_tws_next)
            ),
        ).astype(jnp.int32)
        st2 = jnp.where(active, st_next, st)

        dl2 = jnp.where(active & d_dot, char_num, dl)
        exp_pos2 = jnp.where(active & eos & (chr_col == B_MINUS), False, exp_pos)

        # record where digits ended (":353-356")
        left_digits = (
            active
            & (st == _ST_DIGITS)
            & (st2 != _ST_DIGITS)
            & (st2 != _ST_DECIMAL_POINT)
            & (last_digit == lens)
        )
        last_digit2 = jnp.where(left_digits, j, last_digit)

        # exponent accumulation (":358-364"), int64 guards
        acc = active & (st2 == _ST_EXPONENT) & dig
        d = (chr_col - jnp.uint8(ord("0"))).astype(jnp.int64)
        first = expv == 0
        maxd10 = jnp.int64((2**63 - 1) // 10)
        mind10 = jnp.int64(-((2**63) // 10))
        ov1 = ~first & jnp.where(exp_pos2, expv > maxd10, expv < mind10)
        ev1 = jnp.where(first, expv, expv * 10)
        ov2 = jnp.where(
            exp_pos2,
            ev1 > jnp.int64(2**63 - 1) - d,
            ev1 < jnp.int64(-(2**63)) + d,
        )
        exp_overflow = acc & (ov1 | ov2)
        ev2 = jnp.where(
            acc & ~exp_overflow, jnp.where(exp_pos2, ev1 + d, ev1 - d), expv
        )
        st2 = jnp.where(exp_overflow, _ST_INVALID, st2)

        return (st2, dl2, ev2, exp_pos2, last_digit2), None

    v_init = (
        jnp.full((n,), _ST_DIGITS, dtype=jnp.int32),
        jnp.full((n,), -1, dtype=jnp.int32),
        jnp.zeros((n,), dtype=jnp.int64),
        jnp.ones((n,), dtype=jnp.bool_),
        lens.astype(jnp.int32),
    )
    xs = (padded.T, jnp.arange(L, dtype=jnp.int32))
    (st, dl, expv, _, last_digit1), _ = lax.scan(v_step, v_init, xs)

    valid = valid0 & (st != _ST_INVALID)
    # decimal location defaults to the end of digits, then exponent shift (:367-371)
    dl = jnp.where(dl < 0, last_digit1 - first_digit, dl)
    # clamp into int32 range after exponent add (int64 exponents are absurd inputs
    # that the downstream significant-digit check rejects anyway)
    dl64 = dl.astype(jnp.int64) + expv
    dl = jnp.clip(dl64, -(2**31), 2**31 - 1).astype(jnp.int32)

    # ---- pass 2a: count significant digits before the decimal (":425-441") ----
    def s_step(state, xs):
        digits_found, count, done = state
        chr_col, j = xs
        active = (
            valid
            & (j >= first_digit)
            & (j < lens)
            & ~done
            & (digits_found < dl)
        )
        is_e = (chr_col == B_E1) | (chr_col == B_E2)
        done2 = done | (active & is_e)
        is_num = active & ~is_e & (chr_col != B_DOT)
        digits_found2 = digits_found + is_num.astype(jnp.int32)
        sig = is_num & ((count != 0) | (chr_col != jnp.uint8(ord("0"))))
        return (digits_found2, count + sig.astype(jnp.int32), done2), None

    s_init = (
        jnp.zeros((n,), dtype=jnp.int32),
        jnp.zeros((n,), dtype=jnp.int32),
        jnp.zeros((n,), dtype=jnp.bool_),
    )
    (_, sig_in_string, _), _ = lax.scan(s_step, s_init, xs)

    # target-width bounds for overflow guards
    if precision <= dtypes.MAX_DECIMAL32_PRECISION:
        tmin, tmax = -(2**31), 2**31 - 1
    elif precision <= dtypes.MAX_DECIMAL64_PRECISION:
        tmin, tmax = -(2**63), 2**63 - 1
    else:
        tmin, tmax = -(2**127), 2**127 - 1
    maxd10_h, maxd10_l = int128.const128(tmax // 10)
    mind10_h, mind10_l = int128.const128(-((-tmin) // 10))

    def will_ov_mul10(vh, vl, pos):
        over_pos = int128.gt(vh, vl, jnp.int64(maxd10_h), jnp.uint64(maxd10_l))
        over_neg = int128.lt(vh, vl, jnp.int64(mind10_h), jnp.uint64(mind10_l))
        return jnp.where(pos, over_pos, over_neg)

    def will_ov_add(vh, vl, d, pos):
        # pos: v > tmax - d ; neg: v < tmin + d  (d in [0,9])
        mh, ml = int128.const128(tmax)
        mh2, ml2 = int128.const128(tmin)
        bh, bl = int128.sub_small(jnp.int64(mh), jnp.uint64(ml), d)
        ch, cl = int128.add_small(jnp.int64(mh2), jnp.uint64(ml2), d)
        return jnp.where(
            pos, int128.gt(vh, vl, bh, bl), int128.lt(vh, vl, ch, cl)
        )

    # last processable digit count: scale units past the decimal (":450-452")
    last_digit = dl - jnp.int32(scale)

    # ---- pass 2b: march digits, accumulate with rounding (":462-529") ----
    def m_step(state, xs):
        vh, vl, total, precise, found_sig, rdigits, dloc, valid_m, done = state
        chr_col, j = xs
        active = (
            valid_m & (j >= first_digit) & (j < lens) & ~done & (last_digit >= 0)
        )
        dig = _is_digit(chr_col)
        is_dot = chr_col == B_DOT
        # '.' -> continue; other non-digit -> break (stop processing)
        stop = active & ~dig & ~is_dot
        done2 = done | stop
        proc = active & dig

        d = (chr_col - jnp.uint8(ord("0"))).astype(jnp.int64)
        needs_round = proc & (
            (precise + 1 > precision) | (total + 1 > last_digit)
        )

        # rounding path (":474-512"): half-up toward the sign
        do_inc = needs_round & (d >= 5)
        inc_ov = do_inc & will_ov_add(vh, vl, jnp.int64(1), positive)
        rh, rl = int128.add_small(vh, vl, jnp.int64(1))
        rh2, rl2 = int128.sub_small(vh, vl, jnp.int64(1))
        nh = jnp.where(positive, rh, rh2)
        nl = jnp.where(positive, rl, rl2)
        apply_inc = do_inc & ~inc_ov
        before = int128.count_digits(vh, vl)
        after = int128.count_digits(nh, nl)
        orig_zero = (vh == 0) & (vl == jnp.uint64(0))
        grew = apply_inc & ~orig_zero & (after > before)
        vh2 = jnp.where(apply_inc, nh, vh)
        vl2 = jnp.where(apply_inc, nl, vl)
        total2 = total + grew.astype(jnp.int32)
        precise2 = precise + grew.astype(jnp.int32)
        dloc2 = dloc + grew.astype(jnp.int32)
        rdigits2 = rdigits + grew.astype(jnp.int32)
        done2 = done2 | needs_round
        valid2 = valid_m & ~inc_ov

        # normal accumulate path (":515-527")
        acc = proc & ~needs_round
        total3 = total2 + acc.astype(jnp.int32)
        sig_now = acc & (found_sig | (total3 > dloc2) | (d != 0))
        found_sig2 = found_sig | sig_now
        precise3 = precise2 + sig_now.astype(jnp.int32)

        first = j == first_digit
        ov1 = acc & ~first & will_ov_mul10(vh2, vl2, positive)
        th, tl = int128.mul_small(vh2, vl2, 10)
        vh3 = jnp.where(acc & ~first, th, vh2)
        vl3 = jnp.where(acc & ~first, tl, vl2)
        ov2 = acc & will_ov_add(vh3, vl3, d, positive)
        ah, al = int128.add_small(vh3, vl3, d)
        sh, sl = int128.sub_small(vh3, vl3, d)
        apply = acc & ~ov1 & ~ov2
        vh4 = jnp.where(apply, jnp.where(positive, ah, sh), jnp.where(acc, vh2, vh3))
        vl4 = jnp.where(apply, jnp.where(positive, al, sl), jnp.where(acc, vl2, vl3))
        # on overflow the reference breaks with valid=false
        acc_ov = acc & (ov1 | ov2)
        valid3 = valid2 & ~acc_ov
        done3 = done2 | acc_ov

        return (
            jnp.where(acc, vh4, vh2),
            jnp.where(acc, vl4, vl2),
            total3,
            precise3,
            found_sig2,
            rdigits2,
            dloc2,
            valid3,
            done3,
        ), None

    m_init = (
        jnp.zeros((n,), dtype=jnp.int64),
        jnp.zeros((n,), dtype=jnp.uint64),
        jnp.zeros((n,), dtype=jnp.int32),
        jnp.zeros((n,), dtype=jnp.int32),
        jnp.zeros((n,), dtype=jnp.bool_),
        jnp.zeros((n,), dtype=jnp.int32),
        dl,
        jnp.ones((n,), dtype=jnp.bool_),
        jnp.zeros((n,), dtype=jnp.bool_),
    )
    (vh, vl, total, precise, _, rdigits, dloc, valid_m, _), _ = lax.scan(
        m_step, m_init, xs
    )
    valid = valid & valid_m

    # ---- post-march scaling (":531-575") ----
    preceding_zeros = jnp.where(dloc < 0, -dloc, 0)
    if scale > 0:
        zeros_to_decimal = jnp.maximum(0, dloc - total - jnp.int32(scale))
    else:
        zeros_to_decimal = jnp.maximum(0, dloc - total)
    sig_before_decimal = sig_in_string + zeros_to_decimal + rdigits
    valid = valid & (jnp.int32(precision + scale) >= sig_before_decimal)

    # zero-pad loops (":548-555" and ":562-573"): 40 multiplies covers any
    # in-range value; a nonzero value needing more than 39 would overflow
    # anyway, which we detect directly.
    ZCAP = 40
    zero_val = (vh == 0) & (vl == jnp.uint64(0))

    def pad_zeros(count, vh, vl, valid):
        valid = valid & ~((count > ZCAP) & ~zero_val)

        def body(i, carry):
            vh, vl, valid_p = carry
            run = (i < count) & valid_p
            ov = run & will_ov_mul10(vh, vl, positive)
            th, tl = int128.mul_small(vh, vl, 10)
            apply = run & ~ov
            return (
                jnp.where(apply, th, vh),
                jnp.where(apply, tl, vl),
                valid_p & ~ov,
            )

        return lax.fori_loop(0, ZCAP, body, (vh, vl, valid))

    vh, vl, valid = pad_zeros(zeros_to_decimal, vh, vl, valid)
    precise = precise + zeros_to_decimal

    digits_after_decimal = precise - sig_before_decimal + preceding_zeros
    digits_needed = jnp.minimum(
        jnp.int32(precision) - sig_before_decimal, jnp.int32(-scale)
    )
    pad2_count = jnp.maximum(0, digits_needed - digits_after_decimal)
    vh, vl, valid = pad_zeros(pad2_count, vh, vl, valid)

    vh = jnp.where(valid, vh, jnp.int64(0))
    vl = jnp.where(valid, vl, jnp.uint64(0))
    return vh, vl, valid


def string_to_decimal(
    col: StringColumn,
    precision: int,
    scale: int,
    ansi_mode: bool = False,
    strip: bool = True,
):
    """Cast strings to a Spark decimal(precision, scale) column.

    Equivalent of ``CastStrings.toDecimal`` (CastStrings.java:70-100).  ``scale``
    is Spark-convention (digits after the decimal point); internally the cudf
    convention ``-scale`` keeps formulas aligned with the reference kernel.
    Storage follows precision like cudf: <=9 int32, <=18 int64, else 128-bit.
    """
    cudf_scale = -scale
    dtype = dtypes.decimal(precision, scale)
    n = col.size
    if n == 0:
        if dtype.kind == Kind.DECIMAL128:
            z = jnp.zeros((0,), dtype=jnp.int64)
            return Decimal128Column(z, z.astype(jnp.uint64), None, dtype)
        return Column(jnp.zeros((0,), dtype=dtype.jnp_dtype), None, dtype)
    vh, vl, valid = map_buckets(
        col,
        lambda b, l, v: _string_to_decimal_kernel(
            b, l, v, precision=precision, scale=cudf_scale, strip=strip
        ),
        [((), jnp.int64), ((), jnp.uint64), ((), jnp.bool_)],
        row_args=[col.is_valid()],
    )
    if ansi_mode:
        _raise_if_ansi_error(col, valid)
    if dtype.kind == Kind.DECIMAL128:
        return Decimal128Column(vh, vl, valid, dtype)
    return Column(vl.astype(jnp.int64).astype(dtype.jnp_dtype), valid, dtype)


# ---------------------------------------------------------------------------
# Spark conv(): to/from integers with base
# ---------------------------------------------------------------------------


def _hex_value(c):
    """Hex digit value or 255 for non-hex bytes."""
    dec = jnp.where(_is_digit(c), c - jnp.uint8(ord("0")), jnp.uint8(255))
    up = jnp.where(
        (c >= jnp.uint8(ord("A"))) & (c <= jnp.uint8(ord("F"))),
        c - jnp.uint8(ord("A") - 10),
        jnp.uint8(255),
    )
    lo = jnp.where(
        (c >= jnp.uint8(ord("a"))) & (c <= jnp.uint8(ord("f"))),
        c - jnp.uint8(ord("a") - 10),
        jnp.uint8(255),
    )
    return jnp.minimum(dec, jnp.minimum(up, lo))


# \s in cudf regex: space, \t, \n, \r, \f, \v
def _is_regex_ws(c):
    return (c == jnp.uint8(0x20)) | ((c >= jnp.uint8(0x09)) & (c <= jnp.uint8(0x0D)))


@functools.partial(jax.jit, static_argnames=("base",))
def _to_integers_with_base_kernel(padded, lens, valid_in, *, base: int):
    """Spark conv() parse: ``^\\s*(-?[digits]+).*`` -> uint64 with wraparound;
    junk -> 0; empty/whitespace-only -> null (CastStringJni.cpp:159-227)."""
    n, L = padded.shape
    pos = jnp.arange(L, dtype=jnp.int32)[None, :]
    inb = pos < lens[:, None]
    ws_run = _is_regex_ws(padded) & inb
    lead = jnp.sum(jnp.cumprod(ws_run.astype(jnp.int32), axis=1), axis=1).astype(
        jnp.int32
    )
    all_ws = lead >= lens  # matches ^\s*$ (also empty)

    c0 = jnp.take_along_axis(
        padded, jnp.clip(lead, 0, max(L - 1, 0))[:, None], axis=1
    )[:, 0]
    neg = (c0 == jnp.uint8(ord("-"))) & (lead < lens)
    start = lead + neg.astype(jnp.int32)

    if base == 16:
        dv = _hex_value(padded)
    else:
        dv = jnp.where(_is_digit(padded), padded - jnp.uint8(ord("0")), jnp.uint8(255))
    after_start = pos >= start[:, None]
    is_d = (dv != jnp.uint8(255)) & inb & after_start
    # digit run immediately at `start` (regex: digits must directly follow \s*-?)
    run = jnp.cumprod(
        jnp.where(after_start, is_d.astype(jnp.int32), 1), axis=1
    )
    take_mask = (run > 0) & after_start
    ndigits = jnp.sum(take_mask.astype(jnp.int32), axis=1)
    matched = ndigits > 0

    def step(val, xs):
        d_col, take = xs
        val2 = val * jnp.uint64(base) + d_col.astype(jnp.uint64)
        return jnp.where(take, val2, val), None

    val, _ = lax.scan(
        step,
        jnp.zeros((n,), dtype=jnp.uint64),
        (dv.T, take_mask.T),
    )
    val = jnp.where(neg, jnp.uint64(0) - val, val)
    val = jnp.where(matched, val, jnp.uint64(0))
    valid = valid_in & ~all_ws
    return val, valid


def to_integers_with_base(col: StringColumn, base: int = 10) -> Column:
    """Spark ``conv(str, base, 10)`` front half: parse string in ``base`` to
    UINT64 (stored as int64 bits) with wraparound for negatives.

    Mirrors ``CastStrings.toIntegersWithBase`` (CastStrings.java:116-130).
    """
    if base not in (10, 16):
        raise CastException(f"Bases supported 10, 16; Actual: {base}", 0)
    n = col.size
    if n == 0:
        return Column(jnp.zeros((0,), dtype=jnp.uint64), None, dtypes.UINT64)
    val, valid = map_buckets(
        col,
        lambda b, l, v: _to_integers_with_base_kernel(b, l, v, base=base),
        [((), jnp.uint64), ((), jnp.bool_)],
        row_args=[col.is_valid()],
    )
    return Column(val, valid, dtypes.UINT64)


@functools.partial(jax.jit, static_argnames=("base", "signed", "width"))
def _format_int_kernel(data, *, base: int, signed: bool, width: int):
    """integer -> digit bytes, no leading zeros (uppercase hex).

    Hex formats the two's-complement bits at the column's type width (cudf
    integers_to_hex behavior: int32 -5 -> "FFFFFFFB", not 16 F's).
    """
    if base == 10:
        max_digits = 20
    else:
        max_digits = 16
    if signed and base == 10:
        i = data.astype(jnp.int64)
        negative = i < 0
        u = i.astype(jnp.uint64)
        mag = jnp.where(negative, jnp.uint64(0) - u, u)
    else:
        negative = jnp.zeros(data.shape, dtype=jnp.bool_)
        # sign-extend then mask to the type width so hex shows type-width bits
        mask = jnp.uint64((1 << (8 * width)) - 1) if width < 8 else jnp.uint64(
            0xFFFFFFFFFFFFFFFF
        )
        mag = data.astype(jnp.int64).astype(jnp.uint64) & mask

    # digit j counted from the least-significant end
    if base == 16:
        shifts = jnp.arange(max_digits, dtype=jnp.uint64) * jnp.uint64(4)
        digs = ((mag[:, None] >> shifts[None, :]) & jnp.uint64(0xF)).astype(jnp.uint8)
        above = mag[:, None] >> shifts[None, :]
        has = above != jnp.uint64(0)
    else:
        divs = jnp.asarray([10**k for k in range(max_digits)], dtype=jnp.uint64)
        quot = mag[:, None] // divs[None, :]
        digs = (quot % jnp.uint64(10)).astype(jnp.uint8)
        has = quot != jnp.uint64(0)

    ndig = jnp.maximum(jnp.sum(has.astype(jnp.int32), axis=1), 1)
    lengths = ndig + negative.astype(jnp.int32)
    # byte at output position p: '-' if p==0 and negative, else digit
    # (length-1-p-ish reversed); gather from digs
    out_pos = jnp.arange(max_digits + 1, dtype=jnp.int32)[None, :]
    digit_pos = out_pos - negative.astype(jnp.int32)[:, None]
    src = ndig[:, None] - 1 - digit_pos
    src_c = jnp.clip(src, 0, max_digits - 1)
    dsel = jnp.take_along_axis(digs, src_c, axis=1)
    chars = jnp.where(
        dsel < 10, dsel + jnp.uint8(ord("0")), dsel - 10 + jnp.uint8(ord("A"))
    )
    bytes_out = jnp.where(
        (out_pos == 0) & negative[:, None], jnp.uint8(ord("-")), chars
    )
    in_len = out_pos < lengths[:, None]
    return jnp.where(in_len, bytes_out, jnp.uint8(0)), lengths


def from_integers_with_base(col: Column, base: int = 10) -> StringColumn:
    """Format integers as strings in ``base`` (CastStrings.java:133-152).

    base 10: signed columns print a leading '-', UINT64 columns (the Spark
    ``conv`` path) print unsigned.  base 16 is always unsigned uppercase over
    the two's-complement bits at the column's type width, with no leading
    zeros (zero -> "0").
    """
    if base not in (10, 16):
        raise CastException(f"Bases supported 10, 16; Actual: {base}", 0)
    n = col.size
    if n == 0:
        return StringColumn(
            jnp.zeros((0,), dtype=jnp.uint8),
            jnp.zeros((1,), dtype=jnp.int32),
            None,
        )
    signed = col.data.dtype.kind == "i"
    width = col.data.dtype.itemsize
    padded, lengths = _format_int_kernel(
        col.data, base=base, signed=signed, width=width
    )
    return strings_from_padded(padded, lengths, col.validity)
