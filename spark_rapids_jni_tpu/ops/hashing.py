"""Spark-exact row hashing: murmur3-32 and xxhash64.

Re-architecture of the reference's `murmur_hash.cu` + `xxhash64.cu` + `hash.cuh`
(spark-rapids-jni, src/main/cpp/src).  Spark's conventions, which both share
(murmur_hash.cu:36-57 documents them):

- the running hash is the *seed* for the next column (serial chaining);
- a null element contributes nothing: the seed passes through;
- floats/doubles normalize NaN -> canonical quiet NaN and -0.0 -> +0.0
  (hash.cuh:34-52 normalize_nans_and_zeros);
- DECIMAL32/64 hash their unscaled value as an 8-byte long; DECIMAL128 hashes the
  *minimal* big-endian two's-complement byte string of the unscaled value, exactly
  java.math.BigDecimal.unscaledValue().toByteArray() (hash.cuh:56-104);
- Spark murmur differs from canonical murmur3 in tail processing: each trailing
  byte (< 4) is sign-extended to int and run through the full mixK1/mixH1 round.

GPU reference parallelizes one thread per row; here each hash step is a dense
vector op over all rows (VPU lanes), and variable-length byte streams are walked
with a `lax.scan` over the padded byte matrix — rows advance in lockstep, masked
by their true lengths.
"""

from __future__ import annotations

from typing import Sequence, Union

import functools

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu.columnar import (
    Column,
    Decimal128Column,
    ListColumn,
    StringColumn,
    StructColumn,
)
from spark_rapids_jni_tpu.columnar.buckets import length_buckets, map_buckets
from spark_rapids_jni_tpu.columnar.dtypes import DType, Kind

DEFAULT_XXHASH64_SEED = 42  # hash.cuh:29

_U32 = jnp.uint32
_U64 = jnp.uint64

# murmur3 constants (Spark Murmur3_x86_32)
_MM_C1 = _U32(0xCC9E2D51)
_MM_C2 = _U32(0x1B873593)

# xxhash64 primes (xxhash64.cu:188-192)
_XX_P1 = _U64(0x9E3779B185EBCA87)
_XX_P2 = _U64(0xC2B2AE3D27D4EB4F)
_XX_P3 = _U64(0x165667B19E3779F9)
_XX_P4 = _U64(0x85EBCA77C2B2AE63)
_XX_P5 = _U64(0x27D4EB2F165667C5)


def _rotl32(x, r: int):
    return (x << _U32(r)) | (x >> _U32(32 - r))


def _rotl64(x, r: int):
    return (x << _U64(r)) | (x >> _U64(64 - r))


# ---------------------------------------------------------------------------
# murmur3-32 primitives (operating on uint32 vectors)
# ---------------------------------------------------------------------------


def _mm_mix_k1(k1):
    k1 = k1 * _MM_C1
    k1 = _rotl32(k1, 15)
    return k1 * _MM_C2


def _mm_mix_h1(h1, k1):
    h1 = h1 ^ k1
    h1 = _rotl32(h1, 13)
    return h1 * _U32(5) + _U32(0xE6546B64)


def _mm_fmix(h, length):
    h = h ^ length
    h = h ^ (h >> _U32(16))
    h = h * _U32(0x85EBCA6B)
    h = h ^ (h >> _U32(13))
    h = h * _U32(0xC2B2AE35)
    return h ^ (h >> _U32(16))


def _mm_hash_int(v_i32, h):
    """Spark Murmur3.hashInt: one mix round + fmix(4)."""
    if _pallas_backend(n=v_i32.size):
        from spark_rapids_jni_tpu.ops.hash_pallas import mm_hash_int_pallas

        return mm_hash_int_pallas(v_i32, h)
    return _mm_fmix(_mm_mix_h1(h, _mm_mix_k1(v_i32.astype(_U32))), _U32(4))


def _mm_hash_long(v_i64, h):
    if _pallas_backend(n=v_i64.size):
        from spark_rapids_jni_tpu.ops.hash_pallas import mm_hash_long_pallas

        return mm_hash_long_pallas(v_i64, h)
    v = v_i64.astype(_U64)
    low = (v & _U64(0xFFFFFFFF)).astype(_U32)
    high = (v >> _U64(32)).astype(_U32)
    h = _mm_mix_h1(h, _mm_mix_k1(low))
    h = _mm_mix_h1(h, _mm_mix_k1(high))
    return _mm_fmix(h, _U32(8))


#: the size window where the pallas fixed-width kernels measured ahead of
#: XLA on the v5e (pallas leads at 2^22; XLA wins at 2^24 — 78.2 vs 43.0
#: Grows/s — and small sizes are launch-overhead-bound)
_PALLAS_AUTO_MIN = 1 << 21
_PALLAS_AUTO_MAX = 1 << 23


def _pallas_backend(kind: str = "fixed", n: int | None = None) -> bool:
    """Backend choice for one hash input: ``kind`` ("fixed" or "bytes")
    and row count ``n`` (None = unknown, treated as in-window).

    Explicit ``hash_backend='xla'|'pallas'`` forces every kind (the A/B
    bench and the pallas parity tests depend on that).  ``'auto'`` is
    adaptive, the same shape as get_json_object's device-render auto:

    - byte/string inputs ALWAYS take the fused XLA scan — the pallas
      word kernel measured 0.37x on strings (BENCH_r07 murmur3_strings
      A/B), its VMEM win lost to the word-gather layout cost;
    - fixed-width inputs take pallas only on a real TPU backend
      (interpret mode off-TPU is pure overhead) and only in the
      measured mid-size window where it actually led.
    """
    from spark_rapids_jni_tpu import config

    v = config.get("hash_backend")
    if v == "auto":
        if kind != "fixed" or jax.default_backend() != "tpu":
            return False
        return n is None or _PALLAS_AUTO_MIN <= n <= _PALLAS_AUTO_MAX
    return v == "pallas"


def _mm_bytes_words(padded: jnp.ndarray):
    """[n, L] u8 -> ([n, Lw] u32 little-endian words, padded-to-x4 bytes)."""
    n, max_len = padded.shape
    pad = (-max_len) % 4
    if pad:
        padded = jnp.pad(padded, ((0, 0), (0, pad)))
    nwords_max = padded.shape[1] // 4
    b = padded.astype(_U32).reshape(n, nwords_max, 4)
    words = b[:, :, 0] | (b[:, :, 1] << _U32(8)) | (b[:, :, 2] << _U32(16)) | (
        b[:, :, 3] << _U32(24)
    )
    return words, padded


def _mm_bytes_tail(padded: jnp.ndarray, lens: jnp.ndarray, nwords, h):
    """The <=3 sign-extended tail-byte rounds + fmix (the Spark deviation);
    shared by the XLA scan and the Pallas word kernel."""
    tail_start = nwords * 4
    for j in range(3):
        idx = jnp.clip(tail_start + j, 0, padded.shape[1] - 1)
        byte = jnp.take_along_axis(padded, idx[:, None], axis=1)[:, 0]
        sbyte = byte.astype(jnp.int8).astype(jnp.int32).astype(_U32)
        upd = _mm_mix_h1(h, _mm_mix_k1(sbyte))
        h = jnp.where(tail_start + j < lens, upd, h)
    return _mm_fmix(h, lens.astype(_U32))


def _mm_hash_bytes(padded: jnp.ndarray, lens: jnp.ndarray, h):
    """Spark Murmur3.hashUnsafeBytes over a dense [n, L] byte matrix.

    Aligned 4-byte little-endian words get the standard round; the <=3 tail bytes
    are each sign-extended and given a full round (the Spark deviation).

    The XLA path runs under ONE module-level jit: the scan body closes
    over per-call arrays, and tracing it eagerly on every call leaks a
    fresh trace-cache entry each time (soak-tool finding — a long-lived
    executor grew without bound); under the cached jit it compiles once
    per byte-matrix geometry.
    """
    if _pallas_backend("bytes"):
        lens = lens.astype(jnp.int32)
        nwords = lens // 4
        words, padded = _mm_bytes_words(padded)

        from spark_rapids_jni_tpu.ops.hash_pallas import mm_bytes_words_pallas

        h = mm_bytes_words_pallas(words, nwords, h)
        return _mm_bytes_tail(padded, lens, nwords, h)
    return _mm_bytes_jit()(padded, lens, h)


@functools.lru_cache(maxsize=1)
def _mm_bytes_jit():
    return jax.jit(_mm_hash_bytes_xla)


def _mm_hash_bytes_xla(padded: jnp.ndarray, lens: jnp.ndarray, h):
    lens = lens.astype(jnp.int32)
    nwords = lens // 4
    words, padded = _mm_bytes_words(padded)
    nwords_max = words.shape[1]

    def word_step(hc, w_idx):
        w = words[:, w_idx]
        upd = _mm_mix_h1(hc, _mm_mix_k1(w))
        return jnp.where(w_idx < nwords, upd, hc), None

    if nwords_max:
        h, _ = jax.lax.scan(word_step, h, jnp.arange(nwords_max))

    return _mm_bytes_tail(padded, lens, nwords, h)


# ---------------------------------------------------------------------------
# xxhash64 primitives (operating on uint64 vectors)
# ---------------------------------------------------------------------------


def _xx_round4(h64, w32_u64):
    h64 = h64 ^ (w32_u64 * _XX_P1)
    return _rotl64(h64, 23) * _XX_P2 + _XX_P3


def _xx_round8(h64, w64):
    k1 = w64 * _XX_P2
    k1 = _rotl64(k1, 31) * _XX_P1
    h64 = h64 ^ k1
    return _rotl64(h64, 27) * _XX_P1 + _XX_P4


def _xx_finalize(h):
    h = h ^ (h >> _U64(33))
    h = h * _XX_P2
    h = h ^ (h >> _U64(29))
    h = h * _XX_P3
    h = h ^ (h >> _U64(32))
    return h


def _xx_hash_fixed4(v_u32, seed):
    if _pallas_backend(n=v_u32.size):
        from spark_rapids_jni_tpu.ops.hash_pallas import xx_hash_fixed4_pallas

        return xx_hash_fixed4_pallas(v_u32, seed)
    h64 = seed + _XX_P5 + _U64(4)
    return _xx_finalize(_xx_round4(h64, v_u32.astype(_U64) & _U64(0xFFFFFFFF)))


def _xx_hash_fixed8(v_u64, seed):
    if _pallas_backend(n=v_u64.size):
        from spark_rapids_jni_tpu.ops.hash_pallas import xx_hash_fixed8_pallas

        return xx_hash_fixed8_pallas(v_u64, seed)
    h64 = seed + _XX_P5 + _U64(8)
    return _xx_finalize(_xx_round8(h64, v_u64))


def _xx_hash_bytes(padded: jnp.ndarray, lens: jnp.ndarray, seed):
    """XXH64 over a dense [n, L] byte matrix (xxhash64.cu:110-177); cached
    module-level jit for the same trace-leak reason as _mm_hash_bytes."""
    return _xx_bytes_jit()(padded, lens, seed)


@functools.lru_cache(maxsize=1)
def _xx_bytes_jit():
    return jax.jit(_xx_hash_bytes_xla)


def _xx_hash_bytes_xla(padded: jnp.ndarray, lens: jnp.ndarray, seed):
    n, max_len = padded.shape
    pad = (-max_len) % 32
    if pad:
        padded = jnp.pad(padded, ((0, 0), (0, pad)))
    lens = lens.astype(jnp.int64)
    l_padded = padded.shape[1]

    b = padded.astype(_U64).reshape(n, l_padded // 8, 8)
    shifts = (_U64(8) * jnp.arange(8, dtype=_U64))[None, None, :]
    words64 = jnp.sum(b << shifts, axis=2, dtype=_U64)  # little-endian u64 lanes
    b32 = padded.astype(_U32).reshape(n, l_padded // 4, 4)
    shifts32 = (_U32(8) * jnp.arange(4, dtype=_U32))[None, None, :]
    words32 = jnp.sum(b32 << shifts32, axis=2, dtype=_U32)

    nstripes = (lens // 32).astype(jnp.int32)
    max_stripes = l_padded // 32

    def stripe_step(carry, s_idx):
        v1, v2, v3, v4 = carry
        active = s_idx < nstripes

        def lane(v, lane_idx):
            w = words64[:, s_idx * 4 + lane_idx]
            nv = _rotl64(v + w * _XX_P2, 31) * _XX_P1
            return jnp.where(active, nv, v)

        return (lane(v1, 0), lane(v2, 1), lane(v3, 2), lane(v4, 3)), None

    v1 = seed + _XX_P1 + _XX_P2
    v2 = seed + _XX_P2
    v3 = seed + _U64(0)
    v4 = seed - _XX_P1
    ones = jnp.ones((n,), _U64)
    carry = (v1 * ones, v2 * ones, v3 * ones, v4 * ones)
    if max_stripes:
        carry, _ = jax.lax.scan(stripe_step, carry, jnp.arange(max_stripes))
    v1, v2, v3, v4 = carry

    merged = _rotl64(v1, 1) + _rotl64(v2, 7) + _rotl64(v3, 12) + _rotl64(v4, 18)
    for v in (v1, v2, v3, v4):
        vk = _rotl64(v * _XX_P2, 31) * _XX_P1
        merged = (merged ^ vk) * _XX_P1 + _XX_P4

    h64 = jnp.where(lens >= 32, merged, (seed + _XX_P5) * ones)
    h64 = h64 + lens.astype(_U64)

    # Tail: up to three 8-byte chunks, one 4-byte chunk, three single bytes.
    offset_w64 = nstripes * 4  # 8-byte word index of first tail byte
    rem = (lens % 32).astype(jnp.int32)
    n8 = rem // 8
    for j in range(3):
        idx = jnp.clip(offset_w64 + j, 0, words64.shape[1] - 1)
        w = jnp.take_along_axis(words64, idx[:, None], axis=1)[:, 0]
        h64 = jnp.where(j < n8, _xx_round8(h64, w), h64)

    has4 = (rem % 8) >= 4
    idx32 = jnp.clip(nstripes * 8 + n8 * 2, 0, words32.shape[1] - 1)
    w32 = jnp.take_along_axis(words32, idx32[:, None], axis=1)[:, 0]
    h64 = jnp.where(has4, _xx_round4(h64, w32.astype(_U64)), h64)

    byte_start = nstripes * 32 + n8 * 8 + jnp.where(has4, 4, 0)
    for j in range(3):
        idx = jnp.clip(byte_start + j, 0, padded.shape[1] - 1)
        byte = jnp.take_along_axis(padded, idx[:, None], axis=1)[:, 0].astype(_U64)
        upd = _rotl64(h64 ^ (byte * _XX_P5), 11) * _XX_P1
        h64 = jnp.where(byte_start + j < lens, upd, h64)

    return _xx_finalize(h64)


# ---------------------------------------------------------------------------
# shared element handling
# ---------------------------------------------------------------------------


def _normalize_float_bits(col: Column):
    """NaN -> canonical quiet NaN, -0.0 -> +0.0, as integer bit patterns.

    FLOAT64 columns already store exact binary64 bits in int64 (TPUs have no
    bit-exact f64), so the double path is pure integer tests on the bits.
    """
    if col.dtype.kind == Kind.FLOAT32:
        bits = jax.lax.bitcast_convert_type(col.data, jnp.int32)
        qnan = jnp.int32(0x7FC00000)
        bits = jnp.where(jnp.isnan(col.data), qnan, bits)
        bits = jnp.where(col.data == 0.0, jnp.int32(0), bits)
        return bits
    bits = col.data.astype(jnp.uint64)
    mag = bits & _U64(0x7FFFFFFFFFFFFFFF)
    is_nan = mag > _U64(0x7FF0000000000000)
    is_zero = mag == _U64(0)
    bits = jnp.where(is_nan, _U64(0x7FF8000000000000), bits)
    bits = jnp.where(is_zero, _U64(0), bits)
    return bits.astype(jnp.int64)


def _decimal128_java_bytes(col: Decimal128Column):
    """Minimal big-endian two's-complement bytes of the unscaled value.

    Mirrors hash.cuh:56-104 (to_java_bigdecimal): drop leading sign bytes, keep at
    least one byte, re-add one byte if the sign bit of the top remaining byte
    disagrees with the value's sign.  Returns ([n,16] big-endian padded bytes, lens).
    """
    n = col.size
    hi_u = col.hi.astype(_U64)
    lo_u = col.lo.astype(_U64)
    # little-endian byte expansion: bytes 0..7 from lo, 8..15 from hi
    shifts = (_U64(8) * jnp.arange(8, dtype=_U64))[None, :]
    le_lo = ((lo_u[:, None] >> shifts) & _U64(0xFF)).astype(jnp.uint8)
    le_hi = ((hi_u[:, None] >> shifts) & _U64(0xFF)).astype(jnp.uint8)
    le = jnp.concatenate([le_lo, le_hi], axis=1)  # [n,16]

    is_neg = col.hi < 0
    zero_byte = jnp.where(is_neg, jnp.uint8(0xFF), jnp.uint8(0x00))
    # length = index of highest byte that differs from the sign filler, plus 1
    differs = le != zero_byte[:, None]  # [n,16]
    pos = jnp.arange(16, dtype=jnp.int32)[None, :]
    top = jnp.max(jnp.where(differs, pos, -1), axis=1)
    length = jnp.maximum(top + 1, 1)
    # sign-preservation: add a byte back if top byte's high bit mismatches the sign
    top_byte = jnp.take_along_axis(le, jnp.maximum(length - 1, 0)[:, None], axis=1)[:, 0]
    msb = (top_byte & jnp.uint8(0x80)) != 0
    length = jnp.where((length < 16) & (is_neg ^ msb), length + 1, length)

    # big-endian: be[p] = le[length-1-p] for p < length
    p = jnp.arange(16, dtype=jnp.int32)[None, :]
    src = jnp.clip(length[:, None] - 1 - p, 0, 15)
    be = jnp.take_along_axis(le, src, axis=1)
    be = jnp.where(p < length[:, None], be, jnp.uint8(0))
    return be, length


def _hash_element(col, h, *, mm: bool):
    """One column's contribution: h' per row, ignoring validity (caller masks)."""
    if isinstance(col, StringColumn):
        # Length-bucketed: each length class hashes over its own dense
        # rectangle, so one long outlier doesn't pad the whole column.
        (out,) = map_buckets(
            col,
            lambda b, l, hh: (
                _mm_hash_bytes(b, l, hh) if mm else _xx_hash_bytes(b, l, hh)
            ),
            [((), _U32 if mm else _U64)],
            row_args=[h],
        )
        return out
    if isinstance(col, Decimal128Column):
        be, lens = _decimal128_java_bytes(col)
        return _mm_hash_bytes(be, lens, h) if mm else _xx_hash_bytes(be, lens, h)

    kind = col.dtype.kind
    if kind in (Kind.FLOAT32, Kind.FLOAT64):
        bits = _normalize_float_bits(col)
        if kind == Kind.FLOAT32:
            return _mm_hash_int(bits, h) if mm else _xx_hash_fixed4(bits.astype(_U32), h)
        return _mm_hash_long(bits, h) if mm else _xx_hash_fixed8(bits.astype(_U64), h)
    if kind == Kind.BOOL:
        v = col.data.astype(jnp.int32)
        return _mm_hash_int(v, h) if mm else _xx_hash_fixed4(v.astype(_U32), h)
    if kind in (Kind.INT8, Kind.INT16, Kind.INT32, Kind.DATE32):
        v = col.data.astype(jnp.int32)  # sign-extend to 4 bytes
        return _mm_hash_int(v, h) if mm else _xx_hash_fixed4(v.astype(_U32), h)
    if kind in (Kind.INT64, Kind.TIMESTAMP_MICROS):
        v = col.data.astype(jnp.int64)
        return _mm_hash_long(v, h) if mm else _xx_hash_fixed8(v.astype(_U64), h)
    if kind in (Kind.DECIMAL32, Kind.DECIMAL64):
        # unscaled value hashed as an 8-byte long (both hashes; xxhash64.cu:248-260)
        v = col.data.astype(jnp.int64)
        return _mm_hash_long(v, h) if mm else _xx_hash_fixed8(v.astype(_U64), h)
    raise ValueError(f"unsupported column type for hashing: {col.dtype}")


def _hash_column(col, h, *, mm: bool):
    """Chain one column into the running hash, with Spark null/nesting rules."""
    if isinstance(col, StructColumn):
        # Structs decompose into their children in order (murmur_hash.cu:117-131);
        # a null struct row masks out all of its children's contributions.
        valid = col.is_valid()
        h_in = h
        for child in col.children:
            h = _hash_column(child, h, mm=mm)
        return jnp.where(valid, h, h_in)
    if isinstance(col, ListColumn):
        return _hash_list(col, h, mm=mm)
    upd = _hash_element(col, h, mm=mm)
    if col.validity is None:
        return upd
    return jnp.where(col.validity, upd, h)


def _hash_list(col: ListColumn, h, *, mm: bool):
    """Serial leaf-element hashing of (arbitrarily nested) LIST rows.

    Mirrors murmur_hash.cu:119-142: nested lists descend to the non-nested
    leaf child by composing offsets, so a row of ``[[1,2],[3]]`` hashes the
    flattened leaf span ``1,2,3`` serially — the hash of each element seeds
    the next.  Null leaf elements and null rows pass the seed through.
    LIST-of-STRUCT is rejected exactly like check_hash_compatibility
    (murmur_hash.cu:164-171).

    Rows are bucketed by leaf-span length (powers of two) so one long list
    doesn't pad the whole column's walk.
    """
    # descend nested lists: leaf span per row by offset composition
    starts = col.offsets[:-1]
    ends = col.offsets[1:]
    child = col.child
    while isinstance(child, ListColumn):
        starts = child.offsets[starts]
        ends = child.offsets[ends]
        child = child.child
    if isinstance(child, StructColumn):
        raise ValueError(
            "hashing a LIST of STRUCT column is not supported"
        )  # murmur_hash.cu:169

    n = col.size
    if n == 0:
        return h
    row_valid = col.is_valid()
    lens_np = np.asarray(ends - starts)
    if int(lens_np.max()) == 0:
        return h
    child_valid = child.is_valid()
    if isinstance(child, StringColumn):
        # Per-step transient gather widths instead of one resident
        # [child_n, global_max] pad: each list bucket pads leaf strings only
        # to the longest leaf *it references* (host metadata compute).
        coffs_np = np.asarray(child.offsets)
        clens_np = (coffs_np[1:] - coffs_np[:-1]).astype(np.int32)
        cstarts = child.offsets[:-1]
        clens = child.offsets[1:] - child.offsets[:-1]
        starts_np = np.asarray(starts)
        # per-list-row max leaf byte length (0 for empty spans)
        safe_starts = np.minimum(starts_np, max(len(clens_np) - 1, 0))
        row_max_leaf = (
            np.maximum.reduceat(clens_np, safe_starts)
            if len(clens_np)
            else np.zeros(n, np.int32)
        )
        row_max_leaf = np.where(lens_np > 0, row_max_leaf, 0)

    nonempty = lens_np > 0  # rows with no elements contribute nothing
    for w, rows_np, n_real in length_buckets(lens_np[nonempty]):
        rows_np = np.nonzero(nonempty)[0].astype(np.int32)[rows_np]
        nb = len(rows_np)
        rows = jnp.asarray(rows_np)
        real = jnp.arange(nb, dtype=jnp.int32) < n_real
        bstart = starts[rows]
        blen = jnp.where(real, (ends - starts)[rows], 0)
        bvalid = row_valid[rows] & real
        hb = h[rows]
        # bucket kernels are module-cached jits (keyed on child kind +
        # static widths): the scan body closes over bucket arrays, so an
        # eager per-call trace would leak a trace-cache entry per call
        # (same soak finding as _mm_hash_bytes)
        # part of each cache key: the traced program bakes the backend
        # choice, so a config.override must not silently reuse the other
        # backend's executable; kind follows the child being hashed
        backend = _pallas_backend(
            "bytes" if isinstance(child, StringColumn) else "fixed", n=nb)
        if isinstance(child, StringColumn):
            w_child = max(int(row_max_leaf[rows_np[:n_real]].max()), 1)
            hb = _list_scan_string_jit(mm, w, w_child, backend)(
                bstart, blen, bvalid, hb, cstarts, clens, child.chars,
                child_valid)
        elif isinstance(child, Decimal128Column):
            hb = _list_scan_dec128_jit(mm, w, child.dtype, backend)(
                bstart, blen, bvalid, hb, child.hi, child.lo, child_valid)
        else:
            hb = _list_scan_fixed_jit(mm, w, child.dtype, backend)(
                bstart, blen, bvalid, hb, child.data, child_valid)
        tgt = jnp.where(real, rows, jnp.int32(n))
        h = h.at[tgt].set(hb, mode="drop")
    return h


@functools.lru_cache(maxsize=256)
def _list_scan_string_jit(mm: bool, w: int, w_child: int,
                          backend: bool):
    @jax.jit
    def run(bstart, blen, bvalid, hb, cstarts, clens, chars, child_valid):
        csize = max(cstarts.shape[0], 1)
        nchars = max(chars.shape[0], 1)
        lane = jnp.arange(w_child, dtype=jnp.int32)[None, :]

        def elem_step(hc, j):
            idx = jnp.clip(bstart + j, 0, csize - 1)
            s0 = cstarts[idx]
            l0 = clens[idx]
            pos = jnp.clip(s0[:, None] + lane, 0, nchars - 1)
            eb = jnp.where(lane < l0[:, None], chars[pos], jnp.uint8(0))
            upd = _mm_hash_bytes(eb, l0, hc) if mm else _xx_hash_bytes(
                eb, l0, hc)
            ok = bvalid & (j < blen) & child_valid[idx]
            return jnp.where(ok, upd, hc), None

        hb2, _ = jax.lax.scan(elem_step, hb, jnp.arange(w))
        return hb2

    return run


@functools.lru_cache(maxsize=256)
def _list_scan_dec128_jit(mm: bool, w: int, dtype, backend: bool):
    @jax.jit
    def run(bstart, blen, bvalid, hb, hi, lo, child_valid):
        csize = max(hi.shape[0], 1)

        def elem_step(hc, j):
            idx = jnp.clip(bstart + j, 0, csize - 1)
            g = Decimal128Column(hi[idx], lo[idx], None, dtype)
            upd = _hash_element(g, hc, mm=mm)
            ok = bvalid & (j < blen) & child_valid[idx]
            return jnp.where(ok, upd, hc), None

        hb2, _ = jax.lax.scan(elem_step, hb, jnp.arange(w))
        return hb2

    return run


@functools.lru_cache(maxsize=256)
def _list_scan_fixed_jit(mm: bool, w: int, dtype, backend: bool):
    @jax.jit
    def run(bstart, blen, bvalid, hb, data, child_valid):
        csize = max(data.shape[0], 1)

        def elem_step(hc, j):
            idx = jnp.clip(bstart + j, 0, csize - 1)
            upd = _hash_element(Column(data[idx], None, dtype), hc, mm=mm)
            ok = bvalid & (j < blen) & child_valid[idx]
            return jnp.where(ok, upd, hc), None

        hb2, _ = jax.lax.scan(elem_step, hb, jnp.arange(w))
        return hb2

    return run


# ---------------------------------------------------------------------------
# raw-array entry points (for shuffle partitioning / shard_map pipelines)
# ---------------------------------------------------------------------------


def murmur3_raw_int64(data: jnp.ndarray, seed: int = 0) -> jnp.ndarray:
    """Spark murmur3-32 of an int64 vector, as uint32 (no Column wrapper)."""
    h = jnp.full(data.shape, jnp.uint32(seed & 0xFFFFFFFF), dtype=_U32)
    return _mm_hash_long(data.astype(jnp.int64), h)


def xxhash64_raw_int64(data: jnp.ndarray, seed: int = DEFAULT_XXHASH64_SEED) -> jnp.ndarray:
    """xxhash64 of an int64 vector, as uint64 (no Column wrapper)."""
    s = jnp.full(data.shape, jnp.uint64(seed & 0xFFFFFFFFFFFFFFFF), dtype=_U64)
    return _xx_hash_fixed8(data.astype(jnp.int64).astype(_U64), s)


def partition_mix32(data: jnp.ndarray) -> jnp.ndarray:
    """Cheap 32-bit mix of an int64 key vector for shuffle PARTITIONING.

    Pure uint32 lane arithmetic — one k1-mix of each half + fmix32, about
    a third of murmur3_raw_int64's multiply count and none of xxhash64's
    emulated u64 limb math (docs/PERF.md "structural facts").  NOT
    Spark-compatible and never user-visible: partition placement only
    needs every participant to agree, which internal exchanges get by
    construction.  The reference is likewise free on this seam — Spark
    compatibility binds murmur3/xxhash64 only where hashes reach users."""
    v = data.astype(jnp.int64).astype(_U64)
    low = (v & _U64(0xFFFFFFFF)).astype(_U32)
    high = (v >> _U64(32)).astype(_U32)
    h = _mm_mix_k1(low) ^ _rotl32(_mm_mix_k1(high), 13)
    return _mm_fmix(h, _U32(8))


# ---------------------------------------------------------------------------
# public API (mirrors Hash.java:40-91)
# ---------------------------------------------------------------------------

HashInput = Union[Column, StringColumn, Decimal128Column, StructColumn, ListColumn]


def murmur_hash32(columns: Sequence[HashInput], seed: int = 0) -> Column:
    """Spark-exact Murmur3-32 row hash of the given columns (Hash.java:40-56)."""
    if not columns:
        raise ValueError("murmur_hash32 requires at least one column")
    n = columns[0].size
    h = jnp.full((n,), jnp.uint32(seed & 0xFFFFFFFF), dtype=_U32)
    for col in columns:
        h = _hash_column(col, h, mm=True)
    return Column(h.astype(jnp.int32), None, DType(Kind.INT32))


def xxhash64(columns: Sequence[HashInput], seed: int = DEFAULT_XXHASH64_SEED) -> Column:
    """Spark-exact xxhash64 row hash of the given columns (Hash.java:58-91)."""
    if not columns:
        raise ValueError("xxhash64 requires at least one column")
    n = columns[0].size
    # analyze: ignore[governed-allocation] - the public column-op entry:
    # governed callers (nds entry, serve handlers) trace it inside their
    # own bracket; direct callers today are oracle/parity tests.  Debt
    # tracked at the site (round 16 baseline burn-down).
    h = jnp.full((n,), jnp.uint64(seed & 0xFFFFFFFFFFFFFFFF), dtype=_U64)
    for col in columns:
        h = _hash_column(col, h, mm=False)
    return Column(h.astype(jnp.int64), None, DType(Kind.INT64))
