"""Device-mesh construction for multi-chip scaling.

The reference has no distributed layer of its own (SURVEY.md §2.3) — Spark
partitions data and UCX moves shuffle blocks in the host plugin.  The TPU-native
equivalent is a `jax.sharding.Mesh` with named axes:

- ``data``: partition parallelism — each device owns a slice of the rows of a
  columnar batch (the analog of Spark partitions mapped onto executors).
- ``model``: sharded auxiliary structures — e.g. a bloom filter's bit array or a
  broadcast-side hash table sharded across chips (tensor-parallel analog).

Collectives ride ICI within a pod slice and DCN across slices; XLA inserts them
from sharding annotations (`pjit`) or explicit `shard_map` collectives.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def shard_map(body, *, mesh, in_specs, out_specs, check_vma=True):
    """``jax.shard_map`` compat wrapper.

    Newer jax exposes shard_map at the top level with a ``check_vma``
    kwarg; 0.4.x only has ``jax.experimental.shard_map.shard_map`` with
    the same semantics under ``check_rep``.  Every shard_map in this
    package goes through here so the framework runs on both.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(body, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(body, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)


def axis_size(name: str):
    """``jax.lax.axis_size`` compat (0.4.x spells it psum(1, name))."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(name)
    return jax.lax.psum(1, name)


def make_mesh(
    shape: Optional[Tuple[int, int]] = None,
    *,
    devices: Optional[Sequence] = None,
    axis_names: Tuple[str, str] = (DATA_AXIS, MODEL_AXIS),
) -> Mesh:
    """Build a 2D (data, model) mesh.

    With no ``shape``, uses all devices as (n, 1) — pure partition parallelism.
    """
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = (len(devices), 1)
    dp, mp = shape
    if dp * mp != len(devices):
        raise ValueError(f"mesh shape {shape} != device count {len(devices)}")
    arr = np.asarray(devices).reshape(dp, mp)
    return Mesh(arr, axis_names)


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Rows sharded over the data axis (leading dim), replicated over model."""
    return NamedSharding(mesh, P(DATA_AXIS))


def model_sharding(mesh: Mesh) -> NamedSharding:
    """A 1D structure (e.g. bloom bits) sharded over the model axis."""
    return NamedSharding(mesh, P(MODEL_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
