"""Multi-host runtime: process-group init + DCN/ICI-aware mesh construction.

The reference delegates cross-executor transport to the host plugin's UCX
shuffle and cross-process coordination to Spark itself (SURVEY.md §2.3); the
TPU-native equivalent is the JAX distributed runtime: every host process
calls :func:`initialize` once, after which ``jax.devices()`` spans the whole
pod/slice fleet and the SAME ``shard_map`` programs (shuffles, query steps)
scale across hosts — XLA routes collectives over ICI within a slice and DCN
between slices.

Mesh layout is what decides which links collectives ride: with
:func:`make_pod_mesh`, the ``data`` axis is laid out with slice-locality
outermost (``create_hybrid_device_mesh``), so the frequent exchanges
(all_to_all shuffle within a partition group) stay on ICI and only
psum-style reductions cross DCN.  This is the standing-in for "NCCL/MPI
backend that scales to multi-host": there is no transport code to write —
placement + sharding annotations are the backend.

On TPU pods the coordinator/process topology comes from the environment and
``initialize()`` needs no arguments; explicit arguments support CPU/GPU
multi-process clusters and tests.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import jax

from spark_rapids_jni_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

__all__ = ["initialize", "is_multihost", "make_pod_mesh", "process_summary"]

_initialized = False

# env markers that indicate a multi-process cluster runtime is present; used
# to decide whether an "initialize too late" condition is fatal or benign
_CLUSTER_ENV_MARKERS = (
    "MEGASCALE_COORDINATOR_ADDRESS",
    "JAX_COORDINATOR_ADDRESS",
    "COORDINATOR_ADDRESS",
    "SLURM_JOB_ID",
    "OMPI_MCA_orte_hnp_uri",
)


def _cluster_env_present() -> bool:
    """True when the environment names a genuinely multi-process cluster.

    TPU_WORKER_HOSTNAMES needs a value check, not a presence check: TPU
    runtimes (including single-chip tunnels) set it to the one local host,
    and a one-entry list is exactly the single-process case this module
    must treat as benign.
    """
    if any(m in os.environ for m in _CLUSTER_ENV_MARKERS):
        return True
    hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    return len([h for h in hosts.split(",") if h.strip()]) > 1


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join the JAX process group (idempotent).

    With no arguments, relies on JAX's cluster auto-detection (Cloud TPU
    pod runtime, GKE, Slurm, ...); when no cluster environment is detected
    — the plain single-process case — the auto-detect attempt fails and
    this degrades to a no-op, so single-host code paths need no changes.
    Must run before the backend is first touched.
    """
    global _initialized
    if _initialized:
        return
    explicit = any(a is not None for a in
                   (coordinator_address, num_processes, process_id))
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except ValueError:
        # jax raises ValueError specifically when no cluster environment
        # could be auto-detected; anything else (e.g. an unreachable
        # coordinator) must propagate rather than silently degrade.
        if explicit:
            raise
        return  # single-process; not latched, a later explicit call works
    except RuntimeError:
        # "must be called before any JAX calls": the backend is already up
        # (module-level device constants initialize it under `python -m`).
        # Benign for plain single-process use; FATAL when a cluster runtime
        # is present — degrading there would compute per-host partial
        # results silently.
        if explicit or _cluster_env_present():
            raise
        return
    _initialized = True


def is_multihost() -> bool:
    return jax.process_count() > 1


def make_pod_mesh(
    mp: int = 1,
    axis_names: Tuple[str, str] = (DATA_AXIS, MODEL_AXIS),
):
    """A (data, model) mesh over ALL processes' devices, DCN-aware.

    The data axis is ordered slice-outermost so a contiguous block of
    partition groups lives on one ICI domain: the shuffle's all_to_all
    between a slice's devices never crosses DCN, and only the final
    psum-style aggregations do.  Falls back to a flat mesh when the
    platform exposes no slice topology (CPU meshes, single slice); real
    layout errors (shape mismatches) propagate.
    """
    devices = jax.devices()
    n = len(devices)
    if mp < 1 or n % mp:
        raise ValueError(f"model parallelism {mp} does not divide {n} devices")
    multi_slice = (getattr(devices[0], "slice_index", None) is not None
                   and len({d.slice_index for d in devices}) > 1)
    if multi_slice:
        try:
            from jax.experimental import mesh_utils
        except ImportError:
            mesh_utils = None
        if mesh_utils is not None:
            arr = mesh_utils.create_hybrid_device_mesh(
                mesh_shape=(n // mp // _num_slices(), mp),
                dcn_mesh_shape=(_num_slices(), 1),
                devices=devices,
            )
            return jax.sharding.Mesh(arr, axis_names)
    from spark_rapids_jni_tpu.parallel.mesh import make_mesh

    return make_mesh((n // mp, mp), devices=devices, axis_names=axis_names)


def _num_slices() -> int:
    return len({d.slice_index for d in jax.devices()})


def process_summary() -> dict:
    """Small diagnostic dict (for logs / the bench header)."""
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }
