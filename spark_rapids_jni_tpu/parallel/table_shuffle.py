"""Columnar table shuffle: all_to_all of real batches over the device mesh.

The reference built its JCUDF row serialization precisely so rows could be
exchanged between executors (row_conversion.cu:574 + SURVEY.md §7.8); the
repo's base shuffle (parallel/shuffle.py) moves only bare fixed-width
arrays.  This module exchanges *tables*: fixed-width columns with validity,
DECIMAL128 limb pairs, and string columns.

TPU-idiomatic exchange form: each column rides the all_to_all as one dense
rectangle — strings as a padded ``bytes[n, width]`` view plus lengths, not
byte-packed variable-size rows.  XLA needs static shapes either way; the
padded form keeps every buffer a single contiguous collective payload and
lands on the receiver already in the framework's device string form (the
same padded view every string kernel consumes, columnar/buckets.py).  The
Arrow chars+offsets materialization (dynamic total length) happens at the
host boundary after the jitted step via ``strings_from_padded``.

Usage: inside ``shard_map`` over the data axis, like ``all_to_all_shuffle``;
string columns must be pre-converted to :class:`PaddedStrings` with a static
width (data-dependent ``max_len`` cannot be computed under jit — compute the
width on host or use a bucket bound).
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Optional

import jax.numpy as jnp

from spark_rapids_jni_tpu.columnar.column import (
    Column,
    Decimal128Column,
    StringColumn,
    strings_from_padded,
)
from spark_rapids_jni_tpu.parallel.mesh import DATA_AXIS
from spark_rapids_jni_tpu.parallel.shuffle import all_to_all_shuffle

__all__ = [
    "PaddedStrings",
    "ShuffledTable",
    "pad_strings",
    "shuffle_table",
    "materialize_strings",
]


class PaddedStrings(NamedTuple):
    """Device string form for exchange: dense padded bytes + lengths."""

    bytes: jnp.ndarray  # uint8[n, width]
    lengths: jnp.ndarray  # int32[n]
    validity: jnp.ndarray  # bool[n]


class ShuffledTable(NamedTuple):
    columns: Dict[str, object]  # Column / Decimal128Column / PaddedStrings
    valid: jnp.ndarray  # bool[ndev*capacity] slot occupancy
    dropped: jnp.ndarray  # int32: local rows lost to capacity overflow


def pad_strings(col: StringColumn, width: Optional[int] = None) -> PaddedStrings:
    """Padded exchange view of a string column.

    ``width`` must be static under jit; defaults to the host-computed max
    byte length (call outside jit, or pass a bucket bound).
    """
    b, lens = col.padded(width)
    return PaddedStrings(b, lens, col.is_valid())


def shuffle_table(
    columns: Dict[str, object],
    part: jnp.ndarray,
    capacity: int,
    axis: str = DATA_AXIS,
    row_valid: Optional[jnp.ndarray] = None,
) -> ShuffledTable:
    """Exchange a table of columns so each device receives the rows whose
    ``part`` equals its index along ``axis`` (inside shard_map).

    Per-column null validity survives the exchange; on the receiving side
    each column's validity is additionally masked with slot occupancy, so
    pad slots read as nulls rather than garbage.
    """
    flat: Dict[str, jnp.ndarray] = {}
    kinds: Dict[str, tuple] = {}
    for name, col in columns.items():
        if isinstance(col, Column):
            flat[name + ".data"] = col.data
            flat[name + ".v"] = col.is_valid()
            kinds[name] = ("fixed", col.dtype)
        elif isinstance(col, Decimal128Column):
            flat[name + ".hi"] = col.hi
            flat[name + ".lo"] = col.lo
            flat[name + ".v"] = col.is_valid()
            kinds[name] = ("dec128", col.dtype)
        elif isinstance(col, PaddedStrings):
            flat[name + ".bytes"] = col.bytes
            flat[name + ".len"] = col.lengths
            flat[name + ".v"] = col.validity
            kinds[name] = ("strings", None)
        elif isinstance(col, StringColumn):
            raise TypeError(
                f"column {name!r}: convert StringColumn to PaddedStrings "
                "(pad_strings) before shuffling — padded width must be "
                "static under jit"
            )
        else:
            raise TypeError(f"column {name!r}: unsupported type {type(col)}")

    res = all_to_all_shuffle(flat, part, capacity, axis, row_valid=row_valid)
    r = res.columns
    out: Dict[str, object] = {}
    for name, (kind, dtype) in kinds.items():
        v = r[name + ".v"] & res.valid
        if kind == "fixed":
            out[name] = Column(r[name + ".data"], v, dtype)
        elif kind == "dec128":
            out[name] = Decimal128Column(r[name + ".hi"], r[name + ".lo"], v, dtype)
        else:
            out[name] = PaddedStrings(r[name + ".bytes"], r[name + ".len"], v)
    return ShuffledTable(out, res.valid, res.dropped)


def materialize_strings(ps: PaddedStrings) -> StringColumn:
    """Arrow chars+offsets form of a received padded string column (host
    boundary: total char count is data-dependent, so call outside jit).
    Pad-slot rows are nulls with zero length."""
    lens = jnp.where(ps.validity, ps.lengths, 0)
    return strings_from_padded(ps.bytes, lens, ps.validity)
