from spark_rapids_jni_tpu.parallel.multihost import (
    initialize as initialize_multihost,
    is_multihost,
    make_pod_mesh,
)
from spark_rapids_jni_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    make_mesh,
    data_sharding,
    model_sharding,
    replicated,
    shard_map,
)
from spark_rapids_jni_tpu.parallel.shuffle import (
    ShuffleResult,
    all_to_all_shuffle,
    bucket_by_partition,
)
from spark_rapids_jni_tpu.parallel.table_shuffle import (
    PaddedStrings,
    ShuffledTable,
    materialize_strings,
    pad_strings,
    shuffle_table,
)

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "make_mesh",
    "data_sharding",
    "model_sharding",
    "replicated",
    "shard_map",
    "PaddedStrings",
    "ShuffleResult",
    "ShuffledTable",
    "all_to_all_shuffle",
    "bucket_by_partition",
    "initialize_multihost",
    "is_multihost",
    "make_pod_mesh",
    "materialize_strings",
    "pad_strings",
    "shuffle_table",
]
