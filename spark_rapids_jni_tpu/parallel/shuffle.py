"""Columnar hash-repartition (shuffle) over the device mesh.

The reference's shuffle transport is UCX in the host Spark plugin; this module is
its TPU-native replacement (SURVEY.md §2.3 planning note): rows move between
devices with a single dense `all_to_all` over ICI/DCN instead of point-to-point
RDMA.  XLA requires static shapes, so the exchange uses fixed-capacity buckets:

    local rows --bucket by hash % ndev--> [ndev, capacity] padded send buffer
              --all_to_all--> [ndev, capacity] receive buffer + slot-valid mask

Capacity defaults to the local row count (no row can ever be dropped); callers
with bounded skew can pass a smaller capacity and check `dropped` (a per-shard
count of rows that exceeded a destination bucket, analogous to a shuffle spill
that the caller must retry with a bigger capacity).

All functions here run *inside* `shard_map` (they use axis names), composing
with the query-step pipelines in models/.
"""

from __future__ import annotations

from typing import Dict, NamedTuple

import jax
import jax.numpy as jnp

from spark_rapids_jni_tpu.obs.seam import COLLECTIVE, instrument
from spark_rapids_jni_tpu.parallel.mesh import DATA_AXIS, axis_size


class ShuffleResult(NamedTuple):
    columns: Dict[str, jnp.ndarray]  # [ndev * capacity] received rows (padded)
    valid: jnp.ndarray  # bool[ndev * capacity] slot occupancy
    dropped: jnp.ndarray  # int32 scalar: rows lost to capacity overflow (local)


def partition_of(keys: jnp.ndarray, n_parts: int) -> jnp.ndarray:
    """Owning partition of each int64 key: the internal placement hash.

    Backend from the ``partition_hash`` config flag, read at TRACE time
    (a cached jitted step keeps the backend it was traced with):
    ``murmur3`` (default; Spark's placement hash) or ``mix32``
    (ops/hashing.partition_mix32 — pure u32 lane math, ~1/3 the multiply
    count; placement only needs every participant to agree, which one
    traced program guarantees).  The A/B lives in bench.py's
    partition-hash stage; flip the default to the measured winner."""
    from spark_rapids_jni_tpu import config
    from spark_rapids_jni_tpu.ops.hashing import (
        murmur3_raw_int64,
        partition_mix32,
    )

    if config.get("partition_hash") == "mix32":
        h = partition_mix32(keys)
    else:
        h = murmur3_raw_int64(keys, 42)
    return (h % jnp.uint32(n_parts)).astype(jnp.int32)


def quantized_rows(n: int, mult: int) -> int:
    """Batch length that is a ``mult`` multiple AND pow2-quantized:
    ``mult * next_pow2(ceil(n / mult))`` (min one block).

    Data-dependent exact batch lengths compile one executable per
    distinct value, which a long-lived executor accumulates until the
    compiler OOMs (the streamed-soak LLVM allocation failure after ~500
    out-of-core runs); quantizing bounds the variant set to
    O(log max_rows) per geometry.  Padding rows are validity-masked by
    the callers, so more padding never changes results."""
    from spark_rapids_jni_tpu.columnar.column import next_pow2

    return mult * next_pow2(max(1, -(-int(n) // mult)))


def bucket_by_partition(part: jnp.ndarray, n_parts: int, capacity: int):
    """Assign each local row a slot in a [n_parts, capacity] send layout.

    Returns (slot index [n], in_capacity mask [n], per-bucket counts [n_parts]).
    Rows overflowing a bucket get mask False.
    """
    n = part.shape[0]
    # rank of each row within its partition = number of earlier rows with same part
    # computed stably via sort: order rows by partition, rank = position - start.
    order = jnp.argsort(part, stable=True)
    sorted_part = part[order]
    counts = jnp.bincount(part, length=n_parts).astype(jnp.int32)
    starts = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts, dtype=jnp.int32)[:-1]]
    )
    rank_sorted = jnp.arange(n, dtype=jnp.int32) - starts[sorted_part]
    rank = jnp.zeros((n,), jnp.int32).at[order].set(rank_sorted)
    in_cap = rank < capacity
    slot = part.astype(jnp.int32) * capacity + jnp.minimum(rank, capacity - 1)
    return slot, in_cap, counts


@instrument(COLLECTIVE, "all_to_all_shuffle")
def all_to_all_shuffle(
    columns: Dict[str, jnp.ndarray],
    part: jnp.ndarray,
    capacity: int,
    axis: str = DATA_AXIS,
    row_valid: jnp.ndarray | None = None,
) -> ShuffleResult:
    """Exchange rows so each device receives the rows whose ``part`` equals its
    index along ``axis``.  Must be called inside shard_map over ``axis``.

    ``row_valid`` (bool[n], optional) marks padding/invalid local rows: they
    are never sent, never occupy a capacity slot, and don't count in
    ``dropped`` — static-shape callers (governed runners padding a batch to a
    shard multiple) rely on this so pads can't evict real rows or trigger
    spurious capacity retries.

    The seam range covers the dispatch (trace) boundary; on-chip timing comes
    from the profiler's optional XPlane capture.
    """
    ndev = axis_size(axis)
    if row_valid is not None:
        # invalid rows ride the out-of-range bucket: excluded from ranking,
        # capacity, sending, and the dropped count
        part = jnp.where(row_valid, part, ndev)
    slot, in_cap, _counts = bucket_by_partition(part, ndev, capacity)
    sendable = in_cap if row_valid is None else in_cap & row_valid
    if row_valid is None:
        dropped = jnp.sum(~in_cap).astype(jnp.int32)
    else:
        dropped = jnp.sum(row_valid & ~in_cap).astype(jnp.int32)

    send_valid = (
        jnp.zeros((ndev * capacity,), jnp.bool_)
        .at[jnp.where(sendable, slot, ndev * capacity)]
        .set(True, mode="drop")
        .reshape(ndev, capacity)
    )

    recv_cols = {}
    for name, data in columns.items():
        send = (
            jnp.zeros((ndev * capacity,) + data.shape[1:], data.dtype)
            .at[jnp.where(sendable, slot, ndev * capacity)]
            .set(data, mode="drop")
            .reshape((ndev, capacity) + data.shape[1:])
        )
        recv = jax.lax.all_to_all(send, axis, split_axis=0, concat_axis=0, tiled=False)
        recv_cols[name] = recv.reshape((ndev * capacity,) + data.shape[1:])

    recv_valid = jax.lax.all_to_all(
        send_valid, axis, split_axis=0, concat_axis=0, tiled=False
    ).reshape(ndev * capacity)
    return ShuffleResult(recv_cols, recv_valid, dropped)
