"""Version stamping (spark_rapids_jni_version.cpp.in analog).

The reference configures build info into a compiled translation unit at
cmake time; here the static version lives in code (kept in sync with
pyproject.toml) and volatile build info (git commit) is resolved lazily so
importing never shells out.
"""

from __future__ import annotations

import functools
import os
import subprocess

__all__ = ["VERSION", "__version__", "build_info"]

# kept in sync with pyproject.toml; the reference stamps 24.06.0-SNAPSHOT
# (pom.xml:24) the same way via spark_rapids_jni_version.cpp.in
VERSION = "26.08.0"
__version__ = VERSION


@functools.lru_cache(maxsize=1)
def build_info() -> dict:
    """Static version plus best-effort git commit of the source tree."""
    info = {"version": VERSION, "commit": "unknown"}
    try:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        out = subprocess.run(
            ["git", "-C", root, "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=5,
        )
        if out.returncode == 0:
            info["commit"] = out.stdout.strip()
    except (OSError, subprocess.SubprocessError):
        pass
    return info
