"""External (disk-backed) shuffle of FULL columnar tables in JCUDF rows.

The reference rides Spark's fully-general external shuffle for every
out-of-core exchange; its own contribution is the serialized row format
those shuffle files carry (row_conversion.cu:574 ``copy_to_rows`` — the
JCUDF row layout, RowConversion.java:44-117).  This module is the
TPU-framework analog: a grace-hash disk partitioner whose spill files hold
JCUDF row batches, so ANY table the columnar model can express — validity,
strings, decimal128 — spills and re-loads without a schema-specific format
(SURVEY §7.8 "all_to_all of serialized row batches").

Three pieces:

- a HOST JCUDF codec (:func:`encode_jcudf_rows` / :func:`decode_jcudf_rows`)
  byte-identical to the device path in ops/row_conversion.py, vectorized in
  numpy (spill routing runs host-side; the device conversion stays on the
  query hot path).  Byte-compat is pinned by tests against
  ``convert_to_rows``.
- key hashing (:func:`pair_mix64`, :func:`chained_key_hash`): a stable,
  well-mixed 64-bit hash of the key columns; bucket-space refinement relies
  only on ``hash % M == b  =>  hash % 2M in {b, b+M}``.
- :class:`ExternalTableShuffle`: append chunks, read buckets back as
  columns, and recursively split an over-budget bucket ON DISK by moving
  raw row bytes (rows are self-delimiting given their sizes; only the key
  columns are ever decoded during a split).

Byte accounting is from ACTUAL spill-file sizes (``bucket_nbytes``), not a
rows*width estimate — the number the host-memory governor reserves before a
bucket is materialized.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_jni_tpu.columnar.column import (
    Column,
    Decimal128Column,
    StringColumn,
)
from spark_rapids_jni_tpu.columnar.dtypes import DType, Kind
from spark_rapids_jni_tpu.ops.row_conversion import (
    JCUDF_ROW_ALIGNMENT,
    compute_layout,
)

__all__ = [
    "encode_jcudf_rows",
    "decode_jcudf_rows",
    "splitmix64",
    "pair_mix64",
    "chained_key_hash",
    "ExternalTableShuffle",
]


# ------------------------------------------------------------- host codec --


def _nrows(col) -> int:
    if isinstance(col, StringColumn):
        return int(np.asarray(col.offsets).shape[0] - 1)
    if isinstance(col, Decimal128Column):
        return int(np.asarray(col.hi).shape[0])
    return int(np.asarray(col.data).shape[0])


def _round_up(x, align: int):
    return (x + align - 1) // align * align


def _ragged_arange(lens: np.ndarray, total: Optional[int] = None) -> np.ndarray:
    """[0..lens[0]), [0..lens[1]), ... concatenated (int64)."""
    if total is None:
        total = int(lens.sum())
    ends = np.cumsum(lens)
    return np.arange(total, dtype=np.int64) - np.repeat(ends - lens, lens)


def _np_le(dt: DType) -> np.dtype:
    """Little-endian numpy dtype of a fixed-width column's DATA buffer.

    FLOAT64 data is the IEEE-754 bit pattern in int64 (columnar convention);
    BOOL is handled by the callers (stored as one 0/1 byte)."""
    if dt.kind == Kind.FLOAT64:
        return np.dtype("<i8")
    if dt.kind == Kind.FLOAT32:
        return np.dtype("<f4")
    if dt.kind == Kind.BOOL:
        return np.dtype(np.uint8)
    return np.dtype(dt.jnp_dtype).newbyteorder("<")


def _fixed_le_bytes(col) -> np.ndarray:
    """[n, w] little-endian value bytes of a fixed-width host column."""
    if col.dtype.kind == Kind.DECIMAL128:
        lo = np.asarray(col.lo).astype("<u8").view(np.uint8).reshape(-1, 8)
        hi = np.asarray(col.hi).astype("<i8").view(np.uint8).reshape(-1, 8)
        return np.concatenate([lo, hi], axis=1)
    data = np.asarray(col.data)
    if col.dtype.kind == Kind.BOOL:
        return data.astype(np.uint8).reshape(-1, 1)
    w = col.dtype.fixed_width
    return np.ascontiguousarray(data.astype(_np_le(col.dtype))) \
        .view(np.uint8).reshape(-1, w)


def _validity_bytes(columns, n: int) -> np.ndarray:
    """[n, ceil(ncols/8)] JCUDF validity bytes (bit c%8 of byte c//8)."""
    nbytes = (len(columns) + 7) // 8
    out = np.zeros((n, nbytes), np.uint8)
    for c, col in enumerate(columns):
        if col.validity is None:
            out[:, c // 8] |= np.uint8(1 << (c % 8))
        else:
            out[:, c // 8] |= (
                np.asarray(col.validity).astype(np.uint8) << (c % 8))
    return out


def encode_jcudf_rows(columns: Sequence) -> Tuple[np.ndarray, np.ndarray]:
    """Host table -> ``(flat_bytes uint8[total], row_sizes int64[n])``.

    Byte-identical to the rows the device path emits (a single
    ``ops.row_conversion.convert_to_rows`` batch): column values aligned to
    their widths, string (offset,length) pairs + char tails, validity bytes,
    rows padded to 8.  Rows are independent of batching, so concatenating
    encoded chunks yields one valid row stream — the append-only spill-file
    property this codec exists for.
    """
    n = _nrows(columns[0])
    dtypes = [c.dtype for c in columns]
    starts, sizes, validity_offset, size_per_row = compute_layout(dtypes)

    fixed = np.zeros((n, size_per_row), np.uint8)
    within = np.full(n, size_per_row, np.int64)
    str_plan: List[tuple] = []
    for col, start, size in zip(columns, starts, sizes):
        if col.dtype.kind == Kind.STRING:
            offs = np.asarray(col.offsets, np.int64)
            lens = offs[1:] - offs[:-1]
            pair = np.empty((n, 2), "<u4")
            pair[:, 0] = within
            pair[:, 1] = lens
            fixed[:, start:start + 8] = pair.view(np.uint8).reshape(n, 8)
            str_plan.append((col, within.copy(), offs, lens))
            within = within + lens
        else:
            fixed[:, start:start + size] = _fixed_le_bytes(col)
    fixed[:, validity_offset:size_per_row] = _validity_bytes(columns, n)

    if str_plan:
        row_sizes = _round_up(within, JCUDF_ROW_ALIGNMENT)
    else:
        row_sizes = np.full(
            n, _round_up(size_per_row, JCUDF_ROW_ALIGNMENT), np.int64)
    total = int(row_sizes.sum())
    out = np.zeros(total, np.uint8)
    row_off = np.cumsum(row_sizes, dtype=np.int64) - row_sizes
    out[row_off[:, None] + np.arange(size_per_row, dtype=np.int64)] = fixed
    for col, sstarts, offs, lens in str_plan:
        nchars = int(lens.sum())
        if nchars == 0:
            continue
        ragged = _ragged_arange(lens, nchars)
        src = np.asarray(col.chars)[np.repeat(offs[:-1], lens) + ragged]
        out[np.repeat(row_off + sstarts, lens) + ragged] = src
    return out, row_sizes


def decode_jcudf_rows(
    buf: np.ndarray,
    row_offsets: np.ndarray,
    dtypes: Sequence[DType],
    select: Optional[Sequence[int]] = None,
) -> List:
    """JCUDF row bytes -> host (numpy-backed) columns.

    ``row_offsets`` is int64[n+1] (exclusive scan of row sizes).  With
    ``select``, only those column indices are decoded (others come back as
    ``None``) — how a disk split reads just the key columns of a bucket.
    """
    starts, sizes, validity_offset, _ = compute_layout(dtypes)
    n = len(row_offsets) - 1
    row_off = np.asarray(row_offsets, np.int64)[:-1]
    nb = (len(dtypes) + 7) // 8
    vbytes = buf[row_off[:, None] + validity_offset
                 + np.arange(nb, dtype=np.int64)]
    sel = set(range(len(dtypes))) if select is None else set(select)
    out: List = []
    for c, (dt, start, size) in enumerate(zip(dtypes, starts, sizes)):
        if c not in sel:
            out.append(None)
            continue
        valid = ((vbytes[:, c // 8] >> np.uint8(c % 8)) & 1).astype(bool)
        validity = None if bool(valid.all()) else valid
        if dt.kind == Kind.STRING:
            praw = np.ascontiguousarray(
                buf[row_off[:, None] + start + np.arange(8, dtype=np.int64)])
            pair = praw.view("<u4").reshape(n, 2)
            soff = pair[:, 0].astype(np.int64)
            slen = pair[:, 1].astype(np.int64)
            nchars = int(slen.sum())
            ragged = _ragged_arange(slen, nchars)
            chars = buf[np.repeat(row_off + soff, slen) + ragged]
            offsets = np.zeros(n + 1, np.int32)
            offsets[1:] = np.cumsum(slen).astype(np.int32)
            out.append(StringColumn(chars, offsets, validity))
        elif dt.kind == Kind.DECIMAL128:
            raw = np.ascontiguousarray(
                buf[row_off[:, None] + start + np.arange(16, dtype=np.int64)])
            lo = raw[:, :8].copy().view("<u8").ravel()
            hi = raw[:, 8:].copy().view("<i8").ravel()
            out.append(Decimal128Column(hi, lo, validity, dt))
        else:
            raw = np.ascontiguousarray(
                buf[row_off[:, None] + start
                    + np.arange(size, dtype=np.int64)])
            data = raw.view(_np_le(dt)).ravel()
            if dt.kind == Kind.BOOL:
                data = data != 0
            out.append(Column(data, validity, dt))
    return out


# ------------------------------------------------------------ key hashing --

_GOLDEN = np.uint64(0x9E3779B97F4A7C15)


def splitmix64(x: np.ndarray) -> np.ndarray:
    """SplitMix64 finalizer over a uint64 vector (well-mixed, stable)."""
    with np.errstate(over="ignore"):
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return x ^ (x >> np.uint64(31))


def pair_mix64(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Mixed hash of an (int32, int32) key pair: splitmix64 over the packed
    pair.  TPC-DS surrogate keys are dense; packing then finalizing spreads
    them (models.streaming.bucket_of_pairs is this mod n_buckets)."""
    k = ((a.astype(np.int64).astype(np.uint64) << np.uint64(32))
         | (b.astype(np.int64).astype(np.uint64) & np.uint64(0xFFFFFFFF)))
    return splitmix64(k)


def _key_limbs(col) -> List[np.ndarray]:
    """uint64 limb(s) of a fixed-width key column, nulls normalized to a
    flag limb (null data bytes are garbage by contract and must not steer
    routing)."""
    if isinstance(col, StringColumn):
        raise TypeError("string key columns are not supported for the "
                        "external shuffle hash (fixed-width keys only)")
    if isinstance(col, Decimal128Column):
        limbs = [np.asarray(col.lo).astype(np.uint64),
                 np.asarray(col.hi).astype(np.int64).astype(np.uint64)]
    else:
        data = np.asarray(col.data)
        if col.dtype.kind == Kind.BOOL:
            data = data.astype(np.uint8)
        limbs = [data.astype(np.int64).astype(np.uint64)]
    # The null-flag limb is UNCONDITIONAL: a chunk appended with an
    # all-valid mask and the same rows decoded later with validity=None
    # must hash identically, or disk splits would re-route rows.
    if col.validity is None:
        limbs.append(np.zeros(len(limbs[0]), np.uint64))
    else:
        valid = np.asarray(col.validity)
        limbs = [np.where(valid, limb, np.uint64(0)) for limb in limbs]
        limbs.append(np.where(valid, np.uint64(0), _GOLDEN))
    return limbs


def chained_key_hash(cols: Sequence) -> np.ndarray:
    """General N-column key hash: fold every column's 64-bit limbs through
    splitmix64.  Any fixed mix works — both sides of a join must agree,
    nothing else — but it must spread dense keys (see pair_mix64)."""
    n = _nrows(cols[0])
    h = np.zeros(n, np.uint64)
    with np.errstate(over="ignore"):
        for i, col in enumerate(cols):
            for limb in _key_limbs(col):
                h = splitmix64(h ^ (limb + np.uint64(i + 1) * _GOLDEN))
    return h


# -------------------------------------------------------- the disk shuffle --


class ExternalTableShuffle:
    """Disk-backed grace-hash partitioner for full columnar tables.

    ``append(side, columns)`` routes a chunk's rows to per-(side, bucket)
    spill files holding JCUDF row bytes (append-only); ``read(side, b)``
    materializes one bucket back into columns.  Peak host memory is one
    chunk during routing plus one bucket during execution.

    ``split_bucket(b)`` refines one bucket into two ON DISK with bounded
    memory: per-bucket hash modulus doubles (``hash % M == b`` implies
    ``hash % 2M in {b, b+M}``), so refinement is consistent across sides —
    the recursive-grace-hash rung of the split-and-retry protocol.  Only
    the key columns are decoded during a split; row bytes move verbatim.

    Spill files: ``{side}.{bucket:04d}.rows`` (JCUDF row bytes) plus, for
    schemas with strings (variable row size), ``.len`` (little-endian
    uint32 row sizes).  Fixed-width schemas need no length file — every
    row is ``fixed_row_size`` bytes.
    """

    def __init__(self, tmpdir: str, n_buckets: int,
                 dtypes: Sequence[DType],
                 key_indices: Sequence[int],
                 key_hash: Optional[Callable[[Sequence], np.ndarray]] = None):
        self.dir = tmpdir
        self.n_buckets = n_buckets
        self.dtypes = list(dtypes)
        self.key_indices = tuple(key_indices)
        self.key_hash = key_hash if key_hash is not None else chained_key_hash
        self.has_strings = any(d.kind == Kind.STRING for d in self.dtypes)
        _, _, _, size_per_row = compute_layout(self.dtypes)
        self.fixed_row_size = _round_up(size_per_row, JCUDF_ROW_ALIGNMENT)
        self.rows: Dict[Tuple[str, int], int] = {}
        self._modulus: Dict[int, int] = {}
        os.makedirs(tmpdir, exist_ok=True)

    # -- paths -------------------------------------------------------------

    def _path(self, side: str, bucket: int, ext: str) -> str:
        return os.path.join(self.dir, f"{side}.{bucket:04d}.{ext}")

    def _sides(self) -> List[str]:
        return sorted({s for (s, _b) in self.rows})

    # -- ingest ------------------------------------------------------------

    def row_hashes(self, columns: Sequence) -> np.ndarray:
        """The routing hash of a chunk (uint64[n]); ``% n_buckets`` is the
        bucket id — exposed so owners can filter chunks before spooling."""
        return self.key_hash([columns[i] for i in self.key_indices])

    def append(self, side: str, columns: Sequence,
               hashes: Optional[np.ndarray] = None) -> None:
        """Route one chunk's rows to this side's bucket spill files."""
        if self._modulus:
            raise ValueError(
                "append after split_bucket would route at the wrong modulus")
        n = _nrows(columns[0])
        if n == 0:
            return
        if hashes is None:
            hashes = self.row_hashes(columns)
        ids = (hashes % np.uint64(self.n_buckets)).astype(np.int64)
        buf, row_sizes = encode_jcudf_rows(columns)
        row_off = np.cumsum(row_sizes, dtype=np.int64) - row_sizes
        order = np.argsort(ids, kind="stable")
        sorted_ids = ids[order]
        uniq, starts = np.unique(sorted_ids, return_index=True)
        ends = np.append(starts[1:], len(sorted_ids))
        for b, s, e in zip(uniq.tolist(), starts.tolist(), ends.tolist()):
            idx = order[s:e]
            sz = row_sizes[idx]
            byte_idx = np.repeat(row_off[idx], sz) + _ragged_arange(sz)
            with open(self._path(side, b, "rows"), "ab") as f:
                f.write(buf[byte_idx].tobytes())
            if self.has_strings:
                with open(self._path(side, b, "len"), "ab") as f:
                    f.write(sz.astype("<u4").tobytes())
            key = (side, int(b))
            self.rows[key] = self.rows.get(key, 0) + int(e - s)

    # -- read back ---------------------------------------------------------

    def _bucket_row_sizes(self, side: str, bucket: int) -> np.ndarray:
        if self.has_strings:
            path = self._path(side, bucket, "len")
            if not os.path.exists(path):
                return np.zeros(0, np.int64)
            with open(path, "rb") as f:
                return np.frombuffer(f.read(), "<u4").astype(np.int64)
        n = self.rows.get((side, bucket), 0)
        return np.full(n, self.fixed_row_size, np.int64)

    def read(self, side: str, bucket: int) -> List:
        """Materialize one (side, bucket) as host columns."""
        path = self._path(side, bucket, "rows")
        if not os.path.exists(path):
            empty = np.zeros(0, np.uint8)
            return decode_jcudf_rows(empty, np.zeros(1, np.int64), self.dtypes)
        with open(path, "rb") as f:
            buf = np.frombuffer(f.read(), np.uint8)
        sizes = self._bucket_row_sizes(side, bucket)
        offsets = np.zeros(len(sizes) + 1, np.int64)
        np.cumsum(sizes, out=offsets[1:])
        return decode_jcudf_rows(buf, offsets, self.dtypes)

    # -- accounting --------------------------------------------------------

    def bucket_nbytes(self, bucket: int) -> int:
        """ACTUAL spill bytes of one bucket (all sides, rows + len files) —
        what the host governor reserves before the bucket materializes."""
        total = 0
        for side in self._sides():
            for ext in ("rows", "len"):
                path = self._path(side, bucket, ext)
                if os.path.exists(path):
                    total += os.path.getsize(path)
        return total

    def bucket_rows(self, bucket: int) -> int:
        return sum(n for (s, b), n in self.rows.items() if b == bucket)

    def max_bucket_rows(self) -> int:
        """Largest combined bucket — sizes the exchange capacity once so
        every bucket reuses ONE compiled step."""
        per_bucket: Dict[int, int] = {}
        for (_side, b), n in self.rows.items():
            per_bucket[b] = per_bucket.get(b, 0) + n
        return max(per_bucket.values(), default=0)

    # -- refinement --------------------------------------------------------

    def split_bucket(self, bucket: int,
                     chunk_rows: int = 1 << 18) -> Tuple[int, int]:
        """Refine one bucket into two on DISK with bounded memory.

        Rows whose key hash lands on ``bucket`` at modulus ``2M`` stay; the
        rest move (raw bytes, no re-encode) to ``bucket + M``.  Streamed in
        ``chunk_rows`` chunks — never the whole bucket in memory.
        """
        m = self._modulus.get(bucket, self.n_buckets)
        new_bucket = bucket + m
        for side in self._sides():
            if (side, bucket) not in self.rows:
                continue
            sizes = self._bucket_row_sizes(side, bucket)
            keep_rows = self._path(side, bucket, "rows") + ".keep"
            keep_len = self._path(side, bucket, "len") + ".keep"
            kept = moved = 0
            with open(self._path(side, bucket, "rows"), "rb") as rf, \
                    open(keep_rows, "wb") as kf:
                lf = open(keep_len, "wb") if self.has_strings else None
                try:
                    for at in range(0, len(sizes), chunk_rows):
                        sz = sizes[at:at + chunk_rows]
                        buf = np.frombuffer(rf.read(int(sz.sum())), np.uint8)
                        offs = np.zeros(len(sz) + 1, np.int64)
                        np.cumsum(sz, out=offs[1:])
                        keys = decode_jcudf_rows(
                            buf, offs, self.dtypes, select=self.key_indices)
                        h = self.key_hash([keys[i] for i in self.key_indices])
                        stay = (h % np.uint64(2 * m)).astype(np.int64) == bucket
                        byte_stay = np.repeat(stay, sz)
                        kf.write(buf[byte_stay].tobytes())
                        if not stay.all():
                            with open(self._path(side, new_bucket, "rows"),
                                      "ab") as mf:
                                mf.write(buf[~byte_stay].tobytes())
                            if self.has_strings:
                                with open(self._path(side, new_bucket, "len"),
                                          "ab") as mlf:
                                    mlf.write(
                                        sz[~stay].astype("<u4").tobytes())
                        if self.has_strings:
                            lf.write(sz[stay].astype("<u4").tobytes())
                        kept += int(stay.sum())
                        moved += int((~stay).sum())
                finally:
                    if lf is not None:
                        lf.close()
            os.replace(keep_rows, self._path(side, bucket, "rows"))
            if self.has_strings:
                os.replace(keep_len, self._path(side, bucket, "len"))
            self.rows[(side, bucket)] = kept
            if moved:
                self.rows[(side, new_bucket)] = (
                    self.rows.get((side, new_bucket), 0) + moved)
        self._modulus[bucket] = 2 * m
        self._modulus[new_bucket] = 2 * m
        return bucket, new_bucket

    def close(self) -> None:
        for (side, b) in list(self.rows):
            for ext in ("rows", "len"):
                try:
                    os.remove(self._path(side, b, ext))
                except OSError:
                    pass
        self.rows.clear()
        self._modulus.clear()
