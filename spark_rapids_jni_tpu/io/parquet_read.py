"""Split-planned parquet reading into framework Columns.

The footer filter IS the planner (parity: ``filter_groups`` feeding the
columnar reader, NativeParquetJni.cpp:584 / ParquetFooter.java:190-215):
``read_split`` parses the file's thrift footer with
:class:`~spark_rapids_jni_tpu.io.ParquetFooter`, prunes its schema to the
expected columns, selects the row groups whose byte midpoint falls inside
``[part_offset, part_offset + part_length)``, and then materializes ONLY
those groups and ONLY the surviving columns through the host columnar
decoder (pyarrow — the cuIO stand-in on this host path; the reference JNI
likewise plans on the CPU and hands the filtered footer to a separate
reader).  Byte-range splits partition a file: every row group belongs to
exactly one split, so N executors reading N splits see each row exactly
once.

Columns come back in the framework's device layout: fixed-width data as
``Column`` (FLOAT64 as IEEE-754 bits in int64, per columnar convention),
strings as ``StringColumn``.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from spark_rapids_jni_tpu.io.parquet_footer import ParquetFooter, StructElement

__all__ = ["footer_bytes", "plan_byte_splits", "read_split",
           "iter_split_batches", "SplitPlan"]

_MAGIC = b"PAR1"


def footer_bytes(path: str) -> bytes:
    """The raw thrift FileMetaData bytes of a parquet file (no magic)."""
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        f.seek(max(0, size - 8))
        tail = f.read(8)
        if tail[-4:] != _MAGIC:
            raise ValueError(f"{path}: not a parquet file (missing PAR1)")
        n = int.from_bytes(tail[:4], "little")
        f.seek(size - 8 - n)
        return f.read(n)


def plan_byte_splits(path: str, n_splits: int) -> List[Tuple[int, int]]:
    """Even byte-range splits of a file, Spark-style ``(offset, length)``.

    The ranges partition ``[0, file_size)`` exactly — never a negative or
    zero length (a negative length would read as read_and_filter's
    "filtering disabled" mode and double-count every row group) — so the
    midpoint rule assigns each row group to exactly one split.  Asking
    for more splits than bytes yields fewer splits.
    """
    size = os.path.getsize(path)
    n_splits = max(1, min(n_splits, max(1, size)))
    bounds = sorted({i * size // n_splits for i in range(n_splits)} | {size})
    return [(lo, hi - lo) for lo, hi in zip(bounds, bounds[1:]) if hi > lo]


class SplitPlan:
    """What one executor reads of one file: surviving row-group indices +
    surviving column projection, both decided by the filtered footer."""

    def __init__(self, path: str, group_indexes: List[int],
                 columns: List[str], num_rows: int):
        self.path = path
        self.group_indexes = group_indexes
        self.columns = columns
        self.num_rows = num_rows


def plan_split(path: str, part_offset: int, part_length: int,
               schema: StructElement, ignore_case: bool = False) -> SplitPlan:
    """Plan a split: ONE footer parse yields both the surviving row-group
    indices and the pruned column projection."""
    fb = footer_bytes(path)
    footer = ParquetFooter.read_and_filter(
        fb, part_offset, part_length, schema, ignore_case)
    return SplitPlan(path, footer.kept_group_indexes,
                     footer.column_names, footer.num_rows)


def _arrow_to_column(arr):
    """One pyarrow ChunkedArray/Array -> framework Column/StringColumn."""
    import jax.numpy as jnp
    import pyarrow as pa

    from spark_rapids_jni_tpu import columnar as c

    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    t = arr.type
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return c.strings_from_bytes(
            [v.as_py().encode() if v.is_valid else None for v in arr])

    validity = None
    if arr.null_count:
        validity = np.asarray(arr.is_valid())

    if pa.types.is_decimal128(t):
        # Parquet DECIMAL(p, s) -> unscaled storage by precision, the same
        # storage rule as dtypes.decimal(): int32/int64 Columns for p<=9 /
        # p<=18, two's-complement limb pairs above (the reference's pruner
        # round-trips the decimal Tag tree, NativeParquetJni.cpp:102-109).
        # Arrow decimal128 buffers are 16-byte little-endian two's
        # complement; decode limbs straight from the buffer.
        n = len(arr)
        raw = np.frombuffer(arr.buffers()[1], np.uint8)
        raw = raw[arr.offset * 16:(arr.offset + n) * 16].reshape(n, 16)
        lo = raw[:, :8].copy().view("<u8").ravel()
        hi = raw[:, 8:].copy().view("<i8").ravel()
        dt = c.decimal(t.precision, t.scale)
        jval = None if validity is None else jnp.asarray(validity)
        if dt.kind == c.Kind.DECIMAL128:
            return c.Decimal128Column(
                jnp.asarray(hi), jnp.asarray(lo), jval, dt)
        # p<=18 fits the low limb exactly (int64 two's complement)
        unscaled = lo.view("<i8")
        if dt.kind == c.Kind.DECIMAL32:
            unscaled = unscaled.astype(np.int32)
        return c.Column(jnp.asarray(unscaled), jval, dt)
    if pa.types.is_int32(t):
        np_vals, dt = arr.fill_null(0).to_numpy().astype(np.int32), c.INT32
    elif pa.types.is_int64(t):
        np_vals, dt = arr.fill_null(0).to_numpy().astype(np.int64), c.INT64
    elif pa.types.is_float64(t):
        # FLOAT64 columns carry IEEE-754 bits as int64 (columnar convention)
        np_vals = arr.fill_null(0.0).to_numpy().astype(np.float64)
        np_vals, dt = np_vals.view(np.int64), c.FLOAT64
    elif pa.types.is_date32(t):
        np_vals = np.asarray(arr.fill_null(0).cast(pa.int32()))
        dt = c.DATE32
    elif pa.types.is_timestamp(t) and t.unit == "us":
        np_vals = np.asarray(arr.fill_null(0).cast(pa.int64()))
        dt = c.TIMESTAMP_MICROS
    else:
        raise NotImplementedError(f"parquet_read: unsupported type {t}")
    return c.Column(jnp.asarray(np_vals),
                    None if validity is None else jnp.asarray(validity), dt)


def _table_columns(table, columns, as_numpy: bool) -> Dict[str, object]:
    """One decoded arrow table -> framework Columns (or, with
    ``as_numpy``, raw ``(values, validity)`` host pairs)."""
    import pyarrow as pa

    out: Dict[str, object] = {}
    for name in columns:
        col = table.column(name)
        if as_numpy:
            arr = col.combine_chunks() if isinstance(
                col, pa.ChunkedArray) else col
            valid: Optional[np.ndarray] = None
            if arr.null_count:
                valid = np.asarray(arr.is_valid())
            if pa.types.is_string(arr.type):
                vals = [v.as_py() if v.is_valid else None for v in arr]
            else:
                filled = arr.fill_null(0)
                try:
                    # decimals need an explicit copy (object array of
                    # decimal.Decimal); numeric types stay zero-copy
                    vals = filled.to_numpy(zero_copy_only=False)
                except TypeError:  # ChunkedArray.to_numpy always copies
                    vals = filled.to_numpy()
            out[name] = (vals, valid)
        else:
            out[name] = _arrow_to_column(col)
    return out


def read_split(path: str, part_offset: int, part_length: int,
               schema: StructElement, ignore_case: bool = False,
               as_numpy: bool = False) -> Dict[str, object]:
    """Read one split of one parquet file into framework Columns.

    Only the row groups the footer filter selected and only the columns
    surviving the schema prune are ever decoded — the projection list
    handed to the decoder comes from the filtered footer itself.  With
    ``as_numpy`` the raw host arrays are returned instead of Columns
    (for host-side pipelines that shard before upload).
    """
    import pyarrow.parquet as pq

    plan = plan_split(path, part_offset, part_length, schema, ignore_case)
    pf = pq.ParquetFile(path)
    tables = [pf.read_row_group(g, columns=plan.columns)
              for g in plan.group_indexes]
    if tables:
        import pyarrow as pa

        table = pa.concat_tables(tables)
    else:
        table = pf.schema_arrow.empty_table().select(plan.columns)
    if table.num_rows != plan.num_rows:
        raise AssertionError(
            f"{path}: footer planned {plan.num_rows} rows, "
            f"decoder produced {table.num_rows}")
    return _table_columns(table, plan.columns, as_numpy)


def iter_split_batches(path: str, part_offset: int, part_length: int,
                       schema: StructElement, ignore_case: bool = False,
                       as_numpy: bool = False):
    """Chunked scan of one split: yield ONE decoded batch per surviving
    row group, never materializing the whole split.

    This is the composition of the footer planner with out-of-core
    execution: each batch feeds the external grace-hash shuffle
    (io/spill.py) with host memory bounded by a single row group — the
    reason the reference's footer filter exists is to plan scans of files
    too big to hold (NativeParquetJni.cpp:584 filter_groups handing the
    filtered footer to a chunked reader).  The split's planned row count
    is re-checked across the yielded batches.
    """
    import pyarrow.parquet as pq

    plan = plan_split(path, part_offset, part_length, schema, ignore_case)
    pf = pq.ParquetFile(path)
    got = 0
    for g in plan.group_indexes:
        table = pf.read_row_group(g, columns=plan.columns)
        got += table.num_rows
        yield _table_columns(table, plan.columns, as_numpy)
    if got != plan.num_rows:
        raise AssertionError(
            f"{path}: footer planned {plan.num_rows} rows, "
            f"chunked decoder produced {got}")
