"""Host-side IO tooling (parquet footer parse/filter/serialize + the
split-planned reader that consumes the filtered footer)."""

from spark_rapids_jni_tpu.io.parquet_footer import (
    ListElement,
    MapElement,
    ParquetFooter,
    StructBuilder,
    StructElement,
    ValueElement,
)
from spark_rapids_jni_tpu.io.parquet_read import (
    iter_split_batches,
    plan_byte_splits,
    plan_split,
    read_split,
)

__all__ = [
    "ListElement",
    "MapElement",
    "ParquetFooter",
    "StructBuilder",
    "StructElement",
    "ValueElement",
    "iter_split_batches",
    "plan_byte_splits",
    "plan_split",
    "read_split",
]
