"""Host-side IO tooling (parquet footer parse/filter/serialize)."""

from spark_rapids_jni_tpu.io.parquet_footer import (
    ListElement,
    MapElement,
    ParquetFooter,
    StructBuilder,
    StructElement,
    ValueElement,
)

__all__ = [
    "ListElement",
    "MapElement",
    "ParquetFooter",
    "StructBuilder",
    "StructElement",
    "ValueElement",
]
