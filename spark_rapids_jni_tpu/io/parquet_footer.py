"""Parquet thrift footer: parse, row-group filter, column prune, re-serialize.

Parity target: ``ParquetFooter.readAndFilter`` (ParquetFooter.java:190-215)
over ``NativeParquetJni.cpp`` — ``deserialize_parquet_footer`` (:639),
``column_pruner`` (:109, Tag tree VALUE/STRUCT/LIST/MAP :102),
``filter_groups`` midpoint-in-split selection (:584), ``filter_columns``
(:671), and the PAR1-wrapped ``serializeThriftFile`` (:793).  Host CPU work in
the reference too (Apache Thrift TCompactProtocol, no GPU), so a host Python
implementation is the idiomatic mapping; the arrays never touch the device.

Instead of transcribing the full parquet.thrift schema, the footer is parsed
into *generic* compact-protocol structs (field-id -> (type, value), in wire
order).  Filtering edits only the fields Spark's split planning needs —
FileMetaData.schema(2) / row_groups(4) / column_orders(7) — and everything
else round-trips byte-for-byte.  Semantic field ids used below (from
parquet-format parquet.thrift):

- FileMetaData: 2=schema, 4=row_groups, 7=column_orders
- SchemaElement: 1=type, 3=repetition_type, 4=name, 5=num_children,
  6=converted_type
- RowGroup: 1=columns, 3=num_rows, 5=file_offset, 6=total_compressed_size
- ColumnChunk: 3=meta_data; ColumnMetaData: 7=total_compressed_size,
  9=data_page_offset, 11=dictionary_page_offset

DELIBERATE DEVIATION from the reference: ``read_and_filter`` rewrites
``FileMetaData.num_rows`` (field 3) to the sum over surviving row groups so
the re-serialized footer is self-consistent; ``NativeParquetJni.cpp`` leaves
the original file-level count stale and computes ``getNumRows`` from
row_groups instead.  Readers that trust FileMetaData.num_rows (parquet-mr
split planning) will see the filtered count here, the unfiltered one there.
"""

from __future__ import annotations

import struct as _structmod
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "ParquetFooter",
    "StructElement",
    "StructBuilder",
    "ValueElement",
    "ListElement",
    "MapElement",
]

# thrift compact-protocol wire types
_T_STOP = 0
_T_TRUE = 1
_T_FALSE = 2
_T_BYTE = 3
_T_I16 = 4
_T_I32 = 5
_T_I64 = 6
_T_DOUBLE = 7
_T_BINARY = 8
_T_LIST = 9
_T_SET = 10
_T_MAP = 11
_T_STRUCT = 12

# parquet enum values used by the pruner
_REPETITION_REPEATED = 2  # FieldRepetitionType.REPEATED
_CONVERTED_MAP = 1  # ConvertedType.MAP
_CONVERTED_MAP_KEY_VALUE = 2  # ConvertedType.MAP_KEY_VALUE

_MAGIC = b"PAR1"


# --------------------------------------------------------------------------
# schema description (mirrors ParquetFooter.java SchemaElement classes)
# --------------------------------------------------------------------------

class SchemaNode:
    """Base of the stripped-down expected-schema tree."""


class ValueElement(SchemaNode):
    pass


class StructElement(SchemaNode):
    def __init__(self, children: Sequence[Tuple[str, SchemaNode]]):
        self.children = list(children)

    @staticmethod
    def builder() -> "StructBuilder":
        return StructBuilder()


class StructBuilder:
    def __init__(self):
        self._children: List[Tuple[str, SchemaNode]] = []

    def add_child(self, name: str, child: SchemaNode) -> "StructBuilder":
        self._children.append((name, child))
        return self

    def build(self) -> StructElement:
        return StructElement(self._children)


class ListElement(SchemaNode):
    def __init__(self, item: SchemaNode):
        self.item = item


class MapElement(SchemaNode):
    def __init__(self, key: SchemaNode, value: SchemaNode):
        self.key = key
        self.value = value


# --------------------------------------------------------------------------
# generic thrift compact protocol
# --------------------------------------------------------------------------

def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63) if n >= 0 else ((-n) << 1) - 1


def _write_varint(out: bytearray, n: int) -> None:
    while True:
        if n & ~0x7F:
            out.append((n & 0x7F) | 0x80)
            n >>= 7
        else:
            out.append(n)
            return


# A parsed struct is a list of (field_id, wire_type, value) in wire order;
# lists are (elem_type, [values]); maps are (ktype, vtype, [(k, v)...]).

TStruct = List[Tuple[int, int, object]]


def _read_value(buf: bytes, pos: int, ttype: int) -> Tuple[object, int]:
    if ttype == _T_TRUE:
        return True, pos
    if ttype == _T_FALSE:
        return False, pos
    if ttype == _T_BYTE:
        return buf[pos], pos + 1
    if ttype in (_T_I16, _T_I32, _T_I64):
        raw, pos = _read_varint(buf, pos)
        return _unzigzag(raw), pos
    if ttype == _T_DOUBLE:
        return _structmod.unpack("<d", buf[pos : pos + 8])[0], pos + 8
    if ttype == _T_BINARY:
        ln, pos = _read_varint(buf, pos)
        return bytes(buf[pos : pos + ln]), pos + ln
    if ttype in (_T_LIST, _T_SET):
        head = buf[pos]
        pos += 1
        etype = head & 0x0F
        size = head >> 4
        if size == 0x0F:
            size, pos = _read_varint(buf, pos)
        vals = []
        for _ in range(size):
            if etype == _T_TRUE:  # bools in lists are one byte each
                vals.append(buf[pos] == 1)
                pos += 1
            else:
                v, pos = _read_value(buf, pos, etype)
                vals.append(v)
        return (etype, vals), pos
    if ttype == _T_MAP:
        size, pos = _read_varint(buf, pos)
        if size == 0:
            return (0, 0, []), pos
        head = buf[pos]
        pos += 1
        ktype, vtype = head >> 4, head & 0x0F
        pairs = []

        def _elem(p, etype):
            # bools in map elements are one byte each, like list elements
            if etype == _T_TRUE:
                return buf[p] == 1, p + 1
            return _read_value(buf, p, etype)

        for _ in range(size):
            k, pos = _elem(pos, ktype)
            v, pos = _elem(pos, vtype)
            pairs.append((k, v))
        return (ktype, vtype, pairs), pos
    if ttype == _T_STRUCT:
        return _read_struct(buf, pos)
    raise ValueError(f"Couldn't deserialize thrift: unknown type {ttype}")


def _read_struct(buf: bytes, pos: int) -> Tuple[TStruct, int]:
    fields: TStruct = []
    last_fid = 0
    while True:
        head = buf[pos]
        pos += 1
        if head == _T_STOP:
            return fields, pos
        delta = head >> 4
        ttype = head & 0x0F
        if delta:
            fid = last_fid + delta
        else:
            raw, pos = _read_varint(buf, pos)
            fid = _unzigzag(raw)
        last_fid = fid
        value, pos = _read_value(buf, pos, ttype)
        fields.append((fid, ttype, value))


def _write_value(out: bytearray, ttype: int, value) -> None:
    if ttype in (_T_TRUE, _T_FALSE):
        return  # encoded in the field header for struct fields
    if ttype == _T_BYTE:
        out.append(value & 0xFF)
    elif ttype in (_T_I16, _T_I32, _T_I64):
        _write_varint(out, _zigzag(value))
    elif ttype == _T_DOUBLE:
        out += _structmod.pack("<d", value)
    elif ttype == _T_BINARY:
        _write_varint(out, len(value))
        out += value
    elif ttype in (_T_LIST, _T_SET):
        etype, vals = value
        if len(vals) < 15:
            out.append((len(vals) << 4) | etype)
        else:
            out.append(0xF0 | etype)
            _write_varint(out, len(vals))
        for v in vals:
            if etype == _T_TRUE:
                out.append(1 if v else 2)
            else:
                _write_value(out, etype, v)
    elif ttype == _T_MAP:
        ktype, vtype, pairs = value
        _write_varint(out, len(pairs))
        if pairs:
            out.append((ktype << 4) | vtype)
            for k, v in pairs:
                if ktype == _T_TRUE:
                    out.append(1 if k else 2)
                else:
                    _write_value(out, ktype, k)
                if vtype == _T_TRUE:
                    out.append(1 if v else 2)
                else:
                    _write_value(out, vtype, v)
    elif ttype == _T_STRUCT:
        _write_struct(out, value)
    else:
        raise ValueError(f"cannot serialize thrift type {ttype}")


def _write_struct(out: bytearray, fields: TStruct) -> None:
    last_fid = 0
    for fid, ttype, value in fields:
        wire_t = ttype
        if ttype in (_T_TRUE, _T_FALSE):
            wire_t = _T_TRUE if value else _T_FALSE
        delta = fid - last_fid
        if 0 < delta <= 15:
            out.append((delta << 4) | wire_t)
        else:
            out.append(wire_t)
            _write_varint(out, _zigzag(fid))
        last_fid = fid
        _write_value(out, ttype, value)
    out.append(_T_STOP)


# --------------------------------------------------------------------------
# field access helpers over generic structs
# --------------------------------------------------------------------------

def _get(fields: TStruct, fid: int, default=None):
    for f, _t, v in fields:
        if f == fid:
            return v
    return default


def _has(fields: TStruct, fid: int) -> bool:
    return any(f == fid for f, _t, _v in fields)


def _set(fields: TStruct, fid: int, ttype: int, value) -> TStruct:
    out = [(f, t, v) for f, t, v in fields if f != fid]
    out.append((fid, ttype, value))
    out.sort(key=lambda x: x[0])  # compact protocol needs ascending ids
    return out


class _Elem:
    """SchemaElement accessor over a generic struct."""

    def __init__(self, fields: TStruct):
        self.fields = fields

    @property
    def name(self) -> str:
        return _get(self.fields, 4, b"").decode("utf-8")

    @property
    def is_leaf(self) -> bool:
        return _has(self.fields, 1)  # type is set

    @property
    def num_children(self) -> int:
        return _get(self.fields, 5, 0) or 0

    @property
    def repetition_type(self) -> Optional[int]:
        return _get(self.fields, 3)

    @property
    def converted_type(self) -> Optional[int]:
        return _get(self.fields, 6)


class _PrunerNode:
    """column_pruner (NativeParquetJni.cpp:109): expected-schema tree node."""

    VALUE, STRUCT, LIST, MAP = range(4)

    def __init__(self, tag: int):
        self.tag = tag
        self.children: Dict[str, "_PrunerNode"] = {}

    @staticmethod
    def from_schema(schema: StructElement, ignore_case: bool) -> "_PrunerNode":
        def build(node: SchemaNode) -> "_PrunerNode":
            if isinstance(node, ValueElement):
                return _PrunerNode(_PrunerNode.VALUE)
            if isinstance(node, StructElement):
                p = _PrunerNode(_PrunerNode.STRUCT)
                for name, child in node.children:
                    p.children[name.lower() if ignore_case else name] = build(child)
                return p
            if isinstance(node, ListElement):
                p = _PrunerNode(_PrunerNode.LIST)
                p.children["element"] = build(node.item)
                return p
            if isinstance(node, MapElement):
                p = _PrunerNode(_PrunerNode.MAP)
                p.children["key"] = build(node.key)
                p.children["value"] = build(node.value)
                return p
            raise TypeError(f"{node} is not a supported schema element type")

        return build(schema)

    # -- filtering (mirrors filter_schema_* at NativeParquetJni.cpp:193-498)

    def filter_schema(self, schema: List[_Elem], ignore_case: bool):
        state = {"si": 0, "ci": 0}
        chunk_map: List[int] = []
        schema_map: List[int] = []
        schema_num_children: List[int] = []
        self._filter(schema, ignore_case, state, chunk_map, schema_map,
                     schema_num_children)
        return schema_map, schema_num_children, chunk_map

    def _name(self, elem: _Elem, ignore_case: bool) -> str:
        return elem.name.lower() if ignore_case else elem.name

    def _skip(self, schema: List[_Elem], state) -> None:
        num_to_skip = 1
        while num_to_skip > 0 and state["si"] < len(schema):
            item = schema[state["si"]]
            if item.is_leaf:
                state["ci"] += 1
            num_to_skip += item.num_children - 1
            state["si"] += 1

    def _filter(self, schema, ignore_case, state, chunk_map, schema_map,
                schema_num_children):
        if self.tag == _PrunerNode.STRUCT:
            self._filter_struct(schema, ignore_case, state, chunk_map,
                                schema_map, schema_num_children)
        elif self.tag == _PrunerNode.VALUE:
            self._filter_value(schema, state, chunk_map, schema_map,
                               schema_num_children)
        elif self.tag == _PrunerNode.LIST:
            self._filter_list(schema, ignore_case, state, chunk_map,
                              schema_map, schema_num_children)
        else:
            self._filter_map(schema, ignore_case, state, chunk_map,
                             schema_map, schema_num_children)

    def _filter_struct(self, schema, ignore_case, state, chunk_map,
                       schema_map, schema_num_children):
        item = schema[state["si"]]
        if item.is_leaf:
            raise ValueError("Found a leaf node, but expected to find a struct")
        num_children = item.num_children
        schema_map.append(state["si"])
        my_nc_index = len(schema_num_children)
        schema_num_children.append(0)
        state["si"] += 1
        for _ in range(num_children):
            if state["si"] >= len(schema):
                break
            child = schema[state["si"]]
            found = self.children.get(self._name(child, ignore_case))
            if found is not None:
                schema_num_children[my_nc_index] += 1
                found._filter(schema, ignore_case, state, chunk_map,
                              schema_map, schema_num_children)
            else:
                self._skip(schema, state)

    def _filter_value(self, schema, state, chunk_map, schema_map,
                      schema_num_children):
        item = schema[state["si"]]
        if not item.is_leaf:
            raise ValueError("found a non-leaf entry when reading a leaf value")
        if item.num_children != 0:
            raise ValueError("found an entry with children when reading a leaf value")
        schema_map.append(state["si"])
        schema_num_children.append(0)
        state["si"] += 1
        chunk_map.append(state["ci"])
        state["ci"] += 1

    def _filter_list(self, schema, ignore_case, state, chunk_map, schema_map,
                     schema_num_children):
        found = self.children["element"]
        item = schema[state["si"]]
        list_name = item.name
        if item.is_leaf:
            # parquet list rule 1: repeated non-group IS the element
            if item.repetition_type != _REPETITION_REPEATED:
                raise ValueError("expected list item to be repeating")
            return self._filter_value(schema, state, chunk_map, schema_map,
                                      schema_num_children)
        if item.num_children > 1:
            # rule 2: repeated group with several fields IS the element
            if item.repetition_type != _REPETITION_REPEATED:
                raise ValueError("expected list item to be repeating")
            return found._filter(schema, ignore_case, state, chunk_map,
                                 schema_map, schema_num_children)
        if item.num_children != 1:
            raise ValueError("the structure of the outer list group is not standard")
        schema_map.append(state["si"])
        schema_num_children.append(1)
        state["si"] += 1

        rep = schema[state["si"]]
        if rep.repetition_type != _REPETITION_REPEATED:
            raise ValueError(
                "the structure of the list's child is not standard (non repeating)")
        if (not rep.is_leaf and rep.num_children == 1
                and rep.name != "array" and rep.name != list_name + "_tuple"):
            # standard 3-level list: keep the middle repeated group too
            schema_map.append(state["si"])
            schema_num_children.append(1)
            state["si"] += 1
            found._filter(schema, ignore_case, state, chunk_map, schema_map,
                          schema_num_children)
        else:
            # legacy 2-level list
            found._filter(schema, ignore_case, state, chunk_map, schema_map,
                          schema_num_children)

    def _filter_map(self, schema, ignore_case, state, chunk_map, schema_map,
                    schema_num_children):
        key_found = self.children["key"]
        value_found = self.children["value"]
        item = schema[state["si"]]
        if item.is_leaf:
            raise ValueError("expected a map item, but found a single value")
        if item.converted_type not in (_CONVERTED_MAP, _CONVERTED_MAP_KEY_VALUE):
            raise ValueError("expected a map type, but it was not found.")
        if item.num_children != 1:
            raise ValueError("the structure of the outer map group is not standard")
        schema_map.append(state["si"])
        schema_num_children.append(1)
        state["si"] += 1

        rep = schema[state["si"]]
        if rep.repetition_type != _REPETITION_REPEATED:
            raise ValueError("found non repeating map child")
        nkids = rep.num_children
        if nkids not in (1, 2):
            raise ValueError("found map with wrong number of children")
        schema_map.append(state["si"])
        schema_num_children.append(nkids)
        state["si"] += 1
        key_found._filter(schema, ignore_case, state, chunk_map, schema_map,
                          schema_num_children)
        if nkids == 2:
            value_found._filter(schema, ignore_case, state, chunk_map,
                                schema_map, schema_num_children)


# --------------------------------------------------------------------------
# row-group split filtering (NativeParquetJni.cpp:554-637)
# --------------------------------------------------------------------------

def _chunk_offset(chunk_fields: TStruct) -> int:
    md = _get(chunk_fields, 3, [])
    offset = _get(md, 9, 0)  # data_page_offset
    dict_off = _get(md, 11)  # dictionary_page_offset
    if dict_off is not None and offset > dict_off:
        offset = dict_off
    return offset


def _invalid_file_offset(start_index, pre_start_index, pre_compressed_size):
    if pre_start_index == 0 and start_index != 4:
        return True
    return start_index < pre_start_index + pre_compressed_size


def _filter_groups(row_groups: List[TStruct], part_offset: int,
                   part_length: int) -> List[int]:
    """Indices of row groups whose byte midpoint lands inside the split
    (filter_groups, NativeParquetJni.cpp:584): every group belongs to
    exactly one split, so byte-range splits partition a file's groups."""
    pre_start_index = 0
    pre_compressed_size = 0
    first_column_with_metadata = True
    if row_groups:
        cols = _get(row_groups[0], 1, (0, []))[1]
        first_column_with_metadata = bool(cols) and _has(cols[0], 3)

    out = []
    for i, rg in enumerate(row_groups):
        cols = _get(rg, 1, (0, []))[1]
        if first_column_with_metadata:
            start_index = _chunk_offset(cols[0])
        else:
            # PARQUET-2078: only the first block's file_offset is reliable
            start_index = _get(rg, 5, 0)
            if _invalid_file_offset(start_index, pre_start_index,
                                    pre_compressed_size):
                start_index = 4 if pre_start_index == 0 else (
                    pre_start_index + pre_compressed_size)
            pre_start_index = start_index
            pre_compressed_size = _get(rg, 6, 0)
        total_size = _get(rg, 6)
        if total_size is None:
            total_size = sum(
                _get(_get(c, 3, []), 7, 0) for c in cols)
        mid_point = start_index + total_size // 2
        if part_offset <= mid_point < part_offset + part_length:
            out.append(i)
    return out


# --------------------------------------------------------------------------
# public API
# --------------------------------------------------------------------------

class ParquetFooter:
    """A parsed + filtered parquet footer (FileMetaData).

    ``kept_group_indexes`` records which ORIGINAL row-group indices
    survived the split filter in :meth:`read_and_filter` — the plan an
    external columnar reader needs to seek by group without re-parsing
    the footer (io/parquet_read.py consumes it)."""

    def __init__(self, fields: TStruct,
                 kept_group_indexes: Optional[List[int]] = None):
        self._fields = fields
        self.kept_group_indexes = kept_group_indexes or []

    @staticmethod
    def read_and_filter(buffer: bytes, part_offset: int, part_length: int,
                        schema: StructElement, ignore_case: bool
                        ) -> "ParquetFooter":
        """Parse a raw thrift footer, filter row groups to the split, and
        prune columns to ``schema`` (ParquetFooter.java:190 readAndFilter).

        ``buffer`` holds only the thrift FileMetaData bytes (no PAR1 magic).
        ``part_length < 0`` disables row-group filtering, as in the JNI.
        """
        try:
            meta, _ = _read_struct(bytes(buffer), 0)
        except (IndexError, ValueError) as e:
            raise ValueError(f"Couldn't deserialize thrift: {e}") from e

        pruner = _PrunerNode.from_schema(schema, ignore_case)
        schema_list = _get(meta, 2, (0, []))[1]
        elems = [_Elem(f) for f in schema_list]
        schema_map, schema_num_children, chunk_map = pruner.filter_schema(
            elems, ignore_case)

        new_schema = []
        for orig_index, n_children in zip(schema_map, schema_num_children):
            f = list(schema_list[orig_index])
            if not _Elem(f).is_leaf or _has(f, 5) or n_children != 0:
                f = _set(f, 5, _T_I32, n_children)
            new_schema.append(f)
        meta = _set(meta, 2, _T_LIST, (_T_STRUCT, new_schema))

        orders = _get(meta, 7)
        if orders is not None:
            etype, olist = orders
            new_orders = [olist[i] for i in chunk_map]
            meta = _set(meta, 7, _T_LIST, (etype, new_orders))

        row_groups = _get(meta, 4, (_T_STRUCT, []))[1]
        keep = list(range(len(row_groups)))
        if part_length >= 0:
            keep = _filter_groups(row_groups, part_offset, part_length)
            row_groups = [row_groups[i] for i in keep]
        # prune each group's chunks to the surviving columns
        new_groups = []
        for rg in row_groups:
            etype, cols = _get(rg, 1, (_T_STRUCT, []))
            new_cols = [cols[i] for i in chunk_map]
            new_groups.append(_set(list(rg), 1, _T_LIST, (etype, new_cols)))
        meta = _set(meta, 4, _T_LIST, (_T_STRUCT, new_groups))
        # keep the file-level row count consistent with the surviving groups
        # (the reference leaves FileMetaData.num_rows stale here; fixed
        # deliberately so the serialized footer is self-consistent)
        meta = _set(meta, 3, _T_I64,
                    sum(_get(rg, 3, 0) for rg in new_groups))
        return ParquetFooter(meta, kept_group_indexes=keep)

    @staticmethod
    def split_group_indexes(buffer: bytes, part_offset: int,
                            part_length: int) -> List[int]:
        """Original row-group indices whose midpoint lands in the split —
        the plan a reader uses to materialize ONLY those groups (the
        filter_groups selection of NativeParquetJni.cpp:584, exposed as
        indices so an external columnar reader can seek by group)."""
        meta, _ = _read_struct(bytes(buffer), 0)
        row_groups = _get(meta, 4, (_T_STRUCT, []))[1]
        return _filter_groups(row_groups, part_offset, part_length)

    @property
    def column_names(self) -> List[str]:
        """Top-level column names surviving the prune, in file order
        (what a reader passes as its column projection)."""
        schema = _get(self._fields, 2, (0, []))[1]
        if not schema:
            return []
        out, i = [], 1
        n_top = _Elem(schema[0]).num_children
        while len(out) < n_top and i < len(schema):
            e = _Elem(schema[i])
            out.append(e.name)
            # skip this element's whole subtree to reach the next sibling
            remaining = e.num_children
            i += 1
            while remaining > 0 and i < len(schema):
                remaining += _Elem(schema[i]).num_children - 1
                i += 1
        return out

    @property
    def num_rows(self) -> int:
        """Total rows across surviving row groups (getNumRows, :763)."""
        return sum(_get(rg, 3, 0)
                   for rg in _get(self._fields, 4, (0, []))[1])

    @property
    def num_columns(self) -> int:
        """Top-level column count after pruning (getNumColumns, :778)."""
        schema = _get(self._fields, 2, (0, []))[1]
        if schema:
            return _Elem(schema[0]).num_children
        return 0

    def serialize_thrift_file(self) -> bytes:
        """PAR1 + thrift bytes + u32le length + PAR1 (:793-826) — a footer
        'file' parquet readers accept in place of the original."""
        out = bytearray()
        _write_struct(out, self._fields)
        n = len(out)
        return (_MAGIC + bytes(out)
                + _structmod.pack("<I", n) + _MAGIC)
